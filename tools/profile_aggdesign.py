"""Head-to-head: scatter-bucket vs sorted-cumsum groupby-sum kernels at
33M rows -> 4M dense int keys (the q3join shape). Data synthesized on
device via integer hashing (no upload, no jax.random)."""
import time
import spark_rapids_tpu  # noqa: F401  (x64 + persistent compile cache)
import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 25
SPAN = 1 << 22  # 4M buckets


def t(name, fn, *a, reps=3):
    float(fn(*a))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(*a))
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms", flush=True)


@jax.jit
def make_data():
    i = jnp.arange(N, dtype=jnp.uint32)
    h = (i * jnp.uint32(2654435761)) ^ (i >> jnp.uint32(13))
    key = (h % jnp.uint32(SPAN)).astype(jnp.int32)
    h2 = (i * jnp.uint32(0x9E3779B9)) ^ (i >> jnp.uint32(7))
    val = (h2.astype(jnp.float64) / jnp.float64(2**32)) * 1e5
    live = (h ^ h2) % jnp.uint32(3) != 0  # ~2/3 live
    return key, val, live


key, val, live = make_data()
float(jnp.sum(val))


@jax.jit
def scatter_design(key, val, live):
    """Mirror of the current bucket path: counts scatter + 2-digit sums."""
    sb = jnp.where(live, key, jnp.int32(SPAN))
    counts = jax.ops.segment_sum(jnp.ones(N, jnp.int32), sb,
                                 num_segments=SPAN + 1)[:SPAN]
    clean = jnp.where(live, val, 0.0)
    m = jnp.max(jnp.abs(clean))
    e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
    scale = jnp.exp2(47.0 - e)
    s = clean * scale
    d0 = jnp.round(s / np.float64(2.0 ** 24))
    d1 = jnp.round(s - d0 * np.float64(2.0 ** 24))
    a0 = jax.ops.segment_sum(d0.astype(jnp.int32), sb,
                             num_segments=SPAN + 1)[:SPAN]
    a1 = jax.ops.segment_sum(d1.astype(jnp.int32), sb,
                             num_segments=SPAN + 1)[:SPAN]
    tot = (a0.astype(jnp.float64) * np.float64(2.0 ** 24)
           + a1.astype(jnp.float64)) / scale
    return tot[0] + counts[-1].astype(jnp.float64)


@jax.jit
def sorted_design(key, val, live):
    """pack i32 -> co-sort (key, val-fixedpoint-as-2xi32) -> i64 cumsum ->
    searchsorted boundaries. No scatters at all."""
    packed = jnp.where(live, key, jnp.int32(SPAN + 1))
    clean = jnp.where(live, val, 0.0)
    m = jnp.max(jnp.abs(clean))
    e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
    bits = 62 - 25  # fits the global i64 cumsum at N=2^25
    scale = jnp.exp2(jnp.float64(bits) - e)
    s = jnp.round(clean * scale)
    hi = jnp.floor(s / np.float64(2.0 ** 31)).astype(jnp.int32)
    lo = (s - hi.astype(jnp.float64) * np.float64(2.0 ** 31)).astype(jnp.int32)
    sk, shi, slo = jax.lax.sort((packed, hi, lo), num_keys=1)
    v64 = shi.astype(jnp.int64) * jnp.int64(2**31) + slo.astype(jnp.int64)
    csum = jnp.cumsum(v64)
    # boundaries of every bucket slot via binary search on the sorted keys
    slots = jnp.arange(SPAN, dtype=jnp.int32)
    starts = jnp.searchsorted(sk, slots, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(sk, slots, side="right").astype(jnp.int32)
    counts = ends - starts
    c0 = jnp.where(starts > 0, csum[jnp.maximum(starts - 1, 0)], 0)
    c1 = jnp.where(ends > 0, csum[jnp.maximum(ends - 1, 0)], 0)
    tot = (c1 - c0).astype(jnp.float64) / scale
    return tot[0] + counts[-1].astype(jnp.float64)


@jax.jit
def sorted_design_ss1(key, val, live):
    """Same but ONE searchsorted (starts only; ends = next start)."""
    packed = jnp.where(live, key, jnp.int32(SPAN + 1))
    clean = jnp.where(live, val, 0.0)
    m = jnp.max(jnp.abs(clean))
    e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
    scale = jnp.exp2(jnp.float64(37.0) - e)
    s = jnp.round(clean * scale)
    hi = jnp.floor(s / np.float64(2.0 ** 31)).astype(jnp.int32)
    lo = (s - hi.astype(jnp.float64) * np.float64(2.0 ** 31)).astype(jnp.int32)
    sk, shi, slo = jax.lax.sort((packed, hi, lo), num_keys=1)
    v64 = shi.astype(jnp.int64) * jnp.int64(2**31) + slo.astype(jnp.int64)
    csum = jnp.cumsum(v64)
    slots = jnp.arange(SPAN + 1, dtype=jnp.int32)
    starts = jnp.searchsorted(sk, slots, side="left").astype(jnp.int32)
    ends = starts[1:]
    st = starts[:-1]
    counts = ends - st
    c0 = jnp.where(st > 0, csum[jnp.maximum(st - 1, 0)], 0)
    c1 = jnp.where(ends > 0, csum[jnp.maximum(ends - 1, 0)], 0)
    tot = (c1 - c0).astype(jnp.float64) / scale
    return tot[0] + counts[-1].astype(jnp.float64)


t("scatter design (3 scatters)", scatter_design, key, val, live)
t("sorted design (2x searchsorted)", sorted_design, key, val, live)
t("sorted design (1x searchsorted)", sorted_design_ss1, key, val, live)

# correctness cross-check
a = float(scatter_design(key, val, live))
b = float(sorted_design(key, val, live))
c = float(sorted_design_ss1(key, val, live))
print("agree:", a, b, c, flush=True)


@jax.jit
def scatter_design_stacked(key, val, live):
    """counts+2 digits as ONE [N,3] segment_sum (shared index vector)."""
    sb = jnp.where(live, key, jnp.int32(SPAN))
    clean = jnp.where(live, val, 0.0)
    m = jnp.max(jnp.abs(clean))
    e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
    scale = jnp.exp2(47.0 - e)
    s = clean * scale
    d0 = jnp.round(s / np.float64(2.0 ** 24))
    d1 = jnp.round(s - d0 * np.float64(2.0 ** 24))
    payload = jnp.stack([jnp.ones(N, jnp.int32), d0.astype(jnp.int32),
                         d1.astype(jnp.int32)], axis=1)
    acc = jax.ops.segment_sum(payload, sb, num_segments=SPAN + 1)[:SPAN]
    counts = acc[:, 0]
    tot = (acc[:, 1].astype(jnp.float64) * np.float64(2.0 ** 24)
           + acc[:, 2].astype(jnp.float64)) / scale
    return tot[0] + counts[-1].astype(jnp.float64)


@jax.jit
def scatter_pow2(key, val, live):
    """3 scatters but into exactly 2^22 segments (dead rows pre-masked
    to slot 0 and subtracted—skip, just measure seg count effect)."""
    sb = jnp.where(live, key, jnp.int32(SPAN - 1))
    clean = jnp.where(live, val, 0.0)
    m = jnp.max(jnp.abs(clean))
    e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
    scale = jnp.exp2(47.0 - e)
    s = clean * scale
    d0 = jnp.round(s / np.float64(2.0 ** 24))
    d1 = jnp.round(s - d0 * np.float64(2.0 ** 24))
    counts = jax.ops.segment_sum(jnp.ones(N, jnp.int32), sb, num_segments=SPAN)
    a0 = jax.ops.segment_sum(d0.astype(jnp.int32), sb, num_segments=SPAN)
    a1 = jax.ops.segment_sum(d1.astype(jnp.int32), sb, num_segments=SPAN)
    tot = (a0.astype(jnp.float64) * np.float64(2.0 ** 24)
           + a1.astype(jnp.float64)) / scale
    return tot[0] + counts[-1].astype(jnp.float64)


@jax.jit
def one_scatter_only(key, live):
    sb = jnp.where(live, key, jnp.int32(SPAN))
    return jax.ops.segment_sum(jnp.ones(N, jnp.int32), sb,
                               num_segments=SPAN + 1)[:SPAN][-1]


t("scatter stacked [N,3] single pass", scatter_design_stacked, key, val, live)
t("scatter 3x pow2 segments", scatter_pow2, key, val, live)
t("single i32 scatter (floor)", one_scatter_only, key, live)
print("agree2:", float(scatter_design(key, val, live)),
      float(scatter_design_stacked(key, val, live)), flush=True)
