"""Micro-benchmark: pipelined batch execution vs the synchronous path,
end-to-end through the session API.

The query is the shape the pipeline layer targets — a host-decode-heavy
scan feeding device compute and an exchange write:

    read_parquet(gzip)  ->  filter  ->  repartition(k)   ->  group_by(k)
    [host: decompress +     [device]    [SERIALIZED shuffle:   .agg(sum, n)
     decode + upload]                    partition kernel +
                                         async serde write]

With `spark.rapids.sql.pipeline.enabled=true` (default) three overlaps
engage at once: the scan->compute PipelineExec boundary decodes batch
i+1 on the host pool while batch i computes; the exchange consumes its
child partitions as live streams with a one-deep deferred offsets
fetch; and the serialized writer's ThrottlingExecutor serializes
sub-batch i while the device partitions batch i+1. With it disabled,
every one of those host steps sits serially between device dispatches.

Device-latency simulation (default --device-ms 25): each fused device
dispatch sleeps via the fuse dispatch hook, modeling the engine's real
deployment regime — a tunneled TPU where a dispatch costs milliseconds
of OFF-HOST latency (RTT + device execution) during which the host CPU
is free. That off-host window is precisely what the pipeline hides host
decode/serde under. The simulation is applied identically to both
modes, so the comparison stays apples-to-apples.

Why simulate at all: on the CPU backend "device" compute is itself host
CPU work, so pipelined wall-clock can only beat synchronous if spare
cores exist — and this repo's CI container advertises 2 CPUs but
schedules them as effectively ONE core of quota (two pure-C matmuls in
parallel take exactly their serial time; measured, not assumed). On
such a box every CPU-vs-CPU overlap measures 1.0x by construction, and
only latency-shaped device time (GIL-released, off-CPU) can demonstrate
the mechanism. Pass --device-ms 0 for the pure-CPU measurement; on a
host with real spare cores it shows the overlap without simulation.

Run:  python tools/bench_pipeline.py [--rows 2500000] [--reps 3]
                                     [--device-ms 25] [--data-dir DIR]

Prints per-mode wall clock and a JSON summary line; exits nonzero if
the pipelined and synchronous results differ (they must be identical).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pyarrow.parquet as pq  # noqa: E402


def make_data(d: str, rows: int) -> None:
    """Gzip parquet with small row groups: maximum host decode work per
    byte, many batches for the pipeline to look ahead over."""
    import glob
    if glob.glob(os.path.join(d, "*.parquet")):
        return
    rng = np.random.default_rng(5)
    t = pa.table({
        "k": rng.integers(0, 500, rows),
        "v": rng.uniform(0, 1000, rows),
        "a": rng.uniform(0, 1, rows), "b": rng.uniform(0, 1, rows),
        "c": rng.uniform(0, 1, rows), "e": rng.uniform(0, 1, rows),
        "f": rng.uniform(0, 1, rows), "g": rng.uniform(0, 1, rows),
    })
    pq.write_table(t, os.path.join(d, "f0.parquet"),
                   compression="gzip", row_group_size=131072)


def _session(enabled: bool):
    from spark_rapids_tpu.sql.session import TpuSession
    return TpuSession({
        "spark.rapids.sql.pipeline.enabled": str(enabled).lower(),
        "spark.rapids.sql.reader.batchSizeRows": "131072",
        "spark.rapids.sql.batchSizeBytes": str(8 << 20),
        "spark.rapids.sql.format.parquet.reader.type": "PERFILE",
        "spark.rapids.shuffle.mode": "SERIALIZED",
    })


def _query(s, d: str):
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.sql import functions as F
    return (s.read_parquet(d)
            .filter(col("v") > lit(700.0))
            .repartition(2, col("k"))
            .group_by("k").agg(F.sum(col("a")).alias("sa"),
                               F.count().alias("n")))


def _norm(tbl):
    return sorted(zip(tbl["k"].to_pylist(),
                      [round(v, 6) for v in tbl["sa"].to_pylist()],
                      tbl["n"].to_pylist()))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_500_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--device-ms", type=float, default=25.0,
                    help="simulated off-host latency per device dispatch "
                         "(0 = pure CPU-backend timing; see module doc)")
    ap.add_argument("--data-dir", default=None,
                    help="reuse/create the parquet input here instead of "
                         "a fresh temp dir")
    args = ap.parse_args()

    from spark_rapids_tpu.exec import fuse

    tmp = None
    if args.data_dir:
        d = args.data_dir
        os.makedirs(d, exist_ok=True)
    else:
        tmp = tempfile.TemporaryDirectory(prefix="bench_pipeline_")
        d = tmp.name
    make_data(d, args.rows)

    sessions = {True: _session(True), False: _session(False)}
    results = {}
    best = {True: float("inf"), False: float("inf")}
    # warmup (no simulated latency) compiles kernels and captures the
    # comparison results
    for mode, s in sessions.items():
        results[mode] = _norm(_query(s, d).collect())

    dev_s = max(0.0, args.device_ms) / 1e3
    if dev_s:
        fuse.set_dispatch_hook(lambda key: time.sleep(dev_s))
    try:
        order = [True, False]
        for i in range(max(1, args.reps)):
            for mode in (order if i % 2 == 0 else reversed(order)):
                df = _query(sessions[mode], d)
                t0 = time.perf_counter()
                df.collect()
                best[mode] = min(best[mode], time.perf_counter() - t0)
    finally:
        fuse.set_dispatch_hook(None)

    same = results[True] == results[False]
    lm = sessions[True].last_metrics()
    pipe = {k: v for k, v in lm.items()
            if k.startswith(("PipelineExec", "ShuffleExchangeExec"))}
    stall_ms = sum(v.get("pipelineStallTime", 0) for v in pipe.values()) / 1e6
    prod_ms = sum(v.get("pipelineProducerTime", 0)
                  for v in pipe.values()) / 1e6

    speedup = best[False] / best[True]
    label = (f"simulated {args.device_ms:g}ms/dispatch device"
             if dev_s else "pure CPU backend")
    print(f"mode: {label}")
    print(f"pipelined:   {best[True] * 1e3:8.1f} ms")
    print(f"synchronous: {best[False] * 1e3:8.1f} ms   ({speedup:.2f}x)")
    print(f"producer time (overlapped host work): {prod_ms:8.1f} ms")
    print(f"consumer stall (host-bound residue):  {stall_ms:8.1f} ms")
    print(json.dumps({
        "rows": args.rows, "reps": args.reps,
        "device_ms": args.device_ms,
        "pipelined_s": round(best[True], 4),
        "synchronous_s": round(best[False], 4),
        "speedup": round(speedup, 3),
        "producer_ms": round(prod_ms, 1),
        "stall_ms": round(stall_ms, 1),
        "identical_results": same,
    }))
    if tmp is not None:
        tmp.cleanup()
    if not same:
        print("FAIL: pipelined and synchronous results differ",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
