"""AQE smoke: adaptive execution must be RIGHT, VISIBLE, and FREE when off.

Three gates, CI-blocking (tools/ci_check.sh):

1. CORRECTNESS — the q3join- and q72shfl-shaped probes (the two bench
   losses the kernel audit attributed to dispatch_overhead) produce
   byte-identical results with adaptive execution on and off
   (canonically sorted: conversion legitimately reorders rows across
   partitions, it must never change them).
2. DECISIONS — the probes run cold then HISTORY-WARM against one
   history store: the q3join probe's shuffle-hash -> broadcast
   conversion fires (runtime-measured, so cold AND warm), and the
   q72shfl probe's measured-cost replan fires on the warm run only —
   from the cold run's own audited dispatch_overhead verdict, the warm
   plan collapses the hash exchange. Every decision must be visible in
   last_aqe() and the history record.
3. OVERHEAD — with spark.rapids.sql.adaptive.enabled=false the hook
   sites must cost <2% of a probe drive. Same count x delta
   methodology as tools/trace_overhead.py (end-to-end A/B timing is
   noise-bound on shared CI machines): count how often each disabled
   hook fires during one drive, measure each hook's per-call disabled
   cost in a 10^5-iteration tight loop, overhead = sum(count_i x
   cost_i) / best-of drive time.

Run:  python tools/aqe_smoke.py [--rows 60000] [--reps 5]
                                [--tolerance 0.02]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402


def make_tables(rows: int):
    rng = np.random.default_rng(42)
    lineitem = pa.table({
        "l_orderkey": pa.array(rng.integers(0, rows // 4, rows)
                               .astype(np.int64)),
        "l_quantity": pa.array(rng.uniform(1, 50, rows)),
        "l_extendedprice": pa.array(rng.uniform(100, 10_000, rows)),
        "l_discount": pa.array(rng.uniform(0, 0.1, rows)),
    })
    orders = pa.table({
        "o_orderkey": pa.array(rng.integers(0, rows // 4, rows // 10)
                               .astype(np.int64)),
        "o_orderdate": pa.array(rng.integers(8000, 10_000, rows // 10)
                                .astype(np.int64)),
    })
    return lineitem, orders


def q3join_probe(sess, lineitem, orders):
    """lineitem x orders through the SHUFFLED branch (row threshold 1
    defeats the static broadcast estimate) -> the adaptive join node
    measures the build exchange and converts."""
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.sql import functions as F
    li = sess.create_dataframe(lineitem, num_partitions=4)
    od = sess.create_dataframe(orders, num_partitions=2)
    j = li.join(od, on=[(col("l_orderkey"), col("o_orderkey"))],
                how="inner")
    g = (j.select(col("l_orderkey"),
                  (col("l_extendedprice")
                   * (lit(1.0) - col("l_discount"))).alias("rev"))
         .group_by(col("l_orderkey")).agg(F.sum("rev").alias("rev")))
    return g.order_by(col("rev").desc(), col("l_orderkey").asc()).limit(10)


def q72shfl_probe(sess, lineitem):
    """4-partition high-cardinality group-by: partial agg -> hash
    exchange -> final, the shape whose exchange the audit called pure
    dispatch tax — the measured cost pass's collapse target."""
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.sql import functions as F
    sh = sess.create_dataframe(
        lineitem.select(["l_orderkey", "l_quantity"]), num_partitions=4)
    return (sh.select((col("l_orderkey") % lit(1000)).alias("k"),
                      col("l_quantity"))
            .group_by(col("k"))
            .agg(F.sum("l_quantity").alias("s"),
                 F.count("l_quantity").alias("c")))


def canon(table: pa.Table):
    rows = table.to_pylist()

    def key(r):
        return [(v is not None, str(v)) for _, v in sorted(r.items())]

    return sorted(rows, key=key)


def decisions(sess, kind):
    return [d for d in (sess.last_aqe() or {}).get("decisions", [])
            if d["kind"] == kind]


def correctness_and_decisions(rows: int) -> dict:
    from spark_rapids_tpu.sql.session import TpuSession
    lineitem, orders = make_tables(rows)
    hist = tempfile.mkdtemp(prefix="aqe_smoke_hist_")
    base = {"spark.rapids.sql.join.broadcastRowThreshold": 1,
            "spark.rapids.obs.audit.enabled": "true",
            "spark.rapids.obs.historyDir": hist}
    off_conf = dict(base)
    off_conf["spark.rapids.sql.adaptive.enabled"] = "false"

    out: dict = {"history_dir": hist}

    # -- cold pass (empty history) --
    s_cold = TpuSession(base)
    t3_cold = q3join_probe(s_cold, lineitem, orders).collect()
    conv = decisions(s_cold, "broadcast_conversion")
    if not conv:
        raise SystemExit("FAIL: q3join probe made no broadcast_conversion "
                         f"decision (aqe={s_cold.last_aqe()!r})")
    out["q3join_conversion"] = conv[0]
    t72_cold = q72shfl_probe(s_cold, lineitem).collect()
    if decisions(s_cold, "measured_cost"):
        raise SystemExit("FAIL: measured_cost decision fired on a COLD "
                         "history — hints must need an audited record")
    roof = s_cold.last_roofline() or {}
    shuffle_bound = (roof.get("groups", {}).get("shuffle") or {}).get("bound")
    if shuffle_bound != "dispatch_overhead":
        raise SystemExit(
            f"FAIL: cold q72shfl shuffle verdict is {shuffle_bound!r}, "
            "expected dispatch_overhead (tiny-partition exchange should "
            "be pure launch tax — did the audit or roofline change?)")

    # -- history-warm pass: same store, fresh session --
    s_warm = TpuSession(base)
    t72_warm = q72shfl_probe(s_warm, lineitem).collect()
    mc = decisions(s_warm, "measured_cost")
    if not mc:
        raise SystemExit("FAIL: warm q72shfl made no measured_cost "
                         f"decision (aqe={s_warm.last_aqe()!r})")
    if mc[0].get("exchange_parts") != 1:
        raise SystemExit(f"FAIL: warm decision did not collapse the "
                         f"exchange: {mc[0]!r}")
    out["q72shfl_warm_decision"] = mc[0]
    t3_warm = q3join_probe(s_warm, lineitem, orders).collect()
    if not decisions(s_warm, "broadcast_conversion"):
        raise SystemExit("FAIL: warm q3join lost its conversion decision")

    # -- AQE-off reference: byte-identical results --
    s_off = TpuSession(off_conf)
    t3_off = q3join_probe(s_off, lineitem, orders).collect()
    if s_off.last_aqe() is not None:
        raise SystemExit("FAIL: adaptive-off session recorded decisions")
    t72_off = q72shfl_probe(s_off, lineitem).collect()
    for name, got, ref in (("q3join/cold", t3_cold, t3_off),
                           ("q3join/warm", t3_warm, t3_off),
                           ("q72shfl/cold", t72_cold, t72_off),
                           ("q72shfl/warm", t72_warm, t72_off)):
        if canon(got) != canon(ref):
            raise SystemExit(f"FAIL: {name} results differ from the "
                             "AQE-off plan")
    out["parity"] = "byte-identical (canonical order) on/off, cold+warm"
    return out


# -- disabled-path overhead (count x delta) ---------------------------------

#: the hook sites the disabled path still executes, as (module attr
#: path, callable builder for the tight loop)
def _hooks():
    from spark_rapids_tpu.exec import adaptive as AQ
    from spark_rapids_tpu.plan import cost as COST
    return AQ, COST


def count_and_cost(rows: int, reps: int) -> dict:
    from spark_rapids_tpu.sql.session import TpuSession
    AQ, COST = _hooks()
    lineitem, _orders = make_tables(rows)
    off = TpuSession({"spark.rapids.sql.adaptive.enabled": "false"})
    conf = off.conf
    df = q72shfl_probe(off, lineitem)
    df.collect()  # warm compile caches out of the timed drives

    counts = {"adaptive.enabled": 0, "cost.measured_hints": 0,
              "cost.current_hints": 0, "adaptive.on_query_start": 0,
              "adaptive.finish_query": 0}
    orig = (AQ.enabled, COST.measured_hints, COST.current_hints,
            AQ.on_query_start, AQ.finish_query)

    def wrap(name, fn):
        def w(*a, **k):
            counts[name] += 1
            return fn(*a, **k)
        return w

    AQ.enabled = wrap("adaptive.enabled", orig[0])
    COST.measured_hints = wrap("cost.measured_hints", orig[1])
    COST.current_hints = wrap("cost.current_hints", orig[2])
    AQ.on_query_start = wrap("adaptive.on_query_start", orig[3])
    AQ.finish_query = wrap("adaptive.finish_query", orig[4])
    try:
        q72shfl_probe(off, lineitem).collect()
    finally:
        (AQ.enabled, COST.measured_hints, COST.current_hints,
         AQ.on_query_start, AQ.finish_query) = orig

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        q72shfl_probe(off, lineitem).collect()
        best = min(best, time.perf_counter() - t0)

    iters = 100_000
    plan = df.plan
    loops = {
        "adaptive.enabled": lambda: AQ.enabled(conf),
        "cost.measured_hints": lambda: COST.measured_hints(plan, conf),
        "cost.current_hints": COST.current_hints,
        "adaptive.on_query_start": lambda: AQ.on_query_start(conf),
        "adaptive.finish_query": AQ.finish_query,
    }
    per_call = {}
    for name, fn in loops.items():
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        per_call[name] = (time.perf_counter() - t0) / iters
    AQ.reset_for_tests()

    added = sum(counts[n] * per_call[n] for n in counts)
    return {"drive_best_s": round(best, 6),
            "hook_counts": counts,
            "per_call_ns": {n: round(c * 1e9, 1)
                            for n, c in per_call.items()},
            "disabled_overhead_s": round(added, 9),
            "disabled_overhead_pct": round(added / best * 100, 4)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=60_000)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    result = correctness_and_decisions(args.rows)
    overhead = count_and_cost(args.rows, args.reps)
    result.update(overhead)
    print(json.dumps(result, sort_keys=True))
    pct = overhead["disabled_overhead_pct"]
    if pct > args.tolerance * 100:
        print(f"FAIL: disabled-path AQE overhead {pct:.3f}% exceeds "
              f"{args.tolerance * 100:.0f}% of the probe drive")
        return 1
    print(f"PASS: AQE on/off byte-identical (q3join conversion + warm "
          f"q72shfl measured-cost collapse fired); disabled-path "
          f"overhead {pct:.4f}% of the drive")
    return 0


if __name__ == "__main__":
    sys.exit(main())
