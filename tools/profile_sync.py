"""Measure what a host sync actually costs on the tunneled axon device,
and whether block_until_ready really blocks."""
import time
import numpy as np
import jax
import jax.numpy as jnp

N = 30_000_000
rng = np.random.default_rng(0)
x = jax.device_put(rng.uniform(0, 1e9, N).astype(np.float32))
jax.block_until_ready(x)


def t(name, fn, reps=3):
    fn()  # warm/compile
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms")


@jax.jit
def tiny(a):
    return a + 1.0


@jax.jit
def sum30(v):
    return jnp.sum(v)


@jax.jit
def sort30(v):
    return jnp.sort(v)


@jax.jit
def argsort30(v):
    return jnp.argsort(v)


one = jax.device_put(np.float32(1.0))

# 1. fetch-only round trip on a tiny jitted op
t("tiny jit dispatch+fetch", lambda: float(tiny(one)))
# 2. big reduction + scalar fetch
t("sum 30M + fetch", lambda: float(sum30(x)))
# 3. sort dispatch with block_until_ready (does it block?)
t("sort 30M block_until_ready", lambda: jax.block_until_ready(sort30(x)))
# 4. sort + fetch one element (forces completion for real)
t("sort 30M + fetch[0]", lambda: float(sort30(x)[0]))
# 5. argsort + fetch
t("argsort 30M + fetch[0]", lambda: int(argsort30(x)[0]))
# 6. back-to-back dependent syncs (2 fetches)
t("two dependent tiny fetches",
  lambda: (float(tiny(one)), float(tiny(one))))
