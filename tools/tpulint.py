"""tpulint CLI: engine-invariant static analysis over the full tree.

    python tools/tpulint.py [--strict] [--json] [--rule TPU-LNNN ...]

Exit status: 0 when clean (suppressed violations with reasons are
allowed), 1 when any unsuppressed violation remains — or, in --strict
mode, when a suppression is missing its reason. The linter is pure-AST
(spark_rapids_tpu/analysis/lint.py is loaded by file path, never
importing the engine or jax), so the full-tree run stays well under the
10-second CI budget; the measured elapsed time is printed and enforced.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_lint():
    """Load analysis/lint.py WITHOUT importing spark_rapids_tpu (whose
    __init__ pulls jax — seconds of import time the lint must not pay)."""
    path = os.path.join(ROOT, "spark_rapids_tpu", "analysis", "lint.py")
    spec = importlib.util.spec_from_file_location("tpulint_rules", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclasses resolve via sys.modules
    spec.loader.exec_module(mod)
    return mod


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--strict", action="store_true",
                    help="fail on unsuppressed violations AND on disable "
                         "comments without a reason")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--rule", action="append", default=None,
                    help="only report these rule ids (repeatable)")
    ap.add_argument("--budget-seconds", type=float, default=10.0,
                    help="fail if the lint itself exceeds this wall time")
    args = ap.parse_args()

    t0 = time.perf_counter()
    lint = _load_lint()
    violations, stats = lint.lint_tree(ROOT)
    elapsed = time.perf_counter() - t0
    if args.rule:
        keep = set(args.rule)
        violations = [v for v in violations if v.rule in keep]

    live = [v for v in violations if not v.suppressed]
    suppressed = [v for v in violations if v.suppressed]
    per_rule = {}
    for v in live:
        per_rule[v.rule] = per_rule.get(v.rule, 0) + 1

    if args.json:
        print(json.dumps({
            "files": stats["files"],
            "elapsed_s": round(elapsed, 3),
            "violations": [dataclass_dict(v) for v in live],
            "suppressed": [dataclass_dict(v) for v in suppressed],
            "per_rule": per_rule,
        }, indent=1))
    else:
        for v in live:
            print(v.render(ROOT))
        if suppressed:
            print(f"-- {len(suppressed)} suppressed "
                  f"(justified # tpulint: disable sites):")
            for v in suppressed:
                print("   " + v.render(ROOT))
        print(f"tpulint: {stats['files']} files, {len(live)} violations, "
              f"{len(suppressed)} suppressed, {elapsed:.2f}s")

    if elapsed > args.budget_seconds:
        print(f"FAIL: lint took {elapsed:.2f}s "
              f"(budget {args.budget_seconds:.0f}s)", file=sys.stderr)
        return 1
    if live:
        return 1
    if args.strict and stats["suppressions_without_reason"]:
        print("FAIL: --strict requires every tpulint disable comment to "
              "carry a reason", file=sys.stderr)
        return 1
    return 0


def dataclass_dict(v):
    return {"rule": v.rule, "path": os.path.relpath(v.path, ROOT),
            "line": v.line, "message": v.message,
            "suppressed": v.suppressed, "reason": v.reason}


if __name__ == "__main__":
    sys.exit(main())
