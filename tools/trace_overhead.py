"""Trace-overhead smoke: tracing must be FREE when disabled.

Gate: the total cost the DISABLED instrumentation adds to one drive of
the fused Filter→Project stage (tools/bench_fusion.py's dispatch-bound
small shape) must be under --tolerance (2%) of the drive's wall time.

Method — the naive way (time the drive with instrumentation vs with it
monkeypatched away, compare) is unsound on shared CI machines: an A/A
experiment on this workload shows the run-to-run noise floor is ±10%+,
an order of magnitude above the quantity under test. Instead the smoke
measures the real thing directly and stably:

1. count how often each instrumentation entry point (exec_span /
   metric_span / span / instant) actually fires during one drive
   (counting wrappers, one instrumented drive);
2. measure each entry point's DISABLED per-call cost minus its
   pre-trace equivalent (the bare GpuMetric timer or nothing) over 10^5
   tight-loop iterations — deltas of tens of nanoseconds measure
   reliably at that scale;
3. overhead = Σ count_i × max(delta_i, 0) against best-of drive time.

The end-to-end paired timings are still reported (informational), and a
trace-ENABLED run must produce Chrome-trace-event JSON that validates
(Perfetto / chrome://tracing loadable).

Run:  python tools/trace_overhead.py [--rows 400000] [--batch 2048]
                                     [--reps 9] [--tolerance 0.02]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import bench_fusion as BF  # noqa: E402

_ENTRY_POINTS = ("exec_span", "metric_span", "span", "instant")


def _count_calls(trace, drive):
    """One drive with counting wrappers on the instrumentation entry
    points (tracing stays disabled; the wrappers call through)."""
    counts = {n: 0 for n in _ENTRY_POINTS}
    saved = {n: getattr(trace, n) for n in _ENTRY_POINTS}

    def wrap(name):
        inner = saved[name]

        def counted(*a, **kw):
            counts[name] += 1
            return inner(*a, **kw)
        return counted

    try:
        for n in _ENTRY_POINTS:
            setattr(trace, n, wrap(n))
        drive()
    finally:
        for n in _ENTRY_POINTS:
            setattr(trace, n, saved[n])
    return counts


def _per_call_deltas(trace, iters=100_000):
    """Disabled-path per-call cost of each entry point MINUS its
    pre-trace equivalent, in seconds (clamped at >= 0)."""
    from spark_rapids_tpu.runtime.metrics import GpuMetric

    class _Node:
        lore_id = None

        def name(self):
            return "X"

    node, m = _Node(), GpuMetric("opTime")

    def loop(fn):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters

    def bare_timer():
        with m.ns():
            pass

    def nothing():
        pass

    def exec_span_full():
        with trace.exec_span(node, m):
            pass

    def metric_span_full():
        with trace.metric_span("x", m):
            pass

    base_timer = min(loop(bare_timer) for _ in range(3))
    base_empty = min(loop(nothing) for _ in range(3))
    costs = {
        "exec_span": min(loop(exec_span_full) for _ in range(3)),
        "metric_span": min(loop(metric_span_full) for _ in range(3)),
        "span": min(loop(lambda: trace.span("x")) for _ in range(3)),
        "instant": min(loop(lambda: trace.instant("x")) for _ in range(3)),
    }
    return {
        "exec_span": max(costs["exec_span"] - base_timer, 0.0),
        "metric_span": max(costs["metric_span"] - base_timer, 0.0),
        "span": max(costs["span"] - base_empty, 0.0),
        "instant": max(costs["instant"] - base_empty, 0.0),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=9)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    from spark_rapids_tpu.runtime import trace

    t = BF._table(args.rows)
    batches = BF._device_batches(t, args.batch)
    # UNFUSED chain: FilterExec/ProjectExec drive exec_span per batch, so
    # the gate counts real instrumentation traffic (the fused stage's hot
    # loop has no per-batch entry-point calls and would measure zero)
    drive, _res = BF.make_chain_stage(t, False, 1, args.batch, batches)
    drive()  # warm every kernel cache before measuring

    # drive wall time: best-of (the only robust end-to-end statistic)
    drive_s = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        drive()
        drive_s.append(time.perf_counter() - t0)
    drive_best = min(drive_s)

    counts = _count_calls(trace, drive)
    deltas = _per_call_deltas(trace)
    added_s = sum(counts[n] * deltas[n] for n in _ENTRY_POINTS)
    overhead = added_s / drive_best

    # enabled run: produce + validate the artifact (correctness, not time)
    out_dir = tempfile.mkdtemp(prefix="trace_smoke_")
    from spark_rapids_tpu import config as C
    tr = trace.start_query(C.RapidsConf({
        "spark.rapids.sql.trace.enabled": "true",
        "spark.rapids.sql.trace.path": out_dir,
        "spark.rapids.sql.trace.level": "DEBUG"}))
    t0 = time.perf_counter()
    drive()
    enabled_s = time.perf_counter() - t0
    paths = trace.end_query(tr)
    import profiler_report as PR
    events = PR.validate_chrome_trace(paths["trace"])
    spans = sum(1 for e in events if e["ph"] == "X")

    result = {
        "drive_best_s": round(drive_best, 5),
        "enabled_s": round(enabled_s, 5),
        "instr_calls_per_drive": counts,
        "per_call_delta_ns": {n: round(d * 1e9, 1)
                              for n, d in deltas.items()},
        "disabled_overhead_s": round(added_s, 7),
        "disabled_overhead_pct": round(overhead * 100, 4),
        "tolerance_pct": args.tolerance * 100,
        "trace_events": len(events),
        "trace_spans": spans,
        "trace_path": paths["trace"],
    }
    print(json.dumps(result))
    if spans == 0:
        print("FAIL: enabled run produced no spans", file=sys.stderr)
        return 1
    if overhead > args.tolerance:
        print(f"FAIL: disabled-trace overhead {overhead * 100:.3f}% "
              f"exceeds {args.tolerance * 100:.1f}%", file=sys.stderr)
        return 1
    print(f"PASS: disabled-trace overhead {overhead * 100:.3f}% of the "
          f"drive (tolerance {args.tolerance * 100:.1f}%); trace "
          f"validates ({spans} spans)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
