"""Device-decode smoke (round 16): the CI gate for the device-side
Parquet decode path.

1. NDS-probe-shaped parity: scan / filter / group-by-agg queries over a
   REAL parquet file (snappy + dictionary + nulls + a string fallback
   column) must be byte-identical with decode.device on and off.
2. Attribution shift: with device decode ON the value decode runs inside
   the fused dispatch — encodedBytes (what crossed the link) and
   decodedBytes (what the kernel materialized) are recorded and the
   host_decode wall share drops against the host path; the plan carries
   DeviceDecodeScanExec and the per-column fallback note.
3. Disabled-path overhead: with decode.device OFF the only new code the
   old path executes is the conf gate at ParquetScan conversion. Same
   count x delta methodology as tools/aqe_smoke.py (end-to-end A/B
   timing is noise-bound on shared CI machines): count the gate's firings
   during a probe drive, measure its per-call cost in a tight loop,
   overhead must stay under --tolerance (2%) of the drive.

Usage: python tools/decode_smoke.py [--rows 200000] [--tolerance 0.02]
"""
import argparse
import json
import os
import shutil
import sys
import tempfile
import time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["XLA_FLAGS"] = _flags

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pyarrow.parquet as pq  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_tpu.sql.session import TpuSession  # noqa: E402
from spark_rapids_tpu.sql import functions as F  # noqa: E402
from spark_rapids_tpu.expr.core import col, lit  # noqa: E402


def write_probe_file(tdir: str, rows: int) -> str:
    """A store_sales-shaped slice: dict / plain / bool / nullable
    columns plus one string column that host-falls-back per column."""
    rng = np.random.default_rng(16)
    qty = rng.integers(1, 100, rows).astype(np.int64)
    price = np.round(rng.uniform(1.0, 300.0, rows), 2)
    null_mask = rng.random(rows) < 0.12
    t = pa.table({
        "ss_item_sk": pa.array(rng.integers(0, 200, rows).astype(np.int32)),
        "ss_quantity": pa.array(qty, mask=null_mask),
        "ss_sales_price": pa.array(price, mask=null_mask),
        "ss_promo": pa.array(rng.integers(0, 2, rows).astype(bool)),
        "ss_store_id": pa.array(
            np.array(["s1", "s2", "s3", None], object)[
                rng.integers(0, 4, rows)]),
    })
    path = os.path.join(tdir, "store_sales.parquet")
    pq.write_table(t, path, row_group_size=max(rows // 4, 1000),
                   use_dictionary=["ss_item_sk", "ss_store_id"],
                   compression="snappy", data_page_version="1.0")
    return path


def queries(path):
    return {
        "scan": lambda s: s.read_parquet(path),
        "filter": lambda s: (s.read_parquet(path)
                             .filter(col("ss_quantity") > lit(50))),
        "agg": lambda s: (s.read_parquet(path)
                          .group_by("ss_item_sk")
                          .agg(F.sum(col("ss_sales_price")).alias("rev"),
                               F.count(col("ss_promo")).alias("n"))),
    }


def _sorted(tbl):
    return tbl.sort_by([(c, "ascending") for c in tbl.column_names])


def parity_and_shift(path, result) -> list:
    """Returns a list of failure strings (empty = pass)."""
    fails = []
    qs = queries(path)
    attr = {}
    bytes_seen = {}
    for flag in ("true", "false"):
        sess = TpuSession({"spark.rapids.sql.decode.device.enabled": flag})
        key = "device" if flag == "true" else "host"
        outs = {}
        for name, q in qs.items():
            outs[name] = _sorted(q(sess).collect())
        try:
            a = sess.last_attribution() or {}
            attr[key] = {k: round(v, 4)
                         for k, v in (a.get("buckets") or {}).items() if v}
        except Exception:  # noqa: BLE001 - attribution is advisory
            attr[key] = {}
        snaps = sess.last_metrics()
        bytes_seen[key] = {
            "encoded": sum(v.get("encodedBytes", 0)
                           for v in snaps.values()),
            "decoded": sum(v.get("decodedBytes", 0)
                           for v in snaps.values()),
            "fallback_columns": sum(v.get("numDecodeFallbackColumns", 0)
                                    for v in snaps.values()),
        }
        if flag == "true":
            dev_outs = outs
            stages = qs["filter"](sess).explain("stages")
            if "DeviceDecodeScanExec" not in stages:
                fails.append("device path missing DeviceDecodeScanExec")
            if "host-fallback{ss_store_id:" not in stages:
                fails.append("per-column fallback note missing from explain")
        else:
            host_outs = outs
            stages = qs["filter"](sess).explain("stages")
            if "DeviceDecodeScanExec" in stages:
                fails.append("disabled path still plans DeviceDecodeScanExec")
    for name in qs:
        if not dev_outs[name].equals(host_outs[name]):
            fails.append(f"parity: {name} differs between decode paths")
    result["attribution"] = attr
    result["bytes"] = bytes_seen
    # the structural shift: encoded planes crossed the link on the device
    # path (and are SMALLER than what the kernel materialized), none on
    # the host path, and the string column fell back per column
    if not bytes_seen["device"]["encoded"]:
        fails.append("device path recorded no encodedBytes")
    if bytes_seen["device"]["decoded"] <= bytes_seen["device"]["encoded"]:
        fails.append("decodedBytes <= encodedBytes: decode is not winning "
                     "link bytes")
    if bytes_seen["host"]["encoded"]:
        fails.append("host path recorded encodedBytes")
    if not bytes_seen["device"]["fallback_columns"]:
        fails.append("string column did not host-fall-back per column")
    # the wall-time shift (advisory on CPU sim, recorded for TPU rounds):
    # host_decode no longer holds the value decode on the device path
    d_att, h_att = attr.get("device", {}), attr.get("host", {})
    if d_att and not d_att.get("device_compute", 0.0) > 0:
        fails.append("device path attributed no device_compute")
    result["host_decode_seconds"] = {
        "device": d_att.get("host_decode", 0.0),
        "host": h_att.get("host_decode", 0.0)}
    return fails


def disabled_overhead(path, reps: int) -> dict:
    """Count x delta: the disabled path's only new site is the decode
    conf gate read at ParquetScan conversion."""
    from spark_rapids_tpu import config as C

    off = TpuSession({"spark.rapids.sql.decode.device.enabled": "false"})
    drive = queries(path)["agg"]
    drive(off).collect()  # warm compile caches out of the timed drives

    conf = off.conf
    counts = {"decode.device.enabled": 0}
    orig_get = type(conf).get

    def counting_get(self, entry, *a, **k):
        if getattr(entry, "key", None) == C.DEVICE_DECODE_ENABLED.key:
            counts["decode.device.enabled"] += 1
        return orig_get(self, entry, *a, **k)

    type(conf).get = counting_get
    try:
        drive(off).collect()
    finally:
        type(conf).get = orig_get

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        drive(off).collect()
        best = min(best, time.perf_counter() - t0)

    iters = 100_000
    t0 = time.perf_counter()
    for _ in range(iters):
        conf.get(C.DEVICE_DECODE_ENABLED)
    per_call = (time.perf_counter() - t0) / iters

    added = counts["decode.device.enabled"] * per_call
    return {"drive_best_s": round(best, 6),
            "gate_counts": counts,
            "gate_per_call_ns": round(per_call * 1e9, 1),
            "disabled_overhead_s": round(added, 9),
            "disabled_overhead_pct": round(added / best * 100, 4)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    tdir = tempfile.mkdtemp(prefix="decode_smoke_")
    try:
        path = write_probe_file(tdir, args.rows)
        result = {"rows": args.rows,
                  "file_bytes": os.path.getsize(path)}
        fails = parity_and_shift(path, result)
        overhead = disabled_overhead(path, args.reps)
        result.update(overhead)
        print(json.dumps(result, sort_keys=True))
        pct = overhead["disabled_overhead_pct"]
        if pct > args.tolerance * 100:
            fails.append(f"disabled-path decode overhead {pct:.3f}% exceeds "
                         f"{args.tolerance * 100:.0f}% of the probe drive")
        if fails:
            for f in fails:
                print("FAIL:", f)
            return 1
        print(f"PASS: decode on/off byte-identical across "
              f"{len(queries(path))} probe queries; encoded "
              f"{result['bytes']['device']['encoded']}B crossed the link "
              f"for {result['bytes']['device']['decoded']}B decoded; "
              f"disabled-path overhead {pct:.4f}% of the drive")
        return 0
    finally:
        shutil.rmtree(tdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
