"""Sanitizer smoke: the concurrency sanitizer must be FREE when disabled
and sharp when enabled.

Gate 1 (overhead, the tracing bar): the total cost the DISABLED lock
proxies add to one drive of the unfused Filter→Project chain
(tools/bench_fusion.py's dispatch-bound shape — every batch acquires the
TPU semaphore, so the drive generates real sanitized-lock traffic) must
be under --tolerance (2%) of the drive's wall time. Same method as
tools/trace_overhead.py, for the same reason (run-to-run noise on shared
CI machines is ±10%+, an order of magnitude above the quantity under
test):

1. count how many sanitized acquire/release pairs one drive performs
   (class-level counting wrappers, sanitizer disabled);
2. measure the proxy's DISABLED per-cycle cost minus a raw
   threading.Lock cycle over 10^5 tight-loop iterations;
3. overhead = pairs × max(delta, 0) against best-of drive time.

Gate 2 (detection): with the sanitizer enabled, a seeded ABBA lock
inversion and a seeded held-lock blocking call must BOTH be reported —
and a re-run of the engine drive must report nothing (the clean engine
stays clean under instrumentation).

Run:  python tools/sanitizer_smoke.py [--rows 400000] [--batch 2048]
                                      [--reps 9] [--tolerance 0.02]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import bench_fusion as BF  # noqa: E402


def _count_lock_ops(san, drive):
    """Sanitized acquire/release counts for one drive (sanitizer stays
    disabled; the wrappers call through)."""
    counts = {"acquire": 0, "release": 0}
    orig_acq = san._SanLock.acquire
    orig_rel = san._SanLock.release

    def acq(self, blocking=True, timeout=-1):
        counts["acquire"] += 1
        return orig_acq(self, blocking, timeout)

    def rel(self):
        counts["release"] += 1
        return orig_rel(self)

    san._SanLock.acquire = acq
    san._SanLock.release = rel
    try:
        drive()
    finally:
        san._SanLock.acquire = orig_acq
        san._SanLock.release = orig_rel
    return counts


def _per_cycle_delta(san, iters=100_000):
    """Disabled-path cost of one proxy acquire+release cycle MINUS a raw
    threading.Lock cycle, in seconds (clamped >= 0)."""
    raw = threading.Lock()
    proxy = san.lock("smoke.timing")

    def loop(lk):
        t0 = time.perf_counter()
        for _ in range(iters):
            lk.acquire()
            lk.release()
        return (time.perf_counter() - t0) / iters

    base = min(loop(raw) for _ in range(3))
    cost = min(loop(proxy) for _ in range(3))
    return max(cost - base, 0.0), base, cost


def _seeded_findings(san):
    """Enabled run over two deliberate bugs: ABBA inversion + held-lock
    blocking. Returns the kinds reported."""
    san.uninstall()
    san.install(hold_warn_ms=5.0)
    try:
        a, b = san.lock("smoke.A"), san.lock("smoke.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        hold = san.lock("smoke.hold")
        with hold:
            time.sleep(0.02)  # stand-in for I/O under the lock
        return sorted({f["kind"] for f in san.report()["findings"]})
    finally:
        san.uninstall()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=9)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    from spark_rapids_tpu.analysis import sanitizer as san

    san.uninstall()  # the overhead half measures the DISABLED path

    t = BF._table(args.rows)
    batches = BF._device_batches(t, args.batch)
    drive, _res = BF.make_chain_stage(t, False, 1, args.batch, batches)
    drive()  # warm kernel caches before measuring

    drive_s = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        drive()
        drive_s.append(time.perf_counter() - t0)
    drive_best = min(drive_s)

    counts = _count_lock_ops(san, drive)
    delta, base_cycle, proxy_cycle = _per_cycle_delta(san)
    pairs = max(counts["acquire"], counts["release"])
    added_s = pairs * delta
    overhead = added_s / drive_best

    kinds = _seeded_findings(san)

    # clean-engine check: the instrumented drive must report nothing
    san.install(hold_warn_ms=250.0)
    try:
        drive()
        clean = san.report()["findings"]
    finally:
        san.uninstall()

    result = {
        "drive_best_s": round(drive_best, 5),
        "lock_ops_per_drive": counts,
        "raw_cycle_ns": round(base_cycle * 1e9, 1),
        "proxy_cycle_ns": round(proxy_cycle * 1e9, 1),
        "per_cycle_delta_ns": round(delta * 1e9, 1),
        "disabled_overhead_s": round(added_s, 7),
        "disabled_overhead_pct": round(overhead * 100, 4),
        "tolerance_pct": args.tolerance * 100,
        "seeded_findings": kinds,
        "clean_engine_findings": len(clean),
    }
    print(json.dumps(result))

    ok = True
    if counts["acquire"] == 0:
        print("FAIL: drive performed no sanitized lock operations — the "
              "overhead gate is vacuous", file=sys.stderr)
        ok = False
    if overhead > args.tolerance:
        print(f"FAIL: disabled-sanitizer overhead {overhead * 100:.3f}% "
              f"exceeds {args.tolerance * 100:.1f}%", file=sys.stderr)
        ok = False
    if "lock-inversion" not in kinds or "held-lock-blocking" not in kinds:
        print(f"FAIL: seeded bugs not both reported (got {kinds}; need "
              f"lock-inversion AND held-lock-blocking)", file=sys.stderr)
        ok = False
    if clean:
        print(f"FAIL: clean engine drive produced {len(clean)} "
              f"finding(s): {json.dumps(clean)}", file=sys.stderr)
        ok = False
    if not ok:
        return 1
    print(f"PASS: disabled-sanitizer overhead {overhead * 100:.3f}% of "
          f"the drive ({pairs} lock cycles, tolerance "
          f"{args.tolerance * 100:.1f}%); seeded inversion + held-lock "
          f"both caught; clean engine silent")
    return 0


if __name__ == "__main__":
    sys.exit(main())
