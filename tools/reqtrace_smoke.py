"""Per-request tracing smoke: the CI gate for runtime/obs/reqtrace.py.

1. Disabled-path overhead: with reqtrace OFF (the default) the only new
   site a flight-armed workload executes is the ONE module-global read
   (``reqtrace._REC``) inside FlightRecorder.record. Count x delta
   methodology (tools/aqe_smoke.py): count record() firings during a
   drive, measure the read's per-call cost in a tight loop, bound the
   product under --tolerance (2%) of the drive. Runs FIRST, before this
   process installs any recorder.
2. Armed-path overhead: with a recorder installed AND a request bound,
   every flight event additionally runs ReqTraceRecorder.feed (one
   thread-local read + one tuple store + one integer bump). Same count
   x delta bound over a request-bound drive.
3. Verdicts over the serving surface (seeded sampler -> deterministic):
   the executed request breaches a tiny absolute SLO and ALWAYS exports
   (verdict slo_breach); injected scan ioerrors fail their requests and
   ALWAYS export (verdict error, 100% of them); N hot cache hits ride
   the seeded sampleRatio draw — the kept count must equal the seed's
   replay exactly and stay at the configured ratio. The incoming W3C
   traceparent is honored verbatim.
4. Timeline validation: every exported artifact is a loadable Chrome
   trace (tools/profiler_report.validate_chrome_trace) whose root
   "request" span carries the W3C identity; executed timelines contain
   the serving span tree AND engine exec spans joined by the request's
   query_id; every artifact has a well-formed OTLP-JSON sibling whose
   child spans parent on the request root.

Usage: python tools/reqtrace_smoke.py [--hits 240] [--ratio 0.05]
                                      [--tolerance 0.02]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from profiler_report import validate_chrome_trace  # noqa: E402

SQL = "SELECT k, SUM(v) AS sv, COUNT(*) AS n FROM t GROUP BY k"
SEED = 20260807
#: an incoming W3C traceparent the server must honor verbatim
TP_TID = "ab" * 16
TP = f"00-{TP_TID}-{'cd' * 8}-01"


def _probe_table(n=30_000, seed=17):
    import numpy as np
    import pyarrow as pa
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 12, n),
                     "v": rng.integers(1, 1000, n)})


def _session(extra=None):
    from spark_rapids_tpu.sql.session import TpuSession
    sess = TpuSession(extra or {})
    sess.create_or_replace_temp_view(
        "t", sess.create_dataframe(_probe_table()))
    return sess


def _counted_drive(drive):
    """Run one drive counting FlightRecorder.record firings (each one
    executes the reqtrace feed site being charged)."""
    from spark_rapids_tpu.runtime.obs import flight
    counts = [0]
    real = flight.FlightRecorder.record

    def counting(self, *a, **kw):
        counts[0] += 1
        return real(self, *a, **kw)

    flight.FlightRecorder.record = counting
    try:
        drive()
    finally:
        flight.FlightRecorder.record = real
    return counts[0]


# ---------------------------------------------------------------------------
# gate 1: disabled-path overhead — MUST run before any recorder install
# ---------------------------------------------------------------------------

def disabled_overhead(reps: int) -> dict:
    from spark_rapids_tpu.runtime.obs import reqtrace
    assert reqtrace.recorder() is None, \
        "gate 1 must run before a reqtrace recorder exists"
    sess = _session()

    def drive():
        sess.sql(SQL).collect()

    drive()  # warm the trace cache out of the timed drives
    count = _counted_drive(drive)
    assert count > 0, "flight recorder not armed — nothing to charge"

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        drive()
        best = min(best, time.perf_counter() - t0)

    iters = 1_000_000
    t0 = time.perf_counter()
    for _ in range(iters):
        rr = reqtrace._REC
        if rr is not None:
            raise AssertionError("recorder appeared mid-measurement")
    per_call = (time.perf_counter() - t0) / iters

    added = count * per_call
    return {"feed_sites": count,
            "per_call_ns": round(per_call * 1e9, 1),
            "drive_best_s": round(best, 6),
            "disabled_overhead_pct": round(added / best * 100, 5)}


# ---------------------------------------------------------------------------
# gate 2: armed-path overhead (recorder installed, request bound)
# ---------------------------------------------------------------------------

def armed_overhead(reps: int, out_dir: str) -> dict:
    from spark_rapids_tpu.runtime.obs import live, reqtrace
    rec = reqtrace.install(out_dir=out_dir, sample_ratio=0.0,
                           replica_id="smoke")
    sess = _session()

    def drive():
        sess.sql(SQL).collect()

    drive()
    ctx = rec.begin()
    prev = live.bind_request(ctx)
    try:
        count = _counted_drive(drive)
        assert ctx.idx > 0, "bound drive fed no events into the ring"
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            drive()
            best = min(best, time.perf_counter() - t0)
        iters = 200_000
        t0 = time.perf_counter()
        for _ in range(iters):
            rec.feed("smoke", "exec", 0, 1, None, 7)
        per_call = (time.perf_counter() - t0) / iters
    finally:
        live.bind_request(prev)
        reqtrace.uninstall_for_tests()

    added = count * per_call
    return {"feed_sites": count,
            "per_call_ns": round(per_call * 1e9, 1),
            "drive_best_s": round(best, 6),
            "armed_overhead_pct": round(added / best * 100, 5)}


# ---------------------------------------------------------------------------
# gate 3: verdicts over the serving surface (seeded -> deterministic)
# ---------------------------------------------------------------------------

def serving_verdicts(out_dir: str, hits: int, ratio: float,
                     errors: int, result: dict) -> list:
    from spark_rapids_tpu.runtime import serving
    from spark_rapids_tpu.runtime.obs import reqtrace
    fails = []
    rec = reqtrace.install(out_dir=out_dir, sample_ratio=ratio,
                           min_interval_s=0.0, max_dumps=10_000,
                           replica_id="smoke-replica", sample_seed=SEED)
    # the serving session: reqtrace armed (first-wins -> the seeded
    # recorder above), a tiny absolute SLO so the one EXECUTED request
    # breaches (cache hits never run the epilogue, so they stay clean)
    _session({"spark.rapids.serving.enabled": "true",
              "spark.rapids.obs.slo.latencySeconds": "0.0005"})

    # -- the executed request: always kept, verdict slo_breach ----------
    code, doc = serving.handle_sql({"sql": SQL})
    rt = doc.get("reqtrace") or {}
    if code != 200 or doc.get("cache") != "miss":
        fails.append(f"seed request: code={code} cache={doc.get('cache')}")
    if rt.get("verdict") != "slo_breach" or not rt.get("path") \
            or not os.path.exists(rt.get("path") or ""):
        fails.append(f"executed SLO breach not exported: {rt}")
    if doc.get("replica_id") != "smoke-replica" or not doc.get("trace_id"):
        fails.append(f"response doc missing trace identity: "
                     f"replica={doc.get('replica_id')} "
                     f"trace={doc.get('trace_id')}")
    result["slo_breach"] = {"code": code, "verdict": rt.get("verdict"),
                            "path": rt.get("path")}

    # -- failed requests: 100% kept, verdict error ----------------------
    err_payload = {
        "sql": SQL, "session": "faulty", "cache": False,
        "conf": {"spark.rapids.debug.faults":
                 f"scan.decode:ioerror:{errors}"}}
    err_kept = 0
    for _ in range(errors):
        code, doc = serving.handle_sql(dict(err_payload))
        rt = doc.get("reqtrace") or {}
        if code != 500 or doc.get("status") != "failed":
            fails.append(f"fault request: code={code} "
                         f"status={doc.get('status')}")
        if rt.get("verdict") == "error" and rt.get("path") \
                and os.path.exists(rt["path"]):
            err_kept += 1
    if err_kept != errors:
        fails.append(f"only {err_kept}/{errors} failed requests exported")
    result["errors"] = {"sent": errors, "kept": err_kept}

    # -- hot cache hits: the seeded sampleRatio draw --------------------
    rng = random.Random(SEED)
    expected = sum(1 for _ in range(hits) if rng.random() < ratio)
    kept = 0
    for i in range(hits):
        payload = {"sql": SQL}
        if i == 0:
            payload["_traceparent"] = TP
        code, doc = serving.handle_sql(payload)
        if code != 200 or doc.get("cache") != "hit":
            fails.append(f"hit {i}: code={code} cache={doc.get('cache')}")
            break
        rt = doc.get("reqtrace") or {}
        if rt.get("verdict") == "sampled":
            kept += 1
        elif rt.get("verdict") != "dropped":
            fails.append(f"hit {i} landed verdict {rt.get('verdict')}")
            break
        if i == 0 and doc.get("trace_id") != TP_TID:
            fails.append(f"incoming traceparent not honored: "
                         f"{doc.get('trace_id')}")
    if kept != expected:
        fails.append(f"seeded sampler kept {kept} hits, expected "
                     f"{expected} (ratio {ratio})")
    if kept > max(1, int(hits * ratio * 3)):
        fails.append(f"kept {kept}/{hits} hot hits — far over the "
                     f"{ratio} sampleRatio")
    stats = rec.doc()
    if stats["exports"] != 1 + err_kept + kept:
        fails.append(f"recorder exports {stats['exports']} != "
                     f"{1 + err_kept + kept} kept requests")
    result["hits"] = {"sent": hits, "ratio": ratio, "kept": kept,
                      "expected": expected,
                      "dropped": stats["dropped"]}
    return fails


# ---------------------------------------------------------------------------
# gate 4: exported timelines validate (Chrome trace + OTLP sibling)
# ---------------------------------------------------------------------------

def validate_timelines(out_dir: str, result: dict) -> list:
    fails = []
    names = sorted(n for n in os.listdir(out_dir)
                   if n.startswith("req_") and n.endswith(".json")
                   and not n.endswith(".otlp.json"))
    if not names:
        return ["no exported timelines to validate"]
    joined = 0
    for name in names:
        path = os.path.join(out_dir, name)
        try:
            events = validate_chrome_trace(path)
        except ValueError as e:
            fails.append(str(e))
            continue
        meta = json.load(open(path)).get("otherData") or {}
        roots = [e for e in events if e["name"] == "request"]
        if len(roots) != 1 or not meta.get("trace_id", "").startswith(
                name[:-len(".json")].split("_")[-1]):
            fails.append(f"{name}: bad root span / trace id")
        serving_spans = {e["name"] for e in events
                         if e.get("cat") == "serving"}
        if "intake" not in serving_spans:
            fails.append(f"{name}: no serving intake span")
        # executed requests: engine exec spans joined by the query id
        if "execute" in serving_spans and meta.get("status") == "ok":
            qid = meta.get("query_id")
            exec_evs = [e for e in events if e.get("cat") != "serving"
                        and (e.get("args") or {}).get("query_id") == qid]
            if qid is None or not exec_evs:
                fails.append(f"{name}: executed timeline has no exec "
                             f"spans joined to query {qid}")
            else:
                joined += 1
        otlp = path[:-5] + ".otlp.json"
        if not os.path.exists(otlp):
            fails.append(f"{name}: missing OTLP sibling")
            continue
        spans = json.load(open(otlp))[
            "resourceSpans"][0]["scopeSpans"][0]["spans"]
        root_ids = {s["spanId"] for s in spans
                    if s["name"] == "POST /sql"}
        if len(root_ids) != 1 or any(
                s["traceId"] != meta["trace_id"] for s in spans):
            fails.append(f"{name}: OTLP trace/root identity broken")
        elif any(s["name"] != "POST /sql"
                 and s.get("parentSpanId") not in root_ids
                 and not any(p["spanId"] == s["parentSpanId"]
                             for p in spans) for s in spans):
            fails.append(f"{name}: OTLP span parents dangle")
    if joined == 0:
        fails.append("no executed timeline carried joined exec spans")
    result["timelines"] = {"artifacts": len(names), "joined": joined}
    return fails


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tolerance", type=float, default=0.02)
    ap.add_argument("--hits", type=int, default=240)
    ap.add_argument("--ratio", type=float, default=0.05)
    ap.add_argument("--errors", type=int, default=3)
    args = ap.parse_args()

    fails = []
    result = {}

    print("[gate 1] disabled-path overhead (count x delta)...",
          flush=True)
    oh = disabled_overhead(args.reps)
    result["disabled"] = oh
    print(f"  {oh['feed_sites']} feed sites x {oh['per_call_ns']}ns over "
          f"{oh['drive_best_s']}s drive -> {oh['disabled_overhead_pct']}%"
          f" (gate < {args.tolerance * 100:.0f}%)")
    if oh["disabled_overhead_pct"] > args.tolerance * 100:
        fails.append("disabled-path reqtrace overhead over budget")

    with tempfile.TemporaryDirectory(prefix="reqtrace_smoke_") as d:
        print("[gate 2] armed-path overhead (request-bound drive)...",
              flush=True)
        ah = armed_overhead(args.reps, os.path.join(d, "unused"))
        result["armed"] = ah
        print(f"  {ah['feed_sites']} feed sites x {ah['per_call_ns']}ns "
              f"over {ah['drive_best_s']}s drive -> "
              f"{ah['armed_overhead_pct']}%")
        if ah["armed_overhead_pct"] > args.tolerance * 100:
            fails.append("armed reqtrace overhead over budget")

        out_dir = os.path.join(d, "reqtrace")
        print("[gate 3] verdicts over the serving surface...", flush=True)
        fails.extend(serving_verdicts(out_dir, args.hits, args.ratio,
                                      args.errors, result))
        print(f"  slo_breach={result.get('slo_breach', {}).get('verdict')}"
              f" errors={result.get('errors')} hits={result.get('hits')}")

        print("[gate 4] exported timelines validate...", flush=True)
        fails.extend(validate_timelines(out_dir, result))
        print(f"  {result.get('timelines')}")

    print(json.dumps(result, sort_keys=True))
    if fails:
        print("reqtrace_smoke: FAIL")
        for f in fails:
            print(f"  - {f}")
        return 1
    h = result["hits"]
    print(f"reqtrace_smoke: PASS (errors/SLO breaches 100% exported; "
          f"{h['kept']}/{h['sent']} hot hits kept at ratio {h['ratio']}; "
          f"disabled {oh['disabled_overhead_pct']}% / armed "
          f"{ah['armed_overhead_pct']}%; {result['timelines']['artifacts']}"
          f" timelines Chrome+OTLP valid)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
