"""Observability smoke: /metrics scrape, /healthz flip, history round-trip.

The live-layer CI gate (tools/ci_check.sh):

1. start a session with `spark.rapids.obs.port` (a free ephemeral port)
   and `spark.rapids.obs.historyDir`; drive queries from a background
   thread and SCRAPE WHILE THEY RUN;
2. /metrics must be Prometheus-parseable (every line a comment or
   `name{labels} value`) and include the acceptance roster: semaphore
   wait, spill bytes, retry count, the per-query wall-time histogram;
3. /healthz must report ok (HTTP 200) with a live device probe, then
   flip to degraded (HTTP 503) when the probe is blocked;
4. the history store must round-trip: two runs of the same query produce
   two records with the SAME plan digest and per-exec rollups;
5. LIVE progress (runtime/obs/live.py): while a multi-batch NDS-shaped
   probe query runs, /queries must answer at least 3 mid-flight scrapes
   showing the query executing with MONOTONE non-decreasing scan-row
   progress, and after completion last_completed must report 100% with
   a plan digest matching the query's history record;
6. the resource sampler (runtime/obs/sampler.py): rapids_sampler_*
   series present on /metrics, and the next flight dump embeds the
   sampler rings as Chrome counter tracks plus ring events tagged with
   the live query id (cross-thread correlation) and the queryStart t0
   marker;
7. always-on live-layer overhead <2% of the probe query's wall time by
   the count x delta methodology (tools/trace_overhead.py /
   flight_smoke.py): events-that-paid-a-thread-local-read x measured
   per-read cost, plus sampler ticks x measured tick cost;
8. the disabled path must stay free: obs.on_task_complete with obs off
   is one global read — measured per-call and gated.

Run:  python tools/obs_smoke.py
"""
from __future__ import annotations

import json
import os
import re
import socket
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"[-+]?(\d+\.?\d*([eE][-+]?\d+)?|NaN|nan|[Ii]nf)$")

ROSTER = (
    "rapids_semaphore_wait_ns_total",
    "rapids_spill_to_host_bytes_total",
    "rapids_retries_total",
    "rapids_query_wall_time_ms",
    "rapids_tasks_completed_total",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def check_prometheus(text: str) -> int:
    """Validate exposition-format lines; returns sample-line count."""
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if not _METRIC_LINE.match(line):
            raise AssertionError(f"unparseable metrics line: {line!r}")
        n += 1
    return n


def main() -> int:
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.runtime import obs
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSession

    obs.shutdown_for_tests()  # fresh singleton (port, registry)
    hist_dir = tempfile.mkdtemp(prefix="obs_smoke_hist_")
    port = _free_port()
    sess = TpuSession({
        "spark.rapids.obs.port": str(port),
        "spark.rapids.obs.historyDir": hist_dir,
        "spark.rapids.sql.reader.batchSizeRows": "4096",
    })
    rng = np.random.default_rng(7)
    t = pa.table({"k": rng.integers(0, 50, 200_000),
                  "v": rng.integers(0, 1000, 200_000)})

    def query():
        return (sess.create_dataframe(t, num_partitions=4)
                .filter(col("v") > lit(10))
                .select(col("k"), (col("v") * lit(2)).alias("v2"))
                .group_by("k").agg(F.sum(col("v2"))).collect())

    # -- scrape while a query runs ----------------------------------------
    errors: list = []

    def driver():
        try:
            for _ in range(3):
                query()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    th = threading.Thread(target=driver)
    th.start()
    mid_scrapes = 0
    while th.is_alive():
        code, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200, f"/metrics -> {code}"
        check_prometheus(body)
        mid_scrapes += 1
        time.sleep(0.05)
    th.join()
    assert not errors, f"query failed under scrape: {errors}"
    assert mid_scrapes >= 1, "no scrape landed while queries ran"

    code, body = _get(f"http://127.0.0.1:{port}/metrics")
    assert code == 200
    samples = check_prometheus(body)
    for name in ROSTER:
        assert name in body, f"roster metric {name} missing from /metrics"
    wall_count = [line for line in body.splitlines()
                  if line.startswith("rapids_query_wall_time_ms_count")]
    assert wall_count and int(wall_count[0].split()[-1]) >= 3, wall_count

    # -- healthz: ok, then degraded under a blocked probe ------------------
    code, hz = _get(f"http://127.0.0.1:{port}/healthz")
    doc = json.loads(hz)
    assert code == 200 and doc["status"] == "ok", (code, doc)
    assert doc["semaphore"] is not None and doc["device"]["alive"]
    obs.set_device_probe(lambda: time.sleep(60) or True)
    t0 = time.time()
    code, hz = _get(f"http://127.0.0.1:{port}/healthz")
    doc = json.loads(hz)
    assert code == 503 and doc["status"] == "degraded", (code, doc)
    assert doc["device"]["blocked"], doc["device"]
    probe_wait = time.time() - t0
    from spark_rapids_tpu.runtime.obs.endpoint import default_device_probe
    obs.set_device_probe(default_device_probe)

    # -- history round-trip ------------------------------------------------
    recs = [r for r in obs.state().history.read_all()
            if r.get("type") == "query"]
    assert len(recs) >= 3, f"expected >=3 history records, got {len(recs)}"
    digests = {r["plan_digest"] for r in recs}
    assert len(digests) == 1 and None not in digests, \
        f"same query must share one digest, got {digests}"
    assert all(r["status"] == "ok" and r.get("execs") for r in recs)

    # -- live progress: monotone mid-flight /queries scrapes ----------------
    from spark_rapids_tpu.runtime.obs import flight, live, sampler

    big = pa.table({"k": rng.integers(0, 50, 600_000),
                    "v": rng.integers(0, 1000, 600_000)})
    probe_sess = TpuSession({
        "spark.rapids.sql.reader.batchSizeRows": "2048",
    })

    def probe_query():
        return (probe_sess.create_dataframe(big, num_partitions=4)
                .filter(col("v") > lit(10))
                .select(col("k"), (col("v") * lit(2)).alias("v2"))
                .group_by("k").agg(F.sum(col("v2"))).collect())

    perrors: list = []

    def pdriver():
        try:
            probe_query()
        except Exception as e:  # noqa: BLE001
            perrors.append(e)

    pth = threading.Thread(target=pdriver)
    pth.start()
    snaps = []
    while pth.is_alive():
        code, qbody = _get(f"http://127.0.0.1:{port}/queries")
        assert code == 200, f"/queries -> {code}"
        qdoc = json.loads(qbody)
        for d in qdoc.get("running") or []:
            if d.get("state") == "executing" and d.get("execs"):
                snaps.append(d)
        time.sleep(0.03)
    pth.join()
    assert not perrors, f"probe query failed under scrape: {perrors}"
    assert len(snaps) >= 3, \
        f"need >=3 mid-flight executing scrapes, got {len(snaps)}"
    qids = {d["query_id"] for d in snaps}
    assert len(qids) == 1, f"one probe query expected, saw ids {qids}"
    rows_seen = [d["scan_rows"] for d in snaps]
    assert rows_seen == sorted(rows_seen), \
        f"scan-row progress must be monotone, got {rows_seen}"
    assert any(d.get("percent_complete") is not None for d in snaps), \
        "no mid-flight scrape carried percent_complete/ETA"
    last = json.loads(_get(f"http://127.0.0.1:{port}/queries")[1]
                      )["last_completed"]
    assert last and last["state"] == "ok" and \
        last.get("percent_complete") == 100.0, last
    probe_recs = [r for r in obs.state().history.read_all()
                  if r.get("plan_digest") == last["plan_digest"]]
    assert probe_recs, "last_completed digest has no history record"

    # -- sampler on /metrics, in flight dumps; correlation + t0 marker ------
    code, mbody = _get(f"http://127.0.0.1:{port}/metrics")
    assert code == 200
    for series in sampler.SERIES:
        assert f"rapids_sampler_{series}" in mbody, \
            f"sampler series {series} missing from /metrics"
    smp = sampler.sampler()
    assert smp is not None and smp.ticks > 0, "sampler never ticked"
    dump_path = flight.dump("smoke_probe")
    assert dump_path, "flight dump rate-limited or recorder missing"
    with open(dump_path) as f:
        dump_events = json.load(f)["traceEvents"]
    counters = {e["name"] for e in dump_events if e.get("ph") == "C"}
    assert {f"sampler/{s}" for s in sampler.SERIES} <= counters, \
        f"sampler counter tracks missing from flight dump: {counters}"
    probe_qid = next(iter(qids))
    tagged = [e for e in dump_events
              if (e.get("args") or {}).get("query_id") == probe_qid]
    assert tagged, "no flight event carries the probe query's id"
    starts = [e for e in dump_events if e["name"] == "queryStart"
              and (e.get("args") or {}).get("query_id") == probe_qid]
    assert starts, "flight dump lacks the probe query's queryStart t0"
    assert starts[0]["args"].get("plan_digest") == last["plan_digest"]

    # -- always-on live-layer overhead <2% (count x delta) ------------------
    # per-event addition: ONE thread-local read (live.current_query_id)
    # on every flight-ring record / trace event / task construction.
    iters = 200_000
    live.bind(12345)
    t0 = time.perf_counter()
    for _ in range(iters):
        live.current_query_id()
    tls_read_s = (time.perf_counter() - t0) / iters
    live.bind(None)
    rec = flight.recorder()
    n_events = sum(r.idx for r in rec._rings) if rec is not None else 0
    n_tasks = obs.state().registry.counter(
        "rapids_tasks_completed_total").value
    wall_s = last["wall_ms"] / 1000.0
    # steady-state tick cost, measured in isolation (best-of like the
    # flight_smoke per-call deltas): a single observed tick is routinely
    # inflated by lazy imports or GIL contention from the probe query.
    # Measured on a DETACHED sampler instance — the installed one's
    # rings are single-writer (its service thread), so the smoke must
    # not tick them concurrently
    probe_smp = sampler.ResourceSampler(interval_ms=200, ring_size=8)
    tick_costs = []
    for _ in range(20):
        tt0 = time.perf_counter_ns()
        probe_smp.sample_once()
        tick_costs.append(time.perf_counter_ns() - tt0)
    tick_cost_s = min(tick_costs) / 1e9
    ticks_per_query = wall_s / smp.interval_s
    added_s = ((n_events + n_tasks) * tls_read_s
               + ticks_per_query * tick_cost_s)
    live_overhead = added_s / wall_s
    assert live_overhead < 0.02, \
        (f"live-layer overhead {live_overhead:.4f} "
         f"({n_events} events x {tls_read_s * 1e9:.0f}ns + "
         f"{ticks_per_query:.1f} ticks x {tick_cost_s * 1e6:.0f}us over "
         f"{wall_s:.2f}s)")

    # -- disabled path stays free ------------------------------------------
    obs.shutdown_for_tests()

    class _Ctx:  # the shape on_task_complete reads
        _failed = False
        _metrics: dict = {}
        start_ns = 0

    ctx = _Ctx()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs.on_task_complete(ctx)
    per_call_ns = (time.perf_counter() - t0) / n * 1e9
    assert per_call_ns < 1000, \
        f"disabled obs hook costs {per_call_ns:.0f}ns/call"

    print(json.dumps({
        "metrics_samples": samples,
        "mid_query_scrapes": mid_scrapes,
        "healthz_degraded_after_s": round(probe_wait, 2),
        "history_records": len(recs),
        "plan_digest": next(iter(digests)),
        "disabled_hook_ns_per_call": round(per_call_ns, 1),
        "progress_scrapes_executing": len(snaps),
        "progress_rows_trajectory": rows_seen[:8],
        "probe_wall_s": round(wall_s, 3),
        "live_overhead_fraction": round(live_overhead, 5),
        "tls_read_ns": round(tls_read_s * 1e9, 1),
        "sampler_tick_us": round(tick_cost_s * 1e6, 1),
        "flight_dump": dump_path,
    }))
    print("PASS: /metrics parseable + roster present, /healthz flips to "
          "degraded on a blocked probe, history round-trips with a "
          "stable digest, /queries shows monotone mid-flight progress "
          "ending at 100%, sampler series on /metrics + inside the "
          "flight dump with query-id-tagged events, live overhead <2%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
