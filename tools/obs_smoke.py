"""Observability smoke: /metrics scrape, /healthz flip, history round-trip.

The live-layer CI gate (tools/ci_check.sh):

1. start a session with `spark.rapids.obs.port` (a free ephemeral port)
   and `spark.rapids.obs.historyDir`; drive queries from a background
   thread and SCRAPE WHILE THEY RUN;
2. /metrics must be Prometheus-parseable (every line a comment or
   `name{labels} value`) and include the acceptance roster: semaphore
   wait, spill bytes, retry count, the per-query wall-time histogram;
3. /healthz must report ok (HTTP 200) with a live device probe, then
   flip to degraded (HTTP 503) when the probe is blocked;
4. the history store must round-trip: two runs of the same query produce
   two records with the SAME plan digest and per-exec rollups;
5. the disabled path must stay free: obs.on_task_complete with obs off
   is one global read — measured per-call and gated.

Run:  python tools/obs_smoke.py
"""
from __future__ import annotations

import json
import os
import re
import socket
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? "
    r"[-+]?(\d+\.?\d*([eE][-+]?\d+)?|NaN|nan|[Ii]nf)$")

ROSTER = (
    "rapids_semaphore_wait_ns_total",
    "rapids_spill_to_host_bytes_total",
    "rapids_retries_total",
    "rapids_query_wall_time_ms",
    "rapids_tasks_completed_total",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def check_prometheus(text: str) -> int:
    """Validate exposition-format lines; returns sample-line count."""
    n = 0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if not _METRIC_LINE.match(line):
            raise AssertionError(f"unparseable metrics line: {line!r}")
        n += 1
    return n


def main() -> int:
    import numpy as np
    import pyarrow as pa

    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.runtime import obs
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSession

    obs.shutdown_for_tests()  # fresh singleton (port, registry)
    hist_dir = tempfile.mkdtemp(prefix="obs_smoke_hist_")
    port = _free_port()
    sess = TpuSession({
        "spark.rapids.obs.port": str(port),
        "spark.rapids.obs.historyDir": hist_dir,
        "spark.rapids.sql.reader.batchSizeRows": "4096",
    })
    rng = np.random.default_rng(7)
    t = pa.table({"k": rng.integers(0, 50, 200_000),
                  "v": rng.integers(0, 1000, 200_000)})

    def query():
        return (sess.create_dataframe(t, num_partitions=4)
                .filter(col("v") > lit(10))
                .select(col("k"), (col("v") * lit(2)).alias("v2"))
                .group_by("k").agg(F.sum(col("v2"))).collect())

    # -- scrape while a query runs ----------------------------------------
    errors: list = []

    def driver():
        try:
            for _ in range(3):
                query()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    th = threading.Thread(target=driver)
    th.start()
    mid_scrapes = 0
    while th.is_alive():
        code, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200, f"/metrics -> {code}"
        check_prometheus(body)
        mid_scrapes += 1
        time.sleep(0.05)
    th.join()
    assert not errors, f"query failed under scrape: {errors}"
    assert mid_scrapes >= 1, "no scrape landed while queries ran"

    code, body = _get(f"http://127.0.0.1:{port}/metrics")
    assert code == 200
    samples = check_prometheus(body)
    for name in ROSTER:
        assert name in body, f"roster metric {name} missing from /metrics"
    wall_count = [line for line in body.splitlines()
                  if line.startswith("rapids_query_wall_time_ms_count")]
    assert wall_count and int(wall_count[0].split()[-1]) >= 3, wall_count

    # -- healthz: ok, then degraded under a blocked probe ------------------
    code, hz = _get(f"http://127.0.0.1:{port}/healthz")
    doc = json.loads(hz)
    assert code == 200 and doc["status"] == "ok", (code, doc)
    assert doc["semaphore"] is not None and doc["device"]["alive"]
    obs.set_device_probe(lambda: time.sleep(60) or True)
    t0 = time.time()
    code, hz = _get(f"http://127.0.0.1:{port}/healthz")
    doc = json.loads(hz)
    assert code == 503 and doc["status"] == "degraded", (code, doc)
    assert doc["device"]["blocked"], doc["device"]
    probe_wait = time.time() - t0
    from spark_rapids_tpu.runtime.obs.endpoint import default_device_probe
    obs.set_device_probe(default_device_probe)

    # -- history round-trip ------------------------------------------------
    recs = [r for r in obs.state().history.read_all()
            if r.get("type") == "query"]
    assert len(recs) >= 3, f"expected >=3 history records, got {len(recs)}"
    digests = {r["plan_digest"] for r in recs}
    assert len(digests) == 1 and None not in digests, \
        f"same query must share one digest, got {digests}"
    assert all(r["status"] == "ok" and r.get("execs") for r in recs)

    # -- disabled path stays free ------------------------------------------
    obs.shutdown_for_tests()

    class _Ctx:  # the shape on_task_complete reads
        _failed = False
        _metrics: dict = {}
        start_ns = 0

    ctx = _Ctx()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs.on_task_complete(ctx)
    per_call_ns = (time.perf_counter() - t0) / n * 1e9
    assert per_call_ns < 1000, \
        f"disabled obs hook costs {per_call_ns:.0f}ns/call"

    print(json.dumps({
        "metrics_samples": samples,
        "mid_query_scrapes": mid_scrapes,
        "healthz_degraded_after_s": round(probe_wait, 2),
        "history_records": len(recs),
        "plan_digest": next(iter(digests)),
        "disabled_hook_ns_per_call": round(per_call_ns, 1),
    }))
    print("PASS: /metrics parseable + roster present, /healthz flips to "
          "degraded on a blocked probe, history round-trips with a "
          "stable digest")
    return 0


if __name__ == "__main__":
    sys.exit(main())
