"""Static HTML renderer for the query history store (the Spark SQL tab
analog for a standalone engine).

Given a `spark.rapids.obs.historyDir` containing query_history.jsonl
(written by the engine's query epilogue and by tools/nds_probe.py),
renders:

- index.html — the query list (id, start time, status, wall ms, digest,
  fallback count) newest first, plus the NDS scorecard records;
- query_<n>.html — one page per query: the physical plan annotated with
  per-exec rollups, HOT-PATH HIGHLIGHTING (execs above 15% of total
  operator time render highlighted), the wall-time ATTRIBUTION BAR
  (phase buckets from runtime/obs/attribution.py), SLO-breach details,
  fusion groups, fallback reasons, config delta, trace artifact paths
  and the flight-recorder dump of failed/degraded/slow queries;
- diff_<digest>.html — for every plan digest with >= 2 runs, a
  run-over-run diff of the latest two runs: per-exec metric deltas side
  by side (the regression-hunting view: same plan, what moved?);
- console.html (with --engine) — the LIVE console: an auto-refreshing
  page polling a running engine's /queries + /healthz endpoint
  (spark.rapids.obs.port) from the browser, rendering in-flight query
  progress bars, state timelines and sampler gauges next to the static
  history. Cross-origin polling requires the engine to opt in with
  spark.rapids.obs.corsOrigin (this site's origin, or '*' on a
  trusted host) — /queries carries in-flight SQL text, so CORS is off
  by default. The engine also serves the same view server-side at
  /console (runtime/obs/console.py), which needs no CORS.

Everything is self-contained static HTML (inline CSS; the live console
is the one page with inline JS, because a static site cannot poll) so
the output can be dropped behind any file server.

Run:  python tools/history_server.py <historyDir> [--out DIR]
      python tools/history_server.py <historyDir> --serve PORT
      python tools/history_server.py <historyDir> --engine http://127.0.0.1:9090
"""
from __future__ import annotations

import argparse
import html
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_rapids_tpu.runtime.obs.history import (  # noqa: E402
    QueryHistoryStore,
)

_CSS = """
body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
       margin: 2em auto; max-width: 1100px; color: #1a1a2e; }
table { border-collapse: collapse; width: 100%; margin: 1em 0; }
th, td { border: 1px solid #d0d0e0; padding: 4px 10px; text-align: left;
         font-size: 14px; }
th { background: #f0f0f8; }
tr.failed td { background: #fde8e8; }
tr.degraded td { background: #fff4de; }
pre { background: #f6f6fb; padding: 1em; overflow-x: auto;
      font-size: 13px; line-height: 1.45; }
.hot { background: #ffe2b8; font-weight: bold; }
.num { text-align: right; font-variant-numeric: tabular-nums; }
.delta-up { color: #b00020; font-weight: bold; }
.delta-down { color: #0a7a2f; font-weight: bold; }
.badge-ok { color: #0a7a2f; } .badge-failed { color: #b00020; }
.badge-degraded { color: #b06f00; } .badge-slow { color: #6a1b9a; }
tr.slow td { background: #f3e8fd; }
.attr-bar { display: flex; height: 22px; width: 100%; margin: 0.5em 0;
            border: 1px solid #d0d0e0; border-radius: 3px;
            overflow: hidden; }
.attr-bar span { display: block; height: 100%; }
.attr-legend { font-size: 13px; }
.attr-chip { display: inline-block; width: 0.8em; height: 0.8em;
             margin-right: 0.3em; border-radius: 2px;
             vertical-align: baseline; }
h1, h2 { font-weight: 600; }
a { color: #3949ab; }
small.digest { font-family: monospace; color: #666; }
"""

#: one stable color per attribution bucket (the bar + legend share it)
_BUCKET_COLORS = {
    "compile": "#8e7cc3", "device_compute": "#3949ab",
    "host_decode": "#43a047", "shuffle": "#fb8c00",
    "semaphore_wait": "#fdd835", "pipeline_stall": "#e53935",
    "retry_backoff": "#d81b60", "spill": "#6d4c41", "other": "#b0bec5",
}


def _page(title: str, body: str) -> str:
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>{_CSS}</style>"
            f"</head><body><h1>{html.escape(title)}</h1>{body}"
            f"</body></html>")


def _esc(x) -> str:
    return html.escape(str(x))


def _fmt_time(unix: Optional[float]) -> str:
    if not unix:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(unix))


def _rollups(rec: dict) -> Dict[str, dict]:
    """exec_key -> rollup dict ({rows,batches,dispatches,time_ns})."""
    out = {}
    for k, snap in (rec.get("execs") or {}).items():
        out[k] = snap.get("_rollup") or {}
    return out


def _page_names(records: List[dict]) -> Dict[int, str]:
    """record index -> unique page name. query_id restarts at 1 per
    PROCESS, so (id, second) collides across processes appending to one
    store — the store position disambiguates."""
    return {i: f"query_{i}_{rec.get('query_id')}.html"
            for i, rec in enumerate(records)
            if rec.get("type") != "nds_scorecard"}


# ---------------------------------------------------------------------------
# per-query page
# ---------------------------------------------------------------------------

_TIME_RE = re.compile(r"time=([0-9.]+)ms")


def render_attribution(attr: dict) -> str:
    """The wall-time breakdown bar: one colored segment per nonzero
    bucket (width = fraction of wall), plus a legend table."""
    buckets = attr.get("buckets") or {}
    fracs = attr.get("fractions") or {}
    ranked = sorted(((b, s) for b, s in buckets.items() if s > 0),
                    key=lambda kv: -kv[1])
    if not ranked:
        return ""
    segs, legend = [], []
    for b, s in ranked:
        frac = fracs.get(b, 0.0)
        color = _BUCKET_COLORS.get(b, "#999")
        segs.append(f"<span style='width:{frac * 100:.2f}%;"
                    f"background:{color}' "
                    f"title='{_esc(b)} {s:.3f}s ({frac * 100:.1f}%)'>"
                    f"</span>")
        legend.append(f"<span class='attr-chip' style='background:"
                      f"{color}'></span>{_esc(b)} {s:.3f}s "
                      f"({frac * 100:.1f}%)")
    conc = attr.get("concurrency_factor", 1.0)
    note = (f" · measured {attr.get('measured_seconds', 0):.3f}s across "
            f"concurrent tasks ({conc:.1f}x wall, shown as "
            f"critical-path shares)" if conc and conc > 1.0 else "")
    return (f"<h2>Time attribution</h2>"
            f"<p class='attr-legend'>wall "
            f"{attr.get('wall_seconds', 0):.3f}s{note}</p>"
            f"<div class='attr-bar'>{''.join(segs)}</div>"
            f"<p class='attr-legend'>{' · '.join(legend)}</p>")


def render_query_page(rec: dict) -> str:
    # the record carries the plan ALREADY annotated by the engine's own
    # canonical walk (session.explain_analyze) — renderer-side matching
    # of plan lines to metric keys is impossible to get right because
    # tree_string prints fused members parent-most first while the
    # metric keys assign child-most first. Here we only highlight: a
    # line whose annotated time is >= 15% of the plan total is hot.
    plan = rec.get("annotated_plan") or rec.get("physical_plan") or ""
    line_times = [float(m.group(1)) if (m := _TIME_RE.search(ln))
                  else None for ln in plan.splitlines()]
    total_ms = sum(t for t in line_times if t) or 1.0
    hot_cut = 0.15 * total_ms
    out_lines = []
    for line, t in zip(plan.splitlines(), line_times):
        hot = t is not None and t > 0 and t >= hot_cut
        out_lines.append(f"<span class='hot'>{_esc(line)}</span>"
                         if hot else _esc(line))

    body = [f"<p>status <b class='badge-{rec.get('status', 'ok')}'>"
            f"{_esc(rec.get('status'))}</b>"
            + (" <b class='badge-slow'>[SLO breach]</b>"
               if rec.get("slo_breach") else "")
            + (f" [degraded to CPU: {_esc(rec.get('degraded_reason'))}]"
               if rec.get("degraded_reason") else "")
            + (f" ({_esc(rec.get('error_class'))}: "
               f"{_esc(rec.get('error', ''))})"
               if rec.get("error_class") else "")
            + f" · started {_fmt_time(rec.get('wall_start_unix'))}"
            f" · wall {rec.get('duration_ns', 0) / 1e6:.1f} ms"
            f" · digest <small class='digest'>"
            f"{_esc(rec.get('plan_digest'))}</small>"
            + (f" · replica <code>{_esc(rec.get('replica_id'))}</code>"
               if rec.get("replica_id") else "") + "</p>"]
    if rec.get("trace_id"):
        # the serving request that carried this query: the join key into
        # its exported per-request timeline (reqtrace/)
        body.append(f"<p>Trace: <small class='digest'>"
                    f"{_esc(rec.get('trace_id'))}</small> — per-request "
                    f"timeline under the replica's reqtrace dir when the "
                    f"sampling verdict kept it</p>")
    if rec.get("slo_breach"):
        b = rec["slo_breach"]
        body.append(
            f"<p class='badge-slow'>SLO breach ({_esc(b.get('kind'))}): "
            f"{b.get('seconds', 0):.3f}s against threshold "
            f"{b.get('threshold_seconds', 0):.3f}s"
            + (f" (baseline {b.get('baseline_seconds', 0):.3f}s over "
               f"{_esc(b.get('runs'))} runs)"
               if b.get("kind") == "baseline" else "") + "</p>")
    if rec.get("attribution"):
        body.append(render_attribution(rec["attribution"]))
    if rec.get("flight_dump"):
        # the retroactive timeline of a failed/degraded/slow query
        body.append(f"<p>Flight-recorder dump: <code>"
                    f"{_esc(rec['flight_dump'])}</code> "
                    f"(Chrome-trace/Perfetto loadable)</p>")
    body.append("<h2>Annotated plan</h2><pre>"
                + "\n".join(out_lines) + "</pre>")

    if rec.get("fusion_groups"):
        body.append("<h2>Fusion groups</h2><table><tr><th>stage</th>"
                    "<th>kind</th><th>members</th></tr>")
        for g in rec["fusion_groups"]:
            body.append(f"<tr><td>*({_esc(g.get('stage_id'))})</td>"
                        f"<td>{_esc(g.get('kind'))}</td>"
                        f"<td>{_esc(' → '.join(g.get('members', [])))}"
                        f"</td></tr>")
        body.append("</table>")

    if rec.get("fallback_reasons"):
        body.append("<h2>Fallback reasons</h2><ul>")
        for r in rec["fallback_reasons"]:
            body.append(f"<li>{_esc(r)}</li>")
        body.append("</ul>")

    if rec.get("conf_delta"):
        body.append("<h2>Config delta (vs defaults)</h2><table>"
                    "<tr><th>key</th><th>value</th></tr>")
        for k in sorted(rec["conf_delta"]):
            body.append(f"<tr><td><code>{_esc(k)}</code></td>"
                        f"<td>{_esc(rec['conf_delta'][k])}</td></tr>")
        body.append("</table>")

    if rec.get("trace_paths"):
        body.append("<h2>Trace artifacts</h2><ul>")
        for k, p in rec["trace_paths"].items():
            body.append(f"<li>{_esc(k)}: <code>{_esc(p)}</code></li>")
        body.append("</ul>")

    body.append("<p><a href='index.html'>&larr; query list</a></p>")
    return _page(f"Query {rec.get('query_id')}", "\n".join(body))


# ---------------------------------------------------------------------------
# run-over-run diff
# ---------------------------------------------------------------------------

def render_diff_page(digest: str, older: dict, newer: dict) -> str:
    ra, rb = _rollups(older), _rollups(newer)
    keys = sorted(set(ra) | set(rb),
                  key=lambda k: (k.split("#")[0], int(k.split("#")[1])))
    rows = ["<table><tr><th>exec</th>"
            "<th class='num'>rows (old → new)</th>"
            "<th class='num'>time ms (old → new)</th>"
            "<th class='num'>Δ time</th></tr>"]
    for k in keys:
        a, b = ra.get(k, {}), rb.get(k, {})
        ta, tb = a.get("time_ns", 0) / 1e6, b.get("time_ns", 0) / 1e6
        delta = tb - ta
        cls = ("delta-up" if delta > ta * 0.1 + 0.01
               else "delta-down" if delta < -ta * 0.1 - 0.01 else "")
        rows.append(
            f"<tr><td>{_esc(k)}</td>"
            f"<td class='num'>{a.get('rows', 0)} → {b.get('rows', 0)}</td>"
            f"<td class='num'>{ta:.3f} → {tb:.3f}</td>"
            f"<td class='num {cls}'>{delta:+.3f}</td></tr>")
    rows.append("</table>")
    wall = (f"<p>wall: {older.get('duration_ns', 0) / 1e6:.1f} ms → "
            f"{newer.get('duration_ns', 0) / 1e6:.1f} ms · runs "
            f"{_fmt_time(older.get('wall_start_unix'))} vs "
            f"{_fmt_time(newer.get('wall_start_unix'))}</p>")
    conf_note = ("<p><b>config changed between runs</b></p>"
                 if older.get("conf_delta") != newer.get("conf_delta")
                 else "")
    return _page(f"Diff {digest}",
                 wall + conf_note + "\n".join(rows)
                 + "<p><a href='index.html'>&larr; query list</a></p>")


# ---------------------------------------------------------------------------
# live console (polls a running engine's obs endpoint)
# ---------------------------------------------------------------------------

def render_live_console(engine_url: str, refresh_seconds: int = 2) -> str:
    """The live half of the history site: a self-contained page whose
    inline JS polls the engine's /queries and /healthz (CORS is open on
    the obs endpoint) and redraws the running-query table, progress
    bars and the sampler's latest gauges. Degrades gracefully to an
    'engine unreachable' banner when the process is down."""
    eng = engine_url.rstrip("/")
    return f"""<!doctype html><html><head><meta charset='utf-8'>
<title>live console</title><style>{_CSS}
.pbar {{ background: #e8e8f2; border-radius: 3px; width: 140px;
        height: 12px; display: inline-block; vertical-align: middle; }}
.pbar span {{ background: #3949ab; height: 100%; display: block;
             border-radius: 3px; }}
#err {{ color: #b00020; }}</style></head><body>
<h1>spark-rapids-tpu live console</h1>
<p><small>engine <code>{html.escape(eng)}</code> · refresh
{refresh_seconds}s · <a href='{html.escape(eng)}/console'>server-rendered
view</a> · <a href='{html.escape(eng)}/serving'>serving doc</a> ·
<a href='index.html'>&larr; history</a></small></p>
<p id='err'></p>
<h2>Running queries</h2><div id='running'>-</div>
<h2>Last completed</h2><div id='last'>-</div>
<h2>Serving</h2><div id='serving'>-</div>
<h2>Resources (latest samples)</h2><div id='sampler'>-</div>
<script>
const ENG = {json.dumps(eng)};
function row(d) {{
  const pct = d.percent_complete;
  const bar = pct == null ? (d.scan_rows || 0) + ' rows'
    : "<span class='pbar'><span style='width:" + pct.toFixed(0)
      + "%'></span></span> " + pct.toFixed(1) + "%"
      + (d.eta_seconds ? " · eta " + d.eta_seconds.toFixed(1) + "s" : "");
  return "<tr><td>" + d.query_id + "</td><td>" + d.state + "</td>"
    + "<td class='num'>" + (d.elapsed_seconds || 0).toFixed(2) + "s</td>"
    + "<td>" + bar + "</td><td><small class='digest'>"
    + (d.plan_digest || "") + "</small></td></tr>";
}}
function table(docs) {{
  if (!docs || !docs.length) return "<p>idle</p>";
  return "<table><tr><th>id</th><th>state</th><th>elapsed</th>"
    + "<th>progress</th><th>digest</th></tr>"
    + docs.map(row).join("") + "</table>";
}}
async function tick() {{
  try {{
    const q = await (await fetch(ENG + "/queries")).json();
    document.getElementById("running").innerHTML = table(q.running);
    document.getElementById("last").innerHTML =
      table(q.last_completed ? [q.last_completed] : []);
    const hz = await (await fetch(ENG + "/healthz")).json().catch(e => null);
    if (hz && hz.serving) {{
      const s = hz.serving, rc = s.result_cache || {{}};
      document.getElementById("serving").innerHTML =
        "<table><tr><th>active</th><th>queue depth</th><th>sessions</th>"
        + "<th>requests</th><th>rejected</th><th>cache hit ratio</th></tr>"
        + "<tr><td class='num'>" + s.active_requests + "/" + s.max_inflight
        + "</td><td class='num'>" + s.queue_depth
        + "</td><td class='num'>" + s.sessions + "/" + s.max_sessions
        + "</td><td class='num'>" + s.requests
        + "</td><td class='num'>" + s.rejected
        + "</td><td class='num'>" + (rc.hit_ratio || 0).toFixed(2)
        + "</td></tr></table>";
    }} else {{
      document.getElementById("serving").innerHTML =
        "<p>serving layer off (spark.rapids.serving.enabled)</p>";
    }}
    if (hz && hz.sampler && hz.sampler.latest) {{
      const rows = Object.entries(hz.sampler.latest).map(
        ([k, v]) => "<tr><td>" + k + "</td><td class='num'>" + v
          + "</td></tr>").join("");
      document.getElementById("sampler").innerHTML =
        "<table><tr><th>series</th><th>value</th></tr>" + rows + "</table>";
    }}
    document.getElementById("err").textContent = "";
  }} catch (e) {{
    document.getElementById("err").textContent =
      "engine unreachable: " + e;
  }}
}}
tick(); setInterval(tick, {refresh_seconds * 1000});
</script></body></html>"""


# ---------------------------------------------------------------------------
# index
# ---------------------------------------------------------------------------

def render_index(records: List[dict], diff_digests: List[str],
                 page_names: Dict[int, str],
                 engine_url: Optional[str] = None) -> str:
    body = []
    if engine_url:
        body.append(f"<p><b><a href='console.html'>live console</a></b> "
                    f"— in-flight query progress + resource gauges "
                    f"(polls {_esc(engine_url)})</p>")
    # the replica column only earns its width on a SHARED historyDir
    # (multiple replicas appending) — single-writer stores skip it
    replicas = {r.get("replica_id") for r in records
                if r.get("type") == "query" and r.get("replica_id")}
    show_replica = len(replicas) > 1
    body += ["<h2>Queries</h2><table><tr><th>id</th><th>started</th>"
            "<th>status</th><th class='num'>wall ms</th><th>digest</th>"
            + ("<th>replica</th>" if show_replica else "")
            + "<th class='num'>fallbacks</th><th></th></tr>"]
    for i in reversed(range(len(records))):
        rec = records[i]
        if rec.get("type") == "nds_scorecard":
            continue
        st = rec.get("status", "?")
        slow = rec.get("slo_breach") is not None
        row_cls = "slow" if slow and st == "ok" else st
        st_cell = _esc(st) + (" <span class='badge-slow'>slow</span>"
                              if slow else "")
        body.append(
            f"<tr class='{row_cls}'><td>{_esc(rec.get('query_id'))}</td>"
            f"<td>{_fmt_time(rec.get('wall_start_unix'))}</td>"
            f"<td class='badge-{st}'>{st_cell}</td>"
            f"<td class='num'>{rec.get('duration_ns', 0) / 1e6:.1f}</td>"
            f"<td><small class='digest'>{_esc(rec.get('plan_digest'))}"
            f"</small></td>"
            + (f"<td><code>{_esc(rec.get('replica_id', ''))}</code></td>"
               if show_replica else "")
            + f"<td class='num'>{len(rec.get('fallback_reasons', []))}</td>"
            f"<td><a href='{page_names[i]}'>plan</a></td></tr>")
    body.append("</table>")
    if diff_digests:
        body.append("<h2>Run-over-run diffs (same plan digest)</h2><ul>")
        for d in diff_digests:
            body.append(f"<li><a href='diff_{d}.html'>"
                        f"<small class='digest'>{d}</small></a></li>")
        body.append("</ul>")
    nds = [r for r in records if r.get("type") == "nds_scorecard"]
    if nds:
        body.append("<h2>NDS probe scorecards</h2><table><tr><th>query"
                    "</th><th>status</th><th>device</th>"
                    "<th class='num'>seconds</th><th>recorded</th></tr>")
        for r in reversed(nds):
            body.append(
                f"<tr><td>{_esc(r.get('query'))}</td>"
                f"<td>{_esc(r.get('status'))}</td>"
                f"<td>{_esc(r.get('device', ''))}</td>"
                f"<td class='num'>{r.get('seconds', '')}</td>"
                f"<td>{_fmt_time(r.get('wall_start_unix'))}</td></tr>")
        body.append("</table>")
    return _page("spark-rapids-tpu query history", "\n".join(body))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def render_site(history_dir: str, out_dir: str,
                engine_url: Optional[str] = None) -> Dict[str, str]:
    """Render everything; returns {page_name: path}. With engine_url,
    also writes the live console page polling that engine's obs
    endpoint."""
    store = QueryHistoryStore(history_dir)
    records = store.read_all()
    os.makedirs(out_dir, exist_ok=True)
    written: Dict[str, str] = {}

    def write(name: str, content: str) -> None:
        p = os.path.join(out_dir, name)
        with open(p, "w") as f:
            f.write(content)
        written[name] = p

    page_names = _page_names(records)
    by_digest: Dict[str, List[dict]] = {}
    for i, rec in enumerate(records):
        if rec.get("type") == "nds_scorecard":
            continue
        write(page_names[i], render_query_page(rec))
        d = rec.get("plan_digest")
        if d:
            by_digest.setdefault(d, []).append(rec)
    diff_digests = []
    for d, recs in by_digest.items():
        if len(recs) >= 2:
            write(f"diff_{d}.html", render_diff_page(d, recs[-2], recs[-1]))
            diff_digests.append(d)
    if engine_url:
        write("console.html", render_live_console(engine_url))
    write("index.html", render_index(records, diff_digests, page_names,
                                     engine_url=engine_url))
    return written


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("history_dir")
    ap.add_argument("--out", default=None,
                    help="output dir (default: <historyDir>/html)")
    ap.add_argument("--serve", type=int, default=0,
                    help="after rendering, serve the output dir on this "
                    "port (blocking)")
    ap.add_argument("--engine", default=None,
                    help="base URL of a running engine's obs endpoint "
                    "(http://host:port from spark.rapids.obs.port); "
                    "adds the live console page polling its /queries")
    args = ap.parse_args()
    out_dir = args.out or os.path.join(args.history_dir, "html")
    written = render_site(args.history_dir, out_dir,
                          engine_url=args.engine)
    print(f"wrote {len(written)} page(s) under {out_dir}")
    if args.serve:
        import functools
        from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
        handler = functools.partial(SimpleHTTPRequestHandler,
                                    directory=out_dir)
        srv = ThreadingHTTPServer(("127.0.0.1", args.serve), handler)
        print(f"serving http://127.0.0.1:{srv.server_address[1]}/")
        srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
