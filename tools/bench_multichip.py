"""Multi-chip scaling bench (round 19): whole fused stages sharded over
the ICI mesh, the all-to-all exchange as the real shuffle.

Sweeps the virtual-device mesh at 1/2/4/8 devices. The device count is
baked into XLA at process start (``--xla_force_host_platform_device_
count`` is read once, before jax imports), so the parent re-execs ONE
CHILD PROCESS PER DEVICE COUNT and aggregates their JSON lines — the
decode_smoke/ci pattern for device-count-parameterized runs.

Probes (in-memory, 8-way partitioned at every device count so the
workload is identical and only the mesh varies):

- ``q72_shuffle`` (shuffle-heavy, q72-shaped): narrow filter/project
  chain -> hash repartition -> narrow chain. Both chains run as
  ShardedStageExec waves on the mesh and the repartition is the
  in-program ``lax.all_to_all`` when the mesh covers the partition
  count.
- ``q6_scan`` (scan-heavy, q6-shaped): a wide filter/project chain with
  no exchange — pure ShardedStageExec wave scaling.

Host CPU simulation cannot reproduce ICI link latency or TPU kernel
launch cost, so the bench models a FIXED per-dispatch device-occupancy
cost with the fuse-layer dispatch hook (``simulated_dispatch_latency_
ms``, recorded in the artifact): every device dispatch — sharded or
not — holds a device-occupancy lock for the same interval, because a
device retires one program at a time, and the measured walls are real
end-to-end clocks over that identical per-dispatch tax. Sharding wins
by issuing FEWER, WIDER dispatches (one SPMD wave instead of one
dispatch per partition batch; one all_to_all program instead of the
per-(dst,src) host loop) — the same mechanism that wins on real ICI.

Acceptance (ROADMAP item 4): the shuffle-heavy probe must scale >= 3x
at 8 virtual devices over the 1-device engine. Results land in
MULTICHIP_r06.json (replacing round 5's literal ``ok: true``).

Usage: python tools/bench_multichip.py [--rows 200000] [--sim-ms 5]
           [--out MULTICHIP_r06.json]
"""
import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

TOOLS = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(TOOLS)
PARTITIONS = 8
DEVICE_SWEEP = (1, 2, 4, 8)


def build_probes(rows: int):
    from spark_rapids_tpu.expr.core import col, lit

    data = {
        "g": [i % 97 for i in range(rows)],
        "v": list(range(rows)),
        "d": [float(i % 13) * 0.25 for i in range(rows)],
    }

    def q72_shuffle(s):
        return (s.create_dataframe(data, num_partitions=PARTITIONS)
                .filter(col("v") % lit(5) != lit(0))
                .select(col("g"), (col("v") * lit(3)).alias("v3"),
                        col("d"))
                .repartition(PARTITIONS, col("g"))
                .filter(col("v3") % lit(2) == lit(0))
                .select(col("g"), (col("v3") + lit(7)).alias("v7"),
                        (col("d") * lit(2.0)).alias("d2")))

    def q6_scan(s):
        return (s.create_dataframe(data, num_partitions=PARTITIONS)
                .filter(col("v") % lit(3) != lit(1))
                .select(col("g"), (col("v") * lit(2) + lit(1)).alias("v2"),
                        (col("d") * lit(0.5) + lit(1.0)).alias("dh"))
                .filter(col("v2") % lit(7) != lit(0))
                .select((col("g") + lit(1)).alias("g1"), col("v2"),
                        (col("dh") * col("dh")).alias("dsq")))

    return {"q72_shuffle": q72_shuffle, "q6_scan": q6_scan}


def _sorted(tbl):
    return tbl.sort_by([(c, "ascending") for c in tbl.column_names])


def run_child(args) -> int:
    """One device count, one process: run every probe, print one JSON
    line. Multichip is ON for every mesh size > 1; the 1-device run is
    the plain single-device engine (the scaling baseline)."""
    import threading

    import jax
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.exec import fuse
    from spark_rapids_tpu.sql.session import TpuSession

    n = len(jax.devices())
    multichip = n > 1
    # row-group-granular scan batches (reader.batchSizeRows), as a real
    # Parquet scan produces them: the single-device engine dispatches
    # once per batch per stage, the sharded engine coalesces a
    # partition's batches into one wave — identical workload on both
    # paths, only the dispatch granularity differs.
    conf = {C.MULTICHIP_ENABLED.key: multichip,
            C.MAX_READER_BATCH_SIZE_ROWS.key: args.batch_rows}
    stats = {"dispatches": 0}
    sim_s = args.sim_ms / 1e3
    # A device retires ONE program at a time: the modeled per-dispatch
    # cost must serialize, or 8 host task threads would let a single
    # virtual device "execute" 8 programs concurrently and no dispatch
    # reduction could ever show up in the wall clock. Every dispatch —
    # single-device or SPMD — pays the same occupancy slot; sharding
    # wins by issuing FEWER, WIDER dispatches (one wave instead of one
    # program per partition batch), which is the ICI mechanism.
    device_occupancy = threading.Lock()

    def hook(_key):
        stats["dispatches"] += 1
        with device_occupancy:
            time.sleep(sim_s)

    out = {"devices": n, "multichip": multichip, "probes": {}}
    for name, build in build_probes(args.rows).items():
        s = TpuSession(dict(conf))
        fuse.set_dispatch_hook(hook)
        try:
            tbl = _sorted(build(s).collect())  # warm: compiles excluded
            digest = hashlib.sha256(
                json.dumps(tbl.to_pylist(), sort_keys=True, default=str)
                .encode()).hexdigest()[:16]
            walls, disp = [], []
            for _ in range(args.reps):
                stats["dispatches"] = 0
                t0 = time.perf_counter()
                build(s).collect()
                walls.append(time.perf_counter() - t0)
                disp.append(stats["dispatches"])
        finally:
            fuse.set_dispatch_hook(None)
        snaps = s.last_metrics()
        out["probes"][name] = {
            "wall_s": round(min(walls), 6),
            "dispatches": disp[-1],
            "shard_waves": int(sum(v.get("shardWaves", 0)
                                   for v in snaps.values())),
            "ici_ns": int(sum(v.get("iciExchangeTime", 0)
                              for v in snaps.values())),
            "rows_out": int(tbl.num_rows),
            "digest": digest,
        }
    print(json.dumps(out))
    return 0


def run_parent(args) -> int:
    per_devices = {}
    for n in DEVICE_SWEEP:
        env = dict(os.environ)
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "").replace(
                "--xla_force_host_platform_device_count=8", "").strip()
            + f" --xla_force_host_platform_device_count={n}").strip()
        env.setdefault("JAX_PLATFORMS", "cpu")
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--rows", str(args.rows), "--reps", str(args.reps),
               "--sim-ms", str(args.sim_ms),
               "--batch-rows", str(args.batch_rows)]
        print(f"-- devices={n}", file=sys.stderr)
        proc = subprocess.run(cmd, env=env, cwd=ROOT,
                              capture_output=True, text=True)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr[-2000:])
            print(f"FAIL: child at devices={n} rc={proc.returncode}")
            return 1
        line = proc.stdout.strip().splitlines()[-1]
        per_devices[n] = json.loads(line)

    # one row of input crosses ~3 int64/float64 planes per probe
    probe_bytes = args.rows * 3 * 8
    doc = {
        "bench": "bench_multichip",
        "round": 19,
        "devices_swept": list(DEVICE_SWEEP),
        "partitions": PARTITIONS,
        "rows": args.rows,
        "reps": args.reps,
        "reader_batch_rows": args.batch_rows,
        "simulated_dispatch_latency_ms": args.sim_ms,
        "note": "walls are measured end-to-end; every dispatch (sharded"
                " or not) pays the same simulated per-dispatch device-"
                "occupancy cost, serialized because a device retires one"
                " program at a time, so scaling comes from issuing"
                " fewer, wider dispatches — the ICI mechanism, modeled"
                " on a CPU host",
        "probes": {},
        "digest_parity": True,
    }
    fails = []
    for probe in ("q72_shuffle", "q6_scan"):
        base = per_devices[DEVICE_SWEEP[0]]["probes"][probe]
        digests = {per_devices[n]["probes"][probe]["digest"]
                   for n in DEVICE_SWEEP}
        if len(digests) != 1:
            doc["digest_parity"] = False
            fails.append(f"{probe}: results differ across device counts")
        rows = {}
        for n in DEVICE_SWEEP:
            p = per_devices[n]["probes"][probe]
            scaling = base["wall_s"] / p["wall_s"] if p["wall_s"] else 0.0
            rows[str(n)] = {
                "wall_s": p["wall_s"],
                "eff_gbps": round(probe_bytes / p["wall_s"] / 1e9, 4)
                if p["wall_s"] else 0.0,
                "dispatches": p["dispatches"],
                "shard_waves": p["shard_waves"],
                "ici_ns": p["ici_ns"],
                "scaling_x": round(scaling, 3),
                "scaling_efficiency": round(scaling / n, 3),
            }
        doc["probes"][probe] = {
            "rows_out": base["rows_out"],
            "input_bytes": probe_bytes,
            "per_devices": rows,
            "scaling_at_8": rows[str(DEVICE_SWEEP[-1])]["scaling_x"],
        }
    shuffle8 = doc["probes"]["q72_shuffle"]["scaling_at_8"]
    if shuffle8 < 3.0:
        fails.append(f"shuffle-heavy probe scaled {shuffle8}x at 8 "
                     f"devices — acceptance floor is 3x")
    out_path = args.out if os.path.isabs(args.out) \
        else os.path.join(ROOT, args.out)
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps({p: doc["probes"][p]["per_devices"]
                      for p in doc["probes"]}, sort_keys=True))
    if fails:
        for fmsg in fails:
            print("FAIL:", fmsg)
        return 1
    print(f"PASS: shuffle-heavy probe {shuffle8}x at 8 devices "
          f"(scan-heavy {doc['probes']['q6_scan']['scaling_at_8']}x); "
          f"results byte-identical across "
          f"{list(DEVICE_SWEEP)} device meshes; wrote {out_path}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--sim-ms", type=float, default=5.0)
    ap.add_argument("--batch-rows", type=int, default=2048)
    ap.add_argument("--out", default="MULTICHIP_r06.json")
    args = ap.parse_args()
    if args.child:
        sys.path.insert(0, ROOT)
        return run_child(args)
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())
