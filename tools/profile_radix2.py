"""Candidate optimizations: sort-free i32 limb-scatter aggregation,
2-level cumsum, elementwise baselines."""
import time
import numpy as np
import spark_rapids_tpu  # noqa: F401
import jax
import jax.numpy as jnp


def _force(out):
    leaves = jax.tree_util.tree_leaves(out)
    jax.device_get([l[:1] if getattr(l, "ndim", 0) else l for l in leaves])


def bench(name, fn, *args, reps=3):
    _force(fn(*args))
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(fn(*args))
        best = min(best or 9e9, time.perf_counter() - t0)
    print(f"{name:52s} {best*1000:10.1f} ms", flush=True)


def main():
    rng = np.random.default_rng(0)
    N = 8_000_000
    S = 3_000_000
    k = jnp.asarray(rng.integers(0, S, N).astype(np.int32))
    v = jnp.asarray(rng.uniform(0, 100, N))

    bench("elementwise f64 +1 8M", jax.jit(lambda x: x + 1.0), v)
    bench("elementwise i32 +1 8M", jax.jit(lambda x: x + 1), k)

    def digits(x, scale):
        # 3 balanced base-2^16 digits of round(x * scale)
        s = x * scale
        d0 = jnp.round(s / np.float64(2.0**32))
        r0 = s - d0 * np.float64(2.0**32)
        d1 = jnp.round(r0 / np.float64(2.0**16))
        d2 = jnp.round(r0 - d1 * np.float64(2.0**16))
        return (d0.astype(jnp.int32), d1.astype(jnp.int32), d2.astype(jnp.int32))

    def scatter_sum(kk, vv, S):
        m = jnp.max(jnp.abs(vv))
        from spark_rapids_tpu.ops.radix import _exponent_scale
        scale = _exponent_scale(m) * np.float64(2.0**12)  # 48 bits below E
        d0, d1, d2 = digits(vv, scale)
        s0 = jax.ops.segment_sum(d0, kk, num_segments=S)
        s1 = jax.ops.segment_sum(d1, kk, num_segments=S)
        s2 = jax.ops.segment_sum(d2, kk, num_segments=S)
        cnt = jax.ops.segment_sum(jnp.ones(kk.shape[0], jnp.int32), kk,
                                  num_segments=S)
        tot = (s0.astype(jnp.float64) * np.float64(2.0**32)
               + s1.astype(jnp.float64) * np.float64(2.0**16)
               + s2.astype(jnp.float64)) / scale
        return tot, cnt
    f = jax.jit(scatter_sum, static_argnums=(2,))
    bench("3-limb i32 scatter sum+cnt 8M->3M", f, k, v, S)
    k8 = jnp.asarray(rng.integers(0, 800_000, N).astype(np.int32))
    bench("3-limb i32 scatter sum+cnt 8M->800k", f, k8, v, 800_000)
    k1 = jnp.asarray(rng.integers(0, 100_000, 2_000_000).astype(np.int32))
    bench("3-limb i32 scatter sum+cnt 2M->100k", f, k1, v[:2_000_000], 100_000)

    # verify accuracy vs numpy
    tot, cnt = f(k1, v[:2_000_000], 100_000)
    ref = np.zeros(100_000)
    np.add.at(ref, np.asarray(k1), np.asarray(v[:2_000_000]))
    err = np.max(np.abs(np.asarray(tot) - ref) / np.maximum(1.0, np.abs(ref)))
    print(f"3-limb max rel err vs numpy: {err:.2e}")

    # minmax double scatter 8M->800k on i64
    def mm(kk, vv):
        v64 = (vv * 1e6).astype(jnp.int64)
        hi = (v64 >> jnp.int64(32)).astype(jnp.int32)
        lo = ((v64 & jnp.int64(0xFFFFFFFF)) - jnp.int64(2**31)).astype(jnp.int32)
        whi = jax.ops.segment_max(hi, kk, num_segments=800_000)
        cand = hi == whi[kk]
        lom = jnp.where(cand, lo, jnp.int32(-2**31))
        wlo = jax.ops.segment_max(lom, kk, num_segments=800_000)
        return whi, wlo
    bench("i64 minmax 2xi32 scatter 8M->800k", jax.jit(mm), k8, v)

    # 2-level cumsum vs native
    bench("native cumsum i64 8M", jax.jit(lambda x: jnp.cumsum(x)),
          (v * 1e6).astype(jnp.int64))

    def cumsum2(x):
        B = 4096
        n = x.shape[0]
        C = n // B
        r = x[: B * C].reshape(B, C)
        rc = jnp.cumsum(r, axis=1)
        blocks = jnp.concatenate([jnp.zeros(1, x.dtype),
                                  jnp.cumsum(rc[:, -1])[:-1]])
        out = (rc + blocks[:, None]).reshape(-1)
        tail = x[B * C:]
        tail_c = jnp.cumsum(tail) + out[-1]
        return jnp.concatenate([out, tail_c])
    x64 = (v * 1e6).astype(jnp.int64)
    f2 = jax.jit(cumsum2)
    bench("2-level cumsum i64 8M", f2, x64)
    ok = bool(jnp.all(f2(x64)[: 100000] == jnp.cumsum(x64)[:100000]))
    print("2-level cumsum correct:", ok)

    # gather widths at 8M
    idx = jnp.asarray(rng.integers(0, N, N).astype(np.int32))
    bench("gather i32 8M", jax.jit(lambda a, i: a[i]), k, idx)
    bench("gather i64 8M", jax.jit(lambda a, i: a[i]), x64, idx)
    # stacked gather: 2 planes in one [2, N] take along axis 1
    two = jnp.stack([x64, x64 + 1])
    bench("gather [2,8M] i64 stacked", jax.jit(lambda a, i: a[:, i]), two, idx)


if __name__ == "__main__":
    main()
