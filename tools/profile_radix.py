"""Stage-by-stage timing of the radix groupby pipeline on raw arrays."""
import time
import numpy as np
import spark_rapids_tpu  # noqa: F401
import jax
import jax.numpy as jnp


def _force(out):
    leaves = jax.tree_util.tree_leaves(out)
    jax.device_get([l[:1] if getattr(l, "ndim", 0) else l for l in leaves])


def bench(name, fn, *args, reps=3):
    _force(fn(*args))
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(fn(*args))
        best = min(best or 9e9, time.perf_counter() - t0)
    print(f"{name:50s} {best*1000:10.1f} ms", flush=True)


def main():
    from spark_rapids_tpu.ops import radix as R
    rng = np.random.default_rng(0)
    N = 8_000_000
    k = jnp.asarray(rng.integers(0, 800_000, N).astype(np.int64))
    v = jnp.asarray(rng.uniform(0, 100, N))
    live = jnp.ones(N, jnp.bool_)

    packed = jnp.where(live, k + 1, R._SENTINEL)

    bench("argsort i64 stable 8M", jax.jit(lambda p: jnp.argsort(p, stable=True)), packed)
    bench("argsort i64 default 8M", jax.jit(jnp.argsort), packed)

    def lay_tuple(p, lv):
        lay = R.group_layout(p, lv)
        return (lay.perm, lay.sorted_packed, lay.boundary, lay.gid,
                lay.starts, lay.ends, lay.n_groups)
    bench("group_layout 8M", jax.jit(lay_tuple), packed, live)

    def full(p, lv, vv):
        lay = R.group_layout(p, lv)
        vs = vv[lay.perm]
        valid = lv[lay.perm]
        s = R.seg_sum_f64(vs, valid, lay)
        c = R.seg_count(valid, lay)
        return s, c, lay.n_groups
    bench("layout+gather+sum+count 8M", jax.jit(full), packed, live, v)

    def just_scatter(p, lv):
        n_live = jnp.sum(lv.astype(jnp.int32))
        perm = jnp.argsort(p, stable=True).astype(jnp.int32)
        sp = p[perm]
        pos = jnp.arange(N, dtype=jnp.int32)
        boundary = jnp.concatenate([jnp.ones(1, jnp.bool_), sp[1:] != sp[:-1]])
        boundary = boundary & (pos < n_live)
        gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        bpos = jnp.where(boundary, gid, N)
        starts = jnp.full(N + 1, -1, jnp.int32).at[bpos].set(pos, mode="drop")[:N]
        return starts
    bench("sort+boundary+starts-scatter 8M", jax.jit(just_scatter), packed, live)

    def sort_gather(p, vv):
        perm = jnp.argsort(p, stable=True).astype(jnp.int32)
        return p[perm], vv[perm]
    bench("sort + 2 gathers 8M", jax.jit(sort_gather), packed, v)

    def limb(vv):
        m = jnp.max(jnp.abs(vv))
        scale = R._exponent_scale(m)
        scaled = vv * scale
        hi = jnp.floor(scaled)
        lo = jnp.round((scaled - hi) * np.float64(2.0) ** 36)
        return jnp.cumsum(hi.astype(jnp.int64)), jnp.cumsum(lo.astype(jnp.int64))
    bench("limb decompose + 2 i64 cumsums 8M", jax.jit(limb), v)

    def specials(vv):
        nan = jnp.isnan(vv)
        pinf = vv == jnp.inf
        spec = (nan.astype(jnp.int64) << jnp.int64(31)) | pinf.astype(jnp.int64)
        return jnp.cumsum(spec)
    bench("specials cumsum i64 8M", jax.jit(specials), v)


if __name__ == "__main__":
    main()
