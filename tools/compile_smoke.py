"""Compile-cache smoke: the compile tax must actually die, for free.

Three CI gates over the ISSUE-10 subsystem (runtime/shapes.py +
runtime/compile_cache.py + runtime/warmup.py):

Gate 1 (steady-state overhead, the trace_overhead bar): the warm-hit
path of the sanctioned compile choke point — what every fused dispatch
now passes through instead of a bare dict probe — must add under
--tolerance (2%) to a representative query drive. Same methodology as
tools/sanitizer_smoke.py: count choke-point passes in one drive, measure
the per-pass delta versus the pre-change equivalent (a plain dict.get)
over tight-loop iterations, multiply.

Gate 2 (cross-process persistent cache): a SECOND process running the
same queries against the same spark.rapids.compile.cacheDir must record
persistent-cache HITS (jax.monitoring's cache_hits events, surfaced in
compile_cache.stats) and spend measurably less backend-compile time than
the first. This is the conf actually working, not just being set.

Gate 3 (warm-history AOT warmup, the ROADMAP item 4 acceptance bar): on
a history warmed by a prior process (two runs of each probe query, SQL
recorded), a fresh process with spark.rapids.compile.warmup.enabled must
replay the hot set at table-registration time and then serve the user's
first run of those queries with an attribution `compile` bucket total at
least --min-drop (5x) below the cold process's first-run total — the
exact compile_seconds methodology tools/nds_probe.py scorecards use,
driven over probe-shaped join/agg/window SQL.

Run:  python tools/compile_smoke.py [--tolerance 0.02] [--min-drop 5]
Internal: --worker cold|warm --dir D (subprocess modes).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: probe-shaped SQL (join+agg, filter+groupby, windowed rank): SQL-born
#: plans record their text in history, which is what warmup replays
QUERIES = (
    "SELECT d.grp, SUM(f.price * (1.0 - f.disc)) AS rev "
    "FROM fact f JOIN dim d ON f.key = d.key "
    "WHERE f.qty < 40 GROUP BY d.grp",
    "SELECT f.qty AS b, SUM(f.price) AS p, COUNT(*) AS c "
    "FROM fact f WHERE f.price > 10.0 GROUP BY f.qty",
    "SELECT grp, MAX(r) AS mr FROM (SELECT d.grp AS grp, RANK() OVER "
    "(PARTITION BY d.grp ORDER BY f.price) AS r FROM fact f "
    "JOIN dim d ON f.key = d.key) t GROUP BY grp",
)


def _make_data(d: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(20260804)
    n, k = 60_000, 500
    pq.write_table(pa.table({
        "key": rng.integers(0, k, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(1.0, 100.0, n), 2),
        "disc": np.round(rng.uniform(0.0, 0.1, n), 2),
    }), os.path.join(d, "fact.parquet"))
    pq.write_table(pa.table({
        "key": np.arange(k, dtype=np.int64),
        "grp": rng.integers(0, 8, k).astype(np.int64),
    }), os.path.join(d, "dim.parquet"))


def _session(d: str, warmup_on: bool):
    from spark_rapids_tpu.sql.session import TpuSession
    conf = {
        "spark.rapids.obs.historyDir": os.path.join(d, "hist"),
        "spark.rapids.compile.cacheDir": os.path.join(d, "xla_cache"),
    }
    if warmup_on:
        conf["spark.rapids.compile.warmup.enabled"] = "true"
    return TpuSession(conf)


def _register(sess, d: str) -> None:
    sess.create_or_replace_temp_view(
        "fact", sess.read_parquet(os.path.join(d, "fact.parquet")))
    sess.create_or_replace_temp_view(
        "dim", sess.read_parquet(os.path.join(d, "dim.parquet")))


def _attr_compile(sess) -> float:
    attr = sess.last_attribution()
    return float(attr["buckets"]["compile"]) if attr else 0.0


def worker_cold(d: str) -> dict:
    """First process: seed history (two runs per query — recurrence for
    warmup) and the persistent cache; report first-run compile totals
    and the in-process determinism check (second runs build nothing)."""
    from spark_rapids_tpu.runtime import compile_cache as CC
    sess = _session(d, warmup_on=False)
    _register(sess, d)
    first_compile = 0.0
    second_misses = 0
    for q in QUERIES:
        sess.sql(q).collect()
        first_compile += _attr_compile(sess)
        before = CC.stats()["misses"]
        sess.sql(q).collect()
        second_misses += CC.stats()["misses"] - before
    s = CC.stats()
    return {"first_compile_seconds": first_compile,
            "second_run_new_misses": second_misses,
            "xla_compile_ns": s["xla_compile_ns"],
            "persistent_hits": s["persistent_hits"],
            "persistent_misses": s["persistent_misses"]}


def worker_warm(d: str) -> dict:
    """Second process: same cache dir + warm history + AOT warmup. The
    user-visible first run of each query is measured AFTER warmup
    drains."""
    from spark_rapids_tpu.runtime import compile_cache as CC
    from spark_rapids_tpu.runtime import warmup as WU
    sess = _session(d, warmup_on=True)
    mgr = WU.manager()
    armed = mgr is not None and mgr.doc()["plans"] > 0
    _register(sess, d)
    drained = mgr.wait(180) if mgr is not None else False
    warm_doc = mgr.doc() if mgr is not None else None
    user_compile = 0.0
    user_misses = 0
    before = CC.stats()["misses"]
    for q in QUERIES:
        sess.sql(q).collect()
        user_compile += _attr_compile(sess)
    user_misses = CC.stats()["misses"] - before
    s = CC.stats()
    return {"armed": armed, "drained": drained, "warmup": warm_doc,
            "user_compile_seconds": user_compile,
            "user_new_misses": user_misses,
            "xla_compile_ns": s["xla_compile_ns"],
            "persistent_hits": s["persistent_hits"],
            "persistent_misses": s["persistent_misses"]}


# ---------------------------------------------------------------------------
# Gate 1: steady-state choke-point overhead
# ---------------------------------------------------------------------------

def overhead_gate(d: str, tolerance: float) -> dict:
    """Count warm choke-point passes in one query drive, measure the
    per-pass cost delta vs a plain dict probe (the pre-change fused()
    body) over tight loops, and bound count x delta against the drive
    wall (the sanitizer_smoke methodology — an A/B wall-clock diff
    would drown in shared-CI noise)."""
    from spark_rapids_tpu.runtime import compile_cache as CC
    sess = _session(d, warmup_on=False)
    _register(sess, d)
    dfs = [sess.sql(q) for q in QUERIES]
    for df in dfs:
        df.collect()  # warm every entry so the drive is all hits

    passes = [0]
    real_get = CC.get

    def counting_get(exec_class, key, builder):
        passes[0] += 1
        return real_get(exec_class, key, builder)

    CC.get = counting_get
    try:
        t0 = time.perf_counter()
        for df in dfs:
            df.collect()
        drive_s = time.perf_counter() - t0
    finally:
        CC.get = real_get

    # per-pass: the warm CC.get path vs the pre-change equivalent
    # (one dict.get on a tuple key)
    key = ("smoke", ("k", 1, 2), ())
    CC.get("smoke", ("k", 1, 2), lambda: (lambda: None))
    baseline_cache = {(("smoke", ("k", 1, 2), ())): lambda: None}
    n = 100_000
    t0 = time.perf_counter_ns()
    for _ in range(n):
        CC.get("smoke", ("k", 1, 2), None)
    per_new = (time.perf_counter_ns() - t0) / n
    t0 = time.perf_counter_ns()
    for _ in range(n):
        baseline_cache.get(key)
    per_old = (time.perf_counter_ns() - t0) / n
    delta_ns = max(per_new - per_old, 0.0)
    overhead = passes[0] * delta_ns / (drive_s * 1e9)
    return {"passes": passes[0], "per_pass_ns": round(per_new, 1),
            "delta_ns": round(delta_ns, 1),
            "drive_s": round(drive_s, 3),
            "overhead_fraction": overhead,
            "ok": overhead < tolerance}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _run_worker(mode: str, d: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", mode,
         "--dir", d],
        capture_output=True, text=True, timeout=600, env=env)
    if out.returncode != 0:
        print(out.stdout)
        print(out.stderr, file=sys.stderr)
        raise SystemExit(f"compile_smoke {mode} worker failed")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tolerance", type=float, default=0.02)
    ap.add_argument("--min-drop", type=float, default=5.0)
    ap.add_argument("--worker", choices=("cold", "warm"))
    ap.add_argument("--dir")
    args = ap.parse_args()

    if args.worker:
        fn = worker_cold if args.worker == "cold" else worker_warm
        print(json.dumps(fn(args.dir)))
        return 0

    import tempfile
    fails = []
    with tempfile.TemporaryDirectory(prefix="compile_smoke_") as d:
        _make_data(d)

        print("[gate 2+3] cold process (seeds history + persistent "
              "cache)...", flush=True)
        cold = _run_worker("cold", d)
        print(f"  cold: first-run compile {cold['first_compile_seconds']:.3f}s, "
              f"second-run new misses {cold['second_run_new_misses']}, "
              f"persistent misses {cold['persistent_misses']}")
        if cold["second_run_new_misses"] != 0:
            fails.append("cold process second runs built new entries "
                         "(warm-trace cache not deterministic)")
        if cold["persistent_misses"] == 0:
            fails.append("cold process recorded no persistent-cache "
                         "traffic (cacheDir conf not applied?)")

        print("[gate 2+3] warm process (persistent hits + AOT warmup)...",
              flush=True)
        warm = _run_worker("warm", d)
        print(f"  warm: armed={warm['armed']} drained={warm['drained']} "
              f"warmup={warm['warmup']}")
        print(f"  warm: user first-run compile "
              f"{warm['user_compile_seconds']:.3f}s, new misses "
              f"{warm['user_new_misses']}, persistent hits "
              f"{warm['persistent_hits']}")
        if not warm["armed"]:
            fails.append("warmup never armed from the warm history")
        if not warm["drained"]:
            fails.append("warmup did not drain within the deadline")
        if (warm["warmup"] or {}).get("replayed", 0) < len(QUERIES):
            fails.append("warmup replayed fewer plans than recorded")
        if warm["persistent_hits"] == 0:
            fails.append("no cross-process persistent-cache hits")
        if warm["user_new_misses"] != 0:
            fails.append("user queries after warmup still built entries")
        drop = cold["first_compile_seconds"] / max(
            warm["user_compile_seconds"], 1e-3)
        print(f"  compile_seconds drop: {drop:.1f}x "
              f"(gate >= {args.min_drop}x)")
        if drop < args.min_drop:
            fails.append(
                f"warm-history compile_seconds dropped only {drop:.1f}x")

        print("[gate 1] steady-state choke-point overhead...", flush=True)
        oh = overhead_gate(d, args.tolerance)
        print(f"  {oh['passes']} passes x {oh['delta_ns']}ns delta over "
              f"{oh['drive_s']}s drive -> "
              f"{oh['overhead_fraction'] * 100:.3f}% "
              f"(gate < {args.tolerance * 100:.0f}%)")
        if not oh["ok"]:
            fails.append("steady-state choke-point overhead over budget")

    if fails:
        print("compile_smoke: FAIL")
        for f in fails:
            print(f"  - {f}")
        return 1
    print("compile_smoke: PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
