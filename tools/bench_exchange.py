"""Micro-benchmark: 'compact' (counting-sort) vs 'masked' shuffle
repartitioning, end-to-end on the CPU backend.

Two measurements, both shuffle-shaped:

1. repartition-only: drive a ShuffleExchangeExec directly and force every
   output sub-batch's planes (what the exchange itself costs);
2. repartition + group-by: the full partial-agg -> hash exchange ->
   final-merge pipeline through the session API (what downstream
   operators save when sub-batches are right-sized instead of
   n_out x capacity mask slices).

Run:  python tools/bench_exchange.py [--rows 200000] [--nout 4] [--reps 3]

Prints per-mode wall-clock and a JSON summary line; exits nonzero if the
two modes disagree on query results (they must be identical).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402


def _table(rows: int) -> pa.Table:
    rng = np.random.default_rng(7)
    return pa.table({
        "k": rng.integers(0, 5000, rows),
        "v": rng.integers(-(1 << 40), 1 << 40, rows),
        "d": rng.uniform(-1e9, 1e9, rows),
        "s": np.array(["tag%d" % i for i in range(64)])[
            rng.integers(0, 64, rows)],
    })


def _session(partitioning: str):
    from spark_rapids_tpu.sql.session import TpuSession
    return TpuSession({"spark.rapids.shuffle.partitioning": partitioning})


def bench_repartition(t: pa.Table, partitioning: str, n_out: int,
                      reps: int) -> float:
    """Exchange-only: materialize + force every output plane."""
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.plan.nodes import bind_expr
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.runtime.task import TaskContext

    def run():
        s = _session(partitioning)
        df = s.create_dataframe(t, num_partitions=n_out)
        child, _ = convert_plan(df.plan, s.conf)
        ex = X.ShuffleExchangeExec(df.plan, [child], s.conf,
                                   [bind_expr(col("k"), df.plan.schema)],
                                   n_out=n_out)
        leaves = []
        for p in range(n_out):
            with TaskContext(partition_id=p) as ctx:
                for b in ex.execute_partition(ctx, p):
                    leaves.extend(jax.tree_util.tree_leaves(b))
        jax.block_until_ready(leaves)

    run()  # warm the kernel caches
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_groupby(t: pa.Table, partitioning: str, n_out: int,
                  reps: int):
    """Shuffle-shaped repartition + group-by: exchange RAW rows by the
    group key, then aggregate each partition completely — the exact
    pipeline the planner builds for no-partial-state aggregates
    (plan/overrides.py) and the q72shfl bench shape. Downstream work is
    proportional to what the exchange emits: n_out x capacity mask
    slices vs right-sized compact slices."""
    from spark_rapids_tpu.columnar.batch import to_arrow
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.expr.core import col
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.runtime.task import TaskContext
    from spark_rapids_tpu.sql import functions as F

    def run():
        s = _session(partitioning)
        df = s.create_dataframe(t, num_partitions=n_out)
        gdf = df.group_by(col("k")).agg(
            F.sum("v").alias("sv"), F.count().alias("n"),
            F.min("d").alias("md"))
        node = gdf.plan
        while not isinstance(node, P.Aggregate):
            node = node.children[0]
        scan, _ = convert_plan(node.children[0], s.conf)
        exch = X.ShuffleExchangeExec(node, [scan], s.conf,
                                     node.group_exprs, n_out=n_out)
        agg = X.HashAggregateExec(node, [exch], s.conf, mode="complete")
        rows = []
        names = list(agg.schema.names)
        for p in range(n_out):
            with TaskContext(partition_id=p) as ctx:
                for b in agg.execute_partition(ctx, p):
                    rows.extend(to_arrow(b, names).to_pylist())
        return sorted(rows, key=lambda r: r["k"])

    result = run()  # warm + capture for the equality check
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best, result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--nout", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()
    t = _table(args.rows)

    out = {"rows": args.rows, "n_out": args.nout}
    results = {}
    for mode in ("compact", "masked"):
        rp = bench_repartition(t, mode, args.nout, args.reps)
        gb, res = bench_groupby(t, mode, args.nout, args.reps)
        results[mode] = res
        out[mode] = {"repartition_s": round(rp, 4),
                     "repartition_groupby_s": round(gb, 4)}
        print(f"{mode:8s} repartition: {rp*1e3:8.1f} ms   "
              f"repartition+group-by: {gb*1e3:8.1f} ms")

    same = results["compact"] == results["masked"]
    out["identical_results"] = same
    out["compact_speedup_groupby"] = round(
        out["masked"]["repartition_groupby_s"]
        / out["compact"]["repartition_groupby_s"], 3)
    out["compact_speedup_repartition"] = round(
        out["masked"]["repartition_s"] / out["compact"]["repartition_s"], 3)
    print(json.dumps(out))
    if not same:
        print("FAIL: compact and masked query results differ",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
