"""Profile the sort/scatter/groupby primitives on the real TPU to decide
where the round-3 perf work goes. Not part of the test suite."""
import time
import numpy as np
import spark_rapids_tpu  # noqa: F401  (enables x64, same as the engine)
import jax
import jax.numpy as jnp


def _force(out):
    """block_until_ready is a no-op on the axon tunnel backend; fetching a
    scalar slice forces the computation."""
    leaves = jax.tree_util.tree_leaves(out)
    jax.device_get([l[:1] if getattr(l, "ndim", 0) else l for l in leaves])


def bench(name, fn, *args, reps=3):
    _force(fn(*args))  # compile + warm
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(fn(*args))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    print(f"{name:50s} {best*1000:10.1f} ms", flush=True)
    return best


def main():
    print(jax.devices())
    rng = np.random.default_rng(0)
    N = 20_000_000
    keys64 = jnp.asarray(rng.integers(0, 3_000_000, N).astype(np.int64))
    keys32 = keys64.astype(jnp.int32)
    keysu64 = keys64.astype(jnp.uint64)
    vals = jnp.asarray(rng.uniform(0, 1, N))
    vals32 = vals.astype(jnp.float32)

    bench("argsort i32 20M", jax.jit(jnp.argsort), keys32)
    bench("argsort i64 20M", jax.jit(jnp.argsort), keys64)
    bench("argsort u64 20M", jax.jit(jnp.argsort), keysu64)
    bench("sort i32 20M (no iota)", jax.jit(jnp.sort), keys32)

    # current lexsort path shape: 3 u64 keys + null planes + iota
    from jax import lax
    def lex3(k1, k2, k3):
        cap = k1.shape[0]
        iota = jnp.arange(cap, dtype=jnp.int32)
        z = jnp.zeros(cap, jnp.uint8)
        out = lax.sort((z, z, k1, z, k2, z, k3, iota), num_keys=7, is_stable=True)
        return out[-1]
    N2 = 10_000_000
    a = keysu64[:N2]
    bench("lexsort 3xu64+nulls 10M (q67win shape)", jax.jit(lex3), a, a, a)

    def lex1_32(k1):
        iota = jnp.arange(k1.shape[0], dtype=jnp.int32)
        out = lax.sort((k1, iota), num_keys=1, is_stable=True)
        return out[-1]
    bench("lax.sort 1xu32+iota 10M", jax.jit(lex1_32), keys32[:N2].astype(jnp.uint32))
    bench("lax.sort 1xu32+iota 20M", jax.jit(lex1_32), keys32.astype(jnp.uint32))

    # segment_sum scatter into large bucket spaces
    def seg(v, k, S):
        return jax.ops.segment_sum(v, k, num_segments=S)
    segj = jax.jit(seg, static_argnums=(2,))
    bench("segment_sum f64 20M -> 3M buckets", segj, vals, keys32, 3_000_000)
    bench("segment_sum f32 20M -> 3M buckets", segj, vals32, keys32, 3_000_000)
    k100 = jnp.asarray(rng.integers(0, 100_000, 2_000_000).astype(np.int32))
    v100 = vals[:2_000_000]
    bench("segment_sum f64 2M -> 100k buckets", segj, v100, k100, 100_000)
    bench("segment_sum f64 8M -> 100k buckets", segj, vals[:8_000_000],
          jnp.asarray(rng.integers(0, 100_000, 8_000_000).astype(np.int32)), 100_000)

    # one-hot matmul variant for 100k buckets? too big. skip.
    bench("top_k 3M k=16", jax.jit(lambda v: lax.top_k(v, 16)), vals[:3_000_000])

    # gather costs
    idx = jnp.asarray(rng.integers(0, N, N).astype(np.int32))
    bench("gather f64 20M random", jax.jit(lambda v, i: v[i]), vals, idx)
    bench("gather i32 20M random", jax.jit(lambda v, i: v[i]), keys32, idx)

    # cumsum
    bench("cumsum i32 20M", jax.jit(lambda v: jnp.cumsum(v)), keys32)

    # searchsorted 20M probes into 1.5M sorted
    srt = jnp.sort(keys64[:1_500_000])
    bench("searchsorted 20M into 1.5M (i64)",
          jax.jit(lambda s, q: jnp.searchsorted(s, q)), srt, keys64)
    srt32 = srt.astype(jnp.int32)
    bench("searchsorted 20M into 1.5M (i32)",
          jax.jit(lambda s, q: jnp.searchsorted(s, q)), srt32, keys32)


if __name__ == "__main__":
    main()
