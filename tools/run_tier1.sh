#!/usr/bin/env bash
# Tier-1 verify wrapper (the ROADMAP.md command, verbatim semantics):
# CPU-backend pytest over the non-slow suite, with a DOTS_PASSED count so
# CI and sessions can diff pass counts against the seed.
#
# Usage: tools/run_tier1.sh [extra pytest args...]
set -o pipefail
cd "$(dirname "$0")/.."
LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
timeout -k 10 "${TIER1_TIMEOUT:-870}" env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
# character class is the ROADMAP one plus 'X' (xpassed) — an xpass in a
# progress line must not drop the whole line's dots from the count
echo "DOTS_PASSED=$(grep -aE '^[.FEsxX]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
exit "$rc"
