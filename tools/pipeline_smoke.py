"""CI smoke for pipelined execution (runtime/pipeline.py): on a small
multi-batch query the pipeline boundary must actually engage (depth
recorded, producer time observed — i.e. host work ran on the pool and
overlapped the consumer), a LIMIT early exit must cancel the producer,
and neither path may leak a thread. Fast (<15s); wired into
tools/ci_check.sh.
"""
from __future__ import annotations

import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402


def _non_pool_threads():
    return {t for t in threading.enumerate()
            if not t.name.startswith("rapids-host-pool")}


def main() -> int:
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSession

    rng = np.random.default_rng(3)
    t = pa.table({"k": rng.integers(0, 50, 40_000),
                  "v": rng.uniform(0, 1, 40_000)})
    s = TpuSession({"spark.rapids.sql.reader.batchSizeRows": "4096"})

    before = _non_pool_threads()
    r = (s.create_dataframe(t, num_partitions=1)
         .filter(col("v") > lit(0.25))
         .group_by("k").agg(F.count().alias("n"),
                            F.sum(col("v")).alias("sv"))).collect()
    assert r.num_rows == 50, r.num_rows
    lm = s.last_metrics()
    pipe = [v for k, v in lm.items() if k.startswith("PipelineExec")]
    assert pipe, f"no PipelineExec in plan: {sorted(lm)}"
    depth = max(v.get("pipelineDepth", 0) for v in pipe)
    produced = sum(v.get("pipelineProducerTime", 0) for v in pipe)
    batches = sum(v.get("numOutputBatches", 0) for v in pipe)
    assert depth >= 1, "pipeline fell back to synchronous"
    assert batches >= 2, f"want a multi-batch query, got {batches} batches"
    assert produced > 0, "no producer-side work observed — no overlap"

    # LIMIT early exit: producer cancelled, nothing leaked
    r2 = (s.create_dataframe(t, num_partitions=1)
          .filter(col("v") >= lit(0.0)).limit(5)).collect()
    assert r2.num_rows == 5
    leaked = _non_pool_threads() - before
    assert not leaked, f"leaked threads: {[t.name for t in leaked]}"

    print(f"pipeline smoke OK: depth={depth} batches={batches} "
          f"producer_ms={produced / 1e6:.1f} no leaked threads")
    return 0


if __name__ == "__main__":
    sys.exit(main())
