"""Serving smoke (round 17): the CI gate for the query-server layer.

1. Disabled-path overhead: with serving.enabled OFF (the default) the
   only new site an ordinary workload executes is the one
   serving.maybe_install read at session construction. Count x delta
   methodology (tools/aqe_smoke.py): count the site's firings during a
   drive, measure its per-call cost in a tight loop, bound the product
   under --tolerance (2%) of the drive. Runs FIRST, before this process
   installs any server.
2. Concurrency parity: N=4 concurrent clients hammering POST /sql over
   a real HTTP endpoint (cache hits, misses, single-flight collisions
   and forced re-executions) must each receive results byte-identical
   to the solo run of the same query.
3. Seeded admission + cancel: with maxInflight saturated by two slow
   queries (scan-delay faults on an overlay session) a third request is
   refused with HTTP 429 and a typed doc; POST /queries/<id>/cancel
   lands both slow requests as HTTP 499 cancelled within the checkpoint
   bound.
4. Replica warm-boot (subprocess): a fresh process sharing the seed
   process's historyDir + persistent compile cacheDir serves its FIRST
   hot-digest request with ZERO backend compiles (the response doc's
   xla_compiles delta and rapids_xla_compiles_total both flat) and
   byte-identical to the seed's result.

Usage: python tools/serving_smoke.py [--clients 4] [--tolerance 0.02]
Internal: --worker seed|replica --dir D (subprocess modes).
"""
from __future__ import annotations

import argparse
import base64
import http.client
import json
import os
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

QUERIES = {
    "agg": "SELECT k, SUM(v) AS sv, COUNT(*) AS n FROM t GROUP BY k",
    "filter": "SELECT k, v FROM t WHERE v > 700",
    "proj": "SELECT k, v * 2 AS v2 FROM t WHERE k < 5",
}

#: the warm-boot hot query (seed records it twice -> warmup replays it)
HOT_SQL = ("SELECT d.grp, SUM(f.price) AS rev FROM fact f "
           "JOIN dim d ON f.key = d.key GROUP BY d.grp")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port: int, path: str, payload: dict, timeout: float = 120.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get(port: int, path: str):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _probe_table(n=40_000, seed=17):
    import numpy as np
    import pyarrow as pa
    rng = np.random.default_rng(seed)
    return pa.table({"k": rng.integers(0, 12, n),
                     "v": rng.integers(1, 1000, n)})


# ---------------------------------------------------------------------------
# gate 1: disabled-path overhead (count x delta) — MUST run before any
# serving-enabled session exists in this process
# ---------------------------------------------------------------------------

def disabled_overhead(reps: int) -> dict:
    from spark_rapids_tpu.runtime import serving
    from spark_rapids_tpu.sql.session import TpuSession
    assert not serving.installed(), \
        "gate 1 must run before a server is installed"

    t = _probe_table(20_000)

    def drive():
        sess = TpuSession()
        sess.create_or_replace_temp_view("t", sess.create_dataframe(t))
        sess.sql(QUERIES["agg"]).collect()
        return sess

    sess = drive()  # warm the trace cache out of the timed drives

    counts = [0]
    real_install = serving.maybe_install

    def counting_install(s):
        counts[0] += 1
        return real_install(s)

    serving.maybe_install = counting_install
    try:
        drive()
    finally:
        serving.maybe_install = real_install

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        drive()
        best = min(best, time.perf_counter() - t0)

    iters = 100_000
    t0 = time.perf_counter()
    for _ in range(iters):
        serving.maybe_install(sess)
    per_call = (time.perf_counter() - t0) / iters

    added = counts[0] * per_call
    return {"install_reads": counts[0],
            "per_call_ns": round(per_call * 1e9, 1),
            "drive_best_s": round(best, 6),
            "disabled_overhead_pct": round(added / best * 100, 5)}


# ---------------------------------------------------------------------------
# gates 2+3: concurrency parity, admission rejection, HTTP cancel
# ---------------------------------------------------------------------------

def concurrency_parity(port: int, clients: int, result: dict) -> list:
    fails = []
    solo = {}
    for name, sql in QUERIES.items():
        code, doc = _post(port, "/sql", {"sql": sql})
        if code != 200:
            return [f"solo {name} returned {code}: {doc}"]
        solo[name] = doc["result"]

    names = list(QUERIES)
    mismatches = []
    statuses = []

    def client(i):
        for j in range(6):
            name = names[(i + j) % len(names)]
            # every third request forces a re-execution: parity must
            # hold for fresh executions too, not just cached replays
            payload = {"sql": QUERIES[name]}
            if j % 3 == 2:
                payload["cache"] = False
            code, doc = _post(port, "/sql", payload)
            statuses.append(code)
            if code != 200:
                mismatches.append(f"client{i} req{j} {name}: HTTP {code}")
            elif doc["result"] != solo[name]:
                mismatches.append(
                    f"client{i} req{j} {name} ({doc['cache']}): result "
                    f"differs from solo run")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(300)
    fails.extend(mismatches[:5])
    if len(statuses) != clients * 6:
        fails.append(f"only {len(statuses)}/{clients * 6} requests "
                     f"completed")
    _, sv = _get(port, "/serving")
    result["concurrency"] = {
        "clients": clients, "requests": len(statuses),
        "cache": sv["result_cache"]}
    if sv["result_cache"]["hits"] == 0:
        fails.append("concurrent drive recorded no cache hits")
    return fails


def admission_and_cancel(port: int, result: dict) -> list:
    from spark_rapids_tpu.runtime import serving
    fails = []
    srv = serving.server()
    old_inflight = srv.max_inflight
    srv.max_inflight = 2
    slow_payload = {
        "sql": "SELECT k, SUM(v) AS sv FROM t GROUP BY k",
        "session": "slow", "cache": False,
        "conf": {"spark.rapids.sql.reader.batchSizeRows": "512",
                 "spark.rapids.debug.faults": "scan.decode:delay:400",
                 "spark.rapids.debug.faults.delayMs": "40"}}
    boxes = [{}, {}]

    def slow_client(box):
        box["resp"] = _post(port, "/sql", slow_payload)

    try:
        threads = [threading.Thread(target=slow_client, args=(b,))
                   for b in boxes]
        for th in threads:
            th.start()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30:
            _, sv = _get(port, "/serving")
            if sv["active_requests"] >= 2:
                break
            time.sleep(0.05)
        else:
            fails.append("slow requests never both went active")
        # saturated: the third request is refused with a typed 429
        code, doc = _post(port, "/sql", {"sql": QUERIES["proj"]})
        if code != 429 or doc.get("error_type") != "QueryRejectedError":
            fails.append(f"saturated server answered {code} {doc}")
        # cancel both via the HTTP surface -> 499 within the bound
        _, qdoc = _get(port, "/queries")
        running = [q["query_id"] for q in qdoc.get("running", [])]
        t0 = time.monotonic()
        for qid in running:
            _post(port, f"/queries/{qid}/cancel", {})
        for th in threads:
            th.join(30)
        cancel_s = time.monotonic() - t0
        codes = sorted(b.get("resp", (0, None))[0] for b in boxes)
        if codes != [499, 499]:
            fails.append(f"cancelled slow requests answered {codes}")
        for b in boxes:
            d = (b.get("resp") or (0, {}))[1] or {}
            if d.get("error_type") != "QueryCancelledError":
                fails.append(f"cancel doc not typed: {d}")
                break
        if cancel_s > 10.0:
            fails.append(f"cancel->terminal took {cancel_s:.1f}s")
        result["admission_cancel"] = {
            "rejected_code": code, "cancelled_codes": codes,
            "cancel_to_terminal_s": round(cancel_s, 3)}
    finally:
        srv.max_inflight = old_inflight
    return fails


# ---------------------------------------------------------------------------
# gate 4: replica warm-boot (subprocess pair)
# ---------------------------------------------------------------------------

def _make_join_data(d: str) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(20260807)
    n, k = 50_000, 400
    pq.write_table(pa.table({
        "key": rng.integers(0, k, n).astype(np.int64),
        "price": np.round(rng.uniform(1.0, 100.0, n), 2),
    }), os.path.join(d, "fact.parquet"))
    pq.write_table(pa.table({
        "key": np.arange(k, dtype=np.int64),
        "grp": rng.integers(0, 8, k).astype(np.int64),
    }), os.path.join(d, "dim.parquet"))


def _register_join(sess, d: str) -> None:
    sess.create_or_replace_temp_view(
        "fact", sess.read_parquet(os.path.join(d, "fact.parquet")))
    sess.create_or_replace_temp_view(
        "dim", sess.read_parquet(os.path.join(d, "dim.parquet")))


def worker_seed(d: str) -> dict:
    """First process: record the hot query twice (warmup recurrence)
    against a shared historyDir + persistent compile cache."""
    from spark_rapids_tpu.runtime.serving.server import serialize_table
    from spark_rapids_tpu.sql.session import TpuSession
    sess = TpuSession({
        "spark.rapids.obs.historyDir": os.path.join(d, "hist"),
        "spark.rapids.compile.cacheDir": os.path.join(d, "xla_cache"),
    })
    _register_join(sess, d)
    sess.sql(HOT_SQL).collect()
    tbl = sess.sql(HOT_SQL).collect()
    return {"result_b64":
            base64.b64encode(serialize_table(tbl)).decode("ascii")}


def worker_replica(d: str) -> dict:
    """Fresh serving replica on the shared state: the first hot-digest
    request must execute with zero backend compiles."""
    from spark_rapids_tpu.runtime import compile_cache as CC
    from spark_rapids_tpu.runtime import obs, serving
    from spark_rapids_tpu.sql.session import TpuSession
    sess = TpuSession({
        "spark.rapids.obs.historyDir": os.path.join(d, "hist"),
        "spark.rapids.compile.cacheDir": os.path.join(d, "xla_cache"),
        "spark.rapids.compile.warmup.enabled": "true",
        "spark.rapids.serving.enabled": "true",
    })
    _register_join(sess, d)
    # drain the replay BEFORE the baseline: its persistent-cache loads
    # fire backend-compile events of their own and must not be charged
    # to the request (a client sees the same thing — the serving layer
    # holds the first request until the replay drains)
    from spark_rapids_tpu.runtime import warmup
    mgr = warmup.manager()
    drained = mgr.wait(180) if mgr is not None else False
    st = obs.state()
    ctr0 = st.registry.counter("rapids_xla_compiles_total").value \
        if st is not None else 0
    stats0 = CC.stats()["xla_compiles"]
    code, doc = serving.handle_sql({"sql": HOT_SQL})
    ctr1 = st.registry.counter("rapids_xla_compiles_total").value \
        if st is not None else 0
    return {"code": code,
            "drained": drained,
            "cache": doc.get("cache"),
            "doc_xla_compiles": doc.get("xla_compiles"),
            "counter_delta": ctr1 - ctr0,
            "stats_delta": CC.stats()["xla_compiles"] - stats0,
            "warm_boot": serving.server().warm_boot,
            "persistent_hits": CC.stats()["persistent_hits"],
            "result_b64": doc.get("result")}


def _run_worker(mode: str, d: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", mode,
         "--dir", d],
        capture_output=True, text=True, timeout=600, env=env)
    if out.returncode != 0:
        print(out.stdout)
        print(out.stderr, file=sys.stderr)
        raise SystemExit(f"serving_smoke {mode} worker failed")
    return json.loads(out.stdout.strip().splitlines()[-1])


def warm_boot_gate(result: dict) -> list:
    import tempfile
    fails = []
    with tempfile.TemporaryDirectory(prefix="serving_smoke_") as d:
        _make_join_data(d)
        seed = _run_worker("seed", d)
        rep = _run_worker("replica", d)
        wb = rep.get("warm_boot") or {}
        result["warm_boot"] = {k: v for k, v in rep.items()
                              if k != "result_b64"}
        if rep["code"] != 200 or rep["cache"] != "miss":
            fails.append(f"replica first request: code={rep['code']} "
                         f"cache={rep['cache']}")
        if not wb.get("warmed"):
            fails.append(f"replica warm boot did not complete: {wb}")
        if rep["doc_xla_compiles"] != 0 or rep["counter_delta"] != 0 \
                or rep["stats_delta"] != 0:
            fails.append(
                f"replica first hot request compiled: doc="
                f"{rep['doc_xla_compiles']} counter={rep['counter_delta']}"
                f" stats={rep['stats_delta']}")
        if rep["result_b64"] != seed["result_b64"]:
            fails.append("replica result not byte-identical to seed")
    return fails


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tolerance", type=float, default=0.02)
    ap.add_argument("--worker", choices=("seed", "replica"))
    ap.add_argument("--dir")
    args = ap.parse_args()

    if args.worker:
        fn = worker_seed if args.worker == "seed" else worker_replica
        print(json.dumps(fn(args.dir)))
        return 0

    fails = []
    result = {}

    print("[gate 1] disabled-path overhead (count x delta)...",
          flush=True)
    oh = disabled_overhead(args.reps)
    result["disabled"] = oh
    print(f"  {oh['install_reads']} install reads x "
          f"{oh['per_call_ns']}ns over {oh['drive_best_s']}s drive -> "
          f"{oh['disabled_overhead_pct']}% "
          f"(gate < {args.tolerance * 100:.0f}%)")
    if oh["disabled_overhead_pct"] > args.tolerance * 100:
        fails.append("disabled-path serving overhead over budget")

    print("[gates 2+3] serving HTTP surface...", flush=True)
    from spark_rapids_tpu.sql.session import TpuSession
    port = _free_port()
    sess = TpuSession({
        "spark.rapids.serving.enabled": "true",
        "spark.rapids.obs.port": str(port),
    })
    sess.create_or_replace_temp_view(
        "t", sess.create_dataframe(_probe_table()))
    from spark_rapids_tpu.runtime import obs
    port = obs.state().server.port

    f2 = concurrency_parity(port, args.clients, result)
    c = result.get("concurrency", {})
    print(f"  parity: {c.get('requests', 0)} requests from "
          f"{args.clients} clients, cache {c.get('cache')}")
    fails.extend(f2)

    f3 = admission_and_cancel(port, result)
    ac = result.get("admission_cancel", {})
    print(f"  admission+cancel: {ac}")
    fails.extend(f3)

    print("[gate 4] replica warm-boot (subprocess pair)...", flush=True)
    f4 = warm_boot_gate(result)
    print(f"  {result.get('warm_boot')}")
    fails.extend(f4)

    print(json.dumps(result, sort_keys=True))
    if fails:
        print("serving_smoke: FAIL")
        for f in fails:
            print(f"  - {f}")
        return 1
    print(f"serving_smoke: PASS ({args.clients} concurrent clients "
          f"byte-identical to solo; saturated intake 429; HTTP cancel "
          f"499 in {ac.get('cancel_to_terminal_s')}s; replica warm boot "
          f"zero-compile; disabled path "
          f"{oh['disabled_overhead_pct']}%)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
