"""Flight-recorder smoke: always-on must stay (nearly) free, triggers
must dump.

The retroactive-observability CI gate (tools/ci_check.sh):

1. **Overhead** (trace_overhead.py methodology — naive A/B wall-clock
   comparison is an order of magnitude noisier than the quantity under
   test on shared CI): count how often each instrumentation entry point
   fires during one drive of the fused-bench chain, measure each entry
   point's per-call cost WITH THE RECORDER ON minus its pre-flight
   equivalent (the bare GpuMetric timer / nothing) over 10^5 tight-loop
   iterations, and gate sum(count_i x delta_i) < 2% of the drive's
   best-of wall time.

2. **Triggers** (chaos_smoke methodology — conf-armed fault injection,
   tracing OFF throughout):
   - a clean query writes NO dump;
   - an injected scan.decode ioerror fails the query and dumps a
     readable Chrome-trace file (validated by profiler_report) whose
     events cover the failing query (exec spans + faultInjected +
     queryError) with reason=query_failed;
   - the same fault under spark.rapids.fallback.cpu.enabled degrades
     the query (answers still correct vs the clean run) and dumps with
     reason=query_degraded;
   - an absolute SLO bound trips on a clean query: slo_breach dump,
     rapids_slo_breaches_total bumped, /healthz carries the last-slow
     digest + attribution summary + dump path;
   - opening the circuit breaker dumps with reason=breaker_open.

3. **Attribution**: the probe query's buckets sum to its wall time
   within 1% (the PR 3 reconciliation bar).

Run:  python tools/flight_smoke.py [--rows 400000] [--batch 2048]
                                   [--reps 9] [--tolerance 0.02]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import bench_fusion as BF  # noqa: E402

_ENTRY_POINTS = ("exec_span", "metric_span", "span", "instant")


def _count_calls(trace, drive):
    counts = {n: 0 for n in _ENTRY_POINTS}
    saved = {n: getattr(trace, n) for n in _ENTRY_POINTS}

    def wrap(name):
        inner = saved[name]

        def counted(*a, **kw):
            counts[name] += 1
            return inner(*a, **kw)
        return counted

    try:
        for n in _ENTRY_POINTS:
            setattr(trace, n, wrap(n))
        drive()
    finally:
        for n in _ENTRY_POINTS:
            setattr(trace, n, saved[n])
    return counts


def _per_call_deltas(trace, iters=100_000):
    """Flight-ON per-call cost of each entry point MINUS its pre-flight
    equivalent, in seconds (clamped >= 0). The recorder must be
    installed when this runs."""
    from spark_rapids_tpu.runtime.metrics import GpuMetric

    class _Node:
        lore_id = None

        def name(self):
            return "X"

    node, m = _Node(), GpuMetric("opTime")

    def loop(fn):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        return (time.perf_counter() - t0) / iters

    def bare_timer():
        with m.ns():
            pass

    def nothing():
        pass

    def exec_span_full():
        with trace.exec_span(node, m):
            pass

    def metric_span_full():
        with trace.metric_span("x", m):
            pass

    def span_full():
        with trace.span("x"):
            pass

    base_timer = min(loop(bare_timer) for _ in range(3))
    base_empty = min(loop(nothing) for _ in range(3))
    costs = {
        "exec_span": min(loop(exec_span_full) for _ in range(3)),
        "metric_span": min(loop(metric_span_full) for _ in range(3)),
        "span": min(loop(span_full) for _ in range(3)),
        "instant": min(loop(lambda: trace.instant("x")) for _ in range(3)),
    }
    return {
        "exec_span": max(costs["exec_span"] - base_timer, 0.0),
        "metric_span": max(costs["metric_span"] - base_timer, 0.0),
        "span": max(costs["span"] - base_empty, 0.0),
        "instant": max(costs["instant"] - base_empty, 0.0),
    }


def _dumps(d):
    return sorted(glob.glob(os.path.join(d, "flight_*.json")))


def _flight_conf(flight_dir, **extra):
    conf = {
        "spark.rapids.obs.flight.path": flight_dir,
        "spark.rapids.obs.flight.minIntervalSeconds": "0",
        "spark.rapids.sql.reader.batchSizeRows": "4096",
    }
    conf.update(extra)
    return conf


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--reps", type=int, default=9)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    import numpy as np
    import pyarrow as pa

    import profiler_report as PR
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.runtime import obs, trace, watchdog
    from spark_rapids_tpu.runtime.obs import flight
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSession

    # -- 1. overhead: recorder ON, tracing OFF ------------------------------
    flight_dir = tempfile.mkdtemp(prefix="flight_smoke_")
    flight.install(capacity=2048, out_dir=flight_dir, min_interval_s=0.0)
    t = BF._table(args.rows)
    batches = BF._device_batches(t, args.batch)
    # UNFUSED chain: per-batch exec_span traffic (the fused stage's hot
    # loop has no per-batch entry-point calls and would measure zero)
    drive, _res = BF.make_chain_stage(t, False, 1, args.batch, batches)
    drive()  # warm every kernel cache before measuring
    drive_s = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        drive()
        drive_s.append(time.perf_counter() - t0)
    drive_best = min(drive_s)
    counts = _count_calls(trace, drive)
    deltas = _per_call_deltas(trace)
    added_s = sum(counts[n] * deltas[n] for n in _ENTRY_POINTS)
    overhead = added_s / drive_best

    # -- 2. triggers --------------------------------------------------------
    obs.shutdown_for_tests()
    flight.uninstall_for_tests()
    watchdog.uninstall_for_tests()
    rng = np.random.default_rng(20260804)
    table = pa.table({"k": rng.integers(0, 50, 60_000),
                      "v": rng.integers(0, 1000, 60_000)})

    def query(sess):
        return (sess.create_dataframe(table, num_partitions=2)
                .filter(col("v") > lit(10))
                .group_by("k").agg(F.sum(col("v")).alias("sv")).collect())

    # clean run: recorder armed, NO dump
    sess = TpuSession(_flight_conf(flight_dir))
    clean = query(sess)
    n0 = len(_dumps(flight_dir))
    assert n0 == 0, f"clean run wrote {n0} flight dump(s)"

    # failed query (tracing OFF): a readable Chrome-trace dump
    sess = TpuSession(_flight_conf(
        flight_dir, **{"spark.rapids.debug.faults": "scan.decode:ioerror"}))
    failed = False
    try:
        query(sess)
    except Exception:  # noqa: BLE001 - the injected fault
        failed = True
    assert failed, "injected scan.decode ioerror did not fail the query"
    dumps = _dumps(flight_dir)
    assert len(dumps) == 1 and "query_failed" in dumps[0], dumps
    events = PR.validate_chrome_trace(dumps[0])
    names = {e["name"] for e in events}
    spans = sum(1 for e in events if e["ph"] == "X")
    assert spans > 0, "failure dump has no spans"
    assert "faultInjected" in names and "queryError" in names \
        and "flightTrigger" in names, sorted(names)
    fail_doc = json.load(open(dumps[0]))["otherData"]
    assert fail_doc["reason"] == "query_failed" \
        and fail_doc["error"] == "InjectedFaultError", fail_doc

    # degraded query: CPU fallback answers, dump says query_degraded
    sess = TpuSession(_flight_conf(
        flight_dir, **{
            "spark.rapids.debug.faults": "scan.decode:ioerror",
            "spark.rapids.fallback.cpu.enabled": "true"}))
    degraded_result = query(sess)
    assert sess.last_action_status[0] == "degraded", \
        sess.last_action_status
    assert degraded_result.sort_by("k").equals(clean.sort_by("k")), \
        "degraded result differs from the clean run"
    dumps = _dumps(flight_dir)
    assert len(dumps) == 2 and "query_degraded" in dumps[1], dumps
    PR.validate_chrome_trace(dumps[1])

    # SLO breach: absolute bound trips a clean query
    obs.shutdown_for_tests()
    sess = TpuSession(_flight_conf(
        flight_dir, **{"spark.rapids.obs.slo.latencySeconds": "1e-6"}))
    query(sess)
    st = obs.state()
    assert st is not None and st.slo.breaches >= 1, "no SLO breach"
    hz = obs.healthz()
    last_slow = hz["slo"]["last_slow"]
    assert last_slow and last_slow["plan_digest"] \
        and last_slow["flight_dump"] \
        and last_slow["attribution"]["top_buckets"], last_slow
    assert hz["flight"]["last_dump"]["reason"] == "slo_breach", \
        hz["flight"]
    slow_events = PR.validate_chrome_trace(last_slow["flight_dump"])
    assert any(e["name"] == "slowQuery" for e in slow_events)
    breach_count = st.registry.counter("rapids_slo_breaches_total").value
    assert breach_count >= 1, breach_count

    # attribution reconciliation (the 1% bar) on the breaching query
    attr = sess.last_attribution()
    bucket_sum = sum(attr["buckets"].values())
    recon = abs(bucket_sum - attr["wall_seconds"]) / attr["wall_seconds"]
    assert recon < 0.01, (bucket_sum, attr["wall_seconds"])

    # breaker open: one more dump
    before = len(_dumps(flight_dir))
    brk = watchdog.breaker()
    brk.configure(1, 60.0, 60.0)
    brk.record_failure("SmokeError")
    assert brk.state == "open"
    dumps = _dumps(flight_dir)
    assert len(dumps) == before + 1 and "breaker_open" in dumps[-1], dumps
    watchdog.uninstall_for_tests()
    obs.shutdown_for_tests()
    flight.uninstall_for_tests()

    result = {
        "drive_best_s": round(drive_best, 5),
        "instr_calls_per_drive": counts,
        "per_call_delta_ns": {n: round(d * 1e9, 1)
                              for n, d in deltas.items()},
        "flight_overhead_s": round(added_s, 7),
        "flight_overhead_pct": round(overhead * 100, 4),
        "tolerance_pct": args.tolerance * 100,
        "failure_dump_spans": spans,
        "attribution_reconciliation_pct": round(recon * 100, 5),
        "dumps_written": len(_dumps(flight_dir)),
    }
    print(json.dumps(result))
    if overhead > args.tolerance:
        print(f"FAIL: always-on flight overhead {overhead * 100:.3f}% "
              f"exceeds {args.tolerance * 100:.1f}%", file=sys.stderr)
        return 1
    print(f"PASS: always-on recorder overhead {overhead * 100:.3f}% of "
          f"the drive (tolerance {args.tolerance * 100:.1f}%); "
          f"failure/degrade/SLO/breaker each dumped a validating "
          f"Chrome trace; clean run silent; attribution reconciles "
          f"({recon * 100:.4f}%)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
