"""Per-dispatch blocking profile of a bench query: wraps fuse.fused so
every fused stage call blocks and is timed individually, exposing where
wall-clock goes inside the async pipeline. Also wraps the non-fused sync
points (prepare_dense_build)."""
import os
import sys
import time
from collections import defaultdict

import numpy as np

ROWS = int(os.environ.get("ROWS", 30_000_000))
ORDERS = ROWS // 10
Q = os.environ.get("Q", "q3join")

import jax
import pyarrow as pa
from spark_rapids_tpu.exec import fuse
from spark_rapids_tpu.ops import join as J

TIMES = defaultdict(float)
COUNTS = defaultdict(int)
_orig_fused = fuse.fused


def timed_fused(key, builder):
    fn = _orig_fused(key, builder)

    def wrapper(*a, **k):
        t0 = time.perf_counter()
        out = fn(*a, **k)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        name = str(key[0]) + (":" + str(key[1]) if len(key) > 1 and isinstance(key[1], str) else "")
        TIMES[name] += dt
        COUNTS[name] += 1
        return out
    return wrapper


fuse.fused = timed_fused
# tpu_nodes imported fuse as a module attr, so patching the module works
# only if call sites do fuse.fused(...) — they do.

_orig_prep = J.prepare_dense_build


def timed_prep(*a, **k):
    t0 = time.perf_counter()
    out = _orig_prep(*a, **k)
    TIMES["prepare_dense_build"] += time.perf_counter() - t0
    COUNTS["prepare_dense_build"] += 1
    return out


J.prepare_dense_build = timed_prep

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit

rng = np.random.default_rng(42)
t = pa.table({
    "l_orderkey": rng.integers(0, ORDERS, ROWS).astype(np.int64),
    "l_returnflag": np.array(["A", "N", "R"])[rng.integers(0, 3, ROWS)],
    "l_linestatus": np.array(["F", "O"])[rng.integers(0, 2, ROWS)],
    "l_quantity": rng.integers(1, 51, ROWS).astype(np.float64),
    "l_extendedprice": np.round(rng.uniform(900.0, 105000.0, ROWS), 2),
    "l_discount": np.round(rng.uniform(0.0, 0.10, ROWS), 2),
    "l_shipdate": rng.integers(8400, 10600, ROWS).astype(np.int32),
})
orders = pa.table({
    "o_orderkey": np.arange(ORDERS, dtype=np.int64),
    "o_orderdate": rng.integers(8400, 10600, ORDERS).astype(np.int32),
})

sess = TpuSession()
print("[prof] uploading...", file=sys.stderr, flush=True)
cached = sess.create_dataframe(t).cache(); cached.count()
ocached = sess.create_dataframe(orders).cache(); ocached.count()
SHFL_ROWS = min(ROWS, 8_000_000)
sharded = sess.create_dataframe(
    t.slice(0, SHFL_ROWS).select(["l_orderkey", "l_quantity"]),
    num_partitions=4).cache()
sharded.count()
WIN_ROWS = min(ROWS, 10_000_000)
wcached = sess.create_dataframe(
    t.slice(0, WIN_ROWS).select(["l_returnflag", "l_linestatus",
                                 "l_shipdate"])).cache()
wcached.count()


def q3join():
    li = cached.filter(col("l_shipdate") > lit(9100))
    od = ocached.filter(col("o_orderdate") < lit(9500))
    j = li.join(od, on=[(col("l_orderkey"), col("o_orderkey"))], how="inner")
    g = (j.select(col("l_orderkey"),
                  (col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias("rev"))
         .group_by(col("l_orderkey")).agg(F.sum("rev").alias("rev")))
    top = g.order_by(col("rev").desc(), col("l_orderkey").asc()).limit(10)
    return top.to_pydict()


def q67win():
    from spark_rapids_tpu.expr.window import Window
    w = Window.partition_by(col("l_returnflag"), col("l_linestatus")) \
              .order_by(col("l_shipdate"))
    out = (wcached.select(col("l_returnflag"), col("l_linestatus"),
                          F.rank().over(w).alias("rk"))
           .group_by(col("l_returnflag"), col("l_linestatus"))
           .agg(F.max("rk").alias("mx")))
    return out.to_pydict()


def q72shfl():
    g = (sharded.select((col("l_orderkey") % lit(100_000)).alias("k"),
                        col("l_quantity"))
         .group_by(col("k"))
         .agg(F.sum("l_quantity").alias("s"), F.count("l_quantity").alias("c")))
    out = g.agg(F.count(col("k")).alias("n"), F.sum(col("s")).alias("ts"),
                F.sum(col("c")).alias("tc"))
    return out.to_pydict()


for Q in [q for q in os.environ.get("QS", "q3join,q72shfl").split(",")]:
    fn = {"q3join": q3join, "q72shfl": q72shfl, "q67win": q67win}[Q]
    print(f"[prof] warmup {Q}...", file=sys.stderr, flush=True)
    t0 = time.perf_counter(); fn(); warm = time.perf_counter() - t0
    TIMES.clear(); COUNTS.clear()
    t0 = time.perf_counter(); fn(); total = time.perf_counter() - t0
    print(f"[prof] {Q} rows={ROWS} warm={warm:.2f}s steady={total:.3f}s (blocking-instrumented)")
    acc = 0.0
    for k in sorted(TIMES, key=lambda k: -TIMES[k]):
        print(f"  {TIMES[k]*1e3:8.1f} ms  x{COUNTS[k]:<3d} {k}")
        acc += TIMES[k]
    print(f"  {'-'*40}\n  {acc*1e3:8.1f} ms accounted; {(total-acc)*1e3:.1f} ms other")
