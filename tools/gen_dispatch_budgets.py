"""Golden dispatch-budget generator for the NDS probe queries.

Writes tests/golden_plans/dispatch_budgets.json: for every translated
NDS query (tools/nds_probe.py QUERIES), the static per-batch device-
dispatch budget of its CONVERTED plan as computed by
``analysis.plan_verify.dispatch_budget`` — narrow dispatches per batch,
fusion groups, pipeline boundaries, exec census. The tables are the
same tiny SF / seed the tier-1 NDS regression uses, so the committed
budgets pin exactly the plans CI sees.

tests/test_analysis.py re-derives each budget and diffs it against this
file (``compare_budget``): a stage-fusion or pipeline-insertion
regression then fails loudly with the changed dimension named, instead
of showing up as silent perf loss in a later benchmark round. The same
test also runs ``verify_plan`` on every probe plan, so the invariant
checks gate CI unconditionally (the debug conf only adds per-query
verification in live sessions).

Run after any INTENDED plan-shape change:

    python tools/gen_dispatch_budgets.py
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# mirror tests/conftest.py EXACTLY: the budgets pin the plans the tier-1
# suite converts, and plan shape depends on the device count (the
# single-device complete-agg path in overrides.py vs partial+exchange)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_fast_math" not in _flags:
    _flags = (_flags + " --xla_cpu_enable_fast_math=false").strip()
os.environ["XLA_FLAGS"] = _flags

#: keep in lockstep with tests/test_nds_probe.py's fixture — the golden
#: budgets must pin the exact plans the tier-1 suite converts
SF = 0.002
SEED = 7

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "tests", "golden_plans", "dispatch_budgets.json")


def _load_nds():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "nds_probe.py")
    spec = importlib.util.spec_from_file_location("nds_probe", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def build_budgets():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.analysis.plan_verify import (dispatch_budget,
                                                       verify_plan)
    from spark_rapids_tpu.sql.session import TpuSession

    nds = _load_nds()
    sess = TpuSession()
    tables = nds.gen_tables(SF, seed=SEED)
    d = {name: sess.create_dataframe(t).cache()
         for name, t in tables.items()}
    budgets = {}
    for qn in sorted(nds.QUERIES):
        df = nds.QUERIES[qn](sess, d)
        exec_root, _meta = sess.prepare_execution(df.plan)
        verify_plan(exec_root)  # a golden pin of an ILLEGAL plan is void
        budgets[qn] = dispatch_budget(exec_root)
    return budgets


def main() -> int:
    budgets = build_budgets()
    doc = {"_generator": "tools/gen_dispatch_budgets.py",
           "_sf": SF, "_seed": SEED, "budgets": budgets}
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    total = sum(b["narrow_dispatches_per_batch"] for b in budgets.values())
    print(f"wrote {os.path.relpath(OUT)}: {len(budgets)} queries, "
          f"{total} narrow dispatches/batch total")
    return 0


if __name__ == "__main__":
    sys.exit(main())
