"""Golden dispatch-budget + cost-signature generator (NDS probe).

Writes two artifacts under tests/golden_plans/:

- ``dispatch_budgets.json``: for every translated NDS query
  (tools/nds_probe.py QUERIES), the static per-batch device-dispatch
  budget of its CONVERTED plan as computed by
  ``analysis.plan_verify.dispatch_budget`` — narrow dispatches per
  batch, fusion groups, pipeline boundaries, exec census.
- ``cost_signatures.json``: the kernel cost auditor's per-query COST
  SIGNATURE (analysis/kernel_audit.py) for every NDS query — per
  kernel family: dispatches, audited entries/shapes, XLA flops and
  bytes accessed, input/output plane bytes — plus the
  ``KERNEL_PRIMITIVES`` roster, so CI catches a kernel that silently
  starts moving 2x the bytes even when wall time hides it.

The tables are the same tiny SF / seed the tier-1 NDS regression uses,
so the committed artifacts pin exactly the plans CI sees.

tests/test_analysis.py re-derives each budget and diffs it against the
budget file (``compare_budget``); tests/test_kernel_audit.py diffs a
cold 2-query prefix (tier-1) and the full set (@slow) against the
signature file (``kernel_audit.compare_signature``) — a regression
fails loudly with the changed dimension named per query.

DETERMINISM CONTRACT (the cost pass): signatures are reproducible only
under the exact replay this generator performs — a FRESH session and
freshly generated tables (the budgets pass leaks session state
otherwise), ``gen_tables(SF=0.002, seed=7)``, the compile cache AND
audit record table cleared together (``clear_for_cold_audit``), and
queries executed in sorted name order. Accounting is shape-complete
(every traced shape is audited), so within that replay the signatures
are thread-order and process independent; two consecutive generator
runs must produce byte-identical cost_signatures —
``tools/audit_smoke.py`` gates exactly that. The generator ABORTS on
any audit finding (an unresolvable cost analysis or a dispatch of an
entry traced before the audit armed): a golden pin of an incompletely
audited run is void.

Run after any INTENDED plan- or kernel-shape change:

    python tools/gen_dispatch_budgets.py
"""
from __future__ import annotations

import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# mirror tests/conftest.py EXACTLY: the budgets pin the plans the tier-1
# suite converts, and plan shape depends on the device count (the
# single-device complete-agg path in overrides.py vs partial+exchange)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_fast_math" not in _flags:
    _flags = (_flags + " --xla_cpu_enable_fast_math=false").strip()
os.environ["XLA_FLAGS"] = _flags

#: keep in lockstep with tests/test_nds_probe.py's fixture — the golden
#: budgets must pin the exact plans the tier-1 suite converts
SF = 0.002
SEED = 7

#: pinned EXPLICITLY (not left to the conf default): adaptive execution
#: changes plan shape (AdaptiveShuffledHashJoinExec in the census, the
#: measured cost pass replanning exchanges from history), so a golden
#: generated under a drifted default would silently pin different plans
#: than CI converts. Recorded in both artifact headers.
ADAPTIVE = "true"

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "tests", "golden_plans", "dispatch_budgets.json")
OUT_SIG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "tests", "golden_plans", "cost_signatures.json")


def _load_nds():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "nds_probe.py")
    spec = importlib.util.spec_from_file_location("nds_probe", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def build_budgets():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.analysis.plan_verify import (dispatch_budget,
                                                       verify_plan)
    from spark_rapids_tpu.sql.session import TpuSession

    nds = _load_nds()
    sess = TpuSession({"spark.rapids.sql.adaptive.enabled": ADAPTIVE})
    tables = nds.gen_tables(SF, seed=SEED)
    d = {name: sess.create_dataframe(t).cache()
         for name, t in tables.items()}
    budgets = {}
    for qn in sorted(nds.QUERIES):
        df = nds.QUERIES[qn](sess, d)
        exec_root, _meta = sess.prepare_execution(df.plan)
        verify_plan(exec_root)  # a golden pin of an ILLEGAL plan is void
        budgets[qn] = dispatch_budget(exec_root)
    return budgets


def build_cost_signatures(limit=None, queries=None):
    """The audited cost pass: execute every NDS query on a FRESH
    session with the kernel cost auditor armed, from a cold compile
    cache, in sorted name order (the determinism contract in the module
    docstring). Returns {query_name: signature}. Raises RuntimeError on
    any audit finding."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.analysis import kernel_audit as KA
    from spark_rapids_tpu.sql.session import TpuSession

    nds = _load_nds()
    # a fresh session AND fresh tables: the budgets pass (or any prior
    # work in this process) must not decide which query first-traces a
    # shared entry
    sess = TpuSession({"spark.rapids.obs.audit.enabled": "true",
                       "spark.rapids.sql.adaptive.enabled": ADAPTIVE})
    tables = nds.gen_tables(SF, seed=SEED)
    d = {name: sess.create_dataframe(t).cache()
         for name, t in tables.items()}
    KA.clear_for_cold_audit()
    names = sorted(queries if queries is not None else nds.QUERIES)
    if limit:
        names = names[:int(limit)]
    sigs = {}
    for qn in names:
        df = nds.QUERIES[qn](sess, d)
        df.collect()
        sig = KA.query_signature(sess.last_audit())
        if sig is None:
            raise RuntimeError(f"{qn}: no audit summary (audit disarmed "
                               f"mid-pass?)")
        sigs[qn] = sig
    found = KA.findings()
    if found:
        raise RuntimeError(
            "audit findings void this golden run:\n  "
            + "\n  ".join(found[:20]))
    return sigs


def signature_doc(sigs) -> dict:
    from spark_rapids_tpu.analysis.kernel_audit import KERNEL_PRIMITIVES
    return {"_generator": "tools/gen_dispatch_budgets.py",
            "_sf": SF, "_seed": SEED, "_adaptive": ADAPTIVE,
            "kernel_primitives": sorted(KERNEL_PRIMITIVES),
            "cost_signatures": sigs}


def dump_signatures(sigs, path) -> None:
    with open(path, "w") as f:
        json.dump(signature_doc(sigs), f, indent=1, sort_keys=True)
        f.write("\n")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    sig_only = "--signatures-only" in argv
    budgets_only = "--budgets-only" in argv
    limit = None
    out_sig = OUT_SIG
    if "--limit" in argv:
        limit = int(argv[argv.index("--limit") + 1])
    if "--out" in argv:
        out_sig = argv[argv.index("--out") + 1]
    if limit and os.path.abspath(out_sig) == os.path.abspath(OUT_SIG):
        # a partial pass must never overwrite the committed 98-query
        # golden: audit_smoke and the tier-1 prefix would then diff
        # against a truncated artifact
        print("error: --limit requires --out (refusing to overwrite "
              "the committed golden with a partial signature set)",
              file=sys.stderr)
        return 2
    if not sig_only:
        budgets = build_budgets()
        doc = {"_generator": "tools/gen_dispatch_budgets.py",
               "_sf": SF, "_seed": SEED, "_adaptive": ADAPTIVE,
               "budgets": budgets}
        with open(OUT, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        total = sum(b["narrow_dispatches_per_batch"]
                    for b in budgets.values())
        print(f"wrote {os.path.relpath(OUT)}: {len(budgets)} queries, "
              f"{total} narrow dispatches/batch total")
    if not budgets_only:
        if not sig_only:
            # process purity: the cost pass replays in a FRESH
            # interpreter so the committed golden comes from exactly
            # the process shape audit_smoke's determinism gate re-runs
            # (the budgets pass above must not be able to leak
            # process-global state into the signatures)
            import subprocess
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--signatures-only", "--out", out_sig]
            if limit:
                cmd += ["--limit", str(limit)]
            rc = subprocess.run(cmd).returncode
            if rc != 0:
                return rc
            return 0
        sigs = build_cost_signatures(limit=limit)
        dump_signatures(sigs, out_sig)
        nbytes = sum(c["bytes_accessed"] for s in sigs.values()
                     for c in s.values())
        print(f"wrote {os.path.relpath(out_sig)}: {len(sigs)} query cost "
              f"signatures, {nbytes / 1e9:.3f} GB audited bytes total")
    return 0


if __name__ == "__main__":
    sys.exit(main())
