"""Microbenchmark TopN primitives on the live chip: where do 684ms go
for top-10 of ~500k grouped rows?"""
import time
import numpy as np
import jax
import jax.numpy as jnp

N = 1 << 20  # ~1M candidate capacity (agg output rounds up)
rng = np.random.default_rng(0)
x = jnp.asarray(rng.uniform(0, 1e9, N).astype(np.float32))
live = jnp.asarray(rng.uniform(0, 1, N) < 0.5)


def bench(name, fn, *args):
    fn(*args)  # compile
    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.2f} ms")


@jax.jit
def topk10(x):
    return jax.lax.top_k(x, 10)[0]


@jax.jit
def topk10_masked(x, live):
    img = jnp.where(live, x, -jnp.inf)
    v = jax.lax.top_k(img, 10)[0]
    thr = v[-1]
    cand = live & (img >= thr)
    return cand, jnp.sum(cand.astype(jnp.int32))


@jax.jit
def max_only(x, live):
    return jnp.max(jnp.where(live, x, -jnp.inf))


@jax.jit
def blockmax_topk(x, live):
    # two-stage: block-reduce to 4096 maxima, top_k the blocks, then
    # threshold = min of those (a lower bound on the true kth value)
    img = jnp.where(live, x, -jnp.inf)
    b = img.reshape(4096, -1)
    bm = jnp.max(b, axis=1)
    v = jax.lax.top_k(bm, 10)[0]
    thr = v[-1]
    cand = live & (img >= thr)
    return cand, jnp.sum(cand.astype(jnp.int32))


@jax.jit
def full_sort(x):
    return jnp.sort(x)


@jax.jit
def argsortx(x):
    return jnp.argsort(x)


bench("max", max_only, x, live)
bench("top_k(k=10)", topk10, x)
bench("top_k masked+count", topk10_masked, x, live)
bench("blockmax topk", blockmax_topk, x, live)
bench("full sort 1M", full_sort, x)
bench("argsort 1M", argsortx, x)

# dispatch overhead measurement: tiny op round trip
t0 = time.perf_counter()
for _ in range(10):
    float(jnp.float32(1.0) + 1.0)
print(f"tiny dispatch+fetch round trip: {(time.perf_counter()-t0)/10*1e3:.1f} ms")
