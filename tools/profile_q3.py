"""Per-exec timing of the losing bench queries at reduced scale."""
import os
import sys
import time
import numpy as np

ROWS = int(os.environ.get("ROWS", 8_000_000))
ORDERS = ROWS // 10
Q = os.environ.get("Q", "q3join")

import pyarrow as pa
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.expr.window import Window

rng = np.random.default_rng(42)
t = pa.table({
    "l_orderkey": rng.integers(0, ORDERS, ROWS).astype(np.int64),
    "l_returnflag": np.array(["A", "N", "R"])[rng.integers(0, 3, ROWS)],
    "l_linestatus": np.array(["F", "O"])[rng.integers(0, 2, ROWS)],
    "l_quantity": rng.integers(1, 51, ROWS).astype(np.float64),
    "l_extendedprice": np.round(rng.uniform(900.0, 105000.0, ROWS), 2),
    "l_discount": np.round(rng.uniform(0.0, 0.10, ROWS), 2),
    "l_shipdate": rng.integers(8400, 10600, ROWS).astype(np.int32),
})
orders = pa.table({
    "o_orderkey": np.arange(ORDERS, dtype=np.int64),
    "o_orderdate": rng.integers(8400, 10600, ORDERS).astype(np.int32),
})

sess = TpuSession()
print("[prof] uploading...", file=sys.stderr, flush=True)
cached = sess.create_dataframe(t).cache(); cached.count()
ocached = sess.create_dataframe(orders).cache(); ocached.count()
SHUFFLE_PARTS = 4
sharded = sess.create_dataframe(t, num_partitions=SHUFFLE_PARTS).cache()
sharded.count()


def q3join():
    li = cached.filter(col("l_shipdate") > lit(9100))
    od = ocached.filter(col("o_orderdate") < lit(9500))
    j = li.join(od, on=[(col("l_orderkey"), col("o_orderkey"))], how="inner")
    g = (j.select(col("l_orderkey"),
                  (col("l_extendedprice") * (lit(1.0) - col("l_discount"))).alias("rev"))
         .group_by(col("l_orderkey")).agg(F.sum("rev").alias("rev")))
    top = g.order_by(col("rev").desc(), col("l_orderkey").asc()).limit(10)
    return top.to_pydict()


def q67win():
    w = Window.partition_by(col("l_returnflag"), col("l_linestatus")) \
              .order_by(col("l_shipdate"))
    out = (cached.select(col("l_returnflag"), col("l_linestatus"),
                         F.rank().over(w).alias("rk"))
           .group_by(col("l_returnflag"), col("l_linestatus"))
           .agg(F.max("rk").alias("mx")))
    return out.to_pydict()


def q72shfl():
    g = (sharded.select((col("l_orderkey") % lit(100_000)).alias("k"),
                        col("l_quantity"))
         .group_by(col("k"))
         .agg(F.sum("l_quantity").alias("s"), F.count("l_quantity").alias("c")))
    return g.to_pydict()


fn = {"q3join": q3join, "q67win": q67win, "q72shfl": q72shfl}[Q]
print(f"[prof] warmup {Q}...", file=sys.stderr, flush=True)
t0 = time.perf_counter(); fn(); warm = time.perf_counter() - t0
times = []
for _ in range(2):
    t0 = time.perf_counter(); fn(); times.append(time.perf_counter() - t0)
print(f"[prof] {Q} rows={ROWS} warm={warm:.2f}s best={min(times):.3f}s")
m = sess.last_metrics()
for k, v in m.items():
    interesting = {mk: mv for mk, mv in v.items()
                   if ("Time" in mk or "time" in mk) and mv and mv > 0.005}
    if interesting:
        print(f"  {k}: " + ", ".join(f"{mk}={mv:.3f}" for mk, mv in
                                     sorted(interesting.items(), key=lambda x: -x[1])))
