"""Isolate q67win costs: tiny-B stacked masked reductions vs scatter-max,
and the window sort/gather pieces, at 10M rows on device."""
import time
import spark_rapids_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

N = 10_000_000
CAP = 1 << 24  # 16.7M (the window batch capacity)


@jax.jit
def make():
    i = jnp.arange(CAP, dtype=jnp.uint32)
    h = (i * jnp.uint32(2654435761)) ^ (i >> jnp.uint32(13))
    bucket = (h % jnp.uint32(12)).astype(jnp.int32)
    rk = (h % jnp.uint32(1 << 22)).astype(jnp.int32)
    codes_rf = (h % jnp.uint32(3)).astype(jnp.int32)
    sd = (h % jnp.uint32(2200)).astype(jnp.int32) + 8400
    live = i < jnp.uint32(N)
    return bucket, rk, codes_rf, sd, live


bucket, rk, codes_rf, sd, live = make()
float(jnp.sum(rk[:8]))


def t(name, fn, *a, reps=3):
    float(fn(*a))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(*a))
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms", flush=True)


@jax.jit
def stacked12_max(bucket, rk, live):
    MIN = jnp.int32(np.iinfo(np.int32).min)
    outs = jnp.stack([jnp.max(jnp.where(live & (bucket == b), rk, MIN))
                      for b in range(12)])
    occ = jnp.stack([jnp.any(live & (bucket == b)) for b in range(12)])
    return outs[0].astype(jnp.float32) + occ[-1]


@jax.jit
def scatter12_max(bucket, rk, live):
    sb = jnp.where(live, bucket, jnp.int32(12))
    mx = jax.ops.segment_max(rk, sb, num_segments=13)[:12]
    cnt = jax.ops.segment_sum(jnp.ones(CAP, jnp.int32), sb,
                              num_segments=13)[:12]
    return mx[0].astype(jnp.float32) + (cnt[-1] > 0)


@jax.jit
def onehot_matmul_max_trick(bucket, rk, live):
    # max via one-hot f32 matmul of exp? no — just measure a SUM matmul
    oh = (bucket[:, None] == jnp.arange(12)[None, :]) & live[:, None]
    s = jnp.sum(oh.astype(jnp.float32) * rk[:, None].astype(jnp.float32),
                axis=0)
    return s[0]


@jax.jit
def pack_sort_10m(codes_rf, sd, live):
    packed = (codes_rf.astype(jnp.int64) << jnp.int64(12)) | sd.astype(jnp.int64)
    packed = jnp.where(live, packed, jnp.int64(1) << jnp.int64(40))
    perm = jnp.argsort(packed, stable=True).astype(jnp.int32)
    return perm[0] + perm[-1]


@jax.jit
def gather3(perm_src, codes_rf, sd, rk):
    a = codes_rf[perm_src]
    b = sd[perm_src]
    c = rk[perm_src]
    return (a[0] + b[0] + c[0]).astype(jnp.float32)


@jax.jit
def rank_machinery(codes_rf, sd, live):
    packed = (codes_rf.astype(jnp.int64) << jnp.int64(12)) | sd.astype(jnp.int64)
    packed = jnp.where(live, packed, jnp.int64(1) << jnp.int64(40))
    perm = jnp.argsort(packed, stable=True).astype(jnp.int32)
    sp = packed[perm]
    first = jnp.zeros(CAP, jnp.bool_).at[0].set(True)
    part = sp >> jnp.int64(12)
    segb = first | jnp.concatenate([jnp.zeros(1, jnp.bool_), part[1:] != part[:-1]])
    peerb = first | jnp.concatenate([jnp.zeros(1, jnp.bool_), sp[1:] != sp[:-1]])
    pos = jnp.arange(CAP, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(segb, pos, 0))
    peer_start = jax.lax.cummax(jnp.where(peerb, pos, 0))
    rank = peer_start - seg_start + 1
    return rank[0] + rank[-1]


perm = jnp.argsort(sd)
int(perm[0])

t("stacked 12-pass max+occ (current)", stacked12_max, bucket, rk, live)
t("scatter max+count into 12", scatter12_max, bucket, rk, live)
t("one-hot matmul sum 12", onehot_matmul_max_trick, bucket, rk, live)
t("window pack+argsort", pack_sort_10m, codes_rf, sd, live)
t("gather 3 cols by perm", gather3, perm, codes_rf, sd, rk)
t("full rank machinery", rank_machinery, codes_rf, sd, live)
