"""Prototype: Pallas windowed segmented-sum over SORTED group ids vs the
3-scatter XLA bucket path at 33M rows -> 4M groups (the q3 shape).

Design: after a co-sort by packed key, group ids are MONOTONE, so each
512-row tile touches a contiguous id span <= 512 wide. A one-hot matmul
[2*TILE, TILE] @ [TILE, P] accumulates the tile's payload into a
2-block output window selected by a scalar-prefetched block base —
sequential-grid read-modify-write, no scatters at all.
"""
import os
import time
import functools

import spark_rapids_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1 << 24
SPAN = 1 << 22
TILE = 1024  # 1-D i32 blocks must match XLA's 1024-element tiling
P = 8  # payload lanes (count, d0..d3, pad)

INTERPRET = os.environ.get("SEGSUM_INTERPRET", "0") == "1"


def make_data():
    i = jnp.arange(N, dtype=jnp.uint32)
    h = (i * jnp.uint32(2654435761)) ^ (i >> jnp.uint32(13))
    key = (h % jnp.uint32(SPAN)).astype(jnp.int32)
    h2 = (i * jnp.uint32(0x9E3779B9)) ^ (i >> jnp.uint32(7))
    val = (h2.astype(jnp.float64) / jnp.float64(2**32)) * 1e5
    live = (h ^ h2) % jnp.uint32(3) != 0
    return key, val, live


def _kernel(bases_ref, gid_ref, pay_ref, olo_ref, ohi_ref):
    # Output blocks are NOT loaded from HBM on first visit (their VMEM
    # content is undefined), so the accumulation protocol is: INITIALIZE
    # on the step that first maps a block, ACCUMULATE on consecutive
    # revisits. gid is monotone with <= TILE new groups per tile, so each
    # buffer's block index advances by 0 or 1 — every block is first-
    # visited exactly once and only consecutively revisited.
    t = pl.program_id(0)
    base = bases_ref[t]
    base_row = base * TILE
    g = gid_ref[...].reshape(TILE)          # [TILE] i32 (monotone)
    local = g - base_row                    # in [0, 2*TILE)
    iota = lax.broadcasted_iota(jnp.int32, (2 * TILE, TILE), 0)
    oh = (iota == local[None, :]).astype(jnp.float32)
    acc = jnp.dot(oh, pay_ref[...], preferred_element_type=jnp.float32)
    moved = jnp.logical_or(t == 0, base != bases_ref[jnp.maximum(t - 1, 0)])

    @pl.when(moved)
    def _init_lo():
        olo_ref[...] = acc[:TILE]

    @pl.when(jnp.logical_not(moved))
    def _acc_lo():
        olo_ref[...] += acc[:TILE]

    # the hi window (block base+1) first appears either at t == 0 or on
    # the same step its block index changes — identical condition
    @pl.when(moved)
    def _init_hi():
        ohi_ref[...] = acc[TILE:]

    @pl.when(jnp.logical_not(moved))
    def _acc_hi():
        ohi_ref[...] += acc[TILE:]


@functools.partial(jax.jit, static_argnames=("outcap",))
def segsum_window(gid, payload, outcap: int):
    """gid i32[N] sorted; payload f32[N, P] -> f32[outcap, P] sums."""
    n = gid.shape[0]
    T = n // TILE
    bases = jnp.clip(gid[:: TILE] // TILE, 0, outcap // TILE - 2)
    with jax.enable_x64(False):
        lo, hi = pl.pallas_call(
            _kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(T,),
                in_specs=[
                    pl.BlockSpec((TILE,), lambda t, b: (t,)),
                    pl.BlockSpec((TILE, P), lambda t, b: (t, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((TILE, P), lambda t, b: (b[t], 0)),
                    pl.BlockSpec((TILE, P), lambda t, b: (b[t] + 1, 0)),
                ],
            ),
            out_shape=[jax.ShapeDtypeStruct((outcap, P), jnp.float32)] * 2,
            interpret=INTERPRET,
        )(bases, gid.astype(jnp.int32), payload)
    # each buffer only ever visited its own block range; everything else
    # is VMEM garbage — mask per buffer before combining
    slot_block = (jnp.arange(outcap, dtype=jnp.int32) // TILE)[:, None]
    lo_keep = (slot_block >= bases[0]) & (slot_block <= bases[-1])
    hi_keep = (slot_block >= bases[0] + 1) & (slot_block <= bases[-1] + 1)
    return jnp.where(lo_keep, lo, 0.0) + jnp.where(hi_keep, hi, 0.0)


@jax.jit
def prep(key, val, live):
    """pack -> co-sort -> gid + digit payload planes."""
    packed = jnp.where(live, key, jnp.int32(SPAN + 1))
    clean = jnp.where(live, val, 0.0)
    m = jnp.max(jnp.abs(clean))
    e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
    scale = jnp.exp2(jnp.float64(47.0) - e)
    s = jnp.round(clean * scale)
    # 8-bit balanced digits: |d| <= 2^7 is exact in bf16, so the MXU
    # one-hot matmul runs at full bf16 speed with exact accumulation
    digs = []
    rem = s
    for shift in (40, 32, 24, 16, 8, 0):
        d = jnp.round(rem / np.float64(2.0 ** shift)) if shift else \
            jnp.round(rem)
        if shift:
            rem = rem - d * np.float64(2.0 ** shift)
        digs.append(d.astype(jnp.float32))
    cnt = jnp.where(live, 1.0, 0.0).astype(jnp.float32)
    sk, c0, d0, d1, d2, d3, d4, d5 = lax.sort(
        (packed, cnt, digs[0], digs[1], digs[2], digs[3], digs[4],
         digs[5]), num_keys=1)
    boundary = jnp.concatenate([jnp.ones(1, jnp.bool_), sk[1:] != sk[:-1]])
    gid = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).astype(jnp.int32)
    pay = jnp.stack([c0, d0, d1, d2, d3, d4, d5,
                     jnp.zeros_like(c0)], axis=1)
    # representative key per gid comes from boundary rows (sk at starts)
    return gid, pay, sk, scale


@jax.jit
def finish(acc, scale):
    cnt = acc[:, 0]
    tot = jnp.zeros(acc.shape[0], jnp.float64)
    for i, shift in enumerate((40, 32, 24, 16, 8, 0)):
        tot = tot + acc[:, 1 + i].astype(jnp.float64) \
            * np.float64(2.0 ** shift)
    tot = tot / scale
    return cnt, tot


def t(name, fn, reps=3):
    float(fn())
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn())
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms", flush=True)


def main():
    key, val, live = make_data()
    float(jnp.sum(val))
    OUTCAP = (1 << 22) + 2048  # gid bound: SPAN+2 groups, TILE-aligned

    def full_pallas():
        gid, pay, sk, scale = prep(key, val, live)
        acc = segsum_window(gid, pay, OUTCAP)
        cnt, tot = finish(acc, scale)
        return tot[0] + cnt[1]

    # reference: the current 3-scatter bucket design
    @jax.jit
    def scatter3():
        sb = jnp.where(live, key, jnp.int32(SPAN))
        counts = jax.ops.segment_sum(jnp.ones(N, jnp.int32), sb,
                                     num_segments=SPAN + 1)[:SPAN]
        clean = jnp.where(live, val, 0.0)
        m = jnp.max(jnp.abs(clean))
        e = jnp.ceil(jnp.log2(jnp.maximum(m, 1e-300)))
        scale = jnp.exp2(47.0 - e)
        s = clean * scale
        d0 = jnp.round(s / np.float64(2.0 ** 24))
        d1 = jnp.round(s - d0 * np.float64(2.0 ** 24))
        a0 = jax.ops.segment_sum(d0.astype(jnp.int32), sb,
                                 num_segments=SPAN + 1)[:SPAN]
        a1 = jax.ops.segment_sum(d1.astype(jnp.int32), sb,
                                 num_segments=SPAN + 1)[:SPAN]
        tot = (a0.astype(jnp.float64) * np.float64(2.0 ** 24)
               + a1.astype(jnp.float64)) / scale
        return tot[0] + counts[1].astype(jnp.float64)

    # correctness cross-check on gid-space vs key-space: compare GLOBAL sums
    gid, pay, sk, scale = prep(key, val, live)
    acc = segsum_window(gid, pay, OUTCAP)
    cnt, tot = finish(acc, scale)
    clean_sum = float(jnp.sum(jnp.where(live, val, 0.0)))
    live_n = float(jnp.sum(live.astype(jnp.int32)))
    # the sentinel group is included in gid space; subtract nothing: its
    # digits are zeros (dead rows zeroed), count contributes 0
    got_sum = float(jnp.sum(tot))
    got_cnt = float(jnp.sum(cnt))
    print("sum check:", got_sum, "vs", clean_sum,
          "cnt:", got_cnt, "vs", live_n, flush=True)
    assert abs(got_cnt - live_n) < 0.5, (got_cnt, live_n)
    assert abs(got_sum - clean_sum) < 1e-6 * abs(clean_sum)

    t("pallas sorted-window segsum (end-to-end)", full_pallas)
    t("3-scatter bucket path", scatter3)


if __name__ == "__main__":
    main()
