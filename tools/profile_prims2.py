"""Round 2 of primitive profiling: f64 segmented-sum strategies and
searchsorted alternatives."""
import time
import numpy as np
import spark_rapids_tpu  # noqa: F401
import jax
import jax.numpy as jnp
from jax import lax


def _force(out):
    leaves = jax.tree_util.tree_leaves(out)
    jax.device_get([l[:1] if getattr(l, "ndim", 0) else l for l in leaves])


def bench(name, fn, *args, reps=3):
    _force(fn(*args))
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(fn(*args))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    print(f"{name:55s} {best*1000:10.1f} ms", flush=True)
    return best


def main():
    rng = np.random.default_rng(0)
    N, S = 20_000_000, 3_000_000
    k = jnp.asarray(rng.integers(0, S, N).astype(np.int32))
    ks = jnp.sort(k)
    v = jnp.asarray(rng.uniform(0, 1, N))
    vi64 = (v * 1e9).astype(jnp.int64)
    vi32 = (v * 1e6).astype(jnp.int32)

    seg = jax.jit(lambda vv, kk: jax.ops.segment_sum(vv, kk, num_segments=S))
    segsrt = jax.jit(lambda vv, kk: jax.ops.segment_sum(
        vv, kk, num_segments=S, indices_are_sorted=True))
    bench("segsum f64 20M->3M unsorted", seg, v, k)
    bench("segsum f64 20M->3M sorted-flag", segsrt, v, ks)
    bench("segsum i64 20M->3M unsorted", seg, vi64, k)
    bench("segsum i64 20M->3M sorted-flag", segsrt, vi64, ks)
    bench("segsum i32 20M->3M unsorted", seg, vi32, k)
    bench("cumsum f64 20M", jax.jit(jnp.cumsum), v)
    bench("cumsum i64 20M", jax.jit(jnp.cumsum), vi64)

    # segmented scan (sorted): associative scan with reset flags
    def segscan(vv, kk):
        flag = jnp.concatenate([jnp.ones(1, jnp.bool_), kk[1:] != kk[:-1]])
        def op(a, b):
            av, af = a
            bv, bf = b
            return jnp.where(bf, bv, av + bv), af | bf
        s, _ = lax.associative_scan(op, (vv, flag))
        return s
    bench("assoc segscan f64 20M (sorted)", jax.jit(segscan), v, ks)

    # 2x i64 limb fixed-point: decompose f64 to hi/lo int64 at a global scale
    def limb_sum(vv, kk):
        hi = jnp.floor(vv)
        lo = (vv - hi) * (2.0 ** 32)
        shi = jax.ops.segment_sum(hi.astype(jnp.int64), kk, num_segments=S)
        slo = jax.ops.segment_sum(lo.astype(jnp.int64), kk, num_segments=S)
        return shi.astype(jnp.float64) + slo.astype(jnp.float64) / 2.0 ** 32
    bench("2x i64-limb segsum 20M->3M", jax.jit(limb_sum), v, k)

    # scatter-add f32 pair (value + compensation-free): err estimate only
    v32 = v.astype(jnp.float32)
    bench("segsum f32 20M->3M", seg, v32, k)

    # searchsorted alternatives for expand/gather paths
    srt = jnp.sort(jnp.asarray(rng.integers(0, 10 * S, 1_500_000)).astype(jnp.int64))
    q64 = jnp.asarray(rng.integers(0, 10 * S, N).astype(np.int64))
    bench("searchsorted i64 20M->1.5M (baseline)",
          jax.jit(lambda s, q: jnp.searchsorted(s, q)), srt, q64)
    # batched/blocked variant via sorting the queries first?
    def sorted_probe(s, q):
        qi = jnp.argsort(q)
        r = jnp.searchsorted(s, q[qi], side="left")
        inv = jnp.zeros_like(qi).at[qi].set(jnp.arange(q.shape[0], dtype=qi.dtype))
        return r[inv]
    bench("searchsorted via sorted queries", jax.jit(sorted_probe), srt, q64)

    # merge-based rank: rank of each query among sorted build = searchsorted
    # computed by sorting the union (sort-merge). cost = sort of 21.5M + cumsum
    def merge_rank(s, q):
        ns, nq = s.shape[0], q.shape[0]
        allv = jnp.concatenate([s, q])
        isq = jnp.concatenate([jnp.zeros(ns, jnp.int32), jnp.ones(nq, jnp.int32)])
        idx = jnp.concatenate([jnp.arange(ns, dtype=jnp.int32),
                               jnp.arange(nq, dtype=jnp.int32)])
        # stable sort by (value, isq): build rows sort before equal queries
        o = lax.sort((allv, isq, idx), num_keys=2, is_stable=True)
        sv, sq, si = o
        nbuild_before = jnp.cumsum(1 - sq) * sq  # for query rows: #build <= v
        out = jnp.zeros(nq, nbuild_before.dtype).at[jnp.where(sq == 1, si, nq)].set(
            nbuild_before, mode="drop")
        return out
    bench("merge-rank (sort union) 20M+1.5M", jax.jit(merge_rank), srt, q64)

    # gather i64/f64 from 3M-sized tables (dense join probe shape)
    tbl = jnp.asarray(rng.integers(0, 100, S).astype(np.int64))
    idx3 = jnp.asarray(rng.integers(0, S, N).astype(np.int32))
    bench("gather i64 20M from 3M table", jax.jit(lambda t, i: t[i]), tbl, idx3)
    bench("gather i32 20M from 3M table", jax.jit(lambda t, i: t[i]),
          tbl.astype(jnp.int32), idx3)
    bench("gather f64 20M from 3M table", jax.jit(lambda t, i: t[i]),
          tbl.astype(jnp.float64), idx3)

    # scatter set (compact_indices shape): 20M -> 20M
    dest = jnp.asarray(rng.permutation(N).astype(np.int32))
    bench("scatter-set i32 20M", jax.jit(
        lambda d, s: jnp.zeros(N, jnp.int32).at[d].set(s)), dest, idx3)


if __name__ == "__main__":
    main()
