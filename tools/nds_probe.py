"""NDS (TPC-DS-shaped) probe harness: generate SF-scaled tables, attempt
every one of the 99 queries, emit a per-query scorecard JSON.

Reference parity: integration_tests/ScaleTest.md + the NDS suites the
reference's BASELINE numbers come from. This engine has no SQL parser
(plans arrive via the DataFrame API or the JSON ingestion contract), so
each NDS query needs a hand translation; `QUERIES` maps qN -> builder.
Untranslated queries are reported as "not_translated" — the scorecard
makes the north-star gap measurable every round instead of invisible.

Known toolchain issue: queries grouping by a FLOAT key at sf>=0.1
capacities (q12/q20/q98 group by i_current_price) wedge the remote TPU
compiler in the general sort-aggregation kernel (>10 min, no return) —
the subprocess isolation turns that into an honest "timeout" entry
instead of hanging the scorecard. The same queries pass on the CPU
simulator (tests/test_nds_probe.py).

Per translated query the probe reports:
- status: ok | wrong | error
- device: clean | fallback (any "cannot run on TPU" in explain)
- seconds: wall-clock on the active backend

Usage: python tools/nds_probe.py [--sf 0.01] [--out NDS_SCORECARD.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col, lit


# ---------------------------------------------------------------------------
# TPC-DS-shaped tables (star schema, SF-scaled row counts)
# ---------------------------------------------------------------------------

def gen_tables(sf: float, seed: int = 42):
    rng = np.random.default_rng(seed)
    n_item = max(int(18000 * sf), 100)
    n_store = max(int(12 * max(sf, 1)), 4)
    n_cust = max(int(100000 * sf), 500)
    n_addr = max(n_cust // 2, 250)
    n_ss = max(int(2_880_000 * sf), 5000)
    n_ws = max(n_ss // 2, 2000)
    n_cs = max(n_ss // 2, 2000)
    n_date = 2556  # 7 years of days
    d0 = 2450815  # 1998-01-01 julian-ish seq

    date_dim = pa.table({
        "d_date_sk": np.arange(d0, d0 + n_date, dtype=np.int64),
        "d_year": (1998 + (np.arange(n_date) // 365)).astype(np.int32),
        "d_moy": ((np.arange(n_date) // 30) % 12 + 1).astype(np.int32),
        "d_dom": (np.arange(n_date) % 30 + 1).astype(np.int32),
        "d_qoy": (((np.arange(n_date) // 30) % 12) // 3 + 1).astype(np.int32),
        "d_day_name": np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                                "Thursday", "Friday", "Saturday"])[
            np.arange(n_date) % 7],
    })
    item = pa.table({
        "i_item_sk": np.arange(n_item, dtype=np.int64),
        "i_item_id": np.char.add("AAAAAAAA",
                                 np.arange(n_item).astype(str)),
        "i_brand_id": rng.integers(1, 1000, n_item).astype(np.int32),
        "i_brand": np.char.add("brand#",
                               rng.integers(1, 1000, n_item).astype(str)),
        "i_category_id": rng.integers(1, 10, n_item).astype(np.int32),
        "i_category": np.array(["Books", "Home", "Electronics", "Jewelry",
                                "Music", "Shoes", "Sports", "Toys", "Men",
                                "Women"])[rng.integers(0, 10, n_item)],
        "i_manufact_id": rng.integers(1, 1000, n_item).astype(np.int32),
        "i_current_price": np.round(rng.uniform(0.5, 300, n_item), 2),
        "i_manager_id": rng.integers(1, 100, n_item).astype(np.int32),
    })
    store = pa.table({
        "s_store_sk": np.arange(n_store, dtype=np.int64),
        "s_store_name": np.char.add("store_",
                                    np.arange(n_store).astype(str)),
        "s_number_employees": rng.integers(200, 300, n_store).astype(np.int32),
        "s_city": np.array(["Midway", "Fairview", "Oakland", "Salem"])[
            rng.integers(0, 4, n_store)],
        "s_gmt_offset": np.full(n_store, -5.0),
    })
    customer = pa.table({
        "c_customer_sk": np.arange(n_cust, dtype=np.int64),
        "c_current_addr_sk": rng.integers(0, n_addr, n_cust).astype(np.int64),
        "c_birth_year": rng.integers(1930, 2000, n_cust).astype(np.int32),
        "c_first_name": np.char.add("fn", np.arange(n_cust).astype(str)),
        "c_last_name": np.char.add("ln",
                                   rng.integers(0, 5000, n_cust).astype(str)),
    })
    customer_address = pa.table({
        "ca_address_sk": np.arange(n_addr, dtype=np.int64),
        "ca_city": np.array(["Midway", "Fairview", "Oakland", "Salem",
                             "Centerville"])[rng.integers(0, 5, n_addr)],
        "ca_zip": np.char.zfill(
            rng.integers(10000, 99999, n_addr).astype(str), 5),
        "ca_gmt_offset": np.where(rng.random(n_addr) < 0.8, -5.0, -6.0),
    })
    n_inv = max(n_item * 8, 4000)
    inventory = pa.table({
        "inv_date_sk": rng.integers(d0, d0 + n_date,
                                    n_inv).astype(np.int64),
        "inv_item_sk": rng.integers(0, n_item, n_inv).astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, n_inv).astype(np.int32),
    })

    def sales(n, prefix, extra=()):
        t = {
            f"{prefix}_sold_date_sk": rng.integers(
                d0, d0 + n_date, n).astype(np.int64),
            f"{prefix}_item_sk": rng.integers(0, n_item, n).astype(np.int64),
            f"{prefix}_customer_sk": rng.integers(0, n_cust, n).astype(np.int64),
            f"{prefix}_store_sk" if prefix == "ss" else f"{prefix}_ship_mode_sk":
                rng.integers(0, n_store, n).astype(np.int64),
            f"{prefix}_quantity": rng.integers(1, 100, n).astype(np.int32),
            f"{prefix}_sales_price": np.round(rng.uniform(1, 300, n), 2),
            f"{prefix}_ext_sales_price": np.round(rng.uniform(1, 3000, n), 2),
            f"{prefix}_ext_discount_amt": np.round(rng.uniform(0, 100, n), 2),
            f"{prefix}_net_profit": np.round(rng.uniform(-500, 500, n), 2),
            f"{prefix}_ticket_number" if prefix == "ss" else f"{prefix}_order_number":
                rng.integers(0, n // 4 + 1, n).astype(np.int64),
        }
        if prefix == "ss":
            t["ss_addr_sk"] = rng.integers(0, n_addr, n).astype(np.int64)
        return pa.table(t)

    return {
        "date_dim": date_dim, "item": item, "store": store,
        "customer": customer, "customer_address": customer_address,
        "inventory": inventory,
        "store_sales": sales(n_ss, "ss"),
        "web_sales": sales(n_ws, "ws"),
        "catalog_sales": sales(n_cs, "cs"),
    }


# ---------------------------------------------------------------------------
# Query translations (DataFrame form). Each takes (session, dfs) -> DataFrame.
# ---------------------------------------------------------------------------

def q3(s, d):
    """report: brand revenue for manufacturer in December."""
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manufact_id") == lit(128)) & (col("d_moy") == lit(11)))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
            .order_by(col("d_year").asc(), col("sum_agg").desc(),
                      col("i_brand_id").asc())
            .limit(100))


def q7(s, d):
    return (d["store_sales"]
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .group_by("i_category")
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_sales_price")).alias("agg2"),
                 F.avg(col("ss_ext_sales_price")).alias("agg3"))
            .order_by(col("i_category").asc()).limit(100))


def q19(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["customer"], on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .join(d["customer_address"],
                  on=[(col("c_current_addr_sk"), col("ca_address_sk"))])
            .filter((col("i_manager_id") == lit(8)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(1998)))
            .group_by("i_brand", "i_brand_id", "i_manufact_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def q42(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manager_id") == lit(1)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(2000)))
            .group_by("d_year", "i_category_id", "i_category")
            .agg(F.sum(col("ss_ext_sales_price")).alias("total"))
            .order_by(col("total").desc(), col("d_year").asc(),
                      col("i_category_id").asc(), col("i_category").asc())
            .limit(100))


def q52(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manager_id") == lit(1)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(2000)))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("d_year").asc(), col("ext_price").desc(),
                      col("i_brand_id").asc())
            .limit(100))


def q55(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manager_id") == lit(28)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(1999)))
            .group_by("i_brand", "i_brand_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def q65(s, d):
    ss = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
          .filter(col("d_year") == lit(2000))
          .group_by("ss_store_sk", "ss_item_sk")
          .agg(F.sum(col("ss_sales_price")).alias("revenue")))
    avg_rev = (ss.group_by("ss_store_sk")
               .agg(F.avg(col("revenue")).alias("ave")))
    return (ss.join(avg_rev, on="ss_store_sk")
            .filter(col("revenue") <= lit(0.1) * col("ave"))
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .select(col("s_store_name"), col("i_brand"), col("revenue"))
            .order_by(col("s_store_name").asc(), col("i_brand").asc())
            .limit(100))


def q68(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                    & col("s_city").isin("Midway", "Fairview"))
            .group_by("ss_ticket_number", "ss_customer_sk", "s_city")
            .agg(F.sum(col("ss_ext_sales_price")).alias("extended_price"),
                 F.sum(col("ss_ext_discount_amt")).alias("extended_tax"))
            .join(d["customer"], on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .order_by(col("c_last_name").asc(),
                      col("ss_ticket_number").asc())
            .limit(100))


def q73(s, d):
    freq = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2)))
            .group_by("ss_ticket_number", "ss_customer_sk")
            .agg(F.count(col("ss_item_sk")).alias("cnt"))
            .filter((col("cnt") >= lit(2)) & (col("cnt") <= lit(5))))
    return (freq.join(d["customer"],
                      on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .select(col("c_last_name"), col("c_first_name"),
                    col("ss_ticket_number"), col("cnt"))
            .order_by(col("cnt").desc(), col("c_last_name").asc())
            .limit(100))


def q79(s, d):
    g = (d["store_sales"]
         .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
         .filter((col("d_dom") == lit(1))
                 & (col("s_number_employees") >= lit(200)))
         .group_by("ss_ticket_number", "ss_customer_sk", "s_city")
         .agg(F.sum(col("ss_net_profit")).alias("profit")))
    return (g.join(d["customer"], on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .order_by(col("c_last_name").asc(), col("profit").desc())
            .limit(100))


def q96(s, d):
    return (d["store_sales"]
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter(col("s_number_employees") >= lit(250))
            .agg(F.count(col("ss_ticket_number")).alias("cnt")))


def q98(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter(col("d_year") == lit(1999))
            .group_by("i_item_sk", "i_category", "i_current_price")
            .agg(F.sum(col("ss_ext_sales_price")).alias("itemrevenue")))
    w = Window.partition_by(col("i_category"))
    return (base.select(
        col("i_category"), col("i_current_price"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(w)).alias("revenueratio"))
        .order_by(col("i_category").asc(), col("revenueratio").desc())
        .limit(100))


def q89(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter(col("d_year") == lit(1999))
            .group_by("i_category", "i_brand", "s_store_name", "d_moy")
            .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col("i_category"), col("i_brand"),
                            col("s_store_name"))
    return (base.select(col("i_category"), col("i_brand"),
                        col("s_store_name"), col("d_moy"),
                        col("sum_sales"),
                        F.avg(col("sum_sales")).over(w).alias("avg_monthly"))
            .filter(col("sum_sales") > col("avg_monthly") * lit(1.1))
            .order_by(col("sum_sales").desc()).limit(100))


def q12(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["web_sales"]
            .join(d["date_dim"], on=[(col("ws_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ws_item_sk"), col("i_item_sk"))])
            .filter((col("d_year") == lit(1999)) & (col("d_moy") == lit(2)))
            .group_by("i_item_sk", "i_category", "i_current_price")
            .agg(F.sum(col("ws_ext_sales_price")).alias("itemrevenue")))
    w = Window.partition_by(col("i_category"))
    return (base.select(
        col("i_category"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(w)).alias("revenueratio"))
        .order_by(col("i_category").asc(), col("revenueratio").desc())
        .limit(100))


def q20(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["catalog_sales"]
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
            .filter((col("d_year") == lit(2000)) & (col("d_qoy") == lit(1)))
            .group_by("i_item_sk", "i_category", "i_current_price")
            .agg(F.sum(col("cs_ext_sales_price")).alias("itemrevenue")))
    w = Window.partition_by(col("i_category"))
    return (base.select(
        col("i_category"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(w)).alias("revenueratio"))
        .order_by(col("i_category").asc(), col("revenueratio").desc())
        .limit(100))


def q26(s, d):
    return (d["catalog_sales"]
            .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"), col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .group_by("i_category")
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_sales_price")).alias("agg2"),
                 F.avg(col("cs_ext_sales_price")).alias("agg3"))
            .order_by(col("i_category").asc()).limit(100))


def q43(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter((col("d_year") == lit(2000))
                    & (col("s_gmt_offset") == lit(-5.0)))
            .group_by("s_store_name", "s_store_sk", "d_day_name")
            .agg(F.sum(col("ss_sales_price")).alias("sales"))
            .order_by(col("s_store_name").asc(), col("d_day_name").asc())
            .limit(100))


def q34(s, d):
    freq = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(3))
                    & col("s_city").isin("Midway", "Fairview"))
            .group_by("ss_ticket_number", "ss_customer_sk")
            .agg(F.count(col("ss_item_sk")).alias("cnt"))
            .filter((col("cnt") >= lit(2)) & (col("cnt") <= lit(20))))
    return (freq.join(d["customer"],
                      on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .select(col("c_last_name"), col("c_first_name"),
                    col("ss_ticket_number"), col("cnt"))
            .order_by(col("c_last_name").asc(), col("cnt").desc())
            .limit(1000))


def q46(s, d):
    g = (d["store_sales"]
         .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
         .join(d["customer"], on=[(col("ss_customer_sk"),
                                   col("c_customer_sk"))])
         .join(d["customer_address"],
               on=[(col("c_current_addr_sk"), col("ca_address_sk"))])
         .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                 & col("s_city").isin("Midway", "Fairview"))
         .group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
         .agg(F.sum(col("ss_ext_sales_price")).alias("amt"),
              F.sum(col("ss_net_profit")).alias("profit")))
    return (g.order_by(col("ss_ticket_number").asc(),
                       col("profit").desc())
            .limit(100))


def q97(s, d):
    ssc = (d["store_sales"]
           .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .group_by("ss_customer_sk", "ss_item_sk")
           .agg(F.count(col("ss_quantity")).alias("sc")))
    csc = (d["catalog_sales"]
           .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .group_by("cs_customer_sk", "cs_item_sk")
           .agg(F.count(col("cs_quantity")).alias("cc")))
    j = ssc.join(csc, on=[(col("ss_customer_sk"), col("cs_customer_sk")),
                          (col("ss_item_sk"), col("cs_item_sk"))],
                 how="full")
    return j.agg(
        F.sum(F.when(col("sc").is_not_null() & col("cc").is_null(),
                     lit(1)).otherwise(lit(0))).alias("store_only"),
        F.sum(F.when(col("sc").is_null() & col("cc").is_not_null(),
                     lit(1)).otherwise(lit(0))).alias("catalog_only"),
        F.sum(F.when(col("sc").is_not_null() & col("cc").is_not_null(),
                     lit(1)).otherwise(lit(0))).alias("both"))


def q62(s, d):
    # web_sales shipping-lag buckets by ship mode (ship_mode_sk stands in
    # for the mode dimension in this shaped schema)
    lag = (col("ws_order_number") % lit(120)).alias("lag_days")
    base = d["web_sales"].select(
        col("ws_ship_mode_sk"), (col("ws_order_number") % lit(120))
        .alias("lag_days"))
    return (base.group_by("ws_ship_mode_sk")
            .agg(F.sum(F.when(col("lag_days") <= lit(30), lit(1))
                       .otherwise(lit(0))).alias("d30"),
                 F.sum(F.when((col("lag_days") > lit(30))
                              & (col("lag_days") <= lit(60)), lit(1))
                       .otherwise(lit(0))).alias("d60"),
                 F.sum(F.when(col("lag_days") > lit(60), lit(1))
                       .otherwise(lit(0))).alias("d90"))
            .order_by(col("ws_ship_mode_sk").asc()).limit(100))


def q33(s, d):
    def chan(sales, date_col, item_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .filter((col("d_year") == lit(1998)) & (col("d_moy") == lit(1))
                        & (col("i_category") == lit("Books")))
                .group_by("i_manufact_id")
                .agg(F.sum(col(price_col)).alias("total_sales")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.group_by("i_manufact_id")
            .agg(F.sum(col("total_sales")).alias("total_sales"))
            .order_by(col("total_sales").desc()).limit(100))


def q48(s, d):
    return (d["store_sales"]
            .join(d["customer_address"],
                  on=[(col("ss_addr_sk"), col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_year") == lit(2000))
                    & (col("ca_gmt_offset") == lit(-5.0))
                    & (col("ss_net_profit") >= lit(0.0)))
            .agg(F.sum(col("ss_quantity")).alias("total_quantity")))


def q71(s, d):
    def chan(sales, date_col, item_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .filter((col("d_moy") == lit(11)) & (col("d_year") == lit(1999))
                        & (col("i_manager_id") == lit(1)))
                .select(col("i_brand_id"), col("i_brand"),
                        col(price_col).alias("ext_price")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.group_by("i_brand_id", "i_brand")
            .agg(F.sum(col("ext_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def q76(s, d):
    # channel ids are ints (1=store, 2=web, 3=catalog): unioning distinct
    # per-branch string literals builds dict columns whose vocab union
    # cannot happen inside a traced kernel (engine limitation, documented)
    def chan(sales, date_col, item_col, price_col, cid):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .select(lit(cid).alias("channel"), col("i_category"),
                        col("d_year"), col("d_qoy"),
                        col(price_col).alias("ext_sales_price")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price", 1)
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price", 2))
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price", 3)))
    return (u.group_by("channel", "i_category", "d_year", "d_qoy")
            .agg(F.count(col("ext_sales_price")).alias("sales_cnt"),
                 F.sum(col("ext_sales_price")).alias("sales_amt"))
            .order_by(col("channel").asc(), col("i_category").asc(),
                      col("d_year").asc(), col("d_qoy").asc())
            .limit(100))


def q45(s, d):
    """web sales by customer zip/city for a quarter (zip-prefix list)."""
    return (d["web_sales"]
            .join(d["customer"], on=[(col("ws_customer_sk"),
                                      col("c_customer_sk"))])
            .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                             col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_qoy") == lit(2)) & (col("d_year") == lit(2000))
                    & col("ca_zip").substr(1, 2).isin(
                        "85", "86", "87", "88", "89"))
            .group_by("ca_zip", "ca_city")
            .agg(F.sum(col("ws_sales_price")).alias("total"))
            .order_by(col("ca_zip").asc(), col("ca_city").asc())
            .limit(100))


def q60(s, d):
    """per-item-id September Music sales across the three channels."""
    def chan(sales, date_col, item_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .filter((col("d_year") == lit(1999)) & (col("d_moy") == lit(9))
                        & (col("i_category") == lit("Music")))
                .group_by("i_item_id")
                .agg(F.sum(col(price_col)).alias("total_sales")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.group_by("i_item_id")
            .agg(F.sum(col("total_sales")).alias("total_sales"))
            .order_by(col("i_item_id").asc(),
                      col("total_sales").asc()).limit(100))


def q82(s, d):
    """items in stock (100..500 on hand) in a price band that sold in
    stores: inventory semi-joined against store_sales."""
    eligible = (d["item"]
                .join(d["inventory"], on=[(col("i_item_sk"),
                                           col("inv_item_sk"))])
                .join(d["date_dim"], on=[(col("inv_date_sk"),
                                          col("d_date_sk"))])
                .filter((col("i_current_price") >= lit(30.0))
                        & (col("i_current_price") <= lit(60.0))
                        & (col("inv_quantity_on_hand") >= lit(100))
                        & (col("inv_quantity_on_hand") <= lit(500))
                        & (col("d_year") == lit(2000))))
    sold = eligible.join(d["store_sales"],
                         on=[(col("i_item_sk"), col("ss_item_sk"))],
                         how="left_semi")
    return (sold.select(col("i_item_id"), col("i_current_price"))
            .distinct()
            .order_by(col("i_item_id").asc()).limit(100))


QUERIES = {3: q3, 7: q7, 12: q12, 19: q19, 20: q20, 26: q26, 33: q33,
           34: q34, 42: q42, 43: q43, 45: q45, 46: q46, 48: q48, 52: q52, 55: q55,
           60: q60, 62: q62, 65: q65, 68: q68, 71: q71, 73: q73, 76: q76, 79: q79, 82: q82,
           89: q89, 96: q96, 97: q97, 98: q98}


def _canon_rows(table):
    """Order-insensitive canonical rows with rounded floats, so the
    differential check compares VALUES, not just counts (most NDS
    queries end in limit(100) — counts alone cannot catch a wrong
    aggregate)."""
    rows = []
    for r in table.to_pylist():
        vals = []
        for k in sorted(r):
            v = r[k]
            if isinstance(v, float):
                v = round(v, 6)
            vals.append((k, v))
        rows.append(tuple(vals))
    return sorted(rows, key=repr)


def run_one(sess, dfs, qn: int) -> dict:
    df = QUERIES[qn](sess, dfs)
    explain = df.explain()
    device = "fallback" if "cannot run on TPU" in explain else "clean"
    t0 = time.perf_counter()
    tpu_table = df.collect()
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    df.count()
    dt = time.perf_counter() - t0  # steady state (kernels cached)
    cpu_table = df.collect_cpu()  # full differential vs CPU interpreter
    status = "ok" if _canon_rows(tpu_table) == _canon_rows(cpu_table) \
        else "wrong"
    return {"status": status, "device": device,
            "rows": int(tpu_table.num_rows),
            "seconds": round(dt, 4), "first_run_seconds": round(first, 4)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--out", default="NDS_SCORECARD.json")
    ap.add_argument("--query", type=int, default=0,
                    help="child mode: run ONE query, print its JSON")
    ap.add_argument("--inline", action="store_true",
                    help="run queries in-process (no isolation)")
    args = ap.parse_args()

    if args.query:
        t0 = time.perf_counter()
        sess = TpuSession()
        dfs = {name: sess.create_dataframe(t).cache()
               for name, t in gen_tables(args.sf).items()}
        for _df in dfs.values():
            _df.count()
        setup_s = round(time.perf_counter() - t0, 2)
        try:
            rec = run_one(sess, dfs, args.query)
            rec["setup_seconds"] = setup_s
            print("RESULT " + json.dumps(rec))
        except Exception as e:  # noqa: BLE001
            print("RESULT " + json.dumps(
                {"status": "error", "setup_seconds": setup_s,
                 "error": f"{type(e).__name__}: {e}"}))
        return

    per_query_s = int(os.environ.get("NDS_QUERY_TIMEOUT_S", "420"))
    card = {}
    if args.inline:
        sess = TpuSession()
        dfs = {name: sess.create_dataframe(t).cache()
               for name, t in gen_tables(args.sf).items()}
    for qn in range(1, 100):
        if qn not in QUERIES:
            card[f"q{qn}"] = {"status": "not_translated"}
            continue
        if args.inline:
            try:
                card[f"q{qn}"] = run_one(sess, dfs, qn)
            except Exception as e:  # noqa: BLE001
                card[f"q{qn}"] = {"status": "error",
                                  "error": f"{type(e).__name__}: {e}"}
        else:
            # SUBPROCESS isolation: a wedged remote compile cannot be
            # interrupted by SIGALRM (it blocks in C), so each query gets
            # its own interpreter and a hard kill on timeout (the
            # reference scale-test isolates queries the same way)
            import subprocess
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--sf", str(args.sf), "--query", str(qn)]
            # setup (data gen + cache upload) happens inside the child:
            # give it an sf-scaled allowance on top of the query budget so
            # a slow upload never reads as a query timeout
            setup_allowance = 90 + int(args.sf * 600)
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=per_query_s + setup_allowance)
                line = [l for l in r.stdout.splitlines()
                        if l.startswith("RESULT ")]
                card[f"q{qn}"] = (json.loads(line[-1][7:]) if line else
                                  {"status": "error",
                                   "error": (r.stderr or "no output")[-300:]})
            except subprocess.TimeoutExpired:
                card[f"q{qn}"] = {"status": "timeout",
                                  "seconds_limit": per_query_s}
        print(f"q{qn}: {card[f'q{qn}']}", file=sys.stderr, flush=True)

    translated = [q for q in card.values() if q["status"] != "not_translated"]
    summary = {
        "sf": args.sf,
        "translated": len(translated),
        "ok": sum(1 for q in translated if q["status"] == "ok"),
        "clean_device": sum(1 for q in translated
                            if q.get("device") == "clean"),
        "queries": card,
    }
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({k: summary[k] for k in
                      ("sf", "translated", "ok", "clean_device")}))


if __name__ == "__main__":
    main()
