"""NDS (TPC-DS-shaped) probe harness: generate SF-scaled tables, attempt
every one of the 99 queries, emit a per-query scorecard JSON.

Reference parity: integration_tests/ScaleTest.md + the NDS suites the
reference's BASELINE numbers come from. This engine has no SQL parser
(plans arrive via the DataFrame API or the JSON ingestion contract), so
each NDS query needs a hand translation; `QUERIES` maps qN -> builder.
Untranslated queries are reported as "not_translated" — the scorecard
makes the north-star gap measurable every round instead of invisible.

Known toolchain issue: queries grouping by a FLOAT key at sf>=0.1
capacities (q12/q20/q98 group by i_current_price) wedge the remote TPU
compiler in the general sort-aggregation kernel (>10 min, no return) —
the subprocess isolation turns that into an honest "timeout" entry
instead of hanging the scorecard. The same queries pass on the CPU
simulator (tests/test_nds_probe.py).

Per translated query the probe reports:
- status: ok | wrong | error
- device: clean | fallback (any "cannot run on TPU" in explain)
- seconds: wall-clock on the active backend

Usage: python tools/nds_probe.py [--sf 0.01] [--out NDS_SCORECARD.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import pyarrow as pa

from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.core import col, lit


# ---------------------------------------------------------------------------
# TPC-DS-shaped tables (star schema, SF-scaled row counts)
# ---------------------------------------------------------------------------

def gen_tables(sf: float, seed: int = 42):
    rng = np.random.default_rng(seed)
    n_item = max(int(18000 * sf), 100)
    n_store = max(int(12 * max(sf, 1)), 4)
    n_cust = max(int(100000 * sf), 500)
    n_addr = max(n_cust // 2, 250)
    n_ss = max(int(2_880_000 * sf), 5000)
    n_ws = max(n_ss // 2, 2000)
    n_cs = max(n_ss // 2, 2000)
    n_date = 2556  # 7 years of days
    d0 = 2450815  # 1998-01-01 julian-ish seq

    date_dim = pa.table({
        "d_date_sk": np.arange(d0, d0 + n_date, dtype=np.int64),
        "d_year": (1998 + (np.arange(n_date) // 365)).astype(np.int32),
        "d_moy": ((np.arange(n_date) // 30) % 12 + 1).astype(np.int32),
        "d_dom": (np.arange(n_date) % 30 + 1).astype(np.int32),
        "d_qoy": (((np.arange(n_date) // 30) % 12) // 3 + 1).astype(np.int32),
        "d_day_name": np.array(["Sunday", "Monday", "Tuesday", "Wednesday",
                                "Thursday", "Friday", "Saturday"])[
            np.arange(n_date) % 7],
        "d_week_seq": (np.arange(n_date) // 7).astype(np.int32),
        "d_dow": (np.arange(n_date) % 7).astype(np.int32),
    })
    item = pa.table({
        "i_item_sk": np.arange(n_item, dtype=np.int64),
        "i_item_id": np.char.add("AAAAAAAA",
                                 np.arange(n_item).astype(str)),
        "i_brand_id": rng.integers(1, 1000, n_item).astype(np.int32),
        "i_brand": np.char.add("brand#",
                               rng.integers(1, 1000, n_item).astype(str)),
        "i_category_id": rng.integers(1, 10, n_item).astype(np.int32),
        "i_category": np.array(["Books", "Home", "Electronics", "Jewelry",
                                "Music", "Shoes", "Sports", "Toys", "Men",
                                "Women"])[rng.integers(0, 10, n_item)],
        "i_manufact_id": rng.integers(1, 1000, n_item).astype(np.int32),
        "i_class": np.char.add("class", rng.integers(1, 16,
                                                     n_item).astype(str)),
        "i_current_price": np.round(rng.uniform(0.5, 300, n_item), 2),
        "i_manager_id": rng.integers(1, 100, n_item).astype(np.int32),
    })
    store = pa.table({
        "s_store_sk": np.arange(n_store, dtype=np.int64),
        "s_store_name": np.char.add("store_",
                                    np.arange(n_store).astype(str)),
        "s_number_employees": rng.integers(200, 300, n_store).astype(np.int32),
        "s_city": np.array(["Midway", "Fairview", "Oakland", "Salem"])[
            rng.integers(0, 4, n_store)],
        "s_gmt_offset": np.full(n_store, -5.0),
    })
    customer = pa.table({
        "c_customer_sk": np.arange(n_cust, dtype=np.int64),
        "c_current_addr_sk": rng.integers(0, n_addr, n_cust).astype(np.int64),
        "c_current_cdemo_sk": rng.integers(0, 19208, n_cust).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(0, 7200, n_cust).astype(np.int64),
        "c_birth_year": rng.integers(1930, 2000, n_cust).astype(np.int32),
        "c_first_name": np.char.add("fn", np.arange(n_cust).astype(str)),
        "c_last_name": np.char.add("ln",
                                   rng.integers(0, 5000, n_cust).astype(str)),
    })
    customer_address = pa.table({
        "ca_address_sk": np.arange(n_addr, dtype=np.int64),
        "ca_city": np.array(["Midway", "Fairview", "Oakland", "Salem",
                             "Centerville"])[rng.integers(0, 5, n_addr)],
        "ca_zip": np.char.zfill(
            rng.integers(10000, 99999, n_addr).astype(str), 5),
        "ca_gmt_offset": np.where(rng.random(n_addr) < 0.8, -5.0, -6.0),
        "ca_state": np.array(["CA", "NY", "TX", "WA", "GA", "TN", "SD",
                              "FL"])[rng.integers(0, 8, n_addr)],
    })
    n_inv = max(n_item * 60, 20000)
    inventory = pa.table({
        "inv_date_sk": rng.integers(d0, d0 + n_date,
                                    n_inv).astype(np.int64),
        "inv_item_sk": rng.integers(0, n_item, n_inv).astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, n_inv).astype(np.int32),
        "inv_warehouse_sk": rng.integers(0, 5, n_inv).astype(np.int64),
    })

    def sales(n, prefix, extra=()):
        t = {
            f"{prefix}_sold_date_sk": rng.integers(
                d0, d0 + n_date, n).astype(np.int64),
            f"{prefix}_item_sk": rng.integers(0, n_item, n).astype(np.int64),
            f"{prefix}_customer_sk": rng.integers(0, n_cust, n).astype(np.int64),
            f"{prefix}_store_sk" if prefix == "ss" else f"{prefix}_ship_mode_sk":
                rng.integers(0, n_store, n).astype(np.int64),
            f"{prefix}_quantity": rng.integers(1, 100, n).astype(np.int32),
            f"{prefix}_sales_price": np.round(rng.uniform(1, 300, n), 2),
            f"{prefix}_ext_sales_price": np.round(rng.uniform(1, 3000, n), 2),
            f"{prefix}_ext_discount_amt": np.round(rng.uniform(0, 100, n), 2),
            f"{prefix}_net_profit": np.round(rng.uniform(-500, 500, n), 2),
            f"{prefix}_ticket_number" if prefix == "ss" else f"{prefix}_order_number":
                rng.integers(0, n // 4 + 1, n).astype(np.int64),
        }
        if prefix == "ss":
            t["ss_addr_sk"] = rng.integers(0, n_addr, n).astype(np.int64)
        return pa.table(t)

    store_sales = sales(n_ss, "ss")
    web_sales = sales(n_ws, "ws")
    catalog_sales = sales(n_cs, "cs")

    def returns(sold, prefix, src_prefix, frac=0.1):
        """~frac of sales rows come back as returns (keys subsampled
        from the sales table so joins hit)."""
        n = max(int(sold.num_rows * frac), 200)
        idx = rng.integers(0, sold.num_rows, n)
        t = {
            f"{prefix}_returned_date_sk":
                sold[f"{src_prefix}_sold_date_sk"].to_numpy()[idx]
                + rng.integers(1, 60, n),
            f"{prefix}_item_sk":
                sold[f"{src_prefix}_item_sk"].to_numpy()[idx],
            f"{prefix}_customer_sk":
                sold[f"{src_prefix}_customer_sk"].to_numpy()[idx],
            f"{prefix}_return_amt": np.round(rng.uniform(1, 500, n), 2),
            f"{prefix}_return_quantity":
                rng.integers(1, 20, n).astype(np.int32),
            f"{prefix}_net_loss": np.round(rng.uniform(0, 200, n), 2),
            f"{prefix}_reason_sk": rng.integers(0, 35, n).astype(np.int64),
        }
        order_col = ("ss_ticket_number" if src_prefix == "ss"
                     else f"{src_prefix}_order_number")
        t[f"{prefix}_{'ticket_number' if src_prefix == 'ss' else 'order_number'}"] = \
            sold[order_col].to_numpy()[idx]
        if src_prefix == "ss":
            t["sr_store_sk"] = sold["ss_store_sk"].to_numpy()[idx]
        return pa.table(t)

    n_hd = 7200
    household_demographics = pa.table({
        "hd_demo_sk": np.arange(n_hd, dtype=np.int64),
        "hd_dep_count": rng.integers(0, 10, n_hd).astype(np.int32),
        "hd_vehicle_count": rng.integers(0, 5, n_hd).astype(np.int32),
        "hd_buy_potential": np.array([">10000", "5001-10000", "1001-5000",
                                      "501-1000", "0-500",
                                      "Unknown"])[rng.integers(0, 6, n_hd)],
    })
    n_cd = 1920800 // 100
    customer_demographics = pa.table({
        "cd_demo_sk": np.arange(n_cd, dtype=np.int64),
        "cd_gender": np.array(["M", "F"])[rng.integers(0, 2, n_cd)],
        "cd_marital_status": np.array(["M", "S", "D", "W", "U"])[
            rng.integers(0, 5, n_cd)],
        "cd_education_status": np.array(
            ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"])[
            rng.integers(0, 7, n_cd)],
        "cd_dep_count": rng.integers(0, 10, n_cd).astype(np.int32),
    })
    n_promo = max(int(300 * max(sf, 0.1)), 30)
    promotion = pa.table({
        "p_promo_sk": np.arange(n_promo, dtype=np.int64),
        "p_channel_email": np.array(["Y", "N"])[
            (rng.random(n_promo) < 0.1).astype(int) ^ 1],
        "p_channel_event": np.array(["Y", "N"])[
            (rng.random(n_promo) < 0.5).astype(int) ^ 1],
        "p_channel_tv": np.array(["Y", "N"])[
            (rng.random(n_promo) < 0.5).astype(int) ^ 1],
    })
    n_wh = 5
    warehouse = pa.table({
        "w_warehouse_sk": np.arange(n_wh, dtype=np.int64),
        "w_warehouse_name": np.char.add("warehouse_",
                                        np.arange(n_wh).astype(str)),
        "w_state": np.array(["CA", "NY", "TX", "WA", "GA"])[:n_wh],
    })
    time_dim = pa.table({
        "t_time_sk": np.arange(86400, dtype=np.int64),
        "t_hour": (np.arange(86400) // 3600).astype(np.int32),
        "t_minute": ((np.arange(86400) % 3600) // 60).astype(np.int32),
    })
    reason = pa.table({
        "r_reason_sk": np.arange(35, dtype=np.int64),
        "r_reason_desc": np.char.add("reason ",
                                     np.arange(35).astype(str)),
    })
    # per-row demographic / promo / time / warehouse keys for the facts
    def widen(t, prefix, tick=False):
        n = t.num_rows
        cols = {
            f"{prefix}_hdemo_sk": rng.integers(0, n_hd, n).astype(np.int64),
            f"{prefix}_cdemo_sk": rng.integers(0, n_cd, n).astype(np.int64),
            f"{prefix}_promo_sk": rng.integers(0, n_promo,
                                               n).astype(np.int64),
            f"{prefix}_sold_time_sk": rng.integers(25200, 75600,
                                                   n).astype(np.int64),
            f"{prefix}_wholesale_cost": np.round(rng.uniform(1, 100, n), 2),
            f"{prefix}_list_price": np.round(rng.uniform(1, 300, n), 2),
            f"{prefix}_coupon_amt": np.round(rng.uniform(0, 50, n), 2),
        }
        if prefix != "ss":
            cols[f"{prefix}_warehouse_sk"] = rng.integers(
                0, n_wh, n).astype(np.int64)
            cols[f"{prefix}_ship_date_sk"] = (
                t[f"{prefix}_sold_date_sk"].to_numpy()
                + rng.integers(1, 120, n))
        for name, arr in cols.items():
            t = t.append_column(name, pa.array(arr))
        return t

    store_sales = widen(store_sales, "ss")
    web_sales = widen(web_sales, "ws")
    catalog_sales = widen(catalog_sales, "cs")

    return {
        "date_dim": date_dim, "item": item, "store": store,
        "customer": customer, "customer_address": customer_address,
        "inventory": inventory,
        "store_sales": store_sales,
        "web_sales": web_sales,
        "catalog_sales": catalog_sales,
        "store_returns": returns(store_sales, "sr", "ss"),
        "web_returns": returns(web_sales, "wr", "ws"),
        "catalog_returns": returns(catalog_sales, "cr", "cs"),
        "household_demographics": household_demographics,
        "customer_demographics": customer_demographics,
        "promotion": promotion,
        "warehouse": warehouse,
        "time_dim": time_dim,
        "reason": reason,
    }


# ---------------------------------------------------------------------------
# Query translations (DataFrame form). Each takes (session, dfs) -> DataFrame.
# ---------------------------------------------------------------------------

def q3(s, d):
    """report: brand revenue for manufacturer in December."""
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manufact_id") == lit(128)) & (col("d_moy") == lit(11)))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("sum_agg"))
            .order_by(col("d_year").asc(), col("sum_agg").desc(),
                      col("i_brand_id").asc())
            .limit(100))


def q7(s, d):
    return (d["store_sales"]
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .group_by("i_category")
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_sales_price")).alias("agg2"),
                 F.avg(col("ss_ext_sales_price")).alias("agg3"))
            .order_by(col("i_category").asc()).limit(100))


def q19(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["customer"], on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .join(d["customer_address"],
                  on=[(col("c_current_addr_sk"), col("ca_address_sk"))])
            .filter((col("i_manager_id") == lit(8)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(1998)))
            .group_by("i_brand", "i_brand_id", "i_manufact_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def q42(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manager_id") == lit(1)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(2000)))
            .group_by("d_year", "i_category_id", "i_category")
            .agg(F.sum(col("ss_ext_sales_price")).alias("total"))
            .order_by(col("total").desc(), col("d_year").asc(),
                      col("i_category_id").asc(), col("i_category").asc())
            .limit(100))


def q52(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manager_id") == lit(1)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(2000)))
            .group_by("d_year", "i_brand", "i_brand_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("d_year").asc(), col("ext_price").desc(),
                      col("i_brand_id").asc())
            .limit(100))


def q55(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter((col("i_manager_id") == lit(28)) & (col("d_moy") == lit(11))
                    & (col("d_year") == lit(1999)))
            .group_by("i_brand", "i_brand_id")
            .agg(F.sum(col("ss_ext_sales_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def q65(s, d):
    ss = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
          .filter(col("d_year") == lit(2000))
          .group_by("ss_store_sk", "ss_item_sk")
          .agg(F.sum(col("ss_sales_price")).alias("revenue")))
    avg_rev = (ss.group_by("ss_store_sk")
               .agg(F.avg(col("revenue")).alias("ave")))
    return (ss.join(avg_rev, on="ss_store_sk")
            .filter(col("revenue") <= lit(0.1) * col("ave"))
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .select(col("s_store_name"), col("i_brand"), col("revenue"))
            .order_by(col("s_store_name").asc(), col("i_brand").asc())
            .limit(100))


def q68(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                    & col("s_city").isin("Midway", "Fairview"))
            .group_by("ss_ticket_number", "ss_customer_sk", "s_city")
            .agg(F.sum(col("ss_ext_sales_price")).alias("extended_price"),
                 F.sum(col("ss_ext_discount_amt")).alias("extended_tax"))
            .join(d["customer"], on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .order_by(col("c_last_name").asc(),
                      col("ss_ticket_number").asc())
            .limit(100))


def q73(s, d):
    freq = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2)))
            .group_by("ss_ticket_number", "ss_customer_sk")
            .agg(F.count(col("ss_item_sk")).alias("cnt"))
            .filter((col("cnt") >= lit(2)) & (col("cnt") <= lit(5))))
    return (freq.join(d["customer"],
                      on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .select(col("c_last_name"), col("c_first_name"),
                    col("ss_ticket_number"), col("cnt"))
            .order_by(col("cnt").desc(), col("c_last_name").asc())
            .limit(100))


def q79(s, d):
    g = (d["store_sales"]
         .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
         .filter((col("d_dom") == lit(1))
                 & (col("s_number_employees") >= lit(200)))
         .group_by("ss_ticket_number", "ss_customer_sk", "s_city")
         .agg(F.sum(col("ss_net_profit")).alias("profit")))
    return (g.join(d["customer"], on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .order_by(col("c_last_name").asc(), col("profit").desc())
            .limit(100))


def q96(s, d):
    return (d["store_sales"]
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter(col("s_number_employees") >= lit(250))
            .agg(F.count(col("ss_ticket_number")).alias("cnt")))


def q98(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter(col("d_year") == lit(1999))
            .group_by("i_item_sk", "i_category", "i_current_price")
            .agg(F.sum(col("ss_ext_sales_price")).alias("itemrevenue")))
    w = Window.partition_by(col("i_category"))
    return (base.select(
        col("i_category"), col("i_current_price"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(w)).alias("revenueratio"))
        .order_by(col("i_category").asc(), col("revenueratio").desc())
        .limit(100))


def q89(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter(col("d_year") == lit(1999))
            .group_by("i_category", "i_brand", "s_store_name", "d_moy")
            .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col("i_category"), col("i_brand"),
                            col("s_store_name"))
    return (base.select(col("i_category"), col("i_brand"),
                        col("s_store_name"), col("d_moy"),
                        col("sum_sales"),
                        F.avg(col("sum_sales")).over(w).alias("avg_monthly"))
            .filter(col("sum_sales") > col("avg_monthly") * lit(1.1))
            .order_by(col("sum_sales").desc()).limit(100))


def q12(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["web_sales"]
            .join(d["date_dim"], on=[(col("ws_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("ws_item_sk"), col("i_item_sk"))])
            .filter((col("d_year") == lit(1999)) & (col("d_moy") == lit(2)))
            .group_by("i_item_sk", "i_category", "i_current_price")
            .agg(F.sum(col("ws_ext_sales_price")).alias("itemrevenue")))
    w = Window.partition_by(col("i_category"))
    return (base.select(
        col("i_category"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(w)).alias("revenueratio"))
        .order_by(col("i_category").asc(), col("revenueratio").desc())
        .limit(100))


def q20(s, d):
    from spark_rapids_tpu.expr.window import Window
    base = (d["catalog_sales"]
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"), col("d_date_sk"))])
            .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
            .filter((col("d_year") == lit(2000)) & (col("d_qoy") == lit(1)))
            .group_by("i_item_sk", "i_category", "i_current_price")
            .agg(F.sum(col("cs_ext_sales_price")).alias("itemrevenue")))
    w = Window.partition_by(col("i_category"))
    return (base.select(
        col("i_category"), col("itemrevenue"),
        (col("itemrevenue") * lit(100.0)
         / F.sum(col("itemrevenue")).over(w)).alias("revenueratio"))
        .order_by(col("i_category").asc(), col("revenueratio").desc())
        .limit(100))


def q26(s, d):
    return (d["catalog_sales"]
            .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"), col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .group_by("i_category")
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_sales_price")).alias("agg2"),
                 F.avg(col("cs_ext_sales_price")).alias("agg3"))
            .order_by(col("i_category").asc()).limit(100))


def q43(s, d):
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter((col("d_year") == lit(2000))
                    & (col("s_gmt_offset") == lit(-5.0)))
            .group_by("s_store_name", "s_store_sk", "d_day_name")
            .agg(F.sum(col("ss_sales_price")).alias("sales"))
            .order_by(col("s_store_name").asc(), col("d_day_name").asc())
            .limit(100))


def q34(s, d):
    freq = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(3))
                    & col("s_city").isin("Midway", "Fairview"))
            .group_by("ss_ticket_number", "ss_customer_sk")
            .agg(F.count(col("ss_item_sk")).alias("cnt"))
            .filter((col("cnt") >= lit(2)) & (col("cnt") <= lit(20))))
    return (freq.join(d["customer"],
                      on=[(col("ss_customer_sk"), col("c_customer_sk"))])
            .select(col("c_last_name"), col("c_first_name"),
                    col("ss_ticket_number"), col("cnt"))
            .order_by(col("c_last_name").asc(), col("cnt").desc())
            .limit(1000))


def q46(s, d):
    g = (d["store_sales"]
         .join(d["date_dim"], on=[(col("ss_sold_date_sk"), col("d_date_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
         .join(d["customer"], on=[(col("ss_customer_sk"),
                                   col("c_customer_sk"))])
         .join(d["customer_address"],
               on=[(col("c_current_addr_sk"), col("ca_address_sk"))])
         .filter((col("d_dom") >= lit(1)) & (col("d_dom") <= lit(2))
                 & col("s_city").isin("Midway", "Fairview"))
         .group_by("ss_ticket_number", "ss_customer_sk", "ca_city")
         .agg(F.sum(col("ss_ext_sales_price")).alias("amt"),
              F.sum(col("ss_net_profit")).alias("profit")))
    return (g.order_by(col("ss_ticket_number").asc(),
                       col("profit").desc())
            .limit(100))


def q97(s, d):
    ssc = (d["store_sales"]
           .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .group_by("ss_customer_sk", "ss_item_sk")
           .agg(F.count(col("ss_quantity")).alias("sc")))
    csc = (d["catalog_sales"]
           .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .group_by("cs_customer_sk", "cs_item_sk")
           .agg(F.count(col("cs_quantity")).alias("cc")))
    j = ssc.join(csc, on=[(col("ss_customer_sk"), col("cs_customer_sk")),
                          (col("ss_item_sk"), col("cs_item_sk"))],
                 how="full")
    return j.agg(
        F.sum(F.when(col("sc").is_not_null() & col("cc").is_null(),
                     lit(1)).otherwise(lit(0))).alias("store_only"),
        F.sum(F.when(col("sc").is_null() & col("cc").is_not_null(),
                     lit(1)).otherwise(lit(0))).alias("catalog_only"),
        F.sum(F.when(col("sc").is_not_null() & col("cc").is_not_null(),
                     lit(1)).otherwise(lit(0))).alias("both"))


def q62(s, d):
    # web_sales shipping-lag buckets by ship mode (ship_mode_sk stands in
    # for the mode dimension in this shaped schema)
    lag = (col("ws_order_number") % lit(120)).alias("lag_days")
    base = d["web_sales"].select(
        col("ws_ship_mode_sk"), (col("ws_order_number") % lit(120))
        .alias("lag_days"))
    return (base.group_by("ws_ship_mode_sk")
            .agg(F.sum(F.when(col("lag_days") <= lit(30), lit(1))
                       .otherwise(lit(0))).alias("d30"),
                 F.sum(F.when((col("lag_days") > lit(30))
                              & (col("lag_days") <= lit(60)), lit(1))
                       .otherwise(lit(0))).alias("d60"),
                 F.sum(F.when(col("lag_days") > lit(60), lit(1))
                       .otherwise(lit(0))).alias("d90"))
            .order_by(col("ws_ship_mode_sk").asc()).limit(100))


def q33(s, d):
    def chan(sales, date_col, item_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .filter((col("d_year") == lit(1998)) & (col("d_moy") == lit(1))
                        & (col("i_category") == lit("Books")))
                .group_by("i_manufact_id")
                .agg(F.sum(col(price_col)).alias("total_sales")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.group_by("i_manufact_id")
            .agg(F.sum(col("total_sales")).alias("total_sales"))
            .order_by(col("total_sales").desc()).limit(100))


def q48(s, d):
    return (d["store_sales"]
            .join(d["customer_address"],
                  on=[(col("ss_addr_sk"), col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_year") == lit(2000))
                    & (col("ca_gmt_offset") == lit(-5.0))
                    & (col("ss_net_profit") >= lit(0.0)))
            .agg(F.sum(col("ss_quantity")).alias("total_quantity")))


def q71(s, d):
    def chan(sales, date_col, item_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .filter((col("d_moy") == lit(11)) & (col("d_year") == lit(1999))
                        & (col("i_manager_id") == lit(1)))
                .select(col("i_brand_id"), col("i_brand"),
                        col(price_col).alias("ext_price")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.group_by("i_brand_id", "i_brand")
            .agg(F.sum(col("ext_price")).alias("ext_price"))
            .order_by(col("ext_price").desc(), col("i_brand_id").asc())
            .limit(100))


def q76(s, d):
    # channel ids are ints (1=store, 2=web, 3=catalog): unioning distinct
    # per-branch string literals builds dict columns whose vocab union
    # cannot happen inside a traced kernel (engine limitation, documented)
    def chan(sales, date_col, item_col, price_col, cid):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .select(lit(cid).alias("channel"), col("i_category"),
                        col("d_year"), col("d_qoy"),
                        col(price_col).alias("ext_sales_price")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price", 1)
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price", 2))
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price", 3)))
    return (u.group_by("channel", "i_category", "d_year", "d_qoy")
            .agg(F.count(col("ext_sales_price")).alias("sales_cnt"),
                 F.sum(col("ext_sales_price")).alias("sales_amt"))
            .order_by(col("channel").asc(), col("i_category").asc(),
                      col("d_year").asc(), col("d_qoy").asc())
            .limit(100))


def q45(s, d):
    """web sales by customer zip/city for a quarter (zip-prefix list)."""
    return (d["web_sales"]
            .join(d["customer"], on=[(col("ws_customer_sk"),
                                      col("c_customer_sk"))])
            .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                             col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_qoy") == lit(2)) & (col("d_year") == lit(2000))
                    & col("ca_zip").substr(1, 2).isin(
                        "85", "86", "87", "88", "89"))
            .group_by("ca_zip", "ca_city")
            .agg(F.sum(col("ws_sales_price")).alias("total"))
            .order_by(col("ca_zip").asc(), col("ca_city").asc())
            .limit(100))


def q60(s, d):
    """per-item-id September Music sales across the three channels."""
    def chan(sales, date_col, item_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .filter((col("d_year") == lit(1999)) & (col("d_moy") == lit(9))
                        & (col("i_category") == lit("Music")))
                .group_by("i_item_id")
                .agg(F.sum(col(price_col)).alias("total_sales")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_ext_sales_price")))
    return (u.group_by("i_item_id")
            .agg(F.sum(col("total_sales")).alias("total_sales"))
            .order_by(col("i_item_id").asc(),
                      col("total_sales").asc()).limit(100))


def q82(s, d):
    """items in stock (100..500 on hand) in a price band that sold in
    stores: inventory semi-joined against store_sales."""
    eligible = (d["item"]
                .join(d["inventory"], on=[(col("i_item_sk"),
                                           col("inv_item_sk"))])
                .join(d["date_dim"], on=[(col("inv_date_sk"),
                                          col("d_date_sk"))])
                .filter((col("i_current_price") >= lit(30.0))
                        & (col("i_current_price") <= lit(60.0))
                        & (col("inv_quantity_on_hand") >= lit(100))
                        & (col("inv_quantity_on_hand") <= lit(500))
                        & (col("d_year") == lit(2000))))
    sold = eligible.join(d["store_sales"],
                         on=[(col("i_item_sk"), col("ss_item_sk"))],
                         how="left_semi")
    return (sold.select(col("i_item_id"), col("i_current_price"))
            .distinct()
            .order_by(col("i_item_id").asc()).limit(100))


def q1(s, d):
    """customers returning more than 1.2x their store's average (the
    correlated scalar subquery, decorrelated into a per-store avg join
    — Spark's own DecorrelateInnerQuery shape)."""
    ctr = (d["store_returns"]
           .join(d["date_dim"], on=[(col("sr_returned_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .group_by("sr_customer_sk", "sr_store_sk")
           .agg(F.sum(col("sr_return_amt")).alias("ctr_total_return")))
    avg = (ctr.group_by("sr_store_sk")
           .agg(F.avg(col("ctr_total_return")).alias("avg_ret")))
    return (ctr.join(avg, on="sr_store_sk")
            .filter(col("ctr_total_return") > col("avg_ret") * lit(1.2))
            .join(d["customer"], on=[(col("sr_customer_sk"),
                                      col("c_customer_sk"))])
            .select(col("c_first_name"), col("c_last_name"),
                    col("ctr_total_return"))
            .order_by(col("c_last_name").asc(), col("c_first_name").asc(),
                      col("ctr_total_return").asc())
            .limit(100))


def q5(s, d):
    """channel sales/returns/profit ROLLUP report."""
    def leg(df, date_col, chan, id_col, sales_col, profit_col):
        return (df.join(d["date_dim"], on=[(col(date_col),
                                            col("d_date_sk"))])
                .filter(col("d_year") == lit(2000))
                .select(lit(chan).alias("channel"),
                        col(id_col).alias("id"),
                        col(sales_col).alias("sales"),
                        lit(0.0).alias("returns_amt"),
                        col(profit_col).alias("profit")))

    def ret_leg(df, date_col, chan, id_col, amt_col, loss_col):
        return (df.join(d["date_dim"], on=[(col(date_col),
                                            col("d_date_sk"))])
                .filter(col("d_year") == lit(2000))
                .select(lit(chan).alias("channel"),
                        col(id_col).alias("id"),
                        lit(0.0).alias("sales"),
                        col(amt_col).alias("returns_amt"),
                        (lit(0.0) - col(loss_col)).alias("profit")))

    u = (leg(d["store_sales"], "ss_sold_date_sk", "store channel",
             "ss_store_sk", "ss_ext_sales_price", "ss_net_profit")
         .union(ret_leg(d["store_returns"], "sr_returned_date_sk",
                        "store channel", "sr_store_sk",
                        "sr_return_amt", "sr_net_loss"))
         .union(leg(d["catalog_sales"], "cs_sold_date_sk",
                    "catalog channel", "cs_warehouse_sk",
                    "cs_ext_sales_price", "cs_net_profit"))
         .union(leg(d["web_sales"], "ws_sold_date_sk", "web channel",
                    "ws_warehouse_sk", "ws_ext_sales_price",
                    "ws_net_profit")))
    return (u.rollup("channel", "id")
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns_amt")).alias("returns_amt"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel").asc(), col("id").asc())
            .limit(100))


def q6(s, d):
    """cities whose customers buy items priced 1.2x over the category
    average (correlated scalar decorrelated to a category-avg join)."""
    cat_avg = (d["item"].group_by("i_category_id")
               .agg(F.avg(col("i_current_price")).alias("cat_avg")))
    hot = (d["item"].join(cat_avg, on="i_category_id")
           .filter(col("i_current_price") > lit(1.2) * col("cat_avg")))
    return (d["store_sales"]
            .join(hot, on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["customer"], on=[(col("ss_customer_sk"),
                                      col("c_customer_sk"))])
            .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                             col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_year") == lit(2001)) & (col("d_moy") == lit(1)))
            .group_by("ca_city").agg(F.count("*").alias("cnt"))
            .filter(col("cnt") >= lit(10))
            .order_by(col("cnt").asc(), col("ca_city").asc()).limit(100))


def q8(s, d):
    """store sales for stores whose customers live in preferred zips:
    an INTERSECT of a zip list with customer-dense zips."""
    zip_list = (d["customer_address"]
                .filter(col("ca_zip").substr(1, 1).isin("1", "2", "3"))
                .select(col("ca_zip")))
    dense = (d["customer"]
             .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                              col("ca_address_sk"))])
             .group_by("ca_zip").agg(F.count("*").alias("cnt"))
             .filter(col("cnt") > lit(2)).select(col("ca_zip")))
    zips = zip_list.intersect(dense)
    cust = (d["customer"]
            .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                             col("ca_address_sk"))])
            .join(zips, on="ca_zip", how="left_semi"))
    return (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_qoy") == lit(2)) & (col("d_year") == lit(1998)))
            .join(cust, on=[(col("ss_customer_sk"), col("c_customer_sk"))],
                  how="left_semi")
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .group_by("s_store_name")
            .agg(F.sum(col("ss_net_profit")).alias("net_profit"))
            .order_by(col("s_store_name").asc()).limit(100))


def q9(s, d):
    """five quantity-bucket statistics in one pass (the reference plans
    the CASE WHEN scalar subqueries; one conditional-agg pass is the
    columnar equivalent)."""
    aggs = []
    for i, (lo, hi) in enumerate([(1, 20), (21, 40), (41, 60), (61, 80),
                                  (81, 100)], 1):
        cond = (col("ss_quantity") >= lit(lo)) & \
            (col("ss_quantity") <= lit(hi))
        aggs.append(F.count(F.when(cond, lit(1)))
                    .alias(f"cnt{i}"))
        aggs.append(F.avg(F.when(cond, col("ss_ext_discount_amt")))
                    .alias(f"avg_disc{i}"))
        aggs.append(F.avg(F.when(cond, col("ss_net_profit")))
                    .alias(f"avg_profit{i}"))
    return d["store_sales"].agg(*aggs)


def q10(s, d):
    """demographics of city customers active in stores AND (web OR
    catalog) — the EXISTS pair lowered to semi joins."""
    c = (d["customer"]
         .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                          col("ca_address_sk"))])
         .filter(col("ca_city").isin("Midway", "Fairview")))
    ss = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(2000))
                  & (col("d_qoy") <= lit(2))))
    c = c.join(ss, on=[(col("c_customer_sk"), col("ss_customer_sk"))],
               how="left_semi")
    other = (d["web_sales"].select(col("ws_customer_sk").alias("k"))
             .union(d["catalog_sales"]
                    .select(col("cs_customer_sk").alias("k"))))
    c = c.join(other, on=[(col("c_customer_sk"), col("k"))],
               how="left_semi")
    return (c.join(d["customer_demographics"],
                   on=[(col("c_current_cdemo_sk"), col("cd_demo_sk"))])
            .group_by("cd_gender", "cd_marital_status",
                      "cd_education_status")
            .agg(F.count("*").alias("cnt"))
            .order_by(col("cd_gender").asc(), col("cd_marital_status").asc(),
                      col("cd_education_status").asc())
            .limit(100))


def q13(s, d):
    """store sales averages under OR'd demographic/address branches."""
    return (d["store_sales"]
            .join(d["customer_demographics"],
                  on=[(col("ss_cdemo_sk"), col("cd_demo_sk"))])
            .join(d["household_demographics"],
                  on=[(col("ss_hdemo_sk"), col("hd_demo_sk"))])
            .join(d["customer_address"], on=[(col("ss_addr_sk"),
                                             col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(2001))
            .filter(((col("cd_marital_status") == lit("M"))
                     & (col("cd_education_status") == lit("College"))
                     & (col("ss_sales_price") >= lit(100.0)))
                    | ((col("cd_marital_status") == lit("S"))
                       & (col("ss_sales_price") <= lit(150.0)))
                    | (col("ca_state").isin("CA", "NY", "TX")
                       & (col("hd_dep_count") >= lit(3))))
            .agg(F.avg(col("ss_quantity")).alias("avg_qty"),
                 F.avg(col("ss_ext_sales_price")).alias("avg_price"),
                 F.avg(col("ss_ext_discount_amt")).alias("avg_disc"),
                 F.sum(col("ss_net_profit")).alias("sum_profit")))


def q15(s, d):
    """catalog sales by customer zip for a quarter (zip/state gate)."""
    return (d["catalog_sales"]
            .join(d["customer"], on=[(col("cs_customer_sk"),
                                      col("c_customer_sk"))])
            .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                             col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_qoy") == lit(1)) & (col("d_year") == lit(2001)))
            .filter(col("ca_zip").substr(1, 2).isin("85", "86", "87",
                                                    "88", "89")
                    | col("ca_state").isin("CA", "WA", "GA")
                    | (col("cs_sales_price") > lit(250.0)))
            .group_by("ca_zip")
            .agg(F.sum(col("cs_sales_price")).alias("total"))
            .order_by(col("ca_zip").asc()).limit(100))


def q16(s, d):
    """catalog orders shipped from more than one warehouse with no
    return: the EXISTS/NOT EXISTS pair as group-derived semi + anti
    joins."""
    cs = (d["catalog_sales"]
          .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(2000))
                  & col("d_moy").isin(3, 4)))
    multi_wh = (cs.group_by("cs_order_number")
                .agg(F.min(col("cs_warehouse_sk")).alias("wmin"),
                     F.max(col("cs_warehouse_sk")).alias("wmax"))
                .filter(col("wmin") < col("wmax"))
                .select(col("cs_order_number").alias("o")))
    kept = (cs.join(multi_wh, on=[(col("cs_order_number"), col("o"))],
                    how="left_semi")
            .join(d["catalog_returns"]
                  .select(col("cr_order_number").alias("r")),
                  on=[(col("cs_order_number"), col("r"))],
                  how="left_anti"))
    orders = kept.select(col("cs_order_number")).distinct() \
        .agg(F.count(col("cs_order_number")).alias("order_count"))
    totals = kept.agg(
        F.sum(col("cs_ext_sales_price")).alias("total_shipping_cost"),
        F.sum(col("cs_net_profit")).alias("total_net_profit"))
    return orders.join(totals, on=None, how="cross")


def q17(s, d):
    """items bought in store, returned, re-bought via catalog: the
    three-fact join with mean/stddev stats."""
    j = (d["store_sales"]
         .join(d["store_returns"],
               on=[(col("ss_ticket_number"), col("sr_ticket_number")),
                   (col("ss_item_sk"), col("sr_item_sk"))])
         .join(d["catalog_sales"],
               on=[(col("sr_customer_sk"), col("cs_customer_sk")),
                   (col("sr_item_sk"), col("cs_item_sk"))])
         .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))]))
    return (j.group_by("i_item_id", "s_city")
            .agg(F.count(col("ss_quantity")).alias("store_sales_cnt"),
                 F.avg(col("ss_quantity")).alias("store_sales_mean"),
                 F.stddev(col("ss_quantity")).alias("store_sales_stdev"),
                 F.avg(col("sr_return_quantity")).alias("return_mean"),
                 F.avg(col("cs_quantity")).alias("catalog_mean"))
            .order_by(col("i_item_id").asc(), col("s_city").asc())
            .limit(100))


def q18(s, d):
    """catalog averages by demographic over a ROLLUP hierarchy."""
    return (d["catalog_sales"]
            .join(d["customer_demographics"],
                  on=[(col("cs_cdemo_sk"), col("cd_demo_sk"))])
            .filter((col("cd_gender") == lit("F"))
                    & (col("cd_education_status") == lit("College")))
            .join(d["customer"], on=[(col("cs_customer_sk"),
                                      col("c_customer_sk"))])
            .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                             col("ca_address_sk"))])
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(1998))
            .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
            .rollup("i_item_id", "ca_state", "ca_city")
            .agg(F.avg(col("cs_quantity")).alias("agg1"),
                 F.avg(col("cs_list_price")).alias("agg2"),
                 F.avg(col("cs_coupon_amt")).alias("agg3"),
                 F.avg(col("cs_net_profit")).alias("agg4"))
            .order_by(col("i_item_id").asc(), col("ca_state").asc(),
                      col("ca_city").asc())
            .limit(100))


def q21(s, d):
    """warehouse inventory balance around a pivot date."""
    pivot = lit(2450815 + 730)
    j = (d["inventory"]
         .join(d["warehouse"], on=[(col("inv_warehouse_sk"),
                                    col("w_warehouse_sk"))])
         .join(d["item"], on=[(col("inv_item_sk"), col("i_item_sk"))])
         .join(d["date_dim"], on=[(col("inv_date_sk"), col("d_date_sk"))])
         .filter((col("i_current_price") >= lit(0.99))
                 & (col("i_current_price") <= lit(200.0))))
    g = (j.group_by("w_warehouse_name", "i_item_id")
         .agg(F.sum(F.when(col("d_date_sk") < pivot,
                           col("inv_quantity_on_hand"))
                    .otherwise(lit(0))).alias("inv_before"),
              F.sum(F.when(col("d_date_sk") >= pivot,
                           col("inv_quantity_on_hand"))
                    .otherwise(lit(0))).alias("inv_after")))
    return (g.filter((col("inv_before") > lit(0))
                     & (col("inv_after") * lit(1.0)
                        / col("inv_before") >= lit(0.5))
                     & (col("inv_after") * lit(1.0)
                        / col("inv_before") <= lit(2.0)))
            .order_by(col("w_warehouse_name").asc(), col("i_item_id").asc())
            .limit(100))


def q22(s, d):
    """inventory quantity-on-hand averages over a ROLLUP hierarchy."""
    return (d["inventory"]
            .join(d["date_dim"], on=[(col("inv_date_sk"),
                                      col("d_date_sk"))])
            .join(d["item"], on=[(col("inv_item_sk"), col("i_item_sk"))])
            .filter((col("d_year") >= lit(1999))
                    & (col("d_year") <= lit(2000)))
            .rollup("i_category", "i_brand", "i_class")
            .agg(F.avg(col("inv_quantity_on_hand")).alias("qoh"))
            .order_by(col("qoh").asc(), col("i_category").asc(),
                      col("i_brand").asc(), col("i_class").asc())
            .limit(100))


def q25(s, d):
    """q17-shaped three-fact join aggregating net profit/loss."""
    j = (d["store_sales"]
         .join(d["store_returns"],
               on=[(col("ss_ticket_number"), col("sr_ticket_number")),
                   (col("ss_item_sk"), col("sr_item_sk"))])
         .join(d["catalog_sales"],
               on=[(col("sr_customer_sk"), col("cs_customer_sk")),
                   (col("sr_item_sk"), col("cs_item_sk"))])
         .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))]))
    return (j.group_by("i_item_id", "s_store_name")
            .agg(F.max(col("ss_net_profit")).alias("store_sales_profit"),
                 F.max(col("sr_net_loss")).alias("store_returns_loss"),
                 F.max(col("cs_net_profit")).alias("catalog_sales_profit"))
            .order_by(col("i_item_id").asc(), col("s_store_name").asc())
            .limit(100))


def q27(s, d):
    """store sales averages by demographic over ROLLUP(i_item_id,
    s_city) with grouping()."""
    return (d["store_sales"]
            .join(d["customer_demographics"],
                  on=[(col("ss_cdemo_sk"), col("cd_demo_sk"))])
            .filter((col("cd_gender") == lit("M"))
                    & (col("cd_marital_status") == lit("S")))
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(2002))
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .rollup("i_item_id", "s_city")
            .agg(F.avg(col("ss_quantity")).alias("agg1"),
                 F.avg(col("ss_list_price")).alias("agg2"),
                 F.avg(col("ss_coupon_amt")).alias("agg3"),
                 F.avg(col("ss_sales_price")).alias("agg4"),
                 F.grouping(col("s_city")).alias("g_city"))
            .order_by(col("i_item_id").asc(), col("s_city").asc())
            .limit(100))


def q28(s, d):
    """six list-price-bucket stats in one conditional-agg pass."""
    aggs = []
    for i, (lo, hi) in enumerate([(0, 50), (51, 100), (101, 150),
                                  (151, 200), (201, 250), (251, 300)], 1):
        cond = (col("ss_list_price") >= lit(float(lo))) & \
            (col("ss_list_price") <= lit(float(hi)))
        aggs.append(F.avg(F.when(cond, col("ss_list_price")))
                    .alias(f"b{i}_lp"))
        aggs.append(F.count(F.when(cond, col("ss_list_price")))
                    .alias(f"b{i}_cnt"))
    return d["store_sales"].agg(*aggs)


def q29(s, d):
    """q17-shaped join with quantity sums by month windows."""
    j = (d["store_sales"]
         .join(d["store_returns"],
               on=[(col("ss_ticket_number"), col("sr_ticket_number")),
                   (col("ss_item_sk"), col("sr_item_sk"))])
         .join(d["catalog_sales"],
               on=[(col("sr_customer_sk"), col("cs_customer_sk")),
                   (col("sr_item_sk"), col("cs_item_sk"))])
         .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))]))
    return (j.group_by("i_item_id", "i_item_id", "s_store_name")
            .agg(F.sum(col("ss_quantity")).alias("store_sales_quantity"),
                 F.sum(col("sr_return_quantity")).alias("return_quantity"),
                 F.sum(col("cs_quantity")).alias("catalog_quantity"))
            .order_by(col("i_item_id").asc(), col("s_store_name").asc())
            .limit(100))


def q30(s, d):
    """web customers returning over 1.2x their state's average
    (decorrelated per-state avg join)."""
    ctr = (d["web_returns"]
           .join(d["date_dim"], on=[(col("wr_returned_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .join(d["customer"], on=[(col("wr_customer_sk"),
                                     col("c_customer_sk"))])
           .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                            col("ca_address_sk"))])
           .group_by("wr_customer_sk", "ca_state")
           .agg(F.sum(col("wr_return_amt")).alias("ctr_total_return")))
    avg = (ctr.group_by("ca_state")
           .agg(F.avg(col("ctr_total_return")).alias("avg_ret")))
    return (ctr.join(avg, on="ca_state")
            .filter(col("ctr_total_return") > col("avg_ret") * lit(1.2))
            .join(d["customer"], on=[(col("wr_customer_sk"),
                                      col("c_customer_sk"))])
            .select(col("c_first_name"), col("c_last_name"),
                    col("ca_state"), col("ctr_total_return"))
            .order_by(col("c_last_name").asc(), col("c_first_name").asc(),
                      col("ctr_total_return").asc())
            .limit(100))


def q32(s, d):
    """catalog sales with discount over 1.3x the item's average
    (decorrelated per-item avg join)."""
    window = (d["catalog_sales"]
              .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                        col("d_date_sk"))])
              .filter(col("d_year") == lit(2000)))
    item_avg = (window.group_by("cs_item_sk")
                .agg(F.avg(col("cs_ext_discount_amt")).alias("avg_disc")))
    return (window
            .join(item_avg.select(col("cs_item_sk").alias("k"),
                                  col("avg_disc")),
                  on=[(col("cs_item_sk"), col("k"))])
            .filter(col("cs_ext_discount_amt")
                    > col("avg_disc") * lit(1.3))
            .agg(F.sum(col("cs_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q35(s, d):
    """q10-shaped: store buyers also active on web or catalog, grouped
    by demographics with count/avg/max stats."""
    c = d["customer"]
    ss = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(1999))
                  & (col("d_qoy") < lit(4))))
    c = c.join(ss, on=[(col("c_customer_sk"), col("ss_customer_sk"))],
               how="left_semi")
    other = (d["web_sales"].select(col("ws_customer_sk").alias("k"))
             .union(d["catalog_sales"]
                    .select(col("cs_customer_sk").alias("k"))))
    c = c.join(other, on=[(col("c_customer_sk"), col("k"))],
               how="left_semi")
    return (c.join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                              col("ca_address_sk"))])
            .join(d["customer_demographics"],
                  on=[(col("c_current_cdemo_sk"), col("cd_demo_sk"))])
            .group_by("ca_state", "cd_gender", "cd_marital_status",
                      "cd_dep_count")
            .agg(F.count("*").alias("cnt"),
                 F.avg(col("cd_dep_count")).alias("avg_dep"),
                 F.max(col("cd_dep_count")).alias("max_dep"),
                 F.sum(col("cd_dep_count")).alias("sum_dep"))
            .order_by(col("ca_state").asc(), col("cd_gender").asc(),
                      col("cd_marital_status").asc(),
                      col("cd_dep_count").asc())
            .limit(100))


def q36(s, d):
    """gross-margin ROLLUP(i_category, i_class) ranked within each
    grouping level."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(2001))
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .rollup("i_category", "i_class")
            .agg(F.sum(col("ss_net_profit")).alias("profit"),
                 F.sum(col("ss_ext_sales_price")).alias("sales"),
                 F.grouping(col("i_category")).alias("g_cat"),
                 F.grouping(col("i_class")).alias("g_cls")))
    w = Window.partition_by(col("lochierarchy")) \
        .order_by(col("margin").asc())
    return (base.select(col("i_category"), col("i_class"),
                        (col("g_cat") + col("g_cls")).alias("lochierarchy"),
                        (col("profit") / col("sales")).alias("margin"))
            .select(col("i_category"), col("i_class"),
                    col("lochierarchy"), col("margin"),
                    F.rank().over(w).alias("rank_within_parent"))
            .order_by(col("lochierarchy").desc(), col("i_category").asc(),
                      col("rank_within_parent").asc())
            .limit(100))


def q37(s, d):
    """q82 for the catalog channel."""
    eligible = (d["item"]
                .join(d["inventory"], on=[(col("i_item_sk"),
                                           col("inv_item_sk"))])
                .join(d["date_dim"], on=[(col("inv_date_sk"),
                                          col("d_date_sk"))])
                .filter((col("i_current_price") >= lit(20.0))
                        & (col("i_current_price") <= lit(50.0))
                        & (col("inv_quantity_on_hand") >= lit(100))
                        & (col("inv_quantity_on_hand") <= lit(500))
                        & (col("d_year") == lit(2001))))
    sold = eligible.join(d["catalog_sales"],
                         on=[(col("i_item_sk"), col("cs_item_sk"))],
                         how="left_semi")
    return (sold.select(col("i_item_id"), col("i_current_price"))
            .distinct()
            .order_by(col("i_item_id").asc()).limit(100))


def q38(s, d):
    """customers active in ALL three channels in one year: a 3-way
    INTERSECT then count."""
    def chan(sales, date_col, cust_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter(col("d_year") == lit(2000))
                .join(d["customer"], on=[(col(cust_col),
                                          col("c_customer_sk"))])
                .select(col("c_first_name"), col("c_last_name")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
         .intersect(chan("catalog_sales", "cs_sold_date_sk",
                         "cs_customer_sk"))
         .intersect(chan("web_sales", "ws_sold_date_sk",
                         "ws_customer_sk")))
    return u.agg(F.count("*").alias("cnt"))


def q39(s, d):
    """inventory coefficient-of-variation pairs for consecutive months."""
    base = (d["inventory"]
            .join(d["date_dim"], on=[(col("inv_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .group_by("inv_warehouse_sk", "inv_item_sk", "d_moy")
            .agg(F.avg(col("inv_quantity_on_hand")).alias("mean"),
                 F.stddev(col("inv_quantity_on_hand")).alias("stdev")))
    cov = (base.filter((col("mean") > lit(0.0))
                       & (col("stdev") / col("mean") > lit(0.4)))
           .select(col("inv_warehouse_sk"), col("inv_item_sk"),
                   col("d_moy"), (col("stdev") / col("mean")).alias("cov")))
    m1 = cov.select(col("inv_warehouse_sk").alias("w1"),
                    col("inv_item_sk").alias("i1"),
                    col("d_moy").alias("m1"), col("cov").alias("cov1"))
    m2 = cov.select(col("inv_warehouse_sk").alias("w2"),
                    col("inv_item_sk").alias("i2"),
                    col("d_moy").alias("m2"), col("cov").alias("cov2"))
    return (m1.join(m2, on=[(col("w1"), col("w2")),
                            (col("i1"), col("i2"))])
            .filter(col("m2") == col("m1") + lit(1))
            .order_by(col("w1").asc(), col("i1").asc(), col("m1").asc(),
                      col("cov2").asc())
            .limit(100))


def q40(s, d):
    """catalog sales value before/after a pivot date by warehouse state,
    return-adjusted via a left join on catalog_returns."""
    pivot = lit(2450815 + 730)
    cr = d["catalog_returns"].select(
        col("cr_order_number").alias("r_ord"),
        col("cr_item_sk").alias("r_item"),
        col("cr_return_amt"))
    j = (d["catalog_sales"]
         .join(cr, on=[(col("cs_order_number"), col("r_ord")),
                       (col("cs_item_sk"), col("r_item"))], how="left")
         .join(d["warehouse"], on=[(col("cs_warehouse_sk"),
                                    col("w_warehouse_sk"))])
         .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
         .filter((col("i_current_price") >= lit(0.99))
                 & (col("i_current_price") <= lit(200.0)))
         .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                   col("d_date_sk"))]))
    net = (col("cs_sales_price")
           - F.coalesce(col("cr_return_amt"), lit(0.0)))
    return (j.group_by("w_state", "i_item_id")
            .agg(F.sum(F.when(col("d_date_sk") < pivot, net)
                       .otherwise(lit(0.0))).alias("sales_before"),
                 F.sum(F.when(col("d_date_sk") >= pivot, net)
                       .otherwise(lit(0.0))).alias("sales_after"))
            .order_by(col("w_state").asc(), col("i_item_id").asc())
            .limit(100))


def q44(s, d):
    """best and worst performing items by store average net profit."""
    from spark_rapids_tpu.expr.window import Window
    perf = (d["store_sales"]
            .group_by("ss_item_sk")
            .agg(F.avg(col("ss_net_profit")).alias("rank_col")))
    w_best = Window.partition_by(lit(1)).order_by(col("rank_col").desc())
    w_worst = Window.partition_by(lit(1)).order_by(col("rank_col").asc())
    ranked = perf.select(col("ss_item_sk"), col("rank_col"),
                         F.rank().over(w_best).alias("rnk_best"),
                         F.rank().over(w_worst).alias("rnk_worst"))
    best = (ranked.filter(col("rnk_best") <= lit(10))
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .select(col("rnk_best").alias("rnk"),
                    col("i_item_id").alias("best_performing")))
    worst = (ranked.filter(col("rnk_worst") <= lit(10))
             .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
             .select(col("rnk_worst").alias("rnk"),
                     col("i_item_id").alias("worst_performing")))
    return (best.join(worst, on="rnk")
            .order_by(col("rnk").asc()).limit(100))


def q47(s, d):
    """monthly brand/store sales vs their yearly average, with the
    previous and next month alongside (lag/lead windows)."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .filter(col("d_year") == lit(1999))
            .group_by("i_category", "i_brand", "s_store_name", "d_year",
                      "d_moy")
            .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w_avg = Window.partition_by(col("i_category"), col("i_brand"),
                                col("s_store_name"), col("d_year"))
    w_seq = Window.partition_by(col("i_category"), col("i_brand"),
                                col("s_store_name")) \
        .order_by(col("d_year"), col("d_moy"))
    out = base.select(
        col("i_category"), col("i_brand"), col("s_store_name"),
        col("d_year"), col("d_moy"), col("sum_sales"),
        F.avg(col("sum_sales")).over(w_avg).alias("avg_monthly_sales"),
        F.lag(col("sum_sales")).over(w_seq).alias("psum"),
        F.lead(col("sum_sales")).over(w_seq).alias("nsum"))
    return (out.filter((col("avg_monthly_sales") > lit(0.0))
                       & ((col("sum_sales") - col("avg_monthly_sales"))
                          / col("avg_monthly_sales") > lit(0.1)))
            .order_by(col("sum_sales").desc(), col("s_store_name").asc(),
                      col("d_moy").asc())
            .limit(100))


def q50(s, d):
    """days-to-return buckets per store."""
    j = (d["store_sales"]
         .join(d["store_returns"],
               on=[(col("ss_ticket_number"), col("sr_ticket_number")),
                   (col("ss_item_sk"), col("sr_item_sk"))])
         .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))]))
    lag_days = col("sr_returned_date_sk") - col("ss_sold_date_sk")
    return (j.group_by("s_store_name", "s_city")
            .agg(F.sum(F.when(lag_days <= lit(30), lit(1))
                       .otherwise(lit(0))).alias("d30"),
                 F.sum(F.when((lag_days > lit(30))
                              & (lag_days <= lit(60)), lit(1))
                       .otherwise(lit(0))).alias("d31_60"),
                 F.sum(F.when(lag_days > lit(60), lit(1))
                       .otherwise(lit(0))).alias("d60plus"))
            .order_by(col("s_store_name").asc(), col("s_city").asc())
            .limit(100))


def q51(s, d):
    """cumulative web vs store revenue crossover by item over time."""
    from spark_rapids_tpu.expr.window import Window
    ws = (d["web_sales"]
          .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter(col("d_year") == lit(2000))
          .group_by("ws_item_sk", "d_week_seq")
          .agg(F.sum(col("ws_sales_price")).alias("sales")))
    ss = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter(col("d_year") == lit(2000))
          .group_by("ss_item_sk", "d_week_seq")
          .agg(F.sum(col("ss_sales_price")).alias("sales")))
    wsr = ws.select(col("ws_item_sk").alias("item_sk"),
                    col("d_week_seq").alias("wk"),
                    col("sales").alias("web_sales"))
    ssr = ss.select(col("ss_item_sk").alias("s_item_sk"),
                    col("d_week_seq").alias("s_wk"),
                    col("sales").alias("store_sales_v"))
    j = wsr.join(ssr, on=[(col("item_sk"), col("s_item_sk")),
                          (col("wk"), col("s_wk"))])
    w = Window.partition_by(col("item_sk")).order_by(col("wk")) \
        .rows_between(Window.unboundedPreceding, Window.currentRow)
    out = j.select(col("item_sk"), col("wk"),
                   F.sum(col("web_sales")).over(w).alias("cume_web"),
                   F.sum(col("store_sales_v")).over(w).alias("cume_store"))
    return (out.filter(col("cume_web") > col("cume_store"))
            .order_by(col("item_sk").asc(), col("wk").asc())
            .limit(100))


def q53(s, d):
    """quarterly manufacturer sales vs their average (q89 shape by
    manufacturer)."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter(col("d_year") == lit(2000))
            .group_by("i_manufact_id", "d_qoy")
            .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col("i_manufact_id"))
    out = base.select(col("i_manufact_id"), col("d_qoy"),
                      col("sum_sales"),
                      F.avg(col("sum_sales")).over(w)
                      .alias("avg_quarterly_sales"))
    return (out.filter((col("avg_quarterly_sales") > lit(0.0))
                       & ((col("sum_sales") - col("avg_quarterly_sales"))
                          / col("avg_quarterly_sales") > lit(0.1)))
            .order_by(col("avg_quarterly_sales").asc(),
                      col("sum_sales").asc(), col("i_manufact_id").asc())
            .limit(100))


def q56(s, d):
    """q60 shape gated by address gmt offset."""
    def chan(sales, date_col, item_col, cust_col, price_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .join(d["customer"], on=[(col(cust_col),
                                          col("c_customer_sk"))])
                .join(d["customer_address"],
                      on=[(col("c_current_addr_sk"),
                           col("ca_address_sk"))])
                .filter((col("d_year") == lit(2000))
                        & (col("d_moy") == lit(2))
                        & (col("ca_gmt_offset") == lit(-5.0))
                        & (col("i_category") == lit("Music")))
                .group_by("i_item_id")
                .agg(F.sum(col(price_col)).alias("total_sales")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_customer_sk", "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_customer_sk", "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_customer_sk", "ws_ext_sales_price")))
    return (u.group_by("i_item_id")
            .agg(F.sum(col("total_sales")).alias("total_sales"))
            .order_by(col("total_sales").asc(), col("i_item_id").asc())
            .limit(100))


def q58(s, d):
    """items whose revenue is within 10% across all three channels."""
    def chan(sales, date_col, item_col, price_col, out):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter(col("d_year") == lit(2000))
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .group_by("i_item_id")
                .agg(F.sum(col(price_col)).alias(out)))
    ss = chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_ext_sales_price", "ss_item_rev")
    cs = (chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
               "cs_ext_sales_price", "cs_item_rev")
          .with_column_renamed("i_item_id", "c_item_id"))
    ws = (chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
               "ws_ext_sales_price", "ws_item_rev")
          .with_column_renamed("i_item_id", "w_item_id"))
    j = (ss.join(cs, on=[(col("i_item_id"), col("c_item_id"))])
         .join(ws, on=[(col("i_item_id"), col("w_item_id"))]))
    avg3 = ((col("ss_item_rev") + col("cs_item_rev") + col("ws_item_rev"))
            / lit(3.0))
    band = lambda c: (c >= avg3 * lit(0.7)) & (c <= avg3 * lit(1.3))  # noqa: E731
    return (j.filter(band(col("ss_item_rev")) & band(col("cs_item_rev"))
                     & band(col("ws_item_rev")))
            .select(col("i_item_id"), col("ss_item_rev"),
                    col("cs_item_rev"), col("ws_item_rev"),
                    avg3.alias("average"))
            .order_by(col("i_item_id").asc(), col("ss_item_rev").asc())
            .limit(100))


def q59(s, d):
    """weekly store sales year-over-year by day of week."""
    wk = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                    col("d_date_sk"))])
          .group_by("d_week_seq", "ss_store_sk")
          .agg(*[F.sum(F.when(col("d_day_name") == lit(day),
                              col("ss_sales_price"))
                       .otherwise(lit(0.0))).alias(day.lower() + "_sales")
                 for day in ["Sunday", "Monday", "Wednesday", "Friday"]]))
    y1 = wk.filter((col("d_week_seq") >= lit(104))
                   & (col("d_week_seq") < lit(156)))
    y2 = (wk.filter((col("d_week_seq") >= lit(156))
                    & (col("d_week_seq") < lit(208)))
          .select(col("d_week_seq").alias("wk2"),
                  col("ss_store_sk").alias("st2"),
                  *[col(day + "_sales").alias(day + "2")
                    for day in ["sunday", "monday", "wednesday",
                                "friday"]]))
    j = y1.join(y2, on=[(col("d_week_seq") + lit(52), col("wk2")),
                        (col("ss_store_sk"), col("st2"))])
    return (j.select(
        col("ss_store_sk"), col("d_week_seq"),
        *[(col(day + "_sales") / col(day + "2")).alias(day + "_ratio")
          for day in ["sunday", "monday", "wednesday", "friday"]])
        .order_by(col("ss_store_sk").asc(), col("d_week_seq").asc())
        .limit(100))


def q63(s, d):
    """q53 by manager."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .filter(col("d_year") == lit(2001))
            .group_by("i_manager_id", "d_moy")
            .agg(F.sum(col("ss_sales_price")).alias("sum_sales")))
    w = Window.partition_by(col("i_manager_id"))
    out = base.select(col("i_manager_id"), col("d_moy"), col("sum_sales"),
                      F.avg(col("sum_sales")).over(w)
                      .alias("avg_monthly_sales"))
    return (out.filter((col("avg_monthly_sales") > lit(0.0))
                       & ((col("sum_sales") - col("avg_monthly_sales"))
                          / col("avg_monthly_sales") > lit(0.1)))
            .order_by(col("i_manager_id").asc(),
                      col("avg_monthly_sales").asc(),
                      col("sum_sales").asc())
            .limit(100))


def q66(s, d):
    """warehouse shipping by month, web + catalog united, with
    time-of-day gates."""
    def chan(sales, date_col, time_col, wh_col, price_col, qty_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .join(d["time_dim"], on=[(col(time_col),
                                          col("t_time_sk"))])
                .filter((col("d_year") == lit(2000))
                        & (col("t_hour") >= lit(8))
                        & (col("t_hour") <= lit(16)))
                .join(d["warehouse"], on=[(col(wh_col),
                                           col("w_warehouse_sk"))])
                .group_by("w_warehouse_name", "w_state", "d_moy")
                .agg(F.sum(col(price_col)).alias("sales"),
                     F.sum(col(qty_col)).alias("qty")))
    u = (chan("web_sales", "ws_sold_date_sk", "ws_sold_time_sk",
              "ws_warehouse_sk", "ws_ext_sales_price", "ws_quantity")
         .union(chan("catalog_sales", "cs_sold_date_sk",
                     "cs_sold_time_sk", "cs_warehouse_sk",
                     "cs_ext_sales_price", "cs_quantity")))
    return (u.group_by("w_warehouse_name", "w_state", "d_moy")
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("qty")).alias("qty"))
            .order_by(col("w_warehouse_name").asc(), col("d_moy").asc())
            .limit(100))


def q69(s, d):
    """demographics of store buyers NOT active on web or catalog (the
    NOT EXISTS pair as anti joins)."""
    c = d["customer"]
    ss = (d["store_sales"]
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(2001))
                  & (col("d_qoy") <= lit(2))))
    c = c.join(ss, on=[(col("c_customer_sk"), col("ss_customer_sk"))],
               how="left_semi")
    ws = (d["web_sales"]
          .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(2001))
                  & (col("d_qoy") <= lit(2)))
          .select(col("ws_customer_sk").alias("k")))
    cs = (d["catalog_sales"]
          .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(2001))
                  & (col("d_qoy") <= lit(2)))
          .select(col("cs_customer_sk").alias("k")))
    c = (c.join(ws, on=[(col("c_customer_sk"), col("k"))],
                how="left_anti")
         .join(cs, on=[(col("c_customer_sk"), col("k"))],
               how="left_anti"))
    return (c.join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                              col("ca_address_sk"))])
            .filter(col("ca_state").isin("CA", "TX", "NY"))
            .join(d["customer_demographics"],
                  on=[(col("c_current_cdemo_sk"), col("cd_demo_sk"))])
            .group_by("cd_gender", "cd_marital_status",
                      "cd_education_status")
            .agg(F.count("*").alias("cnt"))
            .order_by(col("cd_gender").asc(),
                      col("cd_marital_status").asc(),
                      col("cd_education_status").asc())
            .limit(100))


def q2(s, d):
    """web+catalog weekly sales ratios year over year by day name."""
    u = (d["web_sales"].select(col("ws_sold_date_sk").alias("sold"),
                               col("ws_ext_sales_price").alias("price"))
         .union(d["catalog_sales"]
                .select(col("cs_sold_date_sk").alias("sold"),
                        col("cs_ext_sales_price").alias("price"))))
    wk = (u.join(d["date_dim"], on=[(col("sold"), col("d_date_sk"))])
          .group_by("d_week_seq")
          .agg(*[F.sum(F.when(col("d_day_name") == lit(day), col("price"))
                       .otherwise(lit(0.0))).alias(day.lower())
                 for day in ["Sunday", "Monday", "Tuesday", "Wednesday",
                             "Thursday", "Friday", "Saturday"]]))
    y1 = wk.filter((col("d_week_seq") >= lit(104))
                   & (col("d_week_seq") < lit(156)))
    y2 = wk.select(col("d_week_seq").alias("wk2"),
                   *[col(day).alias(day + "2")
                     for day in ["sunday", "monday", "tuesday",
                                 "wednesday", "thursday", "friday",
                                 "saturday"]])
    j = y1.join(y2, on=[(col("d_week_seq") + lit(52), col("wk2"))])
    return (j.select(col("d_week_seq"),
                     *[(col(day) / col(day + "2")).alias("r_" + day)
                       for day in ["sunday", "monday", "tuesday",
                                   "wednesday", "thursday", "friday",
                                   "saturday"]])
            .order_by(col("d_week_seq").asc()).limit(100))


def q23(s, d):
    """best customers buying frequent items: two IN-subquery semi
    joins feeding a global sum."""
    freq_items = (d["store_sales"]
                  .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                            col("d_date_sk"))])
                  .filter(col("d_year").isin(2000, 2001))
                  .group_by("ss_item_sk")
                  .agg(F.count("*").alias("cnt"))
                  .filter(col("cnt") > lit(4))
                  .select(col("ss_item_sk").alias("fi")))
    spend = (d["store_sales"]
             .group_by("ss_customer_sk")
             .agg(F.sum(col("ss_sales_price") * col("ss_quantity"))
                  .alias("spend")))
    thresh = float(spend.agg(F.max(col("spend")).alias("m"))
                   .collect().to_pylist()[0]["m"]) * 0.5
    best = (spend.filter(col("spend") > lit(thresh))
            .select(col("ss_customer_sk").alias("bc")))
    return (d["catalog_sales"]
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_year") == lit(2000)) & (col("d_moy") == lit(2)))
            .join(freq_items, on=[(col("cs_item_sk"), col("fi"))],
                  how="left_semi")
            .join(best, on=[(col("cs_customer_sk"), col("bc"))],
                  how="left_semi")
            .agg(F.sum(col("cs_quantity") * col("cs_sales_price"))
                 .alias("total")))


def q31(s, d):
    """store vs web quarterly sales growth by city."""
    def chan(sales, date_col, cust_col, price_col, name):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter((col("d_year") == lit(2000))
                        & col("d_qoy").isin(1, 2))
                .join(d["customer"], on=[(col(cust_col),
                                          col("c_customer_sk"))])
                .join(d["customer_address"],
                      on=[(col("c_current_addr_sk"),
                           col("ca_address_sk"))])
                .group_by("ca_city")
                .agg(F.sum(F.when(col("d_qoy") == lit(1), col(price_col))
                           .otherwise(lit(0.0))).alias(name + "1"),
                     F.sum(F.when(col("d_qoy") == lit(2), col(price_col))
                           .otherwise(lit(0.0))).alias(name + "2")))
    ss = chan("store_sales", "ss_sold_date_sk", "ss_customer_sk",
              "ss_ext_sales_price", "ss")
    ws = (chan("web_sales", "ws_sold_date_sk", "ws_customer_sk",
               "ws_ext_sales_price", "ws")
          .with_column_renamed("ca_city", "w_city"))
    j = ss.join(ws, on=[(col("ca_city"), col("w_city"))])
    return (j.filter((col("ss1") > lit(0.0)) & (col("ws1") > lit(0.0)))
            .select(col("ca_city"),
                    (col("ws2") / col("ws1")).alias("web_growth"),
                    (col("ss2") / col("ss1")).alias("store_growth"))
            .filter(col("web_growth") > col("store_growth"))
            .order_by(col("ca_city").asc()).limit(100))


def q41(s, d):
    """distinct items from manufacturers with several distinct classes
    (grouped IN-subquery shape)."""
    manuf = (d["item"]
             .group_by("i_category_id")
             .agg(F.count(col("i_class")).alias("item_cnt"))
             .filter(col("item_cnt") > lit(2))
             .select(col("i_category_id").alias("m")))
    return (d["item"]
            .filter((col("i_current_price") >= lit(50.0))
                    & (col("i_current_price") <= lit(100.0)))
            .join(manuf, on=[(col("i_category_id"), col("m"))],
                  how="left_semi")
            .select(col("i_item_id")).distinct()
            .order_by(col("i_item_id").asc()).limit(100))


def q49(s, d):
    """worst return ratios per channel, rank-windowed."""
    from spark_rapids_tpu.expr.window import Window

    def chan(name, sales, ret, s_item, s_ord, s_qty, r_item, r_ord,
             r_qty):
        r = d[ret].select(col(r_item).alias("ri"), col(r_ord).alias("ro"),
                          col(r_qty).alias("rq"))
        j = (d[sales]
             .join(r, on=[(col(s_item), col("ri")),
                          (col(s_ord), col("ro"))], how="left")
             .group_by(s_item)
             .agg(F.sum(F.coalesce(col("rq"), lit(0))).alias("ret_q"),
                  F.sum(col(s_qty)).alias("sold_q"))
             .filter(col("sold_q") > lit(0)))
        ratio = (col("ret_q") * lit(1.0)) / col("sold_q")
        w = Window.partition_by(lit(1)).order_by(col("ratio").desc())
        return (j.select(lit(name).alias("channel"),
                         col(s_item).alias("item"),
                         ratio.alias("ratio"))
                .select(col("channel"), col("item"), col("ratio"),
                        F.rank().over(w).alias("rnk"))
                .filter(col("rnk") <= lit(10)))
    u = (chan("web", "web_sales", "web_returns", "ws_item_sk",
              "ws_order_number", "ws_quantity", "wr_item_sk",
              "wr_order_number", "wr_return_quantity")
         .union(chan("catalog", "catalog_sales", "catalog_returns",
                     "cs_item_sk", "cs_order_number", "cs_quantity",
                     "cr_item_sk", "cr_order_number",
                     "cr_return_quantity"))
         .union(chan("store", "store_sales", "store_returns",
                     "ss_item_sk", "ss_ticket_number", "ss_quantity",
                     "sr_item_sk", "sr_ticket_number",
                     "sr_return_quantity")))
    return u.order_by(col("channel").asc(), col("rnk").asc(),
                      col("item").asc()).limit(100)


def q57(s, d):
    """q47 for the catalog channel by warehouse."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["catalog_sales"]
            .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                      col("d_date_sk"))])
            .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))])
            .join(d["warehouse"], on=[(col("cs_warehouse_sk"),
                                       col("w_warehouse_sk"))])
            .filter(col("d_year") == lit(1999))
            .group_by("i_category", "i_brand", "w_warehouse_name",
                      "d_year", "d_moy")
            .agg(F.sum(col("cs_sales_price")).alias("sum_sales")))
    w_avg = Window.partition_by(col("i_category"), col("i_brand"),
                                col("w_warehouse_name"), col("d_year"))
    w_seq = Window.partition_by(col("i_category"), col("i_brand"),
                                col("w_warehouse_name")) \
        .order_by(col("d_year"), col("d_moy"))
    out = base.select(
        col("i_category"), col("i_brand"), col("w_warehouse_name"),
        col("d_year"), col("d_moy"), col("sum_sales"),
        F.avg(col("sum_sales")).over(w_avg).alias("avg_monthly_sales"),
        F.lag(col("sum_sales")).over(w_seq).alias("psum"),
        F.lead(col("sum_sales")).over(w_seq).alias("nsum"))
    return (out.filter((col("avg_monthly_sales") > lit(0.0))
                       & ((col("sum_sales") - col("avg_monthly_sales"))
                          / col("avg_monthly_sales") > lit(0.1)))
            .order_by(col("sum_sales").desc(),
                      col("w_warehouse_name").asc(), col("d_moy").asc())
            .limit(100))


def q61(s, d):
    """promotional vs total store sales ratio (two single-row aggs
    cross-joined)."""
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_year") == lit(1998))
                    & (col("d_moy") == lit(11))))
    promo = (base.join(d["promotion"], on=[(col("ss_promo_sk"),
                                            col("p_promo_sk"))])
             .filter((col("p_channel_email") == lit("Y"))
                     | (col("p_channel_event") == lit("Y")))
             .agg(F.sum(col("ss_ext_sales_price")).alias("promotions")))
    total = base.agg(F.sum(col("ss_ext_sales_price")).alias("total"))
    return (promo.join(total, on=None, how="cross")
            .select(col("promotions"), col("total"),
                    (col("promotions") / col("total") * lit(100.0))
                    .alias("ratio")))


def q67(s, d):
    """store sales ROLLUP over the full item/time hierarchy, top-ranked
    per category."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .join(d["item"], on=[(col("ss_item_sk"), col("i_item_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .rollup("i_category", "i_class", "i_brand", "d_qoy",
                    "s_store_name")
            .agg(F.sum(col("ss_sales_price") * col("ss_quantity"))
                 .alias("sumsales")))
    w = Window.partition_by(col("i_category")) \
        .order_by(col("sumsales").desc())
    return (base.select(col("i_category"), col("i_class"), col("i_brand"),
                        col("d_qoy"), col("s_store_name"),
                        col("sumsales"))
            .select(col("i_category"), col("i_class"), col("i_brand"),
                    col("d_qoy"), col("s_store_name"), col("sumsales"),
                    F.rank().over(w).alias("rk"))
            .filter(col("rk") <= lit(10))
            .order_by(col("i_category").asc(), col("rk").asc(),
                      col("sumsales").desc(), col("i_class").asc(),
                      col("i_brand").asc(), col("d_qoy").asc(),
                      col("s_store_name").asc())
            .limit(100))


def q70(s, d):
    """store profit ROLLUP(s_city, s_store_name) ranked within each
    grouping level (q36 shape for stores)."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["store_sales"]
            .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(1999))
            .join(d["store"], on=[(col("ss_store_sk"), col("s_store_sk"))])
            .rollup("s_city", "s_store_name")
            .agg(F.sum(col("ss_net_profit")).alias("total_sum"),
                 F.grouping(col("s_city")).alias("g_city"),
                 F.grouping(col("s_store_name")).alias("g_store")))
    w = Window.partition_by(col("lochierarchy")) \
        .order_by(col("total_sum").desc())
    return (base.select(col("s_city"), col("s_store_name"),
                        col("total_sum"),
                        (col("g_city") + col("g_store"))
                        .alias("lochierarchy"))
            .select(col("s_city"), col("s_store_name"), col("total_sum"),
                    col("lochierarchy"),
                    F.rank().over(w).alias("rank_within_parent"))
            .order_by(col("lochierarchy").desc(),
                      col("rank_within_parent").asc(),
                      col("s_city").asc())
            .limit(100))


def q72(s, d):
    """catalog orders where inventory on hand is short of the ordered
    quantity, by item and week."""
    j = (d["catalog_sales"]
         .join(d["inventory"], on=[(col("cs_item_sk"),
                                    col("inv_item_sk"))])
         .filter(col("inv_quantity_on_hand") < col("cs_quantity"))
         .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                   col("d_date_sk"))])
         .filter(col("d_year") == lit(2000))
         .join(d["item"], on=[(col("cs_item_sk"), col("i_item_sk"))]))
    return (j.group_by("i_item_id", "d_week_seq")
            .agg(F.count("*").alias("no_promo"))
            .order_by(col("no_promo").desc(), col("i_item_id").asc(),
                      col("d_week_seq").asc())
            .limit(100))


def q75(s, d):
    """brand sales quantity/amount year-over-year decline across the
    three channels."""
    def chan(sales, date_col, item_col, qty, price):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter(col("d_year").isin(1999, 2000))
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .select(col("d_year"), col("i_brand_id"),
                        col(qty).alias("qty"), col(price).alias("amt")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_item_sk",
              "ss_quantity", "ss_ext_sales_price")
         .union(chan("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                     "cs_quantity", "cs_ext_sales_price"))
         .union(chan("web_sales", "ws_sold_date_sk", "ws_item_sk",
                     "ws_quantity", "ws_ext_sales_price")))
    g = (u.group_by("d_year", "i_brand_id")
         .agg(F.sum(col("qty")).alias("qty"), F.sum(col("amt")).alias("amt")))
    prev = g.filter(col("d_year") == lit(1999)).select(
        col("i_brand_id").alias("pb"), col("qty").alias("pqty"),
        col("amt").alias("pamt"))
    curr = g.filter(col("d_year") == lit(2000))
    j = curr.join(prev, on=[(col("i_brand_id"), col("pb"))])
    return (j.filter(col("qty") < col("pqty"))
            .select(col("i_brand_id"), col("pqty"), col("pamt"),
                    col("qty"), col("amt"),
                    (col("qty") - col("pqty")).alias("qty_diff"))
            .order_by(col("qty_diff").asc(), col("i_brand_id").asc())
            .limit(100))


def q77(s, d):
    """q5-shaped channel profit/returns ROLLUP(channel, id) over 30
    days."""
    def sales_leg(df, date_col, chan, id_col, price, profit):
        return (df.join(d["date_dim"], on=[(col(date_col),
                                            col("d_date_sk"))])
                .filter((col("d_year") == lit(2000))
                        & (col("d_moy") == lit(8)))
                .group_by(id_col)
                .agg(F.sum(col(price)).alias("sales"),
                     F.sum(col(profit)).alias("profit"))
                .select(lit(chan).alias("channel"),
                        col(id_col).alias("id"), col("sales"),
                        lit(0.0).alias("returns_amt"), col("profit")))

    def ret_leg(df, date_col, chan, id_col, amt, loss):
        g = (df.join(d["date_dim"], on=[(col(date_col),
                                         col("d_date_sk"))])
             .filter((col("d_year") == lit(2000))
                     & (col("d_moy") == lit(8))))
        return (g.group_by(id_col)
                .agg(F.sum(col(amt)).alias("returns_amt"),
                     F.sum(col(loss)).alias("loss"))
                .select(lit(chan).alias("channel"),
                        col(id_col).alias("id"), lit(0.0).alias("sales"),
                        col("returns_amt"),
                        (lit(0.0) - col("loss")).alias("profit")))
    u = (sales_leg(d["store_sales"], "ss_sold_date_sk", "store",
                   "ss_store_sk", "ss_ext_sales_price", "ss_net_profit")
         .union(ret_leg(d["store_returns"], "sr_returned_date_sk",
                        "store", "sr_store_sk", "sr_return_amt",
                        "sr_net_loss"))
         .union(sales_leg(d["catalog_sales"], "cs_sold_date_sk",
                          "catalog", "cs_warehouse_sk",
                          "cs_ext_sales_price", "cs_net_profit"))
         .union(sales_leg(d["web_sales"], "ws_sold_date_sk", "web",
                          "ws_warehouse_sk", "ws_ext_sales_price",
                          "ws_net_profit")))
    return (u.rollup("channel", "id")
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns_amt")).alias("returns_amt"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel").asc(), col("id").asc())
            .limit(100))


def q78(s, d):
    """store vs web yearly item/customer sales EXCLUDING returned
    tickets (anti joins on the returns tables)."""
    sr = d["store_returns"].select(col("sr_ticket_number").alias("rt"),
                                   col("sr_item_sk").alias("ri"))
    ss = (d["store_sales"]
          .join(sr, on=[(col("ss_ticket_number"), col("rt")),
                        (col("ss_item_sk"), col("ri"))], how="left_anti")
          .join(d["date_dim"], on=[(col("ss_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter(col("d_year") == lit(2000))
          .group_by("ss_item_sk", "ss_customer_sk")
          .agg(F.sum(col("ss_quantity")).alias("ss_qty"),
               F.sum(col("ss_sales_price")).alias("ss_amt")))
    wr = d["web_returns"].select(col("wr_order_number").alias("rt"),
                                 col("wr_item_sk").alias("ri"))
    ws = (d["web_sales"]
          .join(wr, on=[(col("ws_order_number"), col("rt")),
                        (col("ws_item_sk"), col("ri"))], how="left_anti")
          .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter(col("d_year") == lit(2000))
          .group_by("ws_item_sk", "ws_customer_sk")
          .agg(F.sum(col("ws_quantity")).alias("ws_qty"),
               F.sum(col("ws_sales_price")).alias("ws_amt")))
    j = ss.join(ws, on=[(col("ss_item_sk"), col("ws_item_sk")),
                        (col("ss_customer_sk"), col("ws_customer_sk"))])
    return (j.filter(col("ws_qty") > lit(0))
            .select(col("ss_item_sk"), col("ss_customer_sk"),
                    col("ss_qty"), col("ss_amt"), col("ws_qty"),
                    (col("ss_qty") * lit(1.0)
                     / col("ws_qty")).alias("ratio"))
            .order_by(col("ratio").desc(), col("ss_item_sk").asc(),
                      col("ss_customer_sk").asc())
            .limit(100))


def q81(s, d):
    """q30 for catalog returns."""
    ctr = (d["catalog_returns"]
           .join(d["date_dim"], on=[(col("cr_returned_date_sk"),
                                     col("d_date_sk"))])
           .filter(col("d_year") == lit(2000))
           .join(d["customer"], on=[(col("cr_customer_sk"),
                                     col("c_customer_sk"))])
           .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                            col("ca_address_sk"))])
           .group_by("cr_customer_sk", "ca_state")
           .agg(F.sum(col("cr_return_amt")).alias("ctr_total_return")))
    avg = (ctr.group_by("ca_state")
           .agg(F.avg(col("ctr_total_return")).alias("avg_ret")))
    return (ctr.join(avg, on="ca_state")
            .filter(col("ctr_total_return") > col("avg_ret") * lit(1.2))
            .join(d["customer"], on=[(col("cr_customer_sk"),
                                      col("c_customer_sk"))])
            .select(col("c_first_name"), col("c_last_name"),
                    col("ca_state"), col("ctr_total_return"))
            .order_by(col("c_last_name").asc(), col("c_first_name").asc(),
                      col("ctr_total_return").asc())
            .limit(100))


def q83(s, d):
    """returned quantity per item across the three return channels."""
    def chan(ret, item_col, qty_col, out):
        return (d[ret]
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .group_by("i_item_id")
                .agg(F.sum(col(qty_col)).alias(out)))
    sr = chan("store_returns", "sr_item_sk", "sr_return_quantity",
              "sr_qty")
    cr = (chan("catalog_returns", "cr_item_sk", "cr_return_quantity",
               "cr_qty").with_column_renamed("i_item_id", "c_id"))
    wr = (chan("web_returns", "wr_item_sk", "wr_return_quantity",
               "wr_qty").with_column_renamed("i_item_id", "w_id"))
    j = (sr.join(cr, on=[(col("i_item_id"), col("c_id"))])
         .join(wr, on=[(col("i_item_id"), col("w_id"))]))
    total = (col("sr_qty") + col("cr_qty") + col("wr_qty"))
    return (j.select(col("i_item_id"), col("sr_qty"), col("cr_qty"),
                     col("wr_qty"), (total / lit(3.0)).alias("average"))
            .order_by(col("i_item_id").asc(), col("sr_qty").asc())
            .limit(100))


def q84(s, d):
    """customers in a city with low-income-ish households, via
    store_returns activity."""
    c = (d["customer"]
         .join(d["customer_address"], on=[(col("c_current_addr_sk"),
                                          col("ca_address_sk"))])
         .filter(col("ca_city") == lit("Midway"))
         .join(d["household_demographics"],
               on=[(col("c_current_hdemo_sk"), col("hd_demo_sk"))])
         .filter(col("hd_buy_potential").isin("0-500", "501-1000")))
    return (c.join(d["store_returns"],
                   on=[(col("c_customer_sk"), col("sr_customer_sk"))],
                  how="left_semi")
            .select(col("c_customer_sk"), col("c_first_name"),
                    col("c_last_name"))
            .order_by(col("c_customer_sk").asc())
            .limit(100))


def q86(s, d):
    """web sales ROLLUP(i_category, i_class) ranked within grouping
    level."""
    from spark_rapids_tpu.expr.window import Window
    base = (d["web_sales"]
            .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                      col("d_date_sk"))])
            .filter(col("d_year") == lit(2000))
            .join(d["item"], on=[(col("ws_item_sk"), col("i_item_sk"))])
            .rollup("i_category", "i_class")
            .agg(F.sum(col("ws_net_profit")).alias("total_sum"),
                 F.grouping(col("i_category")).alias("g_cat"),
                 F.grouping(col("i_class")).alias("g_cls")))
    w = Window.partition_by(col("lochierarchy")) \
        .order_by(col("total_sum").desc())
    return (base.select(col("i_category"), col("i_class"),
                        col("total_sum"),
                        (col("g_cat") + col("g_cls"))
                        .alias("lochierarchy"))
            .select(col("i_category"), col("i_class"), col("total_sum"),
                    col("lochierarchy"),
                    F.rank().over(w).alias("rank_within_parent"))
            .order_by(col("lochierarchy").desc(),
                      col("rank_within_parent").asc(),
                      col("i_category").asc())
            .limit(100))


def q87(s, d):
    """store customers NOT in catalog and NOT in web (EXCEPT chain),
    counted."""
    def chan(sales, date_col, cust_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter(col("d_year") == lit(2000))
                .join(d["customer"], on=[(col(cust_col),
                                          col("c_customer_sk"))])
                .select(col("c_first_name"), col("c_last_name")))
    u = (chan("store_sales", "ss_sold_date_sk", "ss_customer_sk")
         .subtract(chan("catalog_sales", "cs_sold_date_sk",
                        "cs_customer_sk"))
         .subtract(chan("web_sales", "ws_sold_date_sk",
                        "ws_customer_sk")))
    return u.agg(F.count("*").alias("cnt"))


def q88(s, d):
    """store-hour traffic counts for eight half-hour windows in one
    conditional-agg pass."""
    j = (d["store_sales"]
         .join(d["time_dim"], on=[(col("ss_sold_time_sk"),
                                   col("t_time_sk"))])
         .join(d["household_demographics"],
               on=[(col("ss_hdemo_sk"), col("hd_demo_sk"))])
         .filter(col("hd_dep_count") >= lit(3)))
    aggs = []
    for i, hr in enumerate([8, 9, 10, 11, 12, 13, 14, 15]):
        cond = (col("t_hour") == lit(hr))
        aggs.append(F.count(F.when(cond, lit(1))).alias(f"h{hr}"))
    return j.agg(*aggs)


def q90(s, d):
    """web sales AM/PM ratio (two single-row conditional counts)."""
    j = (d["web_sales"]
         .join(d["time_dim"], on=[(col("ws_sold_time_sk"),
                                   col("t_time_sk"))])
         .join(d["household_demographics"],
               on=[(col("ws_hdemo_sk"), col("hd_demo_sk"))])
         .filter(col("hd_dep_count") >= lit(2)))
    out = j.agg(
        F.count(F.when((col("t_hour") >= lit(8))
                       & (col("t_hour") < lit(12)), lit(1)))
        .alias("amc"),
        F.count(F.when((col("t_hour") >= lit(14))
                       & (col("t_hour") < lit(18)), lit(1)))
        .alias("pmc"))
    return out.select(col("amc"), col("pmc"),
                      (col("amc") * lit(1.0) / col("pmc"))
                      .alias("am_pm_ratio"))


def q91(s, d):
    """catalog returns by demographic segment for one month."""
    return (d["catalog_returns"]
            .join(d["date_dim"], on=[(col("cr_returned_date_sk"),
                                      col("d_date_sk"))])
            .filter((col("d_year") == lit(1998))
                    & (col("d_moy") == lit(11)))
            .join(d["customer"], on=[(col("cr_customer_sk"),
                                      col("c_customer_sk"))])
            .join(d["customer_demographics"],
                  on=[(col("c_current_cdemo_sk"), col("cd_demo_sk"))])
            .join(d["household_demographics"],
                  on=[(col("c_current_hdemo_sk"), col("hd_demo_sk"))])
            .filter(col("hd_buy_potential").isin(">10000", "Unknown"))
            .group_by("cd_gender", "cd_marital_status",
                      "cd_education_status")
            .agg(F.sum(col("cr_net_loss")).alias("returns_loss"))
            .order_by(col("returns_loss").desc()).limit(100))


def q92(s, d):
    """q32 for web sales."""
    window = (d["web_sales"]
              .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                        col("d_date_sk"))])
              .filter(col("d_year") == lit(2000)))
    item_avg = (window.group_by("ws_item_sk")
                .agg(F.avg(col("ws_ext_discount_amt")).alias("avg_disc")))
    return (window
            .join(item_avg.select(col("ws_item_sk").alias("k"),
                                  col("avg_disc")),
                  on=[(col("ws_item_sk"), col("k"))])
            .filter(col("ws_ext_discount_amt")
                    > col("avg_disc") * lit(1.3))
            .agg(F.sum(col("ws_ext_discount_amt"))
                 .alias("excess_discount_amount")))


def q93(s, d):
    """store net sales after subtracting returns for a given reason."""
    r = (d["reason"].filter(col("r_reason_desc") == lit("reason 28"))
         .select(col("r_reason_sk").alias("rs")))
    sr = (d["store_returns"]
          .join(r, on=[(col("sr_reason_sk"), col("rs"))], how="left_semi")
          .select(col("sr_ticket_number").alias("rt"),
                  col("sr_item_sk").alias("ri"),
                  col("sr_return_quantity")))
    j = (d["store_sales"]
         .join(sr, on=[(col("ss_ticket_number"), col("rt")),
                       (col("ss_item_sk"), col("ri"))], how="left"))
    act = F.when(
        col("sr_return_quantity").is_not_null(),
        (col("ss_quantity") - col("sr_return_quantity"))
        * col("ss_sales_price")).otherwise(
        col("ss_quantity") * col("ss_sales_price"))
    return (j.group_by("ss_customer_sk")
            .agg(F.sum(act).alias("sumsales"))
            .order_by(col("sumsales").desc(),
                      col("ss_customer_sk").asc())
            .limit(100))


def q94(s, d):
    """q16 for web sales."""
    ws = (d["web_sales"]
          .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter((col("d_year") == lit(2000))
                  & col("d_moy").isin(1, 2)))
    multi_wh = (ws.group_by("ws_order_number")
                .agg(F.min(col("ws_warehouse_sk")).alias("wmin"),
                     F.max(col("ws_warehouse_sk")).alias("wmax"))
                .filter(col("wmin") < col("wmax"))
                .select(col("ws_order_number").alias("o")))
    kept = (ws.join(multi_wh, on=[(col("ws_order_number"), col("o"))],
                    how="left_semi")
            .join(d["web_returns"]
                  .select(col("wr_order_number").alias("r")),
                  on=[(col("ws_order_number"), col("r"))],
                  how="left_anti"))
    orders = kept.select(col("ws_order_number")).distinct() \
        .agg(F.count(col("ws_order_number")).alias("order_count"))
    totals = kept.agg(
        F.sum(col("ws_ext_sales_price")).alias("total_shipping_cost"),
        F.sum(col("ws_net_profit")).alias("total_net_profit"))
    return orders.join(totals, on=None, how="cross")


def q95(s, d):
    """web orders in the multi-warehouse set WITH a return (semi joins
    both ways)."""
    ws = (d["web_sales"]
          .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                    col("d_date_sk"))])
          .filter(col("d_year") == lit(2000)))
    multi_wh = (ws.group_by("ws_order_number")
                .agg(F.min(col("ws_warehouse_sk")).alias("wmin"),
                     F.max(col("ws_warehouse_sk")).alias("wmax"))
                .filter(col("wmin") < col("wmax"))
                .select(col("ws_order_number").alias("o")))
    kept = (ws.join(multi_wh, on=[(col("ws_order_number"), col("o"))],
                    how="left_semi")
            .join(d["web_returns"]
                  .select(col("wr_order_number").alias("r")),
                  on=[(col("ws_order_number"), col("r"))],
                  how="left_semi"))
    orders = kept.select(col("ws_order_number")).distinct() \
        .agg(F.count(col("ws_order_number")).alias("order_count"))
    totals = kept.agg(
        F.sum(col("ws_ext_sales_price")).alias("total_shipping_cost"),
        F.sum(col("ws_net_profit")).alias("total_net_profit"))
    return orders.join(totals, on=None, how="cross")


def q99(s, d):
    """catalog days-to-ship buckets by warehouse."""
    lag_days = col("cs_ship_date_sk") - col("cs_sold_date_sk")
    return (d["catalog_sales"]
            .join(d["warehouse"], on=[(col("cs_warehouse_sk"),
                                       col("w_warehouse_sk"))])
            .group_by("w_warehouse_name")
            .agg(F.sum(F.when(lag_days <= lit(30), lit(1))
                       .otherwise(lit(0))).alias("d30"),
                 F.sum(F.when((lag_days > lit(30))
                              & (lag_days <= lit(60)), lit(1))
                       .otherwise(lit(0))).alias("d31_60"),
                 F.sum(F.when((lag_days > lit(60))
                              & (lag_days <= lit(90)), lit(1))
                       .otherwise(lit(0))).alias("d61_90"),
                 F.sum(F.when(lag_days > lit(90), lit(1))
                       .otherwise(lit(0))).alias("d90plus"))
            .order_by(col("w_warehouse_name").asc()).limit(100))


def _year_totals(d, sales, date_col, cust_col, price_col):
    return (d[sales]
            .join(d["date_dim"], on=[(col(date_col), col("d_date_sk"))])
            .filter(col("d_year").isin(1999, 2000))
            .group_by(cust_col, "d_year")
            .agg(F.sum(col(price_col)).alias("tot")))


def _growth_join(d, first, second, f_cust, s_cust, f_name, s_name):
    """(customer, first-channel growth, second-channel growth) for
    customers with positive base-year totals in both channels."""
    def split(g, cust, name):
        y1 = g.filter(col("d_year") == lit(1999)).select(
            col(cust).alias(name + "_c1"), col("tot").alias(name + "1"))
        y2 = g.filter(col("d_year") == lit(2000)).select(
            col(cust).alias(name + "_c2"), col("tot").alias(name + "2"))
        return (y1.join(y2, on=[(col(name + "_c1"), col(name + "_c2"))])
                .filter(col(name + "1") > lit(0.0)))
    a = split(first, f_cust, f_name)
    b = split(second, s_cust, s_name)
    return a.join(b, on=[(col(f_name + "_c1"), col(s_name + "_c1"))])


def q4(s, d):
    """customers whose catalog spend grows faster than store spend
    (the 3-self-join year-over-year shape, catalog vs store)."""
    ss = _year_totals(d, "store_sales", "ss_sold_date_sk",
                      "ss_customer_sk", "ss_ext_sales_price")
    cs = _year_totals(d, "catalog_sales", "cs_sold_date_sk",
                      "cs_customer_sk", "cs_ext_sales_price")
    j = _growth_join(d, ss, cs, "ss_customer_sk", "cs_customer_sk",
                     "s", "c")
    j = j.filter(col("c2") / col("c1") > col("s2") / col("s1"))
    return (j.join(d["customer"], on=[(col("s_c1"),
                                       col("c_customer_sk"))])
            .select(col("c_customer_sk"), col("c_first_name"),
                    col("c_last_name"))
            .order_by(col("c_customer_sk").asc()).limit(100))


def q11(s, d):
    """q4 for web vs store."""
    ss = _year_totals(d, "store_sales", "ss_sold_date_sk",
                      "ss_customer_sk", "ss_ext_sales_price")
    ws = _year_totals(d, "web_sales", "ws_sold_date_sk",
                      "ws_customer_sk", "ws_ext_sales_price")
    j = _growth_join(d, ss, ws, "ss_customer_sk", "ws_customer_sk",
                     "s", "w")
    j = j.filter(col("w2") / col("w1") > col("s2") / col("s1"))
    return (j.join(d["customer"], on=[(col("s_c1"),
                                       col("c_customer_sk"))])
            .select(col("c_customer_sk"), col("c_first_name"),
                    col("c_last_name"))
            .order_by(col("c_customer_sk").asc()).limit(100))


def q74(s, d):
    """q11 with quantity-based totals."""
    ss = _year_totals(d, "store_sales", "ss_sold_date_sk",
                      "ss_customer_sk", "ss_quantity")
    ws = _year_totals(d, "web_sales", "ws_sold_date_sk",
                      "ws_customer_sk", "ws_quantity")
    j = _growth_join(d, ss, ws, "ss_customer_sk", "ws_customer_sk",
                     "s", "w")
    j = j.filter(col("w2") * col("s1") > col("s2") * col("w1"))
    return (j.join(d["customer"], on=[(col("s_c1"),
                                       col("c_customer_sk"))])
            .select(col("c_customer_sk"), col("c_first_name"),
                    col("c_last_name"))
            .order_by(col("c_customer_sk").asc()).limit(100))


def q14(s, d):
    """cross-channel items (3-way INTERSECT) with per-channel ROLLUP
    sales over an average-sales gate."""
    def chan_items(sales, date_col, item_col):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter(col("d_year").isin(1999, 2000))
                .select(col(item_col).alias("item_sk")))
    cross = (chan_items("store_sales", "ss_sold_date_sk", "ss_item_sk")
             .intersect(chan_items("catalog_sales", "cs_sold_date_sk",
                                   "cs_item_sk"))
             .intersect(chan_items("web_sales", "ws_sold_date_sk",
                                   "ws_item_sk")))
    avg_sales = float(
        d["store_sales"].agg(F.avg(col("ss_ext_sales_price"))
                             .alias("a")).collect().to_pylist()[0]["a"])

    def leg(sales, date_col, item_col, price_col, qty_col, chan):
        return (d[sales]
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter((col("d_year") == lit(2000))
                        & (col("d_moy") == lit(11)))
                .join(cross, on=[(col(item_col), col("item_sk"))],
                      how="left_semi")
                .join(d["item"], on=[(col(item_col), col("i_item_sk"))])
                .select(lit(chan).alias("channel"), col("i_brand_id"),
                        (col(price_col) * lit(1.0)).alias("sales"),
                        col(qty_col).alias("number_sales")))
    u = (leg("store_sales", "ss_sold_date_sk", "ss_item_sk",
             "ss_ext_sales_price", "ss_quantity", "store")
         .union(leg("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                    "cs_ext_sales_price", "cs_quantity", "catalog"))
         .union(leg("web_sales", "ws_sold_date_sk", "ws_item_sk",
                    "ws_ext_sales_price", "ws_quantity", "web")))
    return (u.rollup("channel", "i_brand_id")
            .agg(F.sum(col("sales")).alias("sum_sales"),
                 F.sum(col("number_sales")).alias("number_sales"))
            .filter(col("sum_sales") > lit(avg_sales))
            .order_by(col("channel").asc(), col("i_brand_id").asc())
            .limit(100))


def q24(s, d):
    """store-returned purchases by customer name/city over an
    average-gate (decorrelated scalar subquery)."""
    base = (d["store_sales"]
            .join(d["store_returns"],
                  on=[(col("ss_ticket_number"), col("sr_ticket_number")),
                      (col("ss_item_sk"), col("sr_item_sk"))])
            .join(d["store"], on=[(col("ss_store_sk"),
                                   col("s_store_sk"))])
            .join(d["customer"], on=[(col("ss_customer_sk"),
                                      col("c_customer_sk"))])
            .group_by("c_last_name", "c_first_name", "s_city")
            .agg(F.sum(col("ss_net_profit")).alias("netpaid")))
    thresh = float(base.agg(F.avg(col("netpaid")).alias("a"))
                   .collect().to_pylist()[0]["a"]) * 1.05
    return (base.filter(col("netpaid") > lit(thresh))
            .order_by(col("c_last_name").asc(), col("c_first_name").asc(),
                      col("s_city").asc())
            .limit(100))


def q54(s, d):
    """customers buying target-category items on web/catalog in a
    month, bucketed by their store revenue."""
    buyers = (d["web_sales"]
              .join(d["item"], on=[(col("ws_item_sk"),
                                    col("i_item_sk"))])
              .join(d["date_dim"], on=[(col("ws_sold_date_sk"),
                                        col("d_date_sk"))])
              .filter((col("i_category") == lit("Music"))
                      & (col("d_year") == lit(2000)))
              .select(col("ws_customer_sk").alias("k"))
              .union(d["catalog_sales"]
                     .join(d["item"], on=[(col("cs_item_sk"),
                                           col("i_item_sk"))])
                     .join(d["date_dim"], on=[(col("cs_sold_date_sk"),
                                               col("d_date_sk"))])
                     .filter((col("i_category") == lit("Music"))
                             & (col("d_year") == lit(2000)))
                     .select(col("cs_customer_sk").alias("k"))))
    rev = (d["store_sales"]
           .join(buyers.distinct(),
                 on=[(col("ss_customer_sk"), col("k"))], how="left_semi")
           .group_by("ss_customer_sk")
           .agg(F.sum(col("ss_ext_sales_price")).alias("revenue")))
    bucket = E.Cast(col("revenue") / lit(50.0), T.INT64)
    return (rev.select(bucket.alias("segment"))
            .group_by("segment")
            .agg(F.count("*").alias("num_customers"))
            .order_by(col("segment").asc()).limit(100))


def q80(s, d):
    """q77 with per-row return adjustment via order-number joins."""
    def leg(sales, ret, date_col, id_col, item, price, profit, ordr,
            r_item, r_ord, r_amt, r_loss, chan):
        r = d[ret].select(col(r_item).alias("ri"), col(r_ord).alias("ro"),
                          col(r_amt).alias("ramt"),
                          col(r_loss).alias("rloss"))
        return (d[sales]
                .join(r, on=[(col(item), col("ri")),
                             (col(ordr), col("ro"))], how="left")
                .join(d["date_dim"], on=[(col(date_col),
                                          col("d_date_sk"))])
                .filter(col("d_year") == lit(2000))
                .group_by(id_col)
                .agg(F.sum(col(price)).alias("sales"),
                     F.sum(F.coalesce(col("ramt"), lit(0.0)))
                     .alias("returns_amt"),
                     F.sum(col(profit)
                           - F.coalesce(col("rloss"), lit(0.0)))
                     .alias("profit"))
                .select(lit(chan).alias("channel"),
                        col(id_col).alias("id"), col("sales"),
                        col("returns_amt"), col("profit")))
    u = (leg("store_sales", "store_returns", "ss_sold_date_sk",
             "ss_store_sk", "ss_item_sk", "ss_ext_sales_price",
             "ss_net_profit", "ss_ticket_number", "sr_item_sk",
             "sr_ticket_number", "sr_return_amt", "sr_net_loss",
             "store")
         .union(leg("catalog_sales", "catalog_returns",
                    "cs_sold_date_sk", "cs_warehouse_sk", "cs_item_sk",
                    "cs_ext_sales_price", "cs_net_profit",
                    "cs_order_number", "cr_item_sk", "cr_order_number",
                    "cr_return_amt", "cr_net_loss", "catalog"))
         .union(leg("web_sales", "web_returns", "ws_sold_date_sk",
                    "ws_warehouse_sk", "ws_item_sk",
                    "ws_ext_sales_price", "ws_net_profit",
                    "ws_order_number", "wr_item_sk", "wr_order_number",
                    "wr_return_amt", "wr_net_loss", "web")))
    return (u.rollup("channel", "id")
            .agg(F.sum(col("sales")).alias("sales"),
                 F.sum(col("returns_amt")).alias("returns_amt"),
                 F.sum(col("profit")).alias("profit"))
            .order_by(col("channel").asc(), col("id").asc())
            .limit(100))


def q85(s, d):
    """web returns by reason with quantity-bucket gates and
    demographics."""
    j = (d["web_returns"]
         .join(d["customer"], on=[(col("wr_customer_sk"),
                                   col("c_customer_sk"))])
         .join(d["customer_demographics"],
               on=[(col("c_current_cdemo_sk"), col("cd_demo_sk"))])
         .join(d["reason"], on=[(col("wr_reason_sk"),
                                 col("r_reason_sk"))])
         .filter(((col("cd_marital_status") == lit("M"))
                  & (col("wr_return_quantity") >= lit(5)))
                 | ((col("cd_marital_status") == lit("S"))
                    & (col("wr_return_quantity") < lit(5)))))
    return (j.group_by("r_reason_desc")
            .agg(F.avg(col("wr_return_quantity")).alias("avg_qty"),
                 F.avg(col("wr_return_amt")).alias("avg_amt"),
                 F.count("*").alias("cnt"))
            .order_by(col("r_reason_desc").asc()).limit(100))


QUERIES = {1: q1, 3: q3, 5: q5, 6: q6, 7: q7, 8: q8, 9: q9, 10: q10,
           12: q12, 13: q13, 15: q15, 16: q16, 17: q17, 18: q18,
           19: q19, 20: q20, 21: q21, 22: q22, 25: q25, 26: q26,
           27: q27, 28: q28, 29: q29, 30: q30, 32: q32, 33: q33,
           35: q35, 36: q36, 37: q37, 38: q38, 39: q39, 40: q40,
           41: q41, 44: q44, 47: q47, 49: q49, 50: q50, 51: q51,
           53: q53, 56: q56, 57: q57, 58: q58, 59: q59, 61: q61,
           63: q63, 66: q66, 67: q67, 69: q69, 70: q70, 72: q72,
           75: q75, 77: q77, 78: q78, 81: q81, 83: q83, 84: q84,
           86: q86, 87: q87, 88: q88, 90: q90, 91: q91, 92: q92,
           93: q93, 94: q94, 95: q95, 99: q99,
           2: q2, 23: q23, 31: q31, 4: q4, 11: q11, 14: q14,
           24: q24, 54: q54, 74: q74, 80: q80, 85: q85,
           34: q34, 42: q42, 43: q43, 45: q45, 46: q46, 48: q48, 52: q52, 55: q55,
           60: q60, 62: q62, 65: q65, 68: q68, 71: q71, 73: q73, 76: q76, 79: q79, 82: q82,
           89: q89, 96: q96, 97: q97, 98: q98}


def _canon_rows(table):
    """Order-insensitive canonical rows with rounded floats, so the
    differential check compares VALUES, not just counts (most NDS
    queries end in limit(100) — counts alone cannot catch a wrong
    aggregate)."""
    rows = []
    for r in table.to_pylist():
        vals = []
        for k in sorted(r):
            v = r[k]
            if isinstance(v, float):
                v = round(v, 6)
            vals.append((k, v))
        rows.append(tuple(vals))
    return sorted(rows, key=repr)


def run_one(sess, dfs, qn: int, history_dir: str = "",
            sf: float = None) -> dict:
    df = QUERIES[qn](sess, dfs)
    explain = df.explain()
    device = "fallback" if "cannot run on TPU" in explain else "clean"
    wall0 = time.time()
    t0 = time.perf_counter()
    tpu_table = df.collect()
    first = time.perf_counter() - t0
    # the FIRST run's attribution (it carries the compile bucket), taken
    # before df.count() replaces the session's last-action state
    attr = None
    try:
        attr = sess.last_attribution()
    except Exception:  # noqa: BLE001 - attribution is advisory
        attr = None
    t0 = time.perf_counter()
    df.count()
    dt = time.perf_counter() - t0  # steady state (kernels cached)
    cpu_table = df.collect_cpu()  # full differential vs CPU interpreter
    status = "ok" if _canon_rows(tpu_table) == _canon_rows(cpu_table) \
        else "wrong"
    rec = {"status": status, "device": device,
           "rows": int(tpu_table.num_rows),
           "seconds": round(dt, 4), "first_run_seconds": round(first, 4),
           # first-run times are 7-11s vs 0.6s steady-state: nearly all
           # of the delta is XLA compilation, so the second-run delta IS
           # the compile cost — splitting it out makes compile-cache
           # regressions visible instead of smearing into "slow query"
           "compile_seconds": round(max(first - dt, 0.0), 4)}
    if attr:
        b = attr.get("buckets", {})
        # the engine's own wall-time decomposition of the first run
        # (obs/attribution.py): compile vs device vs host vs stall per
        # query — the columns ROADMAP item 4's compile-latency war is
        # measured by
        rec["attribution"] = {k: round(v, 4) for k, v in b.items() if v}
        rec["attr_compile_seconds"] = round(b.get("compile", 0.0), 4)
        rec["attr_device_seconds"] = round(
            b.get("device_compute", 0.0), 4)
        rec["attr_host_seconds"] = round(
            b.get("host_decode", 0.0) + b.get("shuffle", 0.0)
            + b.get("spill", 0.0), 4)
        rec["attr_stall_seconds"] = round(
            b.get("semaphore_wait", 0.0) + b.get("pipeline_stall", 0.0)
            + b.get("retry_backoff", 0.0), 4)
    try:
        # round-16 decode columns — only when a parquet scan actually ran
        # (the probe's default tables are in-memory cached): which decode
        # path served the scan and the encoded-vs-decoded bytes split
        snaps = sess.last_metrics()
        enc_execs = [v for k, v in snaps.items()
                     if k.startswith("EncodedParquetSourceExec")]
        host_scan = any(k.startswith("ParquetScanExec") for k in snaps)
        if enc_execs:
            fbc = sum(v.get("numDecodeFallbackColumns", 0)
                      for v in enc_execs)
            rec["decode_path"] = "mixed" if fbc else "device"
            rec["encoded_gb"] = round(sum(
                v.get("encodedBytes", 0) for v in enc_execs) / 1e9, 4)
            rec["decoded_gb"] = round(sum(
                v.get("decodedBytes", 0) for v in snaps.values()) / 1e9, 4)
            if fbc:
                rec["decode_fallback_columns"] = int(fbc)
        elif host_scan:
            rec["decode_path"] = "host"
    except Exception:  # noqa: BLE001 - decode columns are advisory
        pass
    if history_dir:
        append_scorecard(history_dir, qn, rec, df.plan, wall0, sf=sf)
    return rec


def append_scorecard(history_dir: str, qn: int, rec: dict, plan,
                     wall0: float, sf: float = None) -> None:
    """Persist one probe result as a history record: BENCH_*.json
    trajectories then regenerate from the store (--from-history) instead
    of by hand, and tools/history_server.py lists the scorecards next to
    the queries they measured (shared plan digest)."""
    from spark_rapids_tpu.runtime.obs.history import (QueryHistoryStore,
                                                      plan_digest)
    try:
        try:
            digest = plan_digest(plan)
        except Exception:  # noqa: BLE001
            digest = None
        QueryHistoryStore(history_dir).append({
            "type": "nds_scorecard", "query": f"q{qn}", "sf": sf,
            "wall_start_unix": wall0, "plan_digest": digest, **rec})
    except Exception as e:  # noqa: BLE001 - an unwritable store must not
        # flip an ALREADY-VALIDATED query result to "error"
        print(f"warning: could not append q{qn} scorecard to "
              f"{history_dir!r}: {e}", file=sys.stderr)


def _compile_seconds(q: dict) -> float:
    """Per-query compile cost: the recorded split when present, the
    first-minus-steady delta for records written before the split."""
    if "compile_seconds" in q:
        return float(q["compile_seconds"])
    return max(float(q.get("first_run_seconds", 0.0))
               - float(q.get("seconds", 0.0)), 0.0)


def summarize_card(card: dict, sf: float) -> dict:
    """The scorecard summary shape (shared by a live run and
    --from-history regeneration, so the two can never drift). The
    compile/steady totals aggregate the per-query split so the scorecard
    trajectory shows compile-cache regressions separately from kernel
    regressions."""
    translated = [q for q in card.values()
                  if q["status"] != "not_translated"]
    measured = [q for q in translated if q["status"] in ("ok", "wrong")]
    return {
        "sf": sf,
        "translated": len(translated),
        "ok": sum(1 for q in translated if q["status"] == "ok"),
        "clean_device": sum(1 for q in translated
                            if q.get("device") == "clean"),
        "steady_seconds_total": round(
            sum(float(q.get("seconds", 0.0)) for q in measured), 4),
        "compile_seconds_total": round(
            sum(_compile_seconds(q) for q in measured), 4),
        # engine-attributed first-run totals (obs/attribution.py): where
        # wall-clock goes across the probe — compile vs device vs host
        # vs stall (ROADMAP item 4 reads attr_compile_seconds_total)
        "attr_compile_seconds_total": round(
            sum(float(q.get("attr_compile_seconds", 0.0))
                for q in measured), 4),
        "attr_device_seconds_total": round(
            sum(float(q.get("attr_device_seconds", 0.0))
                for q in measured), 4),
        "attr_host_seconds_total": round(
            sum(float(q.get("attr_host_seconds", 0.0))
                for q in measured), 4),
        "attr_stall_seconds_total": round(
            sum(float(q.get("attr_stall_seconds", 0.0))
                for q in measured), 4),
        "queries": card,
    }


def scorecard_from_history(history_dir: str, sf: float) -> dict:
    """Rebuild the scorecard summary from history records (latest run per
    query wins) — the exact shape main() writes, so BENCH trajectories
    regenerate from persistent state instead of a rerun. Only records of
    the REQUESTED scale factor count (records carry their sf; mixing
    sf=0.01 leftovers into an sf=1 trajectory would mask regressions),
    and error/timeout runs are records too, so a query that regressed
    from ok to error cannot hide behind its older success."""
    from spark_rapids_tpu.runtime.obs.history import QueryHistoryStore
    latest = {}
    for rec in QueryHistoryStore(history_dir).read_all():
        if rec.get("type") == "nds_scorecard" and rec.get("sf") == sf:
            latest[rec["query"]] = {
                k: v for k, v in rec.items()
                if k not in ("type", "query", "sf", "plan_digest",
                             "wall_start_unix")}
    card = {f"q{qn}": latest.get(f"q{qn}", {"status": "not_translated"})
            for qn in range(1, 100)}
    return summarize_card(card, sf)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--out", default="NDS_SCORECARD.json")
    ap.add_argument("--query", type=int, default=0,
                    help="child mode: run ONE query, print its JSON")
    ap.add_argument("--inline", action="store_true",
                    help="run queries in-process (no isolation)")
    ap.add_argument("--history-dir",
                    default=os.environ.get("RAPIDS_TPU_HISTORY_DIR", ""),
                    help="append each per-query scorecard to this query "
                    "history store (spark.rapids.obs.historyDir)")
    ap.add_argument("--from-history", action="store_true",
                    help="skip running: rebuild the scorecard summary "
                    "from --history-dir records (latest run per query)")
    args = ap.parse_args()

    if args.from_history:
        if not args.history_dir:
            ap.error("--from-history requires --history-dir")
        summary = scorecard_from_history(args.history_dir, args.sf)
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
        print(json.dumps({k: summary[k] for k in
                          ("sf", "translated", "ok", "clean_device")}))
        return

    if args.query:
        t0 = time.perf_counter()
        sess = TpuSession()
        dfs = {name: sess.create_dataframe(t).cache()
               for name, t in gen_tables(args.sf).items()}
        for _df in dfs.values():
            _df.count()
        setup_s = round(time.perf_counter() - t0, 2)
        try:
            rec = run_one(sess, dfs, args.query,
                          history_dir=args.history_dir, sf=args.sf)
            rec["setup_seconds"] = setup_s
            print("RESULT " + json.dumps(rec))
        except Exception as e:  # noqa: BLE001
            err = {"status": "error", "setup_seconds": setup_s,
                   "error": f"{type(e).__name__}: {e}"}
            if args.history_dir:
                # failures are history too: --from-history must see a
                # regression from ok to error, not the stale success
                append_scorecard(args.history_dir, args.query, err,
                                 None, time.time(), sf=args.sf)
            print("RESULT " + json.dumps(err))
        return

    per_query_s = int(os.environ.get("NDS_QUERY_TIMEOUT_S", "420"))
    card = {}
    if args.inline:
        sess = TpuSession()
        dfs = {name: sess.create_dataframe(t).cache()
               for name, t in gen_tables(args.sf).items()}
    for qn in range(1, 100):
        if qn not in QUERIES:
            card[f"q{qn}"] = {"status": "not_translated"}
            continue
        if args.inline:
            try:
                card[f"q{qn}"] = run_one(sess, dfs, qn,
                                         history_dir=args.history_dir,
                                         sf=args.sf)
            except Exception as e:  # noqa: BLE001
                card[f"q{qn}"] = {"status": "error",
                                  "error": f"{type(e).__name__}: {e}"}
                if args.history_dir:
                    append_scorecard(args.history_dir, qn, card[f"q{qn}"],
                                     None, time.time(), sf=args.sf)
        else:
            # SUBPROCESS isolation: a wedged remote compile cannot be
            # interrupted by SIGALRM (it blocks in C), so each query gets
            # its own interpreter and a hard kill on timeout (the
            # reference scale-test isolates queries the same way)
            import subprocess
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--sf", str(args.sf), "--query", str(qn)]
            if args.history_dir:
                # children append their scorecards to the SAME store
                # (whole-line appends interleave safely across processes)
                cmd += ["--history-dir", os.path.abspath(args.history_dir)]
            # setup (data gen + cache upload) happens inside the child:
            # give it an sf-scaled allowance on top of the query budget so
            # a slow upload never reads as a query timeout
            setup_allowance = 90 + int(args.sf * 600)
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=per_query_s + setup_allowance)
                line = [l for l in r.stdout.splitlines()
                        if l.startswith("RESULT ")]
                card[f"q{qn}"] = (json.loads(line[-1][7:]) if line else
                                  {"status": "error",
                                   "error": (r.stderr or "no output")[-300:]})
            except subprocess.TimeoutExpired:
                card[f"q{qn}"] = {"status": "timeout",
                                  "seconds_limit": per_query_s}
            if args.history_dir and \
                    card[f"q{qn}"].get("status") in ("error", "timeout"):
                # the child appends its own ok/wrong records; a crashed
                # or killed child never got the chance — the parent
                # records the failure so history mirrors the scorecard
                append_scorecard(args.history_dir, qn, card[f"q{qn}"],
                                 None, time.time(), sf=args.sf)
        print(f"q{qn}: {card[f'q{qn}']}", file=sys.stderr, flush=True)

    summary = summarize_card(card, args.sf)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({k: summary[k] for k in
                      ("sf", "translated", "ok", "clean_device")}))


if __name__ == "__main__":
    main()
