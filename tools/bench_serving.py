"""Serving load bench (round 17) -> SERVING_r01.json.

Drives the query server over its real HTTP surface and records:

* qps + client-observed p50/p99 under N concurrent clients on a
  hot/cold request mix;
* per-bucket p99 attribution for the executed (non-hit) requests — the
  response docs carry the engine's wall breakdown, so the bench explains
  its own tail without any server-side profiling;
* hot-path speedup: cached p50 vs forced re-execution p50 (acceptance:
  >= 10x);
* quota isolation as a load test: a hog session looping heavy uncached
  aggregations under a device-budget quota and the background QoS tier
  (spark.rapids.serving.requestNice) must move a neighbor tenant's p99
  — a hot/uncached request mix, so the tail lands on real device work —
  by <= 1.25x of its solo run;
* request tracing evidence (round 18): the whole run is served with
  reqtrace armed through the real conf surface
  (spark.rapids.obs.reqtrace.*), then a deterministic evidence phase
  proves deadline-cancelled / failed / SLO-breaching requests export
  100% of the time, hot cache hits are kept exactly at the seeded
  sampleRatio, /metrics latency histograms carry exemplars resolving to
  exported timelines on disk, every artifact validates as a Chrome
  trace with serving<->exec spans joined by query id, and the armed
  hot-path overhead stays <2% by count x delta.

Usage: python tools/bench_serving.py [--clients 8] [--out SERVING_r02.json]
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

HOT_SQL = "SELECT k, SUM(v) AS sv, COUNT(*) AS n FROM t GROUP BY k"
COLD_SQLS = (
    "SELECT k, SUM(v) AS sv FROM t WHERE v > 250 GROUP BY k",
    "SELECT k, MIN(v) AS lo, MAX(v) AS hi FROM t GROUP BY k",
    "SELECT k, v * 2 AS v2 FROM t WHERE k < 3",
)
HOG_SQL = ("SELECT k, SUM(v) AS sv, SUM(v * v) AS sq, COUNT(*) AS n "
           "FROM big GROUP BY k")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(port: int, payload: dict, timeout: float = 300.0,
          headers: dict | None = None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(headers or {})
        conn.request("POST", "/sql", body=json.dumps(payload).encode(),
                     headers=hdrs)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _get_text(port: int, path: str, timeout: float = 30.0) -> str:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        return conn.getresponse().read().decode()
    finally:
        conn.close()


def _pct(samples, q):
    if not samples:
        return None
    s = sorted(samples)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def _timed(port, payload):
    t0 = time.perf_counter()
    code, doc = _post(port, payload)
    return (time.perf_counter() - t0) * 1e3, code, doc


def boot(port: int, reqtrace_dir: str, ratio: float):
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.sql.session import TpuSession
    rng = np.random.default_rng(2026)
    # reqtrace armed through the real conf surface: the whole load run
    # buffers + tail-samples every request (minInterval 0 so every
    # sampled keep actually exports — the bench validates the artifacts)
    sess = TpuSession({
        "spark.rapids.serving.enabled": "true",
        "spark.rapids.obs.port": str(port),
        "spark.rapids.obs.reqtrace.enabled": "true",
        "spark.rapids.obs.reqtrace.path": reqtrace_dir,
        "spark.rapids.obs.reqtrace.sampleRatio": str(ratio),
        "spark.rapids.obs.reqtrace.minIntervalSeconds": "0",
        "spark.rapids.obs.reqtrace.maxDumps": "10000",
        "spark.rapids.obs.replicaId": "bench-replica",
    })
    n = 150_000
    sess.create_or_replace_temp_view("t", sess.create_dataframe(
        pa.table({"k": rng.integers(0, 16, n),
                  "v": rng.integers(1, 1000, n)})))
    # the hog table is big enough that a hog request is dominated by
    # XLA compute (which yields the GIL on the CPU sim, as the device
    # does on TPU), not by Python-side planning
    nb = 1_500_000
    sess.create_or_replace_temp_view("big", sess.create_dataframe(
        pa.table({"k": rng.integers(0, 24, nb),
                  "v": rng.integers(1, 1000, nb)})))
    from spark_rapids_tpu.runtime import obs
    return sess, obs.state().server.port


def hot_vs_uncached(port: int, reps: int) -> dict:
    # warm the trace cache first so the uncached baseline measures
    # steady-state execution, not first-run compiles
    _post(port, {"sql": HOT_SQL, "cache": False})
    uncached = [_timed(port, {"sql": HOT_SQL, "cache": False})[0]
                for _ in range(reps)]
    _post(port, {"sql": HOT_SQL})  # populate the entry
    hot = [_timed(port, {"sql": HOT_SQL})[0] for _ in range(reps)]
    p50_u, p50_h = _pct(uncached, 0.5), _pct(hot, 0.5)
    return {"uncached_p50_ms": round(p50_u, 3),
            "uncached_p99_ms": round(_pct(uncached, 0.99), 3),
            "hot_p50_ms": round(p50_h, 3),
            "hot_p99_ms": round(_pct(hot, 0.99), 3),
            "hot_speedup_p50": round(p50_u / p50_h, 1)}


def mixed_load(port: int, clients: int, per_client: int) -> dict:
    lat = []
    docs = []
    lock = threading.Lock()

    def client(i):
        for j in range(per_client):
            if (i + j) % 3 == 0:
                payload = {"sql": COLD_SQLS[(i + j) % len(COLD_SQLS)],
                           "cache": False}
            else:
                payload = {"sql": HOT_SQL}
            ms, code, doc = _timed(port, payload)
            with lock:
                lat.append(ms)
                if code == 200:
                    docs.append(doc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join(600)
    window = time.perf_counter() - t0

    # per-bucket p99 over the EXECUTED requests: the response docs carry
    # the attribution breakdown, so the tail explains itself
    buckets = {}
    for d in docs:
        attr = d.get("attribution") or {}
        for name, secs in (attr.get("buckets") or {}).items():
            buckets.setdefault(name, []).append(secs * 1e3)
    hits = sum(1 for d in docs if d["cache"] == "hit")
    # every response doc carries its trace identity and tail-sampling
    # verdict — the load run explains its own sampling behavior
    verdicts = {}
    for d in docs:
        v = (d.get("reqtrace") or {}).get("verdict") or "untraced"
        verdicts[v] = verdicts.get(v, 0) + 1
    hits_kept = sum(1 for d in docs if d["cache"] == "hit"
                    and (d.get("reqtrace") or {}).get("verdict")
                    == "sampled")
    return {
        "clients": clients,
        "requests": len(lat),
        "window_s": round(window, 3),
        "qps": round(len(lat) / window, 1),
        "p50_ms": round(_pct(lat, 0.5), 3),
        "p99_ms": round(_pct(lat, 0.99), 3),
        "cache_hits": hits,
        "executed": len(docs) - hits,
        "traced": sum(1 for d in docs if d.get("trace_id")),
        "reqtrace_verdicts": verdicts,
        "hot_hits_kept": hits_kept,
        "attribution_p99_ms": {
            name: round(_pct(ms, 0.99), 3)
            for name, ms in sorted(buckets.items())},
    }


def quota_isolation(port: int, samples: int, hogs: int) -> dict:
    # the neighbor is a realistic tenant: mostly hot-path hits with an
    # uncached query every 5th request, so its p99 lands on real device
    # work — the thing the hog's QoS tier must yield to (on one core, a
    # 2ms cache hit's tail is pure GIL scheduling noise either way; a
    # 75ms device query measures the isolation the engine provides)
    uncached = {"sql": COLD_SQLS[0], "cache": False}

    def neighbor_pass():
        # paced 5ms between requests so the pass samples the window
        out = []
        for i in range(samples):
            payload = uncached if i % 5 == 4 else {"sql": HOT_SQL}
            ms, code, _doc = _timed(port, payload)
            if code == 200:
                out.append(ms)
            time.sleep(0.005)
        return out

    # the hog declares itself background tier: a device budget bounds
    # its memory pressure, small reader batches slice its scan into
    # short dispatches and pipeline overlap is off (so the in-order
    # device queue DRAINS between hog batches instead of sitting
    # behind one long kernel or a prefetched lookahead when a neighbor
    # dispatch arrives), and requestNice=19 runs its requests — wave
    # tasks and pool work included, via the host_pool QoS propagation —
    # at low OS priority so its host phases yield the core too; with
    # concurrentTpuTasks=2 a single hog never exhausts the device
    # semaphore, so the neighbor's uncached queries admit immediately
    hog_payload = {
        "sql": HOG_SQL, "cache": False, "session": "hog",
        "conf": {"spark.rapids.query.deviceBudgetBytes": str(192 << 20),
                 "spark.rapids.sql.reader.batchSizeRows": str(16384),
                 "spark.rapids.sql.pipeline.enabled": "false",
                 "spark.rapids.serving.requestNice": "19"}}
    # warm every measured path out of the windows: first runs pay
    # Python tracing + XLA compile that steady state never replays
    _post(port, {"sql": HOT_SQL})
    _post(port, uncached)
    _post(port, hog_payload)
    solo = neighbor_pass()

    stop = threading.Event()
    hog_counts = [0]

    def hog():
        while not stop.is_set():
            code, _ = _post(port, hog_payload)
            if code == 200:
                hog_counts[0] += 1

    threads = [threading.Thread(target=hog) for _ in range(hogs)]
    for th in threads:
        th.start()
    time.sleep(1.0)  # hogs properly under way
    loaded = neighbor_pass()
    stop.set()
    for th in threads:
        th.join(120)

    p99_solo, p99_loaded = _pct(solo, 0.99), _pct(loaded, 0.99)
    return {"neighbor_samples": samples, "hog_clients": hogs,
            "hog_requests_completed": hog_counts[0],
            "neighbor_solo_p50_ms": round(_pct(solo, 0.5), 3),
            "neighbor_solo_p99_ms": round(p99_solo, 3),
            "neighbor_loaded_p50_ms": round(_pct(loaded, 0.5), 3),
            "neighbor_loaded_p99_ms": round(p99_loaded, 3),
            "neighbor_p99_ratio": round(p99_loaded / p99_solo, 3)}


def reqtrace_evidence(port: int, out_dir: str, ratio: float,
                      errors: int, hits: int) -> tuple:
    """Deterministic request-tracing evidence over the served surface.

    The load phases already ran with the conf-armed recorder; this
    phase (a) bounds the armed hot-path cost by count x delta on a real
    served request, then (b) swaps in a SEEDED recorder (same artifact
    dir) so every assertion replays exactly: a deadline-cancelled, N
    failed, and an SLO-breaching request must export 100% of the time,
    hot cache hits must keep exactly the seeded sampleRatio draw, the
    /metrics latency histogram must carry an exemplar resolving to an
    exported timeline, and every artifact in the dir must validate as a
    Chrome trace + OTLP pair with serving<->exec spans joined by query
    id (reqtrace_smoke's validator, run over the bench's own output).
    """
    import random
    import reqtrace_smoke as RS
    from spark_rapids_tpu.runtime.obs import flight, live, reqtrace

    res = {"ratio": ratio}
    checks = {}
    fails = []

    # -- armed hot-path overhead on a served request (count x delta) ----
    rec = reqtrace.recorder()
    assert rec is not None, "load phases must run with reqtrace armed"
    counts = [0]
    real = flight.FlightRecorder.record

    def counting(self, *a, **kw):
        counts[0] += 1
        return real(self, *a, **kw)

    flight.FlightRecorder.record = counting
    try:
        wall_ms, code, _doc = _timed(
            port, {"sql": COLD_SQLS[2], "cache": False})
    finally:
        flight.FlightRecorder.record = real
    assert code == 200 and counts[0] > 0
    ctx = rec.begin()
    prev = live.bind_request(ctx)
    try:
        iters = 200_000
        t0 = time.perf_counter()
        for _ in range(iters):
            rec.feed("bench", "exec", 0, 1, None, 7)
        per_call = (time.perf_counter() - t0) / iters
    finally:
        live.bind_request(prev)
    pct = counts[0] * per_call / (wall_ms / 1e3) * 100
    res["armed_overhead"] = {
        "feed_sites": counts[0], "per_call_ns": round(per_call * 1e9, 1),
        "request_wall_ms": round(wall_ms, 3), "pct": round(pct, 5)}
    checks["armed_overhead_lt_2pct"] = pct < 2.0

    # -- seeded recorder: the verdict assertions replay exactly ---------
    rec = reqtrace.install(out_dir=out_dir, sample_ratio=ratio,
                           min_interval_s=0.0, max_dumps=10_000,
                           replica_id="bench-replica",
                           sample_seed=RS.SEED)

    # deadline-cancelled: a tiny per-query budget against the hog-sized
    # scan (~700ms of device work — a small query can finish before the
    # sweeper's first tick, landing status=ok and silently consuming a
    # sampler draw, which would shift the seeded hits replay below)
    code, doc = _post(port, {
        "sql": HOG_SQL, "cache": False, "session": "deadl",
        "conf": {"spark.rapids.query.timeoutSeconds": "0.01"}})
    rt = doc.get("reqtrace") or {}
    dl_ok = (code == 499 and doc.get("status") == "cancelled"
             and rt.get("verdict") == "deadline" and rt.get("path")
             and os.path.exists(rt["path"]))
    if not dl_ok:
        fails.append(f"deadline request not kept: code={code} rt={rt}")
    res["deadline"] = {"code": code, "verdict": rt.get("verdict")}

    # failed: injected scan ioerrors, 100% kept
    err_kept = 0
    for _ in range(errors):
        code, doc = _post(port, {
            "sql": HOT_SQL, "cache": False, "session": "faulty",
            "conf": {"spark.rapids.debug.faults":
                     f"scan.decode:ioerror:{errors}"}})
        rt = doc.get("reqtrace") or {}
        if code == 500 and rt.get("verdict") == "error" \
                and rt.get("path") and os.path.exists(rt["path"]):
            err_kept += 1
    if err_kept != errors:
        fails.append(f"only {err_kept}/{errors} failed requests kept")
    res["errors"] = {"sent": errors, "kept": err_kept}

    # SLO breach: a tiny absolute bound the executed request must trip
    code, doc = _post(port, {
        "sql": COLD_SQLS[1], "cache": False, "session": "slo",
        "conf": {"spark.rapids.obs.slo.latencySeconds": "0.0005"}})
    rt = doc.get("reqtrace") or {}
    slo_ok = (code == 200 and rt.get("verdict") == "slo_breach"
              and rt.get("path") and os.path.exists(rt["path"]))
    if not slo_ok:
        fails.append(f"SLO breach not kept: code={code} rt={rt}")
    res["slo_breach"] = {"code": code, "verdict": rt.get("verdict")}
    checks["always_keeps_100pct"] = bool(
        dl_ok and err_kept == errors and slo_ok)

    # hot cache hits: only these consume sampler draws on the seeded
    # recorder (always-keeps never draw), serialized -> exact replay
    rng = random.Random(RS.SEED)
    expected = sum(1 for _ in range(hits) if rng.random() < ratio)
    kept = 0
    for i in range(hits):
        hdrs = {"traceparent": RS.TP} if i == 0 else None
        code, doc = _post(port, {"sql": HOT_SQL}, headers=hdrs)
        if code != 200 or doc.get("cache") != "hit":
            fails.append(f"hit {i}: code={code} cache={doc.get('cache')}")
            break
        if i == 0 and doc.get("trace_id") != RS.TP_TID:
            fails.append(f"incoming traceparent not honored over HTTP: "
                         f"{doc.get('trace_id')}")
        if (doc.get("reqtrace") or {}).get("verdict") == "sampled":
            kept += 1
    if kept != expected:
        fails.append(f"seeded sampler kept {kept}/{hits} hits, "
                     f"expected {expected} (ratio {ratio})")
    res["hits"] = {"sent": hits, "kept": kept, "expected": expected}
    checks["hot_hits_kept_at_seeded_ratio"] = kept == expected

    # /metrics exemplar -> exported timeline on disk
    metrics = _get_text(port, "/metrics")
    resolvable = 0
    example = None
    for line in metrics.splitlines():
        if "# {" not in line or "rapids_serving_request_ms" not in line:
            continue
        lbl = line.split("# {", 1)[1].split("}", 1)[0]
        path = next((p.split('"')[1] for p in lbl.split(",")
                     if p.strip().startswith('path="')), None)
        if path and os.path.exists(path):
            resolvable += 1
            example = example or line.strip()
    if resolvable == 0:
        fails.append("no /metrics latency exemplar resolves to an "
                     "exported timeline")
    res["exemplars"] = {"resolvable_bucket_lines": resolvable,
                        "example": example}
    checks["exemplars_resolvable"] = resolvable > 0

    # every artifact (load phases + this one): Chrome trace + OTLP pair,
    # serving<->exec spans joined by the request's query id
    vfails = RS.validate_timelines(out_dir, res)
    fails.extend(vfails)
    checks["timelines_valid_and_joined"] = not vfails
    res["checks"] = checks
    return res, fails


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--per-client", type=int, default=12)
    ap.add_argument("--reps", type=int, default=30)
    ap.add_argument("--samples", type=int, default=200)
    ap.add_argument("--hogs", type=int, default=1)
    ap.add_argument("--ratio", type=float, default=0.05,
                    help="reqtrace sampleRatio for the whole run")
    ap.add_argument("--hits", type=int, default=200,
                    help="serialized hot hits in the evidence phase")
    ap.add_argument("--errors", type=int, default=3)
    ap.add_argument("--reqtrace-dir",
                    default="/tmp/rapids_tpu_bench_reqtrace")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "SERVING_r02.json"))
    args = ap.parse_args()

    # serving-process thread fairness: the default 5ms GIL switch
    # interval lets one executing request stall a concurrent hot-path
    # request for whole scheduling quanta; a latency-serving process
    # runs with a tighter interval (recorded in the artifact)
    sys.setswitchinterval(0.001)

    import shutil
    shutil.rmtree(args.reqtrace_dir, ignore_errors=True)

    port = _free_port()
    _sess, port = boot(port, args.reqtrace_dir, args.ratio)

    print("[1/4] hot-path vs uncached p50...", flush=True)
    hot = hot_vs_uncached(port, args.reps)
    print(f"  {hot}")

    print(f"[2/4] mixed hot/cold load, {args.clients} clients...",
          flush=True)
    load = mixed_load(port, args.clients, args.per_client)
    print(f"  {load}")

    print(f"[3/4] quota isolation ({args.hogs} hogs vs 1 neighbor)...",
          flush=True)
    iso = quota_isolation(port, args.samples, args.hogs)
    print(f"  {iso}")

    print("[4/4] request-tracing evidence (reqtrace armed)...",
          flush=True)
    rt, rt_fails = reqtrace_evidence(port, args.reqtrace_dir,
                                     args.ratio, args.errors, args.hits)
    print(f"  {rt}")
    for f in rt_fails:
        print(f"  FAIL: {f}")

    from spark_rapids_tpu.runtime import serving
    result = {
        "bench": "serving_load",
        "round": 18,
        "backend": "cpu-sim",
        "hot_vs_uncached": hot,
        "mixed_load": load,
        "quota_isolation": iso,
        "reqtrace": rt,
        "server": serving.server_doc(),
        "acceptance": {
            "hot_speedup_p50_ge_10x":
                hot["hot_speedup_p50"] >= 10.0,
            "neighbor_p99_ratio_le_1_25":
                iso["neighbor_p99_ratio"] <= 1.25,
            "clients_ge_8": load["clients"] >= 8,
            "reqtrace_evidence": not rt_fails,
        },
    }
    out = os.path.abspath(args.out)
    with open(out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {out}")
    ok = all(result["acceptance"].values())
    print(f"bench_serving: {'PASS' if ok else 'FAIL'} "
          f"{result['acceptance']}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
