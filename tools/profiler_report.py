"""Offline profiler report: aggregate a query's trace + event log + metrics.

The spark-rapids-tools profiling-report analog: given the artifacts a
traced action writes under spark.rapids.sql.trace.path
(query_<n>_trace.json — Chrome trace-event JSON, query_<n>_events.jsonl —
per-task GpuTaskMetrics rollups, query_<n>_metrics.json — the
last_metrics() per-exec snapshot), render a markdown report:

- top operators by EXCLUSIVE span time (nested spans subtracted, so an
  aggregate's time excludes the serde spans inside it);
- dispatch counts vs batch counts per exec (is the one-dispatch-per-batch
  contract holding?);
- per-stage fusion wins (dispatches saved by whole-stage fusion);
- spill / retry hot spots (bytes, events, which tasks);
- semaphore contention (wait distribution across tasks);
- a reconciliation table proving span totals match the GpuMetric timers
  (they share one instrumentation point, so deltas beyond rounding flag
  an instrumentation bug).

Run:  python tools/profiler_report.py <trace-dir> [--query N] [--json]
      python tools/profiler_report.py <query_N_trace.json>
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_CHROME_PHASES = {"X", "B", "E", "i", "I", "M", "C", "b", "e", "n", "s",
                  "t", "f", "P", "N", "O", "D"}


# ---------------------------------------------------------------------------
# loading & validation
# ---------------------------------------------------------------------------

def validate_chrome_trace(path: str) -> List[dict]:
    """Assert the file is Chrome trace-event JSON (object form). Returns
    the event list; raises ValueError on malformation."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace (no traceEvents)")
    events = doc["traceEvents"]
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"{path}: event {i} is not an object")
        if "ph" not in ev or "name" not in ev:
            raise ValueError(f"{path}: event {i} missing ph/name")
        if ev["ph"] not in _CHROME_PHASES:
            raise ValueError(f"{path}: event {i} unknown phase {ev['ph']!r}")
        if ev["ph"] in ("X", "i", "I", "C"):
            if "ts" not in ev or "pid" not in ev or "tid" not in ev:
                raise ValueError(f"{path}: event {i} missing ts/pid/tid")
        if ev["ph"] == "X" and "dur" not in ev:
            raise ValueError(f"{path}: complete event {i} missing dur")
    return events


def find_query(trace_dir: str, query_id: Optional[int] = None
               ) -> Tuple[int, str]:
    """Locate query_<n>_trace.json in a directory (latest id wins unless
    one is requested)."""
    found = {}
    for p in glob.glob(os.path.join(trace_dir, "query_*_trace.json")):
        m = re.match(r"query_(\d+)_trace\.json$", os.path.basename(p))
        if m:
            found[int(m.group(1))] = p
    if not found:
        raise FileNotFoundError(f"no query_*_trace.json under {trace_dir!r}")
    qid = query_id if query_id is not None else max(found)
    if qid not in found:
        raise FileNotFoundError(f"query {qid} not found in {trace_dir!r} "
                                f"(have {sorted(found)})")
    return qid, found[qid]


def load_artifacts(trace_path: str) -> Dict:
    """Load trace + sibling events.jsonl / metrics.json (both optional)."""
    events = validate_chrome_trace(trace_path)
    base = trace_path[: -len("_trace.json")]
    tasks, query_rec = [], None
    ev_path = base + "_events.jsonl"
    if os.path.exists(ev_path):
        with open(ev_path) as f:
            for line in f:
                if not line.strip():
                    continue
                rec = json.loads(line)
                if rec.get("type") == "task":
                    tasks.append(rec)
                elif rec.get("type") == "query":
                    query_rec = rec
    metrics = None
    m_path = base + "_metrics.json"
    if os.path.exists(m_path):
        with open(m_path) as f:
            metrics = json.load(f)
    return {"events": events, "tasks": tasks, "query": query_rec,
            "metrics": metrics, "trace_path": trace_path}


def cross_link_history(art: Dict, history_dir: str) -> Optional[dict]:
    """Resolve a trace to ITS query-history record through the shared
    plan digest (both the trace's query record and the history record
    carry it — no more filename-convention matching). Among runs of the
    same digest, prefer the record whose trace_paths point at this very
    trace file; otherwise take the run closest in wall-clock start."""
    q = art.get("query") or {}
    digest = q.get("plan_digest")
    if not digest:
        return None
    from spark_rapids_tpu.runtime.obs.history import QueryHistoryStore
    cands = QueryHistoryStore(history_dir).by_digest(digest)
    if not cands:
        return None
    tp = os.path.abspath(art["trace_path"])
    for rec in cands:
        rp = (rec.get("trace_paths") or {}).get("trace")
        if rp and os.path.abspath(rp) == tp:
            return rec
    t0 = q.get("wall_start_unix") or 0
    return min(cands, key=lambda r: abs((r.get("wall_start_unix") or 0)
                                        - t0))


# ---------------------------------------------------------------------------
# span analysis
# ---------------------------------------------------------------------------

def exclusive_times(events: List[dict]) -> Dict[str, dict]:
    """Per span name: count, total (inclusive) and EXCLUSIVE µs. Spans
    nest per (pid, tid) track; a span's exclusive time subtracts every
    child span directly contained in it."""
    by_track: Dict[Tuple, List[dict]] = {}
    for ev in events:
        if ev["ph"] == "X":
            by_track.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    out: Dict[str, dict] = {}

    def acct(name, total, excl):
        rec = out.setdefault(name, {"count": 0, "total_us": 0.0,
                                    "exclusive_us": 0.0})
        rec["count"] += 1
        rec["total_us"] += total
        rec["exclusive_us"] += excl

    for track in by_track.values():
        # sort by start asc, then duration desc so a parent precedes the
        # children that share its start timestamp
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[Tuple[dict, float]] = []  # (event, child_time)
        for ev in track:
            while stack and ev["ts"] >= stack[-1][0]["ts"] + stack[-1][0]["dur"]:
                done, child_t = stack.pop()
                acct(done["name"], done["dur"],
                     max(done["dur"] - child_t, 0.0))
                if stack:
                    stack[-1] = (stack[-1][0], stack[-1][1] + done["dur"])
            stack.append((ev, 0.0))
        while stack:
            done, child_t = stack.pop()
            acct(done["name"], done["dur"], max(done["dur"] - child_t, 0.0))
            if stack:
                stack[-1] = (stack[-1][0], stack[-1][1] + done["dur"])
    return out


def operator_rollup(span_stats: Dict[str, dict]) -> Dict[str, dict]:
    """Fold `ExecName.metricName` spans into per-operator totals."""
    ops: Dict[str, dict] = {}
    for name, rec in span_stats.items():
        op = name.split(".", 1)[0]
        dst = ops.setdefault(op, {"count": 0, "total_us": 0.0,
                                  "exclusive_us": 0.0})
        for k in ("count", "total_us", "exclusive_us"):
            dst[k] += rec[k]
    return ops


def reconcile(span_stats: Dict[str, dict], metrics: Optional[dict]
              ) -> List[dict]:
    """Span totals vs the GpuMetric timers they feed. One instrumentation
    point means the numbers must agree up to µs-rounding; a bigger delta
    is an instrumentation bug. Returns one row per (exec, time-metric)
    that appears in both."""
    if not metrics:
        return []
    metric_totals: Dict[str, int] = {}
    for exec_key, snap in metrics.items():
        op = exec_key.split("#", 1)[0]
        for mname, v in snap.items():
            if mname.lower().endswith("time"):
                metric_totals[f"{op}.{mname}"] = \
                    metric_totals.get(f"{op}.{mname}", 0) + int(v)
    rows = []
    for name, rec in sorted(span_stats.items()):
        if name not in metric_totals:
            continue
        metric_us = metric_totals[name] / 1000.0
        delta = abs(rec["total_us"] - metric_us)
        denom = max(rec["total_us"], metric_us, 1.0)
        rows.append({"name": name, "span_us": rec["total_us"],
                     "metric_us": metric_us,
                     "delta_pct": 100.0 * delta / denom})
    return rows


# ---------------------------------------------------------------------------
# metric-side analyses
# ---------------------------------------------------------------------------

_BATCH_KEYS = ("numInputBatches", "numOutputBatches")


def dispatch_vs_batches(metrics: Optional[dict]) -> List[dict]:
    """Per exec with a stageDispatches metric: dispatches vs batch count
    (the one-dispatch-per-batch contract)."""
    if not metrics:
        return []
    rows = []
    for exec_key, snap in metrics.items():
        if "stageDispatches" not in snap:
            continue
        batches = max((snap.get(k, 0) for k in _BATCH_KEYS), default=0)
        rows.append({"exec": exec_key,
                     "dispatches": snap["stageDispatches"],
                     "batches": batches})
    return rows


def fusion_wins(metrics: Optional[dict], events: List[dict]) -> List[dict]:
    """Dispatches saved by whole-stage fusion, per stage. Trace-driven:
    every FusedStageExec dispatch span carries stage_id + member count in
    its args, so each stage's savings are exact — (members−1) composed
    calls avoided per dispatch. Falls back to the metrics snapshot (count
    only, members unknown) when the trace has no fused spans (e.g. an
    ESSENTIAL-level trace)."""
    per_stage: Dict[int, dict] = {}
    for ev in events:
        if ev["ph"] == "X" and ev["name"].startswith("FusedStageExec("):
            args = ev.get("args") or {}
            sid = args.get("stage_id")
            if sid is None:
                continue
            rec = per_stage.setdefault(sid, {
                "exec": f"{ev['name']} [stage {sid}]",
                "members": args.get("members", 0), "dispatches": 0})
            rec["dispatches"] += 1
        elif ev["ph"] == "i" and ev["name"] == "stageDispatch" \
                and (ev.get("args") or {}).get("absorbed"):
            # absorbed-aggregate stages dispatch inside the agg's update
            # (no FusedStageExec span exists); their instants carry the
            # stage id and composed member count
            args = ev["args"]
            sid = args.get("stage_id")
            if sid is None:
                continue
            rec = per_stage.setdefault(sid, {
                "exec": f"absorbed agg chain [stage {sid}]",
                "members": args.get("members", 0), "dispatches": 0})
            rec["dispatches"] += 1
    rows = list(per_stage.values())
    if not rows and metrics:
        rows = [{"exec": exec_key, "dispatches": snap["stageDispatches"],
                 "members": None}
                for exec_key, snap in metrics.items()
                if exec_key.startswith("FusedStageExec")
                and "stageDispatches" in snap]
    for r in rows:
        r["saved_dispatches"] = ((r["members"] - 1) * r["dispatches"]
                                 if r.get("members") else None)
    return rows


def spill_retry_hotspots(events: List[dict], tasks: List[dict]) -> dict:
    inst = {"spillToHost": [], "spillToDisk": [], "retryOOM": [],
            "splitAndRetryOOM": []}
    for ev in events:
        if ev["ph"] == "i" and ev["name"] in inst:
            inst[ev["name"]].append(ev.get("args") or {})
    per_task = []
    for t in tasks:
        m = t.get("metrics", {})
        keys = ("retryCount", "splitAndRetryCount", "retryBlockTime",
                "retryWastedTime",
                "spillToHostBytes", "spillToDiskBytes",
                "spillToHostTime", "spillToDiskTime", "maxDeviceBytesHeld")
        if any(m.get(k) for k in keys):
            per_task.append({"task_id": t["task_id"],
                             "partition_id": t.get("partition_id"),
                             **{k: m[k] for k in keys if m.get(k)}})
    # retry accounting (satellite): the replayed-attempt split. First-
    # attempt time = the enclosing exec timers MINUS this wasted total —
    # reported separately so a retry storm reads as retry, not as a slow
    # operator.
    wasted_ns = sum(t.get("metrics", {}).get("retryWastedTime", 0)
                    for t in tasks)
    return {
        "spill_to_host_bytes": sum(a.get("bytes", 0)
                                   for a in inst["spillToHost"]),
        "spill_to_disk_bytes": sum(a.get("bytes", 0)
                                   for a in inst["spillToDisk"]),
        "spill_events": len(inst["spillToHost"]) + len(inst["spillToDisk"]),
        "retry_events": len(inst["retryOOM"]),
        "split_retry_events": len(inst["splitAndRetryOOM"]),
        "retry_wasted_ns": wasted_ns,
        "tasks": per_task,
    }


def semaphore_contention(tasks: List[dict], events: List[dict]) -> dict:
    waits = [t.get("metrics", {}).get("semaphoreWaitTime", 0)
             for t in tasks]
    acquires = [ev for ev in events
                if ev["ph"] == "i" and ev["name"] == "semaphoreAcquire"]
    waits_ns = sorted(waits)
    return {
        "tasks": len(waits),
        "acquires": len(acquires),
        "total_wait_ms": sum(waits) / 1e6,
        "max_wait_ms": (max(waits) / 1e6) if waits else 0.0,
        "p50_wait_ms": (waits_ns[len(waits_ns) // 2] / 1e6) if waits_ns
        else 0.0,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _fmt_us(us: float) -> str:
    return f"{us / 1000.0:.3f}"


def generate_report(art: Dict, top_n: int = 20,
                    history_rec: Optional[dict] = None) -> str:
    events, tasks, metrics = art["events"], art["tasks"], art["metrics"]
    spans = exclusive_times(events)
    ops = operator_rollup(spans)
    rec = reconcile(spans, metrics)
    disp = dispatch_vs_batches(metrics)
    wins = fusion_wins(metrics, events)
    hot = spill_retry_hotspots(events, tasks)
    sem = semaphore_contention(tasks, events)

    L = ["# Profiler report", ""]
    if art.get("query"):
        q = art["query"]
        L.append(f"query {q.get('query_id')} · "
                 f"{q.get('duration_ns', 0) / 1e6:.1f} ms wall · "
                 f"{q.get('n_tasks')} tasks · source "
                 f"`{os.path.basename(art['trace_path'])}`")
        L.append("")

    L += ["## Top operators by exclusive time", "",
          "| operator | spans | exclusive ms | inclusive ms |",
          "|---|---:|---:|---:|"]
    for op, r in sorted(ops.items(), key=lambda kv: -kv[1]["exclusive_us"]
                        )[:top_n]:
        L.append(f"| {op} | {r['count']} | {_fmt_us(r['exclusive_us'])} "
                 f"| {_fmt_us(r['total_us'])} |")

    if disp:
        L += ["", "## Dispatches vs batches (one-dispatch-per-batch "
              "contract)", "",
              "| exec | stageDispatches | batches |", "|---|---:|---:|"]
        for r in disp:
            L.append(f"| {r['exec']} | {r['dispatches']} "
                     f"| {r['batches']} |")

    if wins:
        L += ["", "## Whole-stage fusion wins", "",
              "| fused stage | composed dispatches | members "
              "| dispatches saved |", "|---|---:|---:|---:|"]
        for r in wins:
            L.append(f"| {r['exec']} | {r['dispatches']} "
                     f"| {r['members'] or '?'} "
                     f"| {'?' if r['saved_dispatches'] is None else r['saved_dispatches']} |")

    L += ["", "## Spill / retry hot spots", "",
          f"- spill to host: {hot['spill_to_host_bytes']} B over "
          f"{hot['spill_events']} spill event(s); to disk: "
          f"{hot['spill_to_disk_bytes']} B",
          f"- retry OOMs: {hot['retry_events']}; split-and-retry: "
          f"{hot['split_retry_events']}; replayed-attempt time "
          f"{hot['retry_wasted_ns'] / 1e6:.3f} ms (subtract from exec "
          f"timers for first-attempt time)"]
    if hot["tasks"]:
        L += ["", "| task | partition | accumulators |", "|---|---|---|"]
        for t in hot["tasks"][:top_n]:
            acc = ", ".join(f"{k}={v}" for k, v in t.items()
                            if k not in ("task_id", "partition_id"))
            L.append(f"| {t['task_id']} | {t['partition_id']} | {acc} |")

    L += ["", "## Semaphore contention", "",
          f"- {sem['tasks']} task(s), {sem['acquires']} traced acquire(s)",
          f"- total wait {sem['total_wait_ms']:.3f} ms · "
          f"max {sem['max_wait_ms']:.3f} ms · "
          f"p50 {sem['p50_wait_ms']:.3f} ms"]

    if history_rec is not None:
        L += ["", "## History cross-link (by plan digest)", "",
              f"- history query {history_rec.get('query_id')} · status "
              f"{history_rec.get('status')} · wall "
              f"{history_rec.get('duration_ns', 0) / 1e6:.1f} ms · "
              f"digest `{history_rec.get('plan_digest')}`"]
        if history_rec.get("fallback_reasons"):
            L.append(f"- fallbacks: "
                     f"{len(history_rec['fallback_reasons'])}")

    if rec:
        L += ["", "## Trace ↔ metric reconciliation", "",
              "spans and GpuMetric timers share one instrumentation "
              "point; deltas beyond rounding indicate a bug.", "",
              "| span | span total ms | metric total ms | delta % |",
              "|---|---:|---:|---:|"]
        for r in rec:
            L.append(f"| {r['name']} | {_fmt_us(r['span_us'])} "
                     f"| {_fmt_us(r['metric_us'])} "
                     f"| {r['delta_pct']:.2f} |")

    L.append("")
    return "\n".join(L)


def analyze(art: Dict) -> Dict:
    """Machine-readable version of the report (for --json and tests)."""
    spans = exclusive_times(art["events"])
    return {
        "spans": spans,
        "operators": operator_rollup(spans),
        "reconciliation": reconcile(spans, art["metrics"]),
        "dispatch_vs_batches": dispatch_vs_batches(art["metrics"]),
        "fusion_wins": fusion_wins(art["metrics"], art["events"]),
        "hotspots": spill_retry_hotspots(art["events"], art["tasks"]),
        "semaphore": semaphore_contention(art["tasks"], art["events"]),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="trace directory or query_N_trace.json")
    ap.add_argument("--query", type=int, default=None,
                    help="query id (directory mode; default: latest)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable analysis instead")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--history", default=None, metavar="DIR",
                    help="query-history dir: cross-link this trace to its "
                    "history record via the shared plan digest")
    args = ap.parse_args()
    path = args.path
    if os.path.isdir(path):
        _, path = find_query(path, args.query)
    art = load_artifacts(path)
    hist = (cross_link_history(art, args.history)
            if args.history else None)
    if args.json:
        doc = analyze(art)
        if hist is not None:
            doc["history"] = hist
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(generate_report(art, top_n=args.top, history_rec=hist))
    return 0


if __name__ == "__main__":
    sys.exit(main())
