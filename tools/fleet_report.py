"""Multi-replica fleet view over a SHARED query history store.

PR 16's serving layer made N replicas append to one
`spark.rapids.obs.historyDir` (the O_APPEND JSONL store interleaves
whole lines across processes), and the request-tracing round stamped
every query and result-cache-hit record with its `replica_id`
(``spark.rapids.obs.replicaId``, default pid-<pid>) and the W3C
`trace_id` of the serving request that carried it. This tool answers
the fleet operator's question the per-replica pages cannot: **for the
same plan digest, do the replicas agree?**

For every plan digest it splits the fleet's runs per replica —
run count, p50/p99 wall, compile seconds (the attribution bucket:
a replica re-compiling a digest the others replay warm is THE
warm-boot regression signature), SLO breaches, failure counts, and the
result-cache hit/execute split — then flags digests whose slowest
replica p99 exceeds the fastest by more than the skew factor.

It also merges the replicas' exported per-request timelines
(``spark.rapids.obs.reqtrace.path`` dirs): every `req_*.json` artifact
is listed with its sampling verdict and joined back to the history
records sharing its trace id, so a cross-replica investigation starts
from one page.

Run:  python tools/fleet_report.py <historyDir>
          [--reqtrace DIR ...] [--skew 1.5] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_rapids_tpu.runtime.obs.history import (  # noqa: E402
    QueryHistoryStore,
)

#: req_<seq>_<verdict>_<trace8>.json — the reqtrace export pair's
#: Chrome-trace half (runtime/obs/reqtrace.py names both halves)
_ARTIFACT_RE = re.compile(
    r"^req_(\d+)_([a-z_]+)_([0-9a-f]{8})\.json$")

#: replica key for records predating the replica_id stamp (or engines
#: run with obs history but no serving layer)
UNKNOWN_REPLICA = "(unknown)"


def _pctl(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[i]


def _compile_seconds(rec: dict) -> float:
    attr = rec.get("attribution") or {}
    buckets = attr.get("buckets") or {}
    try:
        return float(buckets.get("compile") or 0.0)
    except (TypeError, ValueError):
        return 0.0


def scan_reqtrace(dirs) -> List[dict]:
    """List exported per-request timelines across the replicas' reqtrace
    dirs: [{dir, file, seq, verdict, trace8}], newest last."""
    out: List[dict] = []
    for d in dirs:
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        for name in names:
            m = _ARTIFACT_RE.match(name)
            if m is None:
                continue
            out.append({"dir": d, "file": os.path.join(d, name),
                        "seq": int(m.group(1)), "verdict": m.group(2),
                        "trace8": m.group(3)})
    return out


def fleet_summary(records: List[dict], reqtrace_dirs=(),
                  skew_factor: float = 1.5) -> dict:
    """The whole fleet doc: per-replica totals, the per-digest
    cross-replica split, skew flags, and the merged reqtrace artifact
    index joined to history trace ids."""
    queries = [r for r in records if r.get("type") == "query"]
    hits = [r for r in records if r.get("type") == "result_cache_hit"]

    def replica(rec) -> str:
        return rec.get("replica_id") or UNKNOWN_REPLICA

    def mesh_key(rec) -> str:
        """Compact mesh-shape label ("8 part" / "single"). Multichip
        records carry rec["mesh"] = {n_devices, axes}; records without
        it ran single-device."""
        m = rec.get("mesh")
        if not isinstance(m, dict):
            return "single"
        axes = m.get("axes") or []
        return f"{m.get('n_devices', '?')} {'x'.join(str(a) for a in axes)}"

    # ---- per-replica totals ------------------------------------------------
    totals: Dict[str, dict] = {}
    for r in queries:
        t = totals.setdefault(replica(r), {
            "queries": 0, "ok": 0, "failed": 0, "cancelled": 0,
            "degraded": 0, "slo_breaches": 0, "cache_hits": 0,
            "compile_s": 0.0, "_walls": [], "_meshes": set()})
        t["queries"] += 1
        t["_meshes"].add(mesh_key(r))
        st = r.get("status", "?")
        if st in t:
            t[st] += 1
        if r.get("slo_breach") is not None:
            t["slo_breaches"] += 1
        t["compile_s"] += _compile_seconds(r)
        t["_walls"].append(r.get("duration_ns", 0) / 1e6)
    for r in hits:
        t = totals.setdefault(replica(r), {
            "queries": 0, "ok": 0, "failed": 0, "cancelled": 0,
            "degraded": 0, "slo_breaches": 0, "cache_hits": 0,
            "compile_s": 0.0, "_walls": [], "_meshes": set()})
        t["cache_hits"] += 1
    for t in totals.values():
        walls = sorted(t.pop("_walls"))
        t["p50_ms"] = round(_pctl(walls, 0.50), 3)
        t["p99_ms"] = round(_pctl(walls, 0.99), 3)
        t["compile_s"] = round(t["compile_s"], 3)
        t["meshes"] = sorted(t.pop("_meshes")) or ["single"]

    # ---- per-digest x per-replica split ------------------------------------
    digests: Dict[str, Dict[str, dict]] = {}
    for r in queries:
        d = r.get("plan_digest")
        if not d:
            continue
        cell = digests.setdefault(d, {}).setdefault(replica(r), {
            "runs": 0, "failed": 0, "slo_breaches": 0, "cache_hits": 0,
            "compile_s": 0.0, "_walls": [], "trace_ids": [],
            "_meshes": set()})
        cell["runs"] += 1
        cell["_meshes"].add(mesh_key(r))
        if r.get("status") not in ("ok", "degraded"):
            cell["failed"] += 1
        if r.get("slo_breach") is not None:
            cell["slo_breaches"] += 1
        cell["compile_s"] += _compile_seconds(r)
        cell["_walls"].append(r.get("duration_ns", 0) / 1e6)
        if r.get("trace_id"):
            cell["trace_ids"].append(r["trace_id"])
    for r in hits:
        d = r.get("plan_digest")
        if not d:
            continue
        cell = digests.setdefault(d, {}).setdefault(replica(r), {
            "runs": 0, "failed": 0, "slo_breaches": 0, "cache_hits": 0,
            "compile_s": 0.0, "_walls": [], "trace_ids": [],
            "_meshes": set()})
        cell["cache_hits"] += 1
        if r.get("trace_id"):
            cell["trace_ids"].append(r["trace_id"])
    skewed: List[dict] = []
    for d, per in digests.items():
        # p99s grouped by mesh shape: a 1-device replica being slower
        # than an 8-device one on a shuffle-heavy digest is the
        # EXPECTED scaling, not a fleet anomaly — only replicas on the
        # same mesh are comparable (history records carry rec["mesh"])
        p99s_by_mesh: Dict[str, Dict[str, float]] = {}
        for rep, cell in per.items():
            walls = sorted(cell.pop("_walls"))
            cell["p50_ms"] = round(_pctl(walls, 0.50), 3)
            cell["p99_ms"] = round(_pctl(walls, 0.99), 3)
            cell["compile_s"] = round(cell["compile_s"], 3)
            cell["trace_ids"] = cell["trace_ids"][-5:]  # newest few
            cell["meshes"] = sorted(cell.pop("_meshes")) or ["single"]
            if cell["runs"]:
                for mk in cell["meshes"]:
                    p99s_by_mesh.setdefault(mk, {})[rep] = cell["p99_ms"]
        for mk, p99s in p99s_by_mesh.items():
            if len(p99s) < 2:
                continue
            lo_rep = min(p99s, key=p99s.get)
            hi_rep = max(p99s, key=p99s.get)
            lo, hi = p99s[lo_rep], p99s[hi_rep]
            if lo > 0 and hi > lo * skew_factor:
                skewed.append({"plan_digest": d, "mesh": mk,
                               "fast": lo_rep,
                               "slow": hi_rep, "fast_p99_ms": lo,
                               "slow_p99_ms": hi,
                               "ratio": round(hi / lo, 2)})
    skewed.sort(key=lambda s: -s["ratio"])

    # ---- reqtrace artifact merge + history join ----------------------------
    artifacts = scan_reqtrace(reqtrace_dirs)
    by_trace8: Dict[str, str] = {}
    for r in queries + hits:
        tid = r.get("trace_id")
        if tid:
            by_trace8[tid[:8]] = tid
    for a in artifacts:
        a["trace_id"] = by_trace8.get(a["trace8"])

    return {
        "replicas": sorted(totals),
        "totals": totals,
        "digests": digests,
        "skewed": skewed,
        "skew_factor": skew_factor,
        "reqtrace": artifacts,
    }


def render_text(doc: dict) -> str:
    lines = [f"fleet: {len(doc['replicas'])} replica(s): "
             + ", ".join(doc["replicas"]), ""]
    lines.append(f"{'replica':<24} {'queries':>8} {'hits':>6} "
                 f"{'failed':>7} {'slo':>4} {'p50 ms':>9} {'p99 ms':>9} "
                 f"{'compile s':>10}  {'mesh'}")
    for rep in doc["replicas"]:
        t = doc["totals"][rep]
        lines.append(f"{rep:<24} {t['queries']:>8} {t['cache_hits']:>6} "
                     f"{t['failed']:>7} {t['slo_breaches']:>4} "
                     f"{t['p50_ms']:>9.1f} {t['p99_ms']:>9.1f} "
                     f"{t['compile_s']:>10.3f}  "
                     f"{', '.join(t.get('meshes', ['single']))}")
    lines.append("")
    for d, per in sorted(doc["digests"].items()):
        lines.append(f"digest {d}:")
        for rep in sorted(per):
            c = per[rep]
            lines.append(
                f"  {rep:<22} runs={c['runs']:<4} hits={c['cache_hits']:<4}"
                f" failed={c['failed']:<3} slo={c['slo_breaches']:<3}"
                f" p50={c['p50_ms']:.1f}ms p99={c['p99_ms']:.1f}ms"
                f" compile={c['compile_s']:.3f}s"
                f" mesh={','.join(c.get('meshes', ['single']))}")
    if doc["skewed"]:
        lines.append("")
        lines.append(f"cross-replica skew (p99 ratio > "
                     f"{doc['skew_factor']}x, same mesh only):")
        for s in doc["skewed"]:
            lines.append(f"  {s['plan_digest']} [{s.get('mesh', 'single')}]:"
                         f" {s['slow']} "
                         f"{s['slow_p99_ms']:.1f}ms vs {s['fast']} "
                         f"{s['fast_p99_ms']:.1f}ms ({s['ratio']}x)")
    if doc["reqtrace"]:
        lines.append("")
        lines.append(f"per-request timelines ({len(doc['reqtrace'])}):")
        for a in doc["reqtrace"]:
            join = a["trace_id"] or f"{a['trace8']}… (no history record)"
            lines.append(f"  [{a['verdict']:<17}] {join}  {a['file']}")
    return "\n".join(lines) + "\n"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("history_dir",
                    help="the replicas' SHARED spark.rapids.obs.historyDir")
    ap.add_argument("--reqtrace", action="append", default=[],
                    metavar="DIR",
                    help="a replica's spark.rapids.obs.reqtrace.path dir "
                    "(repeatable); defaults to <historyDir>/reqtrace "
                    "when present")
    ap.add_argument("--skew", type=float, default=1.5,
                    help="flag digests whose slowest replica p99 exceeds "
                    "the fastest by this factor (default 1.5)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full summary as JSON")
    args = ap.parse_args()
    records = QueryHistoryStore(args.history_dir).read_all()
    dirs = list(args.reqtrace)
    default_rt = os.path.join(args.history_dir, "reqtrace")
    if not dirs and os.path.isdir(default_rt):
        dirs = [default_rt]
    doc = fleet_summary(records, reqtrace_dirs=dirs,
                        skew_factor=args.skew)
    if args.json:
        print(json.dumps(doc, indent=1, default=str))
    else:
        sys.stdout.write(render_text(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
