"""Micro-benchmark: whole-stage vertical fusion vs per-operator dispatch,
end-to-end on the CPU backend (tools/bench_exchange.py's shape).

Three measurements over a filter + project + group-by pipeline fed by
MANY small batches (the dispatch-bound regime the fusion pass targets —
on the tunneled TPU every dispatch costs milliseconds; the CPU backend's
per-dispatch overhead is the proxy):

1. pipeline: the full query through the session API (collect), int group
   key — scan upload and arrow hand-back included, so the fusion win is
   diluted by shared I/O;
2. chain_stage (direct exec drive over DEVICE-RESIDENT batches, the
   bench_exchange.py idiom): the Filter→Project stage alone — fused it is
   ONE dispatch per batch (FusedStageExec), unfused two;
3. partial_agg_stage (direct drive, device-resident, float group key so
   the aggregate takes the general update path): Filter→Project→partial-
   HashAggregate — fused, the WHOLE stage is one dispatch per batch
   (HashAggregateExec.pre_chain), unfused three.

Run:  python tools/bench_fusion.py [--rows 400000] [--batch 2048]
                                   [--parts 4] [--reps 7]

Prints per-mode wall-clock and a JSON summary line; exits nonzero if the
fused and unfused pipelines disagree on query results (they must be
identical).
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402


def _table(rows: int) -> pa.Table:
    rng = np.random.default_rng(11)
    return pa.table({
        "k": rng.integers(0, 2000, rows),
        "g": rng.uniform(0, 64, rows).round(0),  # float key: general agg
        "v": rng.integers(-(1 << 30), 1 << 30, rows),
        "d": rng.uniform(-1e6, 1e6, rows),
    })


def _session(fused: bool, batch_rows: int):
    from spark_rapids_tpu.sql.session import TpuSession
    return TpuSession({
        "spark.rapids.sql.stageFusion.enabled": str(fused).lower(),
        "spark.rapids.sql.reader.batchSizeRows": str(batch_rows),
    })


def _query(s, t: pa.Table, parts: int, key: str, grouped: bool):
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.sql import functions as F
    df = (s.create_dataframe(t, num_partitions=parts)
          .filter((col("v") > lit(-(1 << 29))) & (col("d") < lit(9e5)))
          .select(col(key), (col("v") % lit(9973)).alias("m"),
                  (col("d") * lit(0.5) + lit(1.0)).alias("dd")))
    if grouped:
        df = df.group_by(col(key)).agg(F.sum("m").alias("sm"),
                                       F.count().alias("n"))
    return df


def _norm(rows, key):
    def k(r):
        v = r[key]
        bad = v is None or (isinstance(v, float) and math.isnan(v))
        return (bad, 0 if bad else v)
    return sorted(rows, key=k)


def _device_batches(t: pa.Table, batch_rows: int):
    from spark_rapids_tpu.columnar.batch import from_arrow
    batches = [from_arrow(t.slice(o, batch_rows))
               for o in range(0, t.num_rows, batch_rows)]
    jax.block_until_ready(jax.tree_util.tree_leaves(batches))
    return batches


def _reroot(chain_root, source):
    """Replace the chain's scan leaf with a pre-materialized source."""
    from spark_rapids_tpu.exec import tpu_nodes as X
    cur = chain_root
    while cur.children and not isinstance(cur.children[0],
                                          X.InMemoryScanExec):
        cur = cur.children[0]
    cur.children = [source]
    return chain_root


def _paired_best(run_fused, run_unfused, reps: int):
    """Interleave fused/unfused reps (ABBA) so machine-load drift lands on
    both modes equally; report the best of each."""
    best = {"fused": float("inf"), "unfused": float("inf")}
    order = [("fused", run_fused), ("unfused", run_unfused)]
    for i in range(reps):
        for mode, run in (order if i % 2 == 0 else reversed(order)):
            t0 = time.perf_counter()
            run()
            best[mode] = min(best[mode], time.perf_counter() - t0)
    return best["fused"], best["unfused"]


def make_pipeline(t, fused, parts, batch_rows, batches):
    """(run, result) for the full query through the session API."""
    def run():
        s = _session(fused, batch_rows)
        return _query(s, t, parts, "k", grouped=True).collect().to_pylist()
    return run, lambda: _norm(run(), "k")


def make_chain_stage(t, fused, parts, batch_rows, batches):
    """(run, result) for the Filter→Project stage over device batches."""
    from spark_rapids_tpu.columnar.batch import to_arrow
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.runtime.task import TaskContext

    s = _session(fused, batch_rows)
    df = _query(s, t, 1, "k", grouped=False)
    root, _ = convert_plan(df.plan, s.conf)
    _reroot(root, X._MaterializedExec(df.plan, batches, s.conf))

    def drain(rows=None):
        outs = []
        with TaskContext(partition_id=0) as ctx:
            for b in root.execute_partition(ctx, 0):
                if rows is not None:
                    rows.extend(to_arrow(b, ["k", "m", "dd"]).to_pylist())
                else:
                    outs.extend(jax.tree_util.tree_leaves(b))
        jax.block_until_ready(outs)

    def result():
        rows = []
        drain(rows)
        return _norm(rows, "k")

    return drain, result


def make_partial_agg_stage(t, fused, parts, batch_rows, batches):
    """(run, result) for Filter→Project→partial-HashAggregate (float key:
    the general update path, so fusion composes the WHOLE stage)."""
    from spark_rapids_tpu.columnar.batch import to_arrow
    from spark_rapids_tpu.exec import tpu_nodes as X
    from spark_rapids_tpu.exec.stage_fusion import fuse_stages
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.plan.overrides import convert_plan
    from spark_rapids_tpu.runtime.task import TaskContext

    s = _session(fused, batch_rows)
    df = _query(s, t, 1, "g", grouped=True)
    node = df.plan
    while not isinstance(node, P.Aggregate):
        node = node.children[0]
    chain_root, _ = convert_plan(node.children[0], s.conf)
    _reroot(chain_root,
            X._MaterializedExec(node.children[0], batches, s.conf))
    agg = X.HashAggregateExec(node, [chain_root], s.conf, mode="partial")
    root = fuse_stages(agg, s.conf)
    names = [f.name for f in root.state_fields()]

    def drain(rows=None):
        outs = []
        with TaskContext(partition_id=0) as ctx:
            for b in root.execute_partition(ctx, 0):
                if rows is not None:
                    rows.extend(to_arrow(b, names).to_pylist())
                else:
                    outs.extend(jax.tree_util.tree_leaves(b))
        jax.block_until_ready(outs)

    def result():
        rows = []
        drain(rows)
        return _norm(rows, "g")

    return drain, result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--batch", type=int, default=2048,
                    help="rows per batch (small = dispatch-bound)")
    ap.add_argument("--parts", type=int, default=4)
    ap.add_argument("--reps", type=int, default=7)
    args = ap.parse_args()
    t = _table(args.rows)
    batches = _device_batches(t, args.batch)

    out = {"rows": args.rows, "batch_rows": args.batch,
           "parts": args.parts, "n_batches": len(batches)}
    ok = True
    scenarios = [("pipeline", make_pipeline),
                 ("chain_stage", make_chain_stage),
                 ("partial_agg_stage", make_partial_agg_stage)]
    for name, make in scenarios:
        run_f, res_f = make(t, True, args.parts, args.batch, batches)
        run_u, res_u = make(t, False, args.parts, args.batch, batches)
        same = res_f() == res_u()  # warms both kernel caches too
        bf, bu = _paired_best(run_f, run_u, args.reps)
        ok = ok and same
        print(f"{name:18s} fused: {bf * 1e3:8.1f} ms   "
              f"unfused: {bu * 1e3:8.1f} ms   ({bu / bf:.2f}x)")
        out[name] = {"fused_s": round(bf, 4), "unfused_s": round(bu, 4),
                     "speedup": round(bu / bf, 3),
                     "identical_results": same}

    print(json.dumps(out))
    if not ok:
        print("FAIL: fused and unfused query results differ",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
