#!/usr/bin/env bash
# CI gate: tpulint, docs drift, trace-overhead smoke, sanitizer smoke,
# chaos smoke, obs smoke, flight smoke, pipeline smoke, compile smoke,
# audit smoke, aqe smoke, decode smoke, serving smoke, reqtrace smoke,
# multichip smoke, tier-1 tests.
#
#   tools/ci_check.sh            # everything (tier-1 last: ~13 min)
#   tools/ci_check.sh --fast     # skip tier-1 (lint + docs drift + smokes)
#
# Mirrors the reference's build checks: generated docs must match the
# committed ones (SupportedOpsDocs/RapidsConf.help regeneration), the
# observability layer must stay free when disabled, and the tier-1 suite
# (the exact ROADMAP.md command) must pass.
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
step() { echo; echo "=== $1 ==="; }

step "tpulint --strict (engine-invariant static analysis, <10s budget)"
if ! python tools/tpulint.py --strict; then
    fail=1
fi

step "docs drift (tools/gen_docs.py output == committed docs)"
if ! python tools/gen_docs.py >/dev/null; then
    echo "FAIL: gen_docs.py errored"; fail=1
elif ! git diff --exit-code -- docs/configs.md docs/supported_ops.md \
        docs/metrics.md tools/generated_files; then
    echo "FAIL: regenerate docs with 'python tools/gen_docs.py' and commit"
    fail=1
else
    echo "OK: docs match the registries"
fi

step "trace-overhead smoke (disabled <2% of no-trace baseline; enabled run emits Perfetto-loadable JSON)"
if ! python tools/trace_overhead.py; then
    fail=1
fi

step "sanitizer smoke (disabled lock proxies <2%; seeded inversion + held-lock caught; clean engine silent)"
if ! python tools/sanitizer_smoke.py; then
    fail=1
fi

step "chaos smoke (seeded fault injection over NDS probe queries: every run ok/degraded with clean-run results, no hangs/leaks; cancellation storm: cancels mid-scan/mid-shuffle/mid-retry/while-queued land the cancelled terminal state within 2x the longest checkpoint interval with zero stranded permits and device bytes at baseline; fault-hook + lifecycle-checkpoint overhead <2%)"
if ! python tools/chaos_smoke.py; then
    fail=1
fi

step "obs smoke (/metrics scrape while a query runs, /healthz degraded flip, history round-trip, monotone mid-flight /queries progress to 100%, sampler on /metrics + in flight dumps, live-layer overhead <2%)"
if ! python tools/obs_smoke.py; then
    fail=1
fi

step "flight smoke (always-on recorder overhead <2%; failure/degrade/SLO/breaker triggers each dump a readable Chrome trace; clean runs silent; attribution reconciles <1%)"
if ! python tools/flight_smoke.py; then
    fail=1
fi

step "pipeline smoke (overlap engaged on a multi-batch query, LIMIT cancel, no thread leak)"
if ! python tools/pipeline_smoke.py; then
    fail=1
fi

step "compile smoke (cross-process persistent-cache hits; warm-history AOT warmup drops first-run compile_seconds >=5x; warm choke-point overhead <2%)"
if ! python tools/compile_smoke.py; then
    fail=1
fi

step "audit smoke (kernel cost auditor: audited NDS pass reproduces the golden cost signatures byte-identically; two consecutive generator runs identical; armed steady-state overhead <2%; roofline reconciles with attribution device_compute <1%)"
# --fast replays a sorted prefix against the golden instead of the full
# ~340-490s audited 98-query pass (which stays on the default path)
audit_args=""
if [[ "${1:-}" == "--fast" ]]; then
    audit_args="--quick"
fi
if ! python tools/audit_smoke.py $audit_args; then
    fail=1
fi

step "aqe smoke (q3join/q72shfl probes cold then history-warm: broadcast conversion + warm measured-cost collapse fire, results byte-identical to AQE-off, disabled hook sites <2% by count x delta)"
if ! python tools/aqe_smoke.py; then
    fail=1
fi

step "decode smoke (device-side parquet decode: probe-query parity on/off byte-identical, encoded<decoded bytes shift with per-column string fallback, DeviceDecodeScanExec fused into the stage, disabled-path conf gate <2% by count x delta)"
if ! python tools/decode_smoke.py; then
    fail=1
fi

step "serving smoke (query server: 4 concurrent clients byte-identical to solo, saturated intake 429 + HTTP cancel 499, replica warm-boot zero backend compiles on the first hot-digest request, disabled-path install read <2% by count x delta)"
if ! python tools/serving_smoke.py; then
    fail=1
fi

step "reqtrace smoke (per-request tracing: errors/SLO breaches 100% exported, hot cache hits kept exactly at the seeded sampleRatio, disabled + armed paths <2% by count x delta, exported timelines Chrome-trace + OTLP valid with serving<->exec spans joined by query id)"
if ! python tools/reqtrace_smoke.py; then
    fail=1
fi

step "multichip smoke (sharded execution over 8 virtual devices: probe parity on/off byte-identical, narrow chain planned as ShardedStageExec with shardWaves, shuffle spends time in the in-program all_to_all, disabled-path conf gate <2% by count x delta)"
if ! python tools/multichip_smoke.py; then
    fail=1
fi

if [[ "${1:-}" != "--fast" ]]; then
    step "re-homed @slow representatives (tools/slow_rehomed.txt: parametrizations tier-1 deselected in the round-18 headroom squeeze)"
    if ! grep -v '^#' tools/slow_rehomed.txt | grep -v '^$' | \
            xargs env JAX_PLATFORMS=cpu python -m pytest -q \
            -p no:cacheprovider -p no:xdist -p no:randomly; then
        echo "FAIL: re-homed @slow set"
        fail=1
    fi

    step "tier-1 tests (ROADMAP.md command)"
    set -o pipefail; rm -f /tmp/_t1.log
    timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
    rc=${PIPESTATUS[0]}
    echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
        | tr -cd . | wc -c)
    if [[ $rc -ne 0 ]]; then
        echo "FAIL: tier-1 exited $rc"
        fail=1
    fi
fi

echo
if [[ $fail -ne 0 ]]; then
    echo "ci_check: FAIL"
    exit 1
fi
echo "ci_check: PASS"
