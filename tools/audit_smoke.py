"""CI gate for the kernel cost auditor (analysis/kernel_audit.py).

Four gates:

1. **Golden replay**: a full audited NDS pass (the exact
   gen_dispatch_budgets.py cost-pass recipe — fresh interpreter, fresh
   session+tables, cold compile cache, sorted query order) must
   reproduce tests/golden_plans/cost_signatures.json BYTE-IDENTICALLY.
   Because the committed artifact was itself written by that generator,
   this IS the "two consecutive generator runs are byte-identical"
   determinism statement — and it catches any kernel that silently
   changed its flops/bytes even when wall time hides it
   (~340-490s: every query re-traces from cold and every traced shape
   pays one lower+compile at resolution).
2. **Short-interval determinism**: two further consecutive generator
   runs over a sorted prefix (--prefix, default 4) must be
   byte-identical to EACH OTHER — proves the property holds between two
   fresh processes run back to back, independent of the committed file.
3. **Steady-state overhead** (< 2%, count x delta — the
   trace_overhead/sanitizer_smoke methodology): the armed audit's only
   per-dispatch cost is one choke-point note(); count the get() calls a
   warm audited drive makes, price one note() in a tight loop, and
   bound count*delta against the drive wall. The trace-time hook itself
   contributes nothing here by construction — steady dispatches never
   execute traced Python.
4. **Surfaces**: an audited query must produce an audit summary, a
   roofline doc whose device seconds reconcile with the attribution
   device_compute bucket within 1%, a roofline section in
   explain(mode="analyze"), and zero findings.

    python tools/audit_smoke.py [--quick] [--prefix N]

--quick replaces the full golden replay with a prefix replay against
the committed file (for local iteration; CI runs full).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_fast_math" not in _flags:
    _flags = (_flags + " --xla_cpu_enable_fast_math=false").strip()
os.environ["XLA_FLAGS"] = _flags

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
GEN = os.path.join(ROOT, "tools", "gen_dispatch_budgets.py")
GOLDEN = os.path.join(ROOT, "tests", "golden_plans",
                      "cost_signatures.json")
OVERHEAD_BAR_PCT = 2.0
RECONCILE_BAR = 0.01


def _run_generator(out_path: str, limit=None) -> None:
    cmd = [sys.executable, GEN, "--signatures-only", "--out", out_path]
    if limit:
        cmd += ["--limit", str(limit)]
    t0 = time.time()
    r = subprocess.run(cmd, cwd=ROOT)
    if r.returncode != 0:
        raise SystemExit(f"FAIL: generator exited {r.returncode}")
    print(f"  generator pass ({limit or 'full'}) took "
          f"{time.time() - t0:.1f}s")


def _diff_against(tmp_path: str, golden_path: str, limit=None) -> list:
    from spark_rapids_tpu.analysis.kernel_audit import compare_signature
    got = json.load(open(tmp_path))
    want = json.load(open(golden_path))
    gsig, asig = want["cost_signatures"], got["cost_signatures"]
    names = sorted(gsig, key=lambda s: int(s))
    if limit:
        names = names[:limit]
    diffs = []
    for qn in names:
        diffs += compare_signature(f"q{qn}", gsig.get(qn), asig.get(qn))
    for qn in sorted(set(asig) - set(gsig), key=lambda s: int(s)):
        if not limit or int(qn) <= int(names[-1]):
            diffs.append(f"q{qn}: present in run but not in golden")
    if sorted(got.get("kernel_primitives", [])) != \
            sorted(want.get("kernel_primitives", [])):
        diffs.append("kernel_primitives roster drifted: regenerate "
                     "goldens")
    return diffs


def gate_golden_replay(quick: bool, prefix: int) -> None:
    what = f"prefix-{prefix}" if quick else "full"
    print(f"[audit_smoke] golden replay ({what}) vs committed "
          f"cost_signatures.json")
    tmp = os.path.join(ROOT, f"_audit_smoke_golden.json")
    try:
        _run_generator(tmp, limit=prefix if quick else None)
        diffs = _diff_against(tmp, GOLDEN,
                              limit=prefix if quick else None)
        if diffs:
            print("\n".join("  " + d for d in diffs[:40]))
            raise SystemExit(
                f"FAIL: {len(diffs)} cost-signature regressions")
        if not quick:
            # full replay: the bytes themselves must match (dict-level
            # equality already passed; byte identity is the determinism
            # statement vs the committed generator run)
            if open(tmp, "rb").read() != open(GOLDEN, "rb").read():
                raise SystemExit(
                    "FAIL: full replay differs from the committed "
                    "artifact at byte level (ordering/rounding drift)")
        print(f"  OK: signatures match the golden pin")
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def gate_determinism(prefix: int) -> None:
    print(f"[audit_smoke] determinism: two consecutive generator runs "
          f"(prefix {prefix}) byte-identical")
    a = os.path.join(ROOT, "_audit_smoke_det_a.json")
    b = os.path.join(ROOT, "_audit_smoke_det_b.json")
    try:
        _run_generator(a, limit=prefix)
        _run_generator(b, limit=prefix)
        ba, bb = open(a, "rb").read(), open(b, "rb").read()
        if ba != bb:
            raise SystemExit("FAIL: two consecutive generator runs "
                             "produced different cost_signatures")
        print(f"  OK: {len(ba)} bytes, identical")
    finally:
        for p in (a, b):
            if os.path.exists(p):
                os.unlink(p)


def _drive_session():
    import numpy as np
    import pyarrow as pa
    from spark_rapids_tpu.expr.core import col, lit
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.sql.session import TpuSession
    sess = TpuSession({"spark.rapids.obs.audit.enabled": "true",
                       "spark.rapids.sql.reader.batchSizeRows": "4096"})
    rng = np.random.default_rng(11)
    t = pa.table({"k": rng.integers(0, 9, 60000),
                  "v": rng.random(60000)})
    df = sess.create_dataframe(t)
    q = (df.filter(col("v") > lit(0.25)).group_by("k")
         .agg(F.sum(col("v")).alias("s"), F.count(col("v")).alias("c")))
    return sess, q


def gate_overhead() -> None:
    print("[audit_smoke] steady-state overhead of the armed audit "
          f"(count x delta, bar {OVERHEAD_BAR_PCT}%)")
    from spark_rapids_tpu.analysis import kernel_audit as KA
    from spark_rapids_tpu.runtime import compile_cache as CC
    sess, q = _drive_session()
    q.collect()  # warm: every entry traced + audited
    h0 = CC.stats()["hits"]
    t0 = time.perf_counter_ns()
    reps = 5
    for _ in range(reps):
        q.collect()
    wall = time.perf_counter_ns() - t0
    notes = CC.stats()["hits"] - h0  # armed note() fires once per hit
    # price one armed choke-point pass: the `_AUDITOR is not None`
    # branch plus note()'s tally increment, measured in a tight loop
    key = ("smoke", ("k",), (False, True))
    KA.on_query_start()
    n = 20000
    t0 = time.perf_counter_ns()
    for _ in range(n):
        KA.note(key)
    per_note = (time.perf_counter_ns() - t0) / n
    KA.finish_query()
    overhead = notes * per_note
    pct = 100.0 * overhead / wall
    print(f"  {notes} audited dispatches over {wall / 1e6:.1f}ms, "
          f"{per_note:.0f}ns/note -> {pct:.4f}% (trace-time hook adds "
          f"nothing at steady state by construction)")
    if pct >= OVERHEAD_BAR_PCT:
        raise SystemExit(f"FAIL: audit steady-state overhead "
                         f"{pct:.3f}% >= {OVERHEAD_BAR_PCT}%")
    print("  OK")


def gate_surfaces() -> None:
    print("[audit_smoke] surfaces: audit summary, roofline reconciling "
          "with attribution device_compute <1%, explain section, zero "
          "findings")
    from spark_rapids_tpu.analysis import kernel_audit as KA
    sess, q = _drive_session()
    q.collect()
    summary = sess.last_audit()
    roof = sess.last_roofline()
    attr = sess.last_attribution()
    assert summary and summary["total"]["bytes_accessed"] > 0, \
        "no audited bytes"
    assert roof and "device_compute" in roof["groups"], "no roofline"
    dev = roof["groups"]["device_compute"]["seconds"]
    a_dev = (attr["buckets"]["device_compute"]
             * attr.get("concurrency_factor", 1.0))
    denom = max(dev, a_dev, 1e-9)
    rel = abs(dev - a_dev) / denom
    print(f"  roofline device {dev:.6f}s vs attribution "
          f"{a_dev:.6f}s (rel {rel:.4%})")
    if rel >= RECONCILE_BAR:
        raise SystemExit("FAIL: roofline does not reconcile with the "
                         "attribution device_compute bucket")
    text = sess.explain_analyze()
    assert "-- roofline (audit" in text, "explain lacks roofline section"
    if KA.findings():
        raise SystemExit("FAIL: audit findings on a clean drive: "
                         + "; ".join(KA.findings()[:5]))
    print("  OK")


def main() -> int:
    quick = "--quick" in sys.argv
    prefix = 4
    if "--prefix" in sys.argv:
        prefix = int(sys.argv[sys.argv.index("--prefix") + 1])
    gate_surfaces()
    gate_overhead()
    gate_determinism(prefix)
    gate_golden_replay(quick, prefix)
    print("audit_smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
