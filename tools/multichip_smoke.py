"""Multi-chip smoke (round 19): the CI gate for sharded execution over
the ICI mesh.

1. 8-virtual-device parity: multi-partition scan / filter-project /
   group-by-agg (shuffle) probes must be byte-identical with
   spark.rapids.sql.multichip.enabled on and off — the on-path must
   actually engage (ShardedStageExec in the plan, shardWaves >= 1, and
   iciExchangeTime > 0 on the shuffle probe), the off-path must not.
2. Disabled-path overhead: with multichip OFF the only new code the old
   path executes is the planner's conf gate at convert_plan (plus the
   ICI-first check in ShuffleExchangeExec). Same count x delta
   methodology as tools/decode_smoke.py (end-to-end A/B timing is
   noise-bound on shared CI machines): count the gate's firings during
   a probe drive, measure the per-call cost in a tight loop, overhead
   must stay under --tolerance (2%) of the drive.

Usage: python tools/multichip_smoke.py [--rows 50000] [--tolerance 0.02]
"""
import argparse
import json
import os
import sys
import time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["XLA_FLAGS"] = _flags
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_rapids_tpu import config as C  # noqa: E402
from spark_rapids_tpu.expr.core import col, lit  # noqa: E402
from spark_rapids_tpu.sql import functions as F  # noqa: E402
from spark_rapids_tpu.sql.session import TpuSession  # noqa: E402


def _data(rows: int) -> dict:
    return {
        "g": [i % 37 for i in range(rows)],
        "v": list(range(rows)),
        "d": [float(i % 11) * 0.5 for i in range(rows)],
    }


def queries(rows: int):
    data = _data(rows)
    return {
        "scan": lambda s: s.create_dataframe(data, num_partitions=8),
        "narrow": lambda s: (
            s.create_dataframe(data, num_partitions=8)
            .filter(col("v") % lit(3) != lit(0))
            .select(col("g"), (col("v") * lit(2) + lit(1)).alias("v2"),
                    (col("d") * lit(4.0)).alias("d4"))),
        "shuffle": lambda s: (
            s.create_dataframe(data, num_partitions=8)
            .group_by(col("g")).agg(F.sum("v").alias("sv"),
                                    F.count().alias("n"),
                                    F.min("d").alias("md"))),
    }


def _sorted(tbl):
    return tbl.sort_by([(c, "ascending") for c in tbl.column_names])


def parity_and_engagement(rows: int, result: dict) -> list:
    """Returns a list of failure strings (empty = pass)."""
    fails = []
    qs = queries(rows)
    outs = {}
    for flag in ("true", "false"):
        sess = TpuSession({C.MULTICHIP_ENABLED.key: flag})
        key = "multichip" if flag == "true" else "single"
        outs[key] = {}
        engaged = {}
        for name, q in qs.items():
            df = q(sess)
            outs[key][name] = _sorted(df.collect())
            plan = sess._last_exec.tree_string() \
                if getattr(sess, "_last_exec", None) else ""
            snaps = sess.last_metrics()
            engaged[name] = {
                "sharded_in_plan": "ShardedStageExec" in plan,
                "shard_waves": sum(v.get("shardWaves", 0)
                                   for v in snaps.values()),
                "ici_ns": sum(v.get("iciExchangeTime", 0)
                              for v in snaps.values()),
            }
        result[key] = engaged
        if flag == "true":
            if not engaged["narrow"]["sharded_in_plan"]:
                fails.append("multichip path did not plan the narrow "
                             "chain as ShardedStageExec")
            if engaged["narrow"]["shard_waves"] < 1:
                fails.append("multichip narrow probe recorded no "
                             "shardWaves")
            if not engaged["shuffle"]["ici_ns"]:
                fails.append("multichip shuffle probe recorded no "
                             "iciExchangeTime: the in-program all_to_all "
                             "did not run")
        else:
            for name, e in engaged.items():
                if e["sharded_in_plan"] or e["shard_waves"]:
                    fails.append(f"disabled path still shards ({name})")
    for name in qs:
        if not outs["multichip"][name].equals(outs["single"][name]):
            fails.append(f"parity: {name} differs between multichip "
                         f"on/off")
    return fails


def disabled_overhead(rows: int, reps: int) -> dict:
    """Count x delta: the disabled path's new sites are the multichip
    conf gate reads (convert_plan's planner gate + the exchange's
    ICI-first check)."""
    off = TpuSession({C.MULTICHIP_ENABLED.key: "false"})
    drive = queries(rows)["shuffle"]
    drive(off).collect()  # warm compile caches out of the timed drives

    conf = off.conf
    counts = {"multichip.enabled": 0}
    orig_get = type(conf).get

    def counting_get(self, entry, *a, **k):
        if getattr(entry, "key", None) == C.MULTICHIP_ENABLED.key:
            counts["multichip.enabled"] += 1
        return orig_get(self, entry, *a, **k)

    type(conf).get = counting_get
    try:
        drive(off).collect()
    finally:
        type(conf).get = orig_get

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        drive(off).collect()
        best = min(best, time.perf_counter() - t0)

    iters = 100_000
    t0 = time.perf_counter()
    for _ in range(iters):
        conf.get(C.MULTICHIP_ENABLED)
    per_call = (time.perf_counter() - t0) / iters

    added = counts["multichip.enabled"] * per_call
    return {"drive_best_s": round(best, 6),
            "gate_counts": counts,
            "gate_per_call_ns": round(per_call * 1e9, 1),
            "disabled_overhead_s": round(added, 9),
            "disabled_overhead_pct": round(added / best * 100, 4)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=50_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    import jax
    result = {"rows": args.rows, "devices": len(jax.devices())}
    fails = parity_and_engagement(args.rows, result)
    overhead = disabled_overhead(args.rows, args.reps)
    result.update(overhead)
    print(json.dumps(result, sort_keys=True))
    pct = overhead["disabled_overhead_pct"]
    if pct > args.tolerance * 100:
        fails.append(f"disabled-path multichip overhead {pct:.3f}% "
                     f"exceeds {args.tolerance * 100:.0f}% of the drive")
    if fails:
        for f in fails:
            print("FAIL:", f)
        return 1
    print(f"PASS: multichip on/off byte-identical across "
          f"{len(queries(args.rows))} probe queries on "
          f"{result['devices']} virtual devices; "
          f"narrow chain sharded in "
          f"{result['multichip']['narrow']['shard_waves']} wave(s), "
          f"shuffle spent {result['multichip']['shuffle']['ici_ns']}ns "
          f"in the in-program all_to_all; disabled-path overhead "
          f"{pct:.4f}% of the drive")
    return 0


if __name__ == "__main__":
    sys.exit(main())
