"""Isolated groupby micro-bench on the engine: which path runs, and how
long each stage takes."""
import os
import sys
import time
import numpy as np

ROWS = int(os.environ.get("ROWS", 8_000_000))
GROUPS = int(os.environ.get("GROUPS", 800_000))

import pyarrow as pa
from spark_rapids_tpu.sql.session import TpuSession
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.expr.core import col
from spark_rapids_tpu.exec import fuse

rng = np.random.default_rng(0)
t = pa.table({
    "k": rng.integers(0, GROUPS, ROWS).astype(np.int64),
    "v": rng.uniform(0, 100, ROWS),
})
sess = TpuSession()
print("[prof] uploading...", file=sys.stderr, flush=True)
df = sess.create_dataframe(t).cache()
df.count()


def q():
    g = df.group_by(col("k")).agg(F.sum("v").alias("s"), F.count("v").alias("c"))
    # device-side final reduction: don't measure the 100k-row download
    out = g.agg(F.count(col("k")).alias("n"), F.sum(col("s")).alias("ts"))
    return out.to_pydict()


t0 = time.perf_counter(); r = q(); warm = time.perf_counter() - t0
times = []
for _ in range(3):
    t0 = time.perf_counter(); q(); times.append(time.perf_counter() - t0)
print(f"[prof] groupby rows={ROWS} groups={GROUPS} warm={warm:.2f}s "
      f"best={min(times):.3f}s result={r}")
m = sess.last_metrics()
for k, v in m.items():
    it = {mk: mv / 1e9 for mk, mv in v.items()
          if ("Time" in mk) and mv and mv > 5e6}
    if it:
        print(f"  {k}: " + ", ".join(f"{mk}={mv:.3f}s" for mk, mv in
                                     sorted(it.items(), key=lambda x: -x[1])))
from spark_rapids_tpu.runtime import compile_cache
print("fused:", sorted({k[0] for k in compile_cache.cache_keys()}))
