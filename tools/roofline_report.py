"""Offline roofline report over the query history store.

Aggregates the per-query roofline attribution the kernel cost auditor
(analysis/kernel_audit.py, spark.rapids.obs.audit.enabled) wrote into
history records: where the engine's device seconds go relative to the
configured bandwidth/compute rooflines, which queries are memory- vs
compute- vs dispatch-overhead-bound, and how much of the moved bytes
the shape-bucket ladder exposes as padding. The answer to "we are at
1% of the roofline — WHERE is the other 99%?" per query, ranked.

    python tools/roofline_report.py --history <dir> [--json] [--top N]

Reads `query_history.jsonl` (runtime/obs/history.py); only records
carrying a `roofline` doc (audited queries) contribute. Records that
also carry an `aqe` doc (exec/adaptive.py decisions) get an "adaptive"
column — decision kinds × counts and the dispatches those decisions
saved — so a verdict flip (dispatch_overhead -> memory) can be read
next to the replan that caused it.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def load_records(history_dir: str):
    path = os.path.join(history_dir, "query_history.jsonl")
    if not os.path.exists(path):
        raise SystemExit(f"no history at {path}")
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "query" and rec.get("roofline"):
                out.append(rec)
    return out


def summarize(records):
    rows = []
    for rec in records:
        roof = rec["roofline"]
        tot = roof.get("total") or {}
        groups = roof.get("groups") or {}
        kernels = roof.get("kernels") or {}
        top_kernel = None
        if kernels:
            top_kernel = max(kernels.items(),
                             key=lambda kv: kv[1].get("bytes_accessed",
                                                      0))[0]
        bounds = sorted({g.get("bound") for g in groups.values()
                         if g.get("bound")})
        waste = max([g.get("padding_waste_ratio") or 0.0
                     for g in groups.values()] or [0.0])
        aqe = rec.get("aqe") or {}
        counts = aqe.get("counts") or {}
        adaptive = ",".join(f"{k}x{n}" for k, n in sorted(counts.items()))
        saved = aqe.get("dispatches_saved", 0)
        if adaptive and saved:
            adaptive += f"(-{saved}d)"
        rows.append({
            "query_id": rec.get("query_id"),
            "digest": rec.get("plan_digest"),
            "status": rec.get("status"),
            "wall_s": round(rec.get("duration_ns", 0) / 1e9, 3),
            "device_s": tot.get("seconds", 0.0),
            "gb_moved": round(tot.get("bytes_accessed", 0) / 1e9, 4),
            "achieved_gbps": tot.get("achieved_gbps", 0.0),
            "roofline_pct": tot.get("roofline_pct_bw", 0.0),
            "bound": "+".join(bounds) or "?",
            "padding_waste_max": round(waste, 3),
            "adaptive": adaptive or "-",
            "dispatches_saved": saved,
            "top_kernel": top_kernel,
        })
    rows.sort(key=lambda r: r["roofline_pct"])
    return rows


def render(rows, top: int) -> str:
    lines = [f"roofline report — {len(rows)} audited queries "
             f"(lowest roofline share first)",
             f"{'query':>6} {'wall s':>8} {'dev s':>8} {'GB':>8} "
             f"{'GB/s':>8} {'%roof':>7} {'waste<=':>8} "
             f"{'bound':<18} {'adaptive':<28} top kernel"]
    for r in rows[:top]:
        lines.append(
            f"{str(r['query_id']):>6} {r['wall_s']:>8.3f} "
            f"{r['device_s']:>8.3f} {r['gb_moved']:>8.3f} "
            f"{r['achieved_gbps']:>8.2f} {r['roofline_pct']:>7.3f} "
            f"{r['padding_waste_max'] * 100:>7.0f}% "
            f"{r['bound']:<18} {r['adaptive']:<28} {r['top_kernel']}")
    if rows:
        import math
        pcts = [r["roofline_pct"] for r in rows if r["roofline_pct"] > 0]
        if pcts:
            geo = math.exp(sum(math.log(p) for p in pcts) / len(pcts))
            lines.append(f"geomean roofline share: {geo:.4f}% over "
                         f"{len(pcts)} queries with device time")
    return "\n".join(lines)


def main() -> int:
    args = sys.argv[1:]
    hist = None
    as_json = "--json" in args
    top = 50
    if "--history" in args:
        hist = args[args.index("--history") + 1]
    if "--top" in args:
        top = int(args[args.index("--top") + 1])
    if not hist:
        raise SystemExit("usage: roofline_report.py --history <dir> "
                         "[--json] [--top N]")
    rows = summarize(load_records(hist))
    if as_json:
        print(json.dumps(rows, indent=1))
    else:
        print(render(rows, top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
