"""Which groupby backbone is fastest at 33M rows -> ~3M groups on this
chip? block_until_ready does NOT reliably block on the axon backend, so
every candidate ends in a scalar reduction that we fetch; the ~80ms
fetch round trip is a shared constant. Data generated on device."""
import time
import spark_rapids_tpu  # noqa: F401  (x64 + persistent compile cache)
import jax
import jax.numpy as jnp

N = 1 << 23  # 8.4M capacity (upload-bound tunnel)
SPAN = 750_000

import numpy as _np
_rng = _np.random.default_rng(0)
key = jax.device_put(_rng.integers(0, SPAN, N).astype(_np.int32))
val = jax.device_put((_rng.random(N, _np.float32) * 1e5))
val64 = val.astype(jnp.float64)
live = jax.device_put(_rng.random(N) < 0.5)
print("uploaded", flush=True)
float(jnp.sum(val))  # force
print("forced", flush=True)


def t(name, fn, *a, reps=3):
    float(fn(*a))  # compile + run
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(fn(*a))
        ts.append(time.perf_counter() - t0)
    print(f"{name}: {min(ts)*1e3:.1f} ms (incl ~80ms fetch)", flush=True)


@jax.jit
def baseline(key):
    return jnp.sum(key[:16])


@jax.jit
def argsort_i64(key, live):
    packed = jnp.where(live, key.astype(jnp.int64), jnp.int64(1) << 40)
    o = jnp.argsort(packed)
    return o[0] + o[-1]


@jax.jit
def argsort_i32(key, live):
    packed = jnp.where(live, key, jnp.int32(SPAN + 5))
    o = jnp.argsort(packed)
    return o[0] + o[-1]


@jax.jit
def sort2op(key, live):
    packed = jnp.where(live, key, jnp.int32(SPAN + 5))
    iota = jnp.arange(N, dtype=jnp.int32)
    sk, si = jax.lax.sort((packed, iota), num_keys=1)
    return sk[0] + si[-1]


@jax.jit
def sort3op(key, val, live):
    packed = jnp.where(live, key, jnp.int32(SPAN + 5))
    iota = jnp.arange(N, dtype=jnp.int32)
    sk, sv, si = jax.lax.sort((packed, val, iota), num_keys=1)
    return sk[0].astype(jnp.float32) + sv[-1]


@jax.jit
def sort_f32val(key, val, live):
    packed = jnp.where(live, key, jnp.int32(SPAN + 5))
    sk, sv = jax.lax.sort((packed, val), num_keys=1)
    return sk[0].astype(jnp.float32) + sv[-1]


@jax.jit
def gather_f64(order, val64):
    return val64[order][0]


@jax.jit
def cumsum_i64(key):
    return jnp.cumsum(key.astype(jnp.int64))[-1]


@jax.jit
def cumsum_f64(val64):
    return jnp.cumsum(val64)[-1]


@jax.jit
def scatter_i32(key, live):
    v = jnp.where(live, 1, 0).astype(jnp.int32)
    return jax.ops.segment_sum(v, key, num_segments=SPAN)[0]


@jax.jit
def scatter_f32(key, val, live):
    v = jnp.where(live, val, 0.0)
    return jax.ops.segment_sum(v, key, num_segments=SPAN)[0]


@jax.jit
def scatter_f64(key, val64, live):
    v = jnp.where(live, val64, 0.0)
    return jax.ops.segment_sum(v, key, num_segments=SPAN)[0]


@jax.jit
def full_sort_groupby_i32(key, val64, live):
    packed = jnp.where(live, key, jnp.int32(SPAN + 5))
    order = jnp.argsort(packed, stable=True)
    sk = packed[order]
    sv = jnp.where(live[order], val64[order], 0.0)
    s = jnp.cumsum(sv)
    bound = jnp.concatenate([jnp.ones(1, jnp.bool_), sk[1:] != sk[:-1]])
    gid = jnp.cumsum(bound.astype(jnp.int32)) - 1
    return s[-1] + gid[-1].astype(jnp.float64)


@jax.jit
def full_sort_groupby_i64(key, val64, live):
    packed = jnp.where(live, key.astype(jnp.int64), jnp.int64(1) << 40)
    order = jnp.argsort(packed, stable=True)
    sk = packed[order]
    sv = jnp.where(live[order], val64[order], 0.0)
    s = jnp.cumsum(sv)
    bound = jnp.concatenate([jnp.ones(1, jnp.bool_), sk[1:] != sk[:-1]])
    gid = jnp.cumsum(bound.astype(jnp.int32)) - 1
    return s[-1] + gid[-1].astype(jnp.float64)


@jax.jit
def dense_scatter_groupby(key, val64, live):
    """q3 shape: dense int key -> direct 2-limb scatter + count."""
    scaled = jnp.where(live, val64 * (1 << 16), 0.0)
    hi = jnp.floor(scaled / (1 << 24)).astype(jnp.int32)
    lo = (scaled - hi.astype(jnp.float64) * (1 << 24)).astype(jnp.int32)
    shi = jax.ops.segment_sum(hi, key, num_segments=SPAN)
    slo = jax.ops.segment_sum(lo, key, num_segments=SPAN)
    cnt = jax.ops.segment_sum(jnp.where(live, 1, 0).astype(jnp.int32), key,
                              num_segments=SPAN)
    tot = (shi.astype(jnp.float64) * (1 << 24) + slo.astype(jnp.float64)) / (1 << 16)
    return tot[0] + cnt[-1].astype(jnp.float64)


t("baseline tiny fetch", baseline, key)
t("argsort i64-packed", argsort_i64, key, live)
t("argsort i32-packed", argsort_i32, key, live)
t("lax.sort 2-op (k,iota)", sort2op, key, live)
t("lax.sort 3-op (k,f32,iota)", sort3op, key, val, live)
t("lax.sort 2-op (k,f32)", sort_f32val, key, val, live)
order = jnp.argsort(key)
int(order[0])
t("random gather f64 by order", gather_f64, order, val64)
t("cumsum i64 33M", cumsum_i64, key)
t("cumsum f64 33M", cumsum_f64, val64)
t("segment_sum i32 33M->3M", scatter_i32, key, live)
t("segment_sum f32 33M->3M", scatter_f32, key, val, live)
t("segment_sum f64 33M->3M", scatter_f64, key, val64, live)
t("FULL sort-groupby i32 pack", full_sort_groupby_i32, key, val64, live)
t("FULL sort-groupby i64 pack", full_sort_groupby_i64, key, val64, live)
t("FULL dense-scatter groupby", dense_scatter_groupby, key, val64, live)
