"""Golden Catalyst physical-plan corpus generator.

Writes tests/golden_plans/*.json in the EXACT wire shape Spark 3.x's
`df.queryExecution.executedPlan.toJSON` emits (TreeNode.scala jsonValue:
preorder node arrays, child-index fields, ExprId products, enum
objects). The environment has no JVM, so these are format-faithful
reconstructions of the serializer's output for each query — the same
role the reference's golden-file tests play for its shims — consumed by
spark_rapids_tpu/plan/catalyst.py and differentially executed in
tests/test_catalyst_plans.py. Paths use the $DATA placeholder the test
substitutes.

Run: python tools/gen_golden_plans.py
"""
from __future__ import annotations

import json
import os

X = "org.apache.spark.sql.execution"
C = "org.apache.spark.sql.catalyst.expressions"
A = C + ".aggregate"
JVM = "5f20ae84-5a76-4a11-8f74-a712a524e3f2"

_ids = {}


def _eid(name):
    if name not in _ids:
        _ids[name] = len(_ids) + 1
    return {"product-class": C + ".ExprId", "id": _ids[name],
            "jvmId": JVM}


def attr(name, dt):
    return [{"class": C + ".AttributeReference", "num-children": 0,
             "name": name, "dataType": dt, "nullable": True,
             "metadata": {}, "exprId": _eid(name), "qualifier": []}]


def lit(value, dt):
    return [{"class": C + ".Literal", "num-children": 0,
             "value": None if value is None else str(value),
             "dataType": dt}]


def _node(cls, nkids, **fields):
    d = {"class": cls, "num-children": nkids}
    d.update(fields)
    return d


def binop(cls_name, left, right):
    return [_node(C + "." + cls_name, 2, left=0, right=1)] + left + right


def unop(cls_name, child, **extra):
    return [_node(C + "." + cls_name, 1, child=0, **extra)] + child


def alias(child, name):
    return [_node(C + ".Alias", 1, child=0, name=name, exprId=_eid(name),
                  qualifier=[], explicitMetadata=None,
                  nonInheritableMetadataKeys=[])] + child


def cast(child, dt):
    return [_node(C + ".Cast", 1, child=0, dataType=dt,
                  timeZoneId="UTC")] + child


def case_when(branches, default=None):
    kids = []
    for cond, val in branches:
        kids.append(cond)
        kids.append(val)
    if default is not None:
        kids.append(default)
    out = [_node(C + ".CaseWhen", len(kids))]
    for k in kids:
        out += k
    return out


def in_list(probe, values):
    out = [_node(C + ".In", 1 + len(values), value=0,
                 list=list(range(1, 1 + len(values))))]
    out += probe
    for v in values:
        out += v
    return out


def substring(child, pos, length):
    return [_node(C + ".Substring", 3, str=0, pos=1, len=2)] + \
        child + lit(pos, "integer") + lit(length, "integer")


def like(child, pattern):
    return [_node(C + ".Like", 2, left=0, right=1, escapeChar="\\")] + \
        child + lit(pattern, "string")


def agg_expr(fn_cls, children, mode, distinct=False):
    fn = [_node(A + "." + fn_cls, len(children),
                **({"failOnError": False} if fn_cls == "Sum" else {}))]
    for ch in children:
        fn += ch
    return [_node(C + ".AggregateExpression", 1, aggregateFunction=0,
                  mode={"object": A + "." + mode + "$"},
                  isDistinct=distinct, filter=None,
                  resultId=_eid(f"res_{fn_cls}_{len(_ids)}"))] + fn


def sort_order(child, asc=True, nulls_first=None):
    if nulls_first is None:
        nulls_first = asc
    return [_node(C + ".SortOrder", 1, child=0,
                  direction={"object": C + "." +
                             ("Ascending" if asc else "Descending") + "$"},
                  nullOrdering={"object": C + "." +
                                ("NullsFirst" if nulls_first
                                 else "NullsLast") + "$"},
                  sameOrderExpressions=[])] + child


# -- plan-level builders (preorder arrays of PLAN nodes; expression
#    fields hold the nested arrays built above) -----------------------------

def scan(table, cols):
    return [_node(
        X + ".FileSourceScanExec", 0,
        output=[attr(n, t) for n, t in cols],
        requiredSchema={"type": "struct", "fields": [
            {"name": n, "type": t, "nullable": True, "metadata": {}}
            for n, t in cols]},
        partitionFilters=[], dataFilters=[],
        metadata={"Location": f"InMemoryFileIndex[file:$DATA/{table}]",
                  "Format": "Parquet", "Batched": "true",
                  "PushedFilters": "[]"},
        tableIdentifier=None, disableBucketedScan=False)]


def filter_(cond, child):
    return [_node(X + ".FilterExec", 1, condition=cond)] + child


def project(exprs, child):
    return [_node(X + ".ProjectExec", 1, projectList=exprs)] + child


def hash_agg(keys, aggs, results, mode, child):
    return [_node(X + ".aggregate.HashAggregateExec", 1,
                  requiredChildDistributionExpressions=None,
                  isStreaming=False, numShufflePartitions=None,
                  groupingExpressions=keys,
                  aggregateExpressions=[agg_expr(f, ch, mode)
                                        for f, ch in aggs],
                  aggregateAttributes=[],
                  initialInputBufferOffset=0,
                  resultExpressions=results)] + child


def exchange(child):
    return [_node(X + ".exchange.ShuffleExchangeExec", 1,
                  outputPartitioning={"product-class":
                                      "org.apache.spark.sql.catalyst."
                                      "plans.physical.UnknownPartitioning",
                                      "numPartitions": 200},
                  shuffleOrigin={"object": X +
                                 ".exchange.ENSURE_REQUIREMENTS$"})] + child


def bcast_exchange(child):
    return [_node(X + ".exchange.BroadcastExchangeExec", 1,
                  mode={"product-class": "org.apache.spark.sql.catalyst."
                        "plans.physical.BroadcastMode"})] + child


def wsc(child, cid=1):
    return [_node(X + ".WholeStageCodegenExec", 1,
                  codegenStageId=cid)] + child


def smj(lk, rk, how, left, right, cond=None):
    return [_node(X + ".joins.SortMergeJoinExec", 2, leftKeys=lk,
                  rightKeys=rk,
                  joinType={"object":
                            f"org.apache.spark.sql.catalyst.plans."
                            f"{how}$"},
                  condition=cond, isSkewJoin=False)] + left + right


def bhj(lk, rk, how, left, right, cond=None, build="BuildRight"):
    return [_node(X + ".joins.BroadcastHashJoinExec", 2, leftKeys=lk,
                  rightKeys=rk,
                  joinType={"object":
                            f"org.apache.spark.sql.catalyst.plans."
                            f"{how}$"},
                  buildSide={"object": X + f".joins.{build}$"},
                  condition=cond, isNullAwareAntiJoin=False)] + \
        left + right


def sort(orders, child, global_=True):
    n = _node(X + ".SortExec", 1, sortOrder=orders, testSpillFrequency=0)
    n["global"] = global_
    return [n] + child


def limit(n, child, cls="GlobalLimitExec"):
    return [_node(X + "." + cls, 1, limit=n, offset=0)] + child


def take_ordered(n, orders, projlist, child):
    return [_node(X + ".TakeOrderedAndProjectExec", 1, limit=n,
                  sortOrder=orders, projectList=projlist, offset=0)] + child


def union(children):
    out = [_node(X + ".UnionExec", len(children))]
    for ch in children:
        out += ch
    return out


def expand(projections, output, child):
    return [_node(X + ".ExpandExec", 1, projections=projections,
                  output=output)] + child


LINEITEM = [("l_orderkey", "long"), ("l_quantity", "double"),
            ("l_extendedprice", "double"), ("l_discount", "double"),
            ("l_shipdate", "integer"), ("l_flag", "string")]
ORDERS = [("o_orderkey", "long"), ("o_orderdate", "integer"),
          ("o_prio", "string")]


def build_corpus():
    li = scan("lineitem.parquet", LINEITEM)
    od = scan("orders.parquet", ORDERS)
    plans = {}

    # 1. q6: filter + partial/final agg of sum(price*discount)
    cond = binop("And",
                 binop("GreaterThanOrEqual", attr("l_shipdate", "integer"),
                       lit(100, "integer")),
                 binop("LessThan", attr("l_quantity", "double"),
                       lit(24.0, "double")))
    revenue = binop("Multiply", attr("l_extendedprice", "double"),
                    attr("l_discount", "double"))
    partial = hash_agg([], [("Sum", [revenue])], [], "Partial",
                       wsc(filter_(cond, li)))
    plans["q6_filter_agg"] = hash_agg(
        [], [("Sum", [revenue])], [alias(attr("sum_rev", "double"),
                                         "revenue")],
        "Final", exchange(partial))

    # 2. project over filter
    plans["project_filter"] = project(
        [attr("l_orderkey", "long"),
         alias(binop("Add", attr("l_quantity", "double"),
                     lit(1.0, "double")), "qplus")],
        wsc(filter_(unop("IsNotNull", attr("l_quantity", "double")), li)))

    # 3. join + group agg + take-ordered (q3 shape)
    j = smj([attr("l_orderkey", "long")], [attr("o_orderkey", "long")],
            "Inner",
            sort([sort_order(attr("l_orderkey", "long"))],
                 exchange(filter_(binop("GreaterThan",
                                        attr("l_shipdate", "integer"),
                                        lit(50, "integer")), li))),
            sort([sort_order(attr("o_orderkey", "long"))],
                 exchange(filter_(binop("LessThan",
                                        attr("o_orderdate", "integer"),
                                        lit(150, "integer")), od))))
    gp = hash_agg([attr("l_orderkey", "long")],
                  [("Sum", [attr("l_extendedprice", "double")])],
                  [], "Partial", j)
    gf = hash_agg([attr("l_orderkey", "long")],
                  [("Sum", [attr("l_extendedprice", "double")])],
                  [alias(attr("sum_p", "double"), "rev")],
                  "Final", exchange(gp))
    plans["q3_join_agg_topn"] = take_ordered(
        10, [sort_order(attr("rev", "double"), asc=False),
             sort_order(attr("l_orderkey", "long"))],
        [attr("l_orderkey", "long"), attr("rev", "double")], gf)

    # 4. sort + limits
    plans["sort_limit"] = limit(
        5, limit(5, sort([sort_order(attr("l_extendedprice", "double"),
                                     asc=False)], li),
                 cls="LocalLimitExec"))

    # 5. union of two filters
    plans["union_filters"] = union([
        filter_(binop("LessThan", attr("l_quantity", "double"),
                      lit(5.0, "double")), li),
        filter_(binop("GreaterThan", attr("l_quantity", "double"),
                      lit(95.0, "double")),
                scan("lineitem.parquet", LINEITEM))])

    # 6. left semi broadcast join
    plans["semi_join"] = bhj(
        [attr("l_orderkey", "long")], [attr("o_orderkey", "long")],
        "LeftSemi", li,
        bcast_exchange(filter_(binop("EqualTo", attr("o_prio", "string"),
                                     lit("HIGH", "string")), od)))

    # 7. broadcast inner join with residual condition
    plans["bhj_condition"] = bhj(
        [attr("l_orderkey", "long")], [attr("o_orderkey", "long")],
        "Inner", li, bcast_exchange(od),
        cond=binop("GreaterThan", attr("l_shipdate", "integer"),
                   attr("o_orderdate", "integer")))

    # 8. rollup-shaped Expand + aggregate
    ex = expand(
        [[attr("l_flag", "string"), attr("l_quantity", "double"),
          lit(0, "long")],
         [lit(None, "string"), attr("l_quantity", "double"),
          lit(1, "long")]],
        [attr("flag_e", "string"), attr("q_e", "double"),
         attr("spark_grouping_id", "long")], li)
    ep = hash_agg([attr("flag_e", "string"),
                   attr("spark_grouping_id", "long")],
                  [("Sum", [attr("q_e", "double")])], [], "Partial", ex)
    plans["expand_rollup_agg"] = hash_agg(
        [attr("flag_e", "string"), attr("spark_grouping_id", "long")],
        [("Sum", [attr("q_e", "double")])],
        [alias(attr("sq", "double"), "sum_qty")], "Final", exchange(ep))

    # 9. expression breadth: case/in/substring/like/cast
    plans["expr_breadth"] = project(
        [alias(case_when(
            [(binop("LessThan", attr("l_quantity", "double"),
                    lit(10.0, "double")), lit("low", "string"))],
            lit("high", "string")), "bucket"),
         alias(in_list(attr("l_shipdate", "integer"),
                       [lit(1, "integer"), lit(2, "integer"),
                        lit(3, "integer")]), "in3"),
         alias(substring(attr("l_flag", "string"), 1, 1), "f1"),
         alias(like(attr("l_flag", "string"), "A%"), "isa"),
         alias(cast(attr("l_quantity", "double"), "long"), "qlong")],
        li)

    # 10. global count(*) + collect limit
    cp = hash_agg([], [("Count", [lit(1, "integer")])], [], "Partial", li)
    plans["count_star"] = limit(
        1, hash_agg([], [("Count", [lit(1, "integer")])],
                    [alias(attr("cnt", "long"), "count(1)")],
                    "Final", exchange(cp)), cls="CollectLimitExec")

    # 11. multi-agg grouped (avg/min/max)
    mp = hash_agg([attr("l_flag", "string")],
                  [("Average", [attr("l_quantity", "double")]),
                   ("Min", [attr("l_extendedprice", "double")]),
                   ("Max", [attr("l_discount", "double")])],
                  [], "Partial", li)
    plans["multi_agg"] = hash_agg(
        [attr("l_flag", "string")],
        [("Average", [attr("l_quantity", "double")]),
         ("Min", [attr("l_extendedprice", "double")]),
         ("Max", [attr("l_discount", "double")])],
        [alias(attr("a", "double"), "avg_q"),
         alias(attr("mi", "double"), "min_p"),
         alias(attr("ma", "double"), "max_d")], "Final", exchange(mp))

    # 12. anti join through AQE wrapper
    plans["anti_join_aqe"] = [_node(
        X + ".adaptive.AdaptiveSparkPlanExec", 1,
        isFinalPlan=True)] + bhj(
        [attr("l_orderkey", "long")], [attr("o_orderkey", "long")],
        "LeftAnti", li, bcast_exchange(od))

    return plans


def main():
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "tests", "golden_plans")
    os.makedirs(out_dir, exist_ok=True)
    for name, arr in build_corpus().items():
        with open(os.path.join(out_dir, name + ".json"), "w") as f:
            json.dump(arr, f, indent=1)
        print("wrote", name, f"({len(arr)} plan nodes)")


if __name__ == "__main__":
    main()
