"""Chaos smoke: seeded fault injection over NDS probe queries.

The failure-domain acceptance gate (the robustness twin of
sanitizer_smoke/trace_overhead):

Gate 1 (overhead, the tracing bar): the DISABLED fault hooks
(`faults.site`/`site_bytes` with no schedule armed — one module-global
read each; the watchdog adds literally nothing when off because
exec/fuse.py returns the raw jitted function) must cost under
--tolerance (2%) of a clean query drive. Same methodology as
tools/sanitizer_smoke.py: count hook passes in one drive, measure the
disabled per-pass cost minus an empty-call baseline over tight-loop
iterations, multiply.

Gate 2 (chaos): with a FIXED seed, run the probe query set under
randomized injection schedules (spec strings generated from the seeded
RNG — a failing schedule is reproducible from the seed alone) until at
least --min-faults faults have fired across at least --min-sites
distinct sites. EVERY run must end status ok or degraded with results
identical to the clean run of the same query — never a wrong answer,
never an unhandled failure.

Gate 3 (no hangs, no leaks): the whole smoke runs under a global
deadline enforced by a watchdog thread (stack dump + hard exit on
breach), and the thread census at the end must contain nothing beyond
the sanctioned long-lived services (host pool, obs, watchdog) — a
leaked pipeline refill or task thread fails the gate.

Gate 4 (cancellation storm, PR 12): seeded cancels delivered
mid-scan/mid-shuffle/mid-retry (query.cancel:cancel schedules at random
checkpoint passes), externally mid-flight (session.cancel from another
thread), and while-queued (admission gate at maxConcurrent=1) across
--cancel-runs NDS runs. Every cancelled query must land the `cancelled`
terminal state within 2x the longest measured checkpoint interval
(lifecycle's probe), with zero leaked threads, zero stranded semaphore
permits, device_bytes_held() back to baseline, and surviving queries'
results identical to clean. The overhead half of gate 1 also prices the
always-on lifecycle checkpoint (count x delta, same bar).

Run:  python tools/chaos_smoke.py [--seed 20260803] [--sf 0.002]
          [--max-rounds 14] [--min-faults 200] [--min-sites 6]
          [--cancel-runs 20] [--deadline 480] [--tolerance 0.02]
"""
from __future__ import annotations

import argparse
import faulthandler
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import nds_probe as NDS  # noqa: E402

from spark_rapids_tpu import config as C  # noqa: E402
from spark_rapids_tpu.runtime import faults, watchdog  # noqa: E402
from spark_rapids_tpu.runtime import lifecycle  # noqa: E402
from spark_rapids_tpu.sql.session import TpuSession  # noqa: E402

#: probe queries: join + aggregate shapes so exchanges, retries, spills
#: and pipelines all engage (broadcast disabled below forces the joins
#: through real SERIALIZED shuffles)
CHAOS_QUERIES = (3, 7, 42, 52, 55)

#: (site, eligible kinds) the schedule generator draws from. Kind
#: weights favor delay (fires without failing the run, so fault volume
#: accumulates fast) while keeping every failure class in rotation.
SITE_KINDS = (
    ("scan.decode", ("delay", "delay", "ioerror", "oom")),
    ("shuffle.read", ("delay", "corrupt", "corrupt", "ioerror")),
    ("shuffle.write", ("delay", "delay", "corrupt")),
    ("spill.disk", ("delay", "delay", "ioerror")),
    ("device.dispatch", ("delay", "delay", "wedge", "oom")),
    ("pipeline.producer", ("delay", "delay", "ioerror", "oom")),
    ("exchange.fetch", ("delay", "delay", "ioerror")),
    ("retry.oom", ("oom",)),
)

CHAOS_CONF = {
    # real serialized shuffles (blob integrity, store spill) on every
    # exchange; broadcast disabled so the probe joins actually shuffle
    "spark.rapids.shuffle.mode": "SERIALIZED",
    "spark.rapids.sql.join.broadcastRowThreshold": "1",
    "spark.rapids.sql.adaptive.enabled": "false",
    "spark.rapids.sql.reader.batchSizeRows": "2048",
    # tiny store budget: every few blobs spill to disk (spill.disk site)
    "spark.rapids.shuffle.hostSpillBudget": "8192",
    "spark.rapids.fallback.cpu.enabled": "true",
    "spark.rapids.watchdog.enabled": "true",
    # wedge (1.0s) ABOVE the watchdog timeout (0.6s): every wedge-kind
    # fault must drive the full wedge -> watchdogDispatchTimeout ->
    # breaker-failure path, not just sleep unnoticed. Steady dispatches
    # stay well under 0.6s; a first-compile overshoot merely adds a
    # harmless report against the high breaker threshold.
    "spark.rapids.watchdog.dispatchTimeoutSeconds": "0.6",
    # chaos wants the DEVICE path exercised every round: a latched-open
    # breaker would route everything to CPU and starve the fault sites
    "spark.rapids.watchdog.breakerFailureThreshold": "1000",
    "spark.rapids.retry.backoffBaseMs": "1",
    "spark.rapids.debug.faults.delayMs": "5",
    "spark.rapids.debug.faults.wedgeSeconds": "1.0",
}


def _arm_deadline(seconds: float):
    """Global hang-breaker: past the deadline, dump every thread's stack
    and hard-exit — a wedged chaos run must fail loudly, not hang CI."""
    done = threading.Event()

    def trip():
        if not done.wait(seconds):
            print(f"FAIL: chaos smoke exceeded the {seconds:.0f}s global "
                  f"deadline — dumping stacks", file=sys.stderr)
            faulthandler.dump_traceback(file=sys.stderr)
            os._exit(3)

    t = threading.Thread(target=trip, name="chaos-deadline", daemon=True)
    t.start()
    return done


def _gen_spec(rng: random.Random) -> str:
    """One round's injection schedule: 2-4 entries drawn from the
    site/kind table with small counts and skips."""
    n = rng.randint(2, 4)
    parts = []
    for _ in range(n):
        site, kinds = SITE_KINDS[rng.randrange(len(SITE_KINDS))]
        kind = kinds[rng.randrange(len(kinds))]
        count = rng.randint(1, 4)
        skip = rng.randint(0, 2)
        parts.append(f"{site}:{kind}:{count},{skip}")
    return ";".join(parts)


def _canon(table):
    return NDS._canon_rows(table)


def _overhead_gate(session, dfs, tolerance: float) -> dict:
    """Gate 1: disabled-hook cost of one clean drive (sanitizer_smoke
    methodology) — the fault sites AND the always-on lifecycle
    cancellation checkpoint, priced together against the same bar."""
    session.conf.set(C.FAULTS_SPEC, "")
    session.conf.set(C.WATCHDOG_ENABLED, False)

    def drive():
        NDS.QUERIES[CHAOS_QUERIES[0]](session, dfs).collect()

    drive()  # warm kernel caches
    best = min((lambda t0=time.perf_counter(): (drive(),
                time.perf_counter() - t0)[1])() for _ in range(3))

    counts = {"passes": 0, "lc_passes": 0}
    orig_site, orig_bytes = faults.site, faults.site_bytes
    orig_check = lifecycle.check_current

    def csite(name):
        counts["passes"] += 1
        return orig_site(name)

    def cbytes(name, data):
        counts["passes"] += 1
        return orig_bytes(name, data)

    def ccheck():
        counts["lc_passes"] += 1
        return orig_check()

    faults.site, faults.site_bytes = csite, cbytes
    lifecycle.check_current = ccheck
    try:
        drive()
    finally:
        faults.site, faults.site_bytes = orig_site, orig_bytes
        lifecycle.check_current = orig_check

    def loop(fn, *args, iters=100_000):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(*args)
        return (time.perf_counter() - t0) / iters

    def baseline(*_args):
        return None

    base = min(loop(baseline, "scan.decode") for _ in range(3))
    cost = min(loop(orig_site, "scan.decode") for _ in range(3))
    delta = max(cost - base, 0.0)
    # the checkpoint's real in-query cost: a live token registered and
    # bound to the measuring thread (the clean-path worst case — the
    # no-query fast path is a single dict truthiness read)
    tok = lifecycle.begin_action(None, session.conf)
    try:
        base0 = min(loop(baseline) for _ in range(3))
        lc_cost = min(loop(orig_check) for _ in range(3))
    finally:
        lifecycle.finish_action(tok, "ok")
    lc_delta = max(lc_cost - base0, 0.0)
    added = counts["passes"] * delta + counts["lc_passes"] * lc_delta
    overhead = added / best if best else 0.0
    return {
        "drive_best_s": round(best, 5),
        "hook_passes_per_drive": counts["passes"],
        "per_pass_delta_ns": round(delta * 1e9, 1),
        "lifecycle_passes_per_drive": counts["lc_passes"],
        "lifecycle_per_pass_delta_ns": round(lc_delta * 1e9, 1),
        "disabled_overhead_pct": round(overhead * 100, 4),
        "ok": (counts["passes"] > 0 and counts["lc_passes"] > 0
               and overhead <= tolerance),
    }


def _cancel_storm(session, dfs, expected, rng: random.Random,
                  n_runs: int) -> dict:
    """Gate 4: the seeded cancellation storm. Four delivery modes cycle
    across n_runs: `site` (a query.cancel:cancel schedule fires at a
    random checkpoint pass — mid-scan/mid-shuffle/mid-agg — sometimes
    stacked with retry OOMs so the cancel lands mid-retry), `external`
    (session.cancel from another thread mid-flight, latency measured),
    `queued` (admission gate at maxConcurrent=1, the parked query
    cancelled), and `survivor` (a clean run proving neighbors are
    untouched). Asserts the cancellation-latency bound, zero stranded
    permits, device bytes back to baseline, zero leaked tokens, and
    byte-identical surviving results."""
    from spark_rapids_tpu.runtime.lifecycle import QueryCancelledError
    from spark_rapids_tpu.runtime.memory import peek_spill_framework
    from spark_rapids_tpu.runtime.semaphore import peek_semaphore

    fw = peek_spill_framework()
    base_dev = fw.device_bytes_held() if fw is not None else 0
    lifecycle.set_checkpoint_probe(True)
    session.conf.set(C.FAULTS_SPEC, "")
    runs, failures, latencies = [], [], []
    slow_spec = "scan.decode:delay:80"

    def collect_one(qn, box):
        try:
            res = NDS.QUERIES[qn](session, dfs).collect()
            box["status"] = "ok"
            box["correct"] = _canon(res) == expected[qn]
        except QueryCancelledError as e:
            box["status"] = "cancelled"
            box["reason"] = e.reason
            box["correct"] = True  # a cancelled query returns nothing
        except BaseException as e:  # noqa: BLE001 - the gate inspects
            box["status"] = "raised:" + type(e).__name__
            box["correct"] = False
        box["done_mono"] = time.monotonic()

    def wait_for(cond, timeout=30.0):
        t0 = time.monotonic()
        while not cond():
            if time.monotonic() - t0 > timeout:
                return False
            time.sleep(0.005)
        return True

    for i in range(n_runs):
        qn = CHAOS_QUERIES[rng.randrange(len(CHAOS_QUERIES))]
        mode = ("site", "external", "queued", "survivor")[i % 4]
        rec = {"i": i, "q": qn, "mode": mode}
        if mode == "site":
            spec = f"query.cancel:cancel:1,{rng.randint(0, 120)}"
            if rng.random() < 0.5:
                spec += ";retry.oom:oom:2"  # cancel can land mid-retry
            session.conf.set(C.FAULTS_SPEC, spec)
            box = {}
            collect_one(qn, box)
            session.conf.set(C.FAULTS_SPEC, "")
            rec.update(box, spec=spec)
            # a skip past the query's total checkpoint passes completes
            # clean — that run doubles as a survivor check
            if box["status"] not in ("ok", "cancelled") \
                    or not box["correct"]:
                failures.append(rec)
        elif mode == "external":
            session.conf.set(C.FAULTS_SPEC, slow_spec)
            box = {}
            th = threading.Thread(target=collect_one, args=(qn, box))
            th.start()
            if not wait_for(lambda: lifecycle.token_ids()):
                failures.append(dict(rec, error="no token appeared"))
                th.join(60)
                continue
            time.sleep(rng.random() * 0.15)
            ids = lifecycle.token_ids()
            t_cancel = time.monotonic()
            fired = bool(ids) and session.cancel(ids[0], reason="storm")
            th.join(60)
            session.conf.set(C.FAULTS_SPEC, "")
            rec.update(box, fired=fired)
            if fired and box.get("status") == "cancelled":
                lat = box["done_mono"] - t_cancel
                latencies.append(lat)
                rec["latency_s"] = round(lat, 3)
            # raced completion (fired=False -> ok) is legal; anything
            # else outside ok/cancelled is not
            if box.get("status") not in ("ok", "cancelled") \
                    or not box.get("correct"):
                failures.append(rec)
        elif mode == "queued":
            session.conf.set(C.QUERY_MAX_CONCURRENT, 1)
            session.conf.set(C.FAULTS_SPEC, slow_spec)
            box_a, box_b = {}, {}
            tha = threading.Thread(target=collect_one, args=(qn, box_a))
            tha.start()
            if not wait_for(lambda: lifecycle.token_ids()):
                failures.append(dict(rec, error="A never started"))
                tha.join(60)
                session.conf.set(C.QUERY_MAX_CONCURRENT, 0)
                continue
            thb = threading.Thread(target=collect_one, args=(qn, box_b))
            thb.start()
            if not wait_for(
                    lambda: lifecycle.gate().doc()["queued"] == 1):
                failures.append(dict(rec, error="B never queued"))
            else:
                qb = max(lifecycle.token_ids())
                t_cancel = time.monotonic()
                session.cancel(qb, reason="storm")
                thb.join(60)
                if box_b.get("status") == "cancelled":
                    latencies.append(box_b["done_mono"] - t_cancel)
                else:
                    failures.append(dict(rec, b=dict(box_b),
                                         error="queued cancel missed"))
            tha.join(120)
            session.conf.set(C.FAULTS_SPEC, "")
            session.conf.set(C.QUERY_MAX_CONCURRENT, 0)
            rec.update(a=dict(box_a, done_mono=None),
                       b=dict(box_b, done_mono=None))
            if box_a.get("status") != "ok" or not box_a.get("correct"):
                failures.append(dict(rec, error="running neighbor "
                                     "disturbed by queued cancel"))
        else:  # survivor
            box = {}
            collect_one(qn, box)
            rec.update(box)
            if box["status"] != "ok" or not box["correct"]:
                failures.append(rec)
        runs.append(rec)

    lifecycle.set_checkpoint_probe(False)
    max_gap = lifecycle.checkpoint_max_gap_s()
    # terminal-latency bound: 2x the longest observed checkpoint
    # interval, plus a fixed epilogue allowance (the cancelled query
    # still flushes its trace/attribution/history after the unwind)
    bound = 2.0 * max_gap + 0.5
    over = [round(v, 3) for v in latencies if v > bound]
    cancelled_runs = sum(1 for r in runs if (r.get("status") == "cancelled"
                                             or (r.get("b") or {}).get(
                                                 "status") == "cancelled"))
    sem = peek_semaphore()
    stranded = 0 if sem is None else (sem.permits - sem.available)
    doc = {
        "runs": len(runs),
        "cancelled_runs": cancelled_runs,
        "max_checkpoint_gap_s": round(max_gap, 4),
        "latency_bound_s": round(bound, 4),
        "max_cancel_latency_s": round(max(latencies), 4) if latencies
        else None,
        "latencies_over_bound": over,
        "stranded_permits": stranded,
        "parked_waiters": 0 if sem is None else sem.waiting,
        "device_bytes_delta": (fw.device_bytes_held() - base_dev)
        if fw is not None else 0,
        "leaked_tokens": lifecycle.token_ids(),
        "failures": failures[:10],
        "ok": (not failures and not over and cancelled_runs >= n_runs // 3
               and stranded == 0
               and (sem is None or sem.waiting == 0)
               and not lifecycle.token_ids()
               and (fw is None
                    or fw.device_bytes_held() == base_dev)),
    }
    return doc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=20260803)
    ap.add_argument("--sf", type=float, default=0.002)
    ap.add_argument("--max-rounds", type=int, default=14)
    ap.add_argument("--min-faults", type=int, default=200)
    ap.add_argument("--min-sites", type=int, default=6)
    ap.add_argument("--cancel-runs", type=int, default=20)
    ap.add_argument("--deadline", type=float, default=480.0)
    ap.add_argument("--tolerance", type=float, default=0.02)
    args = ap.parse_args()

    deadline_done = _arm_deadline(args.deadline)
    threads_before = {t.name for t in threading.enumerate()}

    watchdog.uninstall_for_tests()
    faults.reset_counters()
    session = TpuSession(dict(CHAOS_CONF))
    dfs = {name: session.create_dataframe(t, num_partitions=2)
           for name, t in NDS.gen_tables(args.sf, seed=args.seed).items()}

    # gate 1 first: the overhead half measures DISABLED hooks, before
    # any chaos schedule or watchdog state exists
    ov = _overhead_gate(session, dfs, args.tolerance)
    session.conf.set(C.WATCHDOG_ENABLED, True)

    # clean expected results (same confs, no faults)
    session.conf.set(C.FAULTS_SPEC, "")
    expected = {}
    for qn in CHAOS_QUERIES:
        expected[qn] = _canon(NDS.QUERIES[qn](session, dfs).collect())
        assert session.last_action_status[0] == "ok", \
            f"clean run of q{qn} not ok: {session.last_action_status}"

    rng = random.Random(args.seed)
    runs = []
    failures = []
    rounds = 0
    while rounds < args.max_rounds:
        rounds += 1
        for qn in CHAOS_QUERIES:
            spec = _gen_spec(rng)
            session.conf.set(C.FAULTS_SPEC, spec)
            fired0 = faults.total_fired()
            t0 = time.perf_counter()
            try:
                result = NDS.QUERIES[qn](session, dfs).collect()
                status, reason = session.last_action_status
                correct = _canon(result) == expected[qn]
            except BaseException as e:  # noqa: BLE001 - a chaos run may
                # never raise: ok or degraded are the only legal ends
                status, reason, correct = "raised", type(e).__name__, False
            rec = {"q": qn, "spec": spec, "status": status,
                   "reason": reason, "correct": correct,
                   "fired": faults.total_fired() - fired0,
                   "seconds": round(time.perf_counter() - t0, 3)}
            runs.append(rec)
            if status not in ("ok", "degraded") or not correct:
                failures.append(rec)
        if faults.total_fired() >= args.min_faults and \
                len(faults.fault_counts()) >= args.min_sites:
            break

    # gate 4: the cancellation storm runs after the fault rounds (warm
    # caches keep its checkpoint intervals honest)
    session.conf.set(C.FAULTS_SPEC, "")
    faults.configure("")
    cancel_doc = _cancel_storm(session, dfs, expected, rng,
                               args.cancel_runs)

    session.conf.set(C.FAULTS_SPEC, "")
    faults.configure("")  # disarm leftovers before the thread census
    wedge_specs = sum(1 for r in runs if ":wedge" in r["spec"])
    from spark_rapids_tpu.runtime import obs
    st = obs.state()
    watchdog_timeouts = int(st.registry.counter(
        "rapids_watchdog_dispatch_timeouts_total").value) if st else 0
    watchdog.uninstall_for_tests()
    time.sleep(0.3)  # drained pool/service threads settle

    allowed = ("rapids-host-pool", "rapids-obs", "rapids-task",
               "rapids-query-deadline", "chaos-deadline", "pymain",
               "MainThread")
    leaked = sorted(
        t.name for t in threading.enumerate()
        if t.name not in threads_before
        and not any(t.name.startswith(p) for p in allowed))

    counts = faults.fault_counts()
    result = {
        "seed": args.seed,
        "rounds": rounds,
        "runs": len(runs),
        "faults_fired": faults.total_fired(),
        "distinct_sites": sorted(counts),
        "per_site": counts,
        "degraded_runs": sum(1 for r in runs if r["status"] == "degraded"),
        "ok_runs": sum(1 for r in runs if r["status"] == "ok"
                       and r["correct"]),
        "failures": failures[:10],
        "leaked_threads": leaked,
        "wedge_specs": wedge_specs,
        "watchdog_timeouts": watchdog_timeouts,
        "overhead": ov,
        "cancel_storm": cancel_doc,
    }
    print(json.dumps(result))

    ok = True
    if failures:
        print(f"FAIL: {len(failures)} chaos run(s) ended outside "
              f"ok/degraded or with wrong results:\n"
              + "\n".join(json.dumps(f) for f in failures[:10]),
              file=sys.stderr)
        ok = False
    if result["faults_fired"] < args.min_faults:
        print(f"FAIL: only {result['faults_fired']} faults fired "
              f"(need >= {args.min_faults})", file=sys.stderr)
        ok = False
    if len(counts) < args.min_sites:
        print(f"FAIL: only {len(counts)} distinct sites fired "
              f"({sorted(counts)}; need >= {args.min_sites})",
              file=sys.stderr)
        ok = False
    if leaked:
        print(f"FAIL: leaked threads after chaos: {leaked}",
              file=sys.stderr)
        ok = False
    if wedge_specs and watchdog_timeouts == 0:
        print(f"FAIL: {wedge_specs} schedule(s) included a wedge fault "
              f"but the watchdog reported no dispatch timeouts — the "
              f"wedge->watchdog->breaker path never ran", file=sys.stderr)
        ok = False
    if not ov["ok"]:
        print(f"FAIL: disabled fault-hook overhead "
              f"{ov['disabled_overhead_pct']}% exceeds "
              f"{args.tolerance * 100:.1f}% (or no hook passes counted)",
              file=sys.stderr)
        ok = False
    if not cancel_doc["ok"]:
        print(f"FAIL: cancellation storm gate failed: "
              f"{json.dumps(cancel_doc)}", file=sys.stderr)
        ok = False

    deadline_done.set()
    if not ok:
        return 1
    print(f"PASS: {result['faults_fired']} faults across "
          f"{len(counts)} sites over {len(runs)} runs "
          f"({result['degraded_runs']} degraded, all correct); "
          f"{cancel_doc['cancelled_runs']} cancels over "
          f"{cancel_doc['runs']} storm runs, max latency "
          f"{cancel_doc['max_cancel_latency_s']}s within bound "
          f"{cancel_doc['latency_bound_s']}s; no leaked threads; "
          f"disabled-hook overhead {ov['disabled_overhead_pct']}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
