"""Typed config registry with documentation generation.

Reference parity: com/nvidia/spark/rapids/RapidsConf.scala (251 typed
`spark.rapids.*` entries built by a ConfBuilder DSL with doc strings and a
`help` main that emits docs/configs.md). Same design here: every knob is
declared once with type/default/doc, values can be overridden per-session,
and `generate_docs()` renders the registry to markdown.
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, Optional

_REGISTRY: "Dict[str, ConfEntry]" = {}


@dataclasses.dataclass(frozen=True)
class ConfEntry:
    key: str
    default: Any
    doc: str
    conv: Callable[[str], Any]
    internal: bool = False
    startup_only: bool = False
    commonly_used: bool = False

    def render_default(self) -> str:
        return "None" if self.default is None else str(self.default)


def _bool_conv(s: str) -> bool:
    return str(s).strip().lower() in ("1", "true", "yes", "on")


def _register(key, default, doc, conv, **kw) -> ConfEntry:
    e = ConfEntry(key, default, doc, conv, **kw)
    if key in _REGISTRY:
        raise ValueError(f"duplicate conf key {key}")
    _REGISTRY[key] = e
    return e


def conf_bool(key, default, doc, **kw):
    return _register(key, default, doc, _bool_conv, **kw)


def conf_int(key, default, doc, **kw):
    return _register(key, default, doc, int, **kw)


def conf_float(key, default, doc, **kw):
    return _register(key, default, doc, float, **kw)


def conf_str(key, default, doc, **kw):
    return _register(key, default, doc, str, **kw)


# ---------------------------------------------------------------------------
# The registry. Key namespace mirrors the reference's spark.rapids.* layout
# so users migrating from the reference find the same knobs.
# ---------------------------------------------------------------------------

SQL_ENABLED = conf_bool(
    "spark.rapids.sql.enabled", True,
    "Enable TPU acceleration of SQL plans (reference RapidsConf.scala:801).",
    commonly_used=True)

SQL_MODE = conf_str(
    "spark.rapids.sql.mode", "executeOnTPU",
    "executeOnTPU runs supported operators on TPU; explainOnly plans and "
    "reports what would run on TPU without requiring a device "
    "(reference RapidsConf.scala:807).",
    commonly_used=True)

SQL_EXPLAIN = conf_str(
    "spark.rapids.sql.explain", "NOT_ON_TPU",
    "What to log about plan placement: NONE, NOT_ON_TPU (every fallback with "
    "its reason), ALL (reference RapidsConf.scala:2107).",
    commonly_used=True)

CONCURRENT_TPU_TASKS = conf_int(
    "spark.rapids.sql.concurrentTpuTasks", 2,
    "Number of tasks admitted to the device concurrently by the semaphore "
    "(reference GpuSemaphore / RapidsConf.scala:545).",
    commonly_used=True)

TARGET_BATCH_SIZE = conf_int(
    "spark.rapids.sql.batchSizeBytes", 1 << 30,
    "Target columnar batch size in bytes; coalesce goals aim for this "
    "(reference gpuTargetBatchSizeBytes).",
    commonly_used=True)

MAX_READER_BATCH_SIZE_ROWS = conf_int(
    "spark.rapids.sql.reader.batchSizeRows", 1 << 20,
    "Soft cap on rows per batch produced by scans.")

BATCH_CAPACITY_MIN = conf_int(
    "spark.rapids.tpu.batchCapacityMinRows", 1024,
    "Minimum padded row capacity of a device batch; capacities are rounded "
    "to size buckets so XLA compiles each stage once per bucket.")

DEVICE_MEMORY_FRACTION = conf_float(
    "spark.rapids.memory.tpu.allocFraction", 0.85,
    "Fraction of per-chip HBM the arena budget may use "
    "(reference rmm.pool allocFraction).", startup_only=True)

WRITER_THREADS = conf_int(
    "spark.rapids.sql.asyncWrite.numThreads", 4,
    "Background threads encoding+writing output files (reference "
    "io/async ThrottlingExecutor).")

OPTIMIZER_ENABLED = conf_bool(
    "spark.rapids.sql.optimizer.enabled", False,
    "Cost-based reversion of TPU subtrees whose estimated device cost "
    "(incl. transfer + dispatch) exceeds the CPU cost "
    "(reference CostBasedOptimizer.scala, off by default).")

PROFILE_DIR = conf_str(
    "spark.rapids.profile.dir", "",
    "When set, each collect() runs under a jax.profiler trace written to "
    "this directory (XProf/TensorBoard-viewable; the reference's "
    "CUPTI-based Profiler + NVTX analog).")

TRACE_ENABLED = conf_bool(
    "spark.rapids.sql.trace.enabled", False,
    "Record a structured trace per query: spans for every exec's device "
    "work (tied to the same GpuMetric timers the SQL metrics use — one "
    "instrumentation point), instant events for semaphore/spill/retry/"
    "host-pool/fused-dispatch activity, and a per-task accumulator event "
    "log, written as Chrome-trace-event JSON plus JSONL under "
    "spark.rapids.sql.trace.path and aggregated offline by "
    "tools/profiler_report.py (reference NvtxWithMetrics + "
    "ProfilerOnExecutor). Off by default; the disabled path costs one "
    "branch per span.", commonly_used=True)

TRACE_PATH = conf_str(
    "spark.rapids.sql.trace.path", "/tmp/rapids_tpu_trace",
    "Directory receiving per-query trace artifacts "
    "(query_<n>_trace.json / _events.jsonl / _metrics.json) when "
    "spark.rapids.sql.trace.enabled is set (reference "
    "spark.rapids.profile pathPrefix).")

TRACE_LEVEL = conf_str(
    "spark.rapids.sql.trace.level", "MODERATE",
    "Trace verbosity, reusing the metric levels: ESSENTIAL (exec spans + "
    "task rollups), MODERATE (+ semaphore/spill/retry/dispatch instants), "
    "DEBUG (+ host-pool queueing, shuffle serde, per-stage internals).")

TRACE_TASK_METRICS = conf_bool(
    "spark.rapids.sql.trace.taskMetrics", True,
    "Roll per-task accumulators (retry count/time, spill bytes/time, "
    "semaphore wait, max device bytes held — the GpuTaskMetrics analog) "
    "into the per-query event log at task completion.")

SANITIZER_ENABLED = conf_bool(
    "spark.rapids.debug.sanitizer.enabled", False,
    "Enable the runtime concurrency sanitizer (analysis/sanitizer.py): "
    "the engine's named lock sites record a process-wide lock-"
    "acquisition-order graph, report cycles (potential ABBA deadlocks) "
    "the first time both orders are merely observed, flag locks held "
    "past the holdWarnMs threshold (blocking work inside a critical "
    "section — the runtime twin of tpulint TPU-L001), and flag "
    "Condition waits made while other locks are held. Findings rank in "
    "sanitizer.report() and emit sanitizerFinding trace instants via "
    "sanitizer.dump(). Debug-only: enabled runs capture a stack per "
    "acquire; disabled, every lock operation costs one global read "
    "(gated <2% by tools/sanitizer_smoke.py).")

SANITIZER_HOLD_WARN_MS = conf_float(
    "spark.rapids.debug.sanitizer.holdWarnMs", 50.0,
    "Hold-duration threshold (milliseconds) above which the sanitizer "
    "reports a held-lock-blocking finding with the acquire-site stack.")

SANITIZER_STACK_DEPTH = conf_int(
    "spark.rapids.debug.sanitizer.stackDepth", 8,
    "Innermost stack frames captured per lock acquisition while the "
    "sanitizer is enabled (deeper = better reports, slower acquires).")

PLAN_VERIFY_ENABLED = conf_bool(
    "spark.rapids.debug.planVerify.enabled", False,
    "Run the plan-invariant verifier (analysis/plan_verify.py) on every "
    "converted exec tree: schema consistency across exec boundaries, "
    "fusion-group legality, and pipeline-boundary sanity. Violations "
    "raise PlanVerifyError before execution starts. Always exercised in "
    "CI against the golden dispatch budgets regardless of this conf.")

OBS_ENABLED = conf_bool(
    "spark.rapids.obs.enabled", True,
    "Publish live metrics into the process-wide observability registry "
    "(runtime/obs): task accumulators fold in once per task completion, "
    "per-exec rollups once per query — never per batch. Disabled, every "
    "hook costs one global read (same budget as trace.py). The registry "
    "feeds the /metrics endpoint and the query history store.")

OBS_PORT = conf_int(
    "spark.rapids.obs.port", 0,
    "When > 0, serve a background HTTP endpoint on this port: /metrics "
    "(Prometheus text format from the live registry) and /healthz (JSON: "
    "device liveness via a trivial dispatch probe, semaphore saturation, "
    "spill pressure, last-query status; HTTP 200 ok / 503 degraded). "
    "0 disables the endpoint (the reference surfaces GpuMetrics through "
    "the Spark UI; a standalone engine scrapes).", commonly_used=True)

OBS_HISTORY_DIR = conf_str(
    "spark.rapids.obs.historyDir", "",
    "When set, append one JSON record per query to "
    "<dir>/query_history.jsonl: plan digest, per-exec metric rollups, "
    "fusion groups, fallback reasons, config delta, wall time, status "
    "(ok/failed + exception class), trace artifact paths. Rendered by "
    "tools/history_server.py (query list -> annotated plan -> "
    "run-over-run diff by plan digest); tools/nds_probe.py appends its "
    "scorecards here too.", commonly_used=True)

OBS_PROBE_TIMEOUT_MS = conf_int(
    "spark.rapids.obs.probeTimeoutMs", 2000,
    "Timeout for the /healthz device dispatch probe; a probe that "
    "exceeds it reports the device as blocked and flips the endpoint "
    "to degraded (503).")

OBS_FLIGHT_ENABLED = conf_bool(
    "spark.rapids.obs.flight.enabled", True,
    "Run the always-on flight recorder (runtime/obs/flight.py): a "
    "bounded per-thread ring of the most recent span/instant events, "
    "fed from the SAME instrumentation points structured tracing uses, "
    "auto-dumped as a Chrome-trace file when a query fails or degrades, "
    "the dispatch watchdog reports a wedge, the circuit breaker opens, "
    "or a query breaches its SLO — so failures get a timeline "
    "retroactively even with spark.rapids.sql.trace.enabled off. The "
    "hot path takes no locks (one tuple store per recorded event; "
    "DEBUG-level events are filtered); overhead is gated <2% by "
    "tools/flight_smoke.py.", commonly_used=True)

OBS_FLIGHT_PATH = conf_str(
    "spark.rapids.obs.flight.path", "/tmp/rapids_tpu_flight",
    "Directory receiving flight-recorder dumps "
    "(flight_<seq>_<reason>.json, Chrome-trace/Perfetto loadable).")

OBS_FLIGHT_EVENTS = conf_int(
    "spark.rapids.obs.flight.events", 2048,
    "Per-thread ring capacity of the flight recorder: how many recent "
    "span/instant events each thread retains for a retroactive dump. "
    "Older events are overwritten; the dump reports how many were "
    "dropped.")

OBS_FLIGHT_MIN_INTERVAL_S = conf_float(
    "spark.rapids.obs.flight.minIntervalSeconds", 5.0,
    "Rate limit between flight-recorder dumps: a failure storm dumps at "
    "most one timeline per interval instead of one per failing query. "
    "0 disables the limit (tests).")

OBS_FLIGHT_MAX_DUMPS = conf_int(
    "spark.rapids.obs.flight.maxDumps", 50,
    "Bounded retention: only the newest N flight dump files are kept in "
    "spark.rapids.obs.flight.path; older ones are pruned after each "
    "dump.")

OBS_REQTRACE_ENABLED = conf_bool(
    "spark.rapids.obs.reqtrace.enabled", False,
    "Run the per-request tail-sampled tracer "
    "(runtime/obs/reqtrace.py): every serving request buffers its span "
    "tree (serving spans + the engine exec spans of its query, joined "
    "by query id) in a bounded per-request ring fed from the SAME "
    "instrumentation points the flight recorder uses. At request end a "
    "sampling verdict either drops the buffer or exports a "
    "self-contained per-request timeline (Chrome-trace + an OTLP-JSON-"
    "shaped file) under reqtrace.path. Errors, cancellations, "
    "deadlines, SLO breaches and runs slower than the digest baseline "
    "are ALWAYS kept; ordinary requests and hot cache hits sample at "
    "reqtrace.sampleRatio. The disabled path is one module-global "
    "read; armed overhead is gated <2% by tools/reqtrace_smoke.py.",
    commonly_used=True)

OBS_REQTRACE_PATH = conf_str(
    "spark.rapids.obs.reqtrace.path", "/tmp/rapids_tpu_reqtrace",
    "Directory receiving per-request timeline exports "
    "(req_<seq>_<verdict>_<trace_id>.json Chrome-trace files plus the "
    "matching req_<seq>_<verdict>_<trace_id>.otlp.json OTLP-JSON-"
    "shaped file).")

OBS_REQTRACE_EVENTS = conf_int(
    "spark.rapids.obs.reqtrace.events", 4096,
    "Per-request ring capacity: how many span/instant events one "
    "request retains for its timeline. Older events are overwritten; "
    "the export reports how many were dropped.")

OBS_REQTRACE_SAMPLE_RATIO = conf_float(
    "spark.rapids.obs.reqtrace.sampleRatio", 0.01,
    "Probability that an ordinary successful request (including a hot "
    "result-cache hit) exports its timeline. Error/cancelled/deadline/"
    "SLO-breach/slower-than-baseline requests always export regardless "
    "of this ratio. 0 keeps only the always-keep classes.")

OBS_REQTRACE_MIN_INTERVAL_S = conf_float(
    "spark.rapids.obs.reqtrace.minIntervalSeconds", 1.0,
    "Rate limit between per-request timeline exports: a failure storm "
    "exports at most one timeline per interval (always-keep verdicts "
    "and sampled keeps alike). 0 disables the limit (tests).")

OBS_REQTRACE_MAX_DUMPS = conf_int(
    "spark.rapids.obs.reqtrace.maxDumps", 100,
    "Bounded retention: only the newest N per-request exports (Chrome "
    "+ OTLP pairs) are kept in spark.rapids.obs.reqtrace.path; older "
    "ones are pruned after each export.")

OBS_REPLICA_ID = conf_str(
    "spark.rapids.obs.replicaId", "",
    "Stable identity of THIS serving replica in a fleet sharing one "
    "spark.rapids.obs.historyDir. Stamped into every query history "
    "record, response doc and per-request timeline so "
    "tools/fleet_report.py can split a digest's latency/compile/cache "
    "profile per replica. Empty (the default) derives pid-<os pid>, "
    "which is unique per process but not stable across restarts.",
    commonly_used=True)

OBS_SLO_ENABLED = conf_bool(
    "spark.rapids.obs.slo.enabled", True,
    "Check every successful top-level query against its SLO "
    "(runtime/obs/slo.py): a per-plan-digest latency baseline built "
    "from the query history (mean of the last slo.baselineWindow ok "
    "runs, armed after slo.minRuns samples) times slo.baselineFactor, "
    "plus the absolute bound slo.latencySeconds. A breach emits a "
    "slowQuery instant, bumps rapids_slo_breaches_total, surfaces on "
    "/healthz with its attribution summary, and triggers a "
    "flight-recorder dump. Baselines seed from "
    "spark.rapids.obs.historyDir when set, so they survive restarts.")

OBS_SLO_FACTOR = conf_float(
    "spark.rapids.obs.slo.baselineFactor", 3.0,
    "A query breaches its SLO when its wall time exceeds the per-digest "
    "baseline mean times this factor.")

OBS_SLO_MIN_RUNS = conf_int(
    "spark.rapids.obs.slo.minRuns", 5,
    "Successful runs of a plan digest required before its baseline arms "
    "(fewer samples would flag ordinary warm-up variance).")

OBS_SLO_ABS_SECONDS = conf_float(
    "spark.rapids.obs.slo.latencySeconds", 0.0,
    "Absolute per-query latency SLO in seconds, checked regardless of "
    "baseline state. 0 disables the absolute bound (the baseline check "
    "still applies).")

OBS_SLO_WINDOW = conf_int(
    "spark.rapids.obs.slo.baselineWindow", 32,
    "Successful runs per plan digest retained for the baseline mean "
    "(a bounded sliding window, newest runs win).")

OBS_CORS_ORIGIN = conf_str(
    "spark.rapids.obs.corsOrigin", "",
    "Value for the Access-Control-Allow-Origin header on obs endpoint "
    "responses. Empty (the default) sends no CORS header, so browser "
    "pages from other origins cannot read /queries (which carries "
    "in-flight SQL text) or /healthz. Set it to the history server's "
    "origin (or '*' on a trusted host) to enable the "
    "tools/history_server.py --engine live-console page, which polls "
    "the endpoint cross-origin from the browser.")

OBS_PROGRESS_ENABLED = conf_bool(
    "spark.rapids.obs.progress.enabled", True,
    "Register every top-level action in the live query registry "
    "(runtime/obs/live.py): query id, plan digest, state machine "
    "(queued -> planning -> executing -> finishing -> ok/failed/"
    "degraded), and per-exec batches/rows progress with %-complete and "
    "ETA derived from the plan's scan-size estimates. Surfaced by "
    "session.running_queries(), the /queries JSON endpoint, and the "
    "/console live page. Progress reads are pull-based snapshots of "
    "the metrics the execs already keep (no per-batch publish) and "
    "never resolve lazy device counts, so a scrape adds no device "
    "syncs to a running query.")

OBS_SAMPLER_ENABLED = conf_bool(
    "spark.rapids.obs.sampler.enabled", True,
    "Run the always-on resource time-series sampler "
    "(runtime/obs/sampler.py): a service thread samples the SERIES "
    "roster (device/host bytes held, semaphore permits and waiters, "
    "host-pool queue depths, pipeline stall state, breaker state, "
    "process RSS, running queries) into bounded per-series rings "
    "every sampler.intervalMs. Exported as rapids_sampler_* gauges on "
    "/metrics, rendered as sparklines on /console, and embedded as "
    "Chrome counter tracks in every flight-recorder dump so a "
    "post-mortem carries the resource context leading up to the "
    "trigger.")

OBS_SAMPLER_INTERVAL_MS = conf_int(
    "spark.rapids.obs.sampler.intervalMs", 200,
    "Resource-sampler period in milliseconds. Each tick reads ~10 "
    "in-process gauges (no locks shared with query hot paths, no "
    "device syncs); the ring covers ringSize*intervalMs of history.")

OBS_SAMPLER_RING = conf_int(
    "spark.rapids.obs.sampler.ringSize", 512,
    "Samples retained per sampler series (a bounded ring, newest "
    "kept — the flight-recorder ring discipline). At the default "
    "200ms interval, 512 samples cover the last ~102 seconds.")

OBS_AUDIT_ENABLED = conf_bool(
    "spark.rapids.obs.audit.enabled", False,
    "Arm the kernel cost auditor (analysis/kernel_audit.py): every "
    "computation resolved through the compile-cache choke point is "
    "audited AT TRACE TIME for XLA flops, bytes accessed, input/output "
    "plane bytes and shape-bucket padding exposure, deduped per "
    "(entry, shape signature) so steady-state dispatches add zero "
    "work. Joined with dispatch tallies and attribution device "
    "seconds into per-query roofline attribution: achieved GB/s and "
    "FLOP/s, % of the configured rooflines, memory/compute/"
    "dispatch-overhead boundedness — surfaced in "
    "explain(mode='analyze'), history records, rapids_roofline_* "
    "gauges, /console, and tools/roofline_report.py. Off by default: "
    "audited runs pay one extra lower+compile per traced shape at "
    "resolution time (CI's audit_smoke and the golden cost-signature "
    "generator run with it on).")

OBS_AUDIT_PEAK_GBPS = conf_float(
    "spark.rapids.obs.audit.peakGbps", 819.0,
    "Memory-bandwidth roofline in GB/s for roofline attribution "
    "(819 = one v5e chip's HBM bandwidth). Achieved GB/s is audited "
    "bytes over measured device seconds; roofline_pct_bw is its share "
    "of this peak.")

OBS_AUDIT_PEAK_GFLOPS = conf_float(
    "spark.rapids.obs.audit.peakGflops", 197000.0,
    "Compute roofline in GFLOP/s for roofline attribution (197000 = "
    "one v5e chip's bf16 peak). Drives roofline_pct_flops and the "
    "memory-vs-compute boundedness verdict.")

OBS_AUDIT_OVERHEAD_FACTOR = conf_float(
    "spark.rapids.obs.audit.overheadBoundFactor", 10.0,
    "A kernel group whose measured device seconds exceed this multiple "
    "of its best-case roofline time (max of bytes/peakGbps and "
    "flops/peakGflops) classifies as dispatch_overhead-bound: the "
    "device is waiting on per-dispatch latency, not moving data or "
    "computing.")

LORE_DUMP_DIR = conf_str(
    "spark.rapids.sql.lore.dumpPath", "",
    "When set, every exec's input batches dump as parquet under "
    "<dir>/<loreId>/ for local operator replay "
    "(reference LORE, lore/GpuLore.scala).")

SORT_OOC_BYTES = conf_int(
    "spark.rapids.sql.sort.outOfCoreBytes", 2 << 30,
    "Sorts over inputs larger than this run out-of-core: the device "
    "computes only the key permutation while row data stages through host "
    "memory (reference GpuSortExec out-of-core merge path).")

JOIN_SUBPARTITION_ROWS = conf_int(
    "spark.rapids.sql.join.subPartitionRows", 8 << 20,
    "Build sides larger than this many rows hash-split into buckets joined "
    "pairwise (skew/no-fit handling; reference GpuSubPartitionHashJoin).")

BROADCAST_JOIN_ROW_THRESHOLD = conf_int(
    "spark.rapids.sql.join.broadcastRowThreshold", 1 << 22,
    "Estimated build-side row count below which joins broadcast instead of "
    "shuffling both sides (reference: Spark autoBroadcastJoinThreshold).")

DEVICE_MEMORY_BUDGET = conf_int(
    "spark.rapids.memory.tpu.budgetBytes", 12 << 30,
    "Cooperative HBM budget in bytes for registered (spillable) batches; "
    "reservations beyond it drain the spill stores "
    "(reference rmm pool size; XLA owns the physical allocator).")

HOST_SPILL_LIMIT = conf_int(
    "spark.rapids.memory.host.spillStorageSize", 4 << 30,
    "Bytes of host memory for spilled device data before overflowing to disk "
    "(reference SpillFramework host store limit).")

SPILL_DIR = conf_str(
    "spark.rapids.memory.spillDir", "/tmp/rapids_tpu_spill",
    "Directory for disk spill files (reference RapidsDiskBlockManager).")

RETRY_OOM_INJECT = conf_str(
    "spark.rapids.sql.test.injectRetryOOM", "",
    "Fault-injection grammar 'count[,skip]' forcing retry-OOMs for tests "
    "(reference RapidsConf.scala:1627,2753).", internal=True)

FAULTS_SPEC = conf_str(
    "spark.rapids.debug.faults", "",
    "General fault-injection schedule (runtime/faults.py): "
    "'site:kind[:count[,skip]]' entries joined by ';', where site is a "
    "registered fault site (scan.decode, shuffle.read, shuffle.write, "
    "spill.disk, device.dispatch, pipeline.producer, exchange.fetch, "
    "retry.oom, query.cancel, semaphore.wait — tpulint TPU-L008 keeps "
    "the roster honest) and kind is ioerror, corrupt (data sites only), "
    "delay, wedge, oom, or cancel (fire the current query's cancel "
    "token at the site — chaos storms use it to deliver cancels at "
    "named checkpoints). Every "
    "fired fault emits a faultInjected trace instant and counts into "
    "rapids_faults_injected_total and /healthz. Empty disables injection "
    "(one global read per site pass — gated <2% by tools/chaos_smoke.py). "
    "Generalizes injectRetryOOM, which remains the retry.oom facade.")

FAULTS_DELAY_MS = conf_float(
    "spark.rapids.debug.faults.delayMs", 50.0,
    "Sleep injected by a 'delay'-kind fault, in milliseconds.")

FAULTS_WEDGE_S = conf_float(
    "spark.rapids.debug.faults.wedgeSeconds", 0.25,
    "Sleep injected by a 'wedge'-kind fault, in seconds. To exercise "
    "the watchdog detection path end-to-end, set this ABOVE "
    "spark.rapids.watchdog.dispatchTimeoutSeconds (tools/chaos_smoke.py "
    "does) — a wedge shorter than the timeout completes unnoticed.")

WATCHDOG_ENABLED = conf_bool(
    "spark.rapids.watchdog.enabled", False,
    "Run the device dispatch watchdog (runtime/watchdog.py): a heartbeat "
    "service thread detects fused dispatches exceeding "
    "dispatchTimeoutSeconds, reports each wedge once (log + "
    "watchdogDispatchTimeout trace instant + obs counter) and records a "
    "circuit-breaker failure so later queries degrade to CPU instead of "
    "joining the wedge (a wedged libtpu holds the GIL — the call itself "
    "cannot be interrupted). Disabled, dispatches run unwrapped at zero "
    "added cost.")

WATCHDOG_DISPATCH_TIMEOUT_S = conf_float(
    "spark.rapids.watchdog.dispatchTimeoutSeconds", 60.0,
    "Deadline for one fused device dispatch before the watchdog reports "
    "it wedged and records a breaker failure.")

WATCHDOG_BREAKER_THRESHOLD = conf_int(
    "spark.rapids.watchdog.breakerFailureThreshold", 3,
    "Consecutive device failures (failed/degraded queries, dispatch "
    "timeouts) that open the device circuit breaker. While open — and "
    "CPU fallback is enabled — queries skip the device entirely and run "
    "degraded on the CPU backend.")

WATCHDOG_BREAKER_BACKOFF_S = conf_float(
    "spark.rapids.watchdog.breakerBaseBackoffSeconds", 1.0,
    "Initial open-state backoff before the breaker half-opens and lets "
    "one probe query try the device again; doubles on each failed probe "
    "up to breakerMaxBackoffSeconds, resets on success.")

WATCHDOG_BREAKER_MAX_BACKOFF_S = conf_float(
    "spark.rapids.watchdog.breakerMaxBackoffSeconds", 60.0,
    "Cap on the breaker's exponential open-state backoff.")

FALLBACK_CPU_ENABLED = conf_bool(
    "spark.rapids.fallback.cpu.enabled", False,
    "Graceful degradation: when a top-level query fails with an engine/"
    "device error (exhausted OOM retries, corrupted shuffle data, a "
    "device error, an injected fault — NOT user-semantic errors like "
    "ANSI overflow, which surface unchanged), re-execute it on the CPU "
    "backend and record it as status=degraded (with the triggering "
    "error class) in query history, /metrics and /healthz instead of "
    "failed. Also consults the device circuit breaker: while the "
    "breaker is open, queries skip the device entirely. Off by default: "
    "batch/test workloads want failures loud; serving deployments flip "
    "this on (the reference's per-operator CPU fallback generalized to "
    "the query failure domain).", commonly_used=True)

RETRY_BACKOFF_BASE_MS = conf_float(
    "spark.rapids.retry.backoffBaseMs", 10.0,
    "Base of the bounded exponential backoff between OOM retry attempts "
    "(after the spill-store drain): attempt n sleeps "
    "base*2^(n-1) ms, jittered to 50-100%, capped at backoffMaxMs — so "
    "concurrent tasks that OOMed together do not re-dispatch together "
    "(thundering herd). Folded into the retryBlockTime accumulator. "
    "0 disables the backoff (drain-then-immediate-retry).")

RETRY_BACKOFF_MAX_MS = conf_float(
    "spark.rapids.retry.backoffMaxMs", 500.0,
    "Cap on the per-attempt OOM retry backoff.")

SHUFFLE_VERIFY_CHECKSUMS = conf_bool(
    "spark.rapids.shuffle.verifyChecksums", True,
    "Verify the CRC32 wire checksum on every serialized shuffle blob at "
    "read time (the serde header carries it; the frame body also keeps "
    "its xxhash64). A corrupt blob triggers ONE transparent re-fetch "
    "from the shuffle store (counted in shuffleCorruptionRetries) "
    "before the error surfaces — a transient disk bit-flip recovers, a "
    "persistent corruption fails the query (and degrades to CPU when "
    "spark.rapids.fallback.cpu.enabled).")

SHUFFLE_MODE = conf_str(
    "spark.rapids.shuffle.mode", "MULTITHREADED",
    "MULTITHREADED: in-process exchange by zero-copy selection-mask "
    "slicing on device (no files or serialization involved); "
    "ICI: device-resident exchange via XLA all-to-all collectives over the "
    "mesh; SERIALIZED: partitions serialize through the kudo-analog wire "
    "format into a spillable host store (parallel writers, compression, "
    "disk overflow — the cross-host-capable path) "
    "(reference RapidsConf.scala:1767 UCX|CACHE_ONLY|MULTITHREADED).")

SHUFFLE_PARTITIONING = conf_str(
    "spark.rapids.shuffle.partitioning", "compact",
    "Device repartition strategy for hash/round-robin/range exchanges. "
    "'compact': ONE fused counting-sort kernel per input batch permutes "
    "rows so each target partition is contiguous, a single host fetch of "
    "the n_out+1 offsets vector sizes the outputs, and downstream "
    "operators see right-sized sub-batches (the analog of cudf's "
    "hash-partition kernel returning a table plus offsets). 'masked': "
    "legacy zero-copy selection-mask slicing emitting n_out full-capacity "
    "sub-batches per input batch (escape hatch; costs n_out deferred "
    "count syncs and n_out*capacity downstream work per batch).")

SHUFFLE_WRITER_THREADS = conf_int(
    "spark.rapids.shuffle.multiThreaded.writer.threads", 8,
    "Threads in the executor-wide shuffle writer pool "
    "(reference RapidsShuffleInternalManagerBase.scala:119-218).")

SHUFFLE_READER_THREADS = conf_int(
    "spark.rapids.shuffle.multiThreaded.reader.threads", 8,
    "Threads in the executor-wide shuffle reader pool.")

SHUFFLE_COMPRESSION = conf_str(
    "spark.rapids.shuffle.compression.codec", "auto",
    "Codec for serialized shuffle tables: auto, none, zstd, zlib "
    "(reference TableCompressionCodec; nvcomp lz4 has no TPU-side analog "
    "in this environment, zstd plays that role). 'auto' resolves to zstd "
    "when the zstandard package is importable and zlib (stdlib, always "
    "present) otherwise; naming zstd explicitly without the package "
    "fails fast.")

SHUFFLE_HOST_BUDGET = conf_int(
    "spark.rapids.shuffle.hostSpillBudget", 256 << 20,
    "Host bytes the SERIALIZED shuffle store may hold resident before "
    "partitions flush to disk spill files "
    "(reference ShuffleBufferCatalog spillable shuffle data).")

ADAPTIVE_ENABLED = conf_bool(
    "spark.rapids.sql.adaptive.enabled", True,
    "Adaptive query execution (the AQE role: reference "
    "GpuCustomShuffleReaderExec / per-stage re-planning): pick the join "
    "strategy at RUNTIME from the measured build side, convert a shuffled "
    "hash join to broadcast when the materialized build side lands under "
    "the byte threshold, split skewed post-shuffle partitions, reuse "
    "materialized broadcast builds across queries, and let the measured "
    "cost pass (plan/cost.py) replan from audited history. Master switch "
    "for every spark.rapids.sql.adaptive.* feature below.")

ADAPTIVE_BROADCAST_BYTES = conf_int(
    "spark.rapids.sql.adaptive.broadcastThresholdBytes", 64 << 20,
    "Runtime shuffle-hash -> broadcast conversion threshold: the build "
    "side of a shuffled hash join materializes its exchange FIRST, and "
    "when its MEASURED device bytes (actual row counts from the compact "
    "offsets fetch - no extra sync) land at or under this many bytes, the "
    "probe-side exchange is never dispatched - the join replans as a "
    "broadcast hash join over the raw probe partitions (reference "
    "spark.sql.adaptive.autoBroadcastJoinThreshold + "
    "GpuBroadcastJoinMeta). <= 0 disables the conversion.")

ADAPTIVE_SKEW_FACTOR = conf_float(
    "spark.rapids.sql.adaptive.skewFactor", 4.0,
    "Skewed-partition split: a post-shuffle partition whose row count "
    "(free host ints from the compact offsets fetch) exceeds this factor "
    "times the median partition is split into median-sized sub-batches "
    "that rejoin under the existing batch semantics, bounding per-"
    "dispatch capacity (reference spark.sql.adaptive.skewJoin."
    "skewedPartitionFactor / GpuSkewJoin). <= 0 disables splitting.")

ADAPTIVE_BUILD_REUSE = conf_bool(
    "spark.rapids.sql.adaptive.buildReuse.enabled", True,
    "Cache materialized broadcast build sides ACROSS queries, keyed by "
    "build-plan digest + table registration version next to the compile "
    "cache, so a repeated join skips the build entirely (reference "
    "ReusedExchangeExec across AQE stages). Entries invalidate when any "
    "temp view is re-registered and are capped at 8.")

ADAPTIVE_MEASURED_COST = conf_bool(
    "spark.rapids.sql.adaptive.measuredCost.enabled", True,
    "Measured cost pass: before converting a plan, consult the query "
    "history store's roofline verdicts for the SAME plan digest and pick "
    "exchange partition counts, aggregate fusion boundaries, and the "
    "coalesceTinyRows threshold from what was MEASURED instead of static "
    "defaults (needs spark.rapids.obs.historyDir; a digest with no "
    "audited history keeps the static plan).")

PALLAS_ENABLED = conf_bool(
    "spark.rapids.sql.pallas.enabled", True,
    "Use hand-tiled Pallas TPU kernels for eligible inner loops "
    "(murmur3 hash, string case map); the XLA twins run otherwise. "
    "Process-wide: the first session's value wins (fused kernels are "
    "cached process-globally).", startup_only=True)

MULTIFILE_READER_TYPE = conf_str(
    "spark.rapids.sql.format.parquet.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED, or AUTO "
    "(reference RapidsConf.scala:317).")

MULTIFILE_READER_THREADS = conf_int(
    "spark.rapids.sql.multiThreadedRead.numThreads", 8,
    "Host threads for multi-file read scheduling "
    "(reference GpuMultiFileReader).")

DEVICE_DECODE_ENABLED = conf_bool(
    "spark.rapids.sql.decode.device.enabled", True,
    "Decode Parquet column chunks ON DEVICE: the scan uploads raw "
    "dictionary/RLE/bit-packed/delta chunk bytes and Pallas/XLA kernels "
    "expand them inside the fused stage body (the cuDF GPU-reader "
    "analog; io/encoded.py + ops/pallas_decode.py). Columns with "
    "unsupported types/encodings/codecs fall back per column to the "
    "host pyarrow path, with the reason surfaced in explain/history. "
    "Off = the classic host-decode scan.")

DEVICE_DECODE_DELTA = conf_bool(
    "spark.rapids.sql.decode.device.delta.enabled", True,
    "Allow DELTA_BINARY_PACKED columns on the device-decode path "
    "(decoded as a cumulative sum with per-page restarts). Off falls "
    "such columns back to host decode.")

DEVICE_DECODE_MAX_BITS = conf_int(
    "spark.rapids.sql.decode.device.maxBits", 32,
    "Widest dictionary/delta packed bit width decoded on device (the "
    "bit-slice kernel extracts from 32-bit word pairs). Columns packed "
    "wider fall back per column to host decode; values above 32 are "
    "capped at 32.")

ASYNC_WRITE_MAX_INFLIGHT = conf_int(
    "spark.rapids.sql.asyncWrite.maxInFlightHostMemoryBytes", 2 << 30,
    "Throttle for async output writes "
    "(reference io/async/TrafficController.scala).")

ASYNC_WRITE_STALL_WARN_S = conf_int(
    "spark.rapids.sql.asyncWrite.stallWarnSeconds", 60,
    "Seconds a producer may block in TrafficController.acquire before a "
    "stall diagnostic fires (one log warning + asyncWriteStalled trace "
    "instant + rapids_async_write_stalls_total obs counter). Admission "
    "semantics are unchanged — the producer keeps waiting. 0 disables "
    "the diagnostic.")

IMPROVED_FLOAT_OPS = conf_bool(
    "spark.rapids.sql.improvedFloatOps.enabled", True,
    "Allow float aggregation orderings that may differ from CPU Spark in "
    "ULP-level ways (reference incompat float handling).")

ANSI_ENABLED = conf_bool(
    "spark.sql.ansi.enabled", False,
    "ANSI mode: arithmetic overflow and invalid casts raise instead of "
    "returning null (Spark conf honored by the expression compiler).")

CASE_SENSITIVE = conf_bool(
    "spark.sql.caseSensitive", False,
    "Column resolution case sensitivity (Spark conf).")

SESSION_TIMEZONE = conf_str(
    "spark.sql.session.timeZone", "UTC",
    "Session timezone. This engine evaluates timestamps in UTC only: any "
    "other value makes timezone-sensitive expressions raise at planning "
    "instead of silently returning UTC answers (reference: GpuOverrides "
    "tags non-UTC ops as unsupported).")

TEST_MODE = conf_bool(
    "spark.rapids.sql.test.enabled", False,
    "Assert that everything that should be on TPU is on TPU "
    "(reference GpuTransitionOverrides assertIsOnTheGpu).", internal=True)

ALLOW_NON_TPU = conf_str(
    "spark.rapids.sql.test.allowedNonTpu", "",
    "Comma-separated exec names allowed to fall back in test mode.",
    internal=True)

CPU_RANGE_PARTITION_SAMPLE = conf_int(
    "spark.rapids.sql.rangePartitioning.sampleSizePerPartition", 1024,
    "Rows sampled per partition to compute range bounds "
    "(reference GpuRangePartitioner/SamplingUtils).")

AGG_FORCE_SINGLE_PASS = conf_bool(
    "spark.rapids.sql.agg.forceSinglePassPartialSort", False,
    "Concat all input batches and run the partial aggregation as ONE update "
    "pass instead of per-batch update + merge (testing knob, reference "
    "forceSinglePassPartialSortAgg).", internal=True)

MAX_RECORDS_PER_FILE = conf_int(
    "spark.sql.files.maxRecordsPerFile", 0,
    "Maximum rows per output file (0 = unlimited). Writers split output "
    "batches into numbered part files past the limit (reference "
    "GpuFileFormatDataWriter maxRecordsPerFile).")

PY_WORKER_POOL_ENABLED = conf_bool(
    "spark.rapids.sql.python.workerPool.enabled", True,
    "Evaluate large row-UDF batches on a persistent multiprocessing "
    "worker pool (reference PySpark daemon analog). Unpicklable UDFs "
    "and small batches stay in-process.")

PY_WORKER_POOL_PARALLELISM = conf_int(
    "spark.rapids.sql.python.workerPool.parallelism", 0,
    "Worker processes for the python UDF pool (0 = cpu count, cap 8).")

UDF_COMPILER_ENABLED = conf_bool(
    "spark.rapids.sql.udfCompiler.enabled", False,
    "Translate simple Python UDF bytecode (arithmetic, comparisons, "
    "conditionals, math builtins) into fused device expressions "
    "(reference udf-compiler). Untranslatable UDFs stay on the row tier. "
    "Semantics note (same tradeoff as the reference compiler): compiled "
    "UDFs null-propagate instead of calling fn(None), and arithmetic "
    "errors yield null instead of raising (non-ANSI Spark semantics) — "
    "a row-tier UDF that RAISES on bad input behaves differently. "
    "Off by default for that reason (matching the reference).")

SKIP_AGG_PASS_RATIO = conf_float(
    "spark.rapids.sql.agg.skipAggPassReductionRatio", 1.0,
    "Skip later agg passes when a pass reduces rows by less than this ratio "
    "(reference skipAggPassReductionRatio).")

METRICS_LEVEL = conf_str(
    "spark.rapids.sql.metrics.level", "MODERATE",
    "ESSENTIAL, MODERATE, or DEBUG metric collection "
    "(reference spark.rapids.sql.metrics.level).")

INCOMPAT_ENABLED = conf_bool(
    "spark.rapids.sql.incompatibleOps.enabled", True,
    "Enable operators whose results can differ from CPU Spark in documented "
    "corner cases (reference incompatOps).")

PIPELINE_ENABLED = conf_bool(
    "spark.rapids.sql.pipeline.enabled", True,
    "Overlap host-side batch production (pyarrow decode, pad/H2D upload, "
    "shuffle deserialization) with device compute: a planner pass inserts "
    "bounded producer/consumer pipeline boundaries at scan->compute edges, "
    "running the upstream generator on the shared host pool so batch i+1 "
    "is decoded/uploaded while the device computes batch i (reference "
    "MultiFileReaderThreadPool / ThrottlingExecutor overlap). Also gates "
    "the deferred per-batch scalar fetches (shuffle offsets, LIMIT carry) "
    "and the async throttled serialized-shuffle writer. A stage whose "
    "pipeline setup fails falls back to the synchronous path.",
    commonly_used=True)

PIPELINE_DEPTH = conf_int(
    "spark.rapids.sql.pipeline.depth", 2,
    "Bounded lookahead of each pipeline boundary: how many produced "
    "batches may sit decoded/uploaded ahead of the consumer. 0 disables "
    "pipelining (identical to pipeline.enabled=false).")

COMPILE_CACHE_DIR = conf_str(
    "spark.rapids.compile.cacheDir", "",
    "When set, enable jax's persistent compilation cache in this "
    "directory (jax_compilation_cache_dir with the minimum-entry "
    "thresholds zeroed): compiled XLA executables are reused ACROSS "
    "processes, so a restarted engine pays trace + deserialize instead "
    "of a full backend compile on its first run of a known computation. "
    "Process-global — the first session to configure it wins (jax "
    "config is global); tools/compile_smoke.py CI-gates that the "
    "cross-process hits actually happen. Empty disables the persistent "
    "layer (the in-process warm-trace cache in runtime/compile_cache.py "
    "is always on).", commonly_used=True)

COMPILE_WARMUP_ENABLED = conf_bool(
    "spark.rapids.compile.warmup.enabled", False,
    "AOT warmup (runtime/warmup.py): at session start, replay the most "
    "recurrent successful queries recorded in spark.rapids.obs."
    "historyDir (their SQL text rides in the history records) on a "
    "background service thread as each referenced table is registered, "
    "pre-tracing and pre-compiling the hot exec set before the first "
    "user query needs it. Replays run on a shadow session: they touch "
    "no user-visible session state, produce no history records, and "
    "never fail the session. Progress is surfaced on /healthz "
    "(warmup document) and as warmupReplay trace instants.",
    commonly_used=True)

COMPILE_WARMUP_MAX_PLANS = conf_int(
    "spark.rapids.compile.warmup.maxPlans", 8,
    "Upper bound on distinct recurring plans the AOT warmup replays "
    "(ranked by recurrence count, most-recurrent first).")

COMPILE_WARMUP_MIN_RUNS = conf_int(
    "spark.rapids.compile.warmup.minRuns", 2,
    "Successful history runs of a plan digest required before warmup "
    "considers it recurring (1 replays everything ever run once).")

COMPILE_SHAPES_GROWTH = conf_float(
    "spark.rapids.compile.shapes.growthFactor", 2.0,
    "Geometric growth factor of the capacity padding buckets "
    "(runtime/shapes.py): every device batch capacity snaps to the "
    "smallest bucket >= its row count so XLA traces are shared across "
    "batches and queries. 2.0 (default) is next-power-of-two (up to 2x "
    "padding waste, fewest buckets/compiles); smaller factors (1.25, "
    "1.5) pad tighter at the cost of more distinct shapes to compile. "
    "Clamped to (1.06, 4.0].")

COMPILE_SHAPES_DTYPE_ALIGN = conf_bool(
    "spark.rapids.compile.shapes.dtypeAlign", True,
    "Round capacity buckets up to whole native TPU tiles for the "
    "plane's dtype width (8x128 elements for 4-byte lanes, 16x128 for "
    "2-byte, 32x128 for 1-byte) on bucket requests that carry an "
    "itemsize — today the string/byte planes; dtype-agnostic row "
    "buckets are unaligned. Power-of-two buckets are always aligned "
    "already; this keeps non-2.0 growth factors from paying a "
    "partial-tile relayout on byte-plane kernels.")

SHUFFLE_COALESCE_TINY_ROWS = conf_int(
    "spark.rapids.shuffle.coalesceTinyRows", 1024,
    "Post-shuffle tiny-partition coalescing: after a compact exchange, "
    "adjacent device sub-batches carrying fewer than this many rows "
    "each merge into one batch (bounded by 4x this target) before "
    "downstream dispatch — ragged post-shuffle slice sizes otherwise "
    "make nearly every batch shape a fresh trace AND a separate "
    "dispatch. The decision is free: the compact path's already-"
    "fetched offsets vector supplies exact host-side row counts. "
    "Merges count into the shuffleCoalescedBatches metric (visible in "
    "EXPLAIN ANALYZE). 0 disables coalescing.")

QUERY_TIMEOUT_S = conf_float(
    "spark.rapids.query.timeoutSeconds", 0.0,
    "Per-query deadline in seconds (0 disables). A watchdog-style "
    "sweeper over the live query registry (runtime/lifecycle.py) fires "
    "the query's cancel token with reason 'deadline' when the budget "
    "lapses; the query terminates at its next cooperative checkpoint "
    "with status=cancelled, and its wall-time attribution breakdown is "
    "recorded at death so the history/trace show WHERE the budget went. "
    "session.collect(plan, timeout_seconds=...) overrides per action.",
    commonly_used=True)

QUERY_MAX_CONCURRENT = conf_int(
    "spark.rapids.query.maxConcurrent", 0,
    "Admission control over top-level actions (0 = unlimited): at most "
    "this many queries execute concurrently; excess queries park in a "
    "bounded FIFO queue in the 'queued' live state. The complement of "
    "spark.rapids.sql.concurrentTpuTasks (which bounds TASKS inside "
    "admitted queries on the device semaphore) — the reference's "
    "GpuSemaphore model lifted to whole queries for the serving layer.",
    commonly_used=True)

QUERY_MAX_QUEUED = conf_int(
    "spark.rapids.query.maxQueued", 16,
    "Bound on the admission queue behind spark.rapids.query."
    "maxConcurrent: a query arriving past it is refused immediately "
    "with a typed QueryRejectedError (the HTTP 503/429 analog).")

QUERY_QUEUE_TIMEOUT_S = conf_float(
    "spark.rapids.query.queueTimeoutSeconds", 30.0,
    "Longest a query may wait in the admission queue before it is "
    "refused with QueryRejectedError (0 = wait forever). Queued "
    "queries remain cancellable while they wait.")

QUERY_DEVICE_BUDGET = conf_int(
    "spark.rapids.query.deviceBudgetBytes", 0,
    "Per-query cooperative device-bytes quota (0 disables): the spill "
    "framework keeps a per-query-id ledger of registered device "
    "batches, and a query exceeding its own quota spills ITS OWN "
    "batches (largest first) — or raises a retryable quota OOM that "
    "drains only its own handles — instead of evicting its neighbors' "
    "(the isolation primitive concurrent serving requires; composes "
    "with the process-wide spark.rapids.memory.tpu.budgetBytes).")

SERVING_ENABLED = conf_bool(
    "spark.rapids.serving.enabled", False,
    "Attach the query-serving layer to the obs HTTP endpoint: POST /sql "
    "accepts {sql, session?, conf?, timeout_seconds?} documents, runs "
    "each request as a top-level action through the admission gate / "
    "per-query device quotas / deadlines / cancellation, and returns the "
    "result as Arrow IPC bytes plus the wall-time attribution breakdown. "
    "Requires spark.rapids.obs.enabled with a bindable port. The long-"
    "lived-driver serving model of the reference (one plugin process, "
    "many sessions, concurrentGpuTasks bounding device work) lifted to "
    "an HTTP surface.", commonly_used=True)

SERVING_MAX_SESSIONS = conf_int(
    "spark.rapids.serving.maxSessions", 16,
    "Bound on named client sessions the server materializes (each is a "
    "conf-overlay session sharing the root session's temp views). A "
    "request naming a session past the bound is refused with HTTP 429 "
    "and a typed error doc rather than growing without limit.")

SERVING_MAX_INFLIGHT = conf_int(
    "spark.rapids.serving.maxInflight", 32,
    "Bound on HTTP /sql requests concurrently inside the server (admitted "
    "OR parked in the admission queue). A request arriving past it is "
    "refused immediately with HTTP 429 — the serving layer rejects "
    "rather than piles up, mirroring spark.rapids.query.maxQueued one "
    "level out.")

SERVING_RESULT_CACHE_ENABLED = conf_bool(
    "spark.rapids.serving.resultCache.enabled", True,
    "Plan-digest-keyed result cache for the serving layer: a hit returns "
    "the byte-identical Arrow IPC stream of a prior execution with the "
    "same (plan digest, table-version epoch, compile fingerprint) key. "
    "Invalidated by the table-version epoch the broadcast-reuse cache "
    "established (any create_or_replace_temp_view bumps it). Plans "
    "containing non-deterministic expressions (rand) bypass the cache; "
    "ANSI-divergent plans never share entries (the compile fingerprint "
    "is in the key).")

SERVING_RESULT_CACHE_MAX_BYTES = conf_int(
    "spark.rapids.serving.resultCache.maxBytes", 256 << 20,
    "Byte bound on cached result payloads (Arrow IPC stream bytes, "
    "exact len() accounting). Least-recently-used entries evict to "
    "admit new ones; every eviction is a counter.")

SERVING_RESULT_CACHE_MAX_ENTRIES = conf_int(
    "spark.rapids.serving.resultCache.maxEntries", 64,
    "Entry bound on the result cache (LRU eviction, counted), "
    "independent of the byte bound — many tiny results must not grow "
    "the key set without limit.")

SERVING_WARM_BOOT_ENABLED = conf_bool(
    "spark.rapids.serving.warmBoot.enabled", True,
    "Block server start on the compile-warmup replay when warmup is "
    "armed (spark.rapids.compile.warmup.enabled + obs.historyDir): a "
    "fresh replica pointed at a shared historyDir and persistent "
    "compile cache then serves its first hot-digest query with zero "
    "backend compiles — PR 10's session-construction warmup "
    "generalized to server boot, gated by rapids_xla_compiles_total.")

SERVING_WARM_BOOT_TIMEOUT_S = conf_float(
    "spark.rapids.serving.warmBoot.timeoutSeconds", 60.0,
    "Longest server start waits for the warmup replay before serving "
    "anyway (0 = don't wait). A timeout degrades to cold serving, it "
    "never fails the boot.")

SERVING_REQUEST_NICE = conf_int(
    "spark.rapids.serving.requestNice", 0,
    "OS niceness (0-19) applied to the handler thread for the duration "
    "of each request on this session — the serving QoS tier. A batch "
    "session sets this in its conf overlay to declare itself "
    "background: its host-side work (and on the CPU sim, its device "
    "compute, which runs on the dispatching thread) then yields to "
    "latency-tier requests under CPU contention. Best-effort: applied "
    "per-thread via setpriority, silently skipped where unsupported.")

STAGE_FUSION_ENABLED = conf_bool(
    "spark.rapids.sql.stageFusion.enabled", True,
    "Collapse maximal linear chains of narrow operators (project, filter, "
    "expand, limit, and the partial phase of hash aggregation) into ONE "
    "traced device computation per pipeline stage, so the host issues "
    "exactly one XLA dispatch per input batch per stage — the TPU-idiomatic "
    "analog of Spark's whole-stage codegen (which the reference GPU plugin "
    "deliberately lacks). A stage whose composed trace fails falls back to "
    "the unfused operator chain.", commonly_used=True)

MULTICHIP_ENABLED = conf_bool(
    "spark.rapids.sql.multichip.enabled", False,
    "Shard whole fused stages across the `part` axis of the device mesh "
    "and run them as ONE SPMD dispatch per batch-wave (exec/sharded.py), "
    "with the hash exchange executing as an in-program ICI all-to-all "
    "instead of a host-side round-trip — the TPU analog of the "
    "reference's UCX/RDMA shuffle manager. Stages the planner cannot "
    "shard (carries, LIMIT early-exit, flat string planes) fall back "
    "per-shard to the single-device path through the tagging tree. "
    "Compile-cache keys gain a mesh fingerprint while this is on, so "
    "sharded and single-device executables never collide.",
    commonly_used=True)

MULTICHIP_DEVICES = conf_int(
    "spark.rapids.sql.multichip.devices", 0,
    "Devices to place on the `part` axis of the execution mesh when "
    "multichip is enabled: 0 means all of jax.devices(), any other "
    "value is clamped to what the process actually has. 1 is a valid "
    "degenerate mesh — the full shard/wave machinery runs over a "
    "single device, which is how tier-1 exercises the sharded path "
    "without virtual devices.")


class RapidsConf:
    """A snapshot of config values: defaults, then environment overrides
    (SPARK_RAPIDS_TPU_<KEY with dots as underscores>), then explicit dict.

    The reference re-reads a fresh RapidsConf per rule application
    (GpuOverrides.scala:4748); we do the same per plan rewrite.
    """

    def __init__(self, overrides: Optional[dict] = None):
        self._values: Dict[str, Any] = {}
        for key, entry in _REGISTRY.items():
            env_key = "SPARK_RAPIDS_TPU_" + key.replace(".", "_").upper()
            if env_key in os.environ:
                self._values[key] = entry.conv(os.environ[env_key])
            else:
                self._values[key] = entry.default
        for k, v in (overrides or {}).items():
            if k in _REGISTRY:
                entry = _REGISTRY[k]
                self._values[k] = entry.conv(v) if isinstance(v, str) else v
            else:
                self._values[k] = v  # passthrough for op-enable keys

    def get(self, entry_or_key) -> Any:
        key = entry_or_key.key if isinstance(entry_or_key, ConfEntry) else entry_or_key
        return self._values.get(key, _REGISTRY[key].default if key in _REGISTRY else None)

    def set(self, entry_or_key, value) -> "RapidsConf":
        key = entry_or_key.key if isinstance(entry_or_key, ConfEntry) else entry_or_key
        # string values convert through the registry exactly like
        # constructor overrides ("false" must not read back truthy)
        if key in _REGISTRY and isinstance(value, str):
            value = _REGISTRY[key].conv(value)
        self._values[key] = value
        # the compile cache memoizes its conf fingerprint on this object
        # (runtime/compile_cache._conf_fingerprint): any mutation must
        # drop it, or an ANSI/float-mode flip would keep hitting
        # executables compiled under the old semantics
        self.__dict__.pop("_compile_fp", None)
        return self

    def is_op_enabled(self, op_key: str, default: bool = True) -> bool:
        """Per-op enable keys are auto-derived from rule names, e.g.
        spark.rapids.sql.exec.TpuSortExec (reference auto-derived keys)."""
        v = self._values.get(op_key)
        if v is None:
            return default
        return _bool_conv(v) if isinstance(v, str) else bool(v)

    def copy(self, **overrides) -> "RapidsConf":
        c = RapidsConf()
        c._values = dict(self._values)
        for k, v in overrides.items():
            c._values[k] = v
        return c


_local = threading.local()
_GLOBAL = RapidsConf()


def conf() -> RapidsConf:
    """Active session conf (thread-local override or global default)."""
    return getattr(_local, "conf", _GLOBAL)


def set_session_conf(c: RapidsConf) -> None:
    _local.conf = c
    # capacity bucketing policy is consulted deep inside kernels where no
    # conf rides along: publish the floor and the bucket shape as module
    # globals (runtime/shapes.py is the one home of the policy)
    from spark_rapids_tpu.columnar import batch as _b
    from spark_rapids_tpu.runtime import compile_cache as _cc
    from spark_rapids_tpu.runtime import shapes as _sh
    _b.MIN_CAPACITY = max(8, int(c.get(BATCH_CAPACITY_MIN)))
    _sh.configure(c.get(COMPILE_SHAPES_GROWTH),
                  c.get(COMPILE_SHAPES_DTYPE_ALIGN))
    _cc.publish_conf(c)


class session_conf:
    """Context manager scoping config overrides, used by tests to flip
    between CPU and TPU sessions (reference integration_tests
    spark_session.py with_cpu_session/with_gpu_session)."""

    def __init__(self, **overrides):
        full = {}
        for k, v in overrides.items():
            full[k] = v
        self._new = conf().copy(**full)

    def __enter__(self):
        self._old = getattr(_local, "conf", None)
        _local.conf = self._new
        return self._new

    def __exit__(self, *exc):
        if self._old is None:
            if hasattr(_local, "conf"):
                del _local.conf
        else:
            _local.conf = self._old
        return False


def registry() -> Dict[str, ConfEntry]:
    return dict(_REGISTRY)


def generate_docs() -> str:
    """Render the registry to markdown (reference RapidsConf.help:2505
    emitting docs/configs.md)."""
    lines = [
        "# spark-rapids-tpu configuration",
        "",
        "Generated by `spark_rapids_tpu.config.generate_docs()`; do not edit.",
        "",
        "| key | default | description |",
        "|---|---|---|",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        doc = e.doc.replace("|", "\\|").replace("\n", " ")
        lines.append(f"| `{e.key}` | {e.render_default()} | {doc} |")
    lines.append("")
    return "\n".join(lines)
