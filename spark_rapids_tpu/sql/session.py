"""TpuSession: the driver (reference Plugin.scala driver/executor plugin
bootstrap + the collect path). Owns config, converts plans through the
overrides engine, and runs root partitions as concurrent tasks."""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import pyarrow as pa

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import to_arrow
from spark_rapids_tpu.expr.core import SparkException
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu.runtime.metrics import walk_exec_tree
from spark_rapids_tpu.runtime.task import TaskContext
from spark_rapids_tpu.sql.dataframe import DataFrame

#: per-thread collect nesting depth: degradation policy and breaker
#: accounting apply only to top-level actions (depth 0 at entry) — a
#: nested collect's failure propagates to its enclosing query
_COLLECT_DEPTH = threading.local()


def nested_action_scope():
    """Context manager making collects on the CURRENT thread run as
    nested actions: no attribution aggregate open/reset, no breaker
    probe consumption, no degradation policy, no last_action_status.
    The AOT warmup replays (runtime/warmup.py) run under this — they
    are cache-priming work sharing the process with real queries."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        d = getattr(_COLLECT_DEPTH, "d", 0)
        _COLLECT_DEPTH.d = d + 1
        try:
            yield
        finally:
            _COLLECT_DEPTH.d = d

    return _cm()


def _discover_hive(root: str):
    """Walk a directory for hive-layout partitions (k=v subdirs). Returns
    (files, per_file_partition_values) or (files, None) when the layout is
    flat (reference: Spark's PartitioningAwareFileIndex)."""
    import os
    from urllib.parse import unquote
    files, vals = [], []
    found_parts = False
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        rel = os.path.relpath(dirpath, root)
        parts = {}
        if rel != ".":
            for seg in rel.split(os.sep):
                if "=" not in seg:
                    if any(f.endswith(".parquet") for f in filenames):
                        raise ValueError(
                            f"mixed layout under {root!r}: parquet files in "
                            f"non-partition directory {dirpath!r}")
                    parts = None
                    break
                k, _, v = seg.partition("=")
                parts[k] = (None if v == "__HIVE_DEFAULT_PARTITION__"
                            else unquote(v))
            if parts:
                found_parts = True
        if parts is None:
            continue
        for f in sorted(filenames):
            if f.endswith(".parquet") and not f.startswith("_"):
                files.append(os.path.join(dirpath, f))
                vals.append(parts)
    if not files:
        raise FileNotFoundError(f"no parquet files under {root!r}")
    return files, (vals if found_parts else None)


class TpuSession:
    def __init__(self, conf_overrides: Optional[Dict] = None):
        self.conf = C.RapidsConf(conf_overrides)
        self._views: Dict = {}
        self._last_meta = None
        #: artifact paths of the most recent traced action
        #: ({"trace","events","metrics"}; None until a traced collect runs)
        self.last_trace_paths = None
        #: (status, degraded_reason) of the most recent top-level action:
        #: ("ok", None), ("failed", None), or ("degraded", reason)
        self.last_action_status = ("ok", None)
        from spark_rapids_tpu.ops import pallas_kernels as PK
        PK.set_enabled(self.conf.get(C.PALLAS_ENABLED))
        # live observability (spark.rapids.obs.*): process-wide registry,
        # optional /metrics+/healthz endpoint, optional history store
        from spark_rapids_tpu.runtime import obs
        obs.install(self.conf)
        # persistent compilation cache + AOT warmup
        # (spark.rapids.compile.*): the cache dir applies immediately;
        # warmup arms now and launches replays as tables register
        from spark_rapids_tpu.runtime import compile_cache, warmup
        compile_cache.configure(self.conf)
        # arm the kernel cost auditor NOW, not first at
        # prepare_execution: the audit's per-query tally opens at
        # collect entry, before the plan converts — a session's first
        # query must already be audited
        from spark_rapids_tpu.analysis import kernel_audit
        kernel_audit.configure(self.conf)
        warmup.maybe_arm(self)
        # the serving layer (spark.rapids.serving.*): POST /sql on the
        # obs endpoint, result cache, warm-boot wait. Installs AFTER
        # warmup arms so a warm-boot server can block on the replay
        from spark_rapids_tpu.runtime import serving
        serving.maybe_install(self)

    def _activate(self):
        # name binding (case sensitivity) consults the active session conf
        # at plan-construction time
        from spark_rapids_tpu.config import set_session_conf
        set_session_conf(self.conf)

    # -- sources -----------------------------------------------------------
    def create_or_replace_temp_view(self, name: str, df) -> None:
        """Register a DataFrame for session.sql() FROM resolution."""
        self._views[name.lower()] = df
        # re-registering a view is the one way table data changes under a
        # stable plan digest: advance the table epoch so the adaptive
        # build-reuse cache (exec/adaptive.py) drops every cached build
        from spark_rapids_tpu.exec import adaptive as AQ
        AQ.bump_table_version()
        # a new table may unblock pending AOT warmup replays (one
        # module-global read when warmup is unarmed)
        from spark_rapids_tpu.runtime import warmup
        warmup.notify_view_registered(self)

    createOrReplaceTempView = create_or_replace_temp_view

    def table(self, name: str):
        if name.lower() not in self._views:
            raise SparkException(f"table or view not found: {name}")
        return self._views[name.lower()]

    def sql(self, query: str):
        """Run a SQL string over registered temp views (the analytic
        subset grammar — sql/parser.py)."""
        from spark_rapids_tpu.sql.parser import parse_sql
        df = parse_sql(query, self)
        try:
            # the replayable spec: history records carry the SQL text so
            # AOT warmup (runtime/warmup.py) can re-execute recurring
            # plans at session start
            df.plan._sql_text = query
        except Exception:  # noqa: BLE001 - a slotted plan node just
            pass  # isn't warmup-replayable
        return df

    def create_dataframe(self, data, num_partitions: int = 1) -> DataFrame:
        self._activate()
        if isinstance(data, dict):
            table = pa.table(data)
        elif isinstance(data, pa.Table):
            table = data
        else:
            raise TypeError(type(data))
        return DataFrame(P.InMemorySource(table, num_partitions), self)

    createDataFrame = create_dataframe

    def read_parquet(self, *paths, columns=None) -> DataFrame:
        self._activate()
        import os
        # hive-style partition discovery: dir of k=v subdirs -> recursive
        # file walk with the partition column reconstructed from the path
        if len(paths) == 1 and os.path.isdir(paths[0]):
            files, part_vals = _discover_hive(paths[0])
            if part_vals is not None:
                return DataFrame(P.ParquetScan(files, columns=columns,
                                               partition_values=part_vals),
                                 self)
        return DataFrame(P.ParquetScan(
            self._expand_paths(paths, suffix=".parquet"), columns=columns),
            self)

    def _expand_paths(self, paths, suffix: str = ""):
        import glob as _glob
        import os
        expanded: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                expanded.extend(sorted(
                    f for f in _glob.glob(os.path.join(p, "*" + suffix))
                    if os.path.isfile(f) and not os.path.basename(f).startswith("_")))
            elif any(ch in p for ch in "*?["):
                expanded.extend(sorted(_glob.glob(p)))
            else:
                expanded.append(p)
        if not expanded:
            raise FileNotFoundError(f"no input files matched {list(paths)!r}")
        return expanded

    def read_csv(self, *paths, header: bool = True, sep: str = ",",
                 columns=None) -> DataFrame:
        return DataFrame(P.TextScan("csv", self._expand_paths(paths),
                                    columns=columns,
                                    options={"header": header, "sep": sep}),
                         self)

    def read_json(self, *paths, columns=None) -> DataFrame:
        return DataFrame(P.TextScan("json", self._expand_paths(paths),
                                    columns=columns), self)

    def read_avro(self, *paths, columns=None) -> DataFrame:
        return DataFrame(P.TextScan("avro", self._expand_paths(paths),
                                    columns=columns), self)

    def read_orc(self, *paths, columns=None) -> DataFrame:
        return DataFrame(P.TextScan("orc", self._expand_paths(paths),
                                    columns=columns), self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(P.Range(start, end, step, num_partitions), self)

    # -- execution ---------------------------------------------------------
    def prepare_execution(self, plan: P.PlanNode):
        """Session preamble shared by every action (collect, write):
        activate this session's conf, sync the spill budgets, arm fault
        injection (general sites + the legacy OOM injector), sync the
        retry backoff and the dispatch watchdog/breaker, convert the
        plan. Returns (exec_root, meta)."""
        from spark_rapids_tpu.analysis import kernel_audit, sanitizer
        from spark_rapids_tpu.config import set_session_conf
        from spark_rapids_tpu.plan.overrides import convert_plan
        from spark_rapids_tpu.runtime import faults, watchdog
        from spark_rapids_tpu.runtime.memory import get_spill_framework
        from spark_rapids_tpu.runtime.retry import (
            OomInjector, backoff_from_conf,
        )
        set_session_conf(self.conf)
        sanitizer.maybe_install(self.conf)
        kernel_audit.configure(self.conf)
        OomInjector.from_conf(self.conf)
        faults.from_conf(self.conf)
        backoff_from_conf(self.conf)
        watchdog.maybe_install(self.conf)
        get_spill_framework(self.conf)  # sync budgets to this session
        # measured cost pass: audited history for this plan's digest may
        # override partition counts / coalescing / fusion boundaries
        # during conversion (thread-local — concurrent sessions convert
        # under their own hints)
        from spark_rapids_tpu.exec import adaptive as AQ
        from spark_rapids_tpu.plan import cost as COST
        hints = COST.measured_hints(plan, self.conf)
        COST.install_hints(hints)
        try:
            exec_root, meta = convert_plan(plan, self.conf)
        finally:
            COST.clear_hints()
        if hints is not None and AQ.enabled(self.conf):
            AQ.record(AQ.MEASURED_COST, **hints.detail())
        self._last_meta = meta
        self._last_exec = exec_root
        # attach the converted tree to THIS query's live context (the
        # thread's bound query id) so /queries progress walks the
        # query's OWN execs — not session._last_exec, which concurrent
        # queries in one session clobber. First attach wins: a nested
        # collect re-enters here while the outer query executes
        from spark_rapids_tpu.runtime.obs import live as _live
        qc = _live.current_context()
        if qc is not None:
            qc.attach_exec(exec_root)
        return exec_root, meta

    def last_metrics(self):
        """Per-exec metrics of the most recent action (the SQL-UI metrics
        surface; reference GpuMetric / GpuTaskMetrics §5.5). Returns
        {exec_name#i: {metric: value}} in walk_exec_tree order (fused
        members and absorbed pre-chains snapshot alone — recursing their
        original child links would re-walk shared subtrees)."""
        out = {}
        if getattr(self, "_last_exec", None) is not None:
            for key, node, _d, _role, _sid in walk_exec_tree(
                    self._last_exec):
                snap = node.metrics.snapshot()
                if snap:
                    out[key] = snap
        return out

    def collect(self, plan: P.PlanNode,
                timeout_seconds: Optional[float] = None) -> pa.Table:
        import time as _time

        from spark_rapids_tpu.runtime import lifecycle as LC
        from spark_rapids_tpu.runtime import obs as OBS
        from spark_rapids_tpu.runtime import trace as TR
        # structured trace per action (spark.rapids.sql.trace.*): spans +
        # instants + the task event log, finalized with this action's
        # metrics snapshot so the offline report can reconcile the two.
        # A nested collect (broadcast materialization) returns qt=None and
        # joins the enclosing query's trace.
        qt = TR.start_query(self.conf)
        if qt is None and self.conf.get(C.TRACE_ENABLED):
            # tracing was requested but another query owns the tracer
            # (nested collect, or a concurrent session): this action gets
            # no artifacts of its own — never leave a PREVIOUS query's
            # paths looking like this one's. A same-session outer collect
            # restores its own paths when it finalizes.
            self.last_trace_paths = None
        # live-observability token: None when obs is off or this is a
        # nested collect (only top-level actions publish + make history).
        # The digest is computed UP FRONT (a cheap logical-tree hash) so
        # the live registry and the queryStart marker can carry it while
        # the query is still running — a hung query's flight dump needs
        # its t0 and identity without waiting for the epilogue
        start_digest = None
        if getattr(_COLLECT_DEPTH, "d", 0) == 0:
            try:
                start_digest = OBS.plan_digest(plan)
            except Exception:  # noqa: BLE001 - an undigestable plan
                pass  # still runs and registers
        ot = OBS.on_query_start(plan_digest=start_digest,
                                sql=getattr(plan, "_sql_text", None))
        if getattr(_COLLECT_DEPTH, "d", 0) == 0:
            # queryStart instant for EVERY top-level action, traced or
            # not (the flight ring records it too): ring timelines of a
            # hung or failed query get a t0 marker with the query's
            # identity, pairing with the queryError/queryDegraded
            # epilogue markers
            try:
                TR.instant("queryStart", cat="query", args={
                    "query_id": ot if isinstance(ot, int) else None,
                    "plan_digest": start_digest},
                    level=TR.ESSENTIAL)
            except Exception:  # noqa: BLE001 - a marker failure must
                pass  # not fail the query

        if qt is not None or (ot is not None and ot is not OBS.NESTED):
            # drop the PREVIOUS action's exec tree before this one runs:
            # a failure before convert_plan rebuilds it must publish
            # nothing — republishing the old tree's (unchanged) metrics
            # would double the registry counters and attach the previous
            # query's plan to this query's history record
            self._last_exec = None
            self._last_meta = None
        t0 = _time.perf_counter_ns()
        wall0 = _time.time()
        error: Optional[BaseException] = None
        status = "ok"
        degraded_reason: Optional[str] = None
        cancel_reason: Optional[str] = None
        tok = None  # this action's CancelToken (top-level only)
        # degradation is a TOP-LEVEL policy: a nested collect (broadcast
        # materialization inside a running device query) must propagate
        # its failure to the outer query, which then degrades whole
        depth = getattr(_COLLECT_DEPTH, "d", 0)
        _COLLECT_DEPTH.d = depth + 1
        if depth == 0:
            # open the per-query attribution aggregate (compile timing,
            # task accumulators) — runs regardless of obs state so
            # explain(mode="analyze") always has a breakdown
            from spark_rapids_tpu.runtime.obs import attribution as ATTR
            ATTR.on_query_start()
            # and the kernel cost auditor's dispatch tally (one global
            # read when the audit is off; the conf rides along so a
            # mid-session enable covers THIS query)
            from spark_rapids_tpu.analysis import kernel_audit as KA
            KA.on_query_start(self.conf)
            # and the adaptive decision recorder: every AQE decision this
            # query makes (conversion, skew split, build reuse, measured
            # cost) lands in one per-query doc
            from spark_rapids_tpu.exec import adaptive as AQ
            AQ.on_query_start(self.conf)
        cpu_gate_failed = False
        try:
            if depth == 0:
                # query lifecycle control (runtime/lifecycle.py): the
                # cancel token (deadline-armed from the conf or the
                # per-action override) registers FIRST so the query is
                # cancellable even while queued for admission; admit()
                # then parks this thread in the bounded `queued` state
                # when spark.rapids.query.maxConcurrent is saturated —
                # raising QueryRejectedError (queue full / wait timeout)
                # or QueryCancelledError (cancelled while queued)
                tok = LC.begin_action(
                    ot if isinstance(ot, int) else None, self.conf,
                    timeout_seconds=timeout_seconds)
                LC.admit(tok, self.conf)
                if isinstance(ot, int):
                    try:
                        from spark_rapids_tpu.runtime.obs import (
                            live as _live,
                        )
                        qc = _live.get(ot)
                        if qc is not None:
                            qc.transition("planning")
                    except Exception:  # noqa: BLE001 - registry is
                        pass  # advisory
            if depth == 0 and self._fallback_enabled():
                from spark_rapids_tpu.runtime import watchdog as WD
                brk = WD.peek_breaker()
                if brk is not None and not brk.allow():
                    # breaker open: skip the device entirely instead of
                    # feeding queries into a known-bad backend; allow()
                    # lets exactly one probe query through per backoff
                    # window to test recovery (half-open)
                    status = "degraded"
                    degraded_reason = "circuit_open"
                    try:
                        return self._execute_cpu_fallback(plan)
                    except BaseException:
                        # a CPU-path failure: the device never ran, so
                        # the outer handler must neither record a device
                        # breaker failure nor re-run the identical CPU
                        # fallback a second time
                        cpu_gate_failed = True
                        status = "failed"
                        degraded_reason = None
                        raise
            prof_dir = self.conf.get(C.PROFILE_DIR)
            if prof_dir:
                # XProf trace per action (reference ProfilerOnExecutor /
                # NVTX); structured spans forward TraceAnnotations into
                # this capture so both timelines share operator names
                import jax
                with jax.profiler.trace(prof_dir):
                    result = self._collect_inner(plan)
            else:
                result = self._collect_inner(plan)
            if depth == 0:
                self._record_device_success()
            return result
        except BaseException as e:
            error = e
            if depth == 0 and isinstance(e, LC.QueryCancelledError):
                # a cooperative cancel (user, deadline, or injected
                # fault) is its own terminal status — never degraded to
                # a CPU re-execution, never counted as a plain failure
                status = "cancelled"
                cancel_reason = e.reason
                raise
            fallback = self._maybe_degrade_cpu(plan, e) \
                if depth == 0 and not cpu_gate_failed else None
            if fallback is None:
                status = "failed"
                raise
            status = "degraded"
            degraded_reason = type(e).__name__
            return fallback
        finally:
            _COLLECT_DEPTH.d = depth
            #: (status, reason) of the most recent top-level action —
            #: ok / failed / degraded / cancelled (chaos + serving
            #: callers read this without needing the obs registry)
            if depth == 0:
                self.last_action_status = (
                    status, degraded_reason or cancel_reason)
                # the token leaves the registry BEFORE the epilogue so
                # metric snapshots / history writes can never re-raise
                # the cancel; its admission slot releases here too
                LC.finish_action(tok, status)
            self._finish_action(plan, qt, ot, error,
                                _time.perf_counter_ns() - t0, wall0,
                                status=status,
                                degraded_reason=degraded_reason,
                                cancel_reason=cancel_reason,
                                top_level=depth == 0)

    def _fallback_enabled(self) -> bool:
        return bool(self.conf.get(C.FALLBACK_CPU_ENABLED))

    def _record_device_success(self) -> None:
        """Close the circuit on a successful device query (half-open
        probe succeeded, or plain success resetting the failure count).
        Only consulted when fallback is on — the breaker must not
        accumulate state from test suites that intentionally fail
        queries with fallback off."""
        if not self._fallback_enabled():
            return
        from spark_rapids_tpu.runtime import watchdog as WD
        brk = WD.peek_breaker()
        if brk is not None:
            brk.record_success()

    @staticmethod
    def _degradable(error: BaseException) -> bool:
        """Degradation policy: engine/device failures degrade (exhausted
        OOM retries, corrupted shuffle data, injected faults, wedged or
        failing device dispatch); user-semantic errors do NOT — an ANSI
        overflow or an unsupported-operation SparkException would raise
        identically on the CPU backend, so re-executing only delays the
        answer the user must see."""
        from spark_rapids_tpu.runtime.lifecycle import (
            QueryCancelledError, QueryRejectedError,
        )
        if isinstance(error, (KeyboardInterrupt, SystemExit,
                              GeneratorExit, QueryCancelledError,
                              QueryRejectedError)):
            # a cancelled query must terminate (re-executing it on the
            # CPU would resurrect exactly the work the user killed), and
            # a rejected query re-executing would bypass admission
            return False
        return not isinstance(error, SparkException)

    def _execute_cpu_fallback(self, plan: P.PlanNode) -> pa.Table:
        from spark_rapids_tpu.config import set_session_conf
        from spark_rapids_tpu.exec.cpu_backend import execute_cpu
        set_session_conf(self.conf)
        return execute_cpu(plan, self.conf.get(C.ANSI_ENABLED))

    def _maybe_degrade_cpu(self, plan: P.PlanNode,
                           error: BaseException) -> Optional[pa.Table]:
        """Graceful degradation (spark.rapids.fallback.cpu.enabled): the
        device path failed a top-level query — re-execute it on the CPU
        backend and report `degraded` instead of `failed`. Returns the
        CPU result, or None when degradation is off, the error is
        user-semantic, or the CPU re-execution itself fails (the
        original device error then propagates)."""
        import logging
        if not self._fallback_enabled() or not self._degradable(error):
            return None
        from spark_rapids_tpu.runtime import watchdog as WD
        WD.breaker().record_failure(type(error).__name__)
        log = logging.getLogger("spark_rapids_tpu")
        log.warning(
            "query failed on the device path (%s: %s); degrading to CPU "
            "re-execution", type(error).__name__, str(error)[:200])
        try:
            return self._execute_cpu_fallback(plan)
        except Exception:  # noqa: BLE001 - surface the ORIGINAL device
            # error, with the CPU failure logged beside it
            log.warning("CPU fallback re-execution also failed",
                        exc_info=True)
            return None

    def _finish_action(self, plan, qt, ot, error, duration_ns,
                       wall0, status: Optional[str] = None,
                       degraded_reason: Optional[str] = None,
                       cancel_reason: Optional[str] = None,
                       top_level: bool = False) -> None:
        """Query epilogue: finalize the trace (success OR failure),
        compute the wall-time attribution, trigger a flight-recorder
        dump on failure/degradation, and publish the action to the live
        observability layer. Every step is fenced — a failed query must
        still flush its buffered trace events (with an `error` instant
        and status=failed), and a last_metrics() snapshot that itself
        raises (a lazy device count on a poisoned buffer) must not
        swallow the artifacts, which it previously did by raising
        between the two finalize halves."""
        import logging

        from spark_rapids_tpu.runtime import obs as OBS
        from spark_rapids_tpu.runtime import trace as TR
        from spark_rapids_tpu.runtime.obs import attribution as ATTR
        from spark_rapids_tpu.runtime.obs import flight as FLIGHT
        log = logging.getLogger("spark_rapids_tpu")
        if status is None:
            status = "ok" if error is None else "failed"
        if top_level and isinstance(ot, int):
            # the epilogue (metric snapshot, attribution, trace
            # finalize, history publish) runs with the query visible as
            # `finishing` — a scrape during a slow lazy-count resolve
            # must not show a finished query as still executing
            try:
                from spark_rapids_tpu.runtime.obs import live as _live
                qc = _live.get(ot)
                if qc is not None:
                    qc.transition("finishing")
            except Exception:  # noqa: BLE001 - registry is advisory
                pass
        # ONE metric snapshot serves the trace finalize, the registry
        # rollups, and the history record (resolving lazy device row
        # counts costs real syncs) — and it is taken at all only when
        # something consumes it: a tracer, the endpoint, or the store
        obs_top = ot is not None and ot is not OBS.NESTED
        digest = None
        lm = None
        if qt is not None or (obs_top and OBS.wants_rollups()):
            try:
                lm = self.last_metrics()
            except Exception:  # noqa: BLE001 - snapshot must not block
                log.warning("failed to snapshot last_metrics",
                            exc_info=True)
        if qt is not None:
            try:
                digest = OBS.plan_digest(plan)
            except Exception:  # noqa: BLE001
                pass
        if top_level:
            # close the attribution aggregate and record the wall time
            # whether or not anything consumes them now — last_
            # attribution() / explain(mode="analyze") recompute on
            # demand from these plus a fresh metric snapshot
            try:
                self._last_attr_extra = ATTR.finish()
            except Exception:  # noqa: BLE001
                self._last_attr_extra = None
            self._last_duration_ns = duration_ns
            self._last_attribution = None
            if lm is not None:
                try:
                    self._last_attribution = ATTR.attribute(
                        lm, duration_ns, extra=self._last_attr_extra)
                except Exception:  # noqa: BLE001
                    log.warning("failed to attribute query time",
                                exc_info=True)
            # kernel cost audit: close the dispatch tally, resolve any
            # pending cost analyses (trace-time audits deferred off the
            # dispatch path), and join with the attribution's device
            # seconds into the roofline doc. One global read when off.
            from spark_rapids_tpu.analysis import kernel_audit as KA
            self._last_audit = None
            self._last_roofline = None
            try:
                self._last_audit = KA.finish_query()
                if self._last_audit is not None and lm is not None:
                    self._last_roofline = KA.roofline(
                        self._last_audit, lm, duration_ns,
                        extra=self._last_attr_extra)
            except Exception:  # noqa: BLE001 - the audit must never
                # fail (or mask the real error of) a query
                log.warning("failed to compute kernel cost audit",
                            exc_info=True)
            # close the adaptive decision recorder: the per-query doc
            # feeds last_aqe(), EXPLAIN ANALYZE and the history record
            from spark_rapids_tpu.exec import adaptive as AQ
            self._last_aqe = None
            try:
                self._last_aqe = AQ.finish_query()
            except Exception:  # noqa: BLE001 - decision bookkeeping
                # must never fail (or mask the real error of) a query
                log.warning("failed to close adaptive decisions",
                            exc_info=True)
        flight_dump = None
        if top_level and status in ("failed", "degraded", "cancelled"):
            # emit the outcome marker (tracer AND/OR flight ring), then
            # dump the flight rings: the failing query's timeline exists
            # retroactively even with tracing off
            try:
                if status == "cancelled":
                    # the terminal marker of a cooperative cancel: the
                    # trace ends here because the token fired (reason
                    # user/deadline/fault), with the attribution
                    # breakdown computed above showing where the budget
                    # went before death
                    TR.instant("queryCancelled", cat="query", args={
                        "query_id": ot if isinstance(ot, int) else None,
                        "reason": cancel_reason},
                        level=TR.ESSENTIAL)
                elif status == "degraded":
                    # the device path failed (or the breaker was open)
                    # but the CPU fallback answered: mark the timeline
                    # so the report attributes the tail to degradation
                    TR.instant("queryDegraded", cat="query", args={
                        "reason": degraded_reason,
                        "error": (type(error).__name__
                                  if error is not None else None)},
                        level=TR.ESSENTIAL)
                else:
                    # flush-time marker: the trace ends HERE because the
                    # query raised, not because instrumentation stopped
                    TR.instant("queryError", cat="query", args={
                        "error": type(error).__name__,
                        "message": str(error)[:200]},
                        level=TR.ESSENTIAL)
            except Exception:  # noqa: BLE001 - a marker failure must
                # not mask the query's own error
                log.warning("failed to emit query outcome instant",
                            exc_info=True)
            flight_dump = FLIGHT.dump(
                "query_" + status,
                query_id=ot if isinstance(ot, int) else None,
                error=(type(error).__name__ if error is not None
                       else degraded_reason))
        if qt is not None:
            # cleared first so a finalize failure can never leave a
            # PREVIOUS query's artifacts looking like this one's
            self.last_trace_paths = None
            try:
                self.last_trace_paths = TR.end_query(
                    qt, last_metrics=lm, status=status, error=error,
                    plan_digest=digest)
            except Exception:  # noqa: BLE001 - observability must
                # never fail (or mask the real error of) a query
                log.warning("failed to finalize query trace",
                            exc_info=True)
        if ot is not None:
            try:
                OBS.on_query_end(
                    ot, session=self, plan=plan, status=status,
                    error=error, duration_ns=duration_ns,
                    wall_start_unix=wall0,
                    # only a trace finalized by THIS action may attach:
                    # an untraced query must not inherit a previous
                    # traced query's artifact paths into its history
                    # record (cross_link would then resolve that trace
                    # to the wrong query)
                    trace_paths=(self.last_trace_paths
                                 if qt is not None else None),
                    last_metrics=lm,
                    degraded_reason=degraded_reason,
                    attribution_doc=getattr(self, "_last_attribution",
                                            None),
                    roofline_doc=getattr(self, "_last_roofline", None),
                    aqe_doc=getattr(self, "_last_aqe", None),
                    flight_dump=flight_dump)
            except Exception:  # noqa: BLE001
                log.warning("failed to publish query to obs",
                            exc_info=True)

    def run_partitions(self, exec_root, per_batch):
        """Execute every partition of an exec tree (parallel tasks, up to
        16 concurrent — the Spark task-scheduler role) applying per_batch
        to each output batch. Returns the flat result list in partition
        order. Shared by collect, writes, and the ML handoff."""
        nparts = exec_root.num_partitions

        def run(p: int) -> list:
            with TaskContext(partition_id=p) as ctx:
                return [per_batch(b)
                        for b in exec_root.execute_partition(ctx, p)]

        if nparts == 1:
            return run(0)
        from spark_rapids_tpu.runtime.host_pool import run_task_wave
        out = []
        for res in run_task_wave(run, range(nparts)):
            out.extend(res)
        return out

    def _collect_inner(self, plan: P.PlanNode) -> pa.Table:
        if self.conf.get(C.SQL_MODE).lower() == "explainonly":
            # plan + tag + report only; execution stays on the CPU backend
            # with no device required (reference RapidsConf "explainOnly")
            from spark_rapids_tpu.config import set_session_conf
            from spark_rapids_tpu.plan.overrides import wrap_and_tag
            from spark_rapids_tpu.exec.cpu_backend import execute_cpu
            set_session_conf(self.conf)
            meta = wrap_and_tag(plan, self.conf)
            self._last_meta = meta
            import logging
            logging.getLogger("spark_rapids_tpu").info(
                "\n%s", meta.explain(all_ops=True))
            return execute_cpu(plan, self.conf.get(C.ANSI_ENABLED))
        exec_root, meta = self.prepare_execution(plan)
        explain_mode = self.conf.get(C.SQL_EXPLAIN).upper()
        if explain_mode in ("NOT_ON_TPU", "ALL"):
            text = meta.explain(all_ops=explain_mode == "ALL")
            if "@" in text or explain_mode == "ALL":
                import logging
                logging.getLogger("spark_rapids_tpu").info("\n%s", text)
        names = plan.schema.names

        def fetch(b):
            # compact sparse masked batches ON DEVICE before the download:
            # the tunnel moves full planes, and a bucket-agg output can be
            # a few-percent-occupied 4M-capacity batch
            if b.row_mask is not None and b.capacity > 16384:
                from spark_rapids_tpu.ops import kernels as K
                b = K.compact_batch(b)
            return to_arrow(b, names)

        tables = self.run_partitions(exec_root, fetch)
        if not tables:
            fields = [pa.field(f.name, T.to_arrow(f.dtype))
                      for f in plan.schema.fields]
            return pa.Table.from_arrays(
                [pa.array([], type=f.type) for f in fields], schema=pa.schema(fields))
        return pa.concat_tables(tables)

    def cancel(self, query_id, reason: str = "user") -> bool:
        """Cooperatively cancel an in-flight top-level query by id (the
        ids session.running_queries() / the /queries endpoint report).
        The query's cancel token fires: threads parked on the semaphore,
        the admission queue or a retry backoff wake immediately, and the
        next cooperative checkpoint (per-batch dispatch, pipeline
        refill, wave start, exchange fetch) raises QueryCancelledError,
        which unwinds through normal task completion — permits, pool
        slots and spill handles release on their usual paths. Returns
        False when no such query is in flight (cancel-after-finish is a
        no-op). Also exposed as POST /queries/<id>/cancel on the obs
        endpoint."""
        from spark_rapids_tpu.runtime import lifecycle as LC
        return LC.cancel(query_id, reason=reason)

    def running_queries(self) -> List[dict]:
        """Live progress snapshots of every in-flight top-level query in
        this PROCESS (runtime/obs/live.py; the registry is process-wide,
        like the obs endpoint it feeds): query id, state, elapsed,
        per-exec batches/rows, %-complete and ETA. Pull-based and
        sync-free — scraping never adds device round trips to the
        running queries. Empty when obs or progress tracking is off."""
        from spark_rapids_tpu.runtime.obs import live as _live
        return _live.running_docs(with_execs=True)

    def last_plan_explain(self) -> str:
        return self._last_meta.explain(all_ops=True) if self._last_meta else ""

    def last_attribution(self) -> Optional[dict]:
        """Wall-time attribution of the most recent top-level action
        (runtime/obs/attribution.py): named phase buckets summing to the
        measured wall time. Uses the epilogue's precomputed document
        when one exists; otherwise recomputes from a fresh metric
        snapshot plus the stored per-query aggregate (compile timing,
        task accumulators). None before any action."""
        doc = getattr(self, "_last_attribution", None)
        if doc is not None:
            return doc
        dur = getattr(self, "_last_duration_ns", 0)
        if not dur or getattr(self, "_last_exec", None) is None:
            return None
        from spark_rapids_tpu.runtime.obs import attribution as ATTR
        try:
            return ATTR.attribute(
                self.last_metrics(), dur,
                extra=getattr(self, "_last_attr_extra", None))
        except Exception:  # noqa: BLE001 - attribution is advisory: a
            # poisoned lazy count must not fail an explain
            return None

    def last_audit(self) -> Optional[dict]:
        """Kernel cost audit summary of the most recent top-level action
        (analysis/kernel_audit.py): per-kernel-family dispatches, FLOPs,
        bytes accessed, plane bytes and padding exposure. None when
        spark.rapids.obs.audit.enabled was off for the action."""
        return getattr(self, "_last_audit", None)

    def last_roofline(self) -> Optional[dict]:
        """Roofline attribution of the most recent top-level action:
        audited bytes/FLOPs joined with measured device seconds into
        achieved GB/s + FLOP/s, roofline %, boundedness, and padding
        waste. Uses the epilogue's precomputed doc when one exists;
        otherwise recomputes from the stored audit summary plus a fresh
        metric snapshot. None when the audit was off."""
        doc = getattr(self, "_last_roofline", None)
        if doc is not None:
            return doc
        summary = getattr(self, "_last_audit", None)
        dur = getattr(self, "_last_duration_ns", 0)
        if not summary or not dur \
                or getattr(self, "_last_exec", None) is None:
            return None
        from spark_rapids_tpu.analysis import kernel_audit as KA
        try:
            return KA.roofline(summary, self.last_metrics(), dur,
                               extra=getattr(self, "_last_attr_extra",
                                             None))
        except Exception:  # noqa: BLE001 - the roofline view is
            # advisory: a poisoned lazy count must not fail an explain
            return None

    def last_aqe(self) -> Optional[dict]:
        """Adaptive execution decisions of the most recent top-level
        action (exec/adaptive.py): the decision list plus per-kind
        counts and total dispatches saved. None when adaptive execution
        was off for the action or it made no decisions."""
        return getattr(self, "_last_aqe", None)

    def explain_analyze(self) -> str:
        """The physical exec tree of the MOST RECENT action annotated
        with its actual runtime metrics — rows, batches, dispatches, and
        operator time per exec, straight from last_metrics() (the
        EXPLAIN ANALYZE surface; reference: the Spark SQL tab's metric
        annotations on the live plan). Fused-stage members render
        indented under their stage with the *(N) fusion-group marker,
        each with its own attributed numbers."""
        from spark_rapids_tpu.runtime.metrics import exec_rollup
        root = getattr(self, "_last_exec", None)
        if root is None:
            return "<no executed plan: run an action first>"
        snaps = self.last_metrics()
        lines: List[str] = []
        for key, node, depth, role, sid in walk_exec_tree(root):
            r = exec_rollup(snaps.get(key, {}))
            parts = [f"rows={r['rows']}", f"batches={r['batches']}"]
            if r["dispatches"]:
                parts.append(f"dispatches={r['dispatches']}")
            parts.append(f"time={r['time_ns'] / 1e6:.3f}ms")
            annot = ", ".join(parts)
            pad = "  " * depth
            if role is None:
                mark = f"*({sid}) " if sid is not None else ""
                lines.append(f"{pad}{mark}{node.name()}  [{annot}]")
            else:
                tag = "fused" if role == "member" else role
                lines.append(f"{pad}  *({sid}) {type(node).__name__} "
                             f"[{tag}]  [{annot}]")
        attr = self.last_attribution()
        if attr is not None:
            from spark_rapids_tpu.runtime.obs import attribution as ATTR
            lines.append("")
            lines.extend(ATTR.render_text(attr))
        roof = self.last_roofline()
        if roof is not None:
            from spark_rapids_tpu.analysis import kernel_audit as KA
            lines.append("")
            lines.extend(KA.render_text(roof))
        aqe = self.last_aqe()
        if aqe is not None:
            from spark_rapids_tpu.exec import adaptive as AQ
            lines.append("")
            lines.extend(AQ.render_text(aqe))
        return "\n".join(lines)
