"""SQL string frontend: a recursive-descent parser for the query subset
the engine's DataFrame algebra covers.

Reference parity: the reference is a Spark plugin, so SQL arrives parsed
by Catalyst for free; a standalone framework must carry its own parser
(SURVEY.md §2's user surface). This parser targets the analytic shape
the rest of the engine optimizes: SELECT projections with expressions /
aggregates / aliases, FROM with INNER/LEFT/RIGHT/FULL/SEMI/ANTI JOIN ..
ON equi-conditions, WHERE, GROUP BY, HAVING, ORDER BY .. ASC/DESC
[NULLS FIRST|LAST], LIMIT, UNION ALL, and scalar expression grammar
(arithmetic, comparisons, AND/OR/NOT, BETWEEN, IN, LIKE, IS NULL,
CASE WHEN, CAST(x AS type), function calls routed through
sql.functions). Queries outside the subset raise SparkException with
the offending token — parse-or-reject, never silently misread.
"""
from __future__ import annotations

import re
from typing import List

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.core import SparkException

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
    | (?P<str>'(?:[^']|'')*')
    | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><=|>=|<>|!=|\|\||[-+*/%(),.<>=])
    )""", re.VERBOSE)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "join", "inner",
    "left", "right", "full", "outer", "semi", "anti", "cross", "on",
    "asc", "desc", "union", "all", "distinct", "true", "false", "nulls",
    "first", "last", "with", "over", "partition", "rows",
    "range", "unbounded", "preceding", "following", "current",
    "row", "rollup", "cube", "grouping", "sets", "exists",
    "intersect", "except", "minus",
}

_TYPES = {
    "int": T.INT32, "integer": T.INT32, "bigint": T.INT64,
    "long": T.INT64, "smallint": T.INT16, "tinyint": T.INT8,
    "double": T.FLOAT64, "float": T.FLOAT32, "string": T.STRING,
    "boolean": T.BOOLEAN, "date": T.DATE, "timestamp": T.TIMESTAMP,
}


def _tokenize(text: str):
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise SparkException(f"SQL: cannot tokenize at {rest[:20]!r}")
        pos = m.end()
        if m.group("num") is not None:
            out.append(("num", m.group("num")))
        elif m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("id") is not None:
            word = m.group("id")
            kind = "kw" if word.lower() in _KEYWORDS else "id"
            out.append((kind, word))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", ""))
    return out


class _QCol(E.Col):
    """Qualified column reference (alias.name). The engine resolves by
    bare name, but the parser needs the qualifier to classify
    subquery-correlation predicates (t.k = d.k must NOT collapse to
    k = k)."""

    def __init__(self, name: str, qualifier: str):
        super().__init__(name)
        self.qualifier = qualifier


class _SubSpec:
    """A parsed-but-unbuilt subquery: WHERE conjuncts are kept unapplied
    so correlated predicates (references to OUTER columns) can be
    classified and turned into join keys at lowering time."""

    def __init__(self, items, star, df, conjs, group_keys, having, scope):
        self.items = items          # SELECT item expressions
        self.star = star            # SELECT * ?
        self.df = df                # FROM (joins applied)
        self.conjs = conjs          # WHERE conjuncts, unapplied
        self.group_keys = group_keys
        self.having = having
        self.scope = scope          # alias -> column-name set (FROM)


class _SubqueryMarker(E.Expression):
    """Parser-internal [NOT] EXISTS/IN-subquery placeholder. Lowered to
    a left semi/anti join by _apply_where (the engine's analog of
    Spark's RewritePredicateSubquery; the reference then sees the
    already-lowered joins, GpuBroadcastHashJoinExec etc). Never reaches
    binding."""

    def __init__(self, sub: _SubSpec, in_expr=None):
        self.children = []
        self.sub = sub
        self.in_expr = in_expr      # outer-side expr for IN, None=EXISTS

    def data_type(self):
        return T.BOOLEAN

    def fingerprint(self):
        return f"_SubqueryMarker@{id(self)}"


def _split_and(e):
    if isinstance(e, E.And):
        return _split_and(e.children[0]) + _split_and(e.children[1])
    return [e]


def _has_marker(e):
    if isinstance(e, _SubqueryMarker):
        return True
    fn = getattr(e, "fn", None)  # NamedAgg wraps without .children
    if fn is not None and _has_marker(fn):
        return True
    return any(_has_marker(c) for c in getattr(e, "children", []))


def _and_all(conjs):
    out = conjs[0]
    for c in conjs[1:]:
        out = E.And(out, c)
    return out


class _Parser:
    def __init__(self, text: str, session):
        self.toks = _tokenize(text)
        self.i = 0
        self.session = session
        self.ctes = {}  # WITH-clause name -> DataFrame, query-scoped

    # -- token plumbing -----------------------------------------------------

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def kw(self, *words) -> bool:
        """Consume the keyword sequence if it is next (case-insensitive)."""
        for j, w in enumerate(words):
            k, v = self.peek(j)
            if k != "kw" or v.lower() != w:
                return False
        self.i += len(words)
        return True

    def op(self, sym: str) -> bool:
        k, v = self.peek()
        if k == "op" and v == sym:
            self.i += 1
            return True
        return False

    def expect_op(self, sym: str):
        if not self.op(sym):
            raise SparkException(
                f"SQL: expected {sym!r}, got {self.peek()[1]!r}")

    def ident(self) -> str:
        k, v = self.next()
        if k not in ("id", "kw"):
            raise SparkException(f"SQL: expected identifier, got {v!r}")
        return v

    # -- expressions --------------------------------------------------------

    def expr(self):
        return self._or()

    def _or(self):
        e = self._and()
        while self.kw("or"):
            e = E.Or(e, self._and())
        return e

    def _and(self):
        e = self._not()
        while self.kw("and"):
            e = E.And(e, self._not())
        return e

    def _not(self):
        if self.kw("not"):
            return E.Not(self._not())
        return self._cmp()

    def _cmp(self):
        e = self._add()
        neg = self.kw("not")
        if self.kw("between"):
            lo = self._add()
            if not self.kw("and"):
                raise SparkException("SQL: BETWEEN needs AND")
            hi = self._add()
            out = E.And(E.GreaterThanOrEqual(e, lo),
                        E.LessThanOrEqual(e, hi))
            return E.Not(out) if neg else out
        if self.kw("in"):
            self.expect_op("(")
            if self.peek()[1].lower() == "select":
                sub = self._sub_query_spec()
                self.expect_op(")")
                out = _SubqueryMarker(sub, in_expr=e)
                return E.Not(out) if neg else out
            vals = [self.expr()]
            while self.op(","):
                vals.append(self.expr())
            self.expect_op(")")
            out = E.In(e, vals)
            return E.Not(out) if neg else out
        if self.kw("like"):
            k, v = self.next()
            if k != "str":
                raise SparkException("SQL: LIKE needs a string pattern")
            from spark_rapids_tpu.expr.strings import Like
            out = Like(e, v)
            return E.Not(out) if neg else out
        if neg:
            raise SparkException("SQL: dangling NOT")
        if self.kw("is", "not", "null"):
            return E.IsNotNull(e)
        if self.kw("is", "null"):
            return E.IsNull(e)
        for sym, cls in (("<=", E.LessThanOrEqual),
                         (">=", E.GreaterThanOrEqual),
                         ("<>", None), ("!=", None), ("=", E.EqualTo),
                         ("<", E.LessThan), (">", E.GreaterThan)):
            if self.op(sym):
                r = self._add()
                if cls is None:
                    return E.Not(E.EqualTo(e, r))
                return cls(e, r)
        return e

    def _add(self):
        e = self._mul()
        while True:
            if self.op("+"):
                e = E.Add(e, self._mul())
            elif self.op("-"):
                e = E.Subtract(e, self._mul())
            elif self.op("||"):
                from spark_rapids_tpu.expr.strings import (
                    ConcatStrings)
                e = ConcatStrings(e, self._mul())
            else:
                return e

    def _mul(self):
        e = self._unary()
        while True:
            if self.op("*"):
                e = E.Multiply(e, self._unary())
            elif self.op("/"):
                e = E.Divide(e, self._unary())
            elif self.op("%"):
                e = E.Remainder(e, self._unary())
            else:
                return e

    def _unary(self):
        if self.op("-"):
            return E.UnaryMinus(self._unary())
        if self.op("+"):
            return self._unary()
        return self._primary()

    def _case(self):
        branches = []
        while self.kw("when"):
            cond = self.expr()
            if not self.kw("then"):
                raise SparkException("SQL: CASE WHEN needs THEN")
            branches.append((cond, self.expr()))
        default = self.expr() if self.kw("else") else None
        if not self.kw("end"):
            raise SparkException("SQL: CASE needs END")
        if not branches:
            raise SparkException("SQL: CASE needs at least one WHEN")
        return E.CaseWhen(branches, default)

    def _call(self, name: str):
        """Function call routed through sql.functions (lower-cased)."""
        from spark_rapids_tpu.sql import functions as F
        args: List = []
        if name.lower() == "count" and self.op("*"):
            self.expect_op(")")
            return F.count()
        distinct = self.kw("distinct")
        if not self.op(")"):
            args.append(self.expr())
            while self.op(","):
                args.append(self._scalar_or_expr())
            self.expect_op(")")
        if distinct:
            raise SparkException(
                f"SQL: DISTINCT inside {name}() is not supported")
        fn = getattr(F, name.lower(), None)
        if fn is None or not callable(fn):
            raise SparkException(f"SQL: unknown function {name!r}")
        out = fn(*args)
        if self.kw("over"):
            out = self._over(out)
        return out

    def _frame_bound(self, default):
        if self.kw("unbounded", "preceding") \
                or self.kw("unbounded", "following"):
            return None
        if self.kw("current", "row"):
            return 0
        k, v = self.peek()
        sign = 1
        if k == "op" and v == "-":
            self.next()
            sign = -1
            k, v = self.peek()
        if k == "num":
            self.next()
            n = sign * int(v)
            if self.kw("preceding"):
                return -abs(n)
            if self.kw("following"):
                return abs(n)
            raise SparkException(
                "SQL: frame bound needs PRECEDING/FOLLOWING")
        return default

    def _over(self, fn):
        """fn(...) OVER (PARTITION BY .. ORDER BY .. [ROWS BETWEEN ..])
        -> WindowExpr; aggregates become windowed aggregates."""
        from spark_rapids_tpu.expr import window as WE
        from spark_rapids_tpu.expr.aggregates import AggFunction
        self.expect_op("(")
        spec = WE.WindowSpec()
        if self.kw("partition", "by"):
            parts = [self.expr()]
            while self.op(","):
                parts.append(self.expr())
            spec = spec.partition_by(*parts)
        if self.kw("order", "by"):
            orders = [self._sort_item()]
            while self.op(","):
                orders.append(self._sort_item())
            spec = spec.order_by(*orders)
        if self.kw("rows"):
            if not self.kw("between"):
                raise SparkException("SQL: ROWS needs BETWEEN")
            lo = self._frame_bound(None)
            if not self.kw("and"):
                raise SparkException("SQL: frame needs AND")
            hi = self._frame_bound(None)
            spec = spec.rows_between(lo, hi)
        self.expect_op(")")
        if isinstance(fn, AggFunction):
            return WE.over(fn, spec)
        return fn.over(spec)

    def _scalar_or_expr(self):
        """Trailing function args: plain (optionally negative) numeric
        and string literals stay python values, because many function
        signatures take ints/strs (substring pos, conv bases)."""
        k, v = self.peek()
        sign = 1
        if k == "op" and v == "-" and self.peek(1)[0] == "num" \
                and self.peek(2)[1] in (",", ")"):
            self.next()
            k, v = self.peek()
            sign = -1
        if k == "num" and self.peek(1)[1] in (",", ")"):
            self.next()
            return sign * (float(v) if ("." in v or "e" in v.lower())
                           else int(v))
        if k == "str" and self.peek(1)[1] in (",", ")"):
            self.next()
            return v
        return self.expr()

    def _primary(self):
        k, v = self.peek()
        if k == "num":
            self.next()
            return E.lit(float(v) if ("." in v or "e" in v.lower())
                         else int(v))
        if k == "str":
            self.next()
            return E.lit(v)
        if self.kw("true"):
            return E.lit(True)
        if self.kw("false"):
            return E.lit(False)
        if self.kw("null"):
            return E.Literal(None, T.NULL)
        if self.kw("case"):
            return self._case()
        if self.kw("exists"):
            self.expect_op("(")
            sub = self._sub_query_spec()
            self.expect_op(")")
            return _SubqueryMarker(sub)
        if self.kw("cast"):
            self.expect_op("(")
            e = self.expr()
            if not self.kw("as"):
                raise SparkException("SQL: CAST needs AS")
            tname = self.ident().lower()
            if tname not in _TYPES:
                raise SparkException(f"SQL: unknown type {tname!r}")
            self.expect_op(")")
            return E.Cast(e, _TYPES[tname])
        if self.op("("):
            if self.peek()[1].lower() == "select":
                return self._scalar_subquery()
            e = self.expr()
            self.expect_op(")")
            return e
        if k in ("id", "kw"):
            name = self.ident()
            if self.op("("):
                return self._call(name)
            if self.op("."):
                # qualified a.b: the engine resolves by column name, but
                # the qualifier is kept for subquery-correlation scoping
                return _QCol(self.ident(), name.lower())
            return E.col(name)
        raise SparkException(f"SQL: unexpected token {v!r}")

    # -- subqueries ---------------------------------------------------------

    def _sub_query_spec(self) -> _SubSpec:
        """Parse a predicate subquery WITHOUT applying its WHERE clause
        (correlated conjuncts reference outer columns and must become
        join keys, not filters)."""
        if not self.kw("select"):
            raise SparkException("SQL: subquery must start with SELECT")
        self.kw("distinct")  # semi/anti join semantics make it a no-op
        items, star = [], False
        while True:
            if self.op("*"):
                star = True
            else:
                e = self.expr()
                if self.kw("as") or self.peek()[0] == "id":
                    self.ident()  # aliases are irrelevant to the join
                items.append(e)
            if not self.op(","):
                break
        if not self.kw("from"):
            raise SparkException("SQL: subquery needs FROM")
        saved = getattr(self, "_scope", {})
        df = self._from()
        scope = self._scope
        conjs = []
        if self.kw("where"):
            conjs = _split_and(self.expr())
        group_keys = None
        if self.kw("group", "by"):
            group_keys = [self.expr()]
            while self.op(","):
                group_keys.append(self.expr())
        having = self.expr() if self.kw("having") else None
        # pop the subquery's scope: the ENCLOSING query's scope must not
        # end up holding the subquery's aliases after this parse returns
        self._scope = saved
        return _SubSpec(items, star, df, conjs, group_keys, having, scope)

    def _scalar_subquery(self):
        """(SELECT <single value>): evaluated EAGERLY to a literal (the
        engine analog of Spark's uncorrelated ScalarSubquery, which also
        executes before the main query; correlated scalar subqueries
        raise at build when the outer column fails to resolve)."""
        saved = getattr(self, "_scope", {})
        df = self.select()
        self._scope = saved
        self.expect_op(")")
        tbl = df.limit(2).collect()
        if tbl.num_columns != 1:
            raise SparkException(
                "SQL: scalar subquery must return one column")
        if tbl.num_rows > 1:
            raise SparkException(
                "SQL: scalar subquery returned more than one row")
        dt = T.from_arrow(tbl.schema.field(0).type)
        if tbl.num_rows == 0:
            return E.Literal(None, dt)
        v = tbl.column(0)[0].as_py()
        if v is None:
            return E.Literal(None, dt)
        return E.Cast(E.lit(v), dt)

    def _apply_where(self, df, cond, outer_scope):
        """WHERE lowering: plain conjuncts filter; [NOT] EXISTS/IN
        subquery conjuncts become left semi/anti joins (Spark's
        RewritePredicateSubquery)."""
        plain, subs = [], []
        for c in _split_and(cond):
            neg, inner = False, c
            while isinstance(inner, E.Not) and _has_marker(inner):
                neg = not neg
                inner = inner.children[0]
            if isinstance(inner, _SubqueryMarker):
                subs.append((inner, neg))
            elif _has_marker(c):
                raise SparkException(
                    "SQL: EXISTS/IN subqueries are only supported as "
                    "top-level AND conjuncts of WHERE")
            else:
                plain.append(c)
        if plain:
            df = df.filter(_and_all(plain))
        for m, neg in subs:
            df = self._apply_subquery(df, m, neg, outer_scope)
        return df

    @staticmethod
    def _ref_side(e, sub_cols, sub_scope, outer_cols, outer_scope):
        """'sub' / 'outer' / 'mixed' for one conjunct expression.
        Qualified references resolve innermost-first (the subquery's
        FROM aliases shadow the outer query's), so t.k = d.k keeps its
        two sides apart even though both columns are named k."""
        sides = set()

        def walk(x):
            if isinstance(x, _QCol):
                q = x.qualifier
                if q in sub_scope and x.name.lower() in sub_scope[q]:
                    sides.add("sub")
                elif q in outer_scope and \
                        x.name.lower() in outer_scope[q]:
                    sides.add("outer")
                else:
                    raise SparkException(
                        f"SQL: cannot resolve {q}.{x.name} in the "
                        "subquery or outer scope")
                return
            if isinstance(x, E.Col):
                nm = x.name.lower()
                if nm in sub_cols:
                    sides.add("sub")
                elif nm in outer_cols:
                    sides.add("outer")
                else:
                    raise SparkException(
                        f"SQL: cannot resolve column {x.name!r}")
                return
            for c in x.children:
                walk(c)

        walk(e)
        if sides <= {"sub"}:
            return "sub"
        if sides == {"outer"}:
            return "outer"
        return "mixed"

    def _apply_subquery(self, df, m: _SubqueryMarker, neg: bool,
                        outer_scope):
        spec = m.sub
        outer_cols = {n.lower() for n in df.columns}
        sub_df = spec.df
        sub_cols = {n.lower() for n in sub_df.columns}
        local, pairs = [], []
        for c in spec.conjs:
            side = self._ref_side(c, sub_cols, spec.scope, outer_cols,
                                  outer_scope)
            if side == "sub":
                local.append(c)
                continue
            if isinstance(c, E.EqualTo):
                l, r = c.children
                ls = self._ref_side(l, sub_cols, spec.scope, outer_cols,
                                    outer_scope)
                rs = self._ref_side(r, sub_cols, spec.scope, outer_cols,
                                    outer_scope)
                if ls == "sub" and rs == "outer":
                    pairs.append((r, l))
                    continue
                if rs == "sub" and ls == "outer":
                    pairs.append((l, r))
                    continue
            raise SparkException(
                "SQL: unsupported correlated subquery predicate "
                f"{c!r} (only equality correlation to outer columns)")
        if local:
            sub_df = sub_df.filter(_and_all(local))
        if spec.group_keys is not None:
            if pairs:
                raise SparkException(
                    "SQL: correlated grouped subqueries are not "
                    "supported")
            sub_df = self._grouped_sub(sub_df, spec)
        if m.in_expr is not None:
            if spec.star or len(spec.items) != 1:
                raise SparkException(
                    "SQL: IN subquery must select exactly one item")
            item = spec.items[0]
            if isinstance(item, E.Alias):
                item = item.children[0]
            if neg:
                # NOT IN is null-aware: any NULL in the subquery makes
                # every row UNKNOWN (dropped), and NULL probes only
                # qualify against an EMPTY subquery (no comparisons
                # happen) — the shape the reference handles as a
                # null-aware anti join. The emptiness/has-null shortcuts
                # below evaluate the subquery AS A WHOLE, which is only
                # sound when no correlation restricts it per outer row;
                # a correlated NOT IN would over-drop unrelated outer
                # rows, so reject it instead of guessing.
                if pairs:
                    raise SparkException(
                        "SQL: correlated NOT IN subqueries are not "
                        "supported (null-aware anti join with "
                        "correlation); rewrite as NOT EXISTS with an "
                        "explicit null check")
                if sub_df.limit(1).count() == 0:
                    return df
                has_null = sub_df.filter(
                    E.IsNull(item)).limit(1).count() > 0
                if has_null:
                    return df.filter(E.lit(False))
                df = df.filter(E.IsNotNull(m.in_expr))
            pairs = [(m.in_expr, item)] + pairs
        if not pairs:
            # uncorrelated EXISTS: emptiness decides for every row
            nonempty = sub_df.limit(1).count() > 0
            return df.filter(E.lit(nonempty != neg))
        how = "left_anti" if neg else "left_semi"
        return df.join(sub_df, on=pairs, how=how)

    def _grouped_sub(self, sub_df, spec: _SubSpec):
        """Uncorrelated grouped IN-subquery: GROUP BY + HAVING with the
        single select item preserved."""
        from spark_rapids_tpu.expr.aggregates import AggFunction, NamedAgg
        from spark_rapids_tpu.plan.nodes import expr_name
        aggs = []

        def fold(e):
            if isinstance(e, AggFunction):
                nm = f"__subagg{len(aggs)}"
                aggs.append(NamedAgg(e, nm))
                return E.col(nm)
            return e.with_children([fold(c) for c in e.children])

        having = fold(spec.having) if spec.having is not None else None
        item = spec.items[0] if len(spec.items) == 1 and not spec.star \
            else None
        item_is_agg = isinstance(item, AggFunction) or (
            isinstance(item, E.Alias)
            and isinstance(item.children[0], AggFunction))
        if item_is_agg:
            fn = item.children[0] if isinstance(item, E.Alias) else item
            nm = expr_name(item, 0)
            aggs.append(NamedAgg(fn, nm))
            spec.items = [E.col(nm)]
        out = sub_df.group_by(*spec.group_keys).agg(*aggs)
        if having is not None:
            out = out.filter(having)
        return out

    # -- query --------------------------------------------------------------

    def _table(self):
        alias = None
        if self.op("("):
            # derived table: FROM (SELECT ...) [AS] alias. The nested
            # select()'s own _from rebinds self._scope; save/restore so
            # aliases registered earlier in THIS FROM clause survive and
            # the derived table's inner aliases don't leak into the outer
            # correlation scope.
            saved = getattr(self, "_scope", {})
            df = self.select()
            self._scope = saved
            self.expect_op(")")
        else:
            name = self.ident()
            alias = name.lower()
            df = self.ctes.get(name.lower())
            if df is None:
                df = self.session.table(name)
        # optional alias (resolution stays name-based; recorded for
        # subquery-correlation scoping)
        k, v = self.peek()
        if k == "id" or (k == "kw" and self.kw("as")):
            if k == "id":
                self.next()
                alias = v.lower()
            else:
                alias = self.ident().lower()
        if alias is not None:
            self._scope[alias] = {n.lower() for n in df.columns}
        return df

    def _from(self):
        self._scope = {}
        df = self._table()
        while True:
            how = None
            if self.kw("inner", "join") or self.kw("join"):
                how = "inner"
            elif self.kw("left", "semi", "join"):
                how = "left_semi"
            elif self.kw("left", "anti", "join"):
                how = "left_anti"
            elif self.kw("left", "outer", "join") or self.kw("left", "join"):
                how = "left"
            elif self.kw("right", "outer", "join") \
                    or self.kw("right", "join"):
                how = "right"
            elif self.kw("full", "outer", "join") or self.kw("full", "join"):
                how = "full"
            elif self.kw("cross", "join"):
                how = "cross"
            else:
                return df
            right = self._table()
            if how == "cross":
                df = df.join(right, on=None, how="cross")
                continue
            if not self.kw("on"):
                raise SparkException("SQL: JOIN needs ON")
            cond = self.expr()
            pairs = self._equi_pairs(cond)
            df = df.join(right, on=pairs, how=how)

    def _equi_pairs(self, cond):
        """Flatten `a = b AND c = d ...` into join key pairs."""
        if isinstance(cond, E.And):
            return self._equi_pairs(cond.children[0]) + \
                self._equi_pairs(cond.children[1])
        if isinstance(cond, E.EqualTo):
            return [(cond.children[0], cond.children[1])]
        raise SparkException(
            "SQL: only equi-join ON conditions (a = b AND ...) are "
            f"supported, got {cond!r}")

    def _select_core(self):
        if not self.kw("select"):
            raise SparkException("SQL: expected SELECT")
        distinct = self.kw("distinct")
        items, stars = [], False
        while True:
            if self.op("*"):
                stars = True
            else:
                e = self.expr()
                if self.kw("as"):
                    e = e.alias(self.ident())
                elif self.peek()[0] == "id":
                    e = e.alias(self.ident())
                items.append(e)
            if not self.op(","):
                break
        if not self.kw("from"):
            raise SparkException("SQL: expected FROM")
        df = self._from()
        outer_scope = self._scope
        for it in items:
            if _has_marker(it):
                raise SparkException(
                    "SQL: EXISTS/IN subqueries are only supported in "
                    "WHERE")
        if self.kw("where"):
            df = self._apply_where(df, self.expr(), outer_scope)
        group_keys, group_mode = None, None
        if self.kw("group", "by"):
            if self.kw("rollup") or self.kw("cube"):
                group_mode = self.toks[self.i - 1][1].lower()
                self.expect_op("(")
                group_keys = [self.expr()]
                while self.op(","):
                    group_keys.append(self.expr())
                self.expect_op(")")
            elif self.kw("grouping", "sets"):
                self.expect_op("(")
                raw_sets = []
                while True:
                    self.expect_op("(")
                    s = []
                    if not self.op(")"):
                        s.append(self.expr())
                        while self.op(","):
                            s.append(self.expr())
                        self.expect_op(")")
                    raw_sets.append(s)
                    if not self.op(","):
                        break
                self.expect_op(")")
                # keys = union of set members, first-appearance order
                group_keys, fps = [], []
                for s in raw_sets:
                    for e in s:
                        fp = e.fingerprint()
                        if fp not in fps:
                            fps.append(fp)
                            group_keys.append(e)
                group_mode = [tuple(fps.index(e.fingerprint())
                                    for e in s) for s in raw_sets]
            else:
                group_keys = [self.expr()]
                while self.op(","):
                    group_keys.append(self.expr())
        having = self.expr() if self.kw("having") else None
        if having is not None and _has_marker(having):
            raise SparkException(
                "SQL: EXISTS/IN subqueries are only supported in WHERE")

        from spark_rapids_tpu.expr.aggregates import AggFunction, NamedAgg
        from spark_rapids_tpu.plan.nodes import expr_name  # noqa: F401

        def agg_of(e):
            if isinstance(e, NamedAgg):  # AggFunction.alias() result
                return e.fn, e.name
            if isinstance(e, AggFunction):
                return e, None
            if isinstance(e, E.Alias) and isinstance(e.children[0],
                                                     AggFunction):
                return e.children[0], e.name
            return None, None

        if group_keys is not None:
            aggs, out_names = [], []
            for j, it in enumerate(items):
                fn, nm = agg_of(it)
                if fn is not None:
                    nm = nm or expr_name(it, j)
                    aggs.append(NamedAgg(fn, nm))
                    out_names.append(E.col(nm))
                else:
                    out_names.append(it)

            def fold_agg(e):
                """HAVING aggregates read the agg output: reuse a
                SELECT agg with the same fingerprint or add a hidden
                one (dropped by the final projection)."""
                if isinstance(e, AggFunction):
                    fp = e.fingerprint()
                    for na in aggs:
                        if na.fn.fingerprint() == fp:
                            return E.col(na.name)
                    nm = f"__having{len(aggs)}"
                    aggs.append(NamedAgg(e, nm))
                    return E.col(nm)
                return e.with_children(
                    [fold_agg(c) for c in e.children])

            if having is not None:
                having = fold_agg(having)
            if group_mode == "rollup":
                gd = df.rollup(*group_keys)
            elif group_mode == "cube":
                gd = df.cube(*group_keys)
            elif isinstance(group_mode, list):
                gd = df.grouping_sets(group_mode, *group_keys)
            else:
                gd = df.group_by(*group_keys)
            df = gd.agg(*aggs)
            if having is not None:
                df = df.filter(having)
            final_items = out_names if not stars else None
            if not stars:
                def projector(d):
                    return d.select(*out_names)
            else:
                def projector(d):
                    keep = [E.col(n) for n in d.plan.schema.names
                            if not n.startswith("__having")]
                    return d.select(*keep)
        else:
            if any(agg_of(it)[0] is not None for it in items):
                aggs = []
                for j, it in enumerate(items):
                    fn, nm = agg_of(it)
                    if fn is None:
                        raise SparkException(
                            "SQL: mixing aggregates and plain columns "
                            "needs GROUP BY")
                    aggs.append(NamedAgg(fn, nm or expr_name(it, j)))

                def fold_global(e):
                    if isinstance(e, AggFunction):
                        fp = e.fingerprint()
                        for na in aggs:
                            if na.fn.fingerprint() == fp:
                                return E.col(na.name)
                        nm = f"__having{len(aggs)}"
                        aggs.append(NamedAgg(e, nm))
                        return E.col(nm)
                    return e.with_children(
                        [fold_global(c) for c in e.children])

                if having is not None:
                    having = fold_global(having)
                keep = [E.col(na.name) for na in aggs
                        if not na.name.startswith("__having")]
                df = df.agg(*aggs)
                if having is not None:
                    df = df.filter(having)

                final_items = keep

                def projector(d):
                    return d.select(*keep)
            elif having is not None:
                raise SparkException("SQL: HAVING needs aggregates")
            elif not stars:
                final_items = items

                def projector(d):
                    return d.select(*items)
            elif items:
                raise SparkException(
                    "SQL: SELECT *, expr mixing is not supported")
            else:
                final_items = None

                def projector(d):
                    return d
        if distinct:
            base = projector

            def projector(d):  # noqa: F811 - deliberate wrap
                return base(d).distinct()
        # the projection is DEFERRED so ORDER BY can reference
        # non-projected source columns (standard SQL scoping)
        return df, projector, distinct, final_items

    def select(self):
        """One [SELECT .. UNION ..]* chain with trailing ORDER BY /
        LIMIT applying to the COMBINED result (SQL scoping)."""
        pre, proj, distinct, final_items = self._select_core()
        df = proj(pre)
        unioned = False
        while True:
            # set ops parse left-associative at one precedence level (a
            # documented deviation from the standard's INTERSECT-binds-
            # tighter rule; NDS chains are homogeneous so it is moot)
            if self.kw("union", "all"):
                op = "ua"
            elif self.kw("union"):
                op = "u"
            elif self.kw("intersect"):
                op = "i"
            elif self.kw("except") or self.kw("minus"):
                op = "e"
            else:
                break
            p2, j2, _, _ = self._select_core()
            r = j2(p2)
            if op == "ua":
                df = df.union(r)
            elif op == "u":
                df = df.union(r).distinct()  # bare UNION dedups
            elif op == "i":
                df = df.intersect(r)
            else:
                df = df.subtract(r)
            unioned = True
        if self.kw("order", "by"):
            orders = [self._sort_item()]
            while self.op(","):
                orders.append(self._sort_item())
            try:
                df = df.order_by(*orders)
            except KeyError as ke:
                # ORDER BY a non-projected source column: sort a
                # WIDENED frame (source columns + projected aliases)
                # then project, so aliases and hidden columns mix
                # (unions and DISTINCT expose output columns only)
                if unioned or distinct or final_items is None:
                    raise SparkException(
                        f"SQL: ORDER BY column not in output: {ke}; "
                        "DISTINCT/UNION results sort by output columns "
                        "only") from None
                df = self._order_widened(pre, final_items, orders)
        if self.kw("limit"):
            k, v = self.next()
            if k != "num":
                raise SparkException("SQL: LIMIT needs a number")
            df = df.limit(int(v))
        return df

    def _order_widened(self, pre, final_items, orders):
        from spark_rapids_tpu.plan.nodes import expr_name
        src = pre.plan.schema.names
        lower = {n.lower() for n in src}
        add, names = [], []
        for j, it in enumerate(final_items):
            nm = expr_name(it, j)
            names.append(nm)
            if nm.lower() in lower:
                plain = isinstance(it, E.Col) and it.name.lower() == \
                    nm.lower()
                if not plain:
                    raise SparkException(
                        f"SQL: ORDER BY with alias {nm!r} shadowing a "
                        "source column is not supported")
            else:
                add.append(it if isinstance(it, E.Alias)
                           else E.Alias(it, nm))
        wide = pre.select(*[E.col(n) for n in src], *add)
        try:
            wide = wide.order_by(*orders)
        except KeyError as ke:
            raise SparkException(
                f"SQL: ORDER BY column not found: {ke}") from None
        return wide.select(*[E.col(n) for n in names])

    def _sort_item(self):
        from spark_rapids_tpu.plan.nodes import SortOrder
        e = self.expr()
        asc = True
        if self.kw("desc"):
            asc = False
        else:
            self.kw("asc")
        nulls_first = asc
        if self.kw("nulls", "first"):
            nulls_first = True
        elif self.kw("nulls", "last"):
            nulls_first = False
        return SortOrder(e, ascending=asc, nulls_first=nulls_first)

    def parse(self):
        if self.kw("with"):
            while True:
                name = self.ident()
                if not self.kw("as"):
                    raise SparkException("SQL: WITH needs AS")
                self.expect_op("(")
                self.ctes[name.lower()] = self.select()
                self.expect_op(")")
                if not self.op(","):
                    break
        df = self.select()
        if self.peek()[0] != "eof":
            raise SparkException(
                f"SQL: trailing input at {self.peek()[1]!r}")
        return df


def parse_sql(text: str, session):
    return _Parser(text, session).parse()
