"""SQL string frontend: a recursive-descent parser for the query subset
the engine's DataFrame algebra covers.

Reference parity: the reference is a Spark plugin, so SQL arrives parsed
by Catalyst for free; a standalone framework must carry its own parser
(SURVEY.md §2's user surface). This parser targets the analytic shape
the rest of the engine optimizes: SELECT projections with expressions /
aggregates / aliases, FROM with INNER/LEFT/RIGHT/FULL/SEMI/ANTI JOIN ..
ON equi-conditions, WHERE, GROUP BY, HAVING, ORDER BY .. ASC/DESC
[NULLS FIRST|LAST], LIMIT, UNION ALL, and scalar expression grammar
(arithmetic, comparisons, AND/OR/NOT, BETWEEN, IN, LIKE, IS NULL,
CASE WHEN, CAST(x AS type), function calls routed through
sql.functions). Queries outside the subset raise SparkException with
the offending token — parse-or-reject, never silently misread.
"""
from __future__ import annotations

import re
from typing import List

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.core import SparkException

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
    | (?P<str>'(?:[^']|'')*')
    | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
    | (?P<op><=|>=|<>|!=|\|\||[-+*/%(),.<>=])
    )""", re.VERBOSE)

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "join", "inner",
    "left", "right", "full", "outer", "semi", "anti", "cross", "on",
    "asc", "desc", "union", "all", "distinct", "true", "false", "nulls",
    "first", "last", "with", "over", "partition", "rows",
    "range", "unbounded", "preceding", "following", "current",
    "row",
}

_TYPES = {
    "int": T.INT32, "integer": T.INT32, "bigint": T.INT64,
    "long": T.INT64, "smallint": T.INT16, "tinyint": T.INT8,
    "double": T.FLOAT64, "float": T.FLOAT32, "string": T.STRING,
    "boolean": T.BOOLEAN, "date": T.DATE, "timestamp": T.TIMESTAMP,
}


def _tokenize(text: str):
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            rest = text[pos:].strip()
            if not rest:
                break
            raise SparkException(f"SQL: cannot tokenize at {rest[:20]!r}")
        pos = m.end()
        if m.group("num") is not None:
            out.append(("num", m.group("num")))
        elif m.group("str") is not None:
            out.append(("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("id") is not None:
            word = m.group("id")
            kind = "kw" if word.lower() in _KEYWORDS else "id"
            out.append((kind, word))
        else:
            out.append(("op", m.group("op")))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, text: str, session):
        self.toks = _tokenize(text)
        self.i = 0
        self.session = session
        self.ctes = {}  # WITH-clause name -> DataFrame, query-scoped

    # -- token plumbing -----------------------------------------------------

    def peek(self, k: int = 0):
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def kw(self, *words) -> bool:
        """Consume the keyword sequence if it is next (case-insensitive)."""
        for j, w in enumerate(words):
            k, v = self.peek(j)
            if k != "kw" or v.lower() != w:
                return False
        self.i += len(words)
        return True

    def op(self, sym: str) -> bool:
        k, v = self.peek()
        if k == "op" and v == sym:
            self.i += 1
            return True
        return False

    def expect_op(self, sym: str):
        if not self.op(sym):
            raise SparkException(
                f"SQL: expected {sym!r}, got {self.peek()[1]!r}")

    def ident(self) -> str:
        k, v = self.next()
        if k not in ("id", "kw"):
            raise SparkException(f"SQL: expected identifier, got {v!r}")
        return v

    # -- expressions --------------------------------------------------------

    def expr(self):
        return self._or()

    def _or(self):
        e = self._and()
        while self.kw("or"):
            e = E.Or(e, self._and())
        return e

    def _and(self):
        e = self._not()
        while self.kw("and"):
            e = E.And(e, self._not())
        return e

    def _not(self):
        if self.kw("not"):
            return E.Not(self._not())
        return self._cmp()

    def _cmp(self):
        e = self._add()
        neg = self.kw("not")
        if self.kw("between"):
            lo = self._add()
            if not self.kw("and"):
                raise SparkException("SQL: BETWEEN needs AND")
            hi = self._add()
            out = E.And(E.GreaterThanOrEqual(e, lo),
                        E.LessThanOrEqual(e, hi))
            return E.Not(out) if neg else out
        if self.kw("in"):
            self.expect_op("(")
            vals = [self.expr()]
            while self.op(","):
                vals.append(self.expr())
            self.expect_op(")")
            out = E.In(e, vals)
            return E.Not(out) if neg else out
        if self.kw("like"):
            k, v = self.next()
            if k != "str":
                raise SparkException("SQL: LIKE needs a string pattern")
            from spark_rapids_tpu.expr.strings import Like
            out = Like(e, v)
            return E.Not(out) if neg else out
        if neg:
            raise SparkException("SQL: dangling NOT")
        if self.kw("is", "not", "null"):
            return E.IsNotNull(e)
        if self.kw("is", "null"):
            return E.IsNull(e)
        for sym, cls in (("<=", E.LessThanOrEqual),
                         (">=", E.GreaterThanOrEqual),
                         ("<>", None), ("!=", None), ("=", E.EqualTo),
                         ("<", E.LessThan), (">", E.GreaterThan)):
            if self.op(sym):
                r = self._add()
                if cls is None:
                    return E.Not(E.EqualTo(e, r))
                return cls(e, r)
        return e

    def _add(self):
        e = self._mul()
        while True:
            if self.op("+"):
                e = E.Add(e, self._mul())
            elif self.op("-"):
                e = E.Subtract(e, self._mul())
            elif self.op("||"):
                from spark_rapids_tpu.expr.strings import (
                    ConcatStrings)
                e = ConcatStrings(e, self._mul())
            else:
                return e

    def _mul(self):
        e = self._unary()
        while True:
            if self.op("*"):
                e = E.Multiply(e, self._unary())
            elif self.op("/"):
                e = E.Divide(e, self._unary())
            elif self.op("%"):
                e = E.Remainder(e, self._unary())
            else:
                return e

    def _unary(self):
        if self.op("-"):
            return E.UnaryMinus(self._unary())
        if self.op("+"):
            return self._unary()
        return self._primary()

    def _case(self):
        branches = []
        while self.kw("when"):
            cond = self.expr()
            if not self.kw("then"):
                raise SparkException("SQL: CASE WHEN needs THEN")
            branches.append((cond, self.expr()))
        default = self.expr() if self.kw("else") else None
        if not self.kw("end"):
            raise SparkException("SQL: CASE needs END")
        if not branches:
            raise SparkException("SQL: CASE needs at least one WHEN")
        return E.CaseWhen(branches, default)

    def _call(self, name: str):
        """Function call routed through sql.functions (lower-cased)."""
        from spark_rapids_tpu.sql import functions as F
        args: List = []
        if name.lower() == "count" and self.op("*"):
            self.expect_op(")")
            return F.count()
        distinct = self.kw("distinct")
        if not self.op(")"):
            args.append(self.expr())
            while self.op(","):
                args.append(self._scalar_or_expr())
            self.expect_op(")")
        if distinct:
            raise SparkException(
                f"SQL: DISTINCT inside {name}() is not supported")
        fn = getattr(F, name.lower(), None)
        if fn is None or not callable(fn):
            raise SparkException(f"SQL: unknown function {name!r}")
        out = fn(*args)
        if self.kw("over"):
            out = self._over(out)
        return out

    def _frame_bound(self, default):
        if self.kw("unbounded", "preceding") \
                or self.kw("unbounded", "following"):
            return None
        if self.kw("current", "row"):
            return 0
        k, v = self.peek()
        sign = 1
        if k == "op" and v == "-":
            self.next()
            sign = -1
            k, v = self.peek()
        if k == "num":
            self.next()
            n = sign * int(v)
            if self.kw("preceding"):
                return -abs(n)
            if self.kw("following"):
                return abs(n)
            raise SparkException(
                "SQL: frame bound needs PRECEDING/FOLLOWING")
        return default

    def _over(self, fn):
        """fn(...) OVER (PARTITION BY .. ORDER BY .. [ROWS BETWEEN ..])
        -> WindowExpr; aggregates become windowed aggregates."""
        from spark_rapids_tpu.expr import window as WE
        from spark_rapids_tpu.expr.aggregates import AggFunction
        self.expect_op("(")
        spec = WE.WindowSpec()
        if self.kw("partition", "by"):
            parts = [self.expr()]
            while self.op(","):
                parts.append(self.expr())
            spec = spec.partition_by(*parts)
        if self.kw("order", "by"):
            orders = [self._sort_item()]
            while self.op(","):
                orders.append(self._sort_item())
            spec = spec.order_by(*orders)
        if self.kw("rows"):
            if not self.kw("between"):
                raise SparkException("SQL: ROWS needs BETWEEN")
            lo = self._frame_bound(None)
            if not self.kw("and"):
                raise SparkException("SQL: frame needs AND")
            hi = self._frame_bound(None)
            spec = spec.rows_between(lo, hi)
        self.expect_op(")")
        if isinstance(fn, AggFunction):
            return WE.over(fn, spec)
        return fn.over(spec)

    def _scalar_or_expr(self):
        """Trailing function args: plain (optionally negative) numeric
        and string literals stay python values, because many function
        signatures take ints/strs (substring pos, conv bases)."""
        k, v = self.peek()
        sign = 1
        if k == "op" and v == "-" and self.peek(1)[0] == "num" \
                and self.peek(2)[1] in (",", ")"):
            self.next()
            k, v = self.peek()
            sign = -1
        if k == "num" and self.peek(1)[1] in (",", ")"):
            self.next()
            return sign * (float(v) if ("." in v or "e" in v.lower())
                           else int(v))
        if k == "str" and self.peek(1)[1] in (",", ")"):
            self.next()
            return v
        return self.expr()

    def _primary(self):
        k, v = self.peek()
        if k == "num":
            self.next()
            return E.lit(float(v) if ("." in v or "e" in v.lower())
                         else int(v))
        if k == "str":
            self.next()
            return E.lit(v)
        if self.kw("true"):
            return E.lit(True)
        if self.kw("false"):
            return E.lit(False)
        if self.kw("null"):
            return E.Literal(None, T.NULL)
        if self.kw("case"):
            return self._case()
        if self.kw("cast"):
            self.expect_op("(")
            e = self.expr()
            if not self.kw("as"):
                raise SparkException("SQL: CAST needs AS")
            tname = self.ident().lower()
            if tname not in _TYPES:
                raise SparkException(f"SQL: unknown type {tname!r}")
            self.expect_op(")")
            return E.Cast(e, _TYPES[tname])
        if self.op("("):
            e = self.expr()
            self.expect_op(")")
            return e
        if k in ("id", "kw"):
            name = self.ident()
            if self.op("("):
                return self._call(name)
            if self.op("."):
                # qualified a.b: the engine resolves by column name only
                return E.col(self.ident())
            return E.col(name)
        raise SparkException(f"SQL: unexpected token {v!r}")

    # -- query --------------------------------------------------------------

    def _table(self):
        if self.op("("):
            # derived table: FROM (SELECT ...) [AS] alias
            df = self.select()
            self.expect_op(")")
        else:
            name = self.ident()
            df = self.ctes.get(name.lower())
            if df is None:
                df = self.session.table(name)
        # optional alias (resolution stays name-based)
        k, v = self.peek()
        if k == "id" or (k == "kw" and self.kw("as")):
            if k == "id":
                self.next()
            else:
                self.ident()
        return df

    def _from(self):
        df = self._table()
        while True:
            how = None
            if self.kw("inner", "join") or self.kw("join"):
                how = "inner"
            elif self.kw("left", "semi", "join"):
                how = "left_semi"
            elif self.kw("left", "anti", "join"):
                how = "left_anti"
            elif self.kw("left", "outer", "join") or self.kw("left", "join"):
                how = "left"
            elif self.kw("right", "outer", "join") \
                    or self.kw("right", "join"):
                how = "right"
            elif self.kw("full", "outer", "join") or self.kw("full", "join"):
                how = "full"
            elif self.kw("cross", "join"):
                how = "cross"
            else:
                return df
            right = self._table()
            if how == "cross":
                df = df.join(right, on=None, how="cross")
                continue
            if not self.kw("on"):
                raise SparkException("SQL: JOIN needs ON")
            cond = self.expr()
            pairs = self._equi_pairs(cond)
            df = df.join(right, on=pairs, how=how)

    def _equi_pairs(self, cond):
        """Flatten `a = b AND c = d ...` into join key pairs."""
        if isinstance(cond, E.And):
            return self._equi_pairs(cond.children[0]) + \
                self._equi_pairs(cond.children[1])
        if isinstance(cond, E.EqualTo):
            return [(cond.children[0], cond.children[1])]
        raise SparkException(
            "SQL: only equi-join ON conditions (a = b AND ...) are "
            f"supported, got {cond!r}")

    def _select_core(self):
        if not self.kw("select"):
            raise SparkException("SQL: expected SELECT")
        distinct = self.kw("distinct")
        items, stars = [], False
        while True:
            if self.op("*"):
                stars = True
            else:
                e = self.expr()
                if self.kw("as"):
                    e = e.alias(self.ident())
                elif self.peek()[0] == "id":
                    e = e.alias(self.ident())
                items.append(e)
            if not self.op(","):
                break
        if not self.kw("from"):
            raise SparkException("SQL: expected FROM")
        df = self._from()
        if self.kw("where"):
            df = df.filter(self.expr())
        group_keys = None
        if self.kw("group", "by"):
            group_keys = [self.expr()]
            while self.op(","):
                group_keys.append(self.expr())
        having = self.expr() if self.kw("having") else None

        from spark_rapids_tpu.expr.aggregates import AggFunction, NamedAgg
        from spark_rapids_tpu.plan.nodes import expr_name  # noqa: F401

        def agg_of(e):
            if isinstance(e, NamedAgg):  # AggFunction.alias() result
                return e.fn, e.name
            if isinstance(e, AggFunction):
                return e, None
            if isinstance(e, E.Alias) and isinstance(e.children[0],
                                                     AggFunction):
                return e.children[0], e.name
            return None, None

        if group_keys is not None:
            aggs, out_names = [], []
            for j, it in enumerate(items):
                fn, nm = agg_of(it)
                if fn is not None:
                    nm = nm or expr_name(it, j)
                    aggs.append(NamedAgg(fn, nm))
                    out_names.append(E.col(nm))
                else:
                    out_names.append(it)

            def fold_agg(e):
                """HAVING aggregates read the agg output: reuse a
                SELECT agg with the same fingerprint or add a hidden
                one (dropped by the final projection)."""
                if isinstance(e, AggFunction):
                    fp = e.fingerprint()
                    for na in aggs:
                        if na.fn.fingerprint() == fp:
                            return E.col(na.name)
                    nm = f"__having{len(aggs)}"
                    aggs.append(NamedAgg(e, nm))
                    return E.col(nm)
                return e.with_children(
                    [fold_agg(c) for c in e.children])

            if having is not None:
                having = fold_agg(having)
            df = df.group_by(*group_keys).agg(*aggs)
            if having is not None:
                df = df.filter(having)
            final_items = out_names if not stars else None
            if not stars:
                def projector(d):
                    return d.select(*out_names)
            else:
                def projector(d):
                    keep = [E.col(n) for n in d.plan.schema.names
                            if not n.startswith("__having")]
                    return d.select(*keep)
        else:
            if any(agg_of(it)[0] is not None for it in items):
                aggs = []
                for j, it in enumerate(items):
                    fn, nm = agg_of(it)
                    if fn is None:
                        raise SparkException(
                            "SQL: mixing aggregates and plain columns "
                            "needs GROUP BY")
                    aggs.append(NamedAgg(fn, nm or expr_name(it, j)))

                def fold_global(e):
                    if isinstance(e, AggFunction):
                        fp = e.fingerprint()
                        for na in aggs:
                            if na.fn.fingerprint() == fp:
                                return E.col(na.name)
                        nm = f"__having{len(aggs)}"
                        aggs.append(NamedAgg(e, nm))
                        return E.col(nm)
                    return e.with_children(
                        [fold_global(c) for c in e.children])

                if having is not None:
                    having = fold_global(having)
                keep = [E.col(na.name) for na in aggs
                        if not na.name.startswith("__having")]
                df = df.agg(*aggs)
                if having is not None:
                    df = df.filter(having)

                final_items = keep

                def projector(d):
                    return d.select(*keep)
            elif having is not None:
                raise SparkException("SQL: HAVING needs aggregates")
            elif not stars:
                final_items = items

                def projector(d):
                    return d.select(*items)
            elif items:
                raise SparkException(
                    "SQL: SELECT *, expr mixing is not supported")
            else:
                final_items = None

                def projector(d):
                    return d
        if distinct:
            base = projector

            def projector(d):  # noqa: F811 - deliberate wrap
                return base(d).distinct()
        # the projection is DEFERRED so ORDER BY can reference
        # non-projected source columns (standard SQL scoping)
        return df, projector, distinct, final_items

    def select(self):
        """One [SELECT .. UNION ..]* chain with trailing ORDER BY /
        LIMIT applying to the COMBINED result (SQL scoping)."""
        pre, proj, distinct, final_items = self._select_core()
        df = proj(pre)
        unioned = False
        while True:
            if self.kw("union", "all"):
                p2, j2, _, _ = self._select_core()
                df = df.union(j2(p2))
                unioned = True
            elif self.kw("union"):
                p2, j2, _, _ = self._select_core()
                df = df.union(j2(p2)).distinct()  # bare UNION dedups
                unioned = True
            else:
                break
        if self.kw("order", "by"):
            orders = [self._sort_item()]
            while self.op(","):
                orders.append(self._sort_item())
            try:
                df = df.order_by(*orders)
            except KeyError as ke:
                # ORDER BY a non-projected source column: sort a
                # WIDENED frame (source columns + projected aliases)
                # then project, so aliases and hidden columns mix
                # (unions and DISTINCT expose output columns only)
                if unioned or distinct or final_items is None:
                    raise SparkException(
                        f"SQL: ORDER BY column not in output: {ke}; "
                        "DISTINCT/UNION results sort by output columns "
                        "only") from None
                df = self._order_widened(pre, final_items, orders)
        if self.kw("limit"):
            k, v = self.next()
            if k != "num":
                raise SparkException("SQL: LIMIT needs a number")
            df = df.limit(int(v))
        return df

    def _order_widened(self, pre, final_items, orders):
        from spark_rapids_tpu.plan.nodes import expr_name
        src = pre.plan.schema.names
        lower = {n.lower() for n in src}
        add, names = [], []
        for j, it in enumerate(final_items):
            nm = expr_name(it, j)
            names.append(nm)
            if nm.lower() in lower:
                plain = isinstance(it, E.Col) and it.name.lower() == \
                    nm.lower()
                if not plain:
                    raise SparkException(
                        f"SQL: ORDER BY with alias {nm!r} shadowing a "
                        "source column is not supported")
            else:
                add.append(it if isinstance(it, E.Alias)
                           else E.Alias(it, nm))
        wide = pre.select(*[E.col(n) for n in src], *add)
        try:
            wide = wide.order_by(*orders)
        except KeyError as ke:
            raise SparkException(
                f"SQL: ORDER BY column not found: {ke}") from None
        return wide.select(*[E.col(n) for n in names])

    def _sort_item(self):
        from spark_rapids_tpu.plan.nodes import SortOrder
        e = self.expr()
        asc = True
        if self.kw("desc"):
            asc = False
        else:
            self.kw("asc")
        nulls_first = asc
        if self.kw("nulls", "first"):
            nulls_first = True
        elif self.kw("nulls", "last"):
            nulls_first = False
        return SortOrder(e, ascending=asc, nulls_first=nulls_first)

    def parse(self):
        if self.kw("with"):
            while True:
                name = self.ident()
                if not self.kw("as"):
                    raise SparkException("SQL: WITH needs AS")
                self.expect_op("(")
                self.ctes[name.lower()] = self.select()
                self.expect_op(")")
                if not self.op(","):
                    break
        df = self.select()
        if self.peek()[0] != "eof":
            raise SparkException(
                f"SQL: trailing input at {self.peek()[1]!r}")
        return df


def parse_sql(text: str, session):
    return _Parser(text, session).parse()
