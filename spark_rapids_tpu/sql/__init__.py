from spark_rapids_tpu.sql.session import TpuSession  # noqa: F401
from spark_rapids_tpu.sql.dataframe import DataFrame  # noqa: F401
