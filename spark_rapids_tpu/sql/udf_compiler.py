"""Python-bytecode UDF compiler: CPython bytecode -> expression trees.

Reference parity: /root/reference/udf-compiler/ (CatalystExpressionBuilder
~5.8k LoC translating JVM bytecode to Catalyst so Scala lambdas run as
GPU expressions). The Python-native analog is far smaller because the
target IR (this engine's Expression trees) is already Python: we
symbolically execute the function's bytecode (`dis`) over a stack of
Expression objects, so arithmetic, comparisons, boolean logic,
conditional expressions, str/number builtins, and straight-line local
assignments all become fused device expressions. Anything outside the
supported subset (loops, data-dependent iteration, unknown calls,
closures over mutable state) returns None and the UDF stays on the
row tier — the reference's fall-back-on-unsupported discipline.

Scope notes:
- backward jumps (loops) are rejected; conditional control flow is
  handled by forking the symbolic state at POP_JUMP_* and merging the
  branches into If(cond, a, b) where they reconverge.
- supported calls: abs, min, max, round, float, int, bool, len (on
  strings), and math.{sqrt, exp, log, log10, sin, cos, tan, floor,
  ceil, pow, fabs}.
"""
from __future__ import annotations

import dis
import math
from typing import Dict, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr import math as MA


class _Unsupported(Exception):
    pass


_MATH_CALLS = {
    math.sqrt: MA.Sqrt, math.exp: MA.Exp, math.log: MA.Log,
    math.log10: MA.Log10, math.sin: MA.Sin, math.cos: MA.Cos,
    math.tan: MA.Tan, math.floor: None, math.ceil: None,
    math.fabs: E.Abs,
}

_MAX_STEPS = 500


def _pymod(a: E.Expression, b: E.Expression) -> E.Expression:
    """Python %: result takes the DIVISOR's sign (Spark's Remainder takes
    the dividend's). rem + b where signs disagree."""
    rem = E.Remainder(a, b)
    fix = (E.Not(E.EqualTo(rem, E.Literal.infer(0)))
           & (E.LessThan(rem, E.Literal.infer(0))
              != E.LessThan(b, E.Literal.infer(0))))
    return E.If(fix, E.Add(rem, b), rem)


def _pyfloordiv(a: E.Expression, b: E.Expression) -> E.Expression:
    """Python //: floors toward -inf (IntegralDivide truncates to 0)."""
    q = E.IntegralDivide(a, b)
    rem = E.Remainder(a, b)
    fix = (E.Not(E.EqualTo(rem, E.Literal.infer(0)))
           & (E.LessThan(rem, E.Literal.infer(0))
              != E.LessThan(b, E.Literal.infer(0))))
    return E.If(fix, E.Subtract(q, E.Literal.infer(1)), q)


def _binary(opname: str, a: E.Expression, b: E.Expression) -> E.Expression:
    if opname == "+":
        return E.Add(a, b)
    if opname == "-":
        return E.Subtract(a, b)
    if opname == "*":
        return E.Multiply(a, b)
    if opname == "/":
        return E.Divide(a, b)
    if opname == "%":
        return _pymod(a, b)
    if opname == "//":
        return _pyfloordiv(a, b)
    if opname == "**":
        return MA.Pow(a, b)
    if opname == "&":
        return MA.BitwiseAnd(a, b)
    if opname == "|":
        return MA.BitwiseOr(a, b)
    if opname == "^":
        return MA.BitwiseXor(a, b)
    if opname == "<<":
        return MA.ShiftLeft(a, b)
    if opname == ">>":
        return MA.ShiftRight(a, b)
    raise _Unsupported(f"binary op {opname!r}")


import re as _re

_CMP = {"<": E.LessThan, "<=": E.LessThanOrEqual, ">": E.GreaterThan,
        ">=": E.GreaterThanOrEqual, "==": E.EqualTo,
        "!=": lambda a, b: E.Not(E.EqualTo(a, b))}


def _compare(argrepr: str, a: E.Expression, b: E.Expression) -> E.Expression:
    """Map COMPARE_OP argrepr (possibly wrapped, e.g. 3.13's 'bool(==)')
    to an expression; anything unrecognized is UNSUPPORTED — defaulting
    would silently compile the wrong predicate."""
    m = _re.search(r"(<=|>=|==|!=|<|>)", argrepr)
    if not m:
        raise _Unsupported(f"comparison {argrepr!r}")
    return _CMP[m.group(1)](a, b)


# Python 3.10 emits one opcode per operator (BINARY_ADD, ...); 3.11+
# folds them into BINARY_OP whose argrepr carries the symbol. Support
# both so the compiler works across the interpreter versions this
# engine runs under (the reference compiler has the same bytecode-
# version matrix problem, OpcodeSuite).
_BIN_OPNAMES = {
    "BINARY_ADD": "+", "BINARY_SUBTRACT": "-", "BINARY_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "BINARY_FLOOR_DIVIDE": "//",
    "BINARY_MODULO": "%", "BINARY_POWER": "**", "BINARY_AND": "&",
    "BINARY_OR": "|", "BINARY_XOR": "^", "BINARY_LSHIFT": "<<",
    "BINARY_RSHIFT": ">>",
}
_BIN_OPNAMES.update({k.replace("BINARY_", "INPLACE_"): v
                     for k, v in _BIN_OPNAMES.items()})


class _Frame:
    __slots__ = ("stack", "locals")

    def __init__(self, stack, local_vars):
        self.stack = list(stack)
        self.locals = dict(local_vars)

    def copy(self):
        return _Frame(self.stack, self.locals)


def compile_udf(fn, arg_exprs: List[E.Expression]
                ) -> Optional[E.Expression]:
    """Translate fn's bytecode applied to arg_exprs, or None."""
    try:
        code = fn.__code__
    except AttributeError:
        return None
    if code.co_argcount != len(arg_exprs) or code.co_kwonlyargcount:
        return None
    if fn.__closure__:
        # closures over Expression-free constants could be supported;
        # reject conservatively (mutable captures change semantics)
        cells = [c.cell_contents for c in fn.__closure__]
        if not all(isinstance(v, (int, float, bool, str)) for v in cells):
            return None
    try:
        instrs = list(dis.get_instructions(fn))
        by_offset = {i.offset: idx for idx, i in enumerate(instrs)}
        local_vars = {name: ex for name, ex in
                      zip(code.co_varnames, arg_exprs)}
        cell_map = {}
        if fn.__closure__:
            for name, cell in zip(code.co_freevars, fn.__closure__):
                cell_map[name] = E.Literal.infer(cell.cell_contents)

        def run(idx: int, frame: _Frame, depth: int) -> E.Expression:
            if depth > 40:
                raise _Unsupported("branch nesting too deep")
            steps = 0
            while idx < len(instrs):
                steps += 1
                if steps > _MAX_STEPS:
                    raise _Unsupported("too many instructions")
                ins = instrs[idx]
                op = ins.opname
                st = frame.stack
                if op in ("RESUME", "PRECALL", "CACHE", "NOP",
                          "PUSH_NULL", "MAKE_CELL", "COPY_FREE_VARS"):
                    idx += 1
                elif op == "LOAD_CONST":
                    st.append(("const", ins.argval))
                    idx += 1
                elif op in ("LOAD_FAST", "LOAD_FAST_CHECK"):
                    if ins.argval not in frame.locals:
                        raise _Unsupported(f"unbound local {ins.argval}")
                    st.append(frame.locals[ins.argval])
                    idx += 1
                elif op == "LOAD_DEREF":
                    if ins.argval not in cell_map:
                        raise _Unsupported(f"free var {ins.argval}")
                    st.append(cell_map[ins.argval])
                    idx += 1
                elif op == "STORE_FAST":
                    frame.locals[ins.argval] = _as_expr(st.pop())
                    idx += 1
                elif op == "LOAD_GLOBAL":
                    g = fn.__globals__.get(ins.argval,
                                           getattr(__builtins__, "get",
                                                   lambda *_: None)(
                                               ins.argval)
                                           if isinstance(__builtins__, dict)
                                           else getattr(__builtins__,
                                                        ins.argval, None))
                    if g is None:
                        import builtins
                        g = getattr(builtins, ins.argval, None)
                    if g is None:
                        raise _Unsupported(f"global {ins.argval}")
                    st.append(("callable", g))
                    idx += 1
                elif op == "LOAD_ATTR" or op == "LOAD_METHOD":
                    base = st.pop()
                    if isinstance(base, tuple) and base[0] == "callable":
                        attr = getattr(base[1], ins.argval, None)
                        if attr is None:
                            raise _Unsupported(f"attr {ins.argval}")
                        st.append(("callable", attr))
                        idx += 1
                    else:
                        raise _Unsupported("attribute on value")
                elif op == "BINARY_OP":
                    b = _as_expr(st.pop())
                    a = _as_expr(st.pop())
                    sym = ins.argrepr.rstrip("=")
                    st.append(_binary(sym, a, b))
                    idx += 1
                elif op in _BIN_OPNAMES:  # 3.10 per-operator opcodes
                    b = _as_expr(st.pop())
                    a = _as_expr(st.pop())
                    st.append(_binary(_BIN_OPNAMES[op], a, b))
                    idx += 1
                elif op == "COMPARE_OP":
                    b = _as_expr(st.pop())
                    a = _as_expr(st.pop())
                    st.append(_compare(ins.argrepr, a, b))
                    idx += 1
                elif op == "UNARY_NEGATIVE":
                    st.append(E.UnaryMinus(_as_expr(st.pop())))
                    idx += 1
                elif op == "UNARY_NOT":
                    st.append(E.Not(_as_expr(st.pop())))
                    idx += 1
                elif op == "CALL":
                    n = ins.arg
                    args = [_as_expr(st.pop()) for _ in range(n)][::-1]
                    target = st.pop()
                    if st and isinstance(st[-1], tuple) \
                            and st[-1] == ("null",):
                        st.pop()
                    if not (isinstance(target, tuple)
                            and target[0] == "callable"):
                        raise _Unsupported("call of computed value")
                    st.append(_call(target[1], args))
                    idx += 1
                elif op in ("CALL_FUNCTION", "CALL_METHOD"):
                    # 3.10 call forms: n args above the callable; no NULL
                    # sentinel (LOAD_METHOD's self slot is folded into the
                    # single ("callable", fn) entry LOAD_METHOD pushed)
                    n = ins.arg
                    args = [_as_expr(st.pop()) for _ in range(n)][::-1]
                    target = st.pop()
                    if not (isinstance(target, tuple)
                            and target[0] == "callable"):
                        raise _Unsupported("call of computed value")
                    st.append(_call(target[1], args))
                    idx += 1
                elif op == "DUP_TOP":  # 3.10's COPY(1)
                    st.append(st[-1])
                    idx += 1
                elif op == "ROT_TWO":  # 3.10's SWAP(2)
                    st[-1], st[-2] = st[-2], st[-1]
                    idx += 1
                elif op == "JUMP_ABSOLUTE":
                    # forward only: a backward absolute jump is a loop
                    jump_idx = by_offset[ins.argval]
                    if jump_idx <= idx:
                        raise _Unsupported("loop")
                    idx = jump_idx
                elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
                    cond = _as_expr(st.pop())
                    if op.endswith("TRUE"):
                        cond = E.Not(cond)
                    jump_idx = by_offset[ins.argval]
                    if jump_idx <= idx:
                        raise _Unsupported("loop")
                    then_v = run(idx + 1, frame.copy(), depth + 1)
                    else_v = run(jump_idx, frame.copy(), depth + 1)
                    return E.If(cond, then_v, else_v)
                elif op in ("JUMP_FORWARD",):
                    idx = by_offset[ins.argval]
                elif op == "RETURN_VALUE":
                    return _as_expr(st.pop())
                elif op == "RETURN_CONST":
                    return _as_expr(("const", ins.argval))
                elif op in ("COPY",):
                    st.append(st[-ins.arg])
                    idx += 1
                elif op in ("SWAP",):
                    st[-1], st[-ins.arg] = st[-ins.arg], st[-1]
                    idx += 1
                else:
                    raise _Unsupported(op)
            raise _Unsupported("fell off the end")

        return run(0, _Frame([], local_vars), 0)
    except _Unsupported:
        return None
    except Exception:  # noqa: BLE001 - never break planning on odd bytecode
        return None


def _as_expr(v) -> E.Expression:
    if isinstance(v, E.Expression):
        return v
    if isinstance(v, tuple) and v and v[0] == "const":
        if v[1] is None or isinstance(v[1], (bool, int, float, str)):
            return E.Literal.infer(v[1])
        raise _Unsupported(f"const {type(v[1]).__name__}")
    raise _Unsupported(f"non-expression {v!r}")


def _call(target, args: List[E.Expression]) -> E.Expression:
    import builtins
    if target is builtins.abs:
        return E.Abs(args[0])
    if target is builtins.min and len(args) >= 2:
        return MA.Least(args)
    if target is builtins.max and len(args) >= 2:
        return MA.Greatest(args)
    if target is builtins.round:
        from spark_rapids_tpu.expr.math import Round
        if len(args) == 1:
            return MA.BRound(args[0], 0)  # python round is half-even
        raise _Unsupported("round with dynamic digits")
    if target is builtins.float:
        return E.Cast(args[0], T.FLOAT64)
    if target is builtins.int:
        return E.Cast(args[0], T.INT64)
    if target is builtins.bool:
        return E.Cast(args[0], T.BOOLEAN)
    if target is builtins.len:
        from spark_rapids_tpu.expr.strings import StringLength
        return StringLength(args[0])
    if target is math.sqrt:
        return MA.Sqrt(args[0])
    if target is math.exp:
        return MA.Exp(args[0])
    if target is math.log:
        return MA.Log(args[0])
    if target is math.log10:
        return MA.Log10(args[0])
    if target is math.sin:
        return MA.Sin(args[0])
    if target is math.cos:
        return MA.Cos(args[0])
    if target is math.tan:
        return MA.Tan(args[0])
    if target is math.fabs:
        return E.Abs(E.Cast(args[0], T.FLOAT64))
    if target is math.floor:
        return MA.Floor(args[0])
    if target is math.ceil:
        return MA.Ceil(args[0])
    if target is math.pow:
        return MA.Pow(args[0], args[1])
    raise _Unsupported(f"call {target!r}")
