"""Apache Iceberg table format (v1 subset) over the native engine.

Reference parity: sql-plugin/src/main/java/com/nvidia/spark/rapids/
iceberg/ (31 files wiring Iceberg scans to the GPU parquet reader).
This module implements the table FORMAT itself against the spec's v1
layout so the engine can read and write Iceberg tables standalone:

- ``metadata/vN.metadata.json`` with table uuid, schema, snapshot log;
  ``version-hint.text`` points at the current version; commits claim
  ``vN.metadata.json`` with an exclusive create (optimistic concurrency,
  same discipline as sql/delta.py).
- snapshots reference an Avro MANIFEST LIST whose entries point at Avro
  MANIFEST files; manifest entries carry a nested ``data_file`` record
  (file path, format, record count, size) — written and read with the
  engine's own OCF machinery (io/avro.py nested-record support).
- reads replay the current (or time-traveled) snapshot's manifests,
  keep entries with status EXISTING/ADDED, and scan the parquet files
  through the normal DataFrame path.

Subset notes (documented): unpartitioned tables, parquet data files,
no delete files / positional deletes, single-schema evolution (the
current schema applies to all snapshots).
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.expr.core import SparkException
from spark_rapids_tpu.io.avro import read_avro, write_avro
from spark_rapids_tpu.io import read_parquet_file as _read_pq


class IcebergConcurrentCommit(SparkException):
    pass


_STATUS_ADDED = 1
_STATUS_DELETED = 2


def _iceberg_schema(schema: pa.Schema) -> dict:
    def ftype(t):
        if pa.types.is_int64(t):
            return "long"
        if pa.types.is_int32(t):
            return "int"
        if pa.types.is_float64(t):
            return "double"
        if pa.types.is_float32(t):
            return "float"
        if pa.types.is_boolean(t):
            return "boolean"
        if pa.types.is_date32(t):
            return "date"
        if pa.types.is_timestamp(t):
            return "timestamp"
        return "string"
    return {"type": "struct",
            "schema-id": 0,
            "fields": [{"id": i + 1, "name": f.name, "required": False,
                        "type": ftype(f.type)}
                       for i, f in enumerate(schema)]}


_FROM_ICEBERG_TYPE = {
    "long": pa.int64(), "int": pa.int32(), "double": pa.float64(),
    "float": pa.float32(), "boolean": pa.bool_(), "date": pa.date32(),
    "timestamp": pa.timestamp("us"), "string": pa.string()}


def _arrow_schema(ice_schema: dict) -> pa.Schema:
    fields = []
    for f in ice_schema["fields"]:
        if f["type"] not in _FROM_ICEBERG_TYPE:
            # only foreign tables can hit this: _iceberg_schema never
            # writes other type names
            raise SparkException(
                f"unsupported iceberg type {f['type']!r} for {f['name']!r}")
        fields.append(pa.field(f["name"], _FROM_ICEBERG_TYPE[f["type"]]))
    return pa.schema(fields)


class IcebergTable:
    """Read/write an Iceberg v1-subset table directory."""

    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        self.meta_dir = os.path.join(path, "metadata")

    # -- metadata plumbing --------------------------------------------------

    def _current_version(self) -> int:
        hint = os.path.join(self.meta_dir, "version-hint.text")
        if not os.path.isfile(hint):
            raise SparkException(f"{self.path} is not an Iceberg table")
        with open(hint) as f:
            return int(f.read().strip())

    def _metadata(self, version: Optional[int] = None) -> dict:
        v = self._current_version() if version is None else version
        with open(os.path.join(self.meta_dir,
                               f"v{v}.metadata.json")) as f:
            return json.load(f)

    def _commit_metadata(self, version: int, meta: dict) -> None:
        os.makedirs(self.meta_dir, exist_ok=True)
        target = os.path.join(self.meta_dir, f"v{version}.metadata.json")
        try:
            with open(target, "x") as f:
                json.dump(meta, f, indent=1)
        except FileExistsError:
            raise IcebergConcurrentCommit(
                f"metadata v{version} of {self.path} was committed "
                f"concurrently") from None
        with open(os.path.join(self.meta_dir, "version-hint.text"),
                  "w") as f:
            f.write(str(version))

    # -- manifests ----------------------------------------------------------

    def _write_data_files(self, table: pa.Table) -> List[dict]:
        os.makedirs(os.path.join(self.path, "data"), exist_ok=True)
        name = f"data/{uuid.uuid4().hex}.parquet"
        fp = os.path.join(self.path, name)
        pq.write_table(table, fp, compression="snappy")
        return [{"file_path": name, "file_format": "PARQUET",
                 "record_count": table.num_rows,
                 "file_size_in_bytes": os.path.getsize(fp)}]

    def _write_manifest(self, snapshot_id: int, data_files: List[dict]
                        ) -> dict:
        entries = pa.table({
            "status": pa.array([_STATUS_ADDED] * len(data_files),
                               pa.int32()),
            "snapshot_id": pa.array([snapshot_id] * len(data_files),
                                    pa.int64()),
            "data_file": pa.array(data_files, pa.struct([
                ("file_path", pa.string()),
                ("file_format", pa.string()),
                ("record_count", pa.int64()),
                ("file_size_in_bytes", pa.int64()),
            ])),
        })
        os.makedirs(self.meta_dir, exist_ok=True)
        name = f"metadata/snap-m-{uuid.uuid4().hex}.avro"
        write_avro(os.path.join(self.path, name), entries)
        total = sum(d["record_count"] for d in data_files)
        return {"manifest_path": name,
                "manifest_length": os.path.getsize(
                    os.path.join(self.path, name)),
                "partition_spec_id": 0,
                "added_snapshot_id": snapshot_id,
                "added_data_files_count": len(data_files),
                "added_rows_count": total}

    def _write_manifest_list(self, snapshot_id: int,
                             manifests: List[dict]) -> str:
        t = pa.table({k: pa.array([m[k] for m in manifests])
                      for k in ("manifest_path", "manifest_length",
                                "partition_spec_id", "added_snapshot_id",
                                "added_data_files_count",
                                "added_rows_count")})
        name = f"metadata/snap-{snapshot_id}-{uuid.uuid4().hex}.avro"
        write_avro(os.path.join(self.path, name), t)
        return name

    def _snapshot_manifests(self, meta: dict, snapshot_id: int
                            ) -> List[dict]:
        snap = next(s for s in meta["snapshots"]
                    if s["snapshot-id"] == snapshot_id)
        ml = read_avro(os.path.join(self.path, snap["manifest-list"]))
        return ml.to_pylist()

    # -- public API ---------------------------------------------------------

    @staticmethod
    def create(session, path: str, df) -> "IcebergTable":
        t = IcebergTable(session, path)
        table = df.collect() if hasattr(df, "collect") else df
        os.makedirs(path, exist_ok=True)
        snapshot_id = int(time.time() * 1000)
        files = t._write_data_files(table)
        manifest = t._write_manifest(snapshot_id, files)
        ml = t._write_manifest_list(snapshot_id, [manifest])
        meta = {
            "format-version": 1,
            "table-uuid": str(uuid.uuid4()),
            "location": path,
            "last-updated-ms": int(time.time() * 1000),
            "last-column-id": table.num_columns,
            "schema": _iceberg_schema(table.schema),
            "partition-spec": [],
            "properties": {},
            "current-snapshot-id": snapshot_id,
            "snapshots": [{"snapshot-id": snapshot_id,
                           "timestamp-ms": int(time.time() * 1000),
                           "manifest-list": ml,
                           "summary": {"operation": "append"}}],
        }
        t._commit_metadata(1, meta)
        return t

    @staticmethod
    def for_path(session, path: str) -> "IcebergTable":
        t = IcebergTable(session, path)
        t._metadata()  # validates
        return t

    def append(self, df) -> None:
        table = df.collect() if hasattr(df, "collect") else df
        v = self._current_version()
        meta = self._metadata(v)
        old_manifests = self._snapshot_manifests(
            meta, meta["current-snapshot-id"]) \
            if meta.get("current-snapshot-id") else []
        snapshot_id = max(int(time.time() * 1000),
                          meta["current-snapshot-id"] + 1)
        files = self._write_data_files(table)
        manifest = self._write_manifest(snapshot_id, files)
        ml = self._write_manifest_list(snapshot_id,
                                       old_manifests + [manifest])
        meta = dict(meta)
        meta["current-snapshot-id"] = snapshot_id
        meta["last-updated-ms"] = int(time.time() * 1000)
        meta["snapshots"] = meta["snapshots"] + [
            {"snapshot-id": snapshot_id,
             "timestamp-ms": int(time.time() * 1000),
             "manifest-list": ml,
             "summary": {"operation": "append"}}]
        self._commit_metadata(v + 1, meta)

    def data_files(self, snapshot_id: Optional[int] = None) -> List[dict]:
        meta = self._metadata()
        sid = snapshot_id if snapshot_id is not None \
            else meta["current-snapshot-id"]
        out = []
        for m in self._snapshot_manifests(meta, sid):
            entries = read_avro(
                os.path.join(self.path, m["manifest_path"]))
            for e in entries.to_pylist():
                if e["status"] != _STATUS_DELETED:
                    out.append(e["data_file"])
        return out

    def to_df(self, snapshot_id: Optional[int] = None):
        files = self.data_files(snapshot_id)
        if not files:
            # Empty snapshot: the metadata carries the schema.
            schema = _arrow_schema(self._metadata()["schema"])
            return self.session.create_dataframe(schema.empty_table())
        table = pa.concat_tables([
            _read_pq(os.path.join(self.path, f["file_path"]))
            for f in files])
        return self.session.create_dataframe(table)

    def snapshots(self) -> List[dict]:
        return [{"snapshot_id": s["snapshot-id"],
                 "timestamp_ms": s["timestamp-ms"],
                 "operation": s["summary"].get("operation")}
                for s in self._metadata()["snapshots"]]
