"""User-defined functions.

Reference parity, three tiers mirroring SURVEY.md §2.8:

- `udf(fn, return_type)` — row-wise Python UDF. Like Spark UDFs it is
  opaque; it executes on the CPU interpreter via per-operator fallback
  (the reference's row-based UDF bridge).
- `jax_udf(fn, return_type)` — the RapidsUDF.evaluateColumnar analog,
  TPU-native: fn maps jnp value/validity planes to (values, validity) and
  traces INTO the enclosing fused stage — zero dispatch overhead, full
  XLA fusion. This is strictly stronger than the reference's udf-compiler
  (which reverse-engineers JVM bytecode into Catalyst): here the user
  writes the columnar form directly in jax.
- `df_udf` style — because expressions are first-class Python objects,
  any function composing Column expressions already IS a df_udf
  (reference sql-plugin-api functions.scala / DF_UDF_README.md); no
  bytecode translation layer is needed.
"""
from __future__ import annotations

from typing import Callable, List

import numpy as np

import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector
from spark_rapids_tpu.expr.core import CpuCol, Expression, _valid_of


class PythonRowUDF(Expression):
    """Opaque row-wise UDF: CPU-only (per-operator fallback runs it)."""

    def __init__(self, fn: Callable, return_type: T.DataType,
                 children: List[Expression], name: str = ""):
        self.fn = fn
        self.return_type = return_type
        self.children = list(children)
        self.name = name or getattr(fn, "__name__", "udf")

    def data_type(self):
        return self.return_type

    def _params(self):
        return f"{self.name}@{id(self.fn):x}"

    def with_children(self, children):
        return PythonRowUDF(self.fn, self.return_type, children, self.name)

    def supported_on_tpu(self):
        return False

    def eval_tpu(self, ctx):
        raise NotImplementedError(
            f"python UDF {self.name!r} is opaque; runs on CPU "
            f"(write a jax_udf for device execution)")

    def eval_cpu(self, cols, ansi=False):
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        n = len(ins[0].values) if ins else 0
        rows = [tuple(c.values[i] if c.valid[i] else None for c in ins)
                for i in range(n)]
        out = None
        from spark_rapids_tpu import config as C
        if C.conf().get(C.PY_WORKER_POOL_ENABLED):
            from spark_rapids_tpu.runtime import pyworker
            import os as _os
            par = C.conf().get(C.PY_WORKER_POOL_PARALLELISM) or \
                (_os.cpu_count() or 1)
            out = pyworker.map_rows(self.fn, rows, par)
        if out is None:  # small batch / unpicklable fn: in-process
            out = [self.fn(*args) for args in rows]
        valid = np.array([r is not None for r in out], np.bool_) \
            if n else np.ones(0, np.bool_)
        if isinstance(self.return_type, T.StringType):
            vals = np.array(out, object)
        else:
            vals = np.array([0 if v is None else v for v in out]
                            ).astype(self.return_type.np_dtype)
        return CpuCol(self.return_type, vals, valid)


class JaxColumnarUDF(Expression):
    """Columnar device UDF: fn((values, validity), ...) -> values or
    (values, validity), traced into the fused stage. The TPU-native
    answer to RapidsUDF.evaluateColumnar — and to the udf-compiler, since
    the user writes the columnar computation directly."""

    def __init__(self, fn: Callable, return_type: T.DataType,
                 children: List[Expression], name: str = ""):
        self.fn = fn
        self.return_type = return_type
        self.children = list(children)
        self.name = name or getattr(fn, "__name__", "jax_udf")

    def data_type(self):
        return self.return_type

    def _params(self):
        return f"{self.name}@{id(self.fn):x}"

    def with_children(self, children):
        return JaxColumnarUDF(self.fn, self.return_type, children, self.name)

    def eval_tpu(self, ctx):
        ins = [c.eval_tpu(ctx) for c in self.children]
        args = [(c.data, _valid_of(c, ctx)) for c in ins]
        res = self.fn(*args)
        if isinstance(res, tuple):
            vals, valid = res
        else:
            vals = res
            valid = None
            for c in ins:
                v = _valid_of(c, ctx)
                valid = v if valid is None else (valid & v)
        vals = jnp.asarray(vals)
        if vals.dtype != np.dtype(self.return_type.np_dtype):
            vals = vals.astype(self.return_type.np_dtype)
        return ColumnVector(self.return_type, vals, valid)

    def eval_cpu(self, cols, ansi=False):
        # run the SAME jax function on host arrays: one implementation,
        # both backends (differential tests come for free)
        ins = [c.eval_cpu(cols, ansi) for c in self.children]
        args = [(jnp.asarray(c.values), jnp.asarray(c.valid)) for c in ins]
        res = self.fn(*args)
        if isinstance(res, tuple):
            vals, valid = np.asarray(res[0]), np.asarray(res[1])
        else:
            vals = np.asarray(res)
            valid = np.ones(len(vals), np.bool_)
            for c in ins:
                valid = valid & c.valid
        return CpuCol(self.return_type,
                      vals.astype(self.return_type.np_dtype), valid)


def udf(fn: Callable = None, return_type: T.DataType = T.STRING):
    """Row-wise Python UDF decorator/factory. Simple bodies (arithmetic,
    comparisons, conditionals, math builtins) are TRANSLATED to fused
    device expressions by the bytecode compiler (reference udf-compiler,
    conf spark.rapids.sql.udfCompiler.enabled); everything else runs on
    the CPU row tier via per-operator fallback."""
    def make(f):
        def builder(*cols):
            from spark_rapids_tpu import config as C
            from spark_rapids_tpu.expr.core import (
                Cast, Expression as _E, col as _c)
            es = [c if isinstance(c, _E) else _c(c) for c in cols]
            if C.conf().get(C.UDF_COMPILER_ENABLED):
                from spark_rapids_tpu.sql.udf_compiler import compile_udf
                compiled = compile_udf(f, es)
                if compiled is not None:
                    try:
                        same = compiled.data_type() == return_type
                    except Exception:  # noqa: BLE001 - unresolved refs
                        same = False
                    return compiled if same else Cast(compiled, return_type)
            return PythonRowUDF(f, return_type, es)
        builder.__name__ = getattr(f, "__name__", "udf")
        return builder
    if fn is not None:
        return make(fn)
    return make


def jax_udf(fn: Callable = None, return_type: T.DataType = T.FLOAT64):
    """Columnar jax UDF decorator/factory: fuses into the device stage."""
    def make(f):
        def builder(*cols):
            from spark_rapids_tpu.expr.core import Expression as _E, col as _c
            es = [c if isinstance(c, _E) else _c(c) for c in cols]
            return JaxColumnarUDF(f, return_type, es)
        builder.__name__ = getattr(f, "__name__", "jax_udf")
        return builder
    if fn is not None:
        return make(fn)
    return make
