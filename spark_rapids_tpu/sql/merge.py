"""MERGE INTO: Delta-style upsert on the native engine.

Reference parity: delta-lake/delta-24x/.../GpuMergeIntoCommand.scala
(deletion-vector-free merge): the merged table is built from
 - matched target rows transformed by WHEN MATCHED UPDATE/DELETE clauses,
 - unmatched target rows carried through unchanged,
 - source rows with no target match inserted by WHEN NOT MATCHED,
with the Delta cardinality check: a target row matched by MULTIPLE source
rows while an UPDATE/DELETE clause exists is an error
(DELTA_MULTIPLE_SOURCE_ROW_MATCHING_TARGET_ROW_IN_MERGE).

TPU-first shape: one left join (target x renamed source) evaluates every
matched clause as fused conditional projections; inserts are one anti
join; the result is their union — all existing device operators, no
row-wise command interpreter.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.core import SparkException, col, lit


class MergeInto:
    """Builder mirroring the Delta merge API:

        MergeInto(target, source, on=["k"]) \\
            .when_matched_update({"v": col("__src_v")}) \\
            .when_not_matched_insert() \\
            .result()

    Inside clause expressions, source columns are visible as
    ``__src_<name>``; target columns keep their names."""

    SRC = "__src_"

    def __init__(self, target, source, on: List[str]):
        if not on:
            raise SparkException("MERGE requires at least one ON key")
        self.target = target
        self.source = source
        self.on = list(on)
        self._update: Optional[Dict[str, E.Expression]] = None
        self._update_cond: Optional[E.Expression] = None
        self._delete = False
        self._delete_cond: Optional[E.Expression] = None
        self._insert: Optional[Dict[str, E.Expression]] = None
        self._insert_cond: Optional[E.Expression] = None

    # -- clause builders ---------------------------------------------------
    def when_matched_update(self, set: Dict[str, object],  # noqa: A002
                            condition=None) -> "MergeInto":
        self._update = {k: _e(v) for k, v in set.items()}
        self._update_cond = _e(condition) if condition is not None else None
        return self

    def when_matched_delete(self, condition=None) -> "MergeInto":
        self._delete = True
        self._delete_cond = _e(condition) if condition is not None else None
        return self

    def when_not_matched_insert(self, values: Optional[Dict[str, object]] = None,
                                condition=None) -> "MergeInto":
        self._insert = ({k: _e(v) for k, v in values.items()}
                        if values is not None else {})
        self._insert_cond = _e(condition) if condition is not None else None
        return self

    # -- execution ---------------------------------------------------------
    def _renamed_source(self):
        s = self.source
        return s.select(*[col(n).alias(self.SRC + n)
                          for n in s.plan.schema.names])

    def _check_cardinality(self) -> None:
        """Delta: an UPDATE/DELETE clause + a target row matched by more
        than one source row is an error."""
        from spark_rapids_tpu.sql import functions as F
        if self._update is None and not self._delete:
            return
        dup = (self.source.join(self.target.select(
                   *[col(k) for k in self.on]).distinct(),
                   on=self.on, how="left_semi")
               .group_by(*[col(k) for k in self.on])
               .agg(F.count().alias("__n"))
               .filter(col("__n") > lit(1)))
        if dup.count() > 0:
            raise SparkException(
                "MERGE INTO: a target row was matched by multiple source "
                "rows with an UPDATE/DELETE clause (Delta "
                "DELTA_MULTIPLE_SOURCE_ROW_MATCHING_TARGET_ROW_IN_MERGE)")

    def result(self):
        """The merged table as a DataFrame (collect/write it)."""
        self._check_cardinality()
        tnames = self.target.plan.schema.names
        src = self._renamed_source()
        pairs = [(col(k), col(self.SRC + k)) for k in self.on]
        j = self.target.join(src, on=pairs, how="left")
        matched = col(self.SRC + self.on[0]).is_not_null()

        # WHEN MATCHED DELETE: drop matching target rows (condition-gated)
        keep = lit(True)
        if self._delete:
            dcond = matched if self._delete_cond is None \
                else (matched & self._delete_cond)
            keep = ~dcond
        out = j.filter(keep) if self._delete else j

        # WHEN MATCHED UPDATE: conditional projections per target column
        projs = []
        for n in tnames:
            e = col(n)
            if self._update is not None and n in self._update:
                ucond = matched if self._update_cond is None \
                    else (matched & self._update_cond)
                e = E.If(ucond, self._update[n].cast(
                    self.target.plan.schema.fields[
                        self.target.plan.schema.index_of(n)].dtype), col(n))
            projs.append(e.alias(n))
        merged_target = out.select(*projs)

        if self._insert is None:
            return merged_target

        # WHEN NOT MATCHED INSERT: source anti-join target on keys
        anti = self.source.join(
            self.target.select(*[col(k) for k in self.on]).distinct(),
            on=self.on, how="left_anti")
        if self._insert_cond is not None:
            anti = anti.filter(self._insert_cond)
        snames = set(self.source.plan.schema.names)
        ins = []
        for f in self.target.plan.schema.fields:
            if f.name in self._insert:
                ins.append(self._insert[f.name].cast(f.dtype).alias(f.name))
            elif f.name in snames:
                ins.append(col(f.name).cast(f.dtype).alias(f.name))
            else:
                ins.append(lit(None).cast(f.dtype).alias(f.name))
        inserts = anti.select(*ins)
        return merged_target.union(inserts)

    def execute_to(self, path: str, partition_by=None, mode: str = "overwrite"):
        """Run the merge and write the merged table back (hive-partitioned
        when partition_by is given) — the write-back half of
        GpuMergeIntoCommand."""
        w = self.result().write.mode(mode)
        if partition_by:
            w = w.partition_by(partition_by)
        w.parquet(path)


def _e(x):
    return x if isinstance(x, E.Expression) else lit(x)


def merge_into(target, source, on: List[str]) -> MergeInto:
    return MergeInto(target, source, on)
