"""DataFrame API over the plan algebra (the user surface a Spark user would
recognize; reference: the plugin is transparent to Spark's DataFrame API, so
this module plays PySpark's role in the standalone framework)."""
from __future__ import annotations

from typing import List, Optional, Union as U

from spark_rapids_tpu import config as C
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.aggregates import AggFunction, NamedAgg
from spark_rapids_tpu.plan import nodes as P
from spark_rapids_tpu import types as T


def _e(x):
    return x if isinstance(x, E.Expression) else (E.col(x) if isinstance(x, str) else E.lit(x))


class DataFrame:
    def __init__(self, plan: P.PlanNode, session):
        self.plan = plan
        self.session = session

    # -- transformations ---------------------------------------------------
    def _extract_windows(self, exprs):
        """Hoist WindowExprs out of a projection into WindowNode(s) below
        it (reference: Catalyst's ExtractWindowExpressions)."""
        from spark_rapids_tpu.expr import window as WE
        found = []

        def extract(e):
            def repl(node):
                if isinstance(node, WE.WindowExpr):
                    name = f"__w{len(found)}"
                    found.append((node, name))
                    return E.col(name)
                return node
            return e.transform(repl)

        new_exprs = []
        for e in exprs:
            if isinstance(e, WE.WindowExpr):
                name = f"__w{len(found)}"
                found.append((e, name))
                new_exprs.append(E.Alias(E.col(name),
                                         type(e.fn).__name__.lower()))
            else:
                new_exprs.append(extract(e))
        if not found:
            return exprs, self.plan
        # group by spec so each WindowNode sorts once
        plan = self.plan
        groups = {}
        for w, name in found:
            groups.setdefault(w.spec.fingerprint(), []).append((w, name))
        for items in groups.values():
            plan = P.WindowNode([w for w, _ in items],
                                [n for _, n in items], plan)
        return new_exprs, plan

    def select(self, *exprs) -> "DataFrame":
        es = [_e(x) for x in exprs]
        from spark_rapids_tpu.expr import window as WE
        from spark_rapids_tpu.expr import complex as CX

        stacks = [(i, e) for i, e in enumerate(es)
                  if isinstance(e, CX.Stack)
                  or (isinstance(e, E.Alias)
                      and isinstance(e.children[0], CX.Stack))]
        if stacks:
            if len(stacks) > 1:
                raise E.SparkException(
                    "only one generator allowed per select clause")
            i, se = stacks[0]
            alias = se.name if isinstance(se, E.Alias) else None
            st = se.children[0] if isinstance(se, E.Alias) else se
            st = CX.Stack(st.n, *[P.bind_expr(c, self.plan.schema)
                                  for c in st.children])
            names = [n for n, _ in st.output_fields()]
            if alias is not None:
                if len(names) != 1:
                    raise E.SparkException(
                        "stack() alias needs a single output column, "
                        f"got {len(names)}")
                names = [alias]

            def _plain(e):
                if isinstance(e, (WE.WindowExpr, CX.Explode, CX.Stack)):
                    return False
                return all(_plain(c) for c in e.children)

            if all(_plain(e) for e in es[:i] + es[i + 1:]):
                # one-pass lowering onto the Expand node (multiple
                # projections per input row, like the ROLLUP rewrite)
                out_names = ([P.expr_name(e, j)
                              for j, e in enumerate(es[:i])]
                             + names
                             + [P.expr_name(e, i + 1 + j)
                                for j, e in enumerate(es[i + 1:])])
                projections = [es[:i] + row + es[i + 1:]
                               for row in st.row_exprs()]
                return DataFrame(P.Expand(projections, out_names,
                                          self.plan), self.session)
            # other items carry window/explode markers that need their
            # own lowering: fall back to one select per stack row
            out = None
            for row in st.row_exprs():
                es_r = (es[:i]
                        + [E.Alias(c, n) for c, n in zip(row, names)]
                        + es[i + 1:])
                part = self.select(*es_r)
                out = part if out is None else out.union(part)
            return out

        gens = [(i, e) for i, e in enumerate(es)
                if isinstance(e, CX.Explode)
                or (isinstance(e, E.Alias) and isinstance(e.children[0],
                                                          CX.Explode))]
        if gens:
            if len(gens) > 1:
                raise E.SparkException(
                    "only one generator allowed per select clause")
            i, ge = gens[0]
            alias = ge.name if isinstance(ge, E.Alias) else None
            gen = ge.children[0] if isinstance(ge, E.Alias) else ge
            gen = type(gen)(P.bind_expr(gen.children[0], self.plan.schema))
            fields = gen.output_fields(alias)
            names = [n for n, _ in fields]
            new_exprs = es[:i] + [E.col(n) for n in names] + es[i + 1:]
            # requiredChildOutput: only child columns the projection uses
            # ride through the row-duplicating generate
            refs = set()
            for e in new_exprs:
                refs |= {r.lower() for r in e.references()}
            required = [j for j, f in enumerate(self.plan.schema.fields)
                        if f.name.lower() in refs]
            gplan = P.Generate(gen, names, self.plan, required=required)
            return DataFrame(P.Project(new_exprs, gplan), self.session)

        def has_window(e):
            if isinstance(e, WE.WindowExpr):
                return True
            return any(has_window(c) for c in e.children)

        if any(has_window(e) for e in es):
            new_es, plan = self._extract_windows(es)
            return DataFrame(P.Project(new_es, plan), self.session)
        return self._select_plain(*exprs)

    def _select_plain(self, *exprs) -> "DataFrame":
        bound = [_e(x) for x in exprs]
        return DataFrame(P.Project(bound, self.plan), self.session)

    def with_column(self, name: str, expr) -> "DataFrame":
        # case-insensitive replace, like Spark's default resolver
        existing = [E.col(n) for n in self.plan.schema.names
                    if n.lower() != name.lower()]
        return self.select(*existing, _e(expr).alias(name))

    def filter(self, condition) -> "DataFrame":
        return DataFrame(P.Filter(_e(condition), self.plan), self.session)

    where = filter

    def group_by(self, *keys) -> "GroupedData":
        return GroupedData([_e(k) for k in keys], self)

    groupBy = group_by

    def rollup(self, *keys) -> "GroupedData":
        """Hierarchical grouping sets (full, drop-last, ..., grand
        total) lowered onto Expand (reference GpuExpandExec — the
        Catalyst ROLLUP rewrite done in-engine)."""
        ks = [_e(k) for k in keys]
        sets = [tuple(range(i)) for i in range(len(ks), -1, -1)]
        return GroupedData(ks, self, grouping_sets=sets)

    def cube(self, *keys) -> "GroupedData":
        """All 2^n grouping-set combinations, lowered onto Expand."""
        ks = [_e(k) for k in keys]
        n = len(ks)
        sets = [tuple(j for j in range(n) if not (m >> (n - 1 - j)) & 1)
                for m in range(1 << n)]
        return GroupedData(ks, self, grouping_sets=sets)

    def grouping_sets(self, sets, *keys) -> "GroupedData":
        """Explicit GROUPING SETS: `sets` is a list of key-index tuples
        (or key-name/expr lists matched against `keys`)."""
        ks = [_e(k) for k in keys]
        fps = [k.fingerprint() for k in ks]
        norm = []
        for s in sets:
            idx = []
            for item in s:
                if isinstance(item, int):
                    idx.append(item)
                else:
                    fp = _e(item).fingerprint()
                    if fp not in fps:
                        raise E.SparkException(
                            f"GROUPING SETS item {item!r} is not a "
                            "group-by key")
                    idx.append(fps.index(fp))
            norm.append(tuple(idx))
        return GroupedData(ks, self, grouping_sets=norm)

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData([], self).agg(*aggs)

    def order_by(self, *orders) -> "DataFrame":
        os = []
        for o in orders:
            if isinstance(o, P.SortOrder):
                os.append(o)
            else:
                os.append(P.SortOrder(_e(o)))
        return DataFrame(P.Sort(os, self.plan), self.session)

    orderBy = sort = order_by

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(P.Limit(n, self.plan), self.session)

    def repartition(self, n: int, *cols) -> "DataFrame":
        """Explicit exchange: hash-partition by `cols` into n partitions,
        round-robin when no columns are given (Spark's repartition)."""
        keys = [_e(c) for c in cols]
        return DataFrame(P.Repartition(n, keys, self.plan), self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(P.Union([self.plan, other.plan]), self.session)

    unionAll = union

    def to_device_batches(self):
        """Zero-copy ML handoff: execute the plan and return the raw
        device-resident ColumnarBatches (flat list, partition order;
        reference ColumnarRdd / InternalColumnarRddConverter — the
        XGBoost-style hand-off of device tables without a host round
        trip). The arrays inside are jax Arrays usable directly in
        downstream jax/flax code."""
        from spark_rapids_tpu.ops.kernels import compact_batch
        exec_root, _ = self.session.prepare_execution(self.plan)
        return self.session.run_partitions(exec_root, compact_batch)

    @property
    def write(self):
        """df.write.mode(...).partition_by(...).parquet(path)."""
        from spark_rapids_tpu.io.writer import DataFrameWriter
        return DataFrameWriter(self)

    def cache(self) -> "DataFrame":
        """Pin this DataFrame's result in device HBM; repeated queries over
        it skip the scan + upload entirely."""
        return DataFrame(P.CachedRelation(self.plan), self.session)

    persist = cache

    def distinct(self) -> "DataFrame":
        keys = [E.col(n) for n in self.plan.schema.names]
        return DataFrame(P.Aggregate(keys, [], self.plan), self.session)

    def drop_duplicates(self, subset: Optional[List[str]] = None
                        ) -> "DataFrame":
        """dropDuplicates: with a subset, keep one arbitrary row per key
        (Spark keeps the partition-order first; both are 'some row')."""
        if not subset:
            return self.distinct()
        # row_number over the key keeps one WHOLE input row per key
        # (a first() per remaining column would stitch cells from
        # different rows when the earliest value is null)
        from spark_rapids_tpu.sql import functions as F
        from spark_rapids_tpu.expr import window as WE
        spec = WE.Window.partition_by(*[E.col(s) for s in subset]) \
            .order_by(E.lit(1))
        marked = self.select(*[E.col(n) for n in self.plan.schema.names],
                             F.row_number().over(spec).alias("__rn"))
        return (marked.filter(E.col("__rn") == E.lit(1))
                .select(*[E.col(n) for n in self.plan.schema.names]))

    dropDuplicates = drop_duplicates

    def dropna(self, how: str = "any", thresh: Optional[int] = None,
               subset: Optional[List[str]] = None) -> "DataFrame":
        """DataFrameNaFunctions.drop: keep rows with enough non-null
        cells (thresh wins over how; how='any' means all cells non-null,
        'all' means at least one — Spark's AtLeastNNonNulls filter)."""
        if how not in ("any", "all"):
            raise ValueError(f"how must be 'any' or 'all', got {how!r}")
        names = subset or list(self.plan.schema.names)
        if thresh is None:
            thresh = len(names) if how == "any" else 1
        # Catalyst's predicate (NaN counts as missing, like Spark)
        return self.filter(E.AtLeastNNonNulls(
            int(thresh), *[E.col(n) for n in names]))

    def fillna(self, value, subset: Optional[List[str]] = None
               ) -> "DataFrame":
        """DataFrameNaFunctions.fill: replace nulls in TYPE-COMPATIBLE
        columns (numeric value fills numeric columns, string fills
        string — Spark's rule), others pass through untouched."""
        names = {s.lower() for s in subset} if subset else None
        out = []
        for f in self.plan.schema.fields:
            compat = (f.dtype.is_numeric
                      if isinstance(value, (int, float))
                      and not isinstance(value, bool)
                      else isinstance(f.dtype, type(E.lit(value).dtype)))
            if (names is None or f.name.lower() in names) and compat:
                # cast the fill to the COLUMN type (Spark truncates
                # 0.5 -> 0 for an int column and keeps the dtype)
                out.append(E.Alias(
                    E.Coalesce(E.col(f.name),
                               E.Cast(E.lit(value), f.dtype)), f.name))
            else:
                out.append(E.col(f.name))
        return self.select(*out)

    def join(self, other: "DataFrame", on=None, how: str = "inner") -> "DataFrame":
        how = {"leftsemi": "left_semi", "semi": "left_semi",
               "leftanti": "left_anti", "anti": "left_anti",
               "outer": "full", "fullouter": "full", "left_outer": "left",
               "right_outer": "right"}.get(how, how)
        if how == "cross" or on is None:
            return DataFrame(P.Join(self.plan, other.plan, [], [], "cross"),
                             self.session)
        if isinstance(on, E.Expression):
            # non-equi join on an arbitrary condition (binds against the
            # concatenated left+right schema) -> nested-loop join
            return DataFrame(P.Join(self.plan, other.plan, [], [], how,
                                    condition=on), self.session)
        if isinstance(on, str):
            on = [on]
        dedupe_names = None
        if isinstance(on, (list, tuple)) and on and isinstance(on[0], str):
            lk = [E.col(k) for k in on]
            rk = [E.col(k) for k in on]
            dedupe_names = list(on)
        elif isinstance(on, (list, tuple)):
            lk, rk = zip(*on)
            lk, rk = list(lk), list(rk)
        else:
            raise TypeError("join on= must be column name(s) or (left, right) pairs")
        joined = DataFrame(P.Join(self.plan, other.plan, lk, rk, how), self.session)
        if dedupe_names and how not in ("left_semi", "left_anti"):
            # PySpark semantics: a single key column in the output. For right
            # joins the surviving values come from the right side.
            nleft = len(self.plan.schema)
            out = []
            lowered = {n.lower() for n in dedupe_names}
            for i, f in enumerate(joined.plan.schema.fields):
                if i >= nleft and f.name.lower() in lowered:
                    continue  # drop right-side key duplicate
                ref = E.BoundRef(i, f.dtype, f.name)
                if i < nleft and f.name.lower() in lowered and how in ("right", "full"):
                    # take the non-null side for the key
                    ridx = nleft + _index_of(joined.plan.schema.names[nleft:], f.name)
                    rref = E.BoundRef(ridx, joined.plan.schema.fields[ridx].dtype, f.name)
                    out.append(E.Coalesce(ref, rref).alias(f.name))
                else:
                    out.append(ref.alias(f.name))
            joined = DataFrame(P.Project(out, joined.plan), joined.session)
        return joined

    # -- actions -----------------------------------------------------------
    @property
    def schema(self):
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return self.plan.schema.names

    # -- pyspark convenience surface ---------------------------------------

    def drop(self, *cols) -> "DataFrame":
        """Drop columns by name (unknown names are ignored, like
        pyspark)."""
        gone = {(c if isinstance(c, str) else c.name).lower()
                for c in cols}
        keep = [E.col(n) for n in self.plan.schema.names
                if n.lower() not in gone]
        if not keep:
            raise E.SparkException("drop() would remove every column")
        return self.select(*keep)

    def with_column_renamed(self, existing: str, new: str) -> "DataFrame":
        out = [E.Alias(E.col(n), new) if n.lower() == existing.lower()
               else E.col(n) for n in self.plan.schema.names]
        return self.select(*out)

    withColumnRenamed = with_column_renamed

    withColumn = with_column

    @property
    def dtypes(self):
        return [(f.name, repr(f.dtype)) for f in self.plan.schema.fields]

    def print_schema(self) -> None:
        print("root")
        for f in self.plan.schema.fields:
            null = "true" if f.nullable else "false"
            print(f" |-- {f.name}: {f.dtype!r} (nullable = {null})")

    printSchema = print_schema

    def show(self, n: int = 20, truncate=True) -> None:
        """Render the first n rows as pyspark's ASCII grid. truncate
        may be a bool (20-char default cut) or an int width."""
        tbl = self.limit(n + 1).collect()
        more = tbl.num_rows > n
        tbl = tbl.slice(0, n)
        names = list(self.plan.schema.names)
        if isinstance(truncate, bool):
            width = 20 if truncate else 0
        else:
            width = int(truncate)

        def cell(v):
            if v is None:
                s = "NULL"
            elif v is True:
                s = "true"
            elif v is False:
                s = "false"
            else:
                s = str(v)
            if width and len(s) > width:
                s = s[: max(width - 3, 0)] + "..."
            return s
        # positional column access: duplicate output names must each
        # show their own values
        cols = [tbl.column(i).to_pylist()
                for i in range(tbl.num_columns)]
        grid = [[cell(cols[i][r]) for i in range(len(names))]
                for r in range(tbl.num_rows)]
        widths = [max(len(c), *(len(g[i]) for g in grid)) if grid
                  else len(c) for i, c in enumerate(names)]
        sep = "+" + "+".join("-" * w for w in widths) + "+"
        print(sep)
        print("|" + "|".join(c.rjust(w)
                             for c, w in zip(names, widths)) + "|")
        print(sep)
        for g in grid:
            print("|" + "|".join(c.rjust(w)
                                 for c, w in zip(g, widths)) + "|")
        print(sep)
        if more:
            print(f"only showing top {n} rows")


    def head(self, n: Optional[int] = None):
        """pyspark surface: head() is one row (or None); head(n) — even
        head(1) — is a list."""
        rows = self.limit(n if n is not None else 1).collect().to_pylist()
        if n is None:
            return rows[0] if rows else None
        return rows

    def take(self, n: int):
        return self.limit(n).collect().to_pylist()

    def first(self):
        return self.head(1)

    def to_pandas(self):
        return self.collect().to_pandas()

    toPandas = to_pandas

    def sample(self, fraction: float, seed: int = 0,
               with_replacement: bool = False) -> "DataFrame":
        """Bernoulli row sample: rand(seed) < fraction per row, Spark's
        without-replacement sampler. With-replacement (Poisson counts)
        is not implemented."""
        if with_replacement:
            raise E.SparkException(
                "sample(withReplacement=True) is not implemented")
        from spark_rapids_tpu.expr.misc import Rand
        return self.filter(Rand(seed) < E.lit(float(fraction)))

    def random_split(self, weights: List[float], seed: int = 0
                     ) -> List["DataFrame"]:
        """Split by disjoint rand(seed) ranges proportional to weights
        (each split re-evaluates the same deterministic rand stream, so
        the splits partition the input exactly)."""
        from spark_rapids_tpu.expr.misc import Rand
        total = float(sum(weights))
        out, lo = [], 0.0
        for i, w in enumerate(weights):
            hi = 1.0 if i == len(weights) - 1 else lo + w / total
            r = Rand(seed)
            out.append(self.filter((r >= E.lit(lo)) & (r < E.lit(hi))))
            lo = hi
        return out

    randomSplit = random_split

    def _null_safe_on(self):
        """EXCEPT/INTERSECT compare NULL as equal to NULL: each column
        becomes an (is-null flag, null-coalesced value) key pair, which
        matches exactly when the null-safe equality would."""
        on = []
        for f in self.plan.schema.fields:
            c = E.col(f.name)
            flag = E.If(E.IsNull(c), E.lit(1), E.lit(0))
            default = E.lit("") if isinstance(f.dtype, T.StringType) \
                else E.Cast(E.lit(0), f.dtype)
            coal = E.Coalesce(c, default)
            on.append((flag, flag))
            on.append((coal, coal))
        return on

    def _align_positional(self, other: "DataFrame") -> "DataFrame":
        """EXCEPT/INTERSECT pair columns by POSITION (Spark): rename
        other's columns to self's names first."""
        mine = self.plan.schema.names
        theirs = other.plan.schema.names
        if len(mine) != len(theirs):
            raise E.SparkException(
                f"set operation needs the same number of columns: "
                f"{len(mine)} vs {len(theirs)}")
        return other.select(*[E.Alias(E.col(t), m)
                              for t, m in zip(theirs, mine)])

    def subtract(self, other: "DataFrame") -> "DataFrame":
        """EXCEPT DISTINCT: distinct rows of self absent from other."""
        return self.distinct().join(self._align_positional(other),
                                    on=self._null_safe_on(),
                                    how="left_anti")

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """INTERSECT DISTINCT."""
        return self.distinct().join(self._align_positional(other),
                                    on=self._null_safe_on(),
                                    how="left_semi")

    def describe(self, *cols) -> "DataFrame":
        """count/mean/stddev/min/max summary rows over numeric columns
        (string rendering like Spark's describe)."""
        from spark_rapids_tpu.sql import functions as F
        import pyarrow as pa
        fields = {f.name: f for f in self.plan.schema.fields}
        names = list(cols) or [f.name for f in self.plan.schema.fields
                               if f.dtype.is_numeric
                               or isinstance(f.dtype, T.StringType)]
        for n in names:
            if n not in fields:
                raise E.SparkException(f"describe: no column {n!r}")
            if n == "summary":
                raise E.SparkException(
                    "describe over a column named 'summary' is not "
                    "supported (it collides with the stat-label column)")
        stats = ["count", "mean", "stddev", "min", "max"]
        if not names:
            return self.session.create_dataframe(
                pa.table({"summary": stats}))
        aggs = []
        for n in names:
            numeric = fields[n].dtype.is_numeric
            aggs += [NamedAgg(F.count(E.col(n)), f"__cnt_{n}"),
                     NamedAgg(F.min(E.col(n)), f"__min_{n}"),
                     NamedAgg(F.max(E.col(n)), f"__max_{n}")]
            if numeric:  # Spark: strings get count/min/max only
                aggs += [NamedAgg(F.avg(E.col(n)), f"__avg_{n}"),
                         NamedAgg(F.stddev(E.col(n)), f"__std_{n}")]
        row = self.agg(*aggs).collect().to_pylist()[0]

        def fmt(v):
            return None if v is None else str(v)
        data = {"summary": stats}
        for n in names:
            data[n] = [fmt(row.get(f"__{k}_{n}"))
                       for k in ("cnt", "avg", "std", "min", "max")]
        return self.session.create_dataframe(pa.table(data))

    def corr(self, c1: str, c2: str) -> float:
        """Pearson correlation (df.stat.corr)."""
        import math
        m = self._moments(c1, c2)
        # E[x^2]-mean^2 can round a hair negative for constant columns
        den = math.sqrt(max(m["vx"], 0.0) * max(m["vy"], 0.0))
        return float("nan") if den == 0 else m["cov"] / den

    def cov(self, c1: str, c2: str) -> float:
        """Sample covariance (df.stat.cov, n-1 denominator)."""
        m = self._moments(c1, c2)
        n = m["n"]
        return 0.0 if n < 2 else m["cov_sum"] / (n - 1)

    def _moments(self, c1: str, c2: str):
        from spark_rapids_tpu.sql import functions as F
        # pairwise-complete rows only (Spark's covar_samp/corr): gate
        # BOTH columns on both being non-null
        both = E.IsNotNull(E.col(c1)) & E.IsNotNull(E.col(c2))
        fx = self.plan.schema.fields[
            [f.name for f in self.plan.schema.fields].index(c1)]
        x = E.If(both, E.col(c1), E.Literal(None, fx.dtype))
        fy = self.plan.schema.fields[
            [f.name for f in self.plan.schema.fields].index(c2)]
        y = E.If(both, E.col(c2), E.Literal(None, fy.dtype))
        row = self.agg(
            NamedAgg(F.count(x), "n"), NamedAgg(F.sum(x), "sx"),
            NamedAgg(F.sum(y), "sy"), NamedAgg(F.sum(x * y), "sxy"),
            NamedAgg(F.sum(x * x), "sxx"),
            NamedAgg(F.sum(y * y), "syy")).collect().to_pylist()[0]
        n = row["n"] or 0
        if n == 0:
            return {"n": 0, "cov": 0.0, "cov_sum": 0.0, "vx": 0.0,
                    "vy": 0.0}
        sx, sy = float(row["sx"]), float(row["sy"])
        cov_sum = float(row["sxy"]) - sx * sy / n
        return {"n": n, "cov_sum": cov_sum, "cov": cov_sum / n,
                "vx": float(row["sxx"]) / n - (sx / n) ** 2,
                "vy": float(row["syy"]) / n - (sy / n) ** 2}

    def crosstab(self, c1: str, c2: str) -> "DataFrame":
        """Pairwise frequency table (df.stat.crosstab): one row per c1
        value, one column per c2 value, 0 for absent combos (Spark's
        crosstab fills 0, unlike pivot+count)."""
        from spark_rapids_tpu.sql import functions as F
        # reserved key name so a c2 VALUE equal to the c1 column name
        # cannot collide with the key column in the pivot output
        key = "__crosstab_key"
        piv = (self.select(E.Alias(E.col(c1), key), E.col(c2))
               .group_by(E.col(key)).pivot(E.col(c2)).agg(F.count()))
        out = []
        for n in piv.plan.schema.names:
            if n == key:
                out.append(E.Alias(E.col(n), f"{c1}_{c2}"))
            else:
                out.append(E.Alias(
                    E.Coalesce(E.col(n), E.lit(0)), n))
        return piv.select(*out)

    def approx_quantile(self, col_name: str, probabilities: List[float],
                        relative_error: float = 1e-4):
        """df.stat.approxQuantile over one column: one engine pass
        collects the non-null values, then every probability reads the
        same sorted array (Spark's rank interpolation; exact, which
        approxQuantile permits for any relative_error)."""
        import numpy as np
        tbl = (self.select(E.col(col_name)).dropna().collect()
               .column(0).to_numpy(zero_copy_only=False))
        if tbl.size == 0:
            return [float("nan")] * len(probabilities)
        return [float(np.quantile(tbl, p)) for p in probabilities]

    approxQuantile = approx_quantile

    def collect(self, timeout_seconds=None):
        """Execute with the TPU engine (per-op CPU fallback as tagged).
        `timeout_seconds` overrides spark.rapids.query.timeoutSeconds
        for THIS action: past the deadline the query's cancel token
        fires and the action raises QueryCancelledError(reason=
        'deadline') at its next cooperative checkpoint."""
        return self.session.collect(self.plan,
                                    timeout_seconds=timeout_seconds)

    def collect_cpu(self):
        """Execute entirely on the CPU reference backend."""
        from spark_rapids_tpu.exec.cpu_backend import execute_cpu
        return execute_cpu(self.plan, ansi=self.session.conf.get(C.ANSI_ENABLED))

    def to_pydict(self):
        return self.collect().to_pydict()

    def count(self) -> int:
        # aggregate ENGINE-side (Spark semantics): collecting the full
        # result to count it would ship every row across the host link
        from spark_rapids_tpu.expr.aggregates import CountAll, NamedAgg
        plan = P.Aggregate([], [NamedAgg(CountAll(), "count")], self.plan)
        out = DataFrame(plan, self.session).collect()
        return int(out.column(0)[0].as_py())

    def explain(self, mode: str = "placement") -> str:
        """'placement' (default): the tagging report — every operator with
        its TPU/CPU placement and fallback reasons. 'stages': the physical
        exec tree after whole-stage vertical fusion, with fusion groups
        annotated `*(N)` the way Spark prints whole-stage-codegen ids.
        'analyze': EXECUTE the query, then print the physical tree
        annotated with the actual rows/batches/dispatches/time each exec
        recorded (Spark's EXPLAIN ANALYZE / the SQL tab's live metric
        annotations) — a slow query is diagnosable from its own run,
        without re-running it under the tracer."""
        if mode == "analyze":
            self.collect()
            s = self.session.explain_analyze()
        elif mode == "stages":
            # build the exec tree WITHOUT convert_plan's action-time side
            # effects (LORE dumper install would overwrite recordings;
            # test-mode fallback assertions would raise instead of print)
            from spark_rapids_tpu.exec.stage_fusion import fuse_stages
            from spark_rapids_tpu.plan.cost import apply_cost_optimizer
            from spark_rapids_tpu.plan.overrides import wrap_and_tag
            from spark_rapids_tpu.plan.prune import prune_plan
            conf = self.session.conf
            meta = wrap_and_tag(prune_plan(self.plan), conf)
            apply_cost_optimizer(meta, conf)
            s = fuse_stages(meta.convert(), conf).tree_string()
        else:
            from spark_rapids_tpu.plan.overrides import explain_plan
            s = explain_plan(self.plan, self.session.conf, all_ops=True)
        print(s)
        return s

    def __repr__(self):
        return f"DataFrame[{self.plan.schema!r}]"


class GroupedData:
    def __init__(self, keys: List[E.Expression], df: DataFrame,
                 grouping_sets=None):
        self.keys = keys
        self.df = df
        #: list of tuples of key indices INCLUDED per grouping set
        self.grouping_sets = grouping_sets

    def agg(self, *aggs) -> DataFrame:
        named: List[NamedAgg] = []
        for i, a in enumerate(aggs):
            if isinstance(a, NamedAgg):
                named.append(a)
            elif isinstance(a, AggFunction):
                named.append(NamedAgg(a, _default_agg_name(a, i)))
            else:
                raise TypeError(f"not an aggregate: {a!r}")
        if self.grouping_sets is not None:
            return self._agg_grouping_sets(named)
        return DataFrame(P.Aggregate(self.keys, named, self.df.plan),
                         self.df.session)

    def _agg_grouping_sets(self, named: List[NamedAgg]) -> DataFrame:
        """ROLLUP/CUBE/GROUPING SETS lowering (the Catalyst Expand
        rewrite, reference GpuExpandExec consumes its output): replicate
        each row once per grouping set with excluded keys nulled and a
        __grouping_id bitmask key, aggregate over keys + id, then
        resolve grouping()/grouping_id() markers to bit reads of the
        id and drop it from the output."""
        from spark_rapids_tpu.expr.aggregates import (Grouping,
                                                      GroupingMarker,
                                                      GroupingID)
        df, keys, sets = self.df, self.keys, self.grouping_sets
        nk = len(keys)
        src = df.columns
        gk = [f"__gkey{j}" for j in range(nk)]
        pre = df.select(*[E.col(n) for n in src],
                        *[E.Alias(k, gk[j]) for j, k in enumerate(keys)])
        ktypes = {f.name: f.dtype for f in pre.schema.fields}
        projections, names = [], src + gk + ["__grouping_id"]
        for s in sets:
            gid = 0
            row: List[E.Expression] = [E.col(n) for n in src]
            for j in range(nk):
                if j in s:
                    row.append(E.col(gk[j]))
                else:
                    # typed null (NOT a cast-from-null: Literal evals
                    # natively on device for every type incl. strings)
                    row.append(E.Literal(None, ktypes[gk[j]]))
                    gid |= 1 << (nk - 1 - j)
            row.append(E.Cast(E.lit(gid), T.INT64))
            projections.append(row)
        expanded = DataFrame(P.Expand(projections, names, pre.plan),
                             df.session)

        key_fps = [k.fingerprint() for k in keys]

        def marker_expr(fn: GroupingMarker) -> E.Expression:
            from spark_rapids_tpu.expr.math import BitwiseAnd, ShiftRight
            if isinstance(fn, GroupingID):
                return E.col("__grouping_id")
            child = fn.children[0]
            fp = child.fingerprint()
            if fp in key_fps:
                j = key_fps.index(fp)
            elif isinstance(child, E.Col) and child.name in gk:
                j = gk.index(child.name)
            else:
                raise E.SparkException(
                    f"grouping() argument {child!r} is not a "
                    "group-by key")
            return E.Cast(BitwiseAnd(
                ShiftRight(E.col("__grouping_id"),
                           E.Cast(E.lit(nk - 1 - j), T.INT32)),
                E.Cast(E.lit(1), T.INT64)), T.INT8)

        real, post = [], []
        for na in named:
            if isinstance(na.fn, GroupingMarker):
                post.append(E.Alias(marker_expr(na.fn), na.name))
            else:
                real.append(na)
                post.append(E.col(na.name))
        grouped = DataFrame(
            P.Aggregate([E.col(n) for n in gk] + [E.col("__grouping_id")],
                        real, expanded.plan), df.session)
        out_keys = [E.Alias(E.col(gk[j]), P.expr_name(keys[j], j))
                    for j in range(nk)]
        return grouped.select(*out_keys, *post)

    def count(self) -> DataFrame:
        from spark_rapids_tpu.expr.aggregates import CountAll
        return self.agg(NamedAgg(CountAll(), "count"))

    def pivot(self, pivot_col, values=None) -> "PivotedData":
        """Spark GroupedData.pivot. The engine lowers a pivot to
        conditional aggregation — one `agg(if(pivot = v, child, null))`
        per value — rather than a row-shuffling pivot kernel (the
        reference lowers to GpuPivotFirst, GpuOverrides.scala expr
        [PivotFirst], which is the same gather-by-value idea on GPU).
        With no explicit values the distinct set is computed eagerly,
        like Spark, capped at 10000."""
        pc = _e(pivot_col)
        if values is None:
            rows = (self.df.select(pc.alias("__pv")).distinct()
                    .limit(10_001).collect().column("__pv").to_pylist())
            if len(rows) > 10_000:
                raise E.SparkException(
                    "pivot: more than 10000 distinct values; pass an "
                    "explicit value list")
            # Spark keeps a NULL pivot value as its own column, sorted
            # first (ascending nulls-first collection order)
            values = sorted(rows, key=lambda v: (v is not None, v))
        return PivotedData(self.keys, self.df, pc, list(values))


class PivotedData:
    def __init__(self, keys, df: DataFrame, pivot_col, values):
        self.keys = keys
        self.df = df
        self.pivot_col = pivot_col
        self.values = values

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.expr.aggregates import CountAll, Count
        named = []
        for i, a in enumerate(aggs):
            if isinstance(a, NamedAgg):
                named.append((a.fn, a.name if len(aggs) > 1 else None))
            elif isinstance(a, AggFunction):
                named.append((a, _default_agg_name(a, i)
                              if len(aggs) > 1 else None))
            else:
                raise TypeError(f"not an aggregate: {a!r}")
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.expr.aggregates import Max
        schema = self.df.plan.schema
        out = []
        post = {}   # count column -> presence-marker column
        for vi, v in enumerate(self.values):
            pc = P.bind_expr(self.pivot_col, schema)
            # a NULL pivot value needs null-safe matching
            cond = E.IsNull(pc) if v is None else pc == E.lit(v)
            marker = None
            if any(isinstance(a, (CountAll, Count)) for a, _ in named):
                # Spark's pivot leaves counts NULL (not 0) for combos
                # with no matching rows; a presence marker separates
                # "no rows" from "rows whose counted value is null"
                marker = f"__present{vi}"
                out.append(NamedAgg(
                    Max(E.If(cond, E.lit(1), E.Literal(None, T.INT32))),
                    marker))
            for a, suffix in named:
                if isinstance(a, CountAll):
                    cell = Count(E.If(cond, E.lit(1),
                                      E.Literal(None, T.INT32)))
                else:
                    # EVERY child is gated (min_by's ordering column
                    # must not see other pivot cells' rows)
                    import copy
                    gated = []
                    for ch in a.children:
                        ch = P.bind_expr(ch, schema)
                        gated.append(E.If(cond, ch,
                                          E.Literal(None, ch.data_type())))
                    cell = copy.copy(a)  # keeps extra params (e.g. p)
                    cell.children = gated
                vs = "null" if v is None else str(v)
                name = vs if suffix is None else f"{vs}_{suffix}"
                if isinstance(a, (CountAll, Count)):
                    post[name] = marker
                out.append(NamedAgg(cell, name))
        agged = DataFrame(P.Aggregate(self.keys, out, self.df.plan),
                          self.df.session)
        finals = []
        for n in agged.plan.schema.names:
            if n.startswith("__present"):
                continue
            if n in post:
                finals.append(E.Alias(
                    E.If(E.IsNull(E.col(post[n])),
                         E.Literal(None, T.INT64), E.col(n)), n))
            else:
                finals.append(E.col(n))
        return agged.select(*finals) if post else agged


def _index_of(names: List[str], name: str) -> int:
    for i, n in enumerate(names):
        if n.lower() == name.lower():
            return i
    raise KeyError(name)


def _default_agg_name(a: AggFunction, i: int) -> str:
    base = type(a).__name__.lower()
    if a.children and isinstance(a.children[0], E.Col):
        return f"{base}({a.children[0].name})"
    return f"{base}_{i}"
