"""Hive table support: LazySimpleSerDe text tables + partition discovery.

Reference parity: org/apache/spark/sql/hive/rapids/ (GpuHiveTextFileFormat,
GpuHiveTableScanExec, the hive serde read/write family). The engine
analog reads and writes Hive's default delimited text layout:

- fields separated by ctrl-A (\\x01, configurable), rows by newline,
  ``\\N`` for NULL — LazySimpleSerDe's wire format;
- ``key=value`` partition directories discovered on read and written on
  insert (partition column values come from the directory, not the
  file);
- values parse by a declared schema with Hive's lax casting (bad cells
  become NULL, like LazySimpleSerDe).

Hive UDF bridges (GenericUDF over the JVM) are out of scope without a
JVM; the row-UDF tier plays that role (sql/udf.py).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional
from urllib.parse import quote, unquote

import pyarrow as pa


NULL_TOKEN = "\\N"
DEFAULT_DELIM = "\x01"


def _parse_cell(raw: str, dtype: pa.DataType):
    if raw == NULL_TOKEN:
        return None
    s = _unescape(raw)
    try:
        if pa.types.is_int64(dtype) or pa.types.is_int32(dtype):
            return int(s)
        if pa.types.is_floating(dtype):
            return float(s)
        if pa.types.is_boolean(dtype):
            low = s.lower()
            if low == "true":
                return True
            if low == "false":
                return False
            return None  # LazyBoolean: anything else is NULL
        return s
    except ValueError:
        return None  # LazySimpleSerDe: malformed cells read as NULL


def _escape(s: str, delim: str) -> str:
    """Backslash-escape the wire metacharacters (LazySimpleSerDe with an
    escape char): backslash itself, the field delimiter, and newlines."""
    return (s.replace("\\", "\\\\")
             .replace(delim, "\\" + delim)
             .replace("\n", "\\n"))


def _split_raw(line: str, delim: str) -> List[str]:
    """Split on UNESCAPED delimiters, keeping escape pairs verbatim —
    the \\N null token must be recognized on the RAW cell (a data string
    that unescapes to backslash-N is NOT null, exactly LazySimpleSerDe's
    distinction)."""
    out, cur, i = [], [], 0
    while i < len(line):
        ch = line[i]
        if ch == "\\" and i + 1 < len(line):
            cur.append(line[i: i + 2])
            i += 2
            continue
        if ch == delim:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    out.append("".join(cur))
    return out


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _render_cell(v, delim: str = DEFAULT_DELIM) -> str:
    if v is None:
        return NULL_TOKEN
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        return _escape(v, delim)
    return str(v)


class HiveTable:
    """Delimited-text Hive table over a directory tree."""

    def __init__(self, session, path: str, schema: pa.Schema,
                 partition_cols: Optional[List[str]] = None,
                 delimiter: str = DEFAULT_DELIM):
        self.session = session
        self.path = path
        self.schema = schema
        self.partition_cols = list(partition_cols or [])
        self.delimiter = delimiter
        self._data_fields = [f for f in schema
                             if f.name not in self.partition_cols]

    # -- read ---------------------------------------------------------------

    def _walk(self):
        """Yield (file_path, {partition_col: value_str}). Sibling codecs:
        session._discover_hive (parquet partition discovery) and
        io/writer._partition_dirs (partitioned writes) render/parse the
        same key=value layout — changes here likely apply there too."""
        for root, _dirs, files in os.walk(self.path):
            rel = os.path.relpath(root, self.path)
            parts: Dict[str, str] = {}
            ok = True
            if rel != ".":
                for seg in rel.split(os.sep):
                    if "=" not in seg:
                        ok = False
                        break
                    k, v = seg.split("=", 1)
                    parts[k] = unquote(v)
            if not ok:
                continue
            for name in sorted(files):
                if name.startswith(("_", ".")):
                    continue
                yield os.path.join(root, name), parts

    def to_df(self):
        cols: Dict[str, list] = {f.name: [] for f in self.schema}
        found = False
        for fp, parts in self._walk():
            with open(fp, encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    # a blank line IS a row (empty first cell, rest NULL)
                    found = True
                    cells = _split_raw(line, self.delimiter)
                    for i, fld in enumerate(self._data_fields):
                        raw = cells[i] if i < len(cells) else NULL_TOKEN
                        cols[fld.name].append(_parse_cell(raw, fld.type))
                    for pc in self.partition_cols:
                        pv = parts.get(pc)
                        pf = self.schema.field(pc)
                        cols[pc].append(
                            None if pv in (None,
                                           "__HIVE_DEFAULT_PARTITION__")
                            else _parse_cell(pv, pf.type))
        if not found:
            table = pa.table({f.name: pa.array([], f.type)
                              for f in self.schema})
        else:
            table = pa.table({f.name: pa.array(cols[f.name], f.type)
                              for f in self.schema})
        return self.session.create_dataframe(table)

    # -- write --------------------------------------------------------------

    def insert(self, df, overwrite: bool = False) -> int:
        """INSERT [OVERWRITE] with dynamic partitioning."""
        table = df.collect() if hasattr(df, "collect") else df
        if overwrite and os.path.isdir(self.path):
            import shutil
            shutil.rmtree(self.path)
        os.makedirs(self.path, exist_ok=True)
        import uuid
        rows = table.to_pylist()
        by_dir: Dict[str, list] = {}
        for r in rows:
            segs = []
            for pc in self.partition_cols:
                v = r.get(pc)
                segs.append(
                    f"{pc}=" + ("__HIVE_DEFAULT_PARTITION__" if v is None
                                else quote(_render_cell(v), safe="")))
            by_dir.setdefault("/".join(segs), []).append(r)
        for subdir, sub_rows in by_dir.items():
            d = os.path.join(self.path, subdir) if subdir else self.path
            os.makedirs(d, exist_ok=True)
            fp = os.path.join(d, f"part-{uuid.uuid4().hex[:12]}")
            with open(fp, "w", encoding="utf-8") as f:
                for r in sub_rows:
                    f.write(self.delimiter.join(
                        _render_cell(r.get(fld.name), self.delimiter)
                        for fld in self._data_fields))
                    f.write("\n")
        return len(rows)
