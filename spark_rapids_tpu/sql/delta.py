"""Delta Lake table format: transaction log, ACID commands, time travel.

Reference parity: /root/reference/delta-lake/ (GpuOptimisticTransaction,
GpuMergeIntoCommand, GpuDeleteCommand, GpuUpdateCommand — 40k LoC across
version shims). This module implements the open Delta PROTOCOL (v1
reader/writer: JSON commit files + parquet checkpoints + _last_checkpoint
pointer) over the native engine:

- every command (create/append/delete/update/merge) is an OPTIMISTIC
  TRANSACTION: data files are written first, then the commit file
  ``_delta_log/<version>.json`` is claimed with an exclusive create —
  a concurrent writer that claimed the version first wins and this
  commit raises ConcurrentModification (the GpuOptimisticTransaction
  retry seam).
- the log replays exactly like Delta's Snapshot: actions from the latest
  parquet checkpoint (if any) plus all later JSON commits, last-writer-
  wins per path; `remove` tombstones drop files.
- DELETE/UPDATE/MERGE follow the copy-on-write path (no deletion
  vectors): affected files are rewritten and swapped atomically in one
  commit — the same remove+add action shape the reference emits.
- compute runs on the TPU engine: the scan of live files feeds the
  normal DataFrame operators; the row-level commands build their
  keep/transform masks with fused device expressions.

Out of scope (documented): deletion vectors, column mapping,
generated columns, constraints — protocol features beyond
minReaderVersion=1/minWriterVersion=2.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.core import SparkException, col
from spark_rapids_tpu.io import read_parquet_file as _read_pq


class ConcurrentModification(SparkException):
    """Another writer claimed the commit version first."""


_LOG_DIR = "_delta_log"
_LAST_CHECKPOINT = "_last_checkpoint"
#: write a parquet checkpoint every N commits (delta default is 10)
CHECKPOINT_INTERVAL = 10


def _version_name(v: int) -> str:
    return f"{v:020d}.json"


def _checkpoint_name(v: int) -> str:
    return f"{v:020d}.checkpoint.parquet"


def _schema_string(schema: pa.Schema) -> str:
    """Delta metaData.schemaString (Spark StructType JSON)."""
    def field_json(f: pa.Field):
        t = f.type
        if pa.types.is_int64(t):
            sp = "long"
        elif pa.types.is_int32(t):
            sp = "integer"
        elif pa.types.is_float64(t):
            sp = "double"
        elif pa.types.is_float32(t):
            sp = "float"
        elif pa.types.is_boolean(t):
            sp = "boolean"
        elif pa.types.is_date32(t):
            sp = "date"
        elif pa.types.is_timestamp(t):
            sp = "timestamp"
        else:
            sp = "string"
        return {"name": f.name, "type": sp, "nullable": True,
                "metadata": {}}
    return json.dumps({"type": "struct",
                       "fields": [field_json(f) for f in schema]})


#: Delta spec checkpoint schema (the subset of action fields this writer
#: emits; struct columns, null when the row is a different action kind).
_MAP_SS = pa.map_(pa.string(), pa.string())
_CHECKPOINT_SCHEMA = pa.schema([
    ("protocol", pa.struct([("minReaderVersion", pa.int32()),
                            ("minWriterVersion", pa.int32())])),
    ("metaData", pa.struct([
        ("id", pa.string()), ("name", pa.string()),
        ("description", pa.string()),
        ("format", pa.struct([("provider", pa.string()),
                              ("options", _MAP_SS)])),
        ("schemaString", pa.string()),
        ("partitionColumns", pa.list_(pa.string())),
        ("configuration", _MAP_SS),
        ("createdTime", pa.int64())])),
    ("add", pa.struct([
        ("path", pa.string()), ("partitionValues", _MAP_SS),
        ("size", pa.int64()), ("modificationTime", pa.int64()),
        ("dataChange", pa.bool_()), ("stats", pa.string())])),
    ("remove", pa.struct([
        ("path", pa.string()), ("deletionTimestamp", pa.int64()),
        ("dataChange", pa.bool_())])),
])


def _typed_metadata(meta: dict) -> dict:
    """metaData action dict → checkpoint row (maps as key/value pairs)."""
    fmt = meta.get("format") or {}
    return {"id": meta.get("id"), "name": meta.get("name"),
            "description": meta.get("description"),
            "format": {"provider": fmt.get("provider", "parquet"),
                       "options": sorted((fmt.get("options") or {}).items())},
            "schemaString": meta.get("schemaString"),
            "partitionColumns": meta.get("partitionColumns") or [],
            "configuration": sorted((meta.get("configuration") or {}).items()),
            "createdTime": meta.get("createdTime")}


class DeltaLog:
    """Replay + commit machinery for one table directory."""

    def __init__(self, path: str):
        self.path = path
        self.log_path = os.path.join(path, _LOG_DIR)

    # -- replay ------------------------------------------------------------

    def _checkpoint_start(self):
        """(checkpoint_version, actions) from _last_checkpoint, or
        (-1, [])."""
        lc = os.path.join(self.log_path, _LAST_CHECKPOINT)
        if not os.path.isfile(lc):
            return -1, []
        with open(lc) as f:
            v = int(json.load(f)["version"])
        t = _read_pq(os.path.join(self.log_path, _checkpoint_name(v)))
        if "kind" in t.schema.names and "payload" in t.schema.names:
            # pre-round-5 checkpoint layout (kind + JSON payload columns)
            return v, [{row["kind"]: json.loads(row["payload"])}
                       for row in t.to_pylist()]
        actions = []
        for row in t.to_pylist():
            for kind in ("protocol", "metaData", "add", "remove"):
                a = row.get(kind)
                if a is not None:
                    if "partitionValues" in a:
                        a["partitionValues"] = dict(
                            a["partitionValues"] or [])
                    if kind == "metaData":
                        a["configuration"] = dict(a["configuration"] or [])
                        if a.get("format"):
                            a["format"]["options"] = dict(
                                a["format"]["options"] or [])
                    actions.append({kind: a})
        return v, actions

    def versions_on_disk(self) -> List[int]:
        if not os.path.isdir(self.log_path):
            return []
        out = []
        for name in os.listdir(self.log_path):
            if name.endswith(".json") and name[:20].isdigit():
                out.append(int(name[:20]))
        return sorted(out)

    def snapshot(self, version: Optional[int] = None) -> "Snapshot":
        """Replay the log to `version` (time travel) or to HEAD."""
        cp_v, actions = self._checkpoint_start()
        if version is not None and cp_v > version:
            cp_v, actions = -1, []  # checkpoint is past the asked version
        versions = [v for v in self.versions_on_disk() if v > cp_v
                    and (version is None or v <= version)]
        if cp_v < 0 and not versions:
            raise SparkException(f"{self.path} is not a Delta table")
        for v in versions:
            with open(os.path.join(self.log_path, _version_name(v))) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        actions.append(json.loads(line))
        live: Dict[str, dict] = {}
        meta = proto = None
        for a in actions:
            if "add" in a:
                live[a["add"]["path"]] = a["add"]
            elif "remove" in a:
                live.pop(a["remove"]["path"], None)
            elif "metaData" in a:
                meta = a["metaData"]
            elif "protocol" in a:
                proto = a["protocol"]
        head = versions[-1] if versions else cp_v
        return Snapshot(self, head, live, meta, proto)

    # -- commit ------------------------------------------------------------

    def commit(self, version: int, actions: List[dict], op: str) -> None:
        """Atomically claim `version` (exclusive create). Raises
        ConcurrentModification if a concurrent writer won."""
        os.makedirs(self.log_path, exist_ok=True)
        info = {"commitInfo": {
            "timestamp": int(time.time() * 1000), "operation": op,
            "engineInfo": "spark-rapids-tpu/0.1.0"}}
        payload = "\n".join(json.dumps(a) for a in [info] + actions) + "\n"
        target = os.path.join(self.log_path, _version_name(version))
        try:
            with open(target, "x") as f:
                f.write(payload)
        except FileExistsError:
            raise ConcurrentModification(
                f"version {version} of {self.path} was committed "
                f"concurrently") from None
        if version > 0 and version % CHECKPOINT_INTERVAL == 0:
            self._write_checkpoint(version)

    def _write_checkpoint(self, version: int) -> None:
        # One action per row in the Delta spec's typed checkpoint schema
        # (protocol / metaData / add struct columns, non-applicable
        # columns null) so external Delta readers that follow
        # _last_checkpoint can replay it.
        snap = self.snapshot(version)
        rows = [{"protocol": snap.protocol},
                {"metaData": _typed_metadata(snap.metadata)}]
        for add in snap.files.values():
            a = dict(add)
            a["partitionValues"] = sorted(
                (a.get("partitionValues") or {}).items())
            rows.append({"add": {k: a.get(k) for k in
                                 ("path", "partitionValues", "size",
                                  "modificationTime", "dataChange",
                                  "stats")}})
        pq.write_table(
            pa.Table.from_pylist(rows, schema=_CHECKPOINT_SCHEMA),
            os.path.join(self.log_path, _checkpoint_name(version)))
        with open(os.path.join(self.log_path, _LAST_CHECKPOINT), "w") as f:
            json.dump({"version": version, "size": len(rows)}, f)


class Snapshot:
    def __init__(self, log: DeltaLog, version: int, files: Dict[str, dict],
                 metadata, protocol):
        self.log = log
        self.version = version
        self.files = files
        self.metadata = metadata
        self.protocol = protocol

    def file_paths(self) -> List[str]:
        return [os.path.join(self.log.path, p) for p in sorted(self.files)]


class DeltaTable:
    """User-facing Delta table over the native engine (reference
    io.delta.tables.DeltaTable surface)."""

    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        self.log = DeltaLog(path)

    # -- creation ----------------------------------------------------------

    @staticmethod
    def create(session, path: str, df) -> "DeltaTable":
        """CREATE TABLE AS: write the DataFrame's rows as version 0."""
        t = DeltaTable(session, path)
        table = df.collect() if hasattr(df, "collect") else df
        os.makedirs(path, exist_ok=True)
        adds = t._write_files(table)
        meta = {"metaData": {
            "id": str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": _schema_string(table.schema),
            "partitionColumns": [], "configuration": {},
            "createdTime": int(time.time() * 1000)}}
        proto = {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}}
        t.log.commit(0, [proto, meta] + adds, "CREATE TABLE AS SELECT")
        return t

    @staticmethod
    def for_path(session, path: str) -> "DeltaTable":
        t = DeltaTable(session, path)
        t.log.snapshot()  # validates it IS a delta table
        return t

    def _write_files(self, table: pa.Table, max_rows: int = 1 << 20
                     ) -> List[dict]:
        adds = []
        for off in range(0, max(table.num_rows, 1), max_rows):
            part = table.slice(off, min(max_rows, table.num_rows - off))
            name = f"part-{uuid.uuid4().hex}.snappy.parquet"
            fp = os.path.join(self.path, name)
            pq.write_table(part, fp, compression="snappy")
            adds.append({"add": {
                "path": name, "partitionValues": {},
                "size": os.path.getsize(fp),
                "modificationTime": int(time.time() * 1000),
                "dataChange": True,
                "stats": json.dumps({"numRecords": part.num_rows})}})
            if table.num_rows == 0:
                break
        return adds

    # -- reads -------------------------------------------------------------

    def to_df(self, version: Optional[int] = None):
        snap = self.log.snapshot(version)
        paths = snap.file_paths()
        if not paths:
            schema = _schema_from_string(snap.metadata["schemaString"])
            return self.session.create_dataframe(schema.empty_table())
        table = pa.concat_tables([_read_pq(p) for p in paths])
        return self.session.create_dataframe(table)

    def history(self) -> List[dict]:
        out = []
        for v in reversed(self.log.versions_on_disk()):
            with open(os.path.join(self.log.log_path,
                                   _version_name(v))) as f:
                first = json.loads(f.readline())
            info = first.get("commitInfo", {})
            out.append({"version": v, "operation": info.get("operation"),
                        "timestamp": info.get("timestamp")})
        return out

    # -- transactional commands --------------------------------------------

    def append(self, df) -> None:
        table = df.collect() if hasattr(df, "collect") else df
        snap = self.log.snapshot()
        adds = self._write_files(table)
        self.log.commit(snap.version + 1, adds, "WRITE")

    def delete(self, condition: Optional[E.Expression] = None) -> int:
        """DELETE FROM: copy-on-write rewrite of files containing matches.
        Returns the number of deleted rows."""
        snap = self.log.snapshot()
        if condition is None:
            removes = self._removes(snap)
            n = sum(pq.ParquetFile(p).metadata.num_rows
                    for p in snap.file_paths())
            self.log.commit(snap.version + 1, removes, "DELETE")
            return n
        deleted = 0
        actions: List[dict] = []
        for rel, add in snap.files.items():
            fp = os.path.join(self.path, rel)
            table = _read_pq(fp)
            df = self.session.create_dataframe(table)
            # DELETE removes only rows where the condition is TRUE; rows
            # where it evaluates to NULL are kept (Spark DeleteCommand).
            pred = _as_pred(condition)
            kept = df.filter(pred.is_null() | ~pred).collect()
            if kept.num_rows == table.num_rows:
                continue  # file untouched
            deleted += table.num_rows - kept.num_rows
            actions.append(_remove_action(rel))
            if kept.num_rows:
                actions.extend(self._write_files(kept))
        if actions:
            self.log.commit(snap.version + 1, actions, "DELETE")
        return deleted

    def update(self, set_exprs: Dict[str, E.Expression],
               condition: Optional[E.Expression] = None) -> int:
        """UPDATE SET: rewrite affected files with conditional
        projections (fused device expressions). Returns updated rows."""
        snap = self.log.snapshot()
        updated = 0
        actions: List[dict] = []
        for rel, add in snap.files.items():
            fp = os.path.join(self.path, rel)
            table = _read_pq(fp)
            df = self.session.create_dataframe(table)
            pred = _as_pred(condition) if condition is not None else None
            if pred is not None:
                nmatch = df.filter(pred).count()
                if nmatch == 0:
                    continue
            else:
                nmatch = table.num_rows
                if nmatch == 0:
                    continue
            cols = []
            from spark_rapids_tpu.sql import functions as F
            for name in table.schema.names:
                if name in set_exprs:
                    newv = set_exprs[name]
                    if pred is not None:
                        newv = F.when(pred, newv).otherwise(col(name))
                    cols.append(newv.alias(name))
                else:
                    cols.append(col(name))
            rewritten = df.select(*cols).collect()
            updated += nmatch
            actions.append(_remove_action(rel))
            actions.extend(self._write_files(rewritten))
        if actions:
            self.log.commit(snap.version + 1, actions, "UPDATE")
        return updated

    def merge(self, source, on: List[str]) -> "DeltaMergeBuilder":
        return DeltaMergeBuilder(self, source, on)

    def checkpoint(self) -> None:
        self.log._write_checkpoint(self.log.snapshot().version)

    def vacuum(self, retain_hours: float = 168.0) -> List[str]:
        """Remove data files no longer referenced by the current
        snapshot (simplified: no tombstone retention window check against
        `remove` timestamps beyond the file mtime)."""
        snap = self.log.snapshot()
        live = set(snap.files)
        cutoff = time.time() - retain_hours * 3600
        dropped = []
        for name in os.listdir(self.path):
            if not name.endswith(".parquet") or name in live:
                continue
            fp = os.path.join(self.path, name)
            if os.path.getmtime(fp) < cutoff:
                os.unlink(fp)
                dropped.append(name)
        return dropped

    def _removes(self, snap: Snapshot) -> List[dict]:
        return [_remove_action(rel) for rel in snap.files]


def _remove_action(rel: str) -> dict:
    return {"remove": {"path": rel,
                       "deletionTimestamp": int(time.time() * 1000),
                       "dataChange": True}}


def _as_pred(e: E.Expression) -> E.Expression:
    return e


class DeltaMergeBuilder:
    """MERGE INTO committed as a Delta transaction: the in-memory merge
    (sql/merge.py device operators) computes the new table image; the
    commit swaps the whole matched file set atomically (coarse
    copy-on-write: source tables are small relative to targets in the
    upsert pattern this serves; file-pruned rewrite is a planned
    refinement)."""

    def __init__(self, table: DeltaTable, source, on: List[str]):
        from spark_rapids_tpu.sql.merge import MergeInto
        self.table = table
        snap = table.log.snapshot()
        self._snap = snap
        target_df = table.to_df()
        self._m = MergeInto(target_df, source, on)

    def when_matched_update(self, set_exprs, condition=None):
        self._m.when_matched_update(set_exprs, condition)
        return self

    def when_matched_delete(self, condition=None):
        self._m.when_matched_delete(condition)
        return self

    def when_not_matched_insert(self, values=None, condition=None):
        self._m.when_not_matched_insert(values, condition)
        return self

    def execute(self) -> None:
        merged = self._m.result().collect()
        actions = self.table._removes(self._snap)
        actions.extend(self.table._write_files(merged))
        self.table.log.commit(self._snap.version + 1, actions, "MERGE")


def _schema_from_string(s: str):
    """Minimal inverse of _schema_string for empty-table reads."""
    spec = json.loads(s)
    m = {"long": pa.int64(), "integer": pa.int32(), "double": pa.float64(),
         "float": pa.float32(), "boolean": pa.bool_(), "date": pa.date32(),
         "timestamp": pa.timestamp("us"), "string": pa.string()}

    class _S:
        def __init__(self, fields):
            self.fields = fields

        def empty_table(self):
            return pa.table({f["name"]: pa.array([], m.get(f["type"],
                                                           pa.string()))
                             for f in self.fields})

    return _S(spec["fields"])
