"""User-facing expression builders, mirroring pyspark.sql.functions for the
subset the engine implements (reference sql-plugin-api functions.scala df_udf
style surface)."""
from __future__ import annotations

from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr import aggregates as A
from spark_rapids_tpu.expr import datetime as DT
from spark_rapids_tpu.expr import math as MA
from spark_rapids_tpu.expr import strings as S

col = E.col
lit = E.lit


def _e(x):
    return x if isinstance(x, E.Expression) else (E.col(x) if isinstance(x, str) else E.lit(x))


# aggregates -----------------------------------------------------------------
def sum(c):  # noqa: A001
    return A.Sum(_e(c))


def grouping(c):
    """1 when the key is aggregated away in a ROLLUP/CUBE output row."""
    return A.Grouping(_e(c))


def grouping_id():
    """The grouping-set bitmask over the group-by keys."""
    return A.GroupingID()


def count(c="*"):
    # NB: Expression.__eq__ builds an EqualTo node (truthy), so the
    # "*" probe must be an isinstance check — `c == "*"` on a column
    # silently turned every count(expr) into count(*)
    if isinstance(c, str) and c == "*":
        return A.CountAll()
    return A.Count(_e(c))


def avg(c):
    return A.Average(_e(c))


mean = avg


def min(c):  # noqa: A001
    return A.Min(_e(c))


def max(c):  # noqa: A001
    return A.Max(_e(c))


def first(c):
    return A.First(_e(c))


def last(c):
    return A.Last(_e(c))


def collect_list(c):
    return A.CollectList(_e(c))


def collect_set(c):
    return A.CollectSet(_e(c))


def min_by(c, ord_c):
    return A.MinBy(_e(c), _e(ord_c))


def max_by(c, ord_c):
    return A.MaxBy(_e(c), _e(ord_c))


def percentile(c, p: float):
    return A.Percentile(_e(c), p)


def approx_percentile(c, p: float, accuracy: int = 10000):
    return A.ApproxPercentile(_e(c), p, accuracy)


percentile_approx = approx_percentile


def stddev(c):
    return A.StddevSamp(_e(c))


stddev_samp = stddev


def stddev_pop(c):
    return A.StddevPop(_e(c))


def variance(c):
    return A.VarianceSamp(_e(c))


var_samp = variance


def var_pop(c):
    return A.VariancePop(_e(c))


# scalar ---------------------------------------------------------------------
def row_number():
    from spark_rapids_tpu.expr.window import RowNumber
    return RowNumber()


def rank():
    from spark_rapids_tpu.expr.window import Rank
    return Rank()


def dense_rank():
    from spark_rapids_tpu.expr.window import DenseRank
    return DenseRank()


def ntile(n: int):
    from spark_rapids_tpu.expr.window import NTile
    return NTile(n)


def percent_rank():
    from spark_rapids_tpu.expr.window import PercentRank
    return PercentRank()


def cume_dist():
    from spark_rapids_tpu.expr.window import CumeDist
    return CumeDist()


def nth_value(c, n: int):
    from spark_rapids_tpu.expr.window import NthValue
    return NthValue(_e(c), n)


def first_value(c):
    from spark_rapids_tpu.expr.window import FirstValue
    return FirstValue(_e(c))


def last_value(c):
    from spark_rapids_tpu.expr.window import LastValue
    return LastValue(_e(c))


def lead(c, offset: int = 1, default=None):
    from spark_rapids_tpu.expr.window import Lead
    return Lead(_e(c), offset, default)


def lag(c, offset: int = 1, default=None):
    from spark_rapids_tpu.expr.window import Lag
    return Lag(_e(c), offset, default)


def trim(c):
    return S.Trim(_e(c))


def ltrim(c):
    return S.LTrim(_e(c))


def rtrim(c):
    return S.RTrim(_e(c))


def initcap(c):
    return S.InitCap(_e(c))


def ascii(c):  # noqa: A001
    return S.Ascii(_e(c))


def instr(c, substr: str):
    return S.InStr(_e(c), substr)


def locate(substr: str, c):
    return S.InStr(_e(c), substr)


def repeat(c, n: int):
    return S.StringRepeat(_e(c), n)


def quarter(c):
    return DT.Quarter(_e(c))


def dayofyear(c):
    return DT.DayOfYear(_e(c))


def weekofyear(c):
    return DT.WeekOfYear(_e(c))


def add_months(c, n):
    return DT.AddMonths(_e(c), _e(n))


def trunc(c, fmt: str):
    return DT.TruncDate(_e(c), fmt)


def unix_timestamp(c):
    return DT.UnixTimestampFromTs(_e(c))


def timestamp_seconds(c):
    return DT.TimestampSeconds(_e(c))


def bitwise_not(c):
    return MA.BitwiseNot(_e(c))


def shiftleft(c, n):
    return MA.ShiftLeft(_e(c), _e(n))


def shiftright(c, n):
    return MA.ShiftRight(_e(c), _e(n))


def shiftrightunsigned(c, n):
    return MA.ShiftRightUnsigned(_e(c), _e(n))


def rand(seed: int = 0):
    from spark_rapids_tpu.expr.misc import Rand
    return Rand(seed)


def sequence(start, stop, step=None):
    from spark_rapids_tpu.expr.misc import Sequence
    args = [_e(start), _e(stop)] + ([_e(step)] if step is not None else [])
    return Sequence(*args)


def parse_url(c, part: str, key: str = None):
    from spark_rapids_tpu.expr.misc import ParseUrl
    params = (part,) if key is None else (part, key)
    return ParseUrl(_e(c), params=params)


def raise_error(c):
    from spark_rapids_tpu.expr.misc import RaiseError
    return RaiseError(_e(c))


def hive_hash(*cs):
    from spark_rapids_tpu.expr.misc import HiveHash
    return HiveHash([_e(c) for c in cs])


def hash(*cs):  # noqa: A001
    return MA.Murmur3Hash(*[_e(c) for c in cs])


def spark_partition_id():
    return E.SparkPartitionID()


def monotonically_increasing_id():
    return E.MonotonicallyIncreasingID()


def reverse(c):
    from spark_rapids_tpu.expr.cpu_functions import Reverse
    return Reverse(_e(c))


def concat_ws(sep, *cs):
    from spark_rapids_tpu.expr.cpu_functions import ConcatWs
    return ConcatWs(*[_e(c) for c in cs], params=(sep,))


def lpad(c, ln, pad=" "):
    from spark_rapids_tpu.expr.cpu_functions import LPad
    return LPad(_e(c), params=(ln, pad))


def rpad(c, ln, pad=" "):
    from spark_rapids_tpu.expr.cpu_functions import RPad
    return RPad(_e(c), params=(ln, pad))


def translate(c, src, dst):
    from spark_rapids_tpu.expr.cpu_functions import Translate
    return Translate(_e(c), params=(src, dst))


def substring_index(c, delim, count):
    from spark_rapids_tpu.expr.cpu_functions import SubstringIndex
    return SubstringIndex(_e(c), params=(delim, count))


def md5(c):
    from spark_rapids_tpu.expr.cpu_functions import Md5
    return Md5(_e(c))


def sha2(c, bits=256):
    from spark_rapids_tpu.expr.cpu_functions import Sha2
    return Sha2(_e(c), params=(bits,))


def date_format(c, fmt):
    from spark_rapids_tpu.expr.cpu_functions import DateFormat
    return DateFormat(_e(c), params=(fmt,))


def to_date(c, fmt="yyyy-MM-dd"):
    from spark_rapids_tpu.expr.cpu_functions import ToDateFmt
    return ToDateFmt(_e(c), params=(fmt,))


def from_unixtime(c, fmt="yyyy-MM-dd HH:mm:ss"):
    from spark_rapids_tpu.expr.cpu_functions import FromUnixtime
    return FromUnixtime(_e(c), params=(fmt,))


def format_number(c, d):
    from spark_rapids_tpu.expr.cpu_functions import FormatNumber
    return FormatNumber(_e(c), params=(d,))


def explode(c):
    from spark_rapids_tpu.expr import complex as CX
    return CX.Explode(_e(c))


def explode_outer(c):
    from spark_rapids_tpu.expr import complex as CX
    return CX.ExplodeOuter(_e(c))


def posexplode(c):
    from spark_rapids_tpu.expr import complex as CX
    return CX.PosExplode(_e(c))


def posexplode_outer(c):
    from spark_rapids_tpu.expr import complex as CX
    return CX.PosExplodeOuter(_e(c))


def size(c):  # noqa: A001
    from spark_rapids_tpu.expr import complex as CX
    return CX.Size(_e(c))


def element_at(c, k):
    from spark_rapids_tpu.expr import complex as CX
    return CX.ElementAt(_e(c), _e(k) if isinstance(k, E.Expression) else E.lit(k))


def array(*cs):
    from spark_rapids_tpu.expr import complex as CX
    return CX.CreateArray([_e(c) for c in cs])


def array_contains(c, v):
    from spark_rapids_tpu.expr import complex as CX
    return CX.ArrayContains(_e(c), _e(v) if isinstance(v, E.Expression) else E.lit(v))


def map_keys(c):
    from spark_rapids_tpu.expr import complex as CX
    return CX.MapKeys(_e(c))


def map_values(c):
    from spark_rapids_tpu.expr import complex as CX
    return CX.MapValues(_e(c))


def get_json_object(c, path: str):
    from spark_rapids_tpu.expr import json_functions as JF
    return JF.GetJsonObject(_e(c), params=(path,))


def from_json(c, schema):
    from spark_rapids_tpu.expr import json_functions as JF
    return JF.JsonToStructs(_e(c), params=(schema,))


def nvl(c, default):
    return coalesce(c, default)


def nullif(a, b):
    from spark_rapids_tpu.expr.core import EqualTo, If, NullOf
    ea, eb = _e(a), _e(b)
    return If(EqualTo(ea, eb), NullOf(ea), ea)


def rlike(c, pattern: str):
    from spark_rapids_tpu.expr.strings import RLike
    return RLike(_e(c), pattern)


def regexp_extract(c, pattern: str, group: int = 1):
    from spark_rapids_tpu.expr.strings import RegexpExtract
    return RegexpExtract(_e(c), pattern, group)


def regexp_replace(c, pattern: str, replacement: str):
    from spark_rapids_tpu.expr.strings import RegexpReplace
    return RegexpReplace(_e(c), pattern, replacement)


def coalesce(*cs):
    return E.Coalesce(*[_e(c) for c in cs])


def when(cond, value):
    return _WhenBuilder([(cond, _e(value))])


class _WhenBuilder(E.Expression):
    def __init__(self, branches):
        self._branches = branches
        self.children = []

    def when(self, cond, value):
        return _WhenBuilder(self._branches + [(cond, _e(value))])

    def otherwise(self, value):
        return E.CaseWhen(self._branches, _e(value))

    def _as_case(self):
        return E.CaseWhen(self._branches)

    def data_type(self):
        return self._as_case().data_type()

    def transform(self, fn):
        return E.CaseWhen([(p.transform(fn), v.transform(fn))
                           for p, v in self._branches]).transform(fn)

    def eval_tpu(self, ctx):
        return self._as_case().eval_tpu(ctx)

    def eval_cpu(self, cols, ansi=False):
        return self._as_case().eval_cpu(cols, ansi)

    def fingerprint(self):
        return self._as_case().fingerprint()


def isnull(c):
    return E.IsNull(_e(c))


def isnan(c):
    return E.IsNaN(_e(c))


def abs(c):  # noqa: A001
    return E.Abs(_e(c))


def sqrt(c):
    return MA.Sqrt(_e(c))


def exp(c):
    return MA.Exp(_e(c))


def log(arg1, arg2=None):
    """log(col) is the natural log; log(base, col) is Logarithm."""
    if arg2 is None:
        return MA.Log(_e(arg1))
    return MA.Logarithm(_e(arg1), _e(arg2))


def log10(c):
    return MA.Log10(_e(c))


def log2(c):
    return MA.Log2(_e(c))


def sin(c):
    return MA.Sin(_e(c))


def cos(c):
    return MA.Cos(_e(c))


def tan(c):
    return MA.Tan(_e(c))


def ceil(c):
    return MA.Ceil(_e(c))


def floor(c):
    return MA.Floor(_e(c))


def pow(a, b):  # noqa: A001
    return MA.Pow(_e(a), _e(b))


def round(c, scale=0):  # noqa: A001
    return MA.Round(_e(c), scale)


def signum(c):
    return MA.Signum(_e(c))


def atan2(a, b):
    return MA.Atan2(_e(a), _e(b))


def greatest(*cs):
    return MA.Greatest(*[_e(c) for c in cs])


def least(*cs):
    return MA.Least(*[_e(c) for c in cs])


# strings --------------------------------------------------------------------
def length(c):
    return S.StringLength(_e(c))


def upper(c):
    return S.Upper(_e(c))


def lower(c):
    return S.Lower(_e(c))


def substring(c, pos, length_):
    return S.Substring(_e(c), pos, length_)


def concat(*cs):
    return S.ConcatStrings(*[_e(c) for c in cs])


def startswith(c, prefix):
    return S.StartsWith(_e(c), prefix)


def endswith(c, suffix):
    return S.EndsWith(_e(c), suffix)


def contains(c, s):
    return S.Contains(_e(c), s)


def like(c, pattern):
    return S.Like(_e(c), pattern)


# datetime -------------------------------------------------------------------
def year(c):
    return DT.Year(_e(c))


def month(c):
    return DT.Month(_e(c))


def dayofmonth(c):
    return DT.DayOfMonth(_e(c))


def hour(c):
    return DT.Hour(_e(c))


def minute(c):
    return DT.Minute(_e(c))


def second(c):
    return DT.Second(_e(c))


def dayofweek(c):
    return DT.DayOfWeek(_e(c))


def date_add(c, n):
    return DT.DateAdd(_e(c), _e(n))


def date_sub(c, n):
    return DT.DateSub(_e(c), _e(n))


def datediff(end, start):
    return DT.DateDiff(_e(end), _e(start))


def last_day(c):
    return DT.LastDay(_e(c))


# ---------------------------------------------------------------------------
# Higher-order functions (lambda expressions over arrays/maps)
# Reference: sql-plugin higherOrderFunctions.scala
# ---------------------------------------------------------------------------

def _lambda(fn, n_args, names):
    from spark_rapids_tpu.expr import hof as H
    from spark_rapids_tpu import types as T
    import inspect
    try:
        arity = len(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        arity = n_args
    import builtins
    arity = builtins.min(builtins.max(arity, 1), n_args)
    return H.make_lambda(fn, [T.NULL] * arity, names[:arity])


def transform(c, fn):
    """transform(array, x -> expr) or transform(array, (x, i) -> expr)."""
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 2, ["x", "i"])
    return H.ArrayTransform(_e(c), body, vs)


def filter(c, fn):  # noqa: A001 - Spark's F.filter
    """filter(array, x -> bool) / filter(array, (x, i) -> bool)."""
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 2, ["x", "i"])
    return H.ArrayFilter(_e(c), body, vs)


def exists(c, fn):
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 1, ["x"])
    return H.ArrayExists(_e(c), body, vs)


def forall(c, fn):
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 1, ["x"])
    return H.ArrayForAll(_e(c), body, vs)


def aggregate(c, zero, merge, finish=None):
    """aggregate(array, zero, (acc, x) -> new_acc[, acc -> out])."""
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(merge, 2, ["acc", "x"])
    fb = fvs = None
    if finish is not None:
        fb, fvs = _lambda(finish, 1, ["acc"])
    return H.ArrayAggregate(_e(c), _e(zero), body, vs, fb, fvs)


reduce = aggregate  # Spark 3.4+ alias


def zip_with(a, b, fn):
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 2, ["x", "y"])
    return H.ZipWith(_e(a), _e(b), body, vs)


def transform_keys(c, fn):
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 2, ["k", "v"])
    return H.TransformKeys(_e(c), body, vs)


def transform_values(c, fn):
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 2, ["k", "v"])
    return H.TransformValues(_e(c), body, vs)


def map_filter(c, fn):
    from spark_rapids_tpu.expr import hof as H
    body, vs = _lambda(fn, 2, ["k", "v"])
    return H.MapFilter(_e(c), body, vs)


# ---------------------------------------------------------------------------
# Array collection operations (reference collectionOperations.scala)
# ---------------------------------------------------------------------------

def array_min(c):
    from spark_rapids_tpu.expr.array_ops import ArrayMin
    return ArrayMin(_e(c))


def array_max(c):
    from spark_rapids_tpu.expr.array_ops import ArrayMax
    return ArrayMax(_e(c))


def array_position(c, v):
    from spark_rapids_tpu.expr.array_ops import ArrayPosition
    return ArrayPosition(_e(c), _e(v))


def array_remove(c, v):
    from spark_rapids_tpu.expr.array_ops import ArrayRemove
    return ArrayRemove(_e(c), _e(v))


def slice(c, start, length):  # noqa: A001 - Spark's F.slice
    from spark_rapids_tpu.expr.array_ops import Slice
    return Slice(_e(c), _e(start), _e(length))


def sort_array(c, asc=True):
    from spark_rapids_tpu.expr.array_ops import SortArray
    return SortArray(_e(c), asc)


def flatten(c):
    from spark_rapids_tpu.expr.array_ops import Flatten
    return Flatten(_e(c))


def array_distinct(c):
    from spark_rapids_tpu.expr.array_ops import ArrayDistinct
    return ArrayDistinct(_e(c))


def array_union(a, b):
    from spark_rapids_tpu.expr.array_ops import ArrayUnion
    return ArrayUnion(_e(a), _e(b))


def array_intersect(a, b):
    from spark_rapids_tpu.expr.array_ops import ArrayIntersect
    return ArrayIntersect(_e(a), _e(b))


def array_except(a, b):
    from spark_rapids_tpu.expr.array_ops import ArrayExcept
    return ArrayExcept(_e(a), _e(b))


def arrays_overlap(a, b):
    from spark_rapids_tpu.expr.array_ops import ArraysOverlap
    return ArraysOverlap(_e(a), _e(b))


def from_utc_timestamp(ts, tz):
    from spark_rapids_tpu.expr.datetime import FromUtcTimestamp
    from spark_rapids_tpu.expr.core import Literal
    z = tz.value if isinstance(tz, Literal) else tz
    return FromUtcTimestamp(_e(ts), z)


def to_utc_timestamp(ts, tz):
    from spark_rapids_tpu.expr.datetime import ToUtcTimestamp
    from spark_rapids_tpu.expr.core import Literal
    z = tz.value if isinstance(tz, Literal) else tz
    return ToUtcTimestamp(_e(ts), z)


# ---------------------------------------------------------------------------
# Math / string / datetime / collection breadth second tier
# ---------------------------------------------------------------------------

def _math1(name):
    def f(c):
        from spark_rapids_tpu.expr import math as MA
        return getattr(MA, name)(_e(c))
    f.__name__ = name.lower()
    return f


cbrt = _math1("Cbrt")
cot = _math1("Cot")
sec = _math1("Sec")
csc = _math1("Csc")
degrees = _math1("ToDegrees")
radians = _math1("ToRadians")
expm1 = _math1("Expm1")
log1p = _math1("Log1p")
rint = _math1("Rint")
factorial = _math1("Factorial")
bit_count = _math1("BitwiseCount")


def hypot(a, b):
    from spark_rapids_tpu.expr.math import Hypot
    return Hypot(_e(a), _e(b))


def nanvl(a, b):
    from spark_rapids_tpu.expr.math import NaNvl
    return NaNvl(_e(a), _e(b))


def getbit(c, pos):
    from spark_rapids_tpu.expr.math import BitwiseGet
    return BitwiseGet(_e(c), _e(pos))


bit_get = getbit


def bround(c, scale=0):
    from spark_rapids_tpu.expr.math import BRound
    return BRound(_e(c), scale)


def make_date(y, m, d):
    from spark_rapids_tpu.expr.datetime import MakeDate
    return MakeDate(_e(y), _e(m), _e(d))


def next_day(c, day):
    from spark_rapids_tpu.expr.datetime import NextDay
    return NextDay(_e(c), day)


def months_between(end, start, roundOff=True):
    from spark_rapids_tpu.expr.datetime import MonthsBetween
    return MonthsBetween(_e(end), _e(start), roundOff)


def _dt1(name):
    def f(c):
        from spark_rapids_tpu.expr import datetime as DTm
        return getattr(DTm, name)(_e(c))
    f.__name__ = name.lower()
    return f


unix_date = _dt1("UnixDate")
date_from_unix_date = _dt1("DateFromUnixDate")
unix_micros = _dt1("UnixMicros")
unix_millis = _dt1("UnixMillis")
unix_seconds = _dt1("UnixSeconds")
timestamp_millis = _dt1("TimestampMillis")
timestamp_micros = _dt1("TimestampMicros")


def octet_length(c):
    from spark_rapids_tpu.expr.strings import OctetLength
    return OctetLength(_e(c))


def bit_length(c):
    from spark_rapids_tpu.expr.strings import BitLength
    return BitLength(_e(c))


def left(c, n):
    from spark_rapids_tpu.expr.strings import Left
    from spark_rapids_tpu.expr.core import Literal
    return Left(_e(c), n.value if isinstance(n, Literal) else n)


def right(c, n):
    from spark_rapids_tpu.expr.strings import Right
    from spark_rapids_tpu.expr.core import Literal
    return Right(_e(c), n.value if isinstance(n, Literal) else n)


def chr_(c):
    from spark_rapids_tpu.expr.strings import Chr
    return Chr(_e(c))


char = chr_


def find_in_set(s, csv):
    from spark_rapids_tpu.expr.cpu_functions import FindInSet
    return FindInSet(_e(s), _e(csv))


def levenshtein(a, b):
    from spark_rapids_tpu.expr.cpu_functions import Levenshtein
    return Levenshtein(_e(a), _e(b))


def base64(c):
    from spark_rapids_tpu.expr.cpu_functions import Base64Encode
    return Base64Encode(_e(c))


def unbase64(c):
    from spark_rapids_tpu.expr.cpu_functions import UnBase64
    return UnBase64(_e(c))


def format_string(fmt, *cols):
    from spark_rapids_tpu.expr.cpu_functions import FormatString
    return FormatString(*[_e(c) for c in cols], params=(fmt,))


def elt(n, *cols):
    from spark_rapids_tpu.expr.cpu_functions import Elt
    return Elt(_e(n), *[_e(c) for c in cols])


def soundex(c):
    from spark_rapids_tpu.expr.cpu_functions import Soundex
    return Soundex(_e(c))


def json_tuple(c, *fields):
    from spark_rapids_tpu.expr.cpu_functions import JsonTuple
    return JsonTuple(_e(c), params=tuple(fields))


def crc32(c):
    from spark_rapids_tpu.expr.misc import Crc32
    return Crc32(_e(c))


def xxhash64(*cols):
    from spark_rapids_tpu.expr.misc import XxHash64
    return XxHash64([_e(c) for c in cols])


def array_repeat(v, n):
    from spark_rapids_tpu.expr.array_ops import ArrayRepeat
    return ArrayRepeat(_e(v), _e(n))


def array_join(c, sep, null_replacement=None):
    from spark_rapids_tpu.expr.array_ops import ArrayJoin
    return ArrayJoin(_e(c), sep, null_replacement)


def arrays_zip(*cols):
    from spark_rapids_tpu.expr.array_ops import ArraysZip
    return ArraysZip([_e(c) for c in cols])


def map_entries(c):
    from spark_rapids_tpu.expr.array_ops import MapEntries
    return MapEntries(_e(c))


def map_concat(*cols):
    from spark_rapids_tpu.expr.array_ops import MapConcat
    return MapConcat([_e(c) for c in cols])


def map_from_arrays(k, v):
    from spark_rapids_tpu.expr.array_ops import MapFromArrays
    return MapFromArrays(_e(k), _e(v))


def str_to_map(c, pair_delim=",", kv_delim=":"):
    from spark_rapids_tpu.expr.array_ops import StrToMap
    return StrToMap(_e(c), pair_delim, kv_delim)


def sha1(c):
    from spark_rapids_tpu.expr.cpu_functions import Sha1
    return Sha1(_e(c))


def hex(c):  # noqa: A001 - Spark name
    from spark_rapids_tpu.expr.cpu_functions import HexStr
    return HexStr(_e(c))


def unhex(c):
    from spark_rapids_tpu.expr.cpu_functions import Unhex
    return Unhex(_e(c))


def bin(c):  # noqa: A001 - Spark name
    from spark_rapids_tpu.expr.cpu_functions import Bin
    return Bin(_e(c))


def conv(c, from_base, to_base):
    from spark_rapids_tpu.expr.cpu_functions import Conv
    return Conv(_e(c), params=(int(from_base), int(to_base)))


def url_encode(c):
    from spark_rapids_tpu.expr.cpu_functions import UrlEncode
    return UrlEncode(_e(c))


def url_decode(c):
    from spark_rapids_tpu.expr.cpu_functions import UrlDecode
    return UrlDecode(_e(c))


def stack(n, *cols):
    from spark_rapids_tpu.expr.complex import Stack
    return Stack(n, *[_e(c) for c in cols])


def acosh(c):
    return MA.Acosh(_e(c))


def asinh(c):
    return MA.Asinh(_e(c))


def atanh(c):
    return MA.Atanh(_e(c))


def pmod(a, b):
    return MA.Pmod(_e(a), _e(b))


def positive(c):
    return MA.UnaryPositive(_e(c))


def weekday(c):
    return DT.WeekDay(_e(c))


def date_trunc(fmt, c):
    return DT.TruncTimestamp(_e(c), fmt)


def regexp_extract_all(c, pattern, idx=1):
    from spark_rapids_tpu.expr.cpu_functions import RegexpExtractAll
    return RegexpExtractAll(_e(c), params=(pattern, idx))


def to_json(c):
    from spark_rapids_tpu.expr.cpu_functions import StructsToJson
    return StructsToJson(_e(c))


def width_bucket(v, lo, hi, nb):
    return MA.WidthBucket(_e(v), _e(lo), _e(hi), _e(nb))


def luhn_check(c):
    from spark_rapids_tpu.expr.cpu_functions import Luhncheck
    return Luhncheck(_e(c))
