"""Data type system and TypeSig algebra.

Reference parity: com/nvidia/spark/rapids/TypeChecks.scala (TypeSig, the
algebra of supported types with per-op notes used both for tagging and for
generating supported_ops docs). This implementation keeps the same two roles
-- (1) a closed set of SQL types with nesting, (2) a set-algebra used by every
operator rule to declare what it supports -- but is organised around what XLA
can natively represent: fixed-width primitives map 1:1 onto device arrays,
strings are offset+bytes planes, decimals are scaled integers.
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import numpy as np


class DataType:
    """Base of the closed SQL type set."""

    #: jax/numpy dtype of the primary device plane, or None for nested reprs.
    np_dtype: Optional[np.dtype] = None

    def __repr__(self) -> str:
        return self.__class__.__name__.replace("Type", "").lower()

    def __eq__(self, other) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self))

    @property
    def is_numeric(self) -> bool:
        return isinstance(self, (IntegralType, FractionalType, DecimalType))

    @property
    def is_integral(self) -> bool:
        return isinstance(self, IntegralType)

    def default_size(self) -> int:
        """Estimated bytes per row (reference: GpuBatchUtils size estimation)."""
        if self.np_dtype is not None:
            return np.dtype(self.np_dtype).itemsize
        return 16


class NullType(DataType):
    np_dtype = np.dtype(np.int8)  # carrier plane; every row invalid


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)


class IntegralType(DataType):
    pass


class Int8Type(IntegralType):
    np_dtype = np.dtype(np.int8)


class Int16Type(IntegralType):
    np_dtype = np.dtype(np.int16)


class Int32Type(IntegralType):
    np_dtype = np.dtype(np.int32)


class Int64Type(IntegralType):
    np_dtype = np.dtype(np.int64)


class FractionalType(DataType):
    pass


class Float32Type(FractionalType):
    np_dtype = np.dtype(np.float32)


class Float64Type(FractionalType):
    np_dtype = np.dtype(np.float64)


class DateType(DataType):
    """Days since epoch, int32 (Spark DateType semantics)."""
    np_dtype = np.dtype(np.int32)


class TimestampType(DataType):
    """Microseconds since epoch UTC, int64 (Spark TimestampType semantics)."""
    np_dtype = np.dtype(np.int64)


@dataclasses.dataclass(frozen=True, eq=True)
class DecimalType(DataType):
    """Decimal as scaled int64 (precision<=18) or int128-as-2xint64.

    Reference keeps DECIMAL128 in libcudf (jni DecimalUtils); on TPU we store
    unscaled values in int64 lanes (precision<=18 for round 1) and perform
    arithmetic with explicit rescaling in the expression compiler.
    """
    precision: int = 10
    scale: int = 0

    def __repr__(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    @property
    def np_dtype(self):  # type: ignore[override]
        return np.dtype(np.int64)

    MAX_INT64_PRECISION = 18


class StringType(DataType):
    """UTF-8 strings: int32 offsets plane + uint8 bytes plane on device.

    Dictionary-encoded variant (codes + host dictionary) is produced by scans
    for group/join keys -- see columnar/strings.py.
    """
    np_dtype = None

    def default_size(self) -> int:
        return 24  # offsets + avg payload estimate


@dataclasses.dataclass(frozen=True, eq=True)
class ArrayType(DataType):
    element: DataType = dataclasses.field(default_factory=Int32Type)
    contains_null: bool = True

    def __repr__(self) -> str:
        return f"array<{self.element!r}>"


@dataclasses.dataclass(frozen=True, eq=True)
class StructField:
    name: str
    dtype: DataType
    nullable: bool = True


@dataclasses.dataclass(frozen=True, eq=True)
class StructType(DataType):
    fields: tuple = ()

    def __repr__(self) -> str:
        inner = ",".join(f"{f.name}:{f.dtype!r}" for f in self.fields)
        return f"struct<{inner}>"

    def field_names(self):
        return [f.name for f in self.fields]


@dataclasses.dataclass(frozen=True, eq=True)
class MapType(DataType):
    key: DataType = dataclasses.field(default_factory=StringType)
    value: DataType = dataclasses.field(default_factory=StringType)

    def __repr__(self) -> str:
        return f"map<{self.key!r},{self.value!r}>"


# Singletons for the non-parameterised types.
NULL = NullType()
BOOLEAN = BooleanType()
INT8 = Int8Type()
INT16 = Int16Type()
INT32 = Int32Type()
INT64 = Int64Type()
FLOAT32 = Float32Type()
FLOAT64 = Float64Type()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple

    @staticmethod
    def of(*pairs) -> "Schema":
        return Schema(tuple(StructField(n, t) for n, t in pairs))

    @property
    def names(self):
        return [f.name for f in self.fields]

    @property
    def types(self):
        return [f.dtype for f in self.fields]

    def __len__(self):
        return len(self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def __repr__(self):
        return "Schema(" + ", ".join(f"{f.name}:{f.dtype!r}" for f in self.fields) + ")"


# ---------------------------------------------------------------------------
# TypeSig: set algebra over supported types (reference TypeChecks.scala:168).
# ---------------------------------------------------------------------------

_BASE_ORDER = [
    "NULL", "BOOLEAN", "INT8", "INT16", "INT32", "INT64", "FLOAT32",
    "FLOAT64", "DECIMAL64", "STRING", "DATE", "TIMESTAMP", "ARRAY",
    "STRUCT", "MAP",
]


def _tag_of(dtype: DataType) -> str:
    if isinstance(dtype, NullType):
        return "NULL"
    if isinstance(dtype, BooleanType):
        return "BOOLEAN"
    if isinstance(dtype, Int8Type):
        return "INT8"
    if isinstance(dtype, Int16Type):
        return "INT16"
    if isinstance(dtype, Int32Type):
        return "INT32"
    if isinstance(dtype, Int64Type):
        return "INT64"
    if isinstance(dtype, Float32Type):
        return "FLOAT32"
    if isinstance(dtype, Float64Type):
        return "FLOAT64"
    if isinstance(dtype, DecimalType):
        return "DECIMAL64"
    if isinstance(dtype, StringType):
        return "STRING"
    if isinstance(dtype, DateType):
        return "DATE"
    if isinstance(dtype, TimestampType):
        return "TIMESTAMP"
    if isinstance(dtype, ArrayType):
        return "ARRAY"
    if isinstance(dtype, StructType):
        return "STRUCT"
    if isinstance(dtype, MapType):
        return "MAP"
    raise TypeError(f"unknown dtype {dtype!r}")


class TypeSig:
    """Immutable set of type tags with optional nested-type constraints and
    per-type notes (rendered into supported-ops docs, reference
    TypeChecks.scala "ps notes")."""

    def __init__(self, tags: Iterable[str] = (), nested: Optional["TypeSig"] = None,
                 notes: Optional[dict] = None):
        self.tags = frozenset(tags)
        self.nested_sig = nested
        self.notes = dict(notes or {})

    # -- construction ------------------------------------------------------
    @staticmethod
    def none() -> "TypeSig":
        return TypeSig()

    @staticmethod
    def all() -> "TypeSig":
        return TypeSig(_BASE_ORDER, nested=TypeSig(_BASE_ORDER))

    def __add__(self, other: "TypeSig") -> "TypeSig":
        nested = self.nested_sig or other.nested_sig
        if self.nested_sig and other.nested_sig:
            nested = self.nested_sig + other.nested_sig
        return TypeSig(self.tags | other.tags, nested, {**self.notes, **other.notes})

    def __sub__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(self.tags - other.tags, self.nested_sig, self.notes)

    def nested(self) -> "TypeSig":
        """Allow the same set inside arrays/structs/maps."""
        return TypeSig(self.tags | {"ARRAY", "STRUCT", "MAP"}, nested=self)

    def with_note(self, tag: str, note: str) -> "TypeSig":
        notes = dict(self.notes)
        notes[tag] = note
        return TypeSig(self.tags, self.nested_sig, notes)

    # -- checks ------------------------------------------------------------
    def supports(self, dtype: DataType) -> bool:
        tag = _tag_of(dtype)
        if tag not in self.tags:
            return False
        if isinstance(dtype, ArrayType):
            inner = self.nested_sig or TypeSig.none()
            return inner.supports(dtype.element)
        if isinstance(dtype, StructType):
            inner = self.nested_sig or TypeSig.none()
            return all(inner.supports(f.dtype) for f in dtype.fields)
        if isinstance(dtype, MapType):
            inner = self.nested_sig or TypeSig.none()
            return inner.supports(dtype.key) and inner.supports(dtype.value)
        return True

    def reason_not_supported(self, dtype: DataType) -> Optional[str]:
        if self.supports(dtype):
            return None
        tag = _tag_of(dtype)
        if tag in self.notes:
            return f"{dtype!r} is not supported ({self.notes[tag]})"
        return f"{dtype!r} is not supported"

    def __repr__(self):
        ordered = [t for t in _BASE_ORDER if t in self.tags]
        return "TypeSig(" + "+".join(ordered) + ")"


# Common signatures mirroring the reference's named combinations
# (TypeChecks.scala: integral, numeric, commonCudfTypes, ...).
class Sigs:
    INTEGRAL = TypeSig(["INT8", "INT16", "INT32", "INT64"])
    FP = TypeSig(["FLOAT32", "FLOAT64"])
    NUMERIC = INTEGRAL + FP + TypeSig(["DECIMAL64"])
    COMMON = NUMERIC + TypeSig(["BOOLEAN", "STRING", "DATE", "TIMESTAMP", "NULL"])
    ORDERABLE = COMMON
    COMPARABLE = COMMON
    ALL = TypeSig.all()
    NONE = TypeSig.none()


def common_type(a: DataType, b: DataType) -> DataType:
    """Numeric widening for binary expressions (Spark's findTightestCommonType
    subset used by the expression compiler)."""
    if a == b:
        return a
    order = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        precision = min(max(a.precision - a.scale, b.precision - b.scale) + scale,
                        DecimalType.MAX_INT64_PRECISION)
        return DecimalType(precision, scale)
    if isinstance(a, DecimalType) and b.is_integral:
        return a
    if isinstance(b, DecimalType) and a.is_integral:
        return b
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        return FLOAT64
    if a in order and b in order:
        return order[max(order.index(a), order.index(b))]
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    raise TypeError(f"no common type for {a!r} and {b!r}")


def from_arrow(at) -> DataType:
    """Map a pyarrow type to our type set (host IO boundary)."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BOOLEAN
    if pa.types.is_int8(at):
        return INT8
    if pa.types.is_int16(at):
        return INT16
    if pa.types.is_int32(at):
        return INT32
    if pa.types.is_int64(at):
        return INT64
    if pa.types.is_float32(at):
        return FLOAT32
    if pa.types.is_float64(at):
        return FLOAT64
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_date32(at):
        return DATE
    if pa.types.is_timestamp(at):
        return TIMESTAMP
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_null(at):
        return NULL
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_struct(at):
        return StructType(tuple(StructField(f.name, from_arrow(f.type)) for f in at))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow(dtype: DataType):
    import pyarrow as pa
    mapping = {
        BOOLEAN: pa.bool_(), INT8: pa.int8(), INT16: pa.int16(),
        INT32: pa.int32(), INT64: pa.int64(), FLOAT32: pa.float32(),
        FLOAT64: pa.float64(), STRING: pa.string(), DATE: pa.date32(),
        TIMESTAMP: pa.timestamp("us"), NULL: pa.null(),
    }
    if isinstance(dtype, DecimalType):
        return pa.decimal128(dtype.precision, dtype.scale)
    if isinstance(dtype, ArrayType):
        return pa.list_(to_arrow(dtype.element))
    if isinstance(dtype, StructType):
        return pa.struct([pa.field(f.name, to_arrow(f.dtype)) for f in dtype.fields])
    if isinstance(dtype, MapType):
        return pa.map_(to_arrow(dtype.key), to_arrow(dtype.value))
    return mapping[dtype]
