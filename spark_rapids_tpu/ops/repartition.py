"""Counting-sort repartition: the single-dispatch compacted exchange tail.

Reference parity: GpuShuffleExchangeExecBase partitions a batch with one
cudf hash-partition kernel that returns a contiguous table plus partition
offsets (GpuHashPartitioningBase.hashPartitionAndClose). The masked
analog this module replaces emitted `n_out` full-capacity mask-sliced
sub-batches per input batch, so every downstream operator paid
`n_out * capacity` work on mostly-dead rows and one deferred count sync
per sub-batch.

The TPU-shaped equivalent is the counting-sort trick `ops/join.py`'s
`_dense_table` already uses for the dense build table, applied to target
partition ids:

1. the caller computes `pid` (hash pmod / round-robin / range bounds)
   inside the SAME trace,
2. a stable counting sort permutes rows so partition p's rows are
   contiguous at [offsets[p], offsets[p+1]) in input order,
3. the `n_out+1` offsets vector is the ONLY thing the host fetches —
   one round trip sizes every output slice,
4. per-partition sub-batches are contiguous gathers sized by
   `round_capacity(actual rows)` instead of the input capacity.

Steps 1-3 fuse into ONE XLA computation per input batch (the exchange
execs wrap them in `fuse.fused`); step 4 is host-driven assembly with no
further synchronization.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch, round_capacity
from spark_rapids_tpu.ops import kernels as K
from spark_rapids_tpu.runtime import compile_cache as _cc


def partition_counts(pid: jax.Array, live: jax.Array, n_out: int
                     ) -> jax.Array:
    """Traced: int32[n_out] live-row count per target partition. Dead rows
    fall into an overflow bucket that is sliced away. Shared by the
    counting sort below and the ICI exchange's per-(src,dst) lane sizing."""
    slot = jnp.where(live, pid, n_out).astype(jnp.int32)
    return jax.ops.segment_sum(jnp.ones(slot.shape[0], jnp.int32), slot,
                               num_segments=n_out + 1)[:n_out]


def counting_sort_by_pid(batch: ColumnarBatch, pid: jax.Array, n_out: int):
    """Traced tail shared by the hash / round-robin / range exchanges.

    Stable counting sort of the batch's rows by target partition id:
    returns (sorted_batch, offsets[n_out+1]) where partition p's rows
    occupy [offsets[p], offsets[p+1]) of the sorted planes in input order.
    Dead rows sort past offsets[n_out] and gather as invalid padding.

    Everything here stays on device; the caller's ONE host fetch of the
    offsets vector is the entire synchronization cost of partitioning a
    batch (vs one deferred count sync per masked sub-batch).
    """
    live = batch.live_mask()
    cap = batch.capacity
    cnt = partition_counts(pid, live, n_out)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(cnt).astype(jnp.int32)])
    # stable: rows ordered by (pid, original index); dead rows rank last
    slot = jnp.where(live, pid, n_out).astype(jnp.int32)
    order = jnp.argsort(slot, stable=True).astype(jnp.int32)
    total = offsets[n_out]
    idx = jnp.where(jnp.arange(cap, dtype=jnp.int32) < total, order, -1)
    out = K.gather_batch(batch, idx, total)
    return out, offsets


@_cc.jit(static_argnums=(3,))
def _slice_kernel(batch, start, length, out_cap: int):
    """One jitted gather per output slice. start/length ride as TRACED
    scalars so the executable caches per (input layout, out_cap) bucket
    instead of per offset value."""
    pos = jnp.arange(out_cap, dtype=jnp.int32)
    idx = jnp.where(pos < length, pos + start, -1)
    return K.gather_batch(batch, idx, length)


def slice_rows(batch: ColumnarBatch, start: int, length: int
               ) -> ColumnarBatch:
    """Contiguous row-range slice [start, start+length) of a compact
    batch as a right-sized sub-batch with a plain host-int row count —
    the skew-split primitive (exec/adaptive.py): one gather dispatch per
    slice, capacity bucketed by ``round_capacity(length)`` so the
    sub-dispatches of a split partition share executables with the
    compact exchange's own slices. The caller guarantees the batch is
    unmasked (row_mask None) with a host-int row count."""
    sub = _slice_kernel(batch, jnp.int32(int(start)),
                        jnp.int32(int(length)),
                        round_capacity(int(length)))
    return ColumnarBatch(sub.columns, int(length))


def compact_slices(sorted_batch: ColumnarBatch, offsets: np.ndarray,
                   n_out: int) -> List[Optional[ColumnarBatch]]:
    """Host-side assembly after the single offsets fetch: contiguous
    per-partition sub-batches from the sorted planes, each with capacity
    `round_capacity(rows)` instead of the input capacity and a plain host
    int row count (downstream operators never sync a lazy count for them).
    Empty partitions yield None."""
    out: List[Optional[ColumnarBatch]] = []
    for p in range(n_out):
        start = int(offsets[p])
        cnt = int(offsets[p + 1]) - start
        if cnt <= 0:
            out.append(None)
            continue
        sub = _slice_kernel(sorted_batch, jnp.int32(start), jnp.int32(cnt),
                            round_capacity(cnt))
        out.append(ColumnarBatch(sub.columns, cnt))
    return out
