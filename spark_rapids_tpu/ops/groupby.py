"""Sort-based segmented groupby kernels.

Reference parity: cudf GroupByAggregation (hash-based on GPU). The
TPU-idiomatic formulation is sort-based: normalize keys to uint64 planes,
stable-sort, derive segment ids from key boundaries, then apply
jax.ops.segment_* reductions with a static segment capacity. Sorting keys
also gives deterministic float aggregation order (the reference needs
special handling for that; we get it for free).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector, ColumnarBatch, round_capacity
from spark_rapids_tpu.ops import kernels as K


def group_segments(key_cols: List[ColumnVector], num_rows: int
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort rows by the group keys. Returns (perm, seg_ids, seg_starts_mask)
    over the full capacity, where perm is the sorting permutation, seg_ids
    assigns each sorted position a dense group id (padded rows get id
    capacity-1... they share the trailing group but are masked by callers),
    and seg_starts_mask flags the first sorted row of each group."""
    norm = [K.normalize_key(c, num_rows) for c in key_cols]
    perm = K.lexsort_indices([(k, n, True, True) for k, n in norm], num_rows)
    cap = perm.shape[0]
    in_range = jnp.arange(cap) < num_rows
    boundary = jnp.zeros(cap, jnp.bool_).at[0].set(True)
    for k, nulls in norm:
        ks = k[perm]
        ns = nulls[perm]
        diff = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                (ks[1:] != ks[:-1]) | (ns[1:] != ns[:-1])])
        boundary = boundary | diff
    boundary = boundary & in_range
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_ids = jnp.where(in_range, seg_ids, cap - 1)
    return perm, seg_ids, boundary


def num_groups(boundary: jax.Array) -> int:
    return int(jnp.sum(boundary.astype(jnp.int32)))


_MAX_INIT = {
    np.dtype(np.int8): np.iinfo(np.int8).min,
    np.dtype(np.int16): np.iinfo(np.int16).min,
    np.dtype(np.int32): np.iinfo(np.int32).min,
    np.dtype(np.int64): np.iinfo(np.int64).min,
    np.dtype(np.float32): -np.inf,
    np.dtype(np.float64): -np.inf,
    np.dtype(np.bool_): False,
}
_MIN_INIT = {
    np.dtype(np.int8): np.iinfo(np.int8).max,
    np.dtype(np.int16): np.iinfo(np.int16).max,
    np.dtype(np.int32): np.iinfo(np.int32).max,
    np.dtype(np.int64): np.iinfo(np.int64).max,
    np.dtype(np.float32): np.inf,
    np.dtype(np.float64): np.inf,
    np.dtype(np.bool_): True,
}


def segmented_agg(op: str, values: jax.Array, valid: jax.Array,
                  seg_ids: jax.Array, seg_cap: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Apply one segmented reduction. values/valid are in SORTED order.
    Returns (out_values[seg_cap], out_valid[seg_cap]). SQL null semantics:
    sum/min/max/avg ignore nulls and are null for all-null groups; count
    counts non-null rows."""
    vdt = values.dtype
    nvalid = jax.ops.segment_sum(valid.astype(jnp.int64), seg_ids, num_segments=seg_cap)
    if op == "count":
        return nvalid, jnp.ones(seg_cap, jnp.bool_)
    if op == "count_all":
        ones = jnp.ones_like(seg_ids, dtype=jnp.int64)
        return jax.ops.segment_sum(ones, seg_ids, num_segments=seg_cap), \
            jnp.ones(seg_cap, jnp.bool_)
    if op == "sum":
        masked = jnp.where(valid, values, jnp.zeros_like(values))
        out = jax.ops.segment_sum(masked, seg_ids, num_segments=seg_cap)
        return out, nvalid > 0
    if op == "sumsq":
        masked = jnp.where(valid, values * values, jnp.zeros_like(values))
        out = jax.ops.segment_sum(masked, seg_ids, num_segments=seg_cap)
        return out, nvalid > 0
    if op in ("min", "max"):
        is_float = np.dtype(vdt) in (np.dtype(np.float32), np.dtype(np.float64))
        if is_float:
            # Spark total order: NaN greater than +inf, -0.0 == 0.0 via the
            # order-preserving bit transform; reduce on bits, invert after.
            width = 32 if np.dtype(vdt) == np.dtype(np.float32) else 64
            if width == 32:
                raw = jax.lax.bitcast_convert_type(values, jnp.int32).astype(jnp.int64)
            else:
                raw = jax.lax.bitcast_convert_type(values, jnp.int64)
            bits = K._order_float_bits(raw, width)
            init = jnp.uint64(0xFFFFFFFFFFFFFFFF) if op == "min" else jnp.uint64(0)
            masked = jnp.where(valid, bits, init)
            red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
            out_bits = red(masked, seg_ids, num_segments=seg_cap)
            out = _invert_float_bits(out_bits, width, vdt)
            return out, nvalid > 0
        init = (_MIN_INIT if op == "min" else _MAX_INIT)[np.dtype(vdt)]
        masked = jnp.where(valid, values, jnp.full_like(values, init))
        red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        out = red(masked, seg_ids, num_segments=seg_cap)
        return out, nvalid > 0
    if op in ("first", "last"):
        # position of first/last valid row per segment
        n = values.shape[0]
        pos = jnp.arange(n, dtype=jnp.int64)
        if op == "first":
            masked_pos = jnp.where(valid, pos, n)
            sel = jax.ops.segment_min(masked_pos, seg_ids, num_segments=seg_cap)
        else:
            masked_pos = jnp.where(valid, pos, -1)
            sel = jax.ops.segment_max(masked_pos, seg_ids, num_segments=seg_cap)
        has = (sel >= 0) & (sel < n)
        sel_c = jnp.clip(sel, 0, n - 1).astype(jnp.int32)
        return values[sel_c], has & (nvalid > 0)
    if op == "any":
        masked = jnp.where(valid, values.astype(jnp.bool_), False)
        out = jax.ops.segment_max(masked.astype(jnp.int32), seg_ids, num_segments=seg_cap)
        return out.astype(jnp.bool_), nvalid > 0
    if op == "all":
        masked = jnp.where(valid, values.astype(jnp.bool_), True)
        out = jax.ops.segment_min(masked.astype(jnp.int32), seg_ids, num_segments=seg_cap)
        return out.astype(jnp.bool_), nvalid > 0
    raise ValueError(f"unknown segmented op {op}")


def _invert_float_bits(bits_u64: jax.Array, width: int, vdt):
    """Inverse of kernels._order_float_bits."""
    import jax.lax as lax
    if width == 64:
        sign = jnp.uint64(1 << 63)
        pos = (bits_u64 & sign) != 0
        raw = jnp.where(pos, bits_u64 ^ sign, ~bits_u64)
        return lax.bitcast_convert_type(raw.astype(jnp.uint64), jnp.float64)
    sign = jnp.uint64(0x80000000)
    mask = jnp.uint64(0xFFFFFFFF)
    b = bits_u64 & mask
    pos = (b & sign) != 0
    raw = jnp.where(pos, b ^ sign, (~b) & mask)
    return lax.bitcast_convert_type(raw.astype(jnp.uint32), jnp.float32)


def gather_group_keys(key_cols: List[ColumnVector], perm: jax.Array,
                      boundary: jax.Array, n_groups: int, num_rows: int
                      ) -> List[ColumnVector]:
    """Representative key row per group = first sorted row of each segment."""
    first_idx, _ = K.filter_indices(boundary, boundary.shape[0])
    out = []
    for c in key_cols:
        sorted_col = K.gather_column(c, perm, num_rows)
        out.append(K.gather_column(sorted_col, first_idx, num_rows))
    return out
