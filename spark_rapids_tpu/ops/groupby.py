"""Sort-based segmented groupby kernels.

Reference parity: cudf GroupByAggregation (hash-based on GPU). The
TPU-idiomatic formulation is sort-based: normalize keys to uint64 planes,
stable-sort, derive segment ids from key boundaries, then apply
jax.ops.segment_* reductions with a static segment capacity. Sorting keys
also gives deterministic float aggregation order (the reference needs
special handling for that; we get it for free).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector, ColumnarBatch, round_capacity
from spark_rapids_tpu.ops import kernels as K


def group_segments(key_cols: List[ColumnVector], num_rows: int, live=None
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort rows by the group keys. Returns (perm, seg_ids, seg_starts_mask)
    over the full capacity, where perm is the sorting permutation, seg_ids
    assigns each sorted position a dense group id (padded rows get id
    capacity-1... they share the trailing group but are masked by callers),
    and seg_starts_mask flags the first sorted row of each group."""
    from spark_rapids_tpu.columnar.batch import traced_rows
    nr = traced_rows(num_rows)
    norm = [K.normalize_key(c, num_rows, live=live) for c in key_cols]
    perm = K.lexsort_indices([(k, n, True, True) for k, n in norm], nr, live=live)
    cap = perm.shape[0]
    in_range = (jnp.arange(cap) < nr) if live is None else live[perm]
    boundary = jnp.zeros(cap, jnp.bool_).at[0].set(True)
    for k, nulls in norm:
        ks = k[perm]
        ns = nulls[perm]
        diff = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                (ks[1:] != ks[:-1]) | (ns[1:] != ns[:-1])])
        boundary = boundary | diff
    boundary = boundary & in_range
    seg_ids = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    seg_ids = jnp.where(in_range, seg_ids, cap - 1)
    return perm, seg_ids, boundary


def num_groups(boundary: jax.Array) -> int:
    return int(jnp.sum(boundary.astype(jnp.int32)))


def _float_minmax_prep(op: str, values: jax.Array, valid: jax.Array):
    """Spark float min/max semantics WITHOUT 64-bit bitcasts (the TPU x64
    rewriter cannot lower f64<->s64 bitcast-convert): NaN sorts above
    +inf and all NaNs are equal; -0.0 == 0.0. Returns (clean_plane,
    nan_flag, nonnan_flag): reduce clean_plane with plain min/max, then
    patch groups via the flags — max is NaN if any valid NaN; min is NaN
    only when no valid non-NaN value exists."""
    isnan = jnp.isnan(values)
    sentinel = jnp.array(np.inf if op == "min" else -np.inf, values.dtype)
    clean = jnp.where(values == 0.0, jnp.zeros_like(values), values)
    clean = jnp.where(valid & ~isnan, clean, jnp.full_like(values, sentinel))
    return clean, (valid & isnan), (valid & ~isnan)


def _float_minmax_patch(op: str, red: jax.Array, any_nan: jax.Array,
                        any_nonnan: jax.Array) -> jax.Array:
    nan = jnp.array(np.nan, red.dtype)
    if op == "max":
        return jnp.where(any_nan, nan, red)
    return jnp.where(any_nonnan, red, nan)


def global_agg(op: str, values: jax.Array, valid: jax.Array
               ) -> Tuple[jax.Array, jax.Array]:
    """Ungrouped aggregation: plain masked tree-reductions, no permutation,
    no segment scatter (those cost 100x a reduction on TPU). Returns
    ([1]-shaped value, [1]-shaped validity)."""
    vdt = values.dtype
    nvalid = jnp.sum(valid.astype(jnp.int64))
    some = (nvalid > 0)[None]

    def one(x):
        return x[None]

    if op == "count":
        return one(nvalid), jnp.ones(1, jnp.bool_)
    if op == "count_all":
        return one(nvalid), jnp.ones(1, jnp.bool_)
    if op in ("sum", "sumsq"):
        v = values * values if op == "sumsq" else values
        return one(jnp.sum(jnp.where(valid, v, jnp.zeros_like(v)))), some
    if op in ("min", "max"):
        red = jnp.min if op == "min" else jnp.max
        is_float = np.dtype(vdt) in (np.dtype(np.float32), np.dtype(np.float64))
        if is_float:
            clean, nanf, nonnanf = _float_minmax_prep(op, values, valid)
            out = _float_minmax_patch(op, one(red(clean)),
                                      one(jnp.any(nanf)), one(jnp.any(nonnanf)))
            return out, some
        init = (_MIN_INIT if op == "min" else _MAX_INIT)[np.dtype(vdt)]
        masked = jnp.where(valid, values, jnp.full_like(values, init))
        return one(red(masked)), some
    if op in ("first", "last"):
        n = values.shape[0]
        pos = jnp.arange(n, dtype=jnp.int64)
        if op == "first":
            sel = jnp.min(jnp.where(valid, pos, n))
        else:
            sel = jnp.max(jnp.where(valid, pos, -1))
        has = (sel >= 0) & (sel < n)
        return one(values[jnp.clip(sel, 0, n - 1).astype(jnp.int32)]), has[None] & some
    if op == "any":
        return one(jnp.any(valid & values.astype(jnp.bool_))), some
    if op == "all":
        return one(jnp.all(jnp.where(valid, values.astype(jnp.bool_), True))), some
    raise ValueError(f"unknown global op {op}")


def bucket_agg(op: str, values: jax.Array, valid: jax.Array,
               bucket: jax.Array, B: int, matmul_ok: bool
               ) -> Tuple[jax.Array, jax.Array]:
    """Segmented reduction into a DENSE bucket space with no sort: the MXU
    answer to grouped aggregation (one-hot matmul for tiny B, bounded
    scatter otherwise). values/valid/bucket are in original row order;
    invalid rows route to the overflow bucket B and are dropped."""
    vdt = values.dtype
    safe_bucket = jnp.where(valid, bucket, B)
    if op in ("count", "count_all"):
        if matmul_ok:
            out = jnp.stack([
                jnp.sum((valid & (bucket == b)).astype(jnp.int64))
                for b in range(B)])
        else:
            out = jax.ops.segment_sum(jnp.where(valid, 1, 0), safe_bucket,
                                      num_segments=B + 1)[:B].astype(jnp.int64)
        return out, jnp.ones(B, jnp.bool_)
    if op in ("sum", "sumsq"):
        v = values * values if op == "sumsq" else values
        v = jnp.where(valid, v, jnp.zeros_like(v))
        nvalid = bucket_agg("count", values, valid, bucket, B, matmul_ok)[0]
        if matmul_ok:
            # Tiny bucket spaces: one masked tree-reduction per bucket.
            # B full passes over the plane are bandwidth-cheap, keep full
            # f64 precision (an MXU one-hot matmul accumulates f64 sums
            # with ~1e-6 relative error on TPU), and need no scatter.
            out = jnp.stack([
                jnp.sum(jnp.where(bucket == b, v, jnp.zeros_like(v)))
                for b in range(B)])
        else:
            out = jax.ops.segment_sum(v, safe_bucket, num_segments=B + 1)[:B]
        return out, nvalid > 0
    nvalid = jax.ops.segment_sum(jnp.where(valid, 1, 0), safe_bucket,
                                 num_segments=B + 1)[:B]
    if op in ("min", "max"):
        red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        is_float = np.dtype(vdt) in (np.dtype(np.float32), np.dtype(np.float64))
        if is_float:
            clean, nanf, nonnanf = _float_minmax_prep(op, values, valid)
            out = red(clean, safe_bucket, num_segments=B + 1)[:B]
            any_nan = jax.ops.segment_max(nanf.astype(jnp.int32), safe_bucket,
                                          num_segments=B + 1)[:B] > 0
            any_nonnan = jax.ops.segment_max(nonnanf.astype(jnp.int32), safe_bucket,
                                             num_segments=B + 1)[:B] > 0
            return _float_minmax_patch(op, out, any_nan, any_nonnan), nvalid > 0
        init = (_MIN_INIT if op == "min" else _MAX_INIT)[np.dtype(vdt)]
        masked = jnp.where(valid, values, jnp.full_like(values, init))
        out = red(masked, safe_bucket, num_segments=B + 1)[:B]
        return out, nvalid > 0
    if op in ("first", "last"):
        n = values.shape[0]
        pos = jnp.arange(n, dtype=jnp.int64)
        if op == "first":
            sel = jax.ops.segment_min(jnp.where(valid, pos, n), safe_bucket,
                                      num_segments=B + 1)[:B]
        else:
            sel = jax.ops.segment_max(jnp.where(valid, pos, -1), safe_bucket,
                                      num_segments=B + 1)[:B]
        has = (sel >= 0) & (sel < n)
        return values[jnp.clip(sel, 0, n - 1).astype(jnp.int32)], has & (nvalid > 0)
    if op in ("any", "all"):
        v = values.astype(jnp.int32)
        if op == "any":
            masked = jnp.where(valid, v, 0)
            out = jax.ops.segment_max(masked, safe_bucket, num_segments=B + 1)[:B]
        else:
            masked = jnp.where(valid, v, 1)
            out = jax.ops.segment_min(masked, safe_bucket, num_segments=B + 1)[:B]
        return out.astype(jnp.bool_), nvalid > 0
    raise ValueError(f"unknown bucket op {op}")


_MAX_INIT = {
    np.dtype(np.int8): np.iinfo(np.int8).min,
    np.dtype(np.int16): np.iinfo(np.int16).min,
    np.dtype(np.int32): np.iinfo(np.int32).min,
    np.dtype(np.int64): np.iinfo(np.int64).min,
    np.dtype(np.float32): -np.inf,
    np.dtype(np.float64): -np.inf,
    np.dtype(np.bool_): False,
}
_MIN_INIT = {
    np.dtype(np.int8): np.iinfo(np.int8).max,
    np.dtype(np.int16): np.iinfo(np.int16).max,
    np.dtype(np.int32): np.iinfo(np.int32).max,
    np.dtype(np.int64): np.iinfo(np.int64).max,
    np.dtype(np.float32): np.inf,
    np.dtype(np.float64): np.inf,
    np.dtype(np.bool_): True,
}


def segmented_agg(op: str, values: jax.Array, valid: jax.Array,
                  seg_ids: jax.Array, seg_cap: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Apply one segmented reduction. values/valid are in SORTED order.
    Returns (out_values[seg_cap], out_valid[seg_cap]). SQL null semantics:
    sum/min/max/avg ignore nulls and are null for all-null groups; count
    counts non-null rows."""
    vdt = values.dtype
    nvalid = jax.ops.segment_sum(valid.astype(jnp.int64), seg_ids, num_segments=seg_cap)
    if op == "count":
        return nvalid, jnp.ones(seg_cap, jnp.bool_)
    if op == "count_all":
        ones = jnp.ones_like(seg_ids, dtype=jnp.int64)
        return jax.ops.segment_sum(ones, seg_ids, num_segments=seg_cap), \
            jnp.ones(seg_cap, jnp.bool_)
    if op == "sum":
        masked = jnp.where(valid, values, jnp.zeros_like(values))
        out = jax.ops.segment_sum(masked, seg_ids, num_segments=seg_cap)
        return out, nvalid > 0
    if op == "sumsq":
        masked = jnp.where(valid, values * values, jnp.zeros_like(values))
        out = jax.ops.segment_sum(masked, seg_ids, num_segments=seg_cap)
        return out, nvalid > 0
    if op in ("min", "max"):
        red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
        is_float = np.dtype(vdt) in (np.dtype(np.float32), np.dtype(np.float64))
        if is_float:
            clean, nanf, nonnanf = _float_minmax_prep(op, values, valid)
            out = red(clean, seg_ids, num_segments=seg_cap)
            any_nan = jax.ops.segment_max(nanf.astype(jnp.int32), seg_ids,
                                          num_segments=seg_cap) > 0
            any_nonnan = jax.ops.segment_max(nonnanf.astype(jnp.int32), seg_ids,
                                             num_segments=seg_cap) > 0
            return _float_minmax_patch(op, out, any_nan, any_nonnan), nvalid > 0
        init = (_MIN_INIT if op == "min" else _MAX_INIT)[np.dtype(vdt)]
        masked = jnp.where(valid, values, jnp.full_like(values, init))
        out = red(masked, seg_ids, num_segments=seg_cap)
        return out, nvalid > 0
    if op in ("first", "last"):
        # position of first/last valid row per segment
        n = values.shape[0]
        pos = jnp.arange(n, dtype=jnp.int64)
        if op == "first":
            masked_pos = jnp.where(valid, pos, n)
            sel = jax.ops.segment_min(masked_pos, seg_ids, num_segments=seg_cap)
        else:
            masked_pos = jnp.where(valid, pos, -1)
            sel = jax.ops.segment_max(masked_pos, seg_ids, num_segments=seg_cap)
        has = (sel >= 0) & (sel < n)
        sel_c = jnp.clip(sel, 0, n - 1).astype(jnp.int32)
        return values[sel_c], has & (nvalid > 0)
    if op == "any":
        masked = jnp.where(valid, values.astype(jnp.bool_), False)
        out = jax.ops.segment_max(masked.astype(jnp.int32), seg_ids, num_segments=seg_cap)
        return out.astype(jnp.bool_), nvalid > 0
    if op == "all":
        masked = jnp.where(valid, values.astype(jnp.bool_), True)
        out = jax.ops.segment_min(masked.astype(jnp.int32), seg_ids, num_segments=seg_cap)
        return out.astype(jnp.bool_), nvalid > 0
    raise ValueError(f"unknown segmented op {op}")


def _invert_float_bits(bits_u64: jax.Array, width: int, vdt):
    """Inverse of kernels._order_float_bits."""
    import jax.lax as lax
    if width == 64:
        sign = jnp.uint64(1 << 63)
        pos = (bits_u64 & sign) != 0
        raw = jnp.where(pos, bits_u64 ^ sign, ~bits_u64)
        # u64 -> f64 via two u32 bitcasts (TPU x64 rewriter limitation)
        lo = (raw & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (raw >> jnp.uint64(32)).astype(jnp.uint32)
        pair = jnp.stack([lo, hi], axis=-1)
        return lax.bitcast_convert_type(pair, jnp.float64)
    sign = jnp.uint64(0x80000000)
    mask = jnp.uint64(0xFFFFFFFF)
    b = bits_u64 & mask
    pos = (b & sign) != 0
    raw = jnp.where(pos, b ^ sign, (~b) & mask)
    return lax.bitcast_convert_type(raw.astype(jnp.uint32), jnp.float32)


def gather_group_keys(key_cols: List[ColumnVector], perm: jax.Array,
                      boundary: jax.Array, n_groups: int, num_rows: int,
                      live=None) -> List[ColumnVector]:
    """Representative key row per group = first sorted row of each segment.
    Sync-free: compacts boundary positions at full capacity (callers carry
    the true group count, possibly lazily). `live` is the SOURCE batch's
    selection mask — without it a masked batch's live rows past the live
    COUNT would gather as null (positional validity_or_default is only
    valid for front-packed batches)."""
    cap = boundary.shape[0]
    first_idx = K._compact_indices(boundary, cap, cap)
    out = []
    for c in key_cols:
        sorted_col = K.gather_column(c, perm, num_rows, src_live=live)
        out.append(K.gather_column(sorted_col, first_idx, num_rows))
    return out
