"""Core device kernels: hashing, normalization, sort, compaction, gather,
concat, and join candidate expansion.

Reference parity: the libcudf Table algebra surface enumerated in SURVEY.md
§2.9.1 (join gather-maps, groupby agg, sort/OrderByArg, filter, gather,
concat, slice) and jni.Hash (Spark-compatible murmur3/xxhash64).

TPU-first design: everything here is shape-static and branch-free so XLA can
tile it onto the VPU/MXU. Dynamic-result ops (filter, join) follow the
count-then-gather discipline: a jitted counting pass, a host readback of one
scalar, then a jitted gather pass compiled per output-capacity bucket
(the JoinGatherer analog from SURVEY.md §7.3.1).
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector, ColumnarBatch, round_capacity

# ---------------------------------------------------------------------------
# Spark-compatible Murmur3 (x86_32, seed 42) -- reference jni.Hash murmur3.
# Matching Spark's hash exactly means a future live-Spark adapter places rows
# exactly where CPU Spark would for hash partitioning.
# ---------------------------------------------------------------------------

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
SPARK_MURMUR3_SEED = 42


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mm3_mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    return k1 * _C2


def _mm3_mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _mm3_fmix(h1, length):
    h1 = h1 ^ length.astype(jnp.uint32) if hasattr(length, "astype") else h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * np.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_int32(values: jax.Array, seed: jax.Array) -> jax.Array:
    """Murmur3 of an int32 plane (Spark hashInt)."""
    k1 = _mm3_mix_k1(values.astype(jnp.uint32))
    h1 = _mm3_mix_h1(seed.astype(jnp.uint32), k1)
    return _mm3_fmix(h1, 4)


def murmur3_int64(values: jax.Array, seed: jax.Array) -> jax.Array:
    """Murmur3 of an int64 plane (Spark hashLong: low word then high word)."""
    v = values.astype(jnp.uint64)
    low = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (v >> jnp.uint64(32)).astype(jnp.uint32)
    h1 = seed.astype(jnp.uint32)
    h1 = _mm3_mix_h1(h1, _mm3_mix_k1(low))
    h1 = _mm3_mix_h1(h1, _mm3_mix_k1(high))
    return _mm3_fmix(h1, 8)


def murmur3_bytes(offsets: jax.Array, raw: jax.Array, seed: jax.Array) -> jax.Array:
    """Per-row Murmur3 over variable-length byte slices (Spark
    hashUnsafeBytes over UTF8 payloads): 4-byte little-endian words for the
    aligned prefix, then each trailing byte mixed individually as a
    sign-extended int. Variable trip count handled with a lax.while_loop over
    the batch max length; shorter rows mask out (branch-free)."""
    cap = offsets.shape[0] - 1
    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    nbytes = raw.shape[0]

    def byte_at(pos):
        idx = jnp.clip(pos, 0, nbytes - 1)
        return raw[idx]

    def word_body(state):
        i, h1 = state
        pos = starts + 4 * i
        b0 = byte_at(pos).astype(jnp.uint32)
        b1 = byte_at(pos + 1).astype(jnp.uint32)
        b2 = byte_at(pos + 2).astype(jnp.uint32)
        b3 = byte_at(pos + 3).astype(jnp.uint32)
        k1 = b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
        mixed = _mm3_mix_h1(h1, _mm3_mix_k1(k1))
        active = (i + 1) * 4 <= lens
        return i + 1, jnp.where(active, mixed, h1)

    def word_cond(state):
        i, _ = state
        return (i + 1) * 4 <= jnp.max(lens)

    h0 = jnp.broadcast_to(seed.astype(jnp.uint32), (cap,))
    _, h1 = lax.while_loop(word_cond, word_body, (jnp.int32(0), h0))

    aligned = lens - (lens % 4)
    for j in range(3):
        pos = starts + aligned + j
        active = aligned + j < lens
        b = byte_at(pos).astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        mixed = _mm3_mix_h1(h1, _mm3_mix_k1(b))
        h1 = jnp.where(active, mixed, h1)
    return _mm3_fmix(h1, lens)


def spark_hash_column(col: ColumnVector, num_rows: int, seed: jax.Array) -> jax.Array:
    """Spark Murmur3Hash semantics per type: null fields pass the running
    seed through unchanged."""
    d = col.dtype
    if isinstance(d, T.StringType):
        h = murmur3_bytes(col.data["offsets"], col.data["bytes"], seed)
    elif isinstance(d, T.BooleanType):
        h = murmur3_int32(col.data.astype(jnp.int32), seed)
    elif isinstance(d, (T.Int8Type, T.Int16Type, T.Int32Type, T.DateType)):
        h = murmur3_int32(col.data.astype(jnp.int32), seed)
    elif isinstance(d, T.Float32Type):
        v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data), col.data)  # -0.0 -> +0.0
        h = murmur3_int32(lax.bitcast_convert_type(v, jnp.int32), seed)
    elif isinstance(d, T.Float64Type):
        v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data), col.data)
        h = murmur3_int64(lax.bitcast_convert_type(v, jnp.int64), seed)
    else:  # int64, timestamp, decimal64
        h = murmur3_int64(col.data.astype(jnp.int64), seed)
    valid = col.validity_or_default(num_rows)
    if seed.ndim == 0:
        seed = jnp.broadcast_to(seed, h.shape)
    return jnp.where(valid, h, seed.astype(jnp.uint32))


def spark_murmur3_batch(cols: Sequence[ColumnVector], num_rows: int,
                        seed: int = SPARK_MURMUR3_SEED) -> jax.Array:
    """Chained per-row hash over columns = Spark Murmur3Hash(cols, 42)."""
    cap = cols[0].capacity
    h = jnp.full((cap,), np.uint32(seed))
    for c in cols:
        h = spark_hash_column(c, num_rows, h)
    return h.astype(jnp.int32)


# -- xxhash64 (reference jni.Hash.xxhash64) ---------------------------------

_XXP1 = np.uint64(0x9E3779B185EBCA87)
_XXP2 = np.uint64(0xC2B2AE3D27D4EB4F)
_XXP3 = np.uint64(0x165667B19E3779F9)
_XXP5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(x, r):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def xxhash64_int64(values: jax.Array, seed: int = 42) -> jax.Array:
    v = values.astype(jnp.uint64)
    h = np.uint64(seed) + _XXP5 + np.uint64(8)
    k1 = _rotl64(v * _XXP2, 31) * _XXP1
    h = h ^ k1
    h = _rotl64(h, 27) * _XXP1 + np.uint64(0x85EBCA77C2B2AE63)
    h = (h ^ (h >> np.uint64(33))) * _XXP2
    h = (h ^ (h >> np.uint64(29))) * _XXP3
    return (h ^ (h >> np.uint64(32))).astype(jnp.int64)


# ---------------------------------------------------------------------------
# Key normalization: map a column to an order-preserving uint64 plane so
# sorts/joins/groupbys work on uniform fixed-width lanes.
# ---------------------------------------------------------------------------

_SIGN64 = np.uint64(0x8000000000000000)


def normalize_key(col: ColumnVector, num_rows: int,
                  for_order: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Returns (key_u64, null_flags). Key order matches value order for all
    fixed-width types. Strings get a 64-bit double-hash of the bytes:
    equality-faithful up to astronomically-unlikely collisions, NOT
    order-faithful (string ORDER BY uses the host sort path)."""
    d = col.dtype
    valid = col.validity_or_default(num_rows)
    if isinstance(d, T.StringType):
        if for_order:
            raise NotImplementedError("device string ordering; use host sort")
        h1 = murmur3_bytes(col.data["offsets"], col.data["bytes"], jnp.uint32(0x12345671))
        h2 = murmur3_bytes(col.data["offsets"], col.data["bytes"], jnp.uint32(0x89ABCDE3))
        key = (h1.astype(jnp.uint64) << jnp.uint64(32)) | h2.astype(jnp.uint64)
    elif isinstance(d, T.BooleanType):
        key = col.data.astype(jnp.uint64)
    elif isinstance(d, T.Float32Type):
        v = jnp.where(jnp.isnan(col.data), jnp.float32(np.nan), col.data)
        v = jnp.where(v == 0.0, jnp.zeros_like(v), v)
        key = _order_float_bits(lax.bitcast_convert_type(v, jnp.int32).astype(jnp.int64), 32)
    elif isinstance(d, T.Float64Type):
        v = jnp.where(jnp.isnan(col.data), jnp.float64(np.nan), col.data)
        v = jnp.where(v == 0.0, jnp.zeros_like(v), v)
        key = _order_float_bits(lax.bitcast_convert_type(v, jnp.int64), 64)
    else:
        key = col.data.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64
    key = jnp.where(valid, key, jnp.uint64(0))
    return key, ~valid


def _order_float_bits(bits: jax.Array, width: int) -> jax.Array:
    """IEEE total-order transform: negatives flip all bits, positives flip
    the sign bit. NaN (canonicalized, positive payload) sorts above +inf,
    matching Spark's NaN ordering."""
    u = bits.astype(jnp.uint64)
    if width == 32:
        mask = jnp.uint64(0xFFFFFFFF)
        sign = jnp.uint64(0x80000000)
        u = u & mask
        neg = (u & sign) != 0
        return jnp.where(neg, (~u) & mask, u | sign)
    neg = (u & _SIGN64) != 0
    return jnp.where(neg, ~u, u | _SIGN64)


# ---------------------------------------------------------------------------
# Sort / argsort (reference cudf OrderByArg sort)
# ---------------------------------------------------------------------------

def lexsort_indices(keys: List[Tuple[jax.Array, jax.Array, bool, bool]],
                    num_rows: int) -> jax.Array:
    """Stable lexicographic argsort. keys = [(key_u64, null_flags, ascending,
    nulls_first)]. Padded rows (>= num_rows) sort to the very end. Returns an
    int32 permutation of the full capacity."""
    cap = keys[0][0].shape[0]
    operands: List[jax.Array] = []
    in_range = jnp.arange(cap) < num_rows
    operands.append(jnp.where(in_range, 0, 1).astype(jnp.uint8))
    for key, nulls, asc, nulls_first in keys:
        # null-ordering plane: 0 sorts before 1
        null_rank = jnp.uint8(0) if nulls_first else jnp.uint8(1)
        val_rank = jnp.uint8(1) if nulls_first else jnp.uint8(0)
        operands.append(jnp.where(nulls, null_rank, val_rank))
        operands.append(key if asc else ~key)
    iota = jnp.arange(cap, dtype=jnp.int32)
    out = lax.sort(tuple(operands) + (iota,), num_keys=len(operands), is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# Gather (reference GatherMap + OutOfBoundsPolicy.NULLIFY)
# ---------------------------------------------------------------------------

def gather_column(col: ColumnVector, indices: jax.Array, src_rows: int) -> ColumnVector:
    """Row gather of one column. indices: int32[out_cap]; -1 emits null."""
    oob = indices < 0
    safe = jnp.clip(indices, 0, col.capacity - 1)
    src_valid = col.validity_or_default(src_rows)
    valid = src_valid[safe] & ~oob
    if col.is_string:
        offsets = col.data["offsets"]
        raw = col.data["bytes"]
        lens = (offsets[1:] - offsets[:-1])[safe]
        lens = jnp.where(valid, lens, 0)
        new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                   jnp.cumsum(lens).astype(jnp.int32)])
        out_bytes = _gather_string_bytes(raw, offsets, safe, new_off)
        data = {"offsets": new_off, "bytes": out_bytes}
    else:
        data = col.data[safe]
    return ColumnVector(col.dtype, data, valid)


def _gather_string_bytes(raw, offsets, row_idx, new_off):
    """For each output byte b: output row = searchsorted(new_off, b), source
    byte = src_start + (b - out_start). Output byte plane keeps the source
    byte capacity (gather never grows payload)."""
    nbytes = raw.shape[0]
    b = jnp.arange(nbytes, dtype=jnp.int32)
    row = jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1
    row = jnp.clip(row, 0, row_idx.shape[0] - 1)
    src_row = row_idx[row]
    src = offsets[src_row] + (b - new_off[row])
    src = jnp.clip(src, 0, nbytes - 1)
    return jnp.where(b < new_off[-1], raw[src], 0).astype(jnp.uint8)


def gather_batch(batch: ColumnarBatch, indices: jax.Array, out_rows: int) -> ColumnarBatch:
    cols = [gather_column(c, indices, batch.num_rows) for c in batch.columns]
    return ColumnarBatch(cols, out_rows)


# ---------------------------------------------------------------------------
# Filter: count-then-gather compaction
# ---------------------------------------------------------------------------

@jax.jit
def _count_true(mask: jax.Array, num_rows) -> jax.Array:
    cap = mask.shape[0]
    return jnp.sum((mask & (jnp.arange(cap) < num_rows)).astype(jnp.int32))


@partial(jax.jit, static_argnums=(2,))
def _compact_indices(mask: jax.Array, num_rows, out_cap: int) -> jax.Array:
    cap = mask.shape[0]
    mask = mask & (jnp.arange(cap) < num_rows)
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    scatter_to = jnp.where(mask, pos, out_cap)  # non-selected drop
    out = jnp.full(out_cap + 1, -1, jnp.int32)
    out = out.at[scatter_to].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
    return out[:out_cap]


def filter_indices(mask: jax.Array, num_rows: int) -> Tuple[jax.Array, int]:
    """mask: bool[capacity]. One device->host scalar readback for the count
    (the price of a dynamic result size; paid per batch, not per element)."""
    count = int(_count_true(mask, num_rows))
    out_cap = round_capacity(max(count, 1))
    return _compact_indices(mask, num_rows, out_cap), count


def filter_batch(batch: ColumnarBatch, mask: jax.Array) -> ColumnarBatch:
    idx, count = filter_indices(mask, batch.num_rows)
    return gather_batch(batch, idx, count)


# ---------------------------------------------------------------------------
# Slice / concat (reference cudf Table.concatenate / contiguous split)
# ---------------------------------------------------------------------------

def slice_batch(batch: ColumnarBatch, start: int, length: int) -> ColumnarBatch:
    out_cap = round_capacity(max(length, 1))
    idx = jnp.arange(out_cap, dtype=jnp.int32) + start
    idx = jnp.where(jnp.arange(out_cap) < length, idx, -1)
    return gather_batch(batch, idx, length)


def concat_batches(batches: List[ColumnarBatch]) -> ColumnarBatch:
    nonempty = [b for b in batches if b.num_rows > 0]
    if not nonempty:
        return batches[0]
    if len(nonempty) == 1:
        return nonempty[0]
    total = sum(b.num_rows for b in nonempty)
    cap = round_capacity(total)
    out_cols = []
    for ci in range(nonempty[0].num_cols):
        cols = [b.columns[ci] for b in nonempty]
        rows = [b.num_rows for b in nonempty]
        out_cols.append(_concat_columns(cols, rows, cap))
    return ColumnarBatch(out_cols, total)


def _concat_columns(cols: List[ColumnVector], rows: List[int], cap: int) -> ColumnVector:
    dtype = cols[0].dtype
    validity = jnp.concatenate([c.validity_or_default(r)[:r] for c, r in zip(cols, rows)])
    pad = cap - validity.shape[0]
    if pad > 0:
        validity = jnp.concatenate([validity, jnp.zeros(pad, jnp.bool_)])

    if isinstance(dtype, T.StringType):
        # Host readback of per-part byte lengths keeps destination offsets
        # static; concat happens between batches, off the jitted hot path.
        byte_lens = [int(np.asarray(c.data["offsets"][r])) for c, r in zip(cols, rows)]
        total_bytes = sum(byte_lens)
        out_byte_cap = round_capacity(max(total_bytes, 1))
        out_bytes = jnp.zeros(out_byte_cap, jnp.uint8)
        off_parts = [jnp.zeros(1, jnp.int32)]
        base_rows = 0
        base_bytes = 0
        for c, r, blen in zip(cols, rows, byte_lens):
            o = c.data["offsets"]
            off_parts.append(o[1: r + 1].astype(jnp.int32) + np.int32(base_bytes))
            src = c.data["bytes"]
            part_cap = src.shape[0]
            dest = jnp.where(jnp.arange(part_cap) < blen,
                             base_bytes + jnp.arange(part_cap), out_byte_cap)
            out_bytes = out_bytes.at[dest].set(src, mode="drop")
            base_rows += r
            base_bytes += blen
        offsets = jnp.concatenate(off_parts)
        opad = cap + 1 - offsets.shape[0]
        if opad > 0:
            offsets = jnp.concatenate([offsets, jnp.broadcast_to(offsets[-1:], (opad,))])
        return ColumnVector(dtype, {"offsets": offsets, "bytes": out_bytes}, validity)

    merged = jnp.concatenate([c.data[:r] for c, r in zip(cols, rows)])
    if cap - merged.shape[0] > 0:
        merged = jnp.concatenate([merged, jnp.zeros(cap - merged.shape[0], merged.dtype)])
    return ColumnVector(dtype, merged, validity)


# ---------------------------------------------------------------------------
# Join candidate expansion (count-then-gather; the JoinGatherer analog)
# ---------------------------------------------------------------------------

def expand_ranges(lo: jax.Array, hi: jax.Array, total: int) -> Tuple[jax.Array, jax.Array]:
    """Given per-probe candidate ranges [lo_i, hi_i) into a sorted build side,
    emit flat (probe_idx, build_pos) pairs. total = sum(hi-lo), a host scalar.
    Tail entries (>= total) are -1."""
    out_cap = round_capacity(max(total, 1))
    counts = (hi - lo).astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    r = jnp.arange(out_cap, dtype=jnp.int32)
    probe = jnp.searchsorted(offsets, r, side="right").astype(jnp.int32) - 1
    probe = jnp.clip(probe, 0, lo.shape[0] - 1)
    pos = lo[probe] + (r - offsets[probe])
    in_range = r < total
    return jnp.where(in_range, probe, -1), jnp.where(in_range, pos, -1)
