"""Core device kernels: hashing, normalization, sort, compaction, gather,
concat, and join candidate expansion.

Reference parity: the libcudf Table algebra surface enumerated in SURVEY.md
§2.9.1 (join gather-maps, groupby agg, sort/OrderByArg, filter, gather,
concat, slice) and jni.Hash (Spark-compatible murmur3/xxhash64).

TPU-first design: everything here is shape-static and branch-free so XLA can
tile it onto the VPU/MXU. Dynamic-result ops (filter, join) follow the
count-then-gather discipline: a jitted counting pass, a host readback of one
scalar, then a jitted gather pass compiled per output-capacity bucket
(the JoinGatherer analog from SURVEY.md §7.3.1).
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (
    ColumnVector, ColumnarBatch, LazyRowCount, materialize_counts,
    round_capacity, traced_rows,
)
from spark_rapids_tpu.runtime import compile_cache as _cc

# ---------------------------------------------------------------------------
# Spark-compatible Murmur3 (x86_32, seed 42) -- reference jni.Hash murmur3.
# Matching Spark's hash exactly means a future live-Spark adapter places rows
# exactly where CPU Spark would for hash partitioning.
# ---------------------------------------------------------------------------

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
SPARK_MURMUR3_SEED = 42


def _rotl32(x, r):
    return (x << r) | (x >> (32 - r))


def _mm3_mix_k1(k1):
    k1 = k1 * _C1
    k1 = _rotl32(k1, 15)
    return k1 * _C2


def _mm3_mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def _mm3_fmix(h1, length):
    h1 = h1 ^ length.astype(jnp.uint32) if hasattr(length, "astype") else h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * np.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> 16)


def murmur3_int32(values: jax.Array, seed: jax.Array) -> jax.Array:
    """Murmur3 of an int32 plane (Spark hashInt). Block-aligned planes
    take the hand-tiled Pallas kernel (ops/pallas_kernels.py); the lax
    chain below is the reference twin and the small-plane path."""
    from spark_rapids_tpu.ops import pallas_kernels as PK
    if PK.enabled() and PK.pallas_supported(values.shape[0]) \
            and getattr(seed, "ndim", 1) == 0:
        return PK.murmur3_int32_pallas(values, seed)
    k1 = _mm3_mix_k1(values.astype(jnp.uint32))
    h1 = _mm3_mix_h1(seed.astype(jnp.uint32), k1)
    return _mm3_fmix(h1, 4)


def murmur3_int64(values: jax.Array, seed: jax.Array) -> jax.Array:
    """Murmur3 of an int64 plane (Spark hashLong: low word then high word)."""
    v = values.astype(jnp.uint64)
    low = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (v >> jnp.uint64(32)).astype(jnp.uint32)
    h1 = seed.astype(jnp.uint32)
    h1 = _mm3_mix_h1(h1, _mm3_mix_k1(low))
    h1 = _mm3_mix_h1(h1, _mm3_mix_k1(high))
    return _mm3_fmix(h1, 8)


def murmur3_bytes(offsets: jax.Array, raw: jax.Array, seed: jax.Array) -> jax.Array:
    """Per-row Murmur3 over variable-length byte slices (Spark
    hashUnsafeBytes over UTF8 payloads): 4-byte little-endian words for the
    aligned prefix, then each trailing byte mixed individually as a
    sign-extended int. Variable trip count handled with a lax.while_loop over
    the batch max length; shorter rows mask out (branch-free)."""
    cap = offsets.shape[0] - 1
    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    nbytes = raw.shape[0]

    def byte_at(pos):
        idx = jnp.clip(pos, 0, nbytes - 1)
        return raw[idx]

    def word_body(state):
        i, h1 = state
        pos = starts + 4 * i
        b0 = byte_at(pos).astype(jnp.uint32)
        b1 = byte_at(pos + 1).astype(jnp.uint32)
        b2 = byte_at(pos + 2).astype(jnp.uint32)
        b3 = byte_at(pos + 3).astype(jnp.uint32)
        k1 = b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)
        mixed = _mm3_mix_h1(h1, _mm3_mix_k1(k1))
        active = (i + 1) * 4 <= lens
        return i + 1, jnp.where(active, mixed, h1)

    def word_cond(state):
        i, _ = state
        return (i + 1) * 4 <= jnp.max(lens)

    h0 = jnp.broadcast_to(seed.astype(jnp.uint32), (cap,))
    _, h1 = lax.while_loop(word_cond, word_body, (jnp.int32(0), h0))

    aligned = lens - (lens % 4)
    for j in range(3):
        pos = starts + aligned + j
        active = aligned + j < lens
        b = byte_at(pos).astype(jnp.int8).astype(jnp.int32).astype(jnp.uint32)
        mixed = _mm3_mix_h1(h1, _mm3_mix_k1(b))
        h1 = jnp.where(active, mixed, h1)
    return _mm3_fmix(h1, lens)


def spark_hash_column(col: ColumnVector, num_rows: int, seed: jax.Array,
                      live=None) -> jax.Array:
    """Spark Murmur3Hash semantics per type: null fields pass the running
    seed through unchanged."""
    d = col.dtype
    if col.is_dict:
        # hash the (small) vocab once, then gather by code; per-row seeds
        # force the general path (vocab hash is seed-independent only for
        # scalar seeds)
        if seed.ndim == 0:
            vh = murmur3_bytes(col.data["dict_offsets"], col.data["dict_bytes"], seed)
            h = vh[col.data["codes"]]
        else:
            flat = flatten_dict_column(col, num_rows)
            h = murmur3_bytes(flat.data["offsets"], flat.data["bytes"], seed)
    elif isinstance(d, T.StringType):
        h = murmur3_bytes(col.data["offsets"], col.data["bytes"], seed)
    elif isinstance(d, T.BooleanType):
        h = murmur3_int32(col.data.astype(jnp.int32), seed)
    elif isinstance(d, (T.Int8Type, T.Int16Type, T.Int32Type, T.DateType)):
        h = murmur3_int32(col.data.astype(jnp.int32), seed)
    elif isinstance(d, T.Float32Type):
        v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data), col.data)  # -0.0 -> +0.0
        h = murmur3_int32(lax.bitcast_convert_type(v, jnp.int32), seed)
    elif isinstance(d, T.Float64Type):
        v = jnp.where(col.data == 0.0, jnp.zeros_like(col.data), col.data)
        h = murmur3_int64(_bitcast_f64_u64(v).astype(jnp.int64), seed)
    else:  # int64, timestamp, decimal64
        h = murmur3_int64(col.data.astype(jnp.int64), seed)
    if live is not None:
        valid = live if col.validity is None else (col.validity & live)
    else:
        valid = col.validity_or_default(num_rows)
    if seed.ndim == 0:
        seed = jnp.broadcast_to(seed, h.shape)
    return jnp.where(valid, h, seed.astype(jnp.uint32))


def spark_murmur3_batch(cols: Sequence[ColumnVector], num_rows: int,
                        seed: int = SPARK_MURMUR3_SEED, live=None) -> jax.Array:
    """Chained per-row hash over columns = Spark Murmur3Hash(cols, 42).
    The seed stays SCALAR until the first column hashes it into a
    vector, so a leading dict-string column takes the vocab-lift path
    instead of flattening."""
    h = jnp.uint32(seed)
    for c in cols:
        h = spark_hash_column(c, num_rows, h, live=live)
    if h.ndim == 0:
        h = jnp.full((cols[0].capacity,), h)
    return h.astype(jnp.int32)


def partition_hash_batch(cols: Sequence[ColumnVector], num_rows: int,
                         seed: int = SPARK_MURMUR3_SEED,
                         live=None) -> jax.Array:
    """Exchange/bucket partitioning hash. Spark murmur3 EXCEPT that a
    dict-string column in a non-leading position mixes its vocab-lifted
    entry hash as an int32 instead of flattening the whole column
    (which is bound-limited inside a trace). NOT Spark-hash-compatible
    for that one case — use only where the hash picks a partition and
    is never user-visible (the reference has the same freedom in its
    internal GpuHashPartitioning)."""
    h = jnp.uint32(seed)
    for c in cols:
        if c.is_dict and h.ndim != 0:
            vh = murmur3_bytes(c.data["dict_offsets"], c.data["dict_bytes"],
                               jnp.uint32(SPARK_MURMUR3_SEED))
            lifted = ColumnVector(
                T.INT32, vh[c.data["codes"]].astype(jnp.int32), c.validity)
            h = spark_hash_column(lifted, num_rows, h, live=live)
        else:
            h = spark_hash_column(c, num_rows, h, live=live)
    if h.ndim == 0:
        h = jnp.full((cols[0].capacity,), h)
    return h.astype(jnp.int32)


# -- xxhash64 (reference jni.Hash.xxhash64) ---------------------------------

_XXP1 = np.uint64(0x9E3779B185EBCA87)
_XXP2 = np.uint64(0xC2B2AE3D27D4EB4F)
_XXP3 = np.uint64(0x165667B19E3779F9)
_XXP5 = np.uint64(0x27D4EB2F165667C5)


def _rotl64(x, r):
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


_XXP4 = np.uint64(0x85EBCA77C2B2AE63)


def _xx_avalanche(h):
    h = (h ^ (h >> np.uint64(33))) * _XXP2
    h = (h ^ (h >> np.uint64(29))) * _XXP3
    return h ^ (h >> np.uint64(32))


def xxhash64_int64(values: jax.Array, seed=42) -> jax.Array:
    """XXH64.hashLong: seed may be a scalar or a per-row uint64 vector
    (Spark chains column hashes through the seed)."""
    v = values.astype(jnp.uint64)
    seed = seed.astype(jnp.uint64) if hasattr(seed, "astype")         else np.uint64(seed)
    h = seed + _XXP5 + np.uint64(8)
    k1 = _rotl64(v * _XXP2, 31) * _XXP1
    h = h ^ k1
    h = _rotl64(h, 27) * _XXP1 + _XXP4
    return _xx_avalanche(h).astype(jnp.int64)


def xxhash64_int32(values: jax.Array, seed=42) -> jax.Array:
    """XXH64.hashInt (Spark uses it for <= 4-byte fixed types)."""
    v = values.astype(jnp.int32).astype(jnp.uint32).astype(jnp.uint64)
    seed = seed.astype(jnp.uint64) if hasattr(seed, "astype")         else np.uint64(seed)
    h = seed + _XXP5 + np.uint64(4)
    h = h ^ (v * _XXP1)
    h = _rotl64(h, 23) * _XXP2 + _XXP3
    return _xx_avalanche(h).astype(jnp.int64)


# ---------------------------------------------------------------------------
# Key normalization: map a column to an order-preserving uint64 plane so
# sorts/joins/groupbys work on uniform fixed-width lanes.
# ---------------------------------------------------------------------------

_SIGN64 = np.uint64(0x8000000000000000)


def normalize_key(col: ColumnVector, num_rows: int,
                  for_order: bool = False, live=None) -> Tuple[jax.Array, jax.Array]:
    """Returns (key_u64, null_flags). Key order matches value order for all
    fixed-width types. Strings get a 64-bit double-hash of the bytes:
    equality-faithful up to astronomically-unlikely collisions, NOT
    order-faithful (string ORDER BY uses the host sort path)."""
    d = col.dtype
    if live is not None:
        valid = live if col.validity is None else (col.validity & live)
    else:
        valid = col.validity_or_default(num_rows)
    if col.is_dict:
        if for_order:
            raise NotImplementedError("device string ordering; use host sort")
        vh1 = murmur3_bytes(col.data["dict_offsets"], col.data["dict_bytes"],
                            jnp.uint32(0x12345671))
        vh2 = murmur3_bytes(col.data["dict_offsets"], col.data["dict_bytes"],
                            jnp.uint32(0x89ABCDE3))
        vkey = (vh1.astype(jnp.uint64) << jnp.uint64(32)) | vh2.astype(jnp.uint64)
        key = vkey[col.data["codes"]]
    elif isinstance(d, T.StringType):
        if for_order:
            raise NotImplementedError("device string ordering; use host sort")
        h1 = murmur3_bytes(col.data["offsets"], col.data["bytes"], jnp.uint32(0x12345671))
        h2 = murmur3_bytes(col.data["offsets"], col.data["bytes"], jnp.uint32(0x89ABCDE3))
        key = (h1.astype(jnp.uint64) << jnp.uint64(32)) | h2.astype(jnp.uint64)
    elif isinstance(d, T.BooleanType):
        key = col.data.astype(jnp.uint64)
    elif isinstance(d, T.Float32Type):
        v = jnp.where(jnp.isnan(col.data), jnp.float32(np.nan), col.data)
        v = jnp.where(v == 0.0, jnp.zeros_like(v), v)
        key = _order_float_bits(lax.bitcast_convert_type(v, jnp.int32).astype(jnp.int64), 32)
    elif isinstance(d, T.Float64Type):
        v = jnp.where(jnp.isnan(col.data), jnp.float64(np.nan), col.data)
        v = jnp.where(v == 0.0, jnp.zeros_like(v), v)
        key = _order_float_bits(_bitcast_f64_u64(v), 64)
    else:
        key = col.data.astype(jnp.int64).astype(jnp.uint64) ^ _SIGN64
    key = jnp.where(valid, key, jnp.uint64(0))
    return key, ~valid


def string_chunk_count(col: ColumnVector) -> int:
    """Number of 8-byte chunks covering the longest string in the column
    (HOST-side: one device scalar fetch — call at sort boundaries, never
    inside jit). Rounded up to a power of two to bound kernel variants."""
    off = col.data["dict_offsets"] if col.is_dict else col.data["offsets"]
    mx = int(jnp.max(off[1:] - off[:-1]))
    return round_capacity(max(1, -(-mx // 8)), minimum=1)


def string_chunk_keys(col: ColumnVector, num_rows: int, n_chunks: int,
                      live=None) -> List[Tuple[jax.Array, jax.Array]]:
    """EXACT device string ordering: per row, n_chunks u64 keys holding the
    UTF-8 bytes big-endian (zero padded), most-significant chunk first —
    unsigned lexsort over them IS lexicographic byte order (= Spark's
    binary string ordering). Replaces the host string sort; embedded NUL
    bytes tie with end-of-string (documented, vanishingly rare in UTF-8).
    Dict columns build chunk planes over the (small) vocab once and gather
    by code."""
    if live is not None:
        valid = live if col.validity is None else (col.validity & live)
    else:
        valid = col.validity_or_default(num_rows)
    nulls = ~valid
    if col.is_dict:
        off, raw = col.data["dict_offsets"], col.data["dict_bytes"]
    else:
        off, raw = col.data["offsets"], col.data["bytes"]
    starts = off[:-1].astype(jnp.int32)
    ends = off[1:].astype(jnp.int32)
    nbytes = raw.shape[0]
    out = []
    for j in range(n_chunks):
        pos = starts[:, None] + 8 * j + jnp.arange(8, dtype=jnp.int32)[None, :]
        b = jnp.where(pos < ends[:, None],
                      raw[jnp.clip(pos, 0, nbytes - 1)], 0).astype(jnp.uint64)
        shifts = jnp.uint64(8) * (jnp.uint64(7) - jnp.arange(8, dtype=jnp.uint64))
        key = jnp.sum(b << shifts[None, :], axis=1)
        if col.is_dict:
            key = key[col.data["codes"]]
        out.append((key, nulls))
    return out


def _frexp_arith(a: jax.Array):
    """(m, e) with a = m * 2^e, m in [1, 2), for positive normal a —
    computed with comparisons and exact power-of-two multiplies only.
    jnp.frexp internally does a 64-bit bitcast-convert, which the TPU x64
    rewriter cannot lower; this binary-search normalization avoids it.
    Zero/inf/NaN inputs produce garbage m/e that callers mask out."""
    x = a
    e = jnp.zeros(a.shape, jnp.int32)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        up = np.float64(2.0) ** k
        c = x >= up
        x = jnp.where(c, x * np.float64(2.0) ** (-k), x)
        e = e + jnp.where(c, k, 0)
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        up = np.float64(2.0) ** k
        c = (x < 1.0) & (x * up < 2.0)
        x = jnp.where(c, x * up, x)
        e = e - jnp.where(c, k, 0)
    return x, e


def _bitcast_f64_u64(v: jax.Array) -> jax.Array:
    """IEEE-754 f64 bit pattern as u64, ARITHMETICALLY — the TPU x64
    rewriter cannot lower any 64-bit bitcast-convert, so the bits are
    reconstructed by exponent normalization. On backends with true IEEE
    f64 (the CPU simulator) this is bit-exact and matches
    java.lang.Double.doubleToLongBits (canonical NaN), which Spark's
    murmur3 hashes. On TPUs whose x64 mode emulates f64 with f32 pairs
    (~48-bit mantissa, f32 exponent range — upload of |v|>~3.4e38 is
    already inf), exactness vs host f64 is unattainable by ANY function;
    the contract is instead consistency with DEVICE f64 semantics, which
    this construction satisfies: verified on v5e over random samples +
    specials that key order and key equality agree exactly with the
    device's own f64 comparisons (see docs/compatibility.md)."""
    nan = jnp.isnan(v)
    pinf = v == jnp.inf
    ninf = v == -jnp.inf
    zero = v == 0.0
    # sign via compare, not jnp.signbit (which bitcasts internally); -0.0
    # is normalized to +0.0 by callers (Spark normalizes it before hashing)
    sign = jnp.where(v < 0.0, jnp.uint64(1) << jnp.uint64(63), jnp.uint64(0))
    a = jnp.abs(v)
    m, e = _frexp_arith(a)  # a = m * 2^e, m in [1, 2)
    biased = (e + 1023).astype(jnp.int64)
    normal = biased > 0
    mant = (m * np.float64(2.0 ** 52)).astype(jnp.uint64)  # [2^52, 2^53)
    norm_bits = (jnp.where(normal, biased, 0).astype(jnp.uint64)
                 << jnp.uint64(52)) | (mant & ((jnp.uint64(1) << jnp.uint64(52)) - jnp.uint64(1)))
    # Subnormals: XLA flushes them to zero in f64 arithmetic on both the
    # TPU emulation and the CPU backend (FTZ), so they hash/compare as
    # +/-0 here — consistent with every other op in the engine, divergent
    # from Spark CPU only for exact-subnormal inputs (documented incompat,
    # reference keeps a similar float incompat list).
    mag = jnp.where(normal, norm_bits, jnp.uint64(0))
    mag = jnp.where(zero, jnp.uint64(0), mag)
    mag = jnp.where(pinf | ninf, jnp.uint64(0x7FF0000000000000), mag)
    mag = jnp.where(nan, jnp.uint64(0x7FF8000000000000), mag)
    return sign | mag


def _order_float_bits(bits: jax.Array, width: int) -> jax.Array:
    """IEEE total-order transform: negatives flip all bits, positives flip
    the sign bit. NaN (canonicalized, positive payload) sorts above +inf,
    matching Spark's NaN ordering."""
    u = bits.astype(jnp.uint64)
    if width == 32:
        mask = jnp.uint64(0xFFFFFFFF)
        sign = jnp.uint64(0x80000000)
        u = u & mask
        neg = (u & sign) != 0
        return jnp.where(neg, (~u) & mask, u | sign)
    neg = (u & _SIGN64) != 0
    return jnp.where(neg, ~u, u | _SIGN64)


# ---------------------------------------------------------------------------
# Sort / argsort (reference cudf OrderByArg sort)
# ---------------------------------------------------------------------------

def lexsort_indices(keys: List[Tuple[jax.Array, jax.Array, bool, bool]],
                    num_rows: int, live=None) -> jax.Array:
    """Stable lexicographic argsort. keys = [(key_u64, null_flags, ascending,
    nulls_first)]. Dead rows (mask False / >= num_rows) sort to the very
    end. Returns an int32 permutation of the full capacity."""
    cap = keys[0][0].shape[0]
    operands: List[jax.Array] = []
    in_range = live if live is not None else (jnp.arange(cap) < num_rows)
    operands.append(jnp.where(in_range, 0, 1).astype(jnp.uint8))
    for key, nulls, asc, nulls_first in keys:
        # null-ordering plane: 0 sorts before 1
        null_rank = jnp.uint8(0) if nulls_first else jnp.uint8(1)
        val_rank = jnp.uint8(1) if nulls_first else jnp.uint8(0)
        operands.append(jnp.where(nulls, null_rank, val_rank))
        operands.append(key if asc else ~key)
    iota = jnp.arange(cap, dtype=jnp.int32)
    out = lax.sort(tuple(operands) + (iota,), num_keys=len(operands), is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# Gather (reference GatherMap + OutOfBoundsPolicy.NULLIFY)
# ---------------------------------------------------------------------------

def gather_column(col: ColumnVector, indices: jax.Array, src_rows: int,
                  src_live=None) -> ColumnVector:
    """Row gather of one column. indices: int32[out_cap]; -1 emits null.
    src_live: liveness plane of the source batch (selection mask); dead
    source rows gather as null."""
    oob = indices < 0
    safe = jnp.clip(indices, 0, col.capacity - 1)
    if src_live is not None:
        src_valid = src_live if col.validity is None else (col.validity & src_live)
    else:
        src_valid = col.validity_or_default(src_rows)
    valid = src_valid[safe] & ~oob
    if col.is_string and not col.is_dict:
        # Flat strings gather as an identity-coded dictionary (zero-copy
        # reinterpretation: vocab = the source planes themselves). A
        # byte-plane gather cannot duplicate rows without growing past the
        # static byte capacity — code gather sidesteps that entirely.
        col = flat_string_as_dict(col)
    if col.is_dict:
        # dict strings gather as integer codes; the vocab is shared.
        data = {"codes": col.data["codes"][safe],
                "dict_offsets": col.data["dict_offsets"],
                "dict_bytes": col.data["dict_bytes"]}
        return ColumnVector(col.dtype, data, valid, dict_unique=col.dict_unique)
    if isinstance(col.dtype, T.StructType):
        kids = [gather_column(ch, indices, src_rows, src_live=src_live)
                for ch in col.data["children"]]
        return ColumnVector(col.dtype, {"children": kids}, valid)
    if isinstance(col.dtype, (T.ArrayType, T.MapType)):
        return _gather_list_like(col, safe, valid)
    data = col.data[safe]
    return ColumnVector(col.dtype, data, valid, bounds=col.bounds)


def _gather_list_like(col: ColumnVector, safe: jax.Array, valid: jax.Array
                      ) -> ColumnVector:
    """Gather an array/map column: rebuild offsets from gathered lengths,
    then map each output element back to its source element and gather the
    child planes. Child capacity is preserved — PERMUTING gathers (sort,
    filter compaction, explode passthrough) never grow the element count;
    row-DUPLICATING gathers of nested columns (join payload) are excluded
    by TypeSig until a sized nested gather lands."""
    off = col.data["offsets"]
    out_cap = safe.shape[0]
    lens = jnp.where(valid, (off[1:] - off[:-1])[safe], 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    children = ([("child", col.data["child"])] if "child" in col.data
                else [("keys", col.data["keys"]), ("values", col.data["values"])])
    child_cap = children[0][1].capacity
    e = jnp.arange(child_cap, dtype=jnp.int32)
    orow = jnp.clip(jnp.searchsorted(new_off, e, side="right").astype(jnp.int32) - 1,
                    0, out_cap - 1)
    src_e = off[safe[orow]] + (e - new_off[orow])
    in_range = e < new_off[-1]
    child_idx = jnp.where(in_range, jnp.clip(src_e, 0, child_cap - 1), -1)
    data = {"offsets": new_off}
    for name, ch in children:
        data[name] = gather_column(ch, child_idx, child_cap)
    return ColumnVector(col.dtype, data, valid)


def flat_string_as_dict(col: ColumnVector) -> ColumnVector:
    """Reinterpret a flat offsets+bytes string column as a dictionary
    column with identity codes. Zero-copy: the vocab IS the source planes.
    dict_unique=False (source rows may repeat values). The vocab keeps the
    full source byte plane alive regardless of how few codes survive
    downstream — acceptable: gather outputs share source lifetime anyway."""
    if col.is_dict or not col.is_string:
        return col
    cap = col.capacity
    data = {"codes": jnp.arange(cap, dtype=jnp.int32),
            "dict_offsets": col.data["offsets"],
            "dict_bytes": col.data["bytes"]}
    return ColumnVector(col.dtype, data, col.validity, dict_unique=False)


class LazyGatheredCols:
    """A column list view that gathers source columns by a shared index
    plane ON FIRST ACCESS (memoized). Lambda bodies (expr/hof) and window
    functions (exec/tpu_nodes) evaluate over reindexed row spaces where
    most columns are never read — a 16M-row gather costs ~200ms, so
    laziness is worth real wall-clock, and XLA CSEs the duplicate index
    arithmetic for the columns that ARE read."""

    def __init__(self, cols, indices, num_rows):
        self._cols = cols
        self._idx = indices
        self._rows = num_rows
        self._cache = {}

    def __len__(self):
        return len(self._cols)

    def __getitem__(self, i):
        out = self._cache.get(i)
        if out is None:
            out = gather_column(self._cols[i], self._idx, self._rows)
            self._cache[i] = out
        return out

    def __iter__(self):
        return (self[i] for i in range(len(self._cols)))


def gather_batch(batch: ColumnarBatch, indices: jax.Array, out_rows: int) -> ColumnarBatch:
    live = batch.live_mask() if batch.row_mask is not None else None
    cols = [gather_column(c, indices, batch.num_rows, src_live=live)
            for c in batch.columns]
    return ColumnarBatch(cols, out_rows)


# ---------------------------------------------------------------------------
# Filter: count-then-gather compaction
# ---------------------------------------------------------------------------

@_cc.jit
def _count_true(mask: jax.Array, num_rows) -> jax.Array:
    cap = mask.shape[0]
    return jnp.sum((mask & (jnp.arange(cap) < num_rows)).astype(jnp.int32))


@_cc.jit(static_argnums=(2,))
def _compact_indices(mask: jax.Array, num_rows, out_cap: int) -> jax.Array:
    cap = mask.shape[0]
    mask = mask & (jnp.arange(cap) < num_rows)
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    scatter_to = jnp.where(mask, pos, out_cap)  # non-selected drop
    out = jnp.full(out_cap + 1, -1, jnp.int32)
    out = out.at[scatter_to].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
    return out[:out_cap]


def filter_indices(mask: jax.Array, num_rows: int) -> Tuple[jax.Array, int]:
    """mask: bool[capacity]. One device->host scalar readback for the count
    (the price of a dynamic result size; paid per batch, not per element)."""
    count = int(_count_true(mask, num_rows))
    out_cap = round_capacity(max(count, 1))
    return _compact_indices(mask, num_rows, out_cap), count


def filter_batch(batch: ColumnarBatch, mask: jax.Array) -> ColumnarBatch:
    idx, count = filter_indices(mask, batch.num_rows)
    return gather_batch(batch, idx, count)


def mask_filter_batch(batch: ColumnarBatch, pred_mask: jax.Array) -> ColumnarBatch:
    """The hot-path filter: NO gather, NO host sync. Survivors are marked in
    a selection mask (row_mask); the count stays on device as a
    LazyRowCount. The reference's GpuFilterExec compacts eagerly with a
    cudf kernel and a stream sync — on TPU a full-size gather costs more
    than every downstream op combined, while a mask fuses into them."""
    live = batch.live_mask() & pred_mask
    count = jnp.sum(live.astype(jnp.int32))
    return ColumnarBatch(batch.columns, LazyRowCount(count), live)


def compact_batch(batch: ColumnarBatch) -> ColumnarBatch:
    """Gather live rows to the front and drop the selection mask (for
    consumers that need contiguous rows: sort output, host hand-off,
    not-yet-mask-aware operators). Costs one count sync + one gather."""
    if batch.row_mask is None:
        return shrink_batch(batch)
    n = int(batch.num_rows)
    out_cap = round_capacity(n)
    idx = _compact_indices(batch.row_mask, batch.capacity, out_cap)
    out = gather_batch(batch, idx, n)
    return ColumnarBatch(out.columns, n)


# ---------------------------------------------------------------------------
# Slice / concat (reference cudf Table.concatenate / contiguous split)
# ---------------------------------------------------------------------------

@_cc.jit(static_argnums=(1,))
def _shrink_gather(batch, new_cap: int):
    n = traced_rows(batch.num_rows)
    idx = jnp.arange(new_cap, dtype=jnp.int32)
    idx = jnp.where(idx < n, idx, -1)
    return gather_batch(batch, idx, batch.num_rows)


def shrink_batch(batch: ColumnarBatch) -> ColumnarBatch:
    """Compact a batch whose capacity far exceeds its row count (the shrink
    point for deferred-count operators). Materializes a lazy count (one
    round trip) — call once per stage output, never per input batch."""
    n = int(batch.num_rows)
    new_cap = round_capacity(n)
    if new_cap >= batch.capacity:
        return ColumnarBatch(batch.columns, n)
    out = _shrink_gather(batch, new_cap)
    return ColumnarBatch(out.columns, n)


def slice_batch(batch: ColumnarBatch, start: int, length: int) -> ColumnarBatch:
    out_cap = round_capacity(max(length, 1))
    idx = jnp.arange(out_cap, dtype=jnp.int32) + start
    idx = jnp.where(jnp.arange(out_cap) < length, idx, -1)
    return gather_batch(batch, idx, length)


def flatten_dict_column(col: ColumnVector, num_rows) -> ColumnVector:
    """Dict-encoded string -> flat offsets+bytes. The payload EXPANDS
    (repeated codes repeat their vocab entry), so the output byte plane is
    sized by the expansion: exactly when called eagerly (one scalar sync),
    by the static bound rows*vocab_bytes inside a trace."""
    voff = col.data["dict_offsets"]
    vraw = col.data["dict_bytes"]
    codes = col.data["codes"].astype(jnp.int32)
    valid = col.validity
    cap = int(codes.shape[0])
    vlens = voff[1:] - voff[:-1]
    lens = vlens[jnp.clip(codes, 0, vlens.shape[0] - 1)]
    if valid is not None:
        lens = jnp.where(valid, lens, 0)
    new_off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(lens).astype(jnp.int32)])
    import jax.core as _core
    if isinstance(new_off, jax.Array) and not isinstance(new_off, _core.Tracer):
        out_cap = round_capacity(max(int(new_off[-1]), 1))
    else:
        out_cap = cap * int(vraw.shape[0])
        if out_cap > (1 << 28):
            raise NotImplementedError(
                "flattening a large dict string column inside a traced "
                "kernel (bound > 256MB); restructure via the vocab lift")
    starts = voff[jnp.clip(codes, 0, vlens.shape[0] - 1)]
    b = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(new_off, b, side="right").astype(jnp.int32) - 1,
                   0, cap - 1)
    src = jnp.clip(starts[row] + (b - new_off[row]), 0, int(vraw.shape[0]) - 1)
    out_bytes = jnp.where(b < new_off[-1], vraw[src], 0).astype(jnp.uint8)
    return ColumnVector(col.dtype, {"offsets": new_off, "bytes": out_bytes},
                        col.validity)


def _same_array(a, b) -> bool:
    return a is b


def concat_batches(batches: List[ColumnarBatch]) -> ColumnarBatch:
    materialize_counts(batches)  # one bulk fetch, not one sync per batch
    masked = any(b.row_mask is not None for b in batches)
    nonempty = [b for b in batches if b.num_rows > 0]
    if not nonempty:
        return batches[0]
    if len(nonempty) == 1:
        return nonempty[0]
    total = sum(int(b.num_rows) for b in nonempty)
    if masked:
        # Selection-mask mode: stack FULL planes and concatenate masks — no
        # gather, no per-row work. Capacity grows to the sum of inputs; the
        # consumer (or an explicit compact) shrinks when worthwhile.
        mask = jnp.concatenate([b.live_mask() for b in nonempty])
        out_cols = []
        for ci in range(nonempty[0].num_cols):
            cols = [b.columns[ci] for b in nonempty]
            caps = [b.capacity for b in nonempty]
            out_cols.append(_concat_columns(cols, caps, sum(caps)))
        return ColumnarBatch(out_cols, total, mask)
    out_cols = []
    for ci in range(nonempty[0].num_cols):
        cols = [b.columns[ci] for b in nonempty]
        rows = [int(b.num_rows) for b in nonempty]
        out_cols.append(_concat_columns(cols, rows, round_capacity(total)))
    return ColumnarBatch(out_cols, total)


def _union_bounds(cols: List[ColumnVector]):
    """Conservative (min, max) union across concat inputs; None if any
    input lacks bounds (host metadata — see ColumnVector.bounds)."""
    bs = [c.bounds for c in cols]
    if any(b is None for b in bs):
        return None
    return (min(b[0] for b in bs), max(b[1] for b in bs))


def unify_vocabs(cols: List[ColumnVector]):
    """Union the vocabularies of several dict-string columns host-side.
    Returns (union_offsets np.int32[k+1], union_bytes np.uint8[m],
    per-column code remaps). Equal strings map to ONE union code, so
    code-identity reasoning (bucket agg, ICI fixed-width exchange) stays
    sound across the inputs."""
    vocab_planes = []
    for c in cols:
        vocab_planes.extend([c.data["dict_offsets"], c.data["dict_bytes"]])
    host = jax.device_get(vocab_planes)
    union: dict = {}
    remaps = []
    for i in range(len(cols)):
        off, by = np.asarray(host[2 * i]), np.asarray(host[2 * i + 1])
        remap = np.zeros(len(off) - 1, np.int32)
        for k in range(len(off) - 1):
            sv = bytes(by[off[k]: off[k + 1]])
            if sv not in union:
                union[sv] = len(union)
            remap[k] = union[sv]
        remaps.append(remap)
    ub = b"".join(union.keys())
    uoff = np.zeros(len(union) + 1, np.int32)
    uoff[1:] = np.cumsum([len(sv) for sv in union.keys()])
    ubytes = np.frombuffer(ub, np.uint8) if ub else np.zeros(1, np.uint8)
    return uoff, np.ascontiguousarray(ubytes), remaps


def align_dict_columns(cols: List[ColumnVector]) -> List[ColumnVector]:
    """NEW dict columns whose codes index ONE shared union vocabulary
    (inputs untouched). No-op (returns the same objects) when the vocab
    planes are already identical."""
    same = all(_same_array(c.data["dict_offsets"],
                           cols[0].data["dict_offsets"])
               and _same_array(c.data["dict_bytes"],
                               cols[0].data["dict_bytes"])
               for c in cols[1:])
    if same:
        return list(cols)
    uoff, ubytes, remaps = unify_vocabs(cols)
    doff = jnp.asarray(uoff)
    dby = jnp.asarray(ubytes)
    out = []
    for c, remap in zip(cols, remaps):
        codes = jnp.asarray(remap)[jnp.clip(c.data["codes"], 0,
                                            len(remap) - 1)]
        out.append(ColumnVector(c.dtype,
                                {"codes": codes, "dict_offsets": doff,
                                 "dict_bytes": dby}, c.validity,
                                dict_unique=True))
    return out


def _concat_columns(cols: List[ColumnVector], rows: List[int], cap: int) -> ColumnVector:
    dtype = cols[0].dtype
    if any(c.is_dict for c in cols) and not all(c.is_dict for c in cols):
        cols = [flatten_dict_column(c, r) if c.is_dict else c
                for c, r in zip(cols, rows)]
    validity = jnp.concatenate([c.validity_or_default(r)[:r] for c, r in zip(cols, rows)])
    pad = cap - validity.shape[0]
    if pad > 0:
        validity = jnp.concatenate([validity, jnp.zeros(pad, jnp.bool_)])

    if all(c.is_dict for c in cols):
        shared = all(_same_array(c.data["dict_offsets"], cols[0].data["dict_offsets"])
                     and _same_array(c.data["dict_bytes"], cols[0].data["dict_bytes"])
                     for c in cols[1:])
        if shared:
            codes = jnp.concatenate([c.data["codes"][:r] for c, r in zip(cols, rows)])
            if pad > 0:
                codes = jnp.concatenate([codes, jnp.zeros(pad, codes.dtype)])
            return ColumnVector(dtype, {"codes": codes,
                                        "dict_offsets": cols[0].data["dict_offsets"],
                                        "dict_bytes": cols[0].data["dict_bytes"]},
                                validity,
                                dict_unique=all(c.dict_unique for c in cols))
        # Distinct vocab objects: UNIFY host-side (vocabs are small; this
        # runs at eager concat boundaries only). Equal strings must map to
        # one code — duplicated vocab entries would make "unique bucket"
        # reasoning (bucketed agg, merge-skip) silently wrong.
        uoff, ubytes, remaps = unify_vocabs(cols)
        code_parts = [jnp.asarray(remap)[c.data["codes"][:r]]
                      for c, r, remap in zip(cols, rows, remaps)]
        codes = jnp.concatenate(code_parts)
        if pad > 0:
            codes = jnp.concatenate([codes, jnp.zeros(pad, codes.dtype)])
        return ColumnVector(dtype, {"codes": codes,
                                    "dict_offsets": jnp.asarray(uoff),
                                    "dict_bytes": jnp.asarray(ubytes)},
                            validity)

    if isinstance(dtype, T.StructType):
        kids = []
        for k in range(len(cols[0].data["children"])):
            kids.append(_concat_columns([c.data["children"][k] for c in cols],
                                        rows, cap))
        return ColumnVector(dtype, {"children": kids}, validity)

    if isinstance(dtype, (T.ArrayType, T.MapType)):
        # Host readback of per-part element counts keeps destination
        # offsets static (same discipline as string concat below); child
        # planes concat recursively, so arrays of strings/structs compose.
        elem_lens = [int(np.asarray(c.data["offsets"][r]))
                     for c, r in zip(cols, rows)]
        total_elems = sum(elem_lens)
        child_cap = round_capacity(max(total_elems, 1))
        off_parts = [jnp.zeros(1, jnp.int32)]
        base = 0
        for c, r, el in zip(cols, rows, elem_lens):
            off_parts.append(c.data["offsets"][1: r + 1].astype(jnp.int32)
                             + np.int32(base))
            base += el
        offsets = jnp.concatenate(off_parts)
        if cap + 1 - offsets.shape[0] > 0:
            offsets = jnp.concatenate([
                offsets, jnp.full(cap + 1 - offsets.shape[0], base, jnp.int32)])
        names = ["child"] if "child" in cols[0].data else ["keys", "values"]
        data = {"offsets": offsets}
        for nm in names:
            data[nm] = _concat_columns([c.data[nm] for c in cols],
                                       elem_lens, child_cap)
        return ColumnVector(dtype, data, validity)

    if isinstance(dtype, T.StringType):
        # Host readback of per-part byte lengths keeps destination offsets
        # static; concat happens between batches, off the jitted hot path.
        byte_lens = [int(np.asarray(c.data["offsets"][r])) for c, r in zip(cols, rows)]
        total_bytes = sum(byte_lens)
        out_byte_cap = round_capacity(max(total_bytes, 1))
        out_bytes = jnp.zeros(out_byte_cap, jnp.uint8)
        off_parts = [jnp.zeros(1, jnp.int32)]
        base_rows = 0
        base_bytes = 0
        for c, r, blen in zip(cols, rows, byte_lens):
            o = c.data["offsets"]
            off_parts.append(o[1: r + 1].astype(jnp.int32) + np.int32(base_bytes))
            src = c.data["bytes"]
            part_cap = src.shape[0]
            dest = jnp.where(jnp.arange(part_cap) < blen,
                             base_bytes + jnp.arange(part_cap), out_byte_cap)
            out_bytes = out_bytes.at[dest].set(src, mode="drop")
            base_rows += r
            base_bytes += blen
        offsets = jnp.concatenate(off_parts)
        opad = cap + 1 - offsets.shape[0]
        if opad > 0:
            offsets = jnp.concatenate([offsets, jnp.broadcast_to(offsets[-1:], (opad,))])
        return ColumnVector(dtype, {"offsets": offsets, "bytes": out_bytes}, validity)

    merged = jnp.concatenate([c.data[:r] for c, r in zip(cols, rows)])
    if cap - merged.shape[0] > 0:
        merged = jnp.concatenate([merged, jnp.zeros(cap - merged.shape[0], merged.dtype)])
    return ColumnVector(dtype, merged, validity, bounds=_union_bounds(cols))


# ---------------------------------------------------------------------------
# Join candidate expansion (count-then-gather; the JoinGatherer analog)
# ---------------------------------------------------------------------------

def expand_ranges(lo: jax.Array, hi: jax.Array, total: int) -> Tuple[jax.Array, jax.Array]:
    """Given per-probe candidate ranges [lo_i, hi_i) into a sorted build side,
    emit flat (probe_idx, build_pos) pairs. total = sum(hi-lo), a host scalar.
    Tail entries (>= total) are -1."""
    out_cap = round_capacity(max(total, 1))
    counts = (hi - lo).astype(jnp.int32)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    r = jnp.arange(out_cap, dtype=jnp.int32)
    probe = jnp.searchsorted(offsets, r, side="right").astype(jnp.int32) - 1
    probe = jnp.clip(probe, 0, lo.shape[0] - 1)
    pos = lo[probe] + (r - offsets[probe])
    in_range = r < total
    return jnp.where(in_range, probe, -1), jnp.where(in_range, pos, -1)
