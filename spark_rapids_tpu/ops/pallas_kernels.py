"""Hand-written Pallas TPU kernels for hot inner loops.

Reference parity: the reference's hottest single-purpose device kernels
live in spark-rapids-jni (Hash, CastStrings, ...) below the general cudf
algebra. Same layering here: XLA owns fusion for general expressions;
these Pallas kernels take over specific bandwidth-bound inner loops where
a hand-tiled VMEM pipeline beats the XLA default:

- murmur3_int32: the per-row hash behind every hash exchange, shuffled
  join, and group-key normalization. Elementwise uint32 rotate/multiply
  chains — one VMEM-resident pass, no intermediate HBM traffic.
- ascii_case_map: upper/lower over string BYTE planes (uint8), the inner
  loop of Upper/Lower over flat vocab/byte planes.

Both kernels carry a lax/XLA twin in ops/kernels.py; the conf
spark.rapids.sql.pallas.enabled picks the implementation, and the suite
runs the Pallas path in interpret mode on CPU so correctness is always
differentially checked against the XLA twin without hardware.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

_BLOCK = 1024  # rows per grid step: 8 sublanes x 128 lanes

_ENABLED = True
_APPLIED = False


def set_enabled(v: bool) -> None:
    """spark.rapids.sql.pallas.enabled. PROCESS-GLOBAL and effectively
    startup-only: fused kernels cache compiled closures process-wide, so
    the first session's value wins; later sessions asking for a different
    value get a warning, not a silent partial flip."""
    global _ENABLED, _APPLIED
    v = bool(v)
    if _APPLIED and v != _ENABLED:
        import warnings
        warnings.warn(
            "spark.rapids.sql.pallas.enabled differs from the value the "
            "process started with; kernel caches are process-global, so "
            "the first value stays in effect", stacklevel=2)
        return
    _ENABLED = v
    _APPLIED = True


def enabled() -> bool:
    return _ENABLED


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _x64_off():
    """Context manager tracing in 32-bit mode. `jax.enable_x64` is only
    public API on newer jax; older builds (this container's 0.4.x) spell
    it jax.experimental.enable_x64."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(False)


def pallas_supported(n: int) -> bool:
    """Pallas path eligibility: block-aligned plane sizes only (the
    capacity bucketing makes every plane >= 1024 a multiple of 1024)."""
    return n >= _BLOCK and n % _BLOCK == 0


_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _mm3_kernel(seed_ref, x_ref, o_ref):
    x = x_ref[...]  # already uint32 (a no-op convert here trips Mosaic)
    seed = seed_ref[0]
    k1 = x * _C1
    k1 = (k1 << 15) | (k1 >> 17)
    k1 = k1 * _C2
    h1 = seed ^ k1
    h1 = (h1 << 13) | (h1 >> 19)
    h1 = h1 * np.uint32(5) + np.uint32(0xE6546B64)
    # fmix(h1 ^ len), len = 4
    h1 = h1 ^ np.uint32(4)
    h1 = h1 ^ (h1 >> 16)
    h1 = h1 * np.uint32(0x85EBCA6B)
    h1 = h1 ^ (h1 >> 13)
    h1 = h1 * np.uint32(0xC2B2AE35)
    o_ref[...] = h1 ^ (h1 >> 16)


def murmur3_int32_pallas(values: jax.Array, seed: jax.Array) -> jax.Array:
    """Spark murmur3 of an int32 plane (hashInt), Pallas-tiled. `seed`
    must be a SCALAR riding in SMEM (per-row seed planes — chained
    multi-column hashing — stay on the lax twin: Mosaic on this toolchain
    miscompiles the two-VMEM-input variant of this op chain)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = values.shape[0]
    assert pallas_supported(n) and seed.ndim == 0, (n, seed.shape)
    x = values.astype(jnp.uint32).reshape(n // 128, 128)
    rows = x.shape[0]
    block_rows = _BLOCK // 128
    seed_arr = jnp.reshape(seed.astype(jnp.uint32), (1,))
    # the engine runs with global x64 enabled, under which pallas grid
    # index types lower to i64 and Mosaic fails to legalize; this kernel
    # is all-32-bit, so trace it in 32-bit mode
    with _x64_off():
        out = pl.pallas_call(
            _mm3_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint32),
            grid=(rows // block_rows,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((block_rows, 128), lambda i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, 128), lambda i: (i, 0)),
            interpret=_interpret(),
        )(seed_arr, x)
    return out.reshape(n)


def _swar_case_kernel(lo_b, hi_b, delta_sign):
    """SWAR ASCII case map over u32 words (4 bytes/lane): per-byte range
    test with carry-safe 7-bit arithmetic, then +-32 on selected bytes.
    Mosaic on this toolchain does not lower u8 lanes; 4-bytes-per-u32
    also quarters the lane count."""
    HI = np.uint32(0x80808080)
    LO7 = np.uint32(0x7F7F7F7F)
    ge = np.uint32(0x01010101) * np.uint32(0x80 - lo_b)
    gt = np.uint32(0x01010101) * np.uint32(0x80 - (hi_b + 1))

    def kern(x_ref, o_ref):
        x = x_ref[...]
        hi = x & HI
        lo = x & LO7
        is_ge = (lo + ge) & HI          # byte >= lo_b (7-bit range)
        is_gt = (lo + gt) & HI          # byte > hi_b
        mask = is_ge & ~is_gt & ~hi     # ASCII and in [lo_b, hi_b]
        delta = (mask >> 2)             # 0x80 -> 0x20 (= 32) per byte
        o_ref[...] = (x - delta) if delta_sign < 0 else (x + delta)

    return kern


def ascii_case_map_pallas(raw: jax.Array, upper: bool) -> jax.Array:
    """ASCII case map over a uint8 byte plane (byte planes are
    capacity-bucketed, so multiples of 4096 take this path)."""
    from jax import lax
    from jax.experimental import pallas as pl
    n = raw.shape[0]
    assert n % 4096 == 0, n
    with _x64_off():  # see murmur3_int32_pallas
        words = lax.bitcast_convert_type(raw.reshape(n // 4, 4), jnp.uint32)
        x = words.reshape(n // 4 // 128, 128)
        rows = x.shape[0]
        block_rows = 8
        kern = (_swar_case_kernel(97, 122, -1) if upper
                else _swar_case_kernel(65, 90, +1))
        out = pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct(x.shape, jnp.uint32),
            grid=(rows // block_rows,),
            in_specs=[pl.BlockSpec((block_rows, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((block_rows, 128), lambda i: (i, 0)),
            interpret=_interpret(),
        )(x)
        return lax.bitcast_convert_type(
            out.reshape(n // 4), jnp.uint8).reshape(n)
