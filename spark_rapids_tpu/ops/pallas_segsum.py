"""Pallas sorted-window segmented reduction — the groupby hot path.

Reference parity: SURVEY §7.3.1's "hard" kernel list (the cudf hash-agg
shard). Measured on v5e (tools/profile_pallas_segsum.py): end-to-end
sort + kernel = 317 ms vs 607 ms for the 3-scatter XLA bucket path at
16.7M rows -> 4M groups, bit-exact sums.

Design: after a single co-sort by the packed key, dense group ids are
MONOTONE, so a 1024-row tile touches a contiguous id span <= 1024 wide.
Each grid step runs ONE bf16 one-hot matmul [2*TILE, TILE] @ [TILE, P]
on the MXU and accumulates into a two-block output window selected by a
scalar-prefetched block base — zero scatters, zero gathers. Payload
values are 8-bit balanced digits (|d| <= 2^7), exact in bf16; per-slot
f32 accumulation is exact while a group's row count stays <= 2^17 (the
caller wraps a lax.cond fallback on the post-hoc count column, which is
itself exact to 2^24 rows).

Output-block protocol: Pallas TPU does NOT load output windows from HBM
on first visit, so the kernel INITIALIZES a block on the step that first
maps it and ACCUMULATES on consecutive revisits; monotone ids mean each
buffer's block index advances by 0 or 1, so every block is first-visited
exactly once. Untouched tails are masked out host-side.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.runtime import compile_cache as _cc

TILE = 1024  # 1-D i32 blocks must match XLA's 1024-element tiling
#: per-group row-count bound: 8-bit digits reach 2^8, so counts <= 2^16
#: keep every per-slot f32 accumulation within the exact-integer range
MAX_GROUP_ROWS = 1 << 16
#: digit shifts covering 47 bits below the batch max exponent
#: (callers scale by _exponent_scale(m) * 2^11, so the top digit
#: stays < 2^7 — comfortably bf16-exact)
SHIFTS = (40, 32, 24, 16, 8, 0)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _kernel_factory(P: int):
    from jax.experimental import pallas as pl

    def kernel(bases_ref, gid_ref, pay_ref, olo_ref, ohi_ref):
        t = pl.program_id(0)
        base = bases_ref[t]
        g = gid_ref[...].reshape(TILE)
        local = g - base * TILE
        iota = lax.broadcasted_iota(jnp.int32, (2 * TILE, TILE), 0)
        # bf16 on the HBM side (payload plane), f32 inside VMEM: the
        # one-hot values and 8-bit digits are exact either way, but the
        # ACCUMULATION must be f32 (bf16 dot accumulation drops bits on
        # the interpret backend)
        oh = (iota == local[None, :]).astype(jnp.float32)
        acc = jnp.dot(oh, pay_ref[...].astype(jnp.float32),
                      preferred_element_type=jnp.float32)
        moved = jnp.logical_or(t == 0,
                               base != bases_ref[jnp.maximum(t - 1, 0)])

        @pl.when(moved)
        def _init():
            olo_ref[...] = acc[:TILE]
            ohi_ref[...] = acc[TILE:]

        @pl.when(jnp.logical_not(moved))
        def _accumulate():
            olo_ref[...] += acc[:TILE]
            ohi_ref[...] += acc[TILE:]

    return kernel


#: eligibility ceiling for the engine path (exec/tpu_nodes): past ~8M
#: rows the enclosing fused stage (sorted planes + digit lanes + the
#: cond fallback's scatter temps) measured 18.5G HBM vs the v5e's
#: 15.75G — larger batches stay on the scatter path
CHUNK_ROWS = 1 << 23


@_cc.jit(static_argnames=("outcap",))
def segsum_window(gid: jax.Array, payload: jax.Array, outcap: int
                  ) -> jax.Array:
    """gid i32[N] sorted ascending (dense ids); payload bf16[N, P] (8-bit
    digit values are bf16-exact; bf16 halves the HBM footprint of the
    payload plane) with P a multiple of 8. Returns f32[outcap, P] per-id
    sums; outcap must be a multiple of 2*TILE and exceed max(gid)+1."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n, P = payload.shape
    assert n % TILE == 0 and outcap % (2 * TILE) == 0, (n, outcap)
    T = n // TILE
    bases = jnp.clip(gid[::TILE] // TILE, 0, outcap // TILE - 2)
    from spark_rapids_tpu.ops.pallas_kernels import _x64_off
    with _x64_off():
        lo, hi = pl.pallas_call(
            _kernel_factory(P),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(T,),
                in_specs=[
                    pl.BlockSpec((TILE,), lambda t, b: (t,)),
                    pl.BlockSpec((TILE, P), lambda t, b: (t, 0)),
                ],
                out_specs=[
                    pl.BlockSpec((TILE, P), lambda t, b: (b[t], 0)),
                    pl.BlockSpec((TILE, P), lambda t, b: (b[t] + 1, 0)),
                ],
            ),
            out_shape=[jax.ShapeDtypeStruct((outcap, P), jnp.float32)] * 2,
            interpret=_interpret(),
        )(bases, gid.astype(jnp.int32), payload)
    sb = (jnp.arange(outcap, dtype=jnp.int32) // TILE)[:, None]
    lo_keep = (sb >= bases[0]) & (sb <= bases[-1])
    hi_keep = (sb >= bases[0] + 1) & (sb <= bases[-1] + 1)
    return jnp.where(lo_keep, lo, 0.0) + jnp.where(hi_keep, hi, 0.0)


def float_digits(clean: jax.Array, scale) -> List[jax.Array]:
    """8-bit balanced digit planes of round(clean*scale) (f32 each)."""
    s = jnp.round(clean * scale)
    out = []
    rem = s
    for shift in SHIFTS:
        d = jnp.round(rem / np.float64(2.0 ** shift)) if shift \
            else jnp.round(rem)
        if shift:
            rem = rem - d * np.float64(2.0 ** shift)
        out.append(d.astype(jnp.bfloat16))
    return out


def digits_to_f64(cols: List[jax.Array]) -> jax.Array:
    tot = jnp.zeros(cols[0].shape[0], jnp.float64)
    for d, shift in zip(cols, SHIFTS):
        tot = tot + d.astype(jnp.float64) * np.float64(2.0 ** shift)
    return tot


def int_digits(code: jax.Array, nbits: int) -> Tuple[List[jax.Array], List[int]]:
    """Unsigned 8-bit digit planes of a small nonnegative int plane."""
    shifts = list(range(0, nbits, 8))[::-1]
    out = []
    for sh in shifts:
        out.append(((code >> sh) & 0xFF).astype(jnp.bfloat16))
    return out, shifts


def int_digits_to_val(cols: List[jax.Array], shifts: List[int],
                      counts: jax.Array) -> jax.Array:
    """Recover per-group int values from digit-times-count sums."""
    safe = jnp.maximum(counts, 1.0)
    v = jnp.zeros(cols[0].shape[0], jnp.float64)
    for d, sh in zip(cols, shifts):
        v = v + jnp.round(d.astype(jnp.float64) / safe) \
            * np.float64(1 << sh)
    return v
