"""Device-side Parquet decode: Pallas/XLA expansion of encoded planes.

Reference parity: libcudf's GPU Parquet reader (gpuDecodePages) — the
layer below the cudf algebra where spark-rapids actually earns its scan
bandwidth. There, warps cooperatively expand RLE runs and gather through
dictionaries in shared memory; here the same decode becomes vectorized
TPU-friendly primitives over the run tables io/encoded.py extracts:

- run expansion  = searchsorted(cum, iota) + per-run bit gather — the
  prefix-sum formulation of the warp-cooperative RLE decoder
- dictionary     = one gather through the uploaded vocab plane
- delta          = cumsum with per-stream restarts (first-value anchors)
- null placement = cumsum(def-levels) scatter-free gather, reproducing
  the host path's fill_null(0) + zero-padded tails bit for bit

The one genuinely hand-tiled inner loop is the unaligned bit-slice
(`bitslice_u32`): every encoded value is (pool_word[k] >> s | word[k+1]
<< 32-s) & mask, an elementwise u32 chain exactly like murmur3 — it gets
a Pallas kernel with an XLA twin, gated by the same
spark.rapids.sql.pallas.enabled conf and block-size eligibility as
ops/pallas_kernels.py, and the suite differentially checks the pair in
interpret mode on CPU. Everything else (searchsorted, gathers, cumsum)
stays plain jnp: XLA fuses it into the one stage-body dispatch, which is
the point — Scan→Filter→partial-agg remains ONE dispatch per batch over
encoded bytes.

All decode math runs inside the fused trace, so the kernel cost auditor
sees the ENCODED planes as the dispatch inputs and credits encoded-input
bytes to the roofline (measured effective bandwidth), while the decode
time lands in opTime -> device_compute: the host_decode bucket collapses
structurally, with no attribution-layer special cases.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops import pallas_kernels as PK

_U32_MAX = np.uint32(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# bit-slice: the hand-tiled inner loop
# ---------------------------------------------------------------------------

def _bitslice_kernel(w0_ref, w1_ref, sh_ref, m_ref, o_ref):
    w0 = w0_ref[...]
    w1 = w1_ref[...]
    sh = sh_ref[...]
    m = m_ref[...]
    lo = w0 >> sh
    # shift-by-32 is UB on the VPU: fold the sh==0 case to a where
    hi = jnp.where(sh == np.uint32(0), np.uint32(0),
                   w1 << ((np.uint32(32) - sh) & np.uint32(31)))
    o_ref[...] = (lo | hi) & m


def bitslice_u32_pallas(w0: jax.Array, w1: jax.Array, sh: jax.Array,
                        mask: jax.Array) -> jax.Array:
    """Extract `width`-bit fields straddling u32 word pairs, Pallas-tiled.
    All operands uint32 planes of one block-aligned length."""
    from jax.experimental import pallas as pl
    n = w0.shape[0]
    assert PK.pallas_supported(n), n
    shp = (n // 128, 128)
    block_rows = PK._BLOCK // 128
    spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    # all-32-bit kernel: trace in 32-bit mode (global x64 makes pallas
    # grid indices i64, which Mosaic fails to legalize)
    with PK._x64_off():
        out = pl.pallas_call(
            _bitslice_kernel,
            out_shape=jax.ShapeDtypeStruct(shp, jnp.uint32),
            grid=(shp[0] // block_rows,),
            in_specs=[spec, spec, spec, spec],
            out_specs=spec,
            interpret=PK._interpret(),
        )(w0.reshape(shp), w1.reshape(shp), sh.reshape(shp),
          mask.reshape(shp))
    return out.reshape(n)


def bitslice_u32_lax(w0: jax.Array, w1: jax.Array, sh: jax.Array,
                     mask: jax.Array) -> jax.Array:
    """XLA twin of bitslice_u32_pallas (identical math)."""
    lo = w0 >> sh
    hi = jnp.where(sh == np.uint32(0), np.uint32(0),
                   w1 << ((np.uint32(32) - sh) & np.uint32(31)))
    return (lo | hi) & mask


def _words(pool: jax.Array) -> jax.Array:
    """u8 byte pool -> little-endian u32 word plane. Explicit byte
    combine, not bitcast: endianness-independent and Mosaic never sees
    u8 lanes."""
    b = pool.reshape(-1, 4).astype(jnp.uint32)
    return b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24)


def _gather_bits(words: jax.Array, bitoff: jax.Array, mask: jax.Array
                 ) -> jax.Array:
    """Per-element unaligned bit extraction: bitoff (int64) -> uint32."""
    widx = jnp.clip((bitoff >> 5).astype(jnp.int32), 0,
                    words.shape[0] - 2)
    w0 = words[widx]
    w1 = words[widx + 1]
    sh = (bitoff & 31).astype(jnp.uint32)
    if PK.enabled() and PK.pallas_supported(int(bitoff.shape[0])):
        return bitslice_u32_pallas(w0, w1, sh, mask)
    return bitslice_u32_lax(w0, w1, sh, mask)


# ---------------------------------------------------------------------------
# run-table expansion
# ---------------------------------------------------------------------------

def expand_runs(planes: Dict[str, jax.Array], prefix: str, vcap: int
                ) -> jax.Array:
    """Expand an RLE/bit-packed run table to `vcap` int32 values.
    Positions past the encoded total land on sentinel-padded run slots
    (io/encoded.py guarantees at least one) and decode to exact 0."""
    cum = planes[prefix + "cum"]
    i = jnp.arange(vcap, dtype=jnp.int32)
    seg = jnp.clip(jnp.searchsorted(cum, i, side="right").astype(jnp.int32),
                   0, cum.shape[0] - 1)
    s_start = planes[prefix + "start"][seg]
    s_packed = planes[prefix + "packed"][seg]
    s_bitbase = planes[prefix + "bitbase"][seg]
    width = planes.get(prefix + "width")
    if width is None:  # constant width 1 (def levels, booleans)
        w64 = jnp.int64(1)
        mask = jnp.full(vcap, 1, jnp.uint32)
    else:
        s_width = width[seg]
        wu = s_width.astype(jnp.uint32)
        mask = jnp.where(s_width >= 32, _U32_MAX,
                         (jnp.uint32(1) << (wu & np.uint32(31)))
                         - jnp.uint32(1))
        w64 = s_width.astype(jnp.int64)
    bitoff = s_bitbase + (i - s_start).astype(jnp.int64) * w64
    ext = _gather_bits(_words(planes[prefix + "pool"]), bitoff, mask)
    out = jnp.where(s_packed, ext.astype(jnp.int32),
                    planes[prefix + "val"][seg])
    base = planes.get(prefix + "base")
    if base is not None:
        out = out + base[seg]
    return out


def _expand_delta(planes: Dict[str, jax.Array], vcap: int, vpm: int
                  ) -> jax.Array:
    """DELTA_BINARY_PACKED -> int64 values: per-element miniblock bit
    gather, then one cumsum with per-stream (page) restarts."""
    s_cum = planes["s_cum"]
    j = jnp.arange(vcap, dtype=jnp.int32)
    seg = jnp.clip(
        jnp.searchsorted(s_cum, j, side="right").astype(jnp.int32),
        0, s_cum.shape[0] - 1)
    a = planes["s_start"][seg]
    rel = j - a - 1  # delta index within the stream; -1 at stream starts
    mb = jnp.clip(planes["s_mbbase"][seg]
                  + jnp.where(rel >= 0, rel // vpm, 0),
                  0, planes["mb_width"].shape[0] - 1)
    within = jnp.where(rel >= 0, rel % vpm, 0)
    w = planes["mb_width"][mb]
    wu = w.astype(jnp.uint32)
    mask = jnp.where(w >= 32, _U32_MAX,
                     (jnp.uint32(1) << (wu & np.uint32(31)))
                     - jnp.uint32(1))
    bitoff = planes["mb_bitbase"][mb] \
        + within.astype(jnp.int64) * w.astype(jnp.int64)
    ext = _gather_bits(_words(planes["pool"]), bitoff, mask)
    d = ext.astype(jnp.int64) + planes["mb_min"][mb]
    nnz = planes["nnz"][0]
    d = jnp.where((rel >= 0) & (j < nnz), d, jnp.int64(0))
    c = jnp.cumsum(d)
    # value[j] = first[stream] + sum of deltas in (stream_start, j]
    return planes["s_first"][seg] + c - c[jnp.clip(a, 0, vcap - 1)]


# ---------------------------------------------------------------------------
# column assembly
# ---------------------------------------------------------------------------

def _plain_values(pool: jax.Array, w: int, vcap: int) -> jax.Array:
    """PLAIN fixed-width bytes -> raw uint32/uint64 lanes."""
    words = _words(pool)
    if w == 4:
        return words
    lo = words[0::2].astype(jnp.uint64)
    hi = words[1::2].astype(jnp.uint64)
    return lo | (hi << 32)


def _cast(vals: jax.Array, dtype) -> jax.Array:
    """Raw decoded lanes -> the engine plane dtype. Unsigned raw lanes
    bitcast (not convert) to the same-width signed/float dtype first."""
    if isinstance(dtype, T.BooleanType):
        return vals.astype(jnp.bool_)
    nd = dtype.np_dtype
    if vals.dtype == jnp.uint32 or vals.dtype == jnp.uint64:
        if isinstance(dtype, (T.Float32Type, T.Float64Type)):
            return jax.lax.bitcast_convert_type(vals, nd)
        signed = jnp.int32 if vals.dtype == jnp.uint32 else jnp.int64
        vals = jax.lax.bitcast_convert_type(vals, signed)
    return vals.astype(nd)


def _decode_column(ec, cap: int):
    """One EncodedColumn -> ColumnVector, inside the fused trace."""
    from spark_rapids_tpu.columnar.batch import ColumnVector
    if ec.kind == "decoded":
        return ec.cv
    meta = dict(ec.meta)
    vcap = meta["vcap"]
    planes = ec.planes
    nnz = planes["nnz"][0]
    if ec.kind == "plain":
        vals = _plain_values(planes["pool"], meta["w"], vcap)
    elif ec.kind == "bool":
        vals = expand_runs(planes, "", vcap)
    elif ec.kind == "dict":
        codes = expand_runs(planes, "", vcap)
        vocab = planes["vocab"]
        vals = vocab[jnp.clip(codes, 0, vocab.shape[0] - 1)]
    else:  # delta
        vals = _expand_delta(planes, vcap, meta["vpm"])
    vals = _cast(vals, ec.dtype)
    # zero the padded tail: the host path's from_arrow zero-fills pad
    # rows, and downstream kernels (bounds-trusting aggs) rely on it
    zero = jnp.zeros((), vals.dtype)
    vals = jnp.where(jnp.arange(vcap) < nnz, vals, zero)
    if "d_cum" in planes:
        # sparse values -> row positions via the definition levels:
        # valid rows gather the next value, null rows take fill 0
        dexp = expand_runs(planes, "d_", cap)
        valid = dexp == 1
        pos = jnp.clip(jnp.cumsum(valid.astype(jnp.int32)) - 1, 0,
                       vcap - 1)
        data = jnp.where(valid, vals[pos], zero)
        return ColumnVector(ec.dtype, data, valid, bounds=ec.bounds)
    return ColumnVector(ec.dtype, vals, None, bounds=ec.bounds)


def decode_batch(eb):
    """EncodedBatch -> ColumnarBatch. Traced inside the stage body: the
    fused dispatch's inputs are the encoded planes, its body the decode
    expansion plus whatever Filter/partial-agg stage_fusion packed in."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    cols = [_decode_column(c, eb.capacity) for c in eb.columns]
    return ColumnarBatch(cols, eb.num_rows, None)
