"""Range-compressed radix keys + sorted segmented reductions.

The round-3 performance backbone (reference parity: the cudf hash/radix
groupby + sort kernel library, SURVEY.md §2.9.1/§7.3.1 — re-designed for
what this TPU actually measures, not translated):

Measured on v5e (tools/profile_prims*.py): a single-plane argsort runs in
~175-210 ms for 20M rows and compiles in seconds, while the general
multi-operand u64 ``lax.sort`` takes MINUTES to compile, and 64-bit
scatter reductions (``segment_sum`` on f64/i64) are 13x slower than i32
(3.0 s vs 0.24 s for 20M rows -> 3M buckets).  64-bit ``searchsorted`` is
8.7 s for 20M probes.  The fast primitives are: single-key sorts, 32-bit
scatters, and (exact, integer) cumsums — so the groupby backbone is built
from exactly those:

1. **Pack** all group keys into ONE int64 plane by runtime range
   compression: per key, ``code = value - min`` occupies
   ``ceil_log2(span+2)`` bits (slot 0 encodes NULL, so null groups work).
   Bit widths are static per compiled kernel (rounded up to multiples of
   4 to bound recompiles); the per-key minima ride in as traced scalars.
2. **Sort once** by the packed plane (stable argsort; dead rows get an
   above-range sentinel and sink to the tail).
3. **Segmented reductions over the sorted order** without any 64-bit
   scatter:
   - counts/any/all: i32 cumsum + boundary diff,
   - int64/decimal sums: ONE i64 cumsum (exact mod 2^64 — matching Java
     long overflow semantics bit-for-bit) + boundary diff,
   - f64 sums: TWO i64 "limb" cumsums of a fixed-point decomposition
     scaled to the batch maximum — error <= 1 ulp of the largest element
     regardless of group size (better than sequential summation),
   - min/max on 64-bit types: two chained i32 scatter reductions
     (high word, then low word among high-word winners),
   - first/last: i32 scatter-min/max of valid sorted positions.

Group keys are reconstructed arithmetically from the packed plane at the
segment boundaries — no gather of the original key columns at all.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnVector

#: packed planes are int64 with a dead-row sentinel above all live codes
MAX_PACK_BITS = 62
_SENTINEL = jnp.int64(1) << jnp.int64(MAX_PACK_BITS)

#: key kinds (static part of a pack spec)
KIND_INT = "int"      # needs runtime (min, span) — int-family/date/timestamp
KIND_DICT = "dict"    # dictionary codes, static span = vocab size
KIND_BOOL = "bool"    # static span = 2


@dataclass(frozen=True)
class PackSpec:
    """Static layout of a packed key plane: per-key (kind, bits). bits
    includes the +1 null slot and is rounded up to a multiple of 4 so the
    jit cache doesn't fragment across batches with slightly different
    spans."""
    kinds: Tuple[str, ...]
    bits: Tuple[int, ...]

    @property
    def total_bits(self) -> int:
        return sum(self.bits)

    @property
    def key(self):
        return (self.kinds, self.bits)


_INT_KINDS = (T.Int8Type, T.Int16Type, T.Int32Type, T.Int64Type,
              T.DateType, T.TimestampType)


def packable_dtype(c: ColumnVector) -> Optional[str]:
    if c.is_dict:
        return KIND_DICT
    d = c.dtype
    if isinstance(d, T.BooleanType):
        return KIND_BOOL
    if isinstance(d, _INT_KINDS):
        return KIND_INT
    if isinstance(d, T.DecimalType):
        return KIND_INT  # unscaled int64 representation
    return None


def static_kinds(key_cols: Sequence[ColumnVector]) -> Optional[List[str]]:
    kinds = []
    for c in key_cols:
        k = packable_dtype(c)
        if k is None:
            return None
        kinds.append(k)
    return kinds


def needs_range_probe(kinds: Sequence[str]) -> bool:
    return any(k == KIND_INT for k in kinds)


def probe_ranges(key_cols: Sequence[ColumnVector], live: jax.Array
                 ) -> jax.Array:
    """Traced: stacked [min_0, max_0, min_1, max_1, ...] (i64) for the
    KIND_INT keys (dict/bool keys contribute placeholder zeros to keep the
    layout positional). Null/dead rows are excluded."""
    out = []
    for c in key_cols:
        kind = packable_dtype(c)
        if kind != KIND_INT:
            out.extend([jnp.int64(0), jnp.int64(0)])
            continue
        v = c.data.astype(jnp.int64)
        valid = live if c.validity is None else (live & c.validity)
        lo = jnp.min(jnp.where(valid, v, jnp.int64(2**62)))
        hi = jnp.max(jnp.where(valid, v, -jnp.int64(2**62)))
        # all-null column: collapse to span 0
        lo = jnp.minimum(lo, hi)
        out.extend([lo, hi])
    return jnp.stack(out)


def _round_bits(b: int) -> int:
    # multiples of 2 bound jit-cache fragmentation across batches whose
    # spans drift, without pushing small keys past the BUCKET_BITS gate
    return max(2, -(-b // 2) * 2)


def plan_packing(key_cols: Sequence[ColumnVector],
                 ranges_host: Optional[np.ndarray]) -> Optional[PackSpec]:
    """Host-side: decide the static bit layout. ranges_host is the fetched
    probe_ranges vector (None when no KIND_INT keys)."""
    kinds = static_kinds(key_cols)
    if kinds is None:
        return None
    bits = []
    for i, (c, kind) in enumerate(zip(key_cols, kinds)):
        if kind == KIND_DICT:
            span = max(int(c.dict_size) - 1, 0)
        elif kind == KIND_BOOL:
            span = 1
        else:
            lo = int(ranges_host[2 * i])
            hi = int(ranges_host[2 * i + 1])
            span = hi - lo
            if span < 0:
                span = 0
        # codes occupy [0, span+1]; slot 0 is NULL
        bits.append(_round_bits(int(span + 2).bit_length()))
    spec = PackSpec(tuple(kinds), tuple(bits))
    if spec.total_bits > MAX_PACK_BITS:
        return None
    return spec


def pack_keys(spec: PackSpec, key_cols: Sequence[ColumnVector],
              mins: jax.Array, live: jax.Array) -> jax.Array:
    """Traced: ONE int64 plane with the range-compressed key codes.
    mins = the probe_ranges vector (device; only KIND_INT entries used).
    Dead rows get the above-range sentinel so they sort to the tail."""
    cap = live.shape[0]
    packed = jnp.zeros(cap, jnp.int64)
    for i, (c, kind, b) in enumerate(zip(key_cols, spec.kinds, spec.bits)):
        if kind == KIND_DICT:
            code = c.data["codes"].astype(jnp.int64)
        elif kind == KIND_BOOL:
            code = c.data.astype(jnp.int64)
        else:
            code = c.data.astype(jnp.int64) - mins[2 * i]
        code = code + 1  # slot 0 = NULL
        if c.validity is not None:
            code = jnp.where(c.validity, code, jnp.int64(0))
        packed = (packed << jnp.int64(b)) | jnp.clip(
            code, 0, (jnp.int64(1) << jnp.int64(b)) - 1)
    return jnp.where(live, packed, _SENTINEL)


def pack_keys_sort(spec: PackSpec, key_cols: Sequence[ColumnVector],
                   mins: jax.Array, live: jax.Array,
                   flags: Sequence[Tuple[bool, bool]]) -> jax.Array:
    """Order-faithful variant of pack_keys: per key, (ascending,
    nulls_first) decides the field encoding so an ascending sort of the
    packed plane IS the requested lexicographic order. KIND_INT/BOOL
    only for order-significant keys (dict codes are not value-ordered;
    callers place dict keys only in grouping positions with (True, True)
    where any consistent order suffices)."""
    cap = live.shape[0]
    packed = jnp.zeros(cap, jnp.int64)
    for i, (c, kind, b, (asc, nf)) in enumerate(
            zip(key_cols, spec.kinds, spec.bits, flags)):
        if kind == KIND_DICT:
            v = c.data["codes"].astype(jnp.int64)
            lo = jnp.int64(0)
            hi = jnp.int64(max(int(c.dict_size) - 1, 0))
        elif kind == KIND_BOOL:
            v = c.data.astype(jnp.int64)
            lo, hi = jnp.int64(0), jnp.int64(1)
        else:
            v = c.data.astype(jnp.int64)
            lo, hi = mins[2 * i], mins[2 * i + 1]
        code = (v - lo) if asc else (hi - v)
        span_max = (jnp.int64(1) << jnp.int64(b)) - jnp.int64(2)
        code = jnp.clip(code, 0, span_max)
        if nf:
            code = code + 1
            null_code = jnp.int64(0)
        else:
            null_code = span_max + 1
        if c.validity is not None:
            code = jnp.where(c.validity, code, null_code)
        packed = (packed << jnp.int64(b)) | code
    return jnp.where(live, packed, _SENTINEL)


def unpack_keys(spec: PackSpec, group_packed: jax.Array,
                mins: jax.Array, key_cols: Sequence[ColumnVector]
                ) -> List[ColumnVector]:
    """Traced: rebuild representative key columns from packed group values
    (arithmetic only — no gather of the source key planes). key_cols
    supply dtype + (for dict) the shared vocab planes."""
    out = []
    rem = group_packed
    fields = []
    for b in reversed(spec.bits):
        fields.append(rem & ((jnp.int64(1) << jnp.int64(b)) - 1))
        rem = rem >> jnp.int64(b)
    fields.reverse()
    for i, (c, kind, code) in enumerate(zip(key_cols, spec.kinds, fields)):
        valid = code != 0
        v = code - 1
        if kind == KIND_DICT:
            data = {"codes": v.astype(jnp.int32),
                    "dict_offsets": c.data["dict_offsets"],
                    "dict_bytes": c.data["dict_bytes"]}
            out.append(ColumnVector(c.dtype, data, valid,
                                    dict_unique=c.dict_unique))
            continue
        if kind == KIND_BOOL:
            out.append(ColumnVector(c.dtype, v.astype(jnp.bool_), valid))
            continue
        v = v + mins[2 * i]
        out.append(ColumnVector(c.dtype, v.astype(c.data.dtype), valid))
    return out


# ---------------------------------------------------------------------------
# Sorted segment layout
# ---------------------------------------------------------------------------

@dataclass
class GroupLayout:
    """Everything downstream reductions need, all traced arrays.
    Positions are in SORTED row order; group g lives at slot g in
    [0, n_groups)."""
    perm: jax.Array          # i32[cap] stable sort permutation
    sorted_packed: jax.Array  # i64[cap]
    boundary: jax.Array      # bool[cap] first sorted row of each group
    gid: jax.Array           # i32[cap] dense group id per sorted row
    safe_gid: jax.Array      # gid with dead rows routed to slot `cap`
    starts: jax.Array        # i32[cap] sorted position of group g's first row (-1 pad)
    ends: jax.Array          # i32[cap] sorted position of group g's last row (-1 pad)
    n_live: jax.Array        # i32 scalar
    n_groups: jax.Array      # i32 scalar
    cap: int


def group_layout(packed: jax.Array, live: jax.Array) -> GroupLayout:
    cap = packed.shape[0]
    n_live = jnp.sum(live.astype(jnp.int32))
    perm = jnp.argsort(packed, stable=True).astype(jnp.int32)
    sp = packed[perm]
    pos = jnp.arange(cap, dtype=jnp.int32)
    in_range = pos < n_live
    boundary = jnp.concatenate([jnp.ones(1, jnp.bool_), sp[1:] != sp[:-1]])
    boundary = boundary & in_range
    gid = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    n_groups = jnp.sum(boundary.astype(jnp.int32))
    safe_gid = jnp.where(in_range, gid, cap)
    # compacted boundary positions -> per-group start index
    bpos = jnp.where(boundary, gid, cap)
    starts = jnp.full(cap + 1, -1, jnp.int32).at[bpos].set(pos, mode="drop")[:cap]
    nxt = jnp.concatenate([starts[1:], jnp.full(1, -1, jnp.int32)])
    ends = jnp.where(nxt >= 0, nxt - 1, n_live - 1)
    ends = jnp.where(starts >= 0, ends, -1)
    return GroupLayout(perm, sp, boundary, gid, safe_gid, starts, ends,
                       n_live, n_groups, cap)


def _seg_diff(csum: jax.Array, x0: jax.Array, lay: GroupLayout) -> jax.Array:
    """Per-group total from an inclusive cumsum over sorted rows:
    total[g] = csum[end_g] - csum[start_g] + x[start_g]."""
    s = jnp.clip(lay.starts, 0, lay.cap - 1)
    e = jnp.clip(lay.ends, 0, lay.cap - 1)
    return csum[e] - csum[s] + x0[s]


def seg_count(valid_sorted: jax.Array, lay: GroupLayout) -> jax.Array:
    v = valid_sorted.astype(jnp.int32)
    return _seg_diff(jnp.cumsum(v), v, lay).astype(jnp.int64)


def seg_count_all(lay: GroupLayout) -> jax.Array:
    return (lay.ends - lay.starts + 1).astype(jnp.int64)


def seg_sum_int(vals_sorted: jax.Array, valid_sorted: jax.Array,
                lay: GroupLayout) -> jax.Array:
    """Exact mod-2^64 segmented integer sum (wraparound matches Java)."""
    v = jnp.where(valid_sorted, vals_sorted.astype(jnp.int64),
                  jnp.int64(0))
    return _seg_diff(jnp.cumsum(v), v, lay)


def _exponent_scale(m: jax.Array) -> jax.Array:
    """2^(36 - floor(log2(m))) for a positive scalar m, via compare-and-
    multiply (no 64-bit bitcasts — see kernels._frexp_arith). m == 0 maps
    to scale 1 (all-zero plane, sums are exactly 0 anyway)."""
    x = jnp.where(m > 0, m, jnp.float64(1.0))
    scale = jnp.float64(2.0) ** 36
    for k in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        up = np.float64(2.0) ** k
        c = x >= up
        x = jnp.where(c, x * np.float64(2.0) ** (-k), x)
        scale = jnp.where(c, scale * np.float64(2.0) ** (-k), scale)
        c2 = x * up < 2.0
        x = jnp.where(c2, x * up, x)
        scale = jnp.where(c2, scale * up, scale)
    return scale


def seg_sum_f64(vals_sorted: jax.Array, valid_sorted: jax.Array,
                lay: GroupLayout) -> jax.Array:
    """Segmented float sum via two exact int64 limb cumsums. Finite part
    is summed with error <= 1 ulp of the largest |value| in the batch;
    NaN/Inf propagate with Spark semantics (counted per segment through
    the same cumsum-diff machinery — no 64-bit scatter anywhere)."""
    v = vals_sorted.astype(jnp.float64)
    nan = jnp.isnan(v) & valid_sorted
    pinf = (v == jnp.inf) & valid_sorted
    ninf = (v == -jnp.inf) & valid_sorted
    finite = valid_sorted & ~nan & ~pinf & ~ninf
    clean = jnp.where(finite, v, jnp.float64(0.0))

    m = jnp.max(jnp.abs(clean))
    scale = _exponent_scale(m)  # 2^(36-E): |clean|*scale < 2^37
    scaled = clean * scale
    hi = jnp.floor(scaled)
    lo = jnp.round((scaled - hi) * np.float64(2.0) ** 36)
    shi = _seg_diff(jnp.cumsum(hi.astype(jnp.int64)), hi.astype(jnp.int64), lay)
    slo = _seg_diff(jnp.cumsum(lo.astype(jnp.int64)), lo.astype(jnp.int64), lay)
    total = (shi.astype(jnp.float64)
             + slo.astype(jnp.float64) * np.float64(2.0) ** -36) / scale

    # special counts: (nan<<31 | pinf) in one i64 cumsum, ninf in an i32
    spec = (nan.astype(jnp.int64) << jnp.int64(31)) | pinf.astype(jnp.int64)
    sspec = _seg_diff(jnp.cumsum(spec), spec, lay)
    n_nan = sspec >> jnp.int64(31)
    n_pinf = sspec & ((jnp.int64(1) << jnp.int64(31)) - 1)
    ni = ninf.astype(jnp.int32)
    n_ninf = _seg_diff(jnp.cumsum(ni), ni, lay)
    is_nan = (n_nan > 0) | ((n_pinf > 0) & (n_ninf > 0))
    out = jnp.where(n_pinf > 0, jnp.float64(np.inf), total)
    out = jnp.where(n_ninf > 0, jnp.float64(-np.inf), out)
    out = jnp.where(is_nan, jnp.float64(np.nan), out)
    return out


def _scatter_red(op: str, vals: jax.Array, gid: jax.Array, cap: int
                 ) -> jax.Array:
    red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    return red(vals, gid, num_segments=cap + 1)[:cap]


def seg_minmax_i32(op: str, vals_sorted: jax.Array, valid_sorted: jax.Array,
                   lay: GroupLayout, init) -> jax.Array:
    v = jnp.where(valid_sorted, vals_sorted.astype(jnp.int32),
                  jnp.full_like(vals_sorted, init, dtype=jnp.int32))
    return _scatter_red(op, v, lay.safe_gid, lay.cap)


def seg_minmax_i64(op: str, vals_sorted: jax.Array, valid_sorted: jax.Array,
                   lay: GroupLayout) -> jax.Array:
    """64-bit segmented min/max as two chained i32 scatter reductions:
    first the high words; then, among rows whose high word equals the
    group winner, the (order-adjusted) low words."""
    init64 = np.iinfo(np.int64).max if op == "min" else np.iinfo(np.int64).min
    v = jnp.where(valid_sorted, vals_sorted.astype(jnp.int64),
                  jnp.int64(init64))
    hi = (v >> jnp.int64(32)).astype(jnp.int32)
    # low word: unsigned order -> shift into signed i32 range for compare
    lo = v & jnp.int64(0xFFFFFFFF)
    lo32 = (lo - jnp.int64(2**31)).astype(jnp.int32)
    whi = _scatter_red(op, hi, lay.safe_gid, lay.cap)
    cand = hi == whi[jnp.clip(lay.safe_gid, 0, lay.cap - 1)]
    init32 = np.iinfo(np.int32).max if op == "min" else np.iinfo(np.int32).min
    lo_m = jnp.where(cand & valid_sorted, lo32, jnp.int32(init32))
    wlo = _scatter_red(op, lo_m, lay.safe_gid, lay.cap)
    return (whi.astype(jnp.int64) << jnp.int64(32)) | \
        (wlo.astype(jnp.int64) + jnp.int64(2**31)).astype(jnp.uint32).astype(jnp.int64)


def seg_first_last(op: str, vals_sorted: jax.Array, valid_sorted: jax.Array,
                   lay: GroupLayout) -> Tuple[jax.Array, jax.Array]:
    """Sorted position of the first/last VALID row per group (stable sort
    keeps original row order within a group), then gather."""
    cap = lay.cap
    pos = jnp.arange(cap, dtype=jnp.int32)
    if op == "first":
        p = jnp.where(valid_sorted, pos, cap)
        sel = _scatter_red("min", p, lay.safe_gid, cap)
        has = sel < cap
    else:
        p = jnp.where(valid_sorted, pos, -1)
        sel = _scatter_red("max", p, lay.safe_gid, cap)
        has = sel >= 0
    selc = jnp.clip(sel, 0, cap - 1)
    return vals_sorted[selc], has


# ---------------------------------------------------------------------------
# Sort-free scatter-bucket aggregation (small packed key spaces)
#
# When the packed key fits BUCKET_BITS (<= 2^23 buckets), skip the sort
# entirely: every reduction is a direct i32 scatter into the bucket space.
# Measured on v5e: one i32 segment_sum of 8M rows into 3M buckets is
# ~95 ms, while the sorted pipeline pays ~150 ms PER GATHER (random
# gathers run at ~0.4 GB/s on this hardware) — so three balanced-digit
# limb scatters beat sort+gather+cumsum by ~4x and need no host sync.
# ---------------------------------------------------------------------------

#: max total packed bits for the scatter-bucket path (8M-slot targets)
BUCKET_BITS = 23
#: per-bucket row-count bound for the 16-bit-digit f64 sum: |digit| can
#: reach 2^16 at the top of the max binade (|s| < 2^48), so counts up to
#: 2^14 keep the i32 accumulator under 2^30
_LIMB_COUNT_LIMIT = 1 << 14
#: int sums keep the original 2^15 bound: their 16-bit balanced digits
#: are strictly |d| <= 2^15 (unlike the f64 path's rounded 2^16 corner)
_INT_LIMB_COUNT_LIMIT = 1 << 15


class BucketLayout:
    __slots__ = ("bucket", "nb", "counts", "occupied", "n_groups",
                 "max_cnt", "live")

    def __init__(self, bucket, nb, counts, occupied, n_groups, max_cnt,
                 live):
        self.bucket = bucket
        self.nb = nb
        self.counts = counts
        self.occupied = occupied
        self.n_groups = n_groups
        self.max_cnt = max_cnt
        self.live = live


def bucket_layout(spec: PackSpec, key_cols, mins, live) -> BucketLayout:
    """i32 bucket id per row (dead rows -> overflow slot nb) + occupancy."""
    nb = 1 << spec.total_bits
    packed = pack_keys(spec, key_cols, mins, live)
    bucket = jnp.where(live, packed, jnp.int64(nb)).astype(jnp.int32)
    counts = jax.ops.segment_sum(jnp.ones(bucket.shape[0], jnp.int32),
                                 bucket, num_segments=nb + 1)[:nb]
    occupied = counts > 0
    n_groups = jnp.sum(occupied.astype(jnp.int32))
    max_cnt = jnp.max(counts)
    return BucketLayout(bucket, nb, counts, occupied, n_groups, max_cnt,
                        live)


def bucket_unpack_keys(spec: PackSpec, mins, key_cols) -> List[ColumnVector]:
    """Group keys for the whole bucket space, decoded from the bucket
    INDEX itself — pure arithmetic over arange, zero data movement."""
    nb = 1 << spec.total_bits
    return unpack_keys(spec, jnp.arange(nb, dtype=jnp.int64), mins, key_cols)


def _safe_bucket(lay: BucketLayout, valid) -> jax.Array:
    return jnp.where(valid, lay.bucket, jnp.int32(lay.nb))


def bucket_count(lay: BucketLayout, valid) -> jax.Array:
    return jax.ops.segment_sum(
        jnp.where(valid, 1, 0).astype(jnp.int32), lay.bucket,
        num_segments=lay.nb + 1)[:lay.nb].astype(jnp.int64)


def bucket_sum_int(lay: BucketLayout, vals, valid) -> jax.Array:
    """Exact mod-2^64 integer sum per bucket from balanced i32 limb
    scatters. Limb width adapts to bucket depth (scatters are ~a full
    batch pass each on this hardware): counts <= 2^9 take three 22-bit
    limbs, counts <= 2^15 four 16-bit limbs (|digit| <= 2^15, so
    2^15 * 2^15 = 2^30 fits i32), pathological skew one slow i64
    scatter. Picked at runtime by lax.cond — no sync."""
    v = jnp.where(valid, vals.astype(jnp.int64), jnp.int64(0))
    sb = _safe_bucket(lay, valid)

    def limb_path(width: int, nlimbs: int):
        half = jnp.int64(1 << (width - 1))
        mask = jnp.int64((1 << width) - 1)

        def go(_):
            x = v
            acc = jnp.zeros(lay.nb, jnp.int64)
            for i in range(nlimbs):
                d = ((x + half) & mask) - half
                if i < nlimbs - 1:
                    x = (x - d) >> jnp.int64(width)
                # else: top limb truncates; wraparound keeps mod-2^64
                s = jax.ops.segment_sum(d.astype(jnp.int32), sb,
                                        num_segments=lay.nb + 1)[:lay.nb]
                acc = acc + (s.astype(jnp.int64) << jnp.int64(width * i))
            return acc
        return go

    def slow_path(_):
        return jax.ops.segment_sum(v, sb, num_segments=lay.nb + 1)[:lay.nb]

    return lax.cond(
        lay.max_cnt <= (1 << 9), limb_path(22, 3),
        lambda _: lax.cond(lay.max_cnt <= _INT_LIMB_COUNT_LIMIT,
                           limb_path(16, 4), slow_path, None),
        None)


#: shallow-bucket bound for the 2-digit f64 sum: |digit| = round(s/2^24)
#: can reach 2^24 at the top of the max binade (|s| < 2^48), so counts up
#: to 64 keep the i32 accumulator under 2^31
_LIMB2_COUNT_LIMIT = 1 << 6


def bucket_sum_f64(lay: BucketLayout, vals, valid) -> jax.Array:
    """Float sum per bucket via balanced fixed-point digit scatters of a
    47-bit representation below the batch max exponent — error <= ~1 ulp
    of the device's own f32-pair f64. Scatters are the dominant cost of
    the bucket path on this hardware (~each a full pass over the batch),
    so the digit count adapts to bucket depth: shallow buckets (the
    high-cardinality-groupby shape) take TWO base-2^24 digits, deeper
    ones three base-2^16 digits, pathological skew one slow f64 scatter.
    The NaN/Inf flag scatters only execute when the batch actually
    contains a special (one cheap any() reduce gates them)."""
    v = vals.astype(jnp.float64)
    nan = jnp.isnan(v) & valid
    pinf = (v == jnp.inf) & valid
    ninf = (v == -jnp.inf) & valid
    finite = valid & ~nan & ~pinf & ~ninf
    clean = jnp.where(finite, v, jnp.float64(0.0))
    sb = _safe_bucket(lay, valid)

    m = jnp.max(jnp.abs(clean))
    scale = _exponent_scale(m) * np.float64(2.0 ** 11)  # 47 bits below E
    s = clean * scale

    def digits_path(widths):
        def go(_):
            tot = jnp.zeros(lay.nb, jnp.float64)
            rem = s
            shift = sum(widths)
            for w in widths:
                shift -= w
                d = jnp.round(rem / np.float64(2.0 ** shift)) if shift \
                    else jnp.round(rem)
                if shift:
                    rem = rem - d * np.float64(2.0 ** shift)
                acc = jax.ops.segment_sum(d.astype(jnp.int32), sb,
                                          num_segments=lay.nb + 1)[:lay.nb]
                tot = tot + acc.astype(jnp.float64) * np.float64(2.0 ** shift)
            return tot / scale
        return go

    def slow_path(_):
        return jax.ops.segment_sum(clean, sb,
                                   num_segments=lay.nb + 1)[:lay.nb]

    total = lax.cond(
        lay.max_cnt <= _LIMB2_COUNT_LIMIT, digits_path((24, 24)),
        lambda _: lax.cond(lay.max_cnt <= _LIMB_COUNT_LIMIT,
                           digits_path((16, 16, 16)), slow_path, None),
        None)

    any_special = nan | pinf | ninf

    def exact_flags(_):
        has_nan = jax.ops.segment_max(
            jnp.where(nan, 1, 0).astype(jnp.int32), sb,
            num_segments=lay.nb + 1)[:lay.nb] > 0
        has_pinf = jax.ops.segment_max(
            jnp.where(pinf, 1, 0).astype(jnp.int32), sb,
            num_segments=lay.nb + 1)[:lay.nb] > 0
        has_ninf = jax.ops.segment_max(
            jnp.where(ninf, 1, 0).astype(jnp.int32), sb,
            num_segments=lay.nb + 1)[:lay.nb] > 0
        return has_nan, has_pinf, has_ninf

    def no_flags(_):
        f = jnp.zeros(lay.nb, jnp.bool_)
        return f, f, f

    has_nan, has_pinf, has_ninf = lax.cond(jnp.any(any_special), exact_flags,
                                           no_flags, None)
    out = jnp.where(has_pinf, jnp.float64(np.inf), total)
    out = jnp.where(has_ninf, jnp.float64(-np.inf), out)
    out = jnp.where(has_nan | (has_pinf & has_ninf), jnp.float64(np.nan), out)
    return out


def bucket_minmax_i32(op, lay: BucketLayout, vals, valid, init) -> jax.Array:
    v = jnp.where(valid, vals.astype(jnp.int32),
                  jnp.full(vals.shape, init, jnp.int32))
    red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    return red(v, _safe_bucket(lay, valid), num_segments=lay.nb + 1)[:lay.nb]


def bucket_minmax_i64(op, lay: BucketLayout, vals, valid) -> jax.Array:
    init64 = np.iinfo(np.int64).max if op == "min" else np.iinfo(np.int64).min
    v = jnp.where(valid, vals.astype(jnp.int64), jnp.int64(init64))
    sb = _safe_bucket(lay, valid)
    red = jax.ops.segment_min if op == "min" else jax.ops.segment_max
    hi = (v >> jnp.int64(32)).astype(jnp.int32)
    lo = ((v & jnp.int64(0xFFFFFFFF)) - jnp.int64(2 ** 31)).astype(jnp.int32)
    whi = red(hi, sb, num_segments=lay.nb + 1)[:lay.nb]
    cand = valid & (hi == whi[jnp.clip(lay.bucket, 0, lay.nb - 1)])
    init32 = np.iinfo(np.int32).max if op == "min" else np.iinfo(np.int32).min
    lom = jnp.where(cand, lo, jnp.int32(init32))
    wlo = red(lom, _safe_bucket(lay, cand), num_segments=lay.nb + 1)[:lay.nb]
    return (whi.astype(jnp.int64) << jnp.int64(32)) | \
        (wlo.astype(jnp.int64) + jnp.int64(2 ** 31)).astype(jnp.uint32).astype(jnp.int64)


def bucket_minmax_f64(op, lay: BucketLayout, vals, valid) -> jax.Array:
    o = _f64_order_i64(vals.astype(jnp.float64))
    init = np.iinfo(np.int64).max if op == "min" else np.iinfo(np.int64).min
    o = jnp.where(valid, o, jnp.int64(init))
    w = bucket_minmax_i64(op, lay, o, jnp.ones_like(valid))
    return _i64_order_f64(w)


def bucket_minmax_f32(op, lay: BucketLayout, vals, valid) -> jax.Array:
    min32 = jnp.int32(np.int32(-2 ** 31))
    v = vals.astype(jnp.float32)
    x = jnp.where(jnp.isnan(v), jnp.float32(np.nan), v)
    x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
    bits = lax.bitcast_convert_type(x, jnp.int32)
    o = jnp.where(bits < 0, ~bits ^ min32, bits)
    init = np.iinfo(np.int32).max if op == "min" else np.iinfo(np.int32).min
    w = bucket_minmax_i32(op, lay, o, valid, init)
    back = jnp.where(w < 0, ~(w ^ min32), w)
    return lax.bitcast_convert_type(back, jnp.float32)


def bucket_first_last(op, lay: BucketLayout, vals, valid
                      ) -> Tuple[jax.Array, jax.Array]:
    n = vals.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    if op == "first":
        p = jnp.where(valid, pos, n)
        sel = jax.ops.segment_min(p, _safe_bucket(lay, valid),
                                  num_segments=lay.nb + 1)[:lay.nb]
        has = sel < n
    else:
        p = jnp.where(valid, pos, -1)
        sel = jax.ops.segment_max(p, _safe_bucket(lay, valid),
                                  num_segments=lay.nb + 1)[:lay.nb]
        has = sel >= 0
    return vals[jnp.clip(sel, 0, n - 1)], has


def _f64_order_i64(v: jax.Array) -> jax.Array:
    """f64 -> order-preserving int64 (Spark total order: NaN above +inf,
    -0.0 == 0.0), via the arithmetic bitcast (no 64-bit bitcast-convert
    on TPU)."""
    from spark_rapids_tpu.ops import kernels as K
    x = jnp.where(jnp.isnan(v), jnp.float64(np.nan), v)
    x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
    bits = K._bitcast_f64_u64(x)
    neg = (bits >> jnp.uint64(63)) != 0
    u = jnp.where(neg, ~bits, bits | (jnp.uint64(1) << jnp.uint64(63)))
    return (u.astype(jnp.int64) ^ jnp.int64(np.int64(-2**63)))


def _i64_order_f64(o: jax.Array) -> jax.Array:
    from spark_rapids_tpu.ops import groupby as G
    u = (o ^ jnp.int64(np.int64(-2**63))).astype(jnp.uint64)
    return G._invert_float_bits(u, 64, np.float64)


def seg_minmax_f64(op: str, vals_sorted: jax.Array, valid_sorted: jax.Array,
                   lay: GroupLayout) -> jax.Array:
    """Segmented f64 min/max through the order-preserving i64 transform +
    the two-pass i32 scatter reduction."""
    o = _f64_order_i64(vals_sorted.astype(jnp.float64))
    init = np.iinfo(np.int64).max if op == "min" else np.iinfo(np.int64).min
    o = jnp.where(valid_sorted, o, jnp.int64(init))
    w = seg_minmax_i64(op, o, valid_sorted | True, lay)
    return _i64_order_f64(w)


def seg_minmax_f32(op: str, vals_sorted: jax.Array, valid_sorted: jax.Array,
                   lay: GroupLayout) -> jax.Array:
    """f32 min/max via the signed-i32 order transform + one i32 scatter.
    forward: o = bits < 0 ? ~bits ^ MIN32 : bits; inverse mirrors it."""
    min32 = jnp.int32(np.int32(-2**31))
    v = vals_sorted.astype(jnp.float32)
    x = jnp.where(jnp.isnan(v), jnp.float32(np.nan), v)
    x = jnp.where(x == 0.0, jnp.zeros_like(x), x)
    bits = lax.bitcast_convert_type(x, jnp.int32)
    o = jnp.where(bits < 0, ~bits ^ min32, bits)
    init = np.iinfo(np.int32).max if op == "min" else np.iinfo(np.int32).min
    w = seg_minmax_i32(op, o, valid_sorted, lay, init)
    back = jnp.where(w < 0, ~(w ^ min32), w)
    return lax.bitcast_convert_type(back, jnp.float32)
